"""Vectorized TCP: per-socket SoA state machines stepped in parallel.

Reference: src/main/host/descriptor/tcp.c (2665 LoC) — state machine
CLOSED..LASTACK (tcp.c:41-46), server child-socket demux (:90-112), seq/ack
send+receive windows (:124-172), retransmit queue + RTO + backoff (:174-189),
congestion vtable with RENO (:202-203, tcp_cong_reno.c), RTT smoothing
(:205-208), SACK lists (:145,171, tcp_retransmit_tally.cc).

TPU-first re-architecture (SURVEY.md §7 hard part #1):

- All sockets of all hosts live in one [H, S] struct-of-arrays table; every
  handler applies masked element-wise updates, so one incoming segment per
  host per micro-step advances H independent state machines at once.
- Segment TRANSMISSION is a self-rearming output pump event (KIND_TCP_OUT,
  one MSS segment per micro-step per host) feeding the NIC ring — the same
  shape as the NIC send pump, replacing tcp.c's throttled-output queue.
- The receive-side reorder buffer / SACK scoreboard
  (tcp_retransmit_tally.cc's sorted interval lists) is re-expressed as a
  bounded [H, S, W] boolean array of MSS-sized chunks beyond rcv_nxt:
  out-of-order arrivals set their chunk flag; an in-order arrival absorbs
  the contiguous prefix with a cumprod count and a gather shift. Segments
  that are not MSS-aligned or land beyond W chunks are dropped (a dup-ACK
  still goes back, so the sender retransmits; correctness is preserved,
  only efficiency of the rare unaligned/far case is lost).
- Retransmit timers are LAZY: the armed expire time lives in the table; the
  scheduled event just says "look at socket s". Re-arming on every ACK
  mutates only `rtx_expire` (no event churn); a firing timer whose expire
  moved into the future re-emits itself at the new time. Generation counters
  invalidate events from closed/reused sockets.
- Sequence-number arithmetic is int32 with two's-complement wraparound
  (seq_lt via sign of the wrapped difference), like the kernel's before/after
  macros.

Byte payloads are never materialized on device: the app-side stream is just
sequence-space (`snd_buf_end` = bytes the app has written). Device apps
consume instantly; the CPU syscall plane moves real bytes host-side keyed by
sequence ranges.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime, soa
from shadow_tpu.core.state import PAYLOAD_WORDS
from shadow_tpu.net import packet as pkt

SUB = "tcp"

# --- states (tcp.c:41-46) ---
CLOSED = 0
LISTEN = 1
SYN_SENT = 2
SYN_RECEIVED = 3
ESTABLISHED = 4
FIN_WAIT_1 = 5
FIN_WAIT_2 = 6
CLOSING = 7
TIME_WAIT = 8
CLOSE_WAIT = 9
LAST_ACK = 10

# --- header flags (standard bit positions) ---
FIN = 0x01
SYN = 0x02
RST = 0x04
ACK = 0x10

MSS = pkt.MTU - pkt.TCP_HEADER_BYTES  # 1460
INIT_CWND_SEGS = 10  # Linux-style initial window
INIT_SSTHRESH = 1 << 30
RTO_INIT_NS = simtime.NS_PER_SEC  # RFC 6298 initial RTO = 1 s
RTO_MIN_NS = 200 * simtime.NS_PER_MS
RTO_MAX_NS = 60 * simtime.NS_PER_SEC
TIME_WAIT_NS = 60 * simtime.NS_PER_SEC  # reference CONFIG_TCPCLOSETIMER_DELAY
RECV_WND = 1 << 20  # advertised receive window (app consumes instantly)
OOO_BITS = 32  # legacy bitmap width (see _popcount/_trailing_ones helpers)
OOO_CHUNKS = 64  # default reorder-scoreboard width in MSS chunks (~93 KiB)

# timer kinds riding in timer-event payloads
TIMER_RTX = 0
TIMER_TIMEWAIT = 1

# payload word assignments for TCP self-events (output pump / timers)
EV_SLOT = 0  # socket slot
EV_TKIND = 1  # timer kind
EV_GEN = 2  # generation at scheduling time

ANY_PEER = -1


@struct.dataclass
class TcpState:
    # GLOBAL host id of each local row (islands engine: the shard's
    # contiguous gid block; arange on the global engine). All self-timer
    # emissions and src_host stamping use this, never jnp.arange.
    gid: jnp.ndarray  # [H] i32
    # identity / binding
    used: jnp.ndarray  # [H,S] bool
    local_port: jnp.ndarray  # [H,S] i32
    peer_host: jnp.ndarray  # [H,S] i32 (ANY_PEER for listeners)
    peer_port: jnp.ndarray  # [H,S] i32
    state: jnp.ndarray  # [H,S] i32
    # send sequence space (int32, wraparound arithmetic)
    snd_una: jnp.ndarray  # [H,S] oldest unacked
    snd_nxt: jnp.ndarray  # [H,S] next to send
    snd_max: jnp.ndarray  # [H,S] highest ever sent (retransmit detection)
    snd_wnd: jnp.ndarray  # [H,S] peer-advertised window
    snd_buf_end: jnp.ndarray  # [H,S] app stream write pointer (seq space)
    fin_pending: jnp.ndarray  # [H,S] bool — app closed; FIN after data
    fin_seq: jnp.ndarray  # [H,S] seq consumed by our FIN (valid once sent)
    fin_sent: jnp.ndarray  # [H,S] bool
    # receive sequence space
    rcv_nxt: jnp.ndarray  # [H,S] i32
    ooo_map: jnp.ndarray  # [H,S,W] bool — MSS chunks beyond rcv_nxt received
    fin_rcvd_seq: jnp.ndarray  # [H,S] i32 seq of peer FIN (valid if fin_rcvd)
    fin_rcvd: jnp.ndarray  # [H,S] bool — peer FIN seen (maybe out of order)
    # congestion control (Reno — tcp_cong_reno.c)
    cwnd: jnp.ndarray  # [H,S] i32 bytes
    ssthresh: jnp.ndarray  # [H,S] i32 bytes
    dup_acks: jnp.ndarray  # [H,S] i32
    fast_recovery: jnp.ndarray  # [H,S] bool
    # sender-side SACK scoreboard (tcp_retransmit_tally.cc bounded form):
    # bit k of sack_bits = peer holds [snd_una + k*MSS, ...); rtx_high =
    # highest seq already retransmitted this recovery episode
    sack_bits: jnp.ndarray  # [H,S] i32 (u32 bitmap)
    rtx_high: jnp.ndarray  # [H,S] i32
    recover: jnp.ndarray  # [H,S] i32 snd_max at FR entry (NewReno)
    # RTT estimation (RFC 6298; tcp.c:205-208)
    srtt: jnp.ndarray  # [H,S] i64 ns (0 = no sample yet)
    rttvar: jnp.ndarray  # [H,S] i64 ns
    rto: jnp.ndarray  # [H,S] i64 ns
    rtt_armed: jnp.ndarray  # [H,S] bool — a timing sample is in flight
    rtt_seq: jnp.ndarray  # [H,S] i32 — ack covering this seq closes the sample
    rtt_start: jnp.ndarray  # [H,S] i64
    # retransmit timer (lazy)
    rtx_armed: jnp.ndarray  # [H,S] bool — an event is in flight
    rtx_expire: jnp.ndarray  # [H,S] i64
    gen: jnp.ndarray  # [H,S] i32 — invalidates stale timer events
    # output pump dedup
    out_pending: jnp.ndarray  # [H,S] bool
    # app-visible accounting
    bytes_acked: jnp.ndarray  # [H,S] i64 — app bytes the peer has acked
    bytes_received: jnp.ndarray  # [H,S] i64 — in-order bytes delivered up
    # drop/diagnostic counters
    drop_no_socket: jnp.ndarray  # [] i64
    drop_ooo: jnp.ndarray  # [] i64 — unaligned/far out-of-order discards
    retransmits: jnp.ndarray  # [] i64
    timeouts: jnp.ndarray  # [] i64
    accept_overflow: jnp.ndarray  # [] i64 — SYN with no free child slot
    # per-cause retransmit split (VERDICT r2 #6; the reference's tally
    # exposes the same distinction via its marked ranges):
    #   rtx_fast — NewReno first-hole sends (recovery entry + partial acks)
    #   rtx_sack — SACK-driven further-hole sends inside recovery
    #   rtx_walk — pump re-walk sends after an RTO rewind
    rtx_fast: jnp.ndarray  # [] i64
    rtx_sack: jnp.ndarray  # [] i64
    rtx_walk: jnp.ndarray  # [] i64


def init(num_hosts: int, sockets_per_host: int = 8,
         ooo_chunks: int = OOO_CHUNKS) -> TcpState:
    H, S = num_hosts, sockets_per_host
    i32 = lambda v=0: jnp.full((H, S), v, jnp.int32)  # noqa: E731
    i64 = lambda v=0: jnp.full((H, S), v, jnp.int64)  # noqa: E731
    b = lambda: jnp.zeros((H, S), bool)  # noqa: E731
    return TcpState(
        gid=jnp.arange(H, dtype=jnp.int32),
        used=b(), local_port=i32(), peer_host=i32(ANY_PEER), peer_port=i32(),
        state=i32(CLOSED),
        snd_una=i32(), snd_nxt=i32(), snd_max=i32(), snd_wnd=i32(RECV_WND),
        snd_buf_end=i32(), fin_pending=b(), fin_seq=i32(), fin_sent=b(),
        rcv_nxt=i32(), ooo_map=jnp.zeros((H, S, ooo_chunks), bool),
        fin_rcvd_seq=i32(), fin_rcvd=b(),
        cwnd=i32(INIT_CWND_SEGS * MSS), ssthresh=i32(INIT_SSTHRESH),
        dup_acks=i32(), fast_recovery=b(), recover=i32(),
        sack_bits=i32(), rtx_high=i32(),
        srtt=i64(), rttvar=i64(), rto=i64(RTO_INIT_NS),
        rtt_armed=b(), rtt_seq=i32(), rtt_start=i64(),
        rtx_armed=b(), rtx_expire=i64(simtime.NEVER), gen=i32(),
        out_pending=b(),
        bytes_acked=jnp.zeros((H, S), jnp.int64),
        bytes_received=jnp.zeros((H, S), jnp.int64),
        drop_no_socket=jnp.zeros((), jnp.int64),
        drop_ooo=jnp.zeros((), jnp.int64),
        retransmits=jnp.zeros((), jnp.int64),
        timeouts=jnp.zeros((), jnp.int64),
        accept_overflow=jnp.zeros((), jnp.int64),
        rtx_fast=jnp.zeros((), jnp.int64),
        rtx_sack=jnp.zeros((), jnp.int64),
        rtx_walk=jnp.zeros((), jnp.int64),
    )


def listen_static(tcp: TcpState, host: int, slot: int, port: int) -> TcpState:
    """Build-time passive open (socket+bind+listen)."""
    return tcp.replace(
        used=tcp.used.at[host, slot].set(True),
        local_port=tcp.local_port.at[host, slot].set(port),
        peer_host=tcp.peer_host.at[host, slot].set(ANY_PEER),
        state=tcp.state.at[host, slot].set(LISTEN),
    )


# ---------------------------------------------------------------------------
# sequence arithmetic (int32 wraparound, kernel before()/after() style)
# ---------------------------------------------------------------------------


def seq_lt(a, b):
    return (b - a).astype(jnp.int32) > 0


def seq_leq(a, b):
    return (b - a).astype(jnp.int32) >= 0


# ---------------------------------------------------------------------------
# gather/scatter helpers at (host, slot)
# ---------------------------------------------------------------------------


def _g(arr, slot):
    H = arr.shape[0]
    hosts = jnp.arange(H, dtype=jnp.int32)
    return arr[hosts, jnp.clip(slot, 0, arr.shape[1] - 1)]


def _s(arr, mask, slot, val):
    """Masked per-host slot write: arr[h, slot[h]] = val[h] where mask.
    Select-based (core.soa) — XLA scatters serialize on TPU."""
    return soa.set_at(arr, mask, slot, val)


# ---------------------------------------------------------------------------
# demux (network_interface.c:391-441 + tcp.c:90-112 child demux)
# ---------------------------------------------------------------------------


def demux(tcp: TcpState, mask, payload, src_host):
    """Match an incoming segment to a socket: established 4-tuple match
    outranks a listener port match; lowest slot wins ties.

    Returns (slot [H] i32, found [H] bool, is_listener [H] bool).
    """
    dport = payload[:, pkt.W_DST_PORT][:, None]
    sport = payload[:, pkt.W_SRC_PORT][:, None]
    srch = src_host.astype(jnp.int32)[:, None]
    port_ok = tcp.used & (tcp.local_port == dport)
    conn = port_ok & (tcp.peer_host == srch) & (tcp.peer_port == sport) & (
        tcp.state != LISTEN
    )
    listener = port_ok & (tcp.state == LISTEN)
    score = conn.astype(jnp.int32) * 2 + listener.astype(jnp.int32)
    best = jnp.max(score, axis=1)
    slot = jnp.argmax(score, axis=1).astype(jnp.int32)
    found = mask & (best > 0)
    is_listener = found & (best == 1)
    return slot, found, is_listener


# ---------------------------------------------------------------------------
# segment assembly
# ---------------------------------------------------------------------------


def make_segment(src_port, dst_port, length, flags, seq, ack, wnd, src_host,
                 socket_slot, sack=None, payload_words=PAYLOAD_WORDS):
    H = src_port.shape[0]
    pl = jnp.zeros((H, payload_words), dtype=jnp.int32)
    if payload_words > pkt.W_TRAIL:
        pl = pl.at[:, pkt.W_TRAIL].set(pkt.PDS_CREATED)
    pl = pl.at[:, pkt.W_PROTO].set(pkt.PROTO_TCP)
    pl = pl.at[:, pkt.W_SRC_PORT].set(src_port.astype(jnp.int32))
    pl = pl.at[:, pkt.W_DST_PORT].set(dst_port.astype(jnp.int32))
    pl = pl.at[:, pkt.W_LEN].set(length.astype(jnp.int32))
    pl = pl.at[:, pkt.W_FLAGS].set(flags.astype(jnp.int32))
    pl = pl.at[:, pkt.W_SEQ].set(seq.astype(jnp.int32))
    pl = pl.at[:, pkt.W_ACK].set(ack.astype(jnp.int32))
    pl = pl.at[:, pkt.W_WND].set(wnd.astype(jnp.int32))
    pl = pl.at[:, pkt.W_SRC_HOST].set(src_host.astype(jnp.int32))
    pl = pl.at[:, pkt.W_SOCKET].set(socket_slot.astype(jnp.int32))
    if sack is not None:
        pl = pl.at[:, pkt.W_SACK].set(sack.astype(jnp.int32))
    return pl


# ---------------------------------------------------------------------------
# RTT / RTO (RFC 6298)
# ---------------------------------------------------------------------------


def _rtt_update(tcp: TcpState, mask, slot, now):
    """Close the in-flight timing sample where the new ack covers rtt_seq."""
    armed = _g(tcp.rtt_armed, slot)
    take = mask & armed
    r = (now - _g(tcp.rtt_start, slot)).astype(jnp.int64)
    srtt0 = _g(tcp.srtt, slot)
    rttvar0 = _g(tcp.rttvar, slot)
    first = srtt0 == 0
    srtt1 = jnp.where(first, r, srtt0 + (r - srtt0) // 8)
    rttvar1 = jnp.where(
        first, r // 2, rttvar0 + (jnp.abs(srtt0 - r) - rttvar0) // 4
    )
    rto1 = jnp.clip(srtt1 + 4 * rttvar1, RTO_MIN_NS, RTO_MAX_NS)
    return tcp.replace(
        srtt=_s(tcp.srtt, take, slot, srtt1),
        rttvar=_s(tcp.rttvar, take, slot, rttvar1),
        rto=_s(tcp.rto, take, slot, rto1),
        rtt_armed=_s(tcp.rtt_armed, take, slot, jnp.zeros_like(armed)),
    )


# ---------------------------------------------------------------------------
# OOO bitmap helpers (the bounded SACK scoreboard)
# ---------------------------------------------------------------------------


def _popcount(x):
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.uint32)


def _bit_length(x):
    """Position of the highest set bit + 1 of uint32 x (0 for x == 0)."""
    x = x.astype(jnp.uint32)
    n = jnp.zeros_like(x)
    for sh in (16, 8, 4, 2, 1):
        gt = x >= (jnp.uint32(1) << sh)
        n = n + jnp.where(gt, jnp.uint32(sh), jnp.uint32(0))
        x = jnp.where(gt, x >> sh, x)
    return (n + (x > 0)).astype(jnp.int32)


def _pack_sack(om):
    """Pack the first 32 reorder-board chunks into a u32 bitmap (int32
    bit pattern) — the wire form riding pure ACKs (pkt.W_SACK)."""
    n = min(32, om.shape[1])
    weights = jnp.uint32(1) << jnp.arange(n, dtype=jnp.uint32)
    u = jnp.sum(
        om[:, :n].astype(jnp.uint32) * weights[None, :], axis=1,
        dtype=jnp.uint32,
    )
    return jax.lax.bitcast_convert_type(u, jnp.int32)


def _trailing_ones(x):
    """Count of consecutive set bits from bit 0 of uint32 x."""
    y = (~x).astype(jnp.uint32)
    lsb = y & (jnp.uint32(0) - y)
    return jnp.where(
        y == 0, jnp.uint32(OOO_BITS), _popcount(lsb - jnp.uint32(1))
    ).astype(jnp.int32)


# ---------------------------------------------------------------------------
# The vectorized TCP machine
# ---------------------------------------------------------------------------


class Tcp:
    """Composable TCP module: the stack feeds it demuxed segments; it feeds
    the stack outgoing segments via ``stack._tx`` and schedules its own
    output-pump/timer events.

    App integration points:
      on_established hooks: (state, mask, slot, is_accept, src, now, emitter,
                             params) -> state
      on_receive hooks:     (state, mask, slot, nbytes, src, now, emitter,
                             params) -> state
      on_peer_fin hooks:    (state, mask, slot, now, emitter, params) -> state
    """

    KIND_OUT = 101  # output pump self-event
    KIND_TIMER = 102  # retransmit / timewait timer event

    def __init__(self, num_hosts: int, sockets_per_host: int = 8,
                 ooo_chunks: int = OOO_CHUNKS, child_base: int = 0,
                 payload_words: int = PAYLOAD_WORDS):
        """child_base partitions the slot space when an external (CPU) plane
        allocates active-open slots: device-accepted children only use slots
        >= child_base, so a pending host-side connect injection can never
        collide with a device-side accept."""
        self.num_hosts = num_hosts
        self.sockets_per_host = sockets_per_host
        self.ooo_chunks = ooo_chunks
        self.child_base = child_base
        self.payload_words = payload_words
        self._init = init(num_hosts, sockets_per_host, ooo_chunks)
        self.established_hooks = []
        self.receive_hooks = []
        self.peer_fin_hooks = []
        # (state, mask, slot, now, emitter, params) -> state
        self.reset_hooks = []  # connection torn down by RST (incl. refused)
        self.closed_hooks = []  # slot freed after orderly close/TIME_WAIT

    def attach(self, stack):
        self.stack = stack

    # ---- build-time API ----

    def listen(self, host: int, slot: int, port: int):
        self._init = listen_static(self._init, host, slot, port)

    def init_sub(self) -> TcpState:
        return self._init

    def on_established(self, hook):
        self.established_hooks.append(hook)

    def on_receive(self, hook):
        self.receive_hooks.append(hook)

    def on_peer_fin(self, hook):
        self.peer_fin_hooks.append(hook)

    def on_reset(self, hook):
        self.reset_hooks.append(hook)

    def on_closed(self, hook):
        self.closed_hooks.append(hook)

    # ---- internal helpers ----

    def _arm_out(self, t: TcpState, emitter, mask, slot, now):
        """Schedule the output pump for (host, slot) unless already pending."""
        pending = _g(t.out_pending, slot)
        need = mask & ~pending
        H = t.gid.shape[0]
        pl = jnp.zeros((H, self.payload_words), jnp.int32)
        pl = pl.at[:, EV_SLOT].set(slot.astype(jnp.int32))
        emitter.emit(
            need, jnp.broadcast_to(now, (H,)).astype(jnp.int64), t.gid,
            jnp.int32(self.KIND_OUT), pl,
        )
        return t.replace(
            out_pending=_s(t.out_pending, need, slot, jnp.ones_like(pending))
        )

    def _arm_rtx(self, t: TcpState, emitter, mask, slot, now):
        """Start the lazy retransmit timer where not already running."""
        armed = _g(t.rtx_armed, slot)
        need = mask & ~armed
        rto = _g(t.rto, slot)
        expire = now + rto
        H = t.gid.shape[0]
        pl = jnp.zeros((H, self.payload_words), jnp.int32)
        pl = pl.at[:, EV_SLOT].set(slot.astype(jnp.int32))
        pl = pl.at[:, EV_TKIND].set(TIMER_RTX)
        pl = pl.at[:, EV_GEN].set(_g(t.gen, slot))
        emitter.emit(
            need, jnp.where(need, expire, 0).astype(jnp.int64), t.gid,
            jnp.int32(self.KIND_TIMER), pl,
        )
        return t.replace(
            rtx_armed=_s(t.rtx_armed, need, slot, jnp.ones_like(armed)),
            rtx_expire=_s(t.rtx_expire, need, slot, expire),
        )

    def _push_back_rtx(self, t: TcpState, mask, slot, now):
        """On new data acked: slide the armed timer's deadline to now+rto
        without touching the in-flight event (it re-checks on fire)."""
        armed = _g(t.rtx_armed, slot)
        m = mask & armed
        return t.replace(
            rtx_expire=_s(t.rtx_expire, m, slot, now + _g(t.rto, slot))
        )

    def _tx_segment(self, state, emitter, mask, now, dst_host, *, slot,
                    length, flags, seq, ack, dst_port=None, src_port=None,
                    params=None, sack=None):
        """Assemble + hand a segment to the NIC (stack transmit path);
        with ``params`` the stack's uncontended fast path applies."""
        t = state.subs[SUB]
        sp = src_port if src_port is not None else _g(t.local_port, slot)
        dp = dst_port if dst_port is not None else _g(t.peer_port, slot)
        Hl = t.gid.shape[0]
        seg = make_segment(
            src_port=sp, dst_port=dp,
            length=jnp.broadcast_to(jnp.asarray(length, jnp.int32), (Hl,)),
            flags=jnp.broadcast_to(jnp.asarray(flags, jnp.int32), (Hl,)),
            seq=seq, ack=ack,
            wnd=jnp.full((Hl,), RECV_WND, jnp.int32),
            src_host=t.gid, socket_slot=slot, sack=sack,
            payload_words=self.payload_words,
        )
        state, _ok = self.stack._tx(
            state, emitter, mask, now, dst_host, seg, params=params
        )
        return state

    # ---- runtime app API ----

    def connect(self, state, emitter, mask, slot, dst_host, dst_port,
                local_port, now, params=None):
        """Active open: full slot re-init + SYN + retransmit timer.

        Reference: tcp.c connect path; ISS is 0 (deterministic) — the
        reference draws a random ISS but determinism is the property that
        matters (SURVEY.md §5.2)."""
        t = state.subs[SUB]
        H = t.gid.shape[0]
        z32 = jnp.zeros((H,), jnp.int32)
        one32 = jnp.ones((H,), jnp.int32)
        fb = jnp.zeros((H,), bool)
        slot = jnp.broadcast_to(jnp.asarray(slot, jnp.int32), (H,))
        dst_host = jnp.broadcast_to(jnp.asarray(dst_host, jnp.int32), (H,))
        dst_port = jnp.broadcast_to(jnp.asarray(dst_port, jnp.int32), (H,))
        local_port = jnp.broadcast_to(jnp.asarray(local_port, jnp.int32), (H,))
        m = mask
        t = t.replace(
            used=_s(t.used, m, slot, jnp.ones((H,), bool)),
            local_port=_s(t.local_port, m, slot, local_port),
            peer_host=_s(t.peer_host, m, slot, dst_host),
            peer_port=_s(t.peer_port, m, slot, dst_port),
            state=_s(t.state, m, slot, jnp.full((H,), SYN_SENT, jnp.int32)),
            snd_una=_s(t.snd_una, m, slot, z32),
            snd_nxt=_s(t.snd_nxt, m, slot, one32),
            snd_max=_s(t.snd_max, m, slot, one32),
            snd_wnd=_s(t.snd_wnd, m, slot, jnp.full((H,), RECV_WND, jnp.int32)),
            snd_buf_end=_s(t.snd_buf_end, m, slot, one32),
            fin_pending=_s(t.fin_pending, m, slot, fb),
            fin_sent=_s(t.fin_sent, m, slot, fb),
            rcv_nxt=_s(t.rcv_nxt, m, slot, z32),
            ooo_map=_s(t.ooo_map, m, slot,
                       jnp.zeros((H, self.ooo_chunks), bool)),
            fin_rcvd=_s(t.fin_rcvd, m, slot, fb),
            cwnd=_s(t.cwnd, m, slot,
                    jnp.full((H,), INIT_CWND_SEGS * MSS, jnp.int32)),
            ssthresh=_s(t.ssthresh, m, slot,
                        jnp.full((H,), INIT_SSTHRESH, jnp.int32)),
            dup_acks=_s(t.dup_acks, m, slot, z32),
            fast_recovery=_s(t.fast_recovery, m, slot, fb),
            sack_bits=_s(t.sack_bits, m, slot, z32),
            rtx_high=_s(t.rtx_high, m, slot, z32),
            srtt=_s(t.srtt, m, slot, jnp.zeros((H,), jnp.int64)),
            rttvar=_s(t.rttvar, m, slot, jnp.zeros((H,), jnp.int64)),
            rto=_s(t.rto, m, slot, jnp.full((H,), RTO_INIT_NS, jnp.int64)),
            rtt_armed=_s(t.rtt_armed, m, slot, jnp.ones((H,), bool)),
            rtt_seq=_s(t.rtt_seq, m, slot, one32),
            rtt_start=_s(t.rtt_start, m, slot,
                         jnp.broadcast_to(now, (H,)).astype(jnp.int64)),
            # a reused slot may carry stale timer state from a previous
            # connection (e.g. TIME_WAIT expiry): disarm and invalidate
            rtx_armed=_s(t.rtx_armed, m, slot, fb),
            rtx_expire=_s(t.rtx_expire, m, slot,
                          jnp.full((H,), simtime.NEVER, jnp.int64)),
            gen=soa.add_at(t.gen, m, slot, 1),
            out_pending=_s(t.out_pending, m, slot, fb),
            bytes_acked=_s(t.bytes_acked, m, slot, jnp.zeros((H,), jnp.int64)),
            bytes_received=_s(t.bytes_received, m, slot,
                              jnp.zeros((H,), jnp.int64)),
        )
        state = state.with_sub(SUB, t)
        # SYN: seq=iss(0), no data
        state = self._tx_segment(
            state, emitter, m, now, dst_host, slot=slot, length=0, flags=SYN,
            seq=z32, ack=z32, dst_port=dst_port, src_port=local_port,
            params=params,
        )
        t = state.subs[SUB]
        t = self._arm_rtx(t, emitter, m, slot, now)
        return state.with_sub(SUB, t)

    def send_app(self, state, emitter, mask, slot, nbytes, now):
        """App writes nbytes into the stream (sequence space only)."""
        t = state.subs[SUB]
        ok = mask & _g(t.used, slot) & (
            (_g(t.state, slot) == ESTABLISHED)
            | (_g(t.state, slot) == CLOSE_WAIT)
            | (_g(t.state, slot) == SYN_SENT)
            | (_g(t.state, slot) == SYN_RECEIVED)
        ) & ~_g(t.fin_pending, slot)
        nb = jnp.broadcast_to(jnp.asarray(nbytes, jnp.int32),
                              (t.gid.shape[0],))
        t = t.replace(
            snd_buf_end=_s(t.snd_buf_end, ok, slot,
                           _g(t.snd_buf_end, slot) + nb)
        )
        t = self._arm_out(t, emitter, ok, slot, now)
        return state.with_sub(SUB, t)

    def close_app(self, state, emitter, mask, slot, now):
        """App close: FIN goes out after all buffered data."""
        t = state.subs[SUB]
        ok = mask & _g(t.used, slot) & ~_g(t.fin_pending, slot) & (
            (_g(t.state, slot) == ESTABLISHED)
            | (_g(t.state, slot) == CLOSE_WAIT)
            | (_g(t.state, slot) == SYN_SENT)
            | (_g(t.state, slot) == SYN_RECEIVED)
        )
        t = t.replace(fin_pending=_s(t.fin_pending, ok, slot,
                                     jnp.ones((t.gid.shape[0],), bool)))
        t = self._arm_out(t, emitter, ok, slot, now)
        return state.with_sub(SUB, t)

    # ---- segment processing (tcp.c:1870 _tcp_processPacket) ----

    def _emit_timer(self, emitter, mask, slot, tkind, gen, time, gid):
        H = gid.shape[0]
        pl = jnp.zeros((H, self.payload_words), jnp.int32)
        pl = pl.at[:, EV_SLOT].set(slot.astype(jnp.int32))
        pl = pl.at[:, EV_TKIND].set(jnp.broadcast_to(
            jnp.asarray(tkind, jnp.int32), (H,)))
        pl = pl.at[:, EV_GEN].set(gen.astype(jnp.int32))
        emitter.emit(mask, jnp.where(mask, time, 0).astype(jnp.int64),
                     gid, jnp.int32(self.KIND_TIMER), pl)

    def on_segment(self, state, mask, src, payload, emitter, now, params):
        """Process one incoming segment per host (vectorized over hosts)."""
        t = state.subs[SUB]
        H = t.gid.shape[0]
        fl = payload[:, pkt.W_FLAGS]
        has_syn = (fl & SYN) != 0
        has_ack = (fl & ACK) != 0
        has_fin = (fl & FIN) != 0
        has_rst = (fl & RST) != 0
        seg_seq = payload[:, pkt.W_SEQ]
        seg_ack = payload[:, pkt.W_ACK]
        seg_wnd = payload[:, pkt.W_WND]
        seg_len = payload[:, pkt.W_LEN]
        sport = payload[:, pkt.W_SRC_PORT]
        dport = payload[:, pkt.W_DST_PORT]
        src = src.astype(jnp.int32)
        now64 = now.astype(jnp.int64)

        z32 = jnp.zeros((H,), jnp.int32)
        one32 = jnp.ones((H,), jnp.int32)
        fb = jnp.zeros((H,), bool)
        tb = jnp.ones((H,), bool)
        z64 = jnp.zeros((H,), jnp.int64)

        slot, found, is_listener = demux(t, mask, payload, src)
        t = t.replace(
            drop_no_socket=t.drop_no_socket
            + jnp.sum(mask & ~found, dtype=jnp.int64)
        )

        # ---------- RST for segments matching no socket ----------
        # (tcp.c replies RST to closed ports so active opens fail fast
        # instead of retrying SYN into the void; never RST a RST)
        no_sock = mask & ~found & ~has_rst
        rst_seq = jnp.where(has_ack, seg_ack, z32)
        rst_ack = (
            seg_seq + seg_len
            + has_syn.astype(jnp.int32) + has_fin.astype(jnp.int32)
        )
        state = state.with_sub(SUB, t)
        state = self._tx_segment(
            state, emitter, no_sock, now64, src, slot=jnp.zeros_like(slot),
            length=0, flags=RST | ACK, seq=rst_seq, ack=rst_ack,
            dst_port=sport, src_port=dport,
            params=params,
        )
        t = state.subs[SUB]

        # ---------- passive open: SYN to listener → child socket ----------
        m_syn = found & is_listener & has_syn & ~has_ack
        slots_row = jnp.arange(t.used.shape[1], dtype=jnp.int32)[None, :]
        free = ~t.used & (slots_row >= self.child_base)
        has_free = jnp.any(free, axis=1)
        child = jnp.argmax(free, axis=1).astype(jnp.int32)
        mc = m_syn & has_free
        t = t.replace(
            accept_overflow=t.accept_overflow
            + jnp.sum(m_syn & ~has_free, dtype=jnp.int64)
        )
        t = t.replace(
            used=_s(t.used, mc, child, tb),
            local_port=_s(t.local_port, mc, child, dport),
            peer_host=_s(t.peer_host, mc, child, src),
            peer_port=_s(t.peer_port, mc, child, sport),
            state=_s(t.state, mc, child,
                     jnp.full((H,), SYN_RECEIVED, jnp.int32)),
            snd_una=_s(t.snd_una, mc, child, z32),
            snd_nxt=_s(t.snd_nxt, mc, child, one32),
            snd_max=_s(t.snd_max, mc, child, one32),
            snd_wnd=_s(t.snd_wnd, mc, child, seg_wnd),
            snd_buf_end=_s(t.snd_buf_end, mc, child, one32),
            fin_pending=_s(t.fin_pending, mc, child, fb),
            fin_sent=_s(t.fin_sent, mc, child, fb),
            rcv_nxt=_s(t.rcv_nxt, mc, child, seg_seq + 1),
            ooo_map=_s(t.ooo_map, mc, child,
                       jnp.zeros((H, self.ooo_chunks), bool)),
            fin_rcvd=_s(t.fin_rcvd, mc, child, fb),
            cwnd=_s(t.cwnd, mc, child,
                    jnp.full((H,), INIT_CWND_SEGS * MSS, jnp.int32)),
            ssthresh=_s(t.ssthresh, mc, child,
                        jnp.full((H,), INIT_SSTHRESH, jnp.int32)),
            dup_acks=_s(t.dup_acks, mc, child, z32),
            fast_recovery=_s(t.fast_recovery, mc, child, fb),
            sack_bits=_s(t.sack_bits, mc, child, z32),
            rtx_high=_s(t.rtx_high, mc, child, z32),
            srtt=_s(t.srtt, mc, child, z64),
            rttvar=_s(t.rttvar, mc, child, z64),
            rto=_s(t.rto, mc, child, jnp.full((H,), RTO_INIT_NS, jnp.int64)),
            rtt_armed=_s(t.rtt_armed, mc, child, tb),
            rtt_seq=_s(t.rtt_seq, mc, child, one32),
            rtt_start=_s(t.rtt_start, mc, child, now64),
            rtx_armed=_s(t.rtx_armed, mc, child, fb),
            gen=soa.add_at(t.gen, mc, child, 1),
            out_pending=_s(t.out_pending, mc, child, fb),
            bytes_acked=_s(t.bytes_acked, mc, child, z64),
            bytes_received=_s(t.bytes_received, mc, child, z64),
        )
        state = state.with_sub(SUB, t)
        state = self._tx_segment(
            state, emitter, mc, now64, src, slot=child, length=0,
            flags=SYN | ACK, seq=z32, ack=seg_seq + 1,
            dst_port=sport, src_port=dport,
            params=params,
        )
        t = state.subs[SUB]
        t = self._arm_rtx(t, emitter, mc, child, now64)

        # ---------- active open completes: SYN+ACK in SYN_SENT ----------
        st = _g(t.state, slot)
        m_conn = found & ~is_listener
        m_ss = (
            m_conn & (st == SYN_SENT) & has_syn & has_ack
            & (seg_ack == _g(t.snd_nxt, slot))
        )
        t = t.replace(
            state=_s(t.state, m_ss, slot,
                     jnp.full((H,), ESTABLISHED, jnp.int32)),
            rcv_nxt=_s(t.rcv_nxt, m_ss, slot, seg_seq + 1),
            snd_una=_s(t.snd_una, m_ss, slot, seg_ack),
            snd_wnd=_s(t.snd_wnd, m_ss, slot, seg_wnd),
        )
        t = _rtt_update(
            t, m_ss & seq_leq(_g(t.rtt_seq, slot), seg_ack), slot, now64
        )
        state = state.with_sub(SUB, t)
        state = self._tx_segment(
            state, emitter, m_ss, now64, src, slot=slot, length=0, flags=ACK,
            seq=_g(state.subs[SUB].snd_nxt, slot),
            ack=_g(state.subs[SUB].rcv_nxt, slot),
            params=params,
        )
        for hook in self.established_hooks:
            state = hook(state, m_ss, slot, fb, src, now64, emitter, params)
        t = state.subs[SUB]
        # app may have queued data inside the hook — pump if so
        want_out = m_ss & (
            seq_lt(_g(t.snd_nxt, slot), _g(t.snd_buf_end, slot))
            | (_g(t.fin_pending, slot) & ~_g(t.fin_sent, slot))
        )
        t = self._arm_out(t, emitter, want_out, slot, now64)

        # ---------- connection-state processing ----------
        st = _g(t.state, slot)
        m_proc = m_conn & ~m_ss & (st >= SYN_RECEIVED)

        # RST tears the connection down (tcp.c RST handling, simplified);
        # a RST in SYN_SENT is connection-refused (reply to our SYN from a
        # closed port) and must also tear down + notify.
        m_rst = (
            m_proc | (m_conn & ~m_ss & (st == SYN_SENT))
        ) & has_rst
        t = t.replace(
            used=_s(t.used, m_rst, slot, fb),
            state=_s(t.state, m_rst, slot, z32),
            gen=soa.add_at(t.gen, m_rst, slot, 1),
        )
        state = state.with_sub(SUB, t)
        for hook in self.reset_hooks:
            state = hook(state, m_rst, slot, now64, emitter, params)
        t = state.subs[SUB]
        m_proc = m_proc & ~m_rst

        # retransmitted SYN to a SYN_RECEIVED child → re-send SYN+ACK
        resyn = m_proc & has_syn & ~has_ack & (st == SYN_RECEIVED)

        # ---------- ACK processing (Reno hooks — tcp_cong_reno.c) ----------
        una = _g(t.snd_una, slot)
        nxt = _g(t.snd_nxt, slot)
        smax = _g(t.snd_max, slot)
        m_ack = m_proc & has_ack
        acceptable = m_ack & seq_leq(una, seg_ack) & seq_leq(seg_ack, smax)
        new_acked = acceptable & seq_lt(una, seg_ack)

        # SYN_RECEIVED + ack of our SYN → ESTABLISHED (accept completes)
        m_sr_est = new_acked & (st == SYN_RECEIVED)
        t = t.replace(
            state=_s(t.state, m_sr_est, slot,
                     jnp.full((H,), ESTABLISHED, jnp.int32))
        )

        # duplicate-ACK detection (before una moves)
        outstanding = seq_lt(una, nxt)
        is_dup = (
            m_ack & (seg_ack == una) & (seg_len == 0)
            & ~has_syn & ~has_fin & outstanding
        )
        fr = _g(t.fast_recovery, slot)
        dups0 = _g(t.dup_acks, slot)
        dups1 = jnp.where(is_dup & ~fr, dups0 + 1, dups0)
        trigger_fr = is_dup & ~fr & (dups1 == 3)
        flight = (nxt - una).astype(jnp.int32)
        ssth_on_loss = jnp.maximum(flight // 2, 2 * MSS)
        inflate = is_dup & fr
        cwnd0 = _g(t.cwnd, slot)
        ssth0 = _g(t.ssthresh, slot)
        cwnd1 = jnp.where(
            trigger_fr, ssth_on_loss + 3 * MSS,
            jnp.where(inflate, cwnd0 + MSS, cwnd0),
        )
        ssth1 = jnp.where(trigger_fr, ssth_on_loss, ssth0)
        fr1 = fr | trigger_fr
        rec1 = jnp.where(trigger_fr, smax, _g(t.recover, slot))

        # new-ack Reno: full ack exits FR; partial ack retransmits the hole
        full_ack = new_acked & fr1 & seq_leq(rec1, seg_ack)
        partial_ack = new_acked & fr1 & ~full_ack
        cwnd2 = jnp.where(full_ack, ssth1, cwnd1)
        fr2 = fr1 & ~full_ack
        dups2 = jnp.where(new_acked, 0, dups1)
        grow = new_acked & ~fr1
        acked_bytes = (seg_ack - una).astype(jnp.int32)
        in_ss = cwnd2 < ssth1
        cwnd3 = jnp.where(
            grow & in_ss, cwnd2 + jnp.minimum(acked_bytes, MSS),
            jnp.where(
                grow & ~in_ss,
                cwnd2 + jnp.maximum(1, (MSS * MSS) // jnp.maximum(cwnd2, 1)),
                cwnd2,
            ),
        )

        # bytes_acked accounting: subtract SYN/FIN phantom bytes
        fin_seq_g = _g(t.fin_seq, slot)
        fin_sent_g = _g(t.fin_sent, slot)
        syn_ph = new_acked & (una == 0)
        fin_acked = (
            new_acked & fin_sent_g & seq_leq(una, fin_seq_g)
            & seq_lt(fin_seq_g, seg_ack)
        )
        app_bytes = (
            acked_bytes - syn_ph.astype(jnp.int32) - fin_acked.astype(jnp.int32)
        )
        # snd_nxt >= snd_una invariant (Linux keeps the same): after an RTO
        # rewind, a cumulative ACK that jumps past the rewound frontier
        # must drag it forward — otherwise the pump re-sends already-ACKED
        # bytes one MSS at a time (the round-2 rtx-inflation cascade).
        t = t.replace(
            snd_nxt=_s(
                t.snd_nxt, new_acked & seq_lt(nxt, seg_ack), slot, seg_ack
            ),
            snd_una=_s(t.snd_una, new_acked, slot, seg_ack),
            snd_wnd=_s(t.snd_wnd, acceptable, slot, seg_wnd),
            cwnd=_s(t.cwnd, m_ack, slot, cwnd3),
            ssthresh=_s(t.ssthresh, m_ack, slot, ssth1),
            dup_acks=_s(t.dup_acks, m_ack, slot, dups2),
            fast_recovery=_s(t.fast_recovery, m_ack, slot, fr2),
            recover=_s(t.recover, m_ack, slot, rec1),
            bytes_acked=soa.add_at(t.bytes_acked, new_acked, slot,
                                   app_bytes.astype(jnp.int64)),
        )
        t = _rtt_update(
            t, new_acked & seq_leq(_g(t.rtt_seq, slot), seg_ack), slot, now64
        )
        t = self._push_back_rtx(t, new_acked, slot, now64)

        # FIN-of-ours acked: FIN_WAIT_1→FIN_WAIT_2, CLOSING→TIME_WAIT,
        # LAST_ACK→CLOSED
        st_now = _g(t.state, slot)
        t = t.replace(
            state=_s(
                t.state,
                fin_acked,
                slot,
                jnp.where(
                    st_now == FIN_WAIT_1, jnp.int32(FIN_WAIT_2),
                    jnp.where(
                        st_now == CLOSING, jnp.int32(TIME_WAIT),
                        jnp.where(st_now == LAST_ACK, jnp.int32(CLOSED),
                                  st_now),
                    ),
                ),
            )
        )
        m_tw_enter = fin_acked & (st_now == CLOSING)
        m_free = fin_acked & (st_now == LAST_ACK)

        # ---- sender SACK scoreboard update (bounded tally) ----
        # Pure ACKs carry the receiver's reorder board relative to seg_ack;
        # after the snd_una update above, seg_ack == snd_una for every ack
        # that can drive recovery, so the incoming bitmap is authoritative.
        # Data-carrying acks just shift the old board by the acked chunks.
        pure_ack = m_ack & (seg_len == 0) & ~has_syn & ~has_fin
        sb0 = jax.lax.bitcast_convert_type(_g(t.sack_bits, slot), jnp.uint32)
        nch = acked_bytes // MSS
        acked_ch = jnp.clip(nch, 0, 31).astype(jnp.uint32)
        # a jump of >= 32 chunks clears the board entirely (a clipped
        # shift would leave old bit 31 aliased onto the new hole)
        sb_shift = jnp.where(
            new_acked,
            jnp.where(nch >= 32, jnp.uint32(0), sb0 >> acked_ch),
            sb0,
        )
        sb_in = jax.lax.bitcast_convert_type(
            payload[:, pkt.W_SACK], jnp.uint32
        )
        sb1 = jnp.where(pure_ack & acceptable, sb_in, sb_shift)
        t = t.replace(
            sack_bits=_s(
                t.sack_bits, m_ack, slot,
                jax.lax.bitcast_convert_type(sb1, jnp.int32),
            )
        )

        # ---- fast/partial/SACK retransmission ----
        # NewReno: entering recovery or a partial ack retransmits the first
        # missing chunk. With SACK info, every further dup-ack retransmits
        # the NEXT unsacked chunk below the highest sacked one — multiple
        # holes repaired per RTT instead of one (tcp_retransmit_tally.cc's
        # mark_lost/retransmit walk, in bounded-bitmap form).
        una2 = _g(t.snd_una, slot)
        rtx_high0 = _g(t.rtx_high, slot)
        rtx_high_eff = jnp.where(trigger_fr, una2, rtx_high0)
        done_ch = jnp.clip(
            (rtx_high_eff - una2).astype(jnp.int32) // MSS, 0, 32
        ).astype(jnp.uint32)
        done_mask = jnp.where(
            done_ch >= 32, jnp.uint32(0xFFFFFFFF),
            (jnp.uint32(1) << done_ch) - jnp.uint32(1),
        )
        v = sb1 | done_mask
        f = _trailing_ones(v)  # first unsacked chunk at/after rtx_high
        blen = _bit_length(sb1)
        have_sack = sb1 != 0
        newreno_rtx = trigger_fr | partial_ack
        # a hole is VISIBLE only below the highest sacked chunk; without
        # that, retransmitting would duplicate the in-flight frontier.
        # f == 0 with rtx_high at/below una is the classic una-hole case
        # (covers empty bitmaps: a dup/partial ack implies the hole).
        hole_visible = have_sack & (f < blen) & (f < 32)
        una_hole = (f == 0)
        sack_rtx = inflate & hole_visible
        do_rtx = (newreno_rtx & (hole_visible | una_hole)) | sack_rtx
        f_eff = jnp.where(hole_visible, jnp.minimum(f, 31), 0)
        rtx_seq = una2 + f_eff * MSS
        buf = _g(t.snd_buf_end, slot)
        rtx_len = jnp.minimum(MSS, (buf - rtx_seq).astype(jnp.int32))
        data_rtx = do_rtx & (rtx_len > 0)
        fin_rtx = newreno_rtx & (
            jnp.minimum(MSS, (buf - una2).astype(jnp.int32)) <= 0
        ) & fin_sent_g
        t = t.replace(
            rtt_armed=_s(t.rtt_armed, do_rtx, slot, fb),  # Karn
            rtx_high=_s(
                t.rtx_high, m_ack, slot,
                jnp.where(data_rtx, rtx_seq + rtx_len, rtx_high_eff),
            ),
            retransmits=t.retransmits + jnp.sum(data_rtx | fin_rtx,
                                                dtype=jnp.int64),
            rtx_fast=t.rtx_fast + jnp.sum(
                (data_rtx | fin_rtx) & newreno_rtx, dtype=jnp.int64
            ),
            rtx_sack=t.rtx_sack + jnp.sum(
                data_rtx & sack_rtx & ~newreno_rtx, dtype=jnp.int64
            ),
        )
        state = state.with_sub(SUB, t)
        state = self._tx_segment(
            state, emitter, data_rtx, now64, src, slot=slot,
            length=rtx_len, flags=ACK, seq=rtx_seq,
            ack=_g(state.subs[SUB].rcv_nxt, slot),
            params=params,
        )
        state = self._tx_segment(
            state, emitter, fin_rtx, now64, src, slot=slot,
            length=0, flags=FIN | ACK, seq=fin_seq_g,
            ack=_g(state.subs[SUB].rcv_nxt, slot),
            params=params,
        )
        t = state.subs[SUB]

        # accept-side established hooks (after accounting so hooks can send)
        state = state.with_sub(SUB, t)
        for hook in self.established_hooks:
            state = hook(state, m_sr_est, slot, tb, src, now64, emitter,
                         params)
        t = state.subs[SUB]

        # window may have opened → pump
        can_more = (
            (new_acked | inflate)
            & (
                seq_lt(_g(t.snd_nxt, slot), _g(t.snd_buf_end, slot))
                | (_g(t.fin_pending, slot) & ~_g(t.fin_sent, slot))
            )
        )
        t = self._arm_out(t, emitter, can_more, slot, now64)

        # ---------- data receive (reorder scoreboard) ----------
        st2 = _g(t.state, slot)
        can_rcv = (
            (st2 == ESTABLISHED) | (st2 == FIN_WAIT_1) | (st2 == FIN_WAIT_2)
        )
        m_data = m_proc & (seg_len > 0) & can_rcv
        rn = _g(t.rcv_nxt, slot)
        d = (seg_seq - rn).astype(jnp.int32)
        in_order = m_data & (d == 0)
        om = _g(t.ooo_map, slot)  # [H, W] bool
        W = om.shape[1]
        # chunk i = [rcv_nxt + i*MSS, +(i+1)*MSS); chunk 0 is by definition
        # the missing in-order chunk and is never set. An in-order MSS
        # arrival shifts everything down one chunk, then absorbs the run of
        # already-received chunks now at the front. A short (final) segment
        # clears the board (nothing beyond the end of stream).
        tail = om[:, 1:].astype(jnp.int32)
        n_absorb = jnp.where(
            seg_len == MSS,
            jnp.sum(jnp.cumprod(tail, axis=1), axis=1),
            0,
        ).astype(jnp.int32)
        adv = jnp.where(in_order, seg_len + n_absorb * MSS, 0)
        rn1 = rn + adv
        shift = jnp.where(seg_len == MSS, 1 + n_absorb, jnp.int32(W))
        idx = jnp.arange(W, dtype=jnp.int32)[None, :] + shift[:, None]
        om_shifted = jnp.take_along_axis(
            jnp.concatenate([om, jnp.zeros_like(om)], axis=1),
            jnp.clip(idx, 0, 2 * W - 1),
            axis=1,
        )
        om1 = jnp.where(in_order[:, None], om_shifted, om)
        # out-of-order: flag the chunk if MSS-aligned and within the board
        m_ooo = m_data & (d > 0)
        kchunk = d // MSS
        aligned = (
            m_ooo & (d % MSS == 0) & (seg_len == MSS)
            & (kchunk >= 1) & (kchunk < W)
        )
        om2 = soa.set_at(om1, aligned, kchunk, True)
        t = t.replace(
            rcv_nxt=_s(t.rcv_nxt, in_order, slot, rn1),
            ooo_map=_s(t.ooo_map, in_order | aligned, slot, om2),
            drop_ooo=t.drop_ooo + jnp.sum(m_ooo & ~aligned, dtype=jnp.int64),
            bytes_received=soa.add_at(t.bytes_received, in_order, slot,
                                      adv.astype(jnp.int64)),
        )

        # ---------- peer FIN ----------
        m_fin = m_proc & has_fin & (
            (st2 == ESTABLISHED) | (st2 == FIN_WAIT_1) | (st2 == FIN_WAIT_2)
        )
        t = t.replace(
            fin_rcvd=_s(t.fin_rcvd, m_fin, slot, tb),
            fin_rcvd_seq=_s(t.fin_rcvd_seq, m_fin, slot, seg_seq + seg_len),
        )
        # consume the FIN once all data before it has arrived
        frs = _g(t.fin_rcvd_seq, slot)
        frcvd = _g(t.fin_rcvd, slot)
        rn_now = _g(t.rcv_nxt, slot)
        st3 = _g(t.state, slot)
        consume = (
            m_proc & frcvd & (rn_now == frs)
            & ((st3 == ESTABLISHED) | (st3 == FIN_WAIT_1)
               | (st3 == FIN_WAIT_2))
        )
        t = t.replace(
            rcv_nxt=_s(t.rcv_nxt, consume, slot, rn_now + 1),
            state=_s(
                t.state, consume, slot,
                jnp.where(
                    st3 == ESTABLISHED, jnp.int32(CLOSE_WAIT),
                    jnp.where(st3 == FIN_WAIT_1, jnp.int32(CLOSING),
                              jnp.int32(TIME_WAIT)),
                ),
            ),
            fin_rcvd=_s(t.fin_rcvd, consume, slot, fb),
        )
        m_tw_enter = m_tw_enter | (consume & (st3 == FIN_WAIT_2))
        # EOF surfaces to the app in every state that consumes a peer FIN —
        # a half-closed endpoint (FIN_WAIT_*) still needs its EOF.
        m_eof = consume

        # ---------- TIME_WAIT timer + socket free ----------
        self._emit_timer(
            emitter, m_tw_enter, slot, TIMER_TIMEWAIT, _g(t.gen, slot),
            now64 + TIME_WAIT_NS, t.gid,
        )
        t = t.replace(
            used=_s(t.used, m_free, slot, fb),
            state=_s(t.state, m_free, slot, z32),
            gen=soa.add_at(t.gen, m_free, slot, 1),
        )
        state = state.with_sub(SUB, t)
        for hook in self.closed_hooks:
            state = hook(state, m_free, slot, now64, emitter, params)
        t = state.subs[SUB]

        # ---------- ACK reply ----------
        # Reply to anything that consumed sequence space or was a
        # (re)transmitted SYN; never reply to a pure ACK (no ack loops).
        # A retransmitted SYN+ACK seen in ESTABLISHED means our handshake
        # ACK was lost — re-ACK or the peer child stays in SYN_RECEIVED.
        resynack = m_proc & has_syn & has_ack & (st == ESTABLISHED)
        need_ack = (m_proc & ((seg_len > 0) | has_fin)) | resyn | resynack
        reply_flags = jnp.where(resyn, jnp.int32(SYN | ACK), jnp.int32(ACK))
        reply_seq = jnp.where(resyn, z32, _g(t.snd_nxt, slot))
        state = state.with_sub(SUB, t)
        # pure ACKs advertise the reorder board as a bounded SACK bitmap
        # (relative to rcv_nxt, whose chunk 0 is the missing hole)
        state = self._tx_segment(
            state, emitter, need_ack, now64, src, slot=slot, length=0,
            flags=reply_flags, seq=reply_seq,
            ack=_g(state.subs[SUB].rcv_nxt, slot),
            sack=jnp.where(
                resyn | resynack, z32,
                _pack_sack(_g(state.subs[SUB].ooo_map, slot)),
            ),
            params=params,
        )

        # ---------- app hooks ----------
        for hook in self.receive_hooks:
            state = hook(state, in_order, slot, adv, src, now64, emitter,
                         params)
        for hook in self.peer_fin_hooks:
            state = hook(state, m_eof, slot, now64, emitter, params)
        return state

    # ---- output pump (tcp.c throttled-output analog) ----

    def on_out(self, state, ev, emitter, params):
        """Send at most one segment per (host, slot) per micro-step; re-arm
        while the window and stream allow more."""
        t = state.subs[SUB]
        H = t.gid.shape[0]
        slot = ev.payload[:, EV_SLOT]
        now64 = ev.time.astype(jnp.int64)
        fb = jnp.zeros((H,), bool)
        m = ev.mask
        t = t.replace(out_pending=_s(t.out_pending, m, slot, fb))
        m = m & _g(t.used, slot)

        st = _g(t.state, slot)
        can_send = (
            (st == ESTABLISHED) | (st == CLOSE_WAIT) | (st == FIN_WAIT_1)
            | (st == CLOSING) | (st == LAST_ACK)
        )
        una = _g(t.snd_una, slot)
        nxt = _g(t.snd_nxt, slot)
        smax = _g(t.snd_max, slot)
        buf = _g(t.snd_buf_end, slot)
        wnd = jnp.minimum(_g(t.cwnd, slot), _g(t.snd_wnd, slot))
        avail_win = (una + wnd - nxt).astype(jnp.int32)
        have_data = seq_lt(nxt, buf)
        seg_len = jnp.minimum(
            jnp.minimum(MSS, (buf - nxt).astype(jnp.int32)), avail_win
        )
        send_data = m & can_send & have_data & (seg_len > 0)
        # While re-walking the flight (nxt < smax, i.e. retransmission
        # territory), chunks the peer already SACKed are SKIPPED — the
        # frontier advances without putting the segment on the wire
        # (reference: the tally's lost-range walk retransmits only holes;
        # sack_bits survive the RTO rewind for exactly this).
        ch = (nxt - una).astype(jnp.int32) // MSS
        sb = jax.lax.bitcast_convert_type(_g(t.sack_bits, slot), jnp.uint32)
        in_board = (ch >= 0) & (ch < 32)
        sacked_chunk = in_board & (
            ((sb >> jnp.clip(ch, 0, 31).astype(jnp.uint32)) & 1) == 1
        )
        skip_sacked = send_data & seq_lt(nxt, smax) & sacked_chunk
        send_data = send_data & ~skip_sacked
        fin_p = _g(t.fin_pending, slot)
        fin_s = _g(t.fin_sent, slot)
        send_fin = m & can_send & ~have_data & fin_p & ~fin_s

        rn = _g(t.rcv_nxt, slot)
        dst = _g(t.peer_host, slot)
        state = state.with_sub(SUB, t)
        state = self._tx_segment(
            state, emitter, send_data, now64, dst, slot=slot,
            length=jnp.maximum(seg_len, 0), flags=ACK, seq=nxt, ack=rn,
            params=params,
        )
        state = self._tx_segment(
            state, emitter, send_fin, now64, dst, slot=slot,
            length=0, flags=FIN | ACK, seq=nxt, ack=rn,
            params=params,
        )
        t = state.subs[SUB]

        sent_any = send_data | send_fin
        skip_len = jnp.minimum(MSS, (buf - nxt).astype(jnp.int32))
        advanced = sent_any | skip_sacked
        nxt1 = jnp.where(
            send_data, nxt + seg_len,
            jnp.where(
                skip_sacked, nxt + skip_len,
                jnp.where(send_fin, nxt + 1, nxt),
            ),
        )
        is_rtx = sent_any & seq_lt(nxt, smax)
        smax1 = jnp.where(seq_lt(smax, nxt1), nxt1, smax)
        # first-FIN bookkeeping + state transition
        t = t.replace(
            snd_nxt=_s(t.snd_nxt, advanced, slot, nxt1),
            snd_max=_s(t.snd_max, sent_any, slot, smax1),
            fin_seq=_s(t.fin_seq, send_fin, slot, nxt),
            fin_sent=_s(t.fin_sent, send_fin, slot, jnp.ones((H,), bool)),
            state=_s(
                t.state, send_fin, slot,
                jnp.where(
                    st == ESTABLISHED, jnp.int32(FIN_WAIT_1),
                    jnp.where(st == CLOSE_WAIT, jnp.int32(LAST_ACK), st),
                ),
            ),
            retransmits=t.retransmits + jnp.sum(is_rtx, dtype=jnp.int64),
            rtx_walk=t.rtx_walk + jnp.sum(is_rtx, dtype=jnp.int64),
        )
        # RTT sample on fresh data
        arm_rtt = send_data & ~_g(t.rtt_armed, slot) & ~is_rtx
        t = t.replace(
            rtt_armed=_s(t.rtt_armed, arm_rtt, slot, jnp.ones((H,), bool)),
            rtt_seq=_s(t.rtt_seq, arm_rtt, slot, nxt1),
            rtt_start=_s(t.rtt_start, arm_rtt, slot, now64),
        )
        t = self._arm_rtx(t, emitter, sent_any, slot, now64)

        # more to send?
        avail1 = (una + wnd - nxt1).astype(jnp.int32)
        more_data = seq_lt(nxt1, buf) & (avail1 > 0)
        more_fin = fin_p & ~_g(t.fin_sent, slot) & ~seq_lt(nxt1, buf)
        more = m & can_send & advanced & (more_data | more_fin)
        t = self._arm_out(t, emitter, more, slot, now64)
        return state.with_sub(SUB, t)

    # ---- timers (lazy retransmit + TIME_WAIT) ----

    def on_timer(self, state, ev, emitter, params):
        t = state.subs[SUB]
        H = t.gid.shape[0]
        slot = ev.payload[:, EV_SLOT]
        tkind = ev.payload[:, EV_TKIND]
        egen = ev.payload[:, EV_GEN]
        now64 = ev.time.astype(jnp.int64)
        fb = jnp.zeros((H,), bool)
        z32 = jnp.zeros((H,), jnp.int32)
        m = ev.mask & (_g(t.gen, slot) == egen) & _g(t.used, slot)

        # TIME_WAIT expiry frees the slot (CONFIG_TCPCLOSETIMER_DELAY)
        m_tw = m & (tkind == TIMER_TIMEWAIT) & (_g(t.state, slot) == TIME_WAIT)
        t = t.replace(
            used=_s(t.used, m_tw, slot, fb),
            state=_s(t.state, m_tw, slot, z32),
            gen=soa.add_at(t.gen, m_tw, slot, 1),
        )
        state = state.with_sub(SUB, t)
        for hook in self.closed_hooks:
            state = hook(state, m_tw, slot, now64, emitter, params)
        t = state.subs[SUB]

        # retransmit timer
        m_rtx = m & (tkind == TIMER_RTX)
        una = _g(t.snd_una, slot)
        nxt = _g(t.snd_nxt, slot)
        outstanding = seq_lt(una, nxt)
        # all acked → quietly disarm
        dis = m_rtx & ~outstanding
        t = t.replace(rtx_armed=_s(t.rtx_armed, dis, slot, fb))
        # deadline was pushed back by ACKs → re-check at the new deadline
        exp = _g(t.rtx_expire, slot)
        pushed = m_rtx & outstanding & (now64 < exp)
        self._emit_timer(emitter, pushed, slot, TIMER_RTX, egen, exp, t.gid)

        # expired → timeout (tcp_cong_reno timeout hooks + RFC 6298 backoff)
        fire = m_rtx & outstanding & (now64 >= exp)
        flight = (nxt - una).astype(jnp.int32)
        rto2 = jnp.minimum(_g(t.rto, slot) * 2, RTO_MAX_NS)
        st = _g(t.state, slot)
        fin_sent_g = _g(t.fin_sent, slot)
        fin_seq_g = _g(t.fin_seq, slot)
        # FIN unacked → re-send it via the pump after data
        fin_rewind = fire & fin_sent_g & seq_leq(una, fin_seq_g)
        hs = (st == SYN_SENT) | (st == SYN_RECEIVED)
        t = t.replace(
            ssthresh=_s(t.ssthresh, fire, slot,
                        jnp.maximum(flight // 2, 2 * MSS)),
            cwnd=_s(t.cwnd, fire, slot, jnp.full((H,), MSS, jnp.int32)),
            dup_acks=_s(t.dup_acks, fire, slot, z32),
            fast_recovery=_s(t.fast_recovery, fire, slot, fb),
            rtt_armed=_s(t.rtt_armed, fire, slot, fb),
            rto=_s(t.rto, fire, slot, rto2),
            rtx_expire=_s(t.rtx_expire, fire, slot, now64 + rto2),
            snd_nxt=_s(t.snd_nxt, fire & ~hs, slot, una),
            # sack_bits are KEPT across the RTO (the reference's tally
            # computes exact lost ranges; Linux likewise keeps the
            # scoreboard unless reneging): the pump skips sacked chunks
            # while re-walking the flight, so a timeout repairs only the
            # actual holes instead of go-back-N re-sending received data
            rtx_high=_s(t.rtx_high, fire, slot, z32),
            fin_sent=_s(t.fin_sent, fin_rewind, slot, fb),
            timeouts=t.timeouts + jnp.sum(fire, dtype=jnp.int64),
            retransmits=t.retransmits + jnp.sum(fire, dtype=jnp.int64),
        )
        self._emit_timer(emitter, fire, slot, TIMER_RTX, egen, now64 + rto2,
                         t.gid)

        # handshake retransmits go out directly; data goes via the pump
        state = state.with_sub(SUB, t)
        dst = _g(t.peer_host, slot)
        state = self._tx_segment(
            state, emitter, fire & (st == SYN_SENT), now64, dst, slot=slot,
            length=0, flags=SYN, seq=z32, ack=z32,
            params=params,
        )
        state = self._tx_segment(
            state, emitter, fire & (st == SYN_RECEIVED), now64, dst,
            slot=slot, length=0, flags=SYN | ACK, seq=z32,
            ack=_g(state.subs[SUB].rcv_nxt, slot),
            params=params,
        )
        t = state.subs[SUB]
        t = self._arm_out(t, emitter, fire & ~hs, slot, now64)
        return state.with_sub(SUB, t)

    def handlers(self) -> dict:
        return {self.KIND_OUT: self.on_out, self.KIND_TIMER: self.on_timer}
