"""Eiffel-style bucketed approximate PIFO (Eiffel, NSDI'19).

Instead of keeping the queue sorted, packets land in the first free slot
and carry their bucket id: bucket = (rank // bucket_width) mod B. Dequeue
is a circular bucket scan from the current service bucket — one argmin
over ((bucket - cur_bucket) mod B) · 2⁴⁰ + seq, so same-bucket packets
serve FIFO and the winner is the nearest non-empty bucket. The
approximation error is bounded by one bucket width (two packets whose
ranks differ by < bucket_width may serve in arrival order instead of rank
order); with bucket_width 1 and every outstanding rank spread < B the scan
is EXACT and chains bit-identically to qdisc/pifo.py — the property tests
pin both bounds.

Layout-friendliness is the point (ROADMAP: "bucketed approximations are
the layout-friendly path"): enqueue is one soa.set_at one-hot write and
dequeue one argmin + one-hot read — no O(Q) shift traffic, no sorts, no
scatters — so Q can grow to real buffer depths without bloating the
window kernel. Bucket wrap past the B·width horizon degrades gracefully
to coarser ordering, Eiffel's own overflow semantics.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import soa
from shadow_tpu.net import packet as pkt
from shadow_tpu.net.qdisc import pifo as pifo_mod

# seq rides in the low bits of the scan key below the bucket distance;
# 2^40 sequence numbers per host per run is plenty of headroom
_SEQ_SPAN = jnp.int64(1) << 40


class EiffelDiscipline(pifo_mod.DeviceQueueDiscipline):
    name = "eiffel"

    def __init__(self, queue_slots: int = 64, buckets: int = 16,
                 bucket_width: int = 1, **kw):
        super().__init__(queue_slots=queue_slots, **kw)
        self.buckets = int(buckets)
        self.bucket_width = int(bucket_width)
        if self.buckets < 2:
            raise ValueError("qdisc buckets must be >= 2")
        if self.bucket_width < 1:
            raise ValueError("qdisc bucket_width must be >= 1")

    # ---- representation hooks (eiffel: free slots + bucket tags) ----

    def _init_ring(self, H: int, Q: int) -> dict:
        return {
            "q_valid": jnp.zeros((H, Q), bool),
            "q_bucket": jnp.zeros((H, Q), jnp.int64),
            "cur_bucket": jnp.zeros((H,), jnp.int64),
        }

    def _room(self, qd):
        return jnp.any(~qd["q_valid"], axis=1)

    def _depth(self, qd):
        return jnp.sum(qd["q_valid"], axis=1, dtype=jnp.int64)

    def _insert(self, qd, ok, rank, dst, payload, now):
        # first free slot per host (argmax over the free mask)
        slot = jnp.argmax(~qd["q_valid"], axis=1).astype(jnp.int32)
        bucket = (rank // self.bucket_width) % self.buckets
        qd = dict(qd)
        qd["q_payload"] = soa.set_at(qd["q_payload"], ok, slot, payload)
        qd["q_dst"] = soa.set_at(
            qd["q_dst"], ok, slot, dst.astype(jnp.int32)
        )
        qd["q_rank"] = soa.set_at(qd["q_rank"], ok, slot, rank)
        qd["q_seq"] = soa.set_at(qd["q_seq"], ok, slot, qd["seq"])
        qd["q_enq_ts"] = soa.set_at(
            qd["q_enq_ts"], ok, slot, now.astype(jnp.int64)
        )
        qd["q_bucket"] = soa.set_at(qd["q_bucket"], ok, slot, bucket)
        qd["q_valid"] = soa.set_at(qd["q_valid"], ok, slot, True)
        return qd

    def _pop(self, qd, want):
        qd = dict(qd)
        valid = qd["q_valid"]
        # circular bucket distance from the service position; FIFO (seq)
        # inside a bucket
        rel = (qd["q_bucket"] - qd["cur_bucket"][:, None]) % self.buckets
        key = jnp.where(
            valid, rel * _SEQ_SPAN + qd["q_seq"],
            jnp.iinfo(jnp.int64).max,
        )
        pick = jnp.argmin(key, axis=1).astype(jnp.int32)
        present = jnp.any(valid, axis=1)
        have = want & present
        empty_hit = want & ~present
        payload = soa.get_at(qd["q_payload"], pick)
        dst = soa.get_at(qd["q_dst"], pick)
        enq_ts = soa.get_at(qd["q_enq_ts"], pick)
        rank = soa.get_at(qd["q_rank"], pick)
        bucket = soa.get_at(qd["q_bucket"], pick)
        size = pkt.total_bytes(payload).astype(jnp.int64)
        qd["q_valid"] = soa.set_at(qd["q_valid"], have, pick, False)
        qd["cur_bucket"] = jnp.where(have, bucket, qd["cur_bucket"])
        qd["q_bytes"] = qd["q_bytes"] - jnp.where(have, size, 0)
        qd["vtime"] = jnp.where(
            have, jnp.maximum(qd["vtime"], rank), qd["vtime"]
        )
        return qd, have, payload, dst, enq_ts, empty_hit
