"""Drop policies for the PIFO/Eiffel disciplines.

Two hooks, both deterministic (no RNG — reruns chain-prove identical):

- RED at enqueue: an EWMA of queue depth (fixed-point, weight 1/8) gates a
  count-based early-drop schedule — between min and max thresholds every
  ceil(1/p)-th admission is dropped where p ramps linearly to max_p, at or
  above max everything drops. The classic gentle-RED shape with the
  probabilistic coin replaced by the deterministic inter-drop count (the
  expectation of the geometric draw), which is what a chain-provable
  simulator wants anyway.

- CoDel at dequeue: the existing router AQM's target/interval control law
  (net/codel.py) folded in as a drop hook over the discipline's own pop —
  the constants, the control law, and the store/drop-mode state machine
  are IMPORTED from net/codel.py, not re-implemented, so the two paths
  cannot drift (the parity test drives both against the same schedule).

The pop callable a discipline supplies has signature
  pop(qd, want) -> (qd, have, payload, dst, enq_ts, empty_hit)
and must already have decremented qd["q_bytes"] for the popped packet
(CoDel's "good" test reads the post-pop backlog, exactly like
codel._pop_helper's new_total).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.net import packet as pkt
from shadow_tpu.net.codel import (
    DROP_UNROLL,
    INTERVAL_NS,
    TARGET_NS,
    _control_law,
)

# fixed-point shifts for the RED average: depth carried as depth << 8,
# EWMA weight 1/8
RED_FP_SHIFT = 8
RED_W_SHIFT = 3

DROP_NAMES = ("none", "red", "codel")


class RedConfig:
    def __init__(self, queue_slots: int, min_frac: float, max_frac: float,
                 max_p: float):
        if not (0.0 <= min_frac < max_frac <= 1.0):
            raise ValueError(
                "qdisc red thresholds need 0 <= min_frac < max_frac <= 1"
            )
        if not (0.0 < max_p <= 1.0):
            raise ValueError("qdisc red_max_p must be in (0, 1]")
        self.min_fp = int(min_frac * queue_slots) << RED_FP_SHIFT
        self.max_fp = int(max_frac * queue_slots) << RED_FP_SHIFT
        if self.max_fp <= self.min_fp:
            self.max_fp = self.min_fp + (1 << RED_FP_SHIFT)
        self.max_p = float(max_p)


def red_enqueue(qd: dict, attempt, depth, red: RedConfig | None):
    """EWMA + deterministic early drop. `attempt` masks admission
    attempts that have ring room; `depth` is the pre-enqueue queue depth
    [H] i64. Returns (qd, drop [H] bool)."""
    if red is None:
        return qd, jnp.zeros(attempt.shape, bool)
    qd = dict(qd)
    avg = qd["red_avg"]
    avg = jnp.where(
        attempt,
        avg + (((depth << RED_FP_SHIFT) - avg) >> RED_W_SHIFT),
        avg,
    )
    over = avg >= red.max_fp
    between = (avg >= red.min_fp) & ~over
    # deterministic inter-drop spacing: ceil(1/p) admissions per drop,
    # p ramping linearly min→max threshold (float64 like the codel law —
    # [H] control math, not the packet fast path)
    p = red.max_p * (avg - red.min_fp).astype(jnp.float64) / float(
        red.max_fp - red.min_fp
    )
    interval = jnp.ceil(1.0 / jnp.maximum(p, 1e-9)).astype(jnp.int64)
    cnt = qd["red_count"] + attempt.astype(jnp.int64)
    drop = attempt & (over | (between & (cnt >= interval)))
    qd["red_avg"] = avg
    # the counter runs only inside the ramp region; a drop (or leaving
    # the region) restarts the spacing
    qd["red_count"] = jnp.where(drop | ~between, 0, cnt)
    qd["drops_red"] = qd["drops_red"] + drop.astype(jnp.int64)
    return qd, drop


def _pop_bookkeeping(pop, qd, now, want):
    """One masked pop with CoDel sojourn bookkeeping — the discipline-
    generic form of codel._pop_helper. Returns
    (qd, have, payload, dst, enq_ts, ok_to_drop)."""
    ie0 = qd["interval_expire"]
    qd, have, payload, dst, enq_ts, empty_hit = pop(qd, want)
    sojourn = now - enq_ts
    good = (sojourn < TARGET_NS) | (qd["q_bytes"] < pkt.MTU)

    # good state: reset interval expiration
    ie = jnp.where(have & good, 0, ie0)
    # bad state, first time: arm the interval
    entering_bad = have & ~good & (ie0 == 0)
    ie = jnp.where(entering_bad, now + INTERVAL_NS, ie)
    # bad state, sustained a full interval: ok to drop
    ok_to_drop = have & ~good & (ie0 != 0) & (now >= ie0)
    # empty queue resets the interval expiration
    ie = jnp.where(empty_hit, 0, ie)

    qd = dict(qd)
    qd["interval_expire"] = ie
    return qd, have, payload, dst, enq_ts, ok_to_drop


def plain_dequeue(pop, qd: dict, now, mask):
    """No dequeue-side AQM: a single masked pop."""
    qd, have, payload, dst, enq_ts, _empty = pop(qd, mask)
    return qd, have, payload, dst, enq_ts


def codel_dequeue(pop, qd: dict, now, mask):
    """CoDel dequeue over a discipline pop — net/codel.py's dequeue state
    machine verbatim, with the ring pop abstracted and drops tallied
    per-host in qd["drops_codel"]. Returns
    (qd, have, payload, dst, enq_ts)."""
    qd, have, payload, dst, enq_ts, ok = _pop_bookkeeping(pop, qd, now, mask)

    # empty → store mode
    qd["drop_mode"] = jnp.where(mask & ~have, False, qd["drop_mode"])

    in_drop = mask & have & qd["drop_mode"]
    # delays low again → leave drop mode
    qd["drop_mode"] = jnp.where(in_drop & ~ok, False, qd["drop_mode"])

    # drop-mode loop: drop while now >= next_drop (bounded unroll). `ok`
    # tracks the okToDrop verdict of the packet CURRENTLY in hand.
    for _ in range(DROP_UNROLL):
        cond = mask & have & qd["drop_mode"] & (now >= qd["next_drop"])
        qd["drops_codel"] = qd["drops_codel"] + cond.astype(jnp.int64)
        qd["drop_count"] = qd["drop_count"] + cond.astype(jnp.int32)
        qd, have2, payload2, dst2, enq2, ok2 = _pop_bookkeeping(
            pop, qd, now, cond
        )
        have = jnp.where(cond, have2, have)
        payload = jnp.where(cond[:, None], payload2, payload)
        dst = jnp.where(cond, dst2, dst)
        enq_ts = jnp.where(cond, enq2, enq_ts)
        ok = jnp.where(cond, ok2, ok)
        qd["next_drop"] = jnp.where(
            cond & ok2,
            _control_law(qd["drop_count"], qd["next_drop"]),
            qd["next_drop"],
        )
        qd["drop_mode"] = jnp.where(cond & ~ok2, False, qd["drop_mode"])

    # store mode but the packet in hand should now drop: drop it, enter
    # drop mode
    trans = mask & have & ~qd["drop_mode"] & ok
    qd["drops_codel"] = qd["drops_codel"] + trans.astype(jnp.int64)
    qd, have3, payload3, dst3, enq3, _ok3 = _pop_bookkeeping(
        pop, qd, now, trans
    )
    have = jnp.where(trans, have3, have)
    payload = jnp.where(trans[:, None], payload3, payload)
    dst = jnp.where(trans, dst3, dst)
    enq_ts = jnp.where(trans, enq3, enq_ts)
    delta = qd["drop_count"] - qd["drop_count_last"]
    recently = now < (qd["next_drop"] + 16 * INTERVAL_NS)
    new_count = jnp.where(recently & (delta > 1), delta, 1).astype(jnp.int32)
    qd["drop_mode"] = jnp.where(trans, True, qd["drop_mode"])
    qd["drop_count"] = jnp.where(trans, new_count, qd["drop_count"])
    qd["next_drop"] = jnp.where(
        trans,
        _control_law(new_count, jnp.broadcast_to(now, new_count.shape)),
        qd["next_drop"],
    )
    qd["drop_count_last"] = jnp.where(
        trans, new_count, qd["drop_count_last"]
    )
    return qd, have, payload, dst, enq_ts
