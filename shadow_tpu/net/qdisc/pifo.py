"""Exact PIFO: rank-ordered push-in-first-out via masked compare-and-place.

The queue is a per-host sorted array — slots 0..len-1 hold packets ordered
by (rank, enqueue seq) ascending. Enqueue computes the insertion position
with one broadcast compare (stable: equal ranks keep arrival order),
then materializes the insert as two elementwise selects over a
shift-right; dequeue takes slot 0 and shift-lefts. No scatters, no sorts —
the whole [H, Q] plane moves as full-bandwidth selects, which is why Q
should stay modest (the Eiffel variant is the layout-friendly path for
large Q: O(1)-ish bucket scan instead of O(Q) shift traffic per op).

`DeviceQueueDiscipline` here is also the shared base for qdisc/eiffel.py:
admission (overflow + RED), rank computation, drop-hook dispatch and the
qdisc.* counter plane are common; only the ring representation
(_room/_depth/_insert/_pop) differs.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.net import packet as pkt
from shadow_tpu.net.qdisc import SUB, Discipline, drops, ranks


def _shift_insert(arr, ok, pos, val):
    """Insert val at pos, shifting slots pos.. right by one, where ok."""
    Q = arr.shape[1]
    j = jnp.arange(Q, dtype=jnp.int32)
    shifted = jnp.concatenate([arr[:, :1], arr[:, :-1]], axis=1)
    if arr.ndim == 3:
        jj, pp = j[None, :, None], pos[:, None, None]
        ins = jnp.where(
            jj < pp, arr,
            jnp.where(jj == pp, jnp.asarray(val, arr.dtype)[:, None, :],
                      shifted),
        )
        return jnp.where(ok[:, None, None], ins, arr)
    jj, pp = j[None, :], pos[:, None]
    v = jnp.asarray(val, arr.dtype)
    if v.ndim == 1:
        v = v[:, None]
    ins = jnp.where(jj < pp, arr, jnp.where(jj == pp, v, shifted))
    return jnp.where(ok[:, None], ins, arr)


def _shift_left(arr, have):
    shifted = jnp.concatenate([arr[:, 1:], arr[:, -1:]], axis=1)
    if arr.ndim == 3:
        return jnp.where(have[:, None, None], shifted, arr)
    return jnp.where(have[:, None], shifted, arr)


class DeviceQueueDiscipline(Discipline):
    """Shared machinery for the device-queue disciplines (pifo/eiffel):
    owns the `subs["qdisc"]` SoA plane (every leaf [H]-leading — islands
    sharding, fleet stacking, checkpoints and rollback compose for
    free), admission with RED, rank functions, and the CoDel drop hook."""

    def __init__(self, queue_slots: int = 64, ranker: ranks.Ranker | None = None,
                 drop: str = "none", red: drops.RedConfig | None = None,
                 host_class=None):
        if drop not in drops.DROP_NAMES:
            raise ValueError(f"unknown qdisc drop {drop!r}")
        self.queue_slots = int(queue_slots)
        self.ranker = ranker or ranks.FifoRank()
        self.drop = drop
        self.red = red if drop == "red" else None
        self.host_class = host_class  # [H] ints or None (per-socket classes)
        self.num_hosts = 0
        self.payload_words = 12

    def attach(self, stack) -> None:
        self.num_hosts = stack.num_hosts
        self.payload_words = stack.payload_words

    def init_subs(self) -> dict:
        import numpy as np

        H, Q, P = self.num_hosts, self.queue_slots, self.payload_words
        C = self.ranker.classes
        if self.host_class is None:
            cls = jnp.full((H,), -1, jnp.int32)
        else:
            cls = jnp.asarray(np.asarray(self.host_class, np.int32))
        z64 = lambda: jnp.zeros((H,), jnp.int64)  # noqa: E731
        qd = {
            "q_payload": jnp.zeros((H, Q, P), jnp.int32),
            "q_dst": jnp.zeros((H, Q), jnp.int32),
            "q_rank": jnp.zeros((H, Q), jnp.int64),
            "q_seq": jnp.zeros((H, Q), jnp.int64),
            "q_enq_ts": jnp.zeros((H, Q), jnp.int64),
            "q_bytes": z64(),
            "seq": z64(),
            "cls": cls,
            # wfq virtual clock + per-class finish times; shaping spacing
            "vtime": z64(),
            "finish": jnp.zeros((H, C), jnp.int64),
            "shape_next": jnp.zeros((H, C), jnp.int64),
            # codel drop-hook state (net/codel.py state machine)
            "drop_mode": jnp.zeros((H,), bool),
            "interval_expire": z64(),
            "next_drop": z64(),
            "drop_count": jnp.zeros((H,), jnp.int32),
            "drop_count_last": jnp.zeros((H,), jnp.int32),
            # red state
            "red_avg": z64(),
            "red_count": z64(),
            # observability counters (schema v17 qdisc.*)
            "enqueues": z64(),
            "dequeues": z64(),
            "drops_overflow": z64(),
            "drops_red": z64(),
            "drops_codel": z64(),
            "sojourn_sum": z64(),
            "depth_peak": z64(),
        }
        qd.update(self._init_ring(H, Q))
        return {SUB: qd}

    # ---- representation hooks (pifo: sorted array) ----

    def _init_ring(self, H: int, Q: int) -> dict:
        return {"q_len": jnp.zeros((H,), jnp.int32)}

    def _room(self, qd):
        return qd["q_len"] < self.queue_slots

    def _depth(self, qd):
        return qd["q_len"].astype(jnp.int64)

    def _insert(self, qd, ok, rank, dst, payload, now):
        Q = self.queue_slots
        j = jnp.arange(Q, dtype=jnp.int32)[None, :]
        valid = j < qd["q_len"][:, None]
        # stable compare-and-place: existing equal-rank packets carry
        # smaller seqs, so the new packet lands after them
        pos = jnp.sum(
            valid & (qd["q_rank"] <= rank[:, None]), axis=1
        ).astype(jnp.int32)
        qd = dict(qd)
        qd["q_payload"] = _shift_insert(qd["q_payload"], ok, pos, payload)
        qd["q_dst"] = _shift_insert(
            qd["q_dst"], ok, pos, dst.astype(jnp.int32)
        )
        qd["q_rank"] = _shift_insert(qd["q_rank"], ok, pos, rank)
        qd["q_seq"] = _shift_insert(qd["q_seq"], ok, pos, qd["seq"])
        qd["q_enq_ts"] = _shift_insert(
            qd["q_enq_ts"], ok, pos, now.astype(jnp.int64)
        )
        qd["q_len"] = qd["q_len"] + ok.astype(jnp.int32)
        return qd

    def _pop(self, qd, want):
        qd = dict(qd)
        present = qd["q_len"] > 0
        have = want & present
        empty_hit = want & ~present
        payload = qd["q_payload"][:, 0]
        dst = qd["q_dst"][:, 0]
        enq_ts = qd["q_enq_ts"][:, 0]
        rank = qd["q_rank"][:, 0]
        size = pkt.total_bytes(payload).astype(jnp.int64)
        for k in ("q_payload", "q_dst", "q_rank", "q_seq", "q_enq_ts"):
            qd[k] = _shift_left(qd[k], have)
        qd["q_len"] = qd["q_len"] - have.astype(jnp.int32)
        qd["q_bytes"] = qd["q_bytes"] - jnp.where(have, size, 0)
        qd["vtime"] = jnp.where(
            have, jnp.maximum(qd["vtime"], rank), qd["vtime"]
        )
        return qd, have, payload, dst, enq_ts, empty_hit

    # ---- Discipline interface ----

    def nonempty(self, state):
        return self._depth(state.subs[SUB]) > 0

    def enqueue(self, state, mask, dst, payload, now):
        qd = dict(state.subs[SUB])
        now64 = now.astype(jnp.int64)
        depth = self._depth(qd)
        room = self._room(qd)
        attempt = mask & room
        qd, red_drop = drops.red_enqueue(qd, attempt, depth, self.red)
        ok = attempt & ~red_drop
        size = pkt.total_bytes(payload).astype(jnp.int64)
        qd, rank = self.ranker.rank(qd, ok, payload, now64, size)
        qd = self._insert(qd, ok, rank, dst, payload, now64)
        qd["q_bytes"] = qd["q_bytes"] + jnp.where(ok, size, 0)
        qd["seq"] = qd["seq"] + ok.astype(jnp.int64)
        qd["enqueues"] = qd["enqueues"] + ok.astype(jnp.int64)
        qd["drops_overflow"] = (
            qd["drops_overflow"] + (mask & ~room).astype(jnp.int64)
        )
        qd["depth_peak"] = jnp.maximum(
            qd["depth_peak"], depth + ok.astype(jnp.int64)
        )
        return state.with_sub(SUB, qd), ok

    def dequeue(self, state, now, want):
        qd = dict(state.subs[SUB])
        if self.drop == "codel":
            qd, have, payload, dst, enq_ts = drops.codel_dequeue(
                self._pop, qd, now, want
            )
        else:
            qd, have, payload, dst, enq_ts = drops.plain_dequeue(
                self._pop, qd, now, want
            )
        qd["dequeues"] = qd["dequeues"] + have.astype(jnp.int64)
        qd["sojourn_sum"] = qd["sojourn_sum"] + jnp.where(
            have, now - enq_ts, 0
        )
        return state.with_sub(SUB, qd), have, payload, dst


class PifoDiscipline(DeviceQueueDiscipline):
    name = "pifo"
