"""Rank functions for the PIFO/Eiffel disciplines.

A rank function maps an admitted packet to an int64 rank; the queue serves
ranks ascending with the per-host enqueue sequence number as the FIFO
tiebreak (so equal-rank packets keep arrival order — PIFO's push-in
stability contract). Rankers may carry per-host running state in the qdisc
sub so they step inside the window kernel:

  fifo   rank 0 for every packet — the sequence tiebreak makes the queue
         a plain FIFO (the parity arm for compat and Eiffel-vs-exact
         equivalence tests).
  prio   the packet's app-priority word (pkt.W_PRIORITY), strict priority.
  wfq    weighted fair queueing virtual finish times per flow class:
         vft = max(vtime[h], finish[h, c]) + size * inv_weight[c], with
         the per-host virtual clock advanced to each dequeued rank.

Flow class: per-packet ``socket_slot % classes`` unless the host carries a
config override (qdisc.overrides host-prefix → class pins ALL the host's
packets to that class; the [H] class table rides in the qdisc sub so the
islands engine shards it like any other host-leading array).

Token-bucket shaping composes with any ranker as an eligibility term:
shaped classes keep a virtual next-eligible time that advances by
size × ns_per_byte(rate) per packet, and the effective rank is
max(base_rank, eligible_time) — later-eligible packets sink down the
queue instead of head-blocking it. Intended for the time-like rankers
(fifo/prio, where ranks are comparable to timestamps); with wfq the max
still yields a valid monotone schedule but mixes virtual-time units.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import simtime, soa
from shadow_tpu.net import packet as pkt

# fixed-point scale for 1/weight in the virtual-finish increment
WFQ_SCALE = 256

RANK_NAMES = ("fifo", "prio", "wfq")


class Ranker:
    """rank(qd, mask, payload, now, size) -> (qd, rank [H] i64)."""

    name = "fifo"
    classes = 1

    def __init__(self, classes: int = 1, weights=None, shaping=None):
        self.classes = int(classes)
        weights = list(weights) if weights else [1.0] * self.classes
        if len(weights) != self.classes:
            raise ValueError(
                f"qdisc weights length {len(weights)} != classes "
                f"{self.classes}"
            )
        if any(w <= 0 for w in weights):
            raise ValueError("qdisc weights must be > 0")
        self._inv_w = jnp.asarray(
            [max(1, round(WFQ_SCALE / w)) for w in weights], jnp.int64
        )
        # per-class shaping rate → ns per wire byte (0 = unshaped)
        npb = [0] * self.classes
        for c, bits in sorted((shaping or {}).items()):
            npb[int(c)] = max(1, simtime.NS_PER_SEC * 8 // int(bits))
        self._ns_per_byte = jnp.asarray(npb, jnp.int64)
        self.shaped = any(npb)

    def _cls(self, qd, payload):
        """Per-packet flow class [H] i32: host override else socket slot
        mod classes."""
        sock = payload[:, pkt.W_SOCKET] % jnp.int32(self.classes)
        return jnp.where(qd["cls"] >= 0, qd["cls"], sock)

    def _base(self, qd, mask, payload, now, size, cls):
        return qd, jnp.zeros(mask.shape, jnp.int64)

    def rank(self, qd, mask, payload, now, size):
        cls = self._cls(qd, payload)
        qd, base = self._base(qd, mask, payload, now, size, cls)
        if not self.shaped:
            return qd, base
        npb = self._ns_per_byte[cls]
        shaped = mask & (npb > 0)
        elig = jnp.maximum(now.astype(jnp.int64), soa.get_at(
            qd["shape_next"], cls
        ))
        qd = dict(qd)
        qd["shape_next"] = soa.set_at(
            qd["shape_next"], shaped, cls, elig + size * npb
        )
        return qd, jnp.where(shaped, jnp.maximum(base, elig), base)


class FifoRank(Ranker):
    name = "fifo"


class PrioRank(Ranker):
    name = "prio"

    def _base(self, qd, mask, payload, now, size, cls):
        return qd, payload[:, pkt.W_PRIORITY].astype(jnp.int64)


class WfqRank(Ranker):
    name = "wfq"

    def _base(self, qd, mask, payload, now, size, cls):
        start = jnp.maximum(qd["vtime"], soa.get_at(qd["finish"], cls))
        vft = start + size * self._inv_w[cls]
        qd = dict(qd)
        qd["finish"] = soa.set_at(qd["finish"], mask, cls, vft)
        return qd, vft


def make_ranker(rank: str, classes: int = 1, weights=None,
                shaping=None) -> Ranker:
    cls = {"fifo": FifoRank, "prio": PrioRank, "wfq": WfqRank}.get(rank)
    if cls is None:
        raise ValueError(f"unknown qdisc rank {rank!r}")
    return cls(classes=classes, weights=weights, shaping=shaping)
