"""Per-interface scheduling plane: the qdisc discipline interface.

The reference models exactly two egress disciplines (fifo-by-priority and
round-robin-over-sockets, network_queuing_disciplines.c); everything else —
PIFO-style programmable scheduling, Eiffel's bucketed approximate priority
queues, WFQ, shaping, AQM drops on the SEND side — is out of its reach.
This package lifts the egress queue behind a small discipline interface so
the NIC send pump (net/stack.py) is policy-agnostic:

  nonempty(state)                      -> [H] bool
  enqueue(state, mask, dst, payload, now) -> (state, admitted)
  dequeue(state, now, want)            -> (state, sent, payload, dst)
  note_direct(state, mask, payload)    -> state   (uncontended fast path)

Two families implement it:

- ``fifo`` / ``roundrobin`` wrap the existing per-host NIC send ring
  (net/nic.py) unchanged — zero new state, and the default ``fifo`` arm is
  bit-identical to pre-qdisc builds (the compat regression test pins the
  audit chains).
- ``pifo`` / ``eiffel`` own a `subs["qdisc"]` SoA plane of fixed-capacity
  [H, Q] rings (every leaf [H]-leading, so islands sharding, fleet
  stacking, checkpoint slices and rollback all compose for free), with
  rank functions (qdisc/ranks.py: fifo / prio / wfq virtual finish times +
  token-bucket shaping as a rank-eligibility term) and drop policies
  (qdisc/drops.py: deterministic RED at enqueue, CoDel — folded in from
  net/codel.py — as a dequeue hook).

Kernel-shape discipline: no scatters (soa.set_at one-hot writes), no sorts
(PIFO inserts by masked compare-and-place, Eiffel dequeues by argmin over a
circular bucket scan) — the HLO ledger carries a variant cell per
discipline to keep it that way.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.net import nic

SUB = "qdisc"


class Discipline:
    """Egress-discipline interface the send pump drives.

    Implementations operate on the whole SimState so ring-wrapping
    disciplines (fifo/roundrobin) can reuse the NIC sub while
    device-queue disciplines (pifo/eiffel) own their own sub plane.
    """

    name = "base"

    def attach(self, stack) -> None:
        """Bind build-time stack facts (host count, payload width,
        sockets per host). Called once from NetStack.__init__."""

    def init_subs(self) -> dict:
        """Extra SimState subs this discipline owns ({} for ring
        wrappers)."""
        return {}

    def nonempty(self, state):
        raise NotImplementedError

    def enqueue(self, state, mask, dst, payload, now):
        raise NotImplementedError

    def dequeue(self, state, now, want):
        raise NotImplementedError

    def note_direct(self, state, mask, payload):
        """Observe a packet that took the uncontended direct-send path
        (bypassing the queue). Only round-robin needs it (last-served
        socket bookkeeping)."""
        return state


class FifoDiscipline(Discipline):
    """The reference's default qdisc: the NIC ring in arrival order
    (arrival order IS priority order for device apps)."""

    name = "fifo"

    def nonempty(self, state):
        n = state.subs[nic.SUB]
        return n.q_head < n.q_tail

    def enqueue(self, state, mask, dst, payload, now):
        n, ok = nic.enqueue_send(state.subs[nic.SUB], mask, dst, payload)
        return state.with_sub(nic.SUB, n), ok

    def dequeue(self, state, now, want):
        n = state.subs[nic.SUB]
        payload, dst, has_pkt = nic.peek_send(n)
        do = want & has_pkt
        n = nic.pop_send(n, do)
        return state.with_sub(nic.SUB, n), do, payload, dst


class RoundRobinDiscipline(Discipline):
    """Round-robin over sockets (network_queuing_disciplines.c RR): the
    next non-empty socket after the last-served one sends its oldest
    packet; mid-ring slots are consumed via the taken-mask helpers."""

    name = "roundrobin"

    def __init__(self):
        self.sockets_per_host = 8

    def attach(self, stack) -> None:
        self.sockets_per_host = stack.sockets_per_host

    def nonempty(self, state):
        n = state.subs[nic.SUB]
        return n.q_head < n.q_tail

    def enqueue(self, state, mask, dst, payload, now):
        n, ok = nic.enqueue_send(state.subs[nic.SUB], mask, dst, payload)
        return state.with_sub(nic.SUB, n), ok

    def dequeue(self, state, now, want):
        n = state.subs[nic.SUB]
        payload, dst, has_pkt, rr_slot = nic.peek_send_rr(
            n, self.sockets_per_host
        )
        do = want & has_pkt
        n = nic.pop_send_rr(n, do, rr_slot)
        return state.with_sub(nic.SUB, n), do, payload, dst

    def note_direct(self, state, mask, payload):
        from shadow_tpu.net import packet as pkt

        n = state.subs[nic.SUB]
        n = n.replace(last_socket=jnp.where(
            mask, payload[:, pkt.W_SOCKET], n.last_socket
        ))
        return state.with_sub(nic.SUB, n)


def make_discipline(qdisc: str) -> Discipline:
    """Legacy-string constructor (experimental.interface_qdisc values).
    Device-queue disciplines (pifo/eiffel) carry config and are built by
    sim.py from the `qdisc:` section instead."""
    if qdisc == "fifo":
        return FifoDiscipline()
    if qdisc == "roundrobin":
        return RoundRobinDiscipline()
    raise ValueError(f"unknown qdisc {qdisc!r}")
