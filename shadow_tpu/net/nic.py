"""Per-host network interface: token buckets + send queue (qdisc).

Reference (src/main/host/network_interface.c):
- Token buckets both directions; refill every 1 ms with bytes = bandwidth ×
  1ms; capacity = one refill + MTU (:99-126, :196-228). A refill task
  self-reschedules only while traffic is pending (:127-193).
- Send loop drains the qdisc while send tokens ≥ MTU, consuming each
  packet's full wire length (:497-539).
- Receive loop drains the upstream router while rx tokens ≥ MTU (:448-485).
- During the bootstrap period bandwidth is unlimited (:459-481).

TPU-first differences:
- Refills are LAZY: effective tokens are recomputed from the 1ms grid
  (anchored at t=0) whenever the bucket is touched — identical arithmetic to
  the reference's periodic refill, with no refill events at all. The only
  scheduled NIC events are send/receive pumps, and those self-defer to the
  next grid tick when out of tokens.
- One packet moves per pump event; the pump re-emits itself at the same
  timestamp while work remains. All hosts pump in parallel each micro-step,
  so per-window cost is max-packets-per-host, not total packets.
- The send queue is a single per-host ring ordered FIFO-by-priority
  (the reference's default fifo qdisc selects by packet app priority).
  The round-robin-over-sockets qdisc variant selects mid-ring via the
  helpers at the bottom of this module.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime, soa
from shadow_tpu.core.state import PAYLOAD_WORDS
from shadow_tpu.net import packet as pkt

REFILL_NS = simtime.NS_PER_MS  # refill interval (1 ms)

SUB = "nic"


@struct.dataclass
class NicState:
    # token buckets, bytes
    tx_rem: jnp.ndarray  # [H] i64
    rx_rem: jnp.ndarray  # [H] i64
    tx_tick: jnp.ndarray  # [H] i64 — last refill grid tick applied
    rx_tick: jnp.ndarray  # [H] i64
    tx_refill: jnp.ndarray  # [H] i64 bytes per interval
    rx_refill: jnp.ndarray  # [H] i64
    tx_cap: jnp.ndarray  # [H] i64 = refill + MTU
    rx_cap: jnp.ndarray  # [H] i64
    # send ring [H, NQ]
    q_payload: jnp.ndarray  # [H, NQ, P] i32
    q_dst: jnp.ndarray  # [H, NQ] i32
    q_head: jnp.ndarray  # [H] i32 (monotonic; slot = idx % NQ)
    q_tail: jnp.ndarray  # [H] i32
    # pump-pending flags (reference isRefillPending analog for pump events)
    send_pending: jnp.ndarray  # [H] bool
    recv_pending: jnp.ndarray  # [H] bool
    # round-robin qdisc state (network_queuing_disciplines.c): which socket
    # was served last, and which ring slots were consumed out of order
    last_socket: jnp.ndarray  # [H] i32 (-1 = none yet)
    q_taken: jnp.ndarray  # [H, NQ] bool
    # drop counter for send-ring overflow
    sendq_dropped: jnp.ndarray  # [] i64
    # per-host byte/packet tracker (tracker.c:215-247 analog)
    tx_packets: jnp.ndarray  # [H] i64
    tx_bytes: jnp.ndarray  # [H] i64
    rx_packets: jnp.ndarray  # [H] i64
    rx_bytes: jnp.ndarray  # [H] i64


def init(bw_up_bits, bw_down_bits, queue_slots: int = 64,
         payload_words: int = PAYLOAD_WORDS) -> NicState:
    """bw_*_bits: [H] int64 bits/sec per host."""
    H = bw_up_bits.shape[0]
    tx_refill = jnp.maximum(
        (jnp.asarray(bw_up_bits, jnp.int64) // 8) * REFILL_NS // simtime.NS_PER_SEC,
        1,
    )
    rx_refill = jnp.maximum(
        (jnp.asarray(bw_down_bits, jnp.int64) // 8) * REFILL_NS // simtime.NS_PER_SEC,
        1,
    )
    tx_cap = tx_refill + pkt.MTU
    rx_cap = rx_refill + pkt.MTU
    NQ = queue_slots
    return NicState(
        tx_rem=tx_cap,
        rx_rem=rx_cap,
        tx_tick=jnp.zeros((H,), jnp.int64),
        rx_tick=jnp.zeros((H,), jnp.int64),
        tx_refill=tx_refill,
        rx_refill=rx_refill,
        tx_cap=tx_cap,
        rx_cap=rx_cap,
        q_payload=jnp.zeros((H, NQ, payload_words), jnp.int32),
        q_dst=jnp.zeros((H, NQ), jnp.int32),
        q_head=jnp.zeros((H,), jnp.int32),
        q_tail=jnp.zeros((H,), jnp.int32),
        send_pending=jnp.zeros((H,), bool),
        recv_pending=jnp.zeros((H,), bool),
        last_socket=jnp.full((H,), -1, jnp.int32),
        q_taken=jnp.zeros((H, NQ), bool),
        sendq_dropped=jnp.zeros((), jnp.int64),
        tx_packets=jnp.zeros((H,), jnp.int64),
        tx_bytes=jnp.zeros((H,), jnp.int64),
        rx_packets=jnp.zeros((H,), jnp.int64),
        rx_bytes=jnp.zeros((H,), jnp.int64),
    )


def count_tx(nic: NicState, mask, size) -> NicState:
    return nic.replace(
        tx_packets=nic.tx_packets + mask.astype(jnp.int64),
        tx_bytes=nic.tx_bytes + jnp.where(mask, size.astype(jnp.int64), 0),
    )


def count_rx(nic: NicState, mask, size) -> NicState:
    return nic.replace(
        rx_packets=nic.rx_packets + mask.astype(jnp.int64),
        rx_bytes=nic.rx_bytes + jnp.where(mask, size.astype(jnp.int64), 0),
    )


def lazy_refill(rem, tick, refill, cap, now, mask=None):
    """Apply all grid refills since `tick` (the reference applies one refill
    per elapsed interval, clamped to capacity — with capacity ≤ refill+MTU a
    single interval always fills the bucket, so the clamp form is exact).

    ``mask`` gates which lanes update: handler lanes whose host is not
    processing a real event carry garbage `now` values (NEVER) and must not
    touch the bucket state.
    """
    now_tick = now // REFILL_NS
    new_rem = jnp.minimum(cap, rem + (now_tick - tick) * refill)
    new_rem = jnp.where(now_tick > tick, new_rem, rem)
    new_tick = jnp.maximum(tick, now_tick)
    if mask is not None:
        new_rem = jnp.where(mask, new_rem, rem)
        new_tick = jnp.where(mask, new_tick, tick)
    return new_rem, new_tick


def next_refill_time(now):
    return (now // REFILL_NS + 1) * REFILL_NS


def enqueue_send(nic: NicState, mask, dst_host, payload) -> tuple[NicState, jnp.ndarray]:
    """Append a packet to the send ring, FIFO order. Returns (nic, ok_mask).

    Priority-ordered selection: the ring is kept in arrival order, and
    arrival order IS priority order for device apps (priority = emission
    sequence), matching the reference's fifo qdisc selection by app priority.
    """
    H, NQ = nic.q_dst.shape
    room = (nic.q_tail - nic.q_head) < NQ
    ok = mask & room
    slot = nic.q_tail % NQ
    nic = nic.replace(
        q_payload=soa.set_at(nic.q_payload, ok, slot, payload),
        q_dst=soa.set_at(nic.q_dst, ok, slot, dst_host.astype(jnp.int32)),
        q_tail=nic.q_tail + ok.astype(jnp.int32),
        sendq_dropped=nic.sendq_dropped + jnp.sum(mask & ~room, dtype=jnp.int64),
    )
    return nic, ok


def peek_send(nic: NicState):
    """Head packet per host: (payload [H,P], dst [H], nonempty [H]).
    One-hot ring reads — row gathers serialize on TPU (soa.get_at)."""
    nonempty = nic.q_head < nic.q_tail
    slot = nic.q_head % nic.q_dst.shape[1]
    payload = soa.get_at(nic.q_payload, slot)
    dst = soa.get_at(nic.q_dst, slot)
    return payload, dst, nonempty


def pop_send(nic: NicState, mask) -> NicState:
    return nic.replace(q_head=nic.q_head + mask.astype(jnp.int32))


# ---------------------------------------------------------------------------
# round-robin-over-sockets qdisc (network_queuing_disciplines.c RR variant):
# the next non-empty socket after the last-served one sends its OLDEST
# queued packet. Mid-ring consumption marks slots taken; the head advances
# lazily past taken slots.
# ---------------------------------------------------------------------------


def _rr_order(nic: NicState, sockets_per_host: int):
    """Per ring position j (age order): (selectable, rr_key, slot index)."""
    H, NQ = nic.q_dst.shape
    j = jnp.arange(NQ, dtype=jnp.int32)[None, :]  # [1, NQ] age rank
    slot = (nic.q_head[:, None] + j) % NQ
    hosts = jnp.arange(H, dtype=jnp.int32)[:, None]
    present = (j < (nic.q_tail - nic.q_head)[:, None]) & ~nic.q_taken[
        hosts, slot
    ]
    sock = nic.q_payload[hosts, slot, pkt.W_SOCKET]
    S = sockets_per_host
    cycle = (sock - nic.last_socket[:, None] - 1) % S
    key = jnp.where(present, cycle * NQ + j, jnp.int32(S * NQ + NQ))
    return present, key, slot


def peek_send_rr(nic: NicState, sockets_per_host: int):
    """RR head packet per host: (payload [H,P], dst [H], nonempty [H],
    slot [H])."""
    present, key, slot = _rr_order(nic, sockets_per_host)
    pick = jnp.argmin(key, axis=1).astype(jnp.int32)
    nonempty = jnp.any(present, axis=1)
    sel = soa.get_at(slot, pick)
    return (
        soa.get_at(nic.q_payload, sel), soa.get_at(nic.q_dst, sel),
        nonempty, sel,
    )


def pop_send_rr(nic: NicState, mask, slot) -> NicState:
    """Consume the RR-selected slot, remember its socket, advance the head
    past any leading taken slots."""
    H, NQ = nic.q_dst.shape
    hosts = jnp.arange(H, dtype=jnp.int32)
    cols = jnp.arange(NQ, dtype=jnp.int32)
    hit = mask[:, None] & (cols[None, :] == slot[:, None])
    taken = nic.q_taken | hit
    sock = nic.q_payload[hosts, slot, pkt.W_SOCKET]
    last = jnp.where(mask, sock, nic.last_socket)
    # first age-rank that is present and not taken → head advance count
    j = jnp.arange(NQ, dtype=jnp.int32)[None, :]
    ring_slot = (nic.q_head[:, None] + j) % NQ
    live = (j < (nic.q_tail - nic.q_head)[:, None]) & ~taken[
        hosts[:, None], ring_slot
    ]
    first_live = jnp.where(
        jnp.any(live, axis=1),
        jnp.argmax(live, axis=1).astype(jnp.int32),
        (nic.q_tail - nic.q_head),
    )
    # clear taken flags for slots the head passes over
    taken = taken & ~_ring_mask(taken.shape, nic.q_head, first_live)
    return nic.replace(
        q_head=nic.q_head + first_live,
        q_taken=taken,
        last_socket=last,
    )


def _ring_mask(shape, head, count):
    """[H, NQ] bool: True for ring slots head..head+count (mod NQ)."""
    H, NQ = shape
    cols = jnp.arange(NQ, dtype=jnp.int32)[None, :]
    rel = (cols - (head[:, None] % NQ)) % NQ
    return rel < count[:, None]
