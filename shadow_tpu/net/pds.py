"""Per-packet delivery-status recording (reference packet.c:37-77 PDS_*).

The trail itself rides in the packet's 13th payload word (see
shadow_tpu.net.packet: W_TRAIL, stamp, decode_trail) when the simulation is
built with ``experimental.packet_trails``. This module holds the per-host
REGISTERS that preserve a trail at the moments a packet leaves the
simulation — dropped or delivered — so the full stage chain of the last
such packet per host is reconstructable afterwards (the reference prints
its trail into the pcap/debug log the same way).

All writes are masked elementwise selects over [H]; zero scatter, zero
cost when the sub is absent (simulations without packet_trails).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.net import packet as pkt

SUB = "pds"


def init(num_hosts: int) -> dict:
    H = num_hosts
    return {
        # last drop seen by each host (the DROPPING side's host index)
        "drop_trail": jnp.zeros((H,), jnp.int32),
        "drop_time": jnp.zeros((H,), jnp.int64),
        "drop_src": jnp.zeros((H,), jnp.int32),
        "drop_count": jnp.zeros((H,), jnp.int64),
        # last in-order delivery per destination host
        "deliver_trail": jnp.zeros((H,), jnp.int32),
        "deliver_time": jnp.zeros((H,), jnp.int64),
    }


def record_drop(state, mask, payload, cause, now):
    """Record masked hosts' in-hand packet as dropped with `cause` shifted
    onto its trail. No-op without the pds sub or the trail word."""
    sub = state.subs.get(SUB)
    if sub is None or payload.shape[-1] <= pkt.W_TRAIL:
        return state
    tr = (payload[..., pkt.W_TRAIL] << 4) | jnp.int32(cause)
    new = dict(sub)
    new["drop_trail"] = jnp.where(mask, tr, sub["drop_trail"])
    new["drop_time"] = jnp.where(
        mask, jnp.broadcast_to(now, mask.shape).astype(jnp.int64),
        sub["drop_time"],
    )
    new["drop_src"] = jnp.where(
        mask, payload[..., pkt.W_SRC_HOST], sub["drop_src"]
    )
    new["drop_count"] = sub["drop_count"] + mask.astype(jnp.int64)
    return state.with_sub(SUB, new)


def record_delivery(state, mask, payload, now):
    sub = state.subs.get(SUB)
    if sub is None or payload.shape[-1] <= pkt.W_TRAIL:
        return state
    tr = (payload[..., pkt.W_TRAIL] << 4) | jnp.int32(pkt.PDS_DELIVERED)
    new = dict(sub)
    new["deliver_trail"] = jnp.where(mask, tr, sub["deliver_trail"])
    new["deliver_time"] = jnp.where(
        mask, jnp.broadcast_to(now, mask.shape).astype(jnp.int64),
        sub["deliver_time"],
    )
    return state.with_sub(SUB, new)


def drop_report(sim) -> list[dict]:
    """Decoded last-drop registers per host (empty without packet_trails)."""
    import jax

    sub = sim.state.subs.get(SUB)
    if sub is None:
        return []
    got = jax.device_get(sub)
    out = []
    for h in range(got["drop_trail"].shape[0]):
        if int(got["drop_count"][h]) == 0:
            continue
        out.append({
            "host": h,
            "src_host": int(got["drop_src"][h]),
            "time_ns": int(got["drop_time"][h]),
            "drops_seen": int(got["drop_count"][h]),
            "trail": pkt.decode_trail(int(got["drop_trail"][h])),
        })
    return out
