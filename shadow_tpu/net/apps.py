"""Device-side application models.

The reference runs real binaries (test_phold.c, tgen) as managed processes.
shadow_tpu supports that via the CPU interposition plane, but ALSO offers
fully on-device app models — vectorized behaviors that generate the same
traffic patterns with zero CPU↔TPU round-trips. These are the workloads for
the staged benchmark configs (BASELINE.md) and the analog of the reference's
PHOLD PDES canary (src/test/phold/test_phold.c: peers exchange
random-destination messages; msgload seeds circulate until runtime ends).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import Emitter, EventView, draw_uniform
from shadow_tpu.core.state import KIND_APP_MSG, NetParams, SimState
from shadow_tpu.net import link


class PholdApp:
    """PHOLD: each received message is forwarded to a random peer over the
    simulated network; message population = hosts × msgload; senders stop
    once sim time passes `runtime` (phold.yaml args: msgload, size, runtime).
    """

    SUB = "phold"

    def __init__(
        self,
        num_hosts: int,
        msgload: int = 1,
        size_bytes: int = 64,
        start_time: int = simtime.NS_PER_SEC,
        runtime: int = 5 * simtime.NS_PER_SEC,
    ):
        self.num_hosts = num_hosts
        self.msgload = msgload
        self.size_bytes = size_bytes
        self.start_time = start_time
        self.stop_sending = start_time + runtime

    def init_sub(self) -> dict:
        H = self.num_hosts
        return {
            "received": jnp.zeros((H,), dtype=jnp.int64),
            "forwarded": jnp.zeros((H,), dtype=jnp.int64),
        }

    def initial_events(self):
        """msgload seed messages per host, self-delivered at start_time; the
        first processing forwards each to a random peer."""
        out = []
        for h in range(self.num_hosts):
            for _ in range(self.msgload):
                out.append(
                    (self.start_time, h, h, KIND_APP_MSG, [self.size_bytes])
                )
        return out

    def handle_msg(
        self, state: SimState, ev: EventView, emitter: Emitter, params: NetParams
    ) -> SimState:
        H = self.num_hosts
        hosts = jnp.arange(H, dtype=jnp.int32)
        sub = state.subs[self.SUB]
        sub = dict(sub)
        sub["received"] = sub["received"] + ev.mask.astype(jnp.int64)

        send_mask = ev.mask & (ev.time < self.stop_sending)
        # Uniform peer choice over the other H-1 hosts.
        state, u = draw_uniform(state, send_mask)
        if H > 1:
            dst = jnp.floor(u * (H - 1)).astype(jnp.int32)
            dst = jnp.clip(dst, 0, H - 2)
            dst = dst + (dst >= hosts)  # skip self
        else:
            dst = hosts
        sub["forwarded"] = sub["forwarded"] + send_mask.astype(jnp.int64)
        subs = dict(state.subs)
        subs[self.SUB] = sub
        state = state.replace(subs=subs)
        return link.send(
            state,
            emitter,
            send_mask,
            dst,
            ev.time,
            KIND_APP_MSG,
            ev.payload,
            params,
            self.size_bytes,
        )

    def handlers(self):
        return {KIND_APP_MSG: self.handle_msg}
