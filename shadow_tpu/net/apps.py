"""Device-side application models.

The reference runs real binaries (test_phold.c, tgen) as managed processes.
shadow_tpu supports that via the CPU interposition plane, but ALSO offers
fully on-device app models — vectorized behaviors that generate the same
traffic patterns with zero CPU↔TPU round-trips. These are the workloads for
the staged benchmark configs (BASELINE.md) and the analog of the reference's
PHOLD PDES canary (src/test/phold/test_phold.c: peers exchange
random-destination messages; msgload seeds circulate until runtime ends).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.engine import Emitter, EventView, draw_uniform
from shadow_tpu.core.state import (
    KIND_APP_MSG,
    KIND_APP_TIMER,
    NetParams,
    SimState,
)
from shadow_tpu.net import link, packet as pkt


def ring_offset_dst(u, my_id, span, num_hosts):
    """Map a uniform draw to a destination a nonzero ring offset in
    [-span..-1, 1..span] away from my_id (mod num_hosts) — the shared
    topology-locality generator (PHOLD's local_span forwarding and any
    neighborhood-biased traffic shape)."""
    off = jnp.clip(
        jnp.floor(u * (2 * span)).astype(jnp.int32), 0, 2 * span - 1
    ) - span
    off = off + (off >= 0)  # skip 0
    return ((jnp.asarray(my_id, jnp.int32) + off) % num_hosts).astype(
        jnp.int32
    )


def locality_targets(num_hosts, anchors, local_span):
    """Static host→anchor table shaped by ring locality: hosts within
    local_span circular hops of some anchor target their nearest one
    (ties to the earlier anchor), the rest fall back to round-robin.
    local_span 0 = pure round-robin — the classic flood fan-in. Build-time
    numpy ([H] int32); riding in an app sub keeps it islands-shardable."""
    import numpy as np

    anchors = list(anchors)
    tgt = np.array(
        [anchors[i % len(anchors)] for i in range(num_hosts)],
        dtype=np.int32,
    )
    if local_span <= 0:
        return tgt
    for h in range(num_hosts):
        best, bd = None, None
        for a in anchors:
            d = abs(h - a)
            d = min(d, num_hosts - d)
            if bd is None or d < bd:
                best, bd = a, d
        if bd <= local_span:
            tgt[h] = best
    return tgt


class PholdApp:
    """PHOLD: each received message is forwarded to a random peer over the
    simulated network; message population = hosts × msgload; senders stop
    once sim time passes `runtime` (phold.yaml args: msgload, size, runtime).
    """

    SUB = "phold"
    # PHOLD events carry only a message size; right-sizing the payload
    # keeps the dominant per-window payload gathers 6x smaller than the
    # full packet-header layout
    PAYLOAD_WORDS = 2

    def __init__(
        self,
        num_hosts: int,
        msgload: int = 1,
        size_bytes: int = 64,
        start_time: int = simtime.NS_PER_SEC,
        runtime: int = 5 * simtime.NS_PER_SEC,
        hot_frac: float = 0.0,
        hot_share: float = 0.0,
        local_span: int = 0,
    ):
        self.num_hosts = num_hosts
        self.msgload = msgload
        self.size_bytes = size_bytes
        self.start_time = start_time
        self.stop_sending = start_time + runtime
        # Locality-biased variant (the async-sync benchmark shape, and
        # the communication structure of relay-mesh workloads): forwards
        # target a ring neighborhood of +-local_span host ids around the
        # sender instead of the uniform all-to-all. 0 = classic PHOLD.
        self.local_span = int(local_span)
        if self.local_span < 0 or self.local_span >= num_hosts:
            raise ValueError(
                "phold local_span must be in [0, num_hosts)"
            )
        if self.local_span and (hot_frac > 0 or hot_share > 0):
            raise ValueError(
                "phold local_span and hot_frac/hot_share are exclusive"
            )
        # Skewed-destination variant (the work-stealing benchmark shape,
        # scheduler_policy_host_steal.c's raison d'etre): hot_share of
        # all messages target the first hot_frac of hosts. hot_frac 0 =
        # classic uniform PHOLD. The hot variant permits self-sends
        # (they arrive at +latency, respecting the bulk contract).
        self.hot_frac = float(hot_frac)
        self.hot_share = float(hot_share)
        if (self.hot_frac > 0) != (self.hot_share > 0):
            raise ValueError(
                "phold hot_frac and hot_share must be set together"
            )
        if not (0.0 <= self.hot_share < 1.0) or not (
            0.0 <= self.hot_frac <= 1.0
        ):
            raise ValueError("hot_share must be in [0,1), hot_frac in [0,1]")
        self.hot_n = (
            max(1, int(num_hosts * self.hot_frac))
            if self.hot_frac > 0 else 0
        )

    def init_sub(self) -> dict:
        H = self.num_hosts
        return {
            "received": jnp.zeros((H,), dtype=jnp.int64),
            "forwarded": jnp.zeros((H,), dtype=jnp.int64),
        }

    def bulk_kinds(self) -> dict[int, int]:
        """KIND_APP_MSG qualifies for the engine's bulk batch (it never
        emits a self event inside the window: forwards go to OTHER hosts,
        and even the H==1 self-loop lands at +latency >= window end). A
        host's per-window wave is ~Poisson(msgload); 2×msgload covers the
        tail without bloating the unrolled handler."""
        return {KIND_APP_MSG: min(2 * self.msgload, 16)}

    def initial_events(self):
        """msgload seed messages per host, self-delivered at start_time; the
        first processing forwards each to a random peer."""
        out = []
        for h in range(self.num_hosts):
            for _ in range(self.msgload):
                out.append(
                    (self.start_time, h, h, KIND_APP_MSG, [self.size_bytes])
                )
        return out

    def handle_msg(
        self, state: SimState, ev: EventView, emitter: Emitter, params: NetParams
    ) -> SimState:
        H = self.num_hosts  # GLOBAL host count (destination id range)
        hosts = state.host.gid  # global ids of this shard's rows
        sub = state.subs[self.SUB]
        sub = dict(sub)
        sub["received"] = sub["received"] + ev.mask.astype(jnp.int64)

        send_mask = ev.mask & (ev.time < self.stop_sending)
        state, u = draw_uniform(state, send_mask)
        dst = self._pick_dst(u, hosts)
        sub["forwarded"] = sub["forwarded"] + send_mask.astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        return link.send(
            state,
            emitter,
            send_mask,
            dst,
            ev.time,
            KIND_APP_MSG,
            ev.payload,
            params,
            self.size_bytes,
        )

    def _pick_dst(self, u, my_id):
        """Map one uniform draw to a destination. Uniform mode skips self
        exactly like the reference's `(me + 1 + rand%(H-1)) %% H`; the hot
        variant splits the unit interval at hot_share; the local_span
        variant draws a nonzero ring offset in [-span, span]."""
        H = self.num_hosts
        if self.local_span > 0:
            return ring_offset_dst(u, my_id, self.local_span, H)
        if self.hot_n > 0:
            hs = self.hot_share
            nh = self.hot_n
            hot = jnp.floor(u / hs * nh).astype(jnp.int32)
            cold = nh + jnp.floor(
                (u - hs) / (1.0 - hs) * (H - nh)
            ).astype(jnp.int32)
            return jnp.clip(
                jnp.where(u < hs, hot, cold), 0, H - 1
            )
        if H <= 1:
            return jnp.broadcast_to(jnp.asarray(my_id), u.shape)
        dst = jnp.clip(jnp.floor(u * (H - 1)).astype(jnp.int32), 0, H - 2)
        return dst + (dst >= my_id)  # skip self

    def handlers(self):
        return {KIND_APP_MSG: self.handle_msg}

    def handle_msg_matrix(self, state, mv, emitter, params):
        """Whole-window vectorized form of handle_msg over [H, K] columns
        (the engine's matrix fast path). Reproduces the sequential
        per-event draw schedule bit-for-bit: event k's (dst, reliability)
        draws use counters c0 + 2·(#sends before k) and +1 — REQUIRES an
        all-reachable topology so every send costs exactly two draws
        (sim.py only registers this handler when that holds)."""
        H = self.num_hosts  # GLOBAL host count (destination id range)
        hosts = state.host.gid
        sub = dict(state.subs[self.SUB])
        sub["received"] = sub["received"] + jnp.sum(
            mv.mask, axis=1, dtype=jnp.int64
        )
        send = mv.mask & (mv.time < self.stop_sending)  # [H, K]
        si = send.astype(jnp.uint32)
        excl = jnp.cumsum(si, axis=1) - si
        c0 = state.host.rng_counter
        off = c0[:, None] + 2 * excl
        u1 = rng.uniform_matrix(state.rng_keys, off)
        u2 = rng.uniform_matrix(state.rng_keys, off + 1)
        state = state.replace(
            host=state.host.replace(
                rng_counter=c0 + 2 * jnp.sum(si, axis=1, dtype=jnp.uint32)
            )
        )
        dst = self._pick_dst(u1, hosts[:, None])
        sub["forwarded"] = sub["forwarded"] + jnp.sum(
            send, axis=1, dtype=jnp.int64
        )
        state = state.with_sub(self.SUB, sub)
        # link.send in matrix form (worker.c:517-576): latency lookup,
        # reliability roll, delivery emission. Single-vertex topologies
        # broadcast; the general case reads the replicated global
        # host->vertex table (params.vertex_g) so dst — a GLOBAL id —
        # never indexes the shard-local vertex array.
        if params.latency_vv.shape[0] == 1:
            lat = jnp.broadcast_to(params.latency_vv[0, 0], dst.shape)
            rel = jnp.broadcast_to(params.reliability_vv[0, 0], dst.shape)
        else:
            vd = (
                params.vertex_g[dst]
                if params.vertex_g is not None
                else state.host.vertex[dst]
            )  # [H, K]
            vs = jnp.broadcast_to(state.host.vertex[:, None], vd.shape)
            lat = params.latency_vv[vs, vd]
            rel = params.reliability_vv[vs, vd]
        kept = (mv.time < params.bootstrap_end) | (u2 < rel)
        emitter.emit(
            send & kept, mv.time + lat, dst, jnp.int32(KIND_APP_MSG),
            mv.payload,
        )
        c = state.counters
        return state.replace(
            counters=c.replace(
                packets_sent=c.packets_sent + jnp.sum(send, dtype=jnp.int64),
                packets_dropped_loss=c.packets_dropped_loss
                + jnp.sum(send & ~kept, dtype=jnp.int64),
                bytes_sent=c.bytes_sent + jnp.int64(self.size_bytes)
                * jnp.sum(send, dtype=jnp.int64),
            )
        )

    def matrix_handlers(self):
        return {KIND_APP_MSG: self.handle_msg_matrix}


SERVER_PORT = 9000
CLIENT_PORT_BASE = 40000


class UdpFloodApp:
    """BASELINE config 2: client hosts flood a server with UDP datagrams at a
    fixed rate through the full NIC/router/token-bucket path.

    role[h]: 0 = server (binds SERVER_PORT), 1 = client (timer-driven sends).
    """

    SUB = "udp_flood"

    def __init__(
        self,
        num_hosts: int,
        server_hosts,  # list[int]
        interval_ns: int,
        size_bytes: int = 1024,
        start_time: int = simtime.NS_PER_SEC,
        stop_sending: int | None = None,
        local_span: int = 0,
    ):
        self.num_hosts = num_hosts
        self.server_hosts = list(server_hosts)
        self.interval_ns = int(interval_ns)
        self.size_bytes = int(size_bytes)
        # locality-shaped fan-in: clients within local_span ring hops of a
        # server flood THAT server (the incast aggregation shape); 0 keeps
        # the classic round-robin spread
        self.local_span = int(local_span)
        if self.local_span < 0 or self.local_span >= num_hosts:
            raise ValueError(
                "udp_flood local_span must be in [0, num_hosts)"
            )
        if self.size_bytes > pkt.MTU - pkt.UDP_HEADER_BYTES:
            raise ValueError(
                f"datagram size {self.size_bytes} exceeds MTU payload "
                f"{pkt.MTU - pkt.UDP_HEADER_BYTES} (fragmentation unsupported)"
            )
        self.start_time = int(start_time)
        self.stop_sending = stop_sending

    def attach(self, stack):
        self.stack = stack
        import numpy as np

        role = np.ones(self.num_hosts, dtype=np.int32)
        role[self.server_hosts] = 0
        self._role = jnp.asarray(role)
        # clients target servers round-robin, locality-biased when
        # local_span is set
        self._target = jnp.asarray(
            locality_targets(
                self.num_hosts, self.server_hosts, self.local_span
            )
        )
        for s in self.server_hosts:
            stack.bind_udp(s, 0, SERVER_PORT)
        for h in range(self.num_hosts):
            if role[h] == 1:
                stack.bind_udp(h, 0, CLIENT_PORT_BASE)

    def init_sub(self) -> dict:
        H = self.num_hosts
        # role/target ride in the sub-state (not python closures) so the
        # islands engine shards them with every other [H]-leading array
        return {
            "sent": jnp.zeros((H,), jnp.int64),
            "recv": jnp.zeros((H,), jnp.int64),
            "role": self._role,
            "target": self._target,
        }

    def initial_events(self):
        return [
            (self.start_time, h, h, KIND_APP_TIMER, [])
            for h in range(self.num_hosts)
            if int(self._role[h]) == 1
        ]

    def on_timer(self, state, ev, emitter, params):
        sub = dict(state.subs[self.SUB])
        send = ev.mask & (sub["role"] == 1)
        if self.stop_sending is not None:
            send = send & (ev.time < self.stop_sending)
        sub["sent"] = sub["sent"] + send.astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        state = self.stack.udp_sendto(
            state, emitter, send, ev.time, sub["target"], SERVER_PORT,
            CLIENT_PORT_BASE, self.size_bytes, 0,
            params=params,
        )
        emitter.emit(
            send, ev.time + self.interval_ns, state.host.gid,
            jnp.int32(KIND_APP_TIMER), ev.payload,
        )
        return state

    def on_receive(self, state, mask, slot, src, payload, emitter, now, params):
        sub = dict(state.subs[self.SUB])
        got = mask & (sub["role"] == 0)
        sub["recv"] = sub["recv"] + got.astype(jnp.int64)
        return state.with_sub(self.SUB, sub)

    def handlers(self):
        return {KIND_APP_TIMER: self.on_timer}


class UdpEchoApp:
    """BASELINE config 1 analog (tgen-echo style): clients send a datagram to
    the server every interval; the server echoes it back; clients accumulate
    round-trip samples. Exercises both directions of the NIC path."""

    SUB = "udp_echo"

    def __init__(
        self,
        num_hosts: int,
        server_host: int,
        interval_ns: int,
        size_bytes: int = 512,
        start_time: int = simtime.NS_PER_SEC,
        stop_sending: int | None = None,
    ):
        self.num_hosts = num_hosts
        self.server_host = int(server_host)
        self.interval_ns = int(interval_ns)
        self.size_bytes = int(size_bytes)
        if self.size_bytes > pkt.MTU - pkt.UDP_HEADER_BYTES:
            raise ValueError(
                f"datagram size {self.size_bytes} exceeds MTU payload "
                f"{pkt.MTU - pkt.UDP_HEADER_BYTES} (fragmentation unsupported)"
            )
        self.start_time = int(start_time)
        self.stop_sending = stop_sending

    def attach(self, stack):
        self.stack = stack
        import numpy as np

        role = np.ones(self.num_hosts, dtype=np.int32)
        role[self.server_host] = 0
        self._role = jnp.asarray(role)
        stack.bind_udp(self.server_host, 0, SERVER_PORT)
        for h in range(self.num_hosts):
            if h != self.server_host:
                stack.bind_udp(h, 0, CLIENT_PORT_BASE)

    def init_sub(self) -> dict:
        H = self.num_hosts
        return {
            "sent": jnp.zeros((H,), jnp.int64),
            "echoed": jnp.zeros((H,), jnp.int64),
            "rtt_sum": jnp.zeros((H,), jnp.int64),
            "rtt_count": jnp.zeros((H,), jnp.int64),
            "role": self._role,
        }

    def initial_events(self):
        return [
            (self.start_time, h, h, KIND_APP_TIMER, [])
            for h in range(self.num_hosts)
            if h != self.server_host
        ]

    def on_timer(self, state, ev, emitter, params):
        hosts = state.host.gid
        H = hosts.shape[0]
        sub = dict(state.subs[self.SUB])
        send = ev.mask & (sub["role"] == 1)
        if self.stop_sending is not None:
            send = send & (ev.time < self.stop_sending)
        sub["sent"] = sub["sent"] + send.astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        # The send timestamp travels IN the datagram (spare seq/ack words)
        # and the server echoes it back — RTT is then exact even when
        # multiple requests are in flight.
        req = pkt.make_udp(
            src_port=jnp.full((H,), CLIENT_PORT_BASE, jnp.int32),
            dst_port=jnp.full((H,), SERVER_PORT, jnp.int32),
            length=jnp.full((H,), self.size_bytes, jnp.int32),
            priority=jnp.zeros((H,), jnp.int32),
            src_host=hosts,
            socket_slot=jnp.zeros((H,), jnp.int32),
            payload_words=self.stack.payload_words,
        )
        req = pkt.pack_time(req, jnp.where(send, ev.time, 0))
        state = self.stack.udp_sendto(
            state, emitter, send, ev.time,
            jnp.full((H,), self.server_host, jnp.int32),
            SERVER_PORT, CLIENT_PORT_BASE, self.size_bytes, 0, payload=req,
            params=params,
        )
        emitter.emit(
            send, ev.time + self.interval_ns, hosts,
            jnp.int32(KIND_APP_TIMER), ev.payload,
        )
        return state

    def on_receive(self, state, mask, slot, src, payload, emitter, now, params):
        hosts = state.host.gid
        sub = dict(state.subs[self.SUB])
        # server: echo back to (src, src_port), preserving the timestamp words
        server_got = mask & (sub["role"] == 0)
        sub["echoed"] = sub["echoed"] + server_got.astype(jnp.int64)
        # client: RTT from the echoed timestamp
        client_got = mask & (sub["role"] == 1)
        rtt = now - pkt.unpack_time(payload)
        sub["rtt_sum"] = sub["rtt_sum"] + jnp.where(client_got, rtt, 0)
        sub["rtt_count"] = sub["rtt_count"] + client_got.astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        reply = payload
        reply = reply.at[:, pkt.W_SRC_PORT].set(SERVER_PORT)
        reply = reply.at[:, pkt.W_DST_PORT].set(payload[:, pkt.W_SRC_PORT])
        reply = reply.at[:, pkt.W_SRC_HOST].set(hosts)
        state = self.stack.udp_sendto(
            state, emitter, server_got, now, src,
            None, None, None, 0, payload=reply,
            params=params,
        )
        return state

    def handlers(self):
        return {KIND_APP_TIMER: self.on_timer}


class TcpBulkApp:
    """BASELINE config 3: each client opens a TCP connection to a server and
    pushes `total_bytes` through the congestion-controlled stream, then
    closes. Exercises handshake, Reno, retransmission, and teardown.

    Server hosts listen on SERVER_PORT (slot 0); child sockets are allocated
    per accepted connection, so a server needs sockets_per_host > its client
    count. Clients connect from slot 0 at start_time.
    """

    SUB = "tcp_bulk"

    def __init__(
        self,
        num_hosts: int,
        server_hosts,
        total_bytes: int,
        start_time: int = simtime.NS_PER_SEC,
    ):
        self.num_hosts = num_hosts
        self.server_hosts = list(server_hosts)
        self.total_bytes = int(total_bytes)
        self.start_time = int(start_time)

    def attach(self, stack):
        self.stack = stack
        import numpy as np

        role = np.ones(self.num_hosts, dtype=np.int32)
        role[self.server_hosts] = 0
        self._role = jnp.asarray(role)
        tgt = np.array(
            [
                self.server_hosts[i % len(self.server_hosts)]
                for i in range(self.num_hosts)
            ],
            dtype=np.int32,
        )
        self._target = jnp.asarray(tgt)
        for s in self.server_hosts:
            stack.tcp_listen(s, 0, SERVER_PORT)
        stack.tcp.on_established(self.on_established)
        stack.tcp.on_peer_fin(self.on_peer_fin)

    def init_sub(self) -> dict:
        H = self.num_hosts
        return {
            "connected": jnp.zeros((H,), jnp.int64),
            "accepted": jnp.zeros((H,), jnp.int64),
            "eof_seen": jnp.zeros((H,), jnp.int64),
            "role": self._role,
            "target": self._target,
        }

    def initial_events(self):
        return [
            (self.start_time, h, h, KIND_APP_TIMER, [])
            for h in range(self.num_hosts)
            if int(self._role[h]) == 1
        ]

    def on_timer(self, state, ev, emitter, params):
        """Client start: active open toward the target server."""
        sub = state.subs[self.SUB]
        go = ev.mask & (sub["role"] == 1)
        state = self.stack.tcp.connect(
            state, emitter, go, jnp.zeros_like(sub["role"]),
            sub["target"], SERVER_PORT, CLIENT_PORT_BASE, ev.time,
            params=params,
        )
        return state

    def on_established(self, state, mask, slot, is_accept, src, now, emitter,
                       params):
        sub = dict(state.subs[self.SUB])
        client_up = mask & ~is_accept & (sub["role"] == 1)
        sub["connected"] = sub["connected"] + client_up.astype(jnp.int64)
        sub["accepted"] = sub["accepted"] + (
            mask & is_accept & (sub["role"] == 0)
        ).astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        # write the whole stream into sequence space; FIN rides after it
        state = self.stack.tcp.send_app(
            state, emitter, client_up, slot, self.total_bytes, now
        )
        state = self.stack.tcp.close_app(state, emitter, client_up, slot, now)
        return state

    def on_peer_fin(self, state, mask, slot, now, emitter, params):
        """Server side: client finished sending → close our half too."""
        sub = dict(state.subs[self.SUB])
        srv = mask & (sub["role"] == 0)
        sub["eof_seen"] = sub["eof_seen"] + srv.astype(jnp.int64)
        state = state.with_sub(self.SUB, sub)
        state = self.stack.tcp.close_app(state, emitter, srv, slot, now)
        return state

    def handlers(self):
        return {KIND_APP_TIMER: self.on_timer}
