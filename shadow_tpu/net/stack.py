"""NetStack: composes NIC + router(CoDel) + UDP into engine handlers.

Wiring mirrors the reference's packet path (SURVEY.md §3.4):

  send:    app → udp_sendto → NIC send ring → send pump (tokens, qdisc)
           → link transit (loss roll + latency) → KIND_PKT_DELIVER event
  receive: KIND_PKT_DELIVER → router CoDel enqueue → receive pump
           (rx tokens) → CoDel dequeue → port demux → socket counters
           → app receive hooks

Loopback traffic (dst == src host) bypasses router and token buckets, like
the reference's loopback interface which has no upstream router
(network_interface.c:448-457).

Event kinds used: KIND_PKT_DELIVER, KIND_NIC_SEND (send pump),
KIND_NIC_REFILL is reused as the receive pump kind (KIND_NIC_RECV alias).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from shadow_tpu.core.engine import Emitter, EventView
from shadow_tpu.core.state import (
    KIND_NIC_REFILL,
    KIND_PKT_DELIVER,
    NetParams,
    SimState,
)
from shadow_tpu.net import codel, link, nic, packet as pkt, pds as pds_mod, tcp as tcp_mod, udp
from shadow_tpu.net import qdisc as qdisc_mod

KIND_NIC_SEND = 100
KIND_NIC_RECV = KIND_NIC_REFILL

# hook(state, mask, slot, src_host, payload, emitter, now, params) -> state
RecvHook = Callable


class NetStack:
    def __init__(
        self,
        num_hosts: int,
        bw_up_bits,
        bw_down_bits,
        sockets_per_host: int = 8,
        router_queue_slots: int = 64,
        nic_queue_slots: int = 64,
        tcp_ooo_chunks: int = tcp_mod.OOO_CHUNKS,
        with_tcp: bool = True,
        tcp_child_base: int = 0,
        qdisc: str = "fifo",
        router_variant: str = "codel",
        payload_words: int = 12,
        discipline: qdisc_mod.Discipline | None = None,
    ):
        # Egress scheduling plane: either a legacy string ("fifo" /
        # "roundrobin" — the ring-wrapping disciplines) or a prebuilt
        # Discipline object (pifo/eiffel, carrying rank/drop config from
        # the `qdisc:` section; sim.py constructs those).
        if discipline is None:
            discipline = qdisc_mod.make_discipline(qdisc)
        self.disc = discipline
        self.payload_words = payload_words
        if router_variant not in ("codel", "static", "single"):
            raise ValueError(f"unknown router variant {router_variant!r}")
        self.qdisc = discipline.name
        # router_queue_codel.c / _static.c / _single.c vtable analog:
        # "static" = drop-tail FIFO without the AQM control law;
        # "single" = the same with a one-packet ring
        self.router_aqm = router_variant == "codel"
        if router_variant == "single":
            router_queue_slots = 1
        self.sockets_per_host = sockets_per_host
        self.num_hosts = num_hosts
        self.disc.attach(self)
        self._init_nic = nic.init(
            bw_up_bits, bw_down_bits, nic_queue_slots,
            payload_words=payload_words,
        )
        self._init_router = codel.init(
            num_hosts, router_queue_slots, payload_words=payload_words
        )
        self._init_udp = udp.init(num_hosts, sockets_per_host)
        # UDP-only sims skip the TCP state machine entirely: its handlers
        # otherwise run (masked) every micro-step and dominate both compile
        # time and per-iteration cost.
        self.tcp = (
            tcp_mod.Tcp(num_hosts, sockets_per_host, tcp_ooo_chunks,
                        child_base=tcp_child_base,
                        payload_words=payload_words)
            if with_tcp else None
        )
        if self.tcp is not None:
            self.tcp.attach(self)
        self.recv_hooks: list[RecvHook] = []
        # Receive-pump batching unrolls _deliver_local (the full demux +
        # hooks + TCP suite) per drained packet; with TCP compiled in, the
        # unroll multiplies XLA compile time for little win, so batch only
        # the UDP-only build.
        self.recv_batch = 1 if with_tcp else self.PUMP_BATCH
        # Gated arrival batching (engine bulk): how many CONSECUTIVE
        # KIND_PKT_DELIVER events one host may consume per micro-step when
        # bulk_gate proves them all direct-deliverable. The reference
        # drains a whole arrival burst in ONE receivePackets task
        # (network_interface.c:448-485); this is that, vectorized. TCP
        # builds keep 1 for now: the segment handler arms sub-window RTO
        # timers, which the gate cannot bound statically.
        self.deliver_batch = 1 if with_tcp else 8

    # ---- build-time API ----

    def bind_udp(self, host: int, slot: int, port: int, peer_host: int = udp.ANY_PEER,
                 peer_port: int = 0):
        self._init_udp = udp.bind_static(
            self._init_udp, host, slot, port, peer_host, peer_port
        )

    def tcp_listen(self, host: int, slot: int, port: int):
        if self.tcp is None:
            raise ValueError("stack built with with_tcp=False")
        self.tcp.listen(host, slot, port)

    def on_receive(self, hook: RecvHook):
        self.recv_hooks.append(hook)

    def init_subs(self) -> dict:
        subs = {
            nic.SUB: self._init_nic,
            codel.SUB: self._init_router,
            udp.SUB: self._init_udp,
        }
        if self.tcp is not None:
            subs[tcp_mod.SUB] = self.tcp.init_sub()
        subs.update(self.disc.init_subs())
        return subs

    # ---- generic transmit path (all protocols) ----

    def _tx(self, state: SimState, emitter: Emitter, mask, now, dst_host,
            payload, params: NetParams | None = None):
        """Transmit an assembled packet (networkinterface_wantsSend analog).

        Uncontended fast path (requires ``params`` for the latency/loss
        lookup): empty send queue + tokens in the bucket → the packet goes
        onto the wire inside THIS micro-step, exactly like the reference's
        send loop which transmits immediately when tokens allow
        (network_interface.c:633-661) — the pump event exists only for the
        throttled/queued case. This halves the per-packet event chain.

        Returns (state, ok) where ok marks hosts whose packet was admitted.
        """
        hosts = state.host.gid  # GLOBAL ids of this shard's rows
        H = hosts.shape[0]
        n = state.subs[nic.SUB]
        now64 = jnp.broadcast_to(now, (H,)).astype(jnp.int64)
        direct = jnp.zeros((H,), bool)
        if params is not None:
            # empty-queue test BEFORE any mutation: the refill touches
            # only the token bucket, never the queue plane
            queued_any = self.disc.nonempty(state)
            tx_rem, tx_tick = nic.lazy_refill(
                n.tx_rem, n.tx_tick, n.tx_refill, n.tx_cap, now64, mask
            )
            n = n.replace(tx_rem=tx_rem, tx_tick=tx_tick)
            size = pkt.total_bytes(payload).astype(jnp.int64)
            bootstrap = now64 < params.bootstrap_end
            # same admission gate as the send pump (rem >= MTU, full size
            # charged, debt allowed) so a packet's timing never depends on
            # which path carried it
            direct = mask & ~queued_any & (
                bootstrap | (n.tx_rem >= pkt.MTU)
            )
            # bootstrap sends are free, exactly like the pump path
            n = n.replace(
                tx_rem=jnp.where(direct & ~bootstrap, n.tx_rem - size,
                                 n.tx_rem)
            )
            n = nic.count_tx(n, direct, size)
            state = state.with_sub(nic.SUB, n)
            state = self.disc.note_direct(state, direct, payload)
            remote = direct & (dst_host != hosts)
            wire = pkt.stamp(payload, direct, pkt.PDS_SENT)
            state = link.send(
                state, emitter, remote, dst_host.astype(jnp.int32), now64,
                KIND_PKT_DELIVER, wire, params,
                jnp.where(remote, size, 0),
                control_mask=payload[:, pkt.W_LEN] == 0,
            )
            lb = direct & (dst_host == hosts)
            emitter.emit(lb, now64, hosts, jnp.int32(KIND_PKT_DELIVER),
                         wire)

        enq = mask & ~direct
        state, ok = self.disc.enqueue(
            state, enq, dst_host.astype(jnp.int32),
            pkt.stamp(payload, enq, pkt.PDS_NIC_QUEUED), now64,
        )
        state = pds_mod.record_drop(
            state, enq & ~ok, payload, pkt.PDS_DROPPED_SENDQ, now64
        )
        n = state.subs[nic.SUB]
        need = ok & ~n.send_pending
        emitter.emit(
            need, now64, hosts,
            jnp.int32(KIND_NIC_SEND), jnp.zeros_like(payload),
        )
        n = n.replace(send_pending=n.send_pending | need)
        return state.with_sub(nic.SUB, n), ok | direct

    # ---- runtime API (called from app handlers) ----

    def udp_sendto(
        self,
        state: SimState,
        emitter: Emitter,
        mask,
        now,
        dst_host,
        dst_port,
        src_port,
        size_bytes,
        socket_slot,
        payload=None,
        params: NetParams | None = None,
    ) -> SimState:
        """Queue a datagram on the sender's NIC and arm the send pump
        (transport_sendUserData → socket buffer → networkinterface_wantsSend).
        Apps may pass a prebuilt [H, P] payload (e.g. carrying timestamps in
        the spare words); ports/size args are ignored in that case."""
        hosts = state.host.gid
        H = hosts.shape[0]
        if payload is None:
            payload = pkt.make_udp(
                src_port=jnp.broadcast_to(jnp.asarray(src_port, jnp.int32), (H,)),
                dst_port=jnp.broadcast_to(jnp.asarray(dst_port, jnp.int32), (H,)),
                length=jnp.broadcast_to(jnp.asarray(size_bytes, jnp.int32), (H,)),
                priority=jnp.zeros((H,), jnp.int32),
                src_host=hosts,
                socket_slot=jnp.broadcast_to(
                    jnp.asarray(socket_slot, jnp.int32), (H,)
                ),
                payload_words=self.payload_words,
            )
        state, ok = self._tx(state, emitter, mask, now, dst_host, payload,
                             params=params)
        u = udp.count_sent(
            state.subs[udp.SUB], ok,
            jnp.broadcast_to(jnp.asarray(socket_slot, jnp.int32), (H,)), payload,
        )
        return state.with_sub(udp.SUB, u)

    # ---- engine handlers ----

    def _deliver_local(self, state, mask, src, payload, emitter, now, params):
        """Demux + deliver + app hooks for packets that reached the NIC."""
        u = state.subs[udp.SUB]
        is_udp = mask & (payload[:, pkt.W_PROTO] == pkt.PROTO_UDP)
        slot, found = udp.demux(u, is_udp, payload, src)
        u = udp.deliver(u, found, slot, payload)
        u = u.replace(
            drop_no_socket=u.drop_no_socket + jnp.sum(is_udp & ~found, dtype=jnp.int64)
        )
        c = state.counters
        state = state.replace(
            counters=c.replace(
                packets_delivered=c.packets_delivered + jnp.sum(mask, dtype=jnp.int64),
                bytes_delivered=c.bytes_delivered
                + jnp.sum(
                    jnp.where(mask, payload[:, pkt.W_LEN].astype(jnp.int64), 0)
                ),
            )
        )
        state = state.with_sub(
            nic.SUB,
            nic.count_rx(
                state.subs[nic.SUB], mask, pkt.total_bytes(payload)
            ),
        )
        state = state.with_sub(udp.SUB, u)
        state = pds_mod.record_delivery(state, mask, payload, now)
        for hook in self.recv_hooks:
            state = hook(state, found, slot, src, payload, emitter, now, params)
        if self.tcp is not None:
            is_tcp = mask & (payload[:, pkt.W_PROTO] == pkt.PROTO_TCP)
            state = self.tcp.on_segment(
                state, is_tcp, src, payload, emitter, now, params
            )
        return state

    def on_pkt_deliver(
        self, state: SimState, ev: EventView, emitter: Emitter, params: NetParams
    ) -> SimState:
        """Packet arrives at the destination: remote traffic enters the
        upstream router (CoDel); loopback skips straight to the socket.

        Uncontended fast path: empty router queue + rx tokens → the packet
        is delivered inside THIS micro-step (the reference's receive loop
        drains arrivals immediately when tokens allow,
        network_interface.c:448-485); the CoDel state updates applied are
        exactly those of dequeueing a zero-sojourn ("good") packet."""
        hosts = state.host.gid
        now = ev.time
        loopback = ev.mask & (ev.src == hosts)
        remote = ev.mask & (ev.src != hosts)

        n = state.subs[nic.SUB]
        r = state.subs[codel.SUB]
        rx_rem, rx_tick = nic.lazy_refill(
            n.rx_rem, n.rx_tick, n.rx_refill, n.rx_cap, now, remote
        )
        n = n.replace(rx_rem=rx_rem, rx_tick=rx_tick)
        bootstrap = now < params.bootstrap_end
        size = pkt.total_bytes(ev.payload).astype(jnp.int64)
        direct = (
            remote & ~codel.nonempty(r)
            & (bootstrap | (n.rx_rem >= pkt.MTU))
        )
        n = n.replace(
            rx_rem=jnp.where(direct & ~bootstrap, n.rx_rem - size, n.rx_rem)
        )
        # zero-sojourn dequeue semantics: good packet → interval reset,
        # drop-mode exit (codel.dequeue with sojourn 0 does exactly this)
        r = r.replace(
            interval_expire=jnp.where(direct, 0, r.interval_expire),
            drop_mode=jnp.where(direct, False, r.drop_mode),
        )

        queued = remote & ~direct
        no_room = queued & ~(
            (r.q_tail - r.q_head) < r.q_src.shape[1]
        )
        state = pds_mod.record_drop(
            state, no_room, ev.payload, pkt.PDS_DROPPED_OVERFLOW, now
        )
        r = codel.enqueue(
            r, queued, pkt.stamp(ev.payload, queued, pkt.PDS_ROUTER_ENQUEUED),
            ev.src, now,
        )
        state = state.with_sub(codel.SUB, r).with_sub(nic.SUB, n)

        state = self._deliver_local(
            state, loopback | direct, ev.src, ev.payload, emitter, now, params
        )

        n = state.subs[nic.SUB]
        need = queued & ~n.recv_pending
        emitter.emit(
            need, now, hosts, jnp.int32(KIND_NIC_RECV),
            jnp.zeros_like(ev.payload),
        )
        n = n.replace(recv_pending=n.recv_pending | need)
        return state.with_sub(nic.SUB, n)

    # Packets drained per pump invocation. The reference's send loop drains
    # the qdisc while tokens allow within ONE task (network_interface.c:
    # 497-539); unrolling the same loop here keeps micro-step counts (and
    # thus full handler-suite invocations) ~BATCH× lower for bursty
    # traffic. 2 balances that against XLA compile time, which grows with
    # the unroll (the accelerator backend has no persistent compile cache).
    PUMP_BATCH = 1

    def on_nic_send(
        self, state: SimState, ev: EventView, emitter: Emitter, params: NetParams
    ) -> SimState:
        """Send pump: up to PUMP_BATCH packets per invocation while tokens
        allow; re-arms itself at `now` (more queued) or the next refill tick
        (tokens exhausted)."""
        hosts = state.host.gid
        now = ev.time
        mask = ev.mask
        n = state.subs[nic.SUB]
        n = n.replace(send_pending=n.send_pending & ~mask)

        tx_rem, tx_tick = nic.lazy_refill(
            n.tx_rem, n.tx_tick, n.tx_refill, n.tx_cap, now, mask
        )
        n = n.replace(tx_rem=tx_rem, tx_tick=tx_tick)
        bootstrap = now < params.bootstrap_end
        state = state.with_sub(nic.SUB, n)

        for _ in range(self.PUMP_BATCH):
            n = state.subs[nic.SUB]
            can = bootstrap | (n.tx_rem >= pkt.MTU)
            want = mask & can
            # the discipline owns head selection, the pop, AND any
            # dequeue-side drop policy (codel hook) — `do` marks hosts
            # that produced a deliverable packet this round
            state, do, payload, dst = self.disc.dequeue(state, now, want)

            # Charge the FULL wire size (may go negative — token debt). For
            # MTU-conformant packets this is identical to the reference's
            # clamp-at-zero (rem ≥ MTU ≥ size when the gate passes); for
            # oversize packets debt prevents exceeding configured bandwidth.
            size = pkt.total_bytes(payload).astype(jnp.int64)
            n = state.subs[nic.SUB]
            n = n.replace(
                tx_rem=jnp.where(do & ~bootstrap, n.tx_rem - size, n.tx_rem)
            )
            n = nic.count_tx(n, do, size)
            state = state.with_sub(nic.SUB, n)

            remote = do & (dst != hosts)
            wire = pkt.stamp(payload, do, pkt.PDS_SENT)
            state = link.send(
                state, emitter, remote, dst, now, KIND_PKT_DELIVER, wire,
                params, jnp.where(remote, size, 0),
                control_mask=payload[:, pkt.W_LEN] == 0,
            )
            # loopback: deliver at the same timestamp, no transit
            lb = do & (dst == hosts)
            emitter.emit(lb, now, hosts, jnp.int32(KIND_PKT_DELIVER), wire)

        still = self.disc.nonempty(state)
        n = state.subs[nic.SUB]
        need = mask & still
        can_next = bootstrap | (n.tx_rem >= pkt.MTU)
        t_next = jnp.where(can_next, now, nic.next_refill_time(now))
        emitter.emit(
            need, t_next, hosts, jnp.int32(KIND_NIC_SEND),
            jnp.zeros_like(ev.payload),
        )
        n = n.replace(send_pending=n.send_pending | need)
        return state.with_sub(nic.SUB, n)

    def on_nic_recv(
        self, state: SimState, ev: EventView, emitter: Emitter, params: NetParams
    ) -> SimState:
        """Receive pump: CoDel-dequeue up to PUMP_BATCH packets per
        invocation while rx tokens allow; re-arms while the router queue is
        non-empty (network_interface.c:448-485 drains in one task too)."""
        hosts = state.host.gid
        now = ev.time
        mask = ev.mask
        n = state.subs[nic.SUB]
        n = n.replace(recv_pending=n.recv_pending & ~mask)

        rx_rem, rx_tick = nic.lazy_refill(
            n.rx_rem, n.rx_tick, n.rx_refill, n.rx_cap, now, mask
        )
        n = n.replace(rx_rem=rx_rem, rx_tick=rx_tick)
        bootstrap = now < params.bootstrap_end

        for _ in range(self.recv_batch):
            can = bootstrap | (n.rx_rem >= pkt.MTU)
            want = mask & can

            r = state.subs[codel.SUB]
            r, have, payload, src = codel.dequeue(
                r, now, want, aqm=self.router_aqm
            )
            size = pkt.total_bytes(payload).astype(jnp.int64)
            n = n.replace(
                rx_rem=jnp.where(have & ~bootstrap, n.rx_rem - size, n.rx_rem)
            )
            state = state.with_sub(codel.SUB, r).with_sub(nic.SUB, n)

            state = self._deliver_local(
                state, have, src, payload, emitter, now, params
            )
            n = state.subs[nic.SUB]

        r = state.subs[codel.SUB]
        still = codel.nonempty(r)
        need = mask & still
        can_next = bootstrap | (n.rx_rem >= pkt.MTU)
        t_next = jnp.where(can_next, now, nic.next_refill_time(now))
        emitter.emit(
            need, t_next, hosts, jnp.int32(KIND_NIC_RECV), jnp.zeros_like(payload)
        )
        n = n.replace(recv_pending=n.recv_pending | need)
        return state.with_sub(nic.SUB, n)

    # ---- gated arrival batching (engine bulk support) ----

    def bulk_kinds(self) -> dict | None:
        if self.deliver_batch <= 1:
            return None
        return {KIND_PKT_DELIVER: self.deliver_batch}

    def bulk_gate(self, state: SimState, params: NetParams, win_start,
                  win_end):
        """[H] i32: how many EXTRA consecutive arrivals each host may batch
        this micro-step, such that EVERY batched arrival provably takes
        on_pkt_deliver's direct path (no queueing → no sub-window self
        pump) and any app reply takes _tx's direct path (no send pump).

        Conservative by construction: token buckets are refilled only to
        win_start (mid-window refills are ignored), each arrival/reply is
        budgeted a full MTU, and any armed pump or non-empty queue zeroes
        the gate. An ineligible host simply falls back to one-event-per-
        micro-step — never incorrect, only slower."""
        from shadow_tpu.net import codel as codel_mod

        n = state.subs[nic.SUB]
        r = state.subs[codel_mod.SUB]
        ws = jnp.asarray(win_start, jnp.int64)
        G = self.deliver_batch
        rx_rem, _ = nic.lazy_refill(
            n.rx_rem, n.rx_tick, n.rx_refill, n.rx_cap, ws
        )
        tx_rem, _ = nic.lazy_refill(
            n.tx_rem, n.tx_tick, n.tx_refill, n.tx_cap, ws
        )
        # whole window inside bootstrap → tokens are not charged at all
        free = jnp.asarray(win_end, jnp.int64) <= params.bootstrap_end
        rx_cap_ev = jnp.where(
            free, G, (rx_rem // pkt.MTU).astype(jnp.int64)
        )
        tx_cap_ev = jnp.where(
            free, G, (tx_rem // pkt.MTU).astype(jnp.int64)
        )
        quiet = (
            ~codel_mod.nonempty(r)
            & ~self.disc.nonempty(state)
            & ~n.recv_pending
            & ~n.send_pending
        )
        cap = jnp.minimum(rx_cap_ev, tx_cap_ev) - 1  # head uses one budget
        return jnp.where(quiet, jnp.clip(cap, 0, G - 1), 0).astype(jnp.int32)

    def handlers(self) -> dict:
        h = {
            KIND_PKT_DELIVER: self.on_pkt_deliver,
            KIND_NIC_SEND: self.on_nic_send,
            KIND_NIC_RECV: self.on_nic_recv,
        }
        if self.tcp is not None:
            h.update(self.tcp.handlers())
        return h
