"""Cross-host packet transit — the TPU form of the reference's hot path.

Reference (src/main/core/worker.c:517-576 worker_sendPacket): per packet,
roll reliability against the path's loss product (skip drops during
bootstrap, and never drop zero-length control packets), look up path latency,
and push a delivery event into the destination host's queue.

Here all of that is one vectorized step over every sending host at once:
two gathers (latency, reliability), one per-host RNG draw, one emission.
The destination "queue push" is the engine's outbox → pool merge; across a
mesh it becomes the all_to_all exchange in shadow_tpu.parallel.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import Emitter, draw_uniform
from shadow_tpu.core.state import NetParams, SimState


def send(
    state: SimState,
    emitter: Emitter,
    mask,
    dst_host,
    now,
    kind,
    payload,
    params: NetParams,
    size_bytes,
    control_mask=None,
):
    """Send one packet per masked host to dst_host, delivering at
    now + path latency, subject to the path's reliability roll.

    Control packets — zero PAYLOAD length (worker.c:543-545 keeps congestion
    control sane) — are never dropped by loss. By default that's inferred
    from size_bytes == 0; callers whose size_bytes includes headers pass
    control_mask explicitly.
    Returns updated state (counters + RNG advance).
    """
    U = params.latency_vv.shape[0]
    if U == 1:
        # Single-vertex topology (self-loop graphs — every staged bench and
        # any host-only sim): the path lookup is a broadcast scalar. This
        # matters because the general case's by-dst table reads are gathers,
        # which serialize per element on TPU.
        lat = jnp.broadcast_to(params.latency_vv[0, 0], dst_host.shape)
        rel = jnp.broadcast_to(params.reliability_vv[0, 0], dst_host.shape)
    else:
        vs = state.host.vertex  # [H] (local rows)
        # dst_host is a GLOBAL id: use the replicated global host→vertex
        # table when present (required under the islands engine, where
        # host.vertex holds only this shard's rows)
        vd = (
            params.vertex_g[dst_host]
            if params.vertex_g is not None
            else state.host.vertex[dst_host]
        )
        lat = params.latency_vv[vs, vd]
        rel = params.reliability_vv[vs, vd]
    reachable = lat != simtime.NEVER

    roll_mask = mask & reachable
    state, u = draw_uniform(state, roll_mask)
    in_bootstrap = now < params.bootstrap_end
    is_control = (
        control_mask
        if control_mask is not None
        else jnp.asarray(size_bytes) == 0
    )
    kept = in_bootstrap | is_control | (u < rel)
    deliver = roll_mask & kept

    emitter.emit(deliver, now + lat, dst_host, kind, payload)

    # breadcrumb registers for loss-dropped packets (worker.c:539-545 drop
    # roll; packet.c PDS_INET_DROPPED analog) — no-op without packet_trails
    from shadow_tpu.net import packet as pkt
    from shadow_tpu.net import pds as pds_mod

    state = pds_mod.record_drop(
        state, roll_mask & ~kept, payload, pkt.PDS_DROPPED_LOSS, now
    )

    c = state.counters
    n_sent = jnp.sum(mask, dtype=jnp.int64)
    state = state.replace(
        counters=c.replace(
            packets_sent=c.packets_sent + n_sent,
            packets_dropped_loss=c.packets_dropped_loss
            + jnp.sum(roll_mask & ~kept, dtype=jnp.int64),
            packets_dropped_unreachable=c.packets_dropped_unreachable
            + jnp.sum(mask & ~reachable, dtype=jnp.int64),
            bytes_sent=c.bytes_sent
            + jnp.sum(
                jnp.where(mask, jnp.asarray(size_bytes, jnp.int64), 0),
                dtype=jnp.int64,
            ),
        )
    )
    return state
