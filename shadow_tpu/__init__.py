"""shadow_tpu — a TPU-native discrete-event network simulation framework.

Capabilities modeled on Shadow (discrete-event network simulator that executes
real Linux binaries under syscall interposition and connects them through a
simulated network), re-architected TPU-first:

- Network state is struct-of-arrays over fixed host/socket/event capacities.
- A simulation round is a pure function ``step(state, window) -> state``
  compiled once with ``jax.jit`` and executed per conservative time window
  (reference: src/main/core/manager.c:543-577 round loop).
- Hosts shard across a ``jax.sharding.Mesh``; cross-shard packet delivery is
  an XLA collective, the round barrier is a global min-reduction
  (reference: src/main/core/scheduler/scheduler.c:232 scheduler_push,
  src/main/core/worker.c:332 min-reduce).
- The CPU side keeps a native interposition plane (preload shim, shared-memory
  IPC, syscall emulation) feeding batched event arrays across the host↔device
  boundary at the Router/Topology seam.

Simulated time is int64 nanoseconds (reference:
src/main/core/support/simulation_time.rs), so x64 mode is enabled on import.
Floating-point dtypes remain explicitly float32/bfloat16 throughout the
package; enabling x64 only widens our integer clocks.
"""

import jax

jax.config.update("jax_enable_x64", True)

from shadow_tpu.core import simtime, units  # noqa: E402
from shadow_tpu.core.config import Config, load_config  # noqa: E402

__version__ = "0.1.0"

__all__ = ["simtime", "units", "Config", "load_config", "__version__"]
