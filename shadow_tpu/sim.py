"""Top-level simulation builder: Config → runnable Simulation.

Plays the reference's controller/manager setup sequence
(src/main/core/controller.c:79-338: load topology, register hosts via DNS +
topology attach, create scheduler, compute runahead windows) and hands back a
`Simulation` whose window kernel runs on device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime, units
from shadow_tpu.core.config import Config, load_config
from shadow_tpu.core.engine import Simulation
from shadow_tpu.core.state import NetParams
from shadow_tpu.net.apps import PholdApp, TcpBulkApp, UdpEchoApp, UdpFloodApp
from shadow_tpu.net.stack import NetStack
from shadow_tpu.routing.dns import Dns
from shadow_tpu.routing.topology import BakedPaths, Topology


class BuildError(ValueError):
    pass


def _qdisc_discipline(cfg: Config, H: int):
    """Resolve the `qdisc:` section (plus the legacy
    experimental.interface_qdisc string) to a Discipline instance."""
    from shadow_tpu.net import qdisc as qdisc_mod

    qopt = cfg.qdisc
    # an explicit qdisc section wins; `fifo` (the default) defers to the
    # legacy string so pre-qdisc configs build the exact same stack
    eff = (
        qopt.discipline
        if qopt.discipline != "fifo"
        else cfg.experimental.interface_qdisc
    )
    if eff not in ("pifo", "eiffel"):
        return qdisc_mod.make_discipline(eff)

    from shadow_tpu.net.qdisc import drops as qdrops
    from shadow_tpu.net.qdisc import ranks as qranks
    from shadow_tpu.net.qdisc.eiffel import EiffelDiscipline
    from shadow_tpu.net.qdisc.pifo import PifoDiscipline

    ranker = qranks.make_ranker(
        qopt.rank, classes=qopt.classes, weights=qopt.weights,
        shaping=qopt.shaping,
    )
    red = (
        qdrops.RedConfig(
            qopt.queue_slots, qopt.red_min_frac, qopt.red_max_frac,
            qopt.red_max_p,
        )
        if qopt.drop == "red"
        else None
    )
    host_class = None
    if qopt.overrides:
        # host names are quantity-expanded and sorted by the config
        # loader, so prefix pins hit every replica of a host block
        host_class = np.full(H, -1, dtype=np.int32)
        for i, h in enumerate(cfg.hosts):
            for prefix, c in qopt.overrides.items():
                if h.name.startswith(prefix):
                    host_class[i] = c
    kw = dict(
        queue_slots=qopt.queue_slots, ranker=ranker, drop=qopt.drop,
        red=red, host_class=host_class,
    )
    if eff == "eiffel":
        return EiffelDiscipline(
            buckets=qopt.buckets, bucket_width=qopt.bucket_width, **kw
        )
    return PifoDiscipline(**kw)


def build_simulation(source) -> Simulation:
    """Build from a Config, YAML path/string, or dict."""
    cfg = source if isinstance(source, Config) else load_config(source)
    if not cfg.hosts:
        raise BuildError("no hosts configured")

    topo = Topology.from_gml(cfg.graph_gml(), cfg.network.use_shortest_path)
    dns = Dns()
    for i, h in enumerate(cfg.hosts):
        topo.attach_host(
            i,
            ip_address_hint=h.ip_address_hint,
            city_code_hint=h.city_code_hint,
            country_code_hint=h.country_code_hint,
            network_node_id=h.network_node_id,
        )
        dns.register(i, h.name, h.ip_address_hint)
    baked: BakedPaths = topo.bake()

    params = NetParams(
        latency_vv=jnp.asarray(baked.latency_vv),
        reliability_vv=jnp.asarray(baked.reliability_vv),
        bootstrap_end=jnp.int64(cfg.general.bootstrap_end_time),
        # replicated GLOBAL host→vertex table for by-dst path lookups
        # (required under islands, where host.vertex is shard-local);
        # single-vertex graphs broadcast instead and skip the gather
        vertex_g=(
            jnp.asarray(baked.host_vertex, dtype=jnp.int32)
            if np.asarray(baked.latency_vv).shape[0] > 1
            else None
        ),
    )
    runahead = cfg.experimental.runahead or baked.min_latency_ns
    if runahead > baked.min_latency_ns:
        # Reference semantics (configuration.rs:288-291): an explicit runahead
        # overrides the computed minimum. Windows longer than the min path
        # latency trade accuracy for speed: sub-window cross-host deliveries
        # are processed one window late. Surface that choice loudly.
        import warnings

        warnings.warn(
            f"runahead {runahead}ns exceeds min topology latency "
            f"{baked.min_latency_ns}ns: cross-host events inside a window "
            f"may be processed one window late (accuracy/speed tradeoff)",
            stacklevel=2,
        )

    # --- device-side app models ---
    handlers: dict = {}
    subs: dict = {}
    initial_events: list = []
    bulk_kinds: dict | None = None
    matrix_handlers: dict | None = None
    bulk_gate = None
    bulk_self_excluded = False
    payload_words = 12  # net/packet.py layout; pure-PDES apps shrink it
    H = len(cfg.hosts)
    app_names = {h.app_model for h in cfg.hosts if h.app_model}
    if "phold" in app_names:
        phold_hosts = [h for h in cfg.hosts if h.app_model == "phold"]
        if len(phold_hosts) != H:
            raise BuildError(
                "phold app model currently requires every host to run it"
            )
        distinct = {tuple(sorted(h.app_options.items())) for h in phold_hosts}
        if len(distinct) > 1:
            raise BuildError(
                "phold app_options must be identical across all hosts "
                "(per-host options are not supported yet)"
            )
        opts = phold_hosts[0].app_options
        app = PholdApp(
            H,
            msgload=int(opts.get("msgload", 1)),
            size_bytes=int(opts.get("size", 64)),
            start_time=units.parse_time_ns(opts.get("start_time", 1)),
            runtime=units.parse_time_ns(opts.get("runtime", 5)),
            hot_frac=float(opts.get("hot_frac", 0.0)),
            hot_share=float(opts.get("hot_share", 0.0)),
            local_span=int(opts.get("local_span", 0)),
        )
        handlers.update(app.handlers())
        subs[PholdApp.SUB] = app.init_sub()
        initial_events.extend(app.initial_events())
        bulk_kinds = app.bulk_kinds()
        payload_words = PholdApp.PAYLOAD_WORDS
        # The matrix fast path's draw-offset arithmetic assumes every
        # destination is reachable (two draws per send, see
        # PholdApp.handle_msg_matrix); register it only when that holds.
        if not np.any(np.asarray(baked.latency_vv) == simtime.NEVER):
            matrix_handlers = app.matrix_handlers()

    stack_apps = app_names & {"udp_flood", "udp_echo", "tcp_bulk"}
    if stack_apps:
        if len(stack_apps) > 1 or "phold" in app_names:
            raise BuildError("only one app model per simulation for now")
        name = next(iter(stack_apps))
        roles = {}
        client_opts = None
        for i, h in enumerate(cfg.hosts):
            if h.app_model != name:
                raise BuildError(f"{name} requires every host to run it")
            roles[i] = str(h.app_options.get("role", "client"))
            if roles[i] == "client":
                o = {k: v for k, v in h.app_options.items() if k != "role"}
                if client_opts is None:
                    client_opts = o
                elif client_opts != o:
                    raise BuildError(
                        f"{name} client app_options must be identical"
                    )
        servers = [i for i, r in roles.items() if r == "server"]
        if not servers:
            raise BuildError(f"{name} needs at least one role: server host")
        client_opts = client_opts or {}

        # per-host bandwidths: host override, else attachment vertex's
        bw_up = np.zeros(H, dtype=np.int64)
        bw_down = np.zeros(H, dtype=np.int64)
        for i, h in enumerate(cfg.hosts):
            v = baked.host_vertex[i]
            bw_up[i] = h.bandwidth_up or baked.vertex_bw_up_bits[v]
            bw_down[i] = h.bandwidth_down or baked.vertex_bw_down_bits[v]
            if bw_up[i] <= 0 or bw_down[i] <= 0:
                raise BuildError(
                    f"host {h.name}: no bandwidth configured (host or graph "
                    f"vertex must set bandwidth_up/down)"
                )
        if cfg.experimental.packet_trails:
            from shadow_tpu.net import packet as pkt_mod

            payload_words = pkt_mod.TRAILED_PAYLOAD_WORDS
        stack = NetStack(
            H,
            jnp.asarray(bw_up),
            jnp.asarray(bw_down),
            sockets_per_host=cfg.experimental.sockets_per_host,
            router_queue_slots=cfg.experimental.router_queue_slots,
            router_variant=cfg.experimental.router_queue_variant,
            with_tcp=(name == "tcp_bulk"),
            discipline=_qdisc_discipline(cfg, H),
            payload_words=payload_words,
        )
        interval = units.parse_time_ns(
            client_opts.get("interval", "100 ms"), default_unit="ms"
        )
        start = units.parse_time_ns(client_opts.get("start_time", 1))
        stop_send = (
            units.parse_time_ns(client_opts["runtime"]) + start
            if "runtime" in client_opts
            else None
        )
        if name == "udp_flood":
            app = UdpFloodApp(
                H, servers, interval,
                size_bytes=int(client_opts.get("size", 1024)),
                start_time=start, stop_sending=stop_send,
                local_span=int(client_opts.get("local_span", 0)),
            )
        elif name == "tcp_bulk":
            app = TcpBulkApp(
                H, servers,
                total_bytes=units.parse_bytes(client_opts.get("total", "1 MiB")),
                start_time=start,
            )
        else:
            if len(servers) != 1:
                raise BuildError("udp_echo supports exactly one server host")
            app = UdpEchoApp(
                H, servers[0], interval,
                size_bytes=int(client_opts.get("size", 512)),
                start_time=start, stop_sending=stop_send,
            )
        app.attach(stack)
        if hasattr(app, "on_receive"):
            stack.on_receive(app.on_receive)
        handlers.update(stack.handlers())
        handlers.update(app.handlers())
        subs.update(stack.init_subs())
        subs[app.SUB] = app.init_sub()
        initial_events.extend(app.initial_events())
        # gated arrival batching: a host consumes a whole burst of
        # same-window arrivals in one micro-step when provably safe
        bulk_kinds = stack.bulk_kinds()
        bulk_gate = stack.bulk_gate if bulk_kinds else None
        bulk_self_excluded = bulk_kinds is not None
        if cfg.experimental.packet_trails:
            from shadow_tpu.net import pds as pds_mod

            subs[pds_mod.SUB] = pds_mod.init(H)

    unknown = app_names - {"phold", "udp_flood", "udp_echo", "tcp_bulk"}
    if unknown:
        raise BuildError(f"unknown app model(s): {sorted(unknown)}")

    cpu_cost = np.array([h.cpu_ns_per_event for h in cfg.hosts], dtype=np.int64)
    sim_cls = Simulation
    sim_kw = {}
    if cfg.experimental.num_shards > 1:
        from shadow_tpu.parallel.islands import IslandSimulation

        sim_cls = IslandSimulation
        balancer_policy = None
        if cfg.experimental.balancer:
            from shadow_tpu.parallel.balancer import BalancerPolicy

            balancer_policy = BalancerPolicy(
                hot_ratio=cfg.experimental.balance_hot_ratio,
                streak=cfg.experimental.balance_streak,
                cooldown=cfg.experimental.balance_cooldown,
                max_moves=cfg.experimental.balance_max_moves,
            )
        sim_kw = dict(
            num_shards=cfg.experimental.num_shards,
            exchange_slots=cfg.experimental.exchange_slots,
            mode=cfg.experimental.island_mode,
            rebalance=cfg.experimental.rebalance,
            balancer=cfg.experimental.balancer,
            balancer_policy=balancer_policy,
            async_sync=cfg.experimental.async_islands,
            async_spread=cfg.experimental.async_spread,
            exchange=cfg.experimental.mesh_exchange,
            placement=cfg.experimental.placement,
            exclude_chips=cfg.experimental.exclude_chips,
            # matrix-capable sims pin the matrix path: under vmap a
            # lax.cond with a batched predicate executes BOTH branches
            force_path="matrix" if matrix_handlers else None,
        )
    sim = sim_cls(
        **sim_kw,
        num_hosts=H,
        handlers=handlers,
        params=params,
        host_vertex=baked.host_vertex,
        cpu_ns_per_event=cpu_cost if cpu_cost.any() else None,
        seed=cfg.general.seed,
        stop_time=cfg.general.stop_time,
        runahead=runahead,
        event_capacity=cfg.experimental.event_capacity,
        K=cfg.experimental.events_per_host_per_window,
        B=cfg.experimental.inbox_slots,
        O=cfg.experimental.outbox_slots,
        subs=subs,
        initial_events=initial_events,
        bulk_kinds=bulk_kinds,
        matrix_handlers=matrix_handlers,
        payload_words=payload_words,
        bulk_gate=bulk_gate,
        bulk_self_excluded=bulk_self_excluded,
        obs_counters=cfg.experimental.obs_counters,
        pool_gears=cfg.experimental.pool_gears,
        audit_digest=cfg.experimental.audit_digest,
        flight_capacity=cfg.experimental.flight_recorder,
        pipelined_dispatch=cfg.experimental.pipelined_dispatch,
        host_workers=cfg.experimental.host_workers,
    )
    # attach build artifacts for inspection/observability
    sim.config = cfg
    sim.topology = topo
    sim.dns = dns
    sim.baked = baked
    return sim
