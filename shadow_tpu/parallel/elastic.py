"""Elastic mesh resilience: survive chip loss by drain → relayout →
resume on the surviving mesh, then grow back when the chip returns.

PR 12 made multi-chip execution real (shard_map islands, per-chip state
pinning, ppermute frontier exchange) but kept the pre-mesh failure
model: the supervisor (core/supervisor.py) treats ANY backend loss as
total, so one sick chip in an 8-chip mesh takes down 8 chips' worth of
simulation — even though `checkpoint.restore_relayout` +
`islands.globalize_state` already prove bit-exact resume across mesh
sizes. This module closes that loop. Multi-processor PDES engines treat
worker count as a deployment knob, not a correctness axis (PARSIR,
arxiv 2410.00644); here the chip count becomes exactly that.

The state machine, layered over the supervisor's:

    RUNNING ──kill_chip / mesh-collective failure──▶ supervisor drains
       ▲                                             (drain-* namespace)
       │                                                   │
       │                                  policy `relayout`: ChipLost
       │                                                   ▼
       │   rebuild over survivors (host_mesh minus the dead chips,
       │   min-cut placement re-run, ppermute schedule re-derived,
       │   kernels rebound ONCE) + checkpoint.restore_relayout
       │                                                   │
       └────────────── DEGRADED ◀──────────────────────────┘
                          │ probe lost chips every `probe_every`
                          │ dispatches; `hysteresis` consecutive
                          │ successes + cooldown + balancer interlock
                          ▼
                     RE-EXPAND: drain ("re_expand") → rebuild at the
                     next admissible shard count → restore_relayout

Both transitions resume through the SAME relayout seam checkpoint
resume across mesh sizes uses, so the audit digest chain extends
exactly — a degraded run, a re-expanded run and an uninterrupted run
commit the identical event stream (bench.py --mesh-resilience-smoke
gates it). The S→1 endpoint falls back to the GLOBAL engine: with one
chip left there is no mesh to shard over, and globalize_state already
proves that resume chain-identical.

Determinism: the deterministic chaos input is the `kill_chip` fault op
(faults/plan.py) — fleet-frontier-keyed like every backend op, so the
loss lands at an exact virtual-time boundary on CPU; probes/hysteresis
only perturb WALL scheduling (which dispatch boundary the re-expansion
lands on), never committed events, because every relayout resumes from
a committed-frontier drain checkpoint.

A SIGKILL at ANY point of a relayout is a non-event: the drain
checkpoint is on disk before the old mesh is torn down, `resume()`
rebuilds from the newest ring entry (drain or periodic), and
`restore_relayout` re-layouts it onto whatever mesh the resuming
process builds.
"""

from __future__ import annotations

import time

import numpy as np

from shadow_tpu.core import checkpoint as ckpt_mod
from shadow_tpu.core.supervisor import BackendSupervisor, ChipLost


class MeshReexpand(Exception):
    """Control-flow signal raised by ElasticMeshRunner.on_dispatch at a
    committed dispatch boundary: lost chips answered probes for the
    hysteresis streak, so the runner should drain and relayout back up.
    Never escapes ElasticMeshRunner.run."""

    def __init__(self, chips: frozenset[int]):
        super().__init__(f"re-expand onto recovered chip(s) {sorted(chips)}")
        self.chips = frozenset(chips)


def admissible_shards(num_hosts: int, max_shards: int) -> int:
    """The largest shard count <= max_shards that divides num_hosts —
    the islands layout pads nothing (mesh.host_mesh), so a 7-survivor
    mesh can only run 7 shards if H divides by 7; otherwise the run
    degrades further (and at 1 falls back to the global engine)."""
    for s in range(min(int(max_shards), int(num_hosts)), 1, -1):
        if num_hosts % s == 0:
            return s
    return 1


class ElasticMeshRunner:
    """Drives a (possibly multi-chip) simulation through chip loss and
    recovery: drain → relayout onto the surviving mesh → resume →
    re-expand when the chip answers probes again.

    `build_fn(num_shards, exclude_chips)` must return a FRESH sim built
    from the same config apart from the partition: an IslandSimulation
    at `num_shards` > 1 (with `exclude_chips` skipped from the device
    mesh under shard_map) or the global engine at 1 — `config_builder`
    builds one from a config dict. The runner owns the supervisor (one
    instance across every rebuild, so loss counters and the dead-chip
    probe state survive relayouts) and the checkpoint ring config.

    Interlocks, per re-expansion decision (a relayout is never elective
    — loss always relayouts — but growing back is):
      * hysteresis: every lost chip must answer `hysteresis` CONSECUTIVE
        probes — a flapping chip resets its streak on every miss, so it
        can never drive a relayout storm;
      * cooldown: at least `cooldown` dispatches since the last mesh
        change;
      * balancer: an armed shard balancer in rollback cooldown, or a
        degraded/pressured supervisor posture, holds the re-expansion
        (the same yield rule the balancer itself follows).
    """

    def __init__(self, build_fn, *, chips: int, ckpt_dir: str,
                 checkpoint_every_ns: int = 0, retain: int = 3,
                 supervisor: BackendSupervisor | None = None,
                 probe_every: int = 2, hysteresis: int = 3,
                 cooldown: int = 4, faults=None,
                 windows_per_dispatch: int = 64, clock=time.monotonic):
        if not ckpt_dir:
            raise ValueError(
                "elastic relayout needs a checkpoint directory: the "
                "drain checkpoint IS the relayout seam"
            )
        self._build_fn = build_fn
        self.chips_total = int(chips)
        self.ckpt_dir = str(ckpt_dir)
        self.checkpoint_every_ns = int(checkpoint_every_ns)
        self.retain = int(retain)
        self.supervisor = supervisor or BackendSupervisor("relayout")
        if self.supervisor.policy != "relayout":
            raise ValueError(
                f"ElasticMeshRunner needs a policy-`relayout` supervisor "
                f"(got {self.supervisor.policy!r}); wait/cpu/abort runs "
                f"attach theirs directly to the sim"
            )
        self.probe_every = max(1, int(probe_every))
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown = max(0, int(cooldown))
        self._faults = list(faults) if faults else None
        self.windows_per_dispatch = int(windows_per_dispatch)
        self._clock = clock
        self.sim = None
        self.down: set[int] = set()
        self._streak: dict[int, int] = {}  # chip -> consecutive probe oks
        self._since_probe = 0
        self._since_change = 0
        self.counters = {
            "chips_lost": 0,
            "relayouts": 0,
            "re_expansions": 0,
            "relayout_downtime_ns": 0,
            "kernel_rebuilds": 0,  # one fresh kernel set per mesh change
            "reexpand_holds": 0,
        }
        self.last_relayout: dict | None = None

    # -- building + bookkeeping ------------------------------------------

    @property
    def chips_up(self) -> int:
        return self.chips_total - len(self.down)

    def _target_shards(self) -> int:
        H = self.sim.num_hosts if self.sim is not None else None
        if H is None:
            raise RuntimeError("no sim built yet")
        return admissible_shards(H, self.chips_up)

    def _attach(self, sim):
        """Wire the shared supervisor / fault plan / checkpoint ring /
        dispatch hook into a freshly-built sim."""
        sim.attach_supervisor(self.supervisor)
        if self._faults is not None:
            # ONE injector across rebuilds: fired marks persist, so a
            # kill_chip that already fired can never re-drain the
            # relayouted run (mirrors engine.resume_from's replay rule)
            if getattr(self, "_injector", None) is None:
                sim.attach_faults(self._faults)
                self._injector = sim.fault_injector
            else:
                sim.fault_injector = self._injector
        sim.configure_auto_checkpoint(
            self.ckpt_dir, self.checkpoint_every_ns, self.retain
        )
        sim.elastic = self
        self.sim = sim
        return sim

    def build(self, num_shards: int | None = None):
        """Build (or rebuild) the sim for the current chip posture."""
        if num_shards is None:
            # initial build: the caller's chip budget (host_mesh checks
            # divisibility); relayouts derive from the live host count
            num_shards = (
                self._target_shards() if self.sim is not None
                else self.chips_up
            )
        sim = self._build_fn(int(num_shards), tuple(sorted(self.down)))
        self.counters["kernel_rebuilds"] += 1
        return self._attach(sim)

    def stats(self) -> dict[str, int]:
        """Counters for the metrics `mesh.*` namespace (schema v12)."""
        return {k: int(v) for k, v in self.counters.items()}

    def gauges(self) -> dict:
        g = {
            "chips_up": int(self.chips_up),
            "chips_total": int(self.chips_total),
        }
        if self.last_relayout is not None:
            g["last_relayout_ns"] = int(
                self.last_relayout.get("frontier_ns", -1)
            )
        return g

    def posture(self) -> dict:
        """Operator-facing mesh posture (serve /healthz, shadowctl
        status): chips up/total, dead set, last relayout record."""
        return {
            "chips_up": int(self.chips_up),
            "chips_total": int(self.chips_total),
            "chips_down": sorted(self.down),
            "relayouts": int(self.counters["relayouts"]),
            "re_expansions": int(self.counters["re_expansions"]),
            "last_relayout": dict(self.last_relayout or {}),
        }

    # -- the dispatch-boundary hook (re-expansion probing) ---------------

    def on_dispatch(self, sim, mn: int) -> None:
        """Called by the driver at every committed dispatch boundary.
        Probes lost chips on the `probe_every` cadence; when every lost
        chip has held `hysteresis` consecutive probe successes AND the
        interlocks clear, raises MeshReexpand (caught by run(), which
        drains and rebuilds). Cheap no-op while nothing is down."""
        self._since_change += 1
        if not self.down:
            return
        self._since_probe += 1
        if self._since_probe < self.probe_every:
            return
        self._since_probe = 0
        recovered = set()
        for chip in sorted(self.down):
            if self.supervisor.probe_chip(chip):
                self._streak[chip] = self._streak.get(chip, 0) + 1
            else:
                self._streak[chip] = 0  # flap: the streak restarts
            if self._streak.get(chip, 0) >= self.hysteresis:
                recovered.add(chip)
        if not recovered:
            return
        if self._since_change < self.cooldown:
            self.counters["reexpand_holds"] += 1
            return
        bal = getattr(sim, "balancer", None)
        if bal is not None and getattr(bal, "in_cooldown", lambda: False)():
            # the balancer just rolled a migration back (or is mid-heal):
            # no elective mesh change while it cools down
            self.counters["reexpand_holds"] += 1
            return
        if self.supervisor.degraded:
            self.counters["reexpand_holds"] += 1
            return
        raise MeshReexpand(frozenset(recovered))

    # -- the elastic run loop --------------------------------------------

    def run(self, until: int | None = None) -> object:
        """Run to completion through any number of chip losses and
        recoveries; returns the final sim (audit chain, counters and
        metrics snapshots read from it)."""
        if self.sim is None:
            self.build(num_shards=None)
        while True:
            try:
                self.sim.run(
                    until=until,
                    windows_per_dispatch=self.windows_per_dispatch,
                )
                return self.sim
            except ChipLost as e:
                self._relayout_down(e)
            except MeshReexpand as e:
                self._relayout_up(e)

    def resume(self) -> None:
        """Crash recovery: rebuild for the current chip posture and
        restore the newest ring checkpoint (drain or periodic) through
        the relayout seam — the SIGKILL-mid-relayout path."""
        entries = ckpt_mod.ring_entries(self.ckpt_dir)
        if not entries:
            raise ckpt_mod.CheckpointError(
                f"{self.ckpt_dir}: nothing to resume from"
            )
        sim = self.build(num_shards=None)
        ckpt_mod.restore_relayout(sim, entries[-1][2])
        self._mark_replayed(sim)

    def _mark_replayed(self, sim) -> None:
        """Backend injections at or before the restored frontier already
        happened (engine.resume_from's rule, applied on the relayout
        path where restore_relayout cannot know about the injector)."""
        inj = getattr(sim, "fault_injector", None)
        if inj is None:
            return
        from shadow_tpu.faults import plan as plan_mod

        now = int(np.max(np.asarray(sim.state.now)))
        for f in inj.faults:
            if (not f.fired and f.op in plan_mod.BACKEND_OPS
                    and f.at_ns <= now):
                inj.mark_fired(f)

    def _relayout_down(self, e: ChipLost) -> None:
        """Chip loss: adopt the dead set, rebuild over the survivors,
        resume from the drain checkpoint the supervisor just wrote."""
        t0 = self._clock()
        if not e.chips:
            # no chip attribution (no injection, no MeshHealth): a
            # whole-backend loss cannot relayout around anything
            raise e
        self.counters["chips_lost"] += len(e.chips - self.down)
        self.down |= set(e.chips)
        for c in e.chips:
            self._streak[c] = 0
        if self.chips_up < 1:
            raise e  # every chip gone: nothing to relayout onto
        path = e.path
        if path is None:
            raise e  # no drain checkpoint: nothing to resume from
        old_s = getattr(self.sim, "num_shards", 1)
        new_s = self._target_shards()
        sim = self.build(new_s)
        ckpt_mod.restore_relayout(sim, path)
        self._mark_replayed(sim)
        self.counters["relayouts"] += 1
        self._since_change = 0
        dt = int((self._clock() - t0) * 1e9)
        self.counters["relayout_downtime_ns"] += dt
        self.last_relayout = {
            "reason": f"chip_lost:{sorted(e.chips)}",
            "from_shards": int(old_s), "to_shards": int(new_s),
            "frontier_ns": int(np.max(np.asarray(sim.state.now))),
            "wall_unix_s": time.time(),
            "downtime_ns": dt,
        }

    def _relayout_up(self, e: MeshReexpand) -> None:
        """Recovery: drain at the committed boundary, rebuild at the
        larger admissible shard count, resume through the same seam."""
        t0 = self._clock()
        path = self.sim._drain_to_checkpoint(
            f"re_expand:{sorted(e.chips)}"
        )
        if path is None:  # pragma: no cover — __init__ requires ckpt_dir
            raise RuntimeError(
                "re-expansion needs a checkpoint directory for the "
                "drain → relayout seam"
            )
        self.down -= set(e.chips)
        for c in e.chips:
            self._streak.pop(c, None)
        old_s = getattr(self.sim, "num_shards", 1)
        new_s = self._target_shards()
        sim = self.build(new_s)
        ckpt_mod.restore_relayout(sim, path)
        self._mark_replayed(sim)
        self.counters["re_expansions"] += 1
        self._since_change = 0
        dt = int((self._clock() - t0) * 1e9)
        self.counters["relayout_downtime_ns"] += dt
        self.last_relayout = {
            "reason": f"re_expand:{sorted(e.chips)}",
            "from_shards": int(old_s), "to_shards": int(new_s),
            "frontier_ns": int(np.max(np.asarray(sim.state.now))),
            "wall_unix_s": time.time(),
            "downtime_ns": dt,
        }


def config_builder(cfg: dict):
    """A `build_fn` over a config DICT (the build_simulation input):
    rebuilds with experimental.num_shards / exclude_chips overridden per
    relayout. At num_shards == 1 the islands keys drop away and the
    global engine builds — the S→1 endpoint. The copy is deep via JSON
    round-trip: configs are plain JSON/YAML data by construction."""
    import json

    base = json.loads(json.dumps(cfg))

    def build(num_shards: int, exclude_chips: tuple):
        from shadow_tpu.sim import build_simulation

        c = json.loads(json.dumps(base))
        exp = c.setdefault("experimental", {})
        if num_shards <= 1:
            for k in ("num_shards", "exchange_slots", "island_mode",
                      "mesh_exchange", "placement", "exclude_chips",
                      "async_spread", "balancer"):
                exp.pop(k, None)
            exp["num_shards"] = 1
        else:
            exp["num_shards"] = int(num_shards)
            exp["exclude_chips"] = [int(c_) for c_ in exclude_chips]
        return build_simulation(c)

    return build
