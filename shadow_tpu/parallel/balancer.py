"""Self-balancing fleet: closed-loop hot-shard healing (ISSUE 11).

The async plane (parallel/islands.py) MEASURES imbalance — per-shard
frontiers, occupancy vectors, blocked-on-neighbor supersteps — and the
traced lookahead matrix plus the slot_of routing table make live
re-partitioning recompile-free (rebalance_now). This module closes the
loop: an online controller that watches the async posture at every
dispatch boundary, and when one shard stays hot — the frontier laggard
with chronically skewed resident load, exactly what a `skew_hosts`
injection or a bursty production tenant produces — recomputes the
host→shard assignment by greedy min-cut refinement (PARSIR's
per-processor partition refinement, PAPERS.md: move boundary hosts off
the hot shard while keeping lookahead-critical links intra-shard) and
migrates at the next boundary through the existing traced-lookahead
seam.

Every migration is VERIFY-THEN-COMMIT: the pre-move digest chain and
committed-event count are captured, the permutation is applied, and the
post-move chain must extend the pre-move chain exactly (a host→shard
permutation commits nothing and the combine is order-independent, so
any difference is a divergence). A divergence — or a mid-migration
failure of any kind (backend loss during the state fetch, a pressure
rung firing) — rolls the simulation back to the pre-move snapshot and
enters a cooldown instead of oscillating. The balancer also YIELDS to
the other robustness planes: it never migrates during a pressure-ladder
episode, mid-optimistic-attempt, or while the backend supervisor is
degraded (holds are counted, never silently dropped).

Determinism: a migration permutes the layout only — per-host event
order, RNG streams and sequence numbering key on GLOBAL host ids — so a
balanced run's audit digest chain is bit-identical to the balancer-off
run (bench.py --balance-smoke gates this, with a forced mid-migration
rollback arm). This is a HOST module: nothing here is ever traced.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from shadow_tpu.core import simtime

NEVER = int(simtime.NEVER)

# balance.state gauge encoding (docs/observability.md v10)
STATE_STABLE = 0
STATE_MIGRATING = 1
STATE_COOLDOWN = 2

_STATE_NAMES = {
    STATE_STABLE: "stable",
    STATE_MIGRATING: "migrating",
    STATE_COOLDOWN: "cooldown",
}


@dataclasses.dataclass
class BalancerPolicy:
    """Knobs for the closed loop (docs/fault_tolerance.md §6).

    hot_ratio       a shard is hot when its resident load exceeds this
                    multiple of the mean shard load
    min_skew_rows   AND leads the lightest shard by at least this many
                    rows (noise floor: tiny absolute skews never trigger)
    streak          consecutive hot dispatches before a migration fires
                    (the hysteresis guard)
    cooldown        dispatches to sit out after any migration, rollback
                    or refinement no-op — the anti-oscillation clamp
    max_moves       boundary-host swaps per migration
    candidates      hosts considered per side of each swap (top loaded
                    on the hot shard x least loaded on the target)
    """

    hot_ratio: float = 1.5
    min_skew_rows: int = 32
    streak: int = 3
    cooldown: int = 8
    max_moves: int = 8
    candidates: int = 8


class HotnessDetector:
    """Pure hysteresis detector over the per-dispatch async posture.

    A shard is HOT when its resident occupancy exceeds ``hot_ratio`` x
    the mean (and the absolute skew clears the noise floor) AND — when
    the async driver's frontier vector is available — it is the frontier
    laggard (ties pass: at a clamped boundary every frontier sits at the
    dispatch stop). The same shard must stay hot for ``streak``
    consecutive dispatches before `observe` returns it; any other
    outcome resets the streak, so transient bursts never migrate.
    """

    def __init__(self, policy: BalancerPolicy):
        self.policy = policy
        self._shard = -1
        self._streak = 0

    def reset(self) -> None:
        self._shard = -1
        self._streak = 0

    @property
    def streak(self) -> int:
        return self._streak

    def observe(self, occ, frontier=None) -> int | None:
        occ = np.asarray(occ, np.float64)
        hot = int(np.argmax(occ))
        mean = float(occ.mean())
        is_hot = (
            mean > 0.0
            and occ[hot] > self.policy.hot_ratio * mean
            and occ[hot] - occ.min() >= self.policy.min_skew_rows
        )
        if is_hot and frontier is not None:
            f = np.asarray(frontier, np.int64)
            # the hot shard must also be the virtual-time laggard (or
            # tied with it) — load skew the schedule absorbs is not worth
            # a migration
            is_hot = bool(f[hot] <= f.min())
        if not is_hot:
            self.reset()
            return None
        if hot != self._shard:
            self._shard, self._streak = hot, 1
        else:
            self._streak += 1
        if self._streak < self.policy.streak:
            return None
        self.reset()
        return hot


# ---------------------------------------------------------------------------
# min-cut refinement (PARSIR-style per-processor partition refinement)
# ---------------------------------------------------------------------------


def _affinity_vv(latency_vv: np.ndarray) -> np.ndarray:
    """Vertex-pair communication affinity: inverse baked path latency
    (1e6/ns — microseconds of slack per event), 0 for unreachable pairs.
    Low-latency links carry the most affinity, so a cut that severs them
    costs the most — exactly the links whose severing would collapse the
    derived cross-shard lookahead (parallel/lookahead.py min_cross)."""
    lat = np.asarray(latency_vv, np.float64)
    with np.errstate(divide="ignore"):
        aff = 1e6 / np.maximum(lat, 1.0)
    aff[np.asarray(latency_vv, np.int64) >= NEVER] = 0.0
    return aff


def host_affinity(latency_vv: np.ndarray, host_vertex: np.ndarray
                  ) -> np.ndarray:
    """[H, H] symmetrized host-pair affinity (O(H^2) floats — computed
    only when a migration actually triggers, never per dispatch)."""
    hv = np.asarray(host_vertex, np.int64)
    aff = _affinity_vv(latency_vv)[np.ix_(hv, hv)]
    return aff + aff.T


def cut_cost(shard_of: np.ndarray, latency_vv: np.ndarray,
             host_vertex: np.ndarray) -> float:
    """Total affinity crossing shard boundaries under `shard_of` ([H]
    shard index per global host id) — the objective the refinement holds
    down, `tools/lookahead_report.py --assignment/--mesh` prints for
    offline review, and the mesh telemetry gauges per run.

    Computed at the VERTEX level — hosts collapse onto used vertices, so
    the cross sum is n'An − Σ_s c_s'A c_s over per-shard vertex counts
    (identical to the O(H²) host-pair sum, since same-host pairs are
    always intra-shard and cancel) — O(S·U²) instead of O(H²), cheap
    enough to gauge every metrics snapshot at dryrun host counts."""
    shard = np.asarray(shard_of, np.int64)
    hv = np.asarray(host_vertex, np.int64)
    aff = _affinity_vv(latency_vv)
    aff = aff + aff.T  # symmetrized, exactly as host_affinity
    S = int(shard.max()) + 1 if shard.size else 1
    cnt = np.zeros((S, aff.shape[0]), np.float64)
    np.add.at(cnt, (shard, hv), 1.0)
    n = cnt.sum(axis=0)
    total = float(n @ aff @ n)
    intra = float(sum(c @ aff @ c for c in cnt))
    return (total - intra) / 2.0  # symmetrized: halve


def min_cut_placement(latency_vv: np.ndarray, host_vertex: np.ndarray,
                      num_shards: int) -> np.ndarray:
    """Build-time min-cut host→chip placement (the PARSIR-style
    per-processor partition, PAPERS.md; Shadow's host-to-worker
    assignment): greedy affinity clustering at the VERTEX level — grow
    each shard by repeatedly pulling in the unassigned vertex with the
    highest total affinity to the shard's current vertex set, seeding
    each shard with the strongest remaining community — so low-latency
    (lookahead-critical) links land intra-chip and the derived
    cross-shard lookahead (parallel/lookahead.py min_cross) stays as
    large as a balanced partition allows. Slot counts are FIXED at H/S
    per shard (the compiled layout); an over-full vertex splits across
    shards at the boundary.

    Returns the [H] host→slot permutation `IslandSimulation.migrate_hosts`
    consumes (hosts of one vertex fill slots in global-id order —
    deterministic for a given topology)."""
    hv = np.asarray(host_vertex, np.int64)
    H = hv.shape[0]
    S = int(num_shards)
    if S <= 0 or H % S:
        raise ValueError(f"num_hosts {H} must divide by num_shards {S}")
    Hl = H // S
    aff = _affinity_vv(latency_vv)
    aff = aff + aff.T
    U = aff.shape[0]
    # hosts per vertex, in global-id order (deterministic slot filling)
    hosts_of = [np.flatnonzero(hv == u) for u in range(U)]
    rem = np.array([len(h) for h in hosts_of], np.int64)
    taken = [0] * U  # hosts of vertex u already placed
    slot = np.empty(H, np.int32)
    prev_in_shard = np.zeros(U, np.float64)
    for s in range(S):
        space = Hl
        in_shard = np.zeros(U, np.float64)  # vertex counts on this shard
        while space > 0:
            open_ = rem > 0
            if in_shard.sum() == 0.0:
                # seed: prefer the unassigned vertex most affine to the
                # PREVIOUS chip — consecutive chips then hold adjacent
                # communities, so the shard-level graph inherits the
                # topology's shape (a community ring stays a ring and
                # the ppermute schedule stays 2 shifts wide) instead of
                # scattering ring edges across arbitrary chip pairs
                score = aff @ prev_in_shard * open_
                if float(score.max(initial=0.0)) <= 0.0:
                    # no tie to the previous chip (first shard, or a
                    # disconnected component): strongest remaining
                    # community seeds the next chain
                    score = aff @ (rem.astype(np.float64)) * open_
            else:
                score = aff @ in_shard * open_
            # ties (e.g. a fully uniform topology) break on vertex id,
            # so the placement degenerates to the block partition
            u = int(np.argmax(score + 1e-12 * open_))
            if not open_[u]:
                u = int(np.flatnonzero(open_)[0])
            take = int(min(rem[u], space))
            hosts = hosts_of[u][taken[u]:taken[u] + take]
            base = s * Hl + (Hl - space)
            slot[hosts] = base + np.arange(take, dtype=np.int32)
            taken[u] += take
            rem[u] -= take
            in_shard[u] += take
            space -= take
        prev_in_shard = in_shard
    # never worse than the block partition: greedy growth can lose to
    # contiguity on topologies whose id order already encodes locality
    # (a plain ring), so keep whichever cut is lower — the identity
    # permutation also means "placement off" costs nothing there
    Hl_slots = np.arange(H, dtype=np.int32)
    if cut_cost(slot // Hl, latency_vv, hv) >= cut_cost(
        Hl_slots // Hl, latency_vv, hv
    ):
        return Hl_slots
    return slot


def refine_assignment(
    load: np.ndarray,
    cur_slot: np.ndarray,
    num_shards: int,
    hot: int,
    latency_vv: np.ndarray,
    host_vertex: np.ndarray,
    policy: BalancerPolicy | None = None,
) -> tuple[np.ndarray, int, float, float]:
    """Greedy min-cut refinement of the host→slot assignment.

    Slot counts per shard are FIXED (the compiled layout holds H/S rows
    per shard), so every move is a SWAP: a heavy host on the hot shard
    exchanges slots with a light host on the currently lightest shard.
    Swap selection is load-first, cut-aware: among candidate pairs whose
    load gain is at least half the best available, take the one with the
    smallest cut-cost increase — boundary hosts (low affinity to their
    own shard) move first, and a host carrying a lookahead-critical
    intra-shard link effectively never does. Stops when the hot shard's
    load falls back under the hot_ratio band, or after max_moves, or
    when no candidate swap still sheds load.

    Returns (new_slot, moves, cut_before, cut_after).
    """
    policy = policy or BalancerPolicy()
    load = np.asarray(load, np.int64)
    slot = np.array(cur_slot, np.int32)
    H = slot.shape[0]
    S = int(num_shards)
    Hl = H // S
    shard_of = slot // Hl
    aff = host_affinity(latency_vv, host_vertex)
    cut0 = cut_before = float(
        aff[shard_of[:, None] != shard_of[None, :]].sum() / 2.0
    )
    cut = cut0

    def shard_loads():
        return np.bincount(shard_of, weights=load, minlength=S)

    moves = 0
    # settle just under the trigger band, not to perfect flatness: a
    # target tighter than the detector's own threshold would re-trigger
    # on the first post-migration wobble
    for _ in range(policy.max_moves):
        sl = shard_loads()
        mean = sl.mean()
        if sl[hot] <= max(policy.hot_ratio * mean, mean + 1):
            break
        target = int(np.argmin(sl))
        if target == hot:
            break
        hot_hosts = np.flatnonzero(shard_of == hot)
        cold_hosts = np.flatnonzero(shard_of == target)
        cand_h = hot_hosts[np.argsort(-load[hot_hosts], kind="stable")][
            :policy.candidates]
        cand_c = cold_hosts[np.argsort(load[cold_hosts], kind="stable")][
            :policy.candidates]
        best = None  # (cut_delta, -gain, h, c)
        gain_best = 0
        pairs = []
        for h in cand_h:
            for c in cand_c:
                gain = int(load[h] - load[c])
                if gain <= 0:
                    continue
                gain_best = max(gain_best, gain)
                pairs.append((int(h), int(c), gain))
        for h, c, gain in pairs:
            if gain * 2 < gain_best:
                continue  # load-first: only near-best shedders compete
            in_hot = shard_of == hot
            in_tgt = shard_of == target
            aff_h_hot = aff[h, in_hot].sum() - aff[h, h]
            aff_h_tgt = aff[h, in_tgt].sum() - aff[h, c]
            aff_c_tgt = aff[c, in_tgt].sum() - aff[c, c]
            aff_c_hot = aff[c, in_hot].sum() - aff[c, h]
            delta = (aff_h_hot - aff_h_tgt) + (aff_c_tgt - aff_c_hot)
            key = (delta, -gain, h, c)
            if best is None or key < best:
                best = key
        if best is None:
            break
        delta, _, h, c = best
        slot[h], slot[c] = slot[c], slot[h]
        shard_of[h], shard_of[c] = target, hot
        cut += delta
        moves += 1
    return slot, moves, cut_before, float(cut)


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------


class ShardBalancer:
    """The closed-loop controller, one per IslandSimulation (attach via
    ``sim.attach_balancer`` / ``experimental.balancer: true``). The
    driver calls ``observe`` at every fused-dispatch boundary with the
    per-shard occupancy vector and (under the async driver) the frontier
    surface; everything else — detection hysteresis, interlocks,
    refinement, verified migration, rollback, cooldown — happens here.
    """

    def __init__(self, policy: BalancerPolicy | None = None):
        self.policy = policy or BalancerPolicy()
        self.detector = HotnessDetector(self.policy)
        self.state = STATE_STABLE
        self._cooldown = 0
        self._fail_next = False  # test/bench hook: forced mid-migration
        # failure on the next attempt (exercises the rollback path)
        self.last_hot = -1
        self.last_moves = 0
        self.last_cut_before = 0.0
        self.last_cut_after = 0.0
        self.last_reason = ""
        self.counters = {
            "migrations": 0,
            "rollbacks": 0,
            "holds": 0,
            "cooldown_dispatches": 0,
            "refine_noops": 0,
            "hosts_moved": 0,
        }

    def in_cooldown(self) -> bool:
        """True while a migration/rollback cooldown is running — the
        elastic mesh runner's re-expansion interlock (parallel/
        elastic.py): no elective mesh change while the balancer is
        settling one of its own."""
        return self._cooldown > 0

    # -- test/bench hook --

    def inject_failure_next(self) -> None:
        """Force the next migration attempt to fail mid-move (after the
        hotness trigger, before commit) — the --balance-smoke rollback
        arm and the rollback regression test drive this."""
        self._fail_next = True

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    # -- interlocks: the balancer yields to every other robustness plane --

    def _held(self, sim) -> bool:
        pc = getattr(sim, "pressure", None)
        if pc is not None and (
            pc.hold_gear
            or pc.fill_shrink > 0
            or pc._stall_steps > 0
            or (pc.saturate_frac is not None and pc.saturate_frac < 1.0)
        ):
            return True  # pressure-ladder episode in progress
        if not getattr(sim, "_pressure_reshape_ok", True):
            return True  # mid-optimistic-attempt snapshot pins the layout
        sup = getattr(sim, "supervisor", None)
        if sup is not None and sup.degraded:
            return True  # backend lost / CPU failover: no elective moves
        return False

    # -- the per-dispatch hook --

    def observe(self, sim, occ, frontier=None) -> bool:
        """One dispatch-boundary observation; True iff a migration
        committed. Called by IslandSimulation.run at the handoff
        boundary (state synced, spill manage done for the dispatch)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            self.counters["cooldown_dispatches"] += 1
            if self._cooldown == 0:
                self.state = STATE_STABLE
            return False
        if self._held(sim):
            self.counters["holds"] += 1
            self.detector.reset()
            return False
        hot = self.detector.observe(occ, frontier)
        if hot is None:
            return False
        return self._migrate(sim, hot)

    def _migrate(self, sim, hot: int) -> bool:
        """Refine + verify-then-commit one migration at this boundary."""
        import jax

        self.last_hot = hot
        load = sim.host_loads()
        cur_slot = np.asarray(jax.device_get(sim.params.slot_of))
        new_slot, moves, cut0, cut1 = refine_assignment(
            load, cur_slot, sim.num_shards, hot,
            sim._latency_np, sim._host_vertex_g, self.policy,
        )
        self.last_cut_before, self.last_cut_after = cut0, cut1
        if moves == 0:
            # refinement found nothing to shed (single over-heavy host,
            # or every swap loses load): cool down rather than re-scoring
            # the same posture every dispatch
            self.counters["refine_noops"] += 1
            self._enter_cooldown("refine_noop")
            return False
        pre_chain = sim.audit_chain()
        pre_events = sim.counters()["events_committed"]
        snap = sim._balance_snapshot()
        self.state = STATE_MIGRATING
        try:
            if self._fail_next:
                self._fail_next = False
                raise RuntimeError(
                    "injected mid-migration failure (balance test hook)"
                )
            sim.migrate_hosts(new_slot)
            ok = (
                sim.audit_chain() == pre_chain
                and sim.counters()["events_committed"] == pre_events
            )
            reason = "" if ok else "digest chain diverged"
        except Exception as e:  # noqa: BLE001 — rollback-or-die is the
            # contract: a mid-migration backend loss or pressure signal
            # must leave the PRE-move layout running (the next dispatch's
            # supervisor handles a genuinely dead backend)
            ok, reason = False, f"{type(e).__name__}: {e}"
        if not ok:
            sim._balance_rollback(snap)
            self.counters["rollbacks"] += 1
            self._enter_cooldown(reason)
            return False
        self.last_moves = moves
        self.counters["migrations"] += 1
        self.counters["hosts_moved"] += 2 * moves  # each move is a swap
        obs = getattr(sim, "obs_session", None)
        if obs is not None and obs.tracer:
            obs.tracer.fault(
                "balance_migration", hot_shard=hot, moves=moves,
            )
        self._enter_cooldown("")
        return True

    def _enter_cooldown(self, reason: str) -> None:
        self.last_reason = reason
        self._cooldown = max(1, self.policy.cooldown)
        self.state = STATE_COOLDOWN

    # -- telemetry (metrics schema v10 `balance.*`) + checkpoint carry --

    def stats(self) -> dict[str, int]:
        return dict(self.counters)

    def gauges(self) -> dict:
        return {
            "state": int(self.state),
            "hot_shard": int(self.last_hot),
            "streak": int(self.detector.streak),
            "cooldown_left": int(self._cooldown),
            "last_moves": int(self.last_moves),
            "last_cut_before": float(self.last_cut_before),
            "last_cut_after": float(self.last_cut_after),
        }

    def meta(self) -> dict:
        """Checkpoint `__meta__.balance` sub-block: controller posture,
        restored by IslandSimulation on resume so a resumed run neither
        forgets an active cooldown nor re-fires instantly."""
        return {
            "state": self.state_name,
            "cooldown_left": int(self._cooldown),
            "counters": dict(self.counters),
        }

    def restore_meta(self, m: dict) -> None:
        self._cooldown = max(0, int(m.get("cooldown_left", 0)))
        self.state = (
            STATE_COOLDOWN if self._cooldown else STATE_STABLE
        )
        for k, v in sorted((m.get("counters") or {}).items()):
            if k in self.counters:
                self.counters[k] = int(v)
        self.detector.reset()
