"""Mesh construction + SimState sharding rules.

One axis — ``hosts`` — because host-parallelism is the simulator's only
data-parallel dimension (SURVEY §2.5: no tensor/pipeline analogs exist; the
reference's work stealing (P3) becomes re-sharding between windows, and CPU
pinning (P5) is owned by XLA).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "hosts"


def host_mesh(n_devices: int | None = None, axis: str = AXIS,
              num_hosts: int | None = None,
              exclude: tuple[int, ...] = ()) -> Mesh:
    """A 1-D mesh over the first n devices (all by default).

    Device order is DETERMINISTIC — sorted by (process_index, id) — so
    every process of a multi-host run (and every restart of this one)
    resolves the identical chip <-> shard binding; jax.devices() order is
    already id-sorted on a single process, but that is an implementation
    detail this function refuses to depend on.

    `exclude` names dead chips by index INTO THAT DETERMINISTIC ORDER
    (the elastic resilience plane's surviving-mesh rebuild,
    parallel/elastic.py): excluded devices are skipped before the first-n
    selection, so a mesh of n survivors is built around the holes and
    every process resolves the identical degraded binding.

    `num_hosts` (when given) must divide evenly over the mesh: the
    islands layout holds exactly H/S host rows per chip and PADS NOTHING
    — an uneven split would give the last chip a short block and break
    the [S, H/S] reshape. The pad rule is explicit and caller-side: round
    the host count UP to the next multiple of the mesh size with idle
    hosts (no app model, no events — they cost one state row each and
    never run), rather than this layer inventing ghost rows that every
    per-host plane (RNG streams, digests, flight rings) would then have
    to mask.
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if exclude:
        dead = {int(c) for c in exclude}
        bad = sorted(c for c in dead if not 0 <= c < len(devs))
        if bad:
            raise ValueError(
                f"exclude names chip indices {bad} outside the "
                f"{len(devs)}-device set"
            )
        devs = [d for i, d in enumerate(devs) if i not in dead]
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(f"need a positive mesh size, got {n_devices}")
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(tests virtualize with xla_force_host_platform_device_count)"
            )
        devs = devs[:n_devices]
    if num_hosts is not None and num_hosts % len(devs):
        raise ValueError(
            f"num_hosts {num_hosts} does not divide evenly over the "
            f"{len(devs)}-device mesh ({num_hosts % len(devs)} hosts "
            f"left over); pad the host count up to "
            f"{-(-num_hosts // len(devs)) * len(devs)} with idle hosts "
            f"(see host_mesh docstring for the pad rule) or pick a mesh "
            f"size that divides it"
        )
    return Mesh(np.array(devs), (axis,))


def replicate(mesh: Mesh):
    return NamedSharding(mesh, P())


def _row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS, *([None] * (ndim - 1))))


def shard_state(state, mesh: Mesh):
    """Place a SimState on the mesh: host-indexed arrays shard over their
    leading axis, scalars replicate.

    Every pool/host/subs leaf is [H]- or [C]-leading (the engine's SoA
    contract), so the rule is uniform; counters and clocks replicate.
    """
    repl = replicate(mesh)

    def row(x):
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            # sub-state scalars (e.g. the TCP machine's counters) replicate
            return jax.device_put(x, repl)
        return jax.device_put(x, _row_sharding(mesh, x.ndim))

    pool = jax.tree.map(row, state.pool)
    host = jax.tree.map(row, state.host)
    subs = jax.tree.map(row, state.subs)
    return state.replace(
        pool=pool,
        host=host,
        subs=subs,
        rng_keys=row(state.rng_keys),
        now=jax.device_put(state.now, repl),
        xmit_min=jax.device_put(state.xmit_min, repl),
        counters=jax.tree.map(lambda x: jax.device_put(x, repl), state.counters),
    )


def shard_params(params, mesh: Mesh):
    """Baked topology matrices + scalars replicate (they are read-only and
    small relative to state; sharding them would turn every latency lookup
    into a collective)."""
    repl = replicate(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, repl), params)


def shard_island_state(state, mesh: Mesh):
    """Place an ISLANDIZED SimState (parallel/islands.islandize_state)
    on the mesh: every leaf is [S, ...]-leading — host rows [S, H/S, ...],
    pool rows [S, C_shard, ...], per-shard counters/clocks [S] — so one
    uniform rule pins each shard's block to its chip: shard axis 0 over
    the mesh axis, everything else replicated within the chip. This is
    what makes shard_map islands TRUE multi-chip execution: each chip
    holds only its own H/S hosts and C_shard pool rows (HBM scales out
    with the mesh), and the window kernel's collectives (bounded
    all_to_all event exchange, neighbor-only ppermute frontier exchange,
    pmin reductions) are the only cross-chip traffic."""
    axis = mesh.axis_names[0]

    def row(x):
        x = jax.numpy.asarray(x)
        if x.ndim == 0:  # defensive: a scalar leaf replicates
            return jax.device_put(x, NamedSharding(mesh, P()))
        # the BARE P(axis) spec, not P(axis, None, ...): trailing dims
        # are implicitly replicated either way, but jit's cache keys on
        # the spec literally — the islands kernel's out_specs use the
        # bare form, so an explicit-None re-pin would retrace every
        # kernel on the first dispatch after a gear resize or migration
        return jax.device_put(x, NamedSharding(mesh, P(axis)))

    return jax.tree.map(row, state)


class MeshHealth:
    """Per-chip liveness probing — the supervisor's probe signal
    (core/supervisor.probe_backend, the cs/0409032 bounded-lag check)
    run PER DEVICE instead of against the default backend, so one sick
    chip in an 8-chip mesh reads as one dead chip, not a dead mesh.

    Chips are addressed by index into the deterministic
    (process_index, id) device order `host_mesh` uses, so a probe
    verdict and a mesh slot always name the same silicon. `probe_fn`
    is injectable for tests: it receives the device and returns
    truthiness (the default dispatches one trivial op pinned to the
    device and blocks on it)."""

    def __init__(self, n_devices: int | None = None, probe_fn=None):
        devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        if n_devices is not None:
            devs = devs[: int(n_devices)]
        self.devices = list(devs)
        self._probe_fn = probe_fn or self._default_probe

    @staticmethod
    def _default_probe(dev) -> bool:
        try:
            jax.device_put(
                jax.numpy.zeros((), jax.numpy.int32), dev
            ).block_until_ready()
            return True
        except Exception:
            return False

    def probe_chip(self, chip: int) -> bool:
        """One liveness probe against chip `chip`; False for an index
        outside the known device set (a chip that fell off the bus)."""
        if not 0 <= int(chip) < len(self.devices):
            return False
        return bool(self._probe_fn(self.devices[int(chip)]))

    def probe_all(self) -> list[bool]:
        """The up/down mask over every chip, probe order = mesh order."""
        return [self.probe_chip(i) for i in range(len(self.devices))]


def shard_sim(sim, mesh: Mesh):
    """Shard a built Simulation's state/params in place and return it.

    The jitted window kernels are sharding-oblivious: GSPMD propagates the
    input shardings and inserts the cross-shard event exchange + min-time
    reduction. Host counts should divide the mesh size for an even split.
    """
    sim.state = shard_state(sim.state, mesh)
    sim.params = shard_params(sim.params, mesh)
    return sim
