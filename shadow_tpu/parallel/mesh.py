"""Mesh construction + SimState sharding rules.

One axis — ``hosts`` — because host-parallelism is the simulator's only
data-parallel dimension (SURVEY §2.5: no tensor/pipeline analogs exist; the
reference's work stealing (P3) becomes re-sharding between windows, and CPU
pinning (P5) is owned by XLA).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "hosts"


def host_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the first n devices (all by default)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(tests virtualize with xla_force_host_platform_device_count)"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def replicate(mesh: Mesh):
    return NamedSharding(mesh, P())


def _row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS, *([None] * (ndim - 1))))


def shard_state(state, mesh: Mesh):
    """Place a SimState on the mesh: host-indexed arrays shard over their
    leading axis, scalars replicate.

    Every pool/host/subs leaf is [H]- or [C]-leading (the engine's SoA
    contract), so the rule is uniform; counters and clocks replicate.
    """
    repl = replicate(mesh)

    def row(x):
        x = jax.numpy.asarray(x)
        if x.ndim == 0:
            # sub-state scalars (e.g. the TCP machine's counters) replicate
            return jax.device_put(x, repl)
        return jax.device_put(x, _row_sharding(mesh, x.ndim))

    pool = jax.tree.map(row, state.pool)
    host = jax.tree.map(row, state.host)
    subs = jax.tree.map(row, state.subs)
    return state.replace(
        pool=pool,
        host=host,
        subs=subs,
        rng_keys=row(state.rng_keys),
        now=jax.device_put(state.now, repl),
        xmit_min=jax.device_put(state.xmit_min, repl),
        counters=jax.tree.map(lambda x: jax.device_put(x, repl), state.counters),
    )


def shard_params(params, mesh: Mesh):
    """Baked topology matrices + scalars replicate (they are read-only and
    small relative to state; sharding them would turn every latency lookup
    into a collective)."""
    repl = replicate(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, repl), params)


def shard_sim(sim, mesh: Mesh):
    """Shard a built Simulation's state/params in place and return it.

    The jitted window kernels are sharding-oblivious: GSPMD propagates the
    input shardings and inserts the cross-shard event exchange + min-time
    reduction. Host counts should divide the mesh size for an even split.
    """
    sim.state = shard_state(sim.state, mesh)
    sim.params = shard_params(sim.params, mesh)
    return sim
