"""Multi-device scale-out: host-dimension data parallelism over a mesh.

The reference scales by sharding hosts across worker threads with per-host
locks (SURVEY §2.5 P1) and a barriered round window (P2); its cross-worker
"communication backend" is a push into the destination's locked queue
(scheduler.c:232). Here the same structure maps onto a `jax.sharding.Mesh`:

- host state and event pool shard over the ``hosts`` mesh axis;
- the baked topology matrices and scalar clocks replicate;
- GSPMD inserts the collectives the reference does by hand: the per-window
  destination-sharded event exchange is an all-to-all over ICI, and the
  min-next-event-time barrier reduction is a global min.

Multi-host (DCN) runs use the same annotations over a multi-process mesh —
the window kernel is oblivious to where the collectives ride.
"""

from shadow_tpu.parallel.balancer import (  # noqa: F401
    BalancerPolicy,
    ShardBalancer,
)
from shadow_tpu.parallel.islands import IslandSimulation  # noqa: F401
from shadow_tpu.parallel.mesh import (  # noqa: F401
    host_mesh,
    replicate,
    shard_params,
    shard_sim,
    shard_state,
)
