"""Force a virtual n-device CPU platform.

Multi-chip TPU hardware is not available in this environment; the sharding
layer is validated on a virtual CPU mesh instead
(``--xla_force_host_platform_device_count``). The axon site hook pins
JAX_PLATFORMS=axon, so the env var alone is not enough — the jax config
value must be overridden too, before any backend initializes. Both the
test suite (tests/conftest.py) and the driver gate
(__graft_entry__.dryrun_multichip) go through this helper.
"""

from __future__ import annotations

import os


def force_cpu_devices(n_devices: int, cache_dir: str | None = None):
    """Virtualize n CPU devices; must run before the JAX backend
    initializes (importing jax is fine — first device use is not).

    Returns the jax module. Raises RuntimeError if virtualization did not
    take (e.g. a backend was already initialized on another platform).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if cache_dir is not None:
        # Persistent compilation cache: the dominant cost everywhere is XLA
        # compiles of the window-step program (one per distinct sim shape).
        jax.config.update(
            "jax_compilation_cache_dir", os.path.abspath(cache_dir)
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    ndev = len(jax.devices())
    if ndev < n_devices:
        raise RuntimeError(
            f"virtualization failed: need {n_devices} devices, have {ndev}"
        )
    return jax
