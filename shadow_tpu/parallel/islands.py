"""The islands runner: per-shard event pools with an all_to_all exchange.

The reference's parallel architecture is per-worker LOCALITY: hosts are
partitioned across workers (scheduler.c:329-353), each worker pops only its
own hosts' queues (scheduler_policy_host_single.c:18-54), and a cross-host
emission is one push into the owner's locked queue (scheduler.c:232-255,
worker.c:517-576). GSPMD auto-sharding of the single-pool engine reproduces
none of that locality: every shard participates in every global sort.

This module is the TPU-native equivalent of the reference design:

  * the host axis splits into S contiguous blocks ("islands"); each owns a
    LOCAL event pool (C/S rows) and a LOCAL dense window (H/S·(K+1) filler
    rows), so per-shard sort volume — the measured dominant window cost —
    drops S×;
  * cross-shard emissions ride ONE bounded all_to_all per window at the
    merge (engine._island_route): the locked-queue push becomes a
    collective;
  * the round barrier + min-next-event-time reduction (worker.c:332-363)
    becomes a lax.pmin over the shard axis;
  * rows that miss the bounded exchange defer to the next window under a
    window-end clamp (state.exch_deferred_min), so the conservative
    invariant survives backpressure — late, never lost, never reordered.

One implementation, two executions:
  mode="vmap"      S virtual islands batched on ONE chip: every local sort
                   becomes a batched sort (S× smaller rows per sort);
                   collectives lower to reshapes. This is how a single
                   TPU benefits from the islands formulation.
  mode="shard_map" S real devices on a jax Mesh: each island lives on its
                   own chip; collectives ride ICI/DCN. Same program,
                   hardware parallelism.

Determinism: per-host event order, RNG streams and sequence numbering are
functions of (seed, GLOBAL host id) only, so islands runs are bit-identical
to the global engine apart from pool-overflow timing (tests assert exact
counter equality on non-overflowing runs).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import gearbox, simtime
from shadow_tpu.core import spill as spill_mod
from shadow_tpu.core.engine import IslandSpec, Simulation, make_window_step
from shadow_tpu.core.spill import HostSpill
from shadow_tpu.core.state import Counters, EventPool, SimState
from shadow_tpu.parallel import lookahead as lookahead_mod

AXIS = "islands"

# Per-attempt sub-step ceiling for optimistic windows: generous (a window
# of factor F needs ~F sub-steps plus exchange-retry rounds), small enough
# that a pool-headroom stall surfaces as a driver error in seconds.
_MAX_SUBSTEPS = 4096


# ---------------------------------------------------------------------------
# Shard kernel factories
# ---------------------------------------------------------------------------
#
# Module-level so the fleet runner (shadow_tpu/fleet) can compose them:
# vmap-of-jobs OUTSIDE, shards INSIDE (the per-shard collectives stay on
# the inner axis). `runahead` and `stop` are traced arguments — the fleet
# passes per-job values; IslandSimulation._build_gear_fns closes over its
# own runahead and delegates here.


def make_shard_run_to(step, hi: int, axis: str = AXIS):
    """Build run_to(state, params, runahead, stop, max_windows) ->
    (state, min_next, pressed, occupancy, windows) for ONE shard of the
    islands engine; wrap with vmap(axis_name=axis) over the shard axis
    (or shard_map) to get the full per-job kernel."""

    def step_shard(state, params, ws, we):
        st, mn = step(state, params, ws, we)
        return st, jax.lax.pmin(mn, axis)

    def _occ(state):
        return jnp.sum(state.pool.time != simtime.NEVER)

    def _press(state):
        return jax.lax.pmax((_occ(state) >= hi).astype(jnp.int32), axis)

    def run_to(state, params, runahead, stop, max_windows):
        runahead = jnp.asarray(runahead, jnp.int64)
        stop = jnp.asarray(stop, jnp.int64)
        max_windows = jnp.asarray(max_windows, jnp.int32)

        def cond(c):
            state, mn, w = c
            return (mn < stop) & (w < max_windows) & (_press(state) == 0)

        def body(c):
            state, mn, w = c
            ws = mn
            # exchange-backpressure clamp: never let any shard process
            # past an event still in transit (deferred exchange)
            clamp = jax.lax.pmin(state.exch_deferred_min, axis)
            we = jnp.minimum(jnp.minimum(ws + runahead, stop), clamp)
            state, mn = step_shard(state, params, ws, we)
            return state, mn, w + 1

        mn0 = jax.lax.pmin(jnp.min(state.pool.time), axis)
        state, mn, w = jax.lax.while_loop(
            cond, body, (state, mn0, jnp.int32(0))
        )
        # occupancy rides back pmax'd: the gearing decision covers the
        # FULLEST shard (every shard's pool compiles the same capacity)
        occ = jax.lax.pmax(_occ(state), axis)
        return state, mn, _press(state) > 0, occ, w

    return run_to


def make_shard_run_to_async(step, hi: int, axis: str = AXIS,
                            shifts: tuple[int, ...] | None = None,
                            num_shards: int | None = None):
    """Build run_to(state, params, runahead, look_in, spread, stop,
    max_windows) -> (state, min_next, pressed, occupancy, windows,
    frontier, spread_max, steps, yields, blocked) — the ASYNCHRONOUS
    conservative window loop (cs/0409032) for ONE shard of the islands
    engine; wrap with vmap(axis_name=axis) over the shard axis (or
    shard_map) to get the full kernel.

    With `shifts` (a static tuple of ring shifts covering every finite
    in-edge — parallel/lookahead.ppermute_shifts — plus `num_shards`),
    the frontier/minimum exchange is NEIGHBOR-ONLY: one
    ``jax.lax.ppermute`` per shift instead of an ``all_gather`` over
    the shard axis, so per-chip collective volume under shard_map is
    len(shifts) scalars per superstep (topology degree), not S (mesh
    size), and the optimized HLO of the mesh kernel carries ZERO
    all-gather ops (hlo_audit-gated). shifts=None keeps the all_gather
    exchange — the bench comparison arm. Both arms compute the
    identical horizon, so committed events and audit chains are
    bit-identical.

    Where make_shard_run_to's barrier loop advances every shard to one
    fleet-wide frontier per window (ws = pmin of all local minima), each
    shard here carries its OWN virtual-time frontier in the loop carry
    and steps its own window [mn_local, mn_local + runahead_local)
    whenever its next local event lies below its safe horizon

        horizon_i = min over in-neighbors j of  frontier[j] + look_in[j]

    with the lookahead matrix derived from the baked topology at
    partition time (parallel/lookahead.py). Shards with no admissible
    work run a NULL window at their frontier — under vmap every shard
    rides the batched step anyway, so the bounded all_to_all exchange
    retries deferred rows every superstep — and advance their frontier
    to the horizon (the async protocol's null-message advance). When NO
    shard can step, every frontier jumps to the global next-event time
    (all future events derive from events at or after it): the barrier
    driver's ws = global-min gap jump, recovered for idle regions.

    Roughness suppression (cond-mat/0302050): a shard more than `spread`
    ns above the minimum frontier yields its slot (a null window),
    keeping the virtual-time surface flat so run-ahead pool/exchange
    buffering stays bounded; the minimum-frontier shard can never yield,
    so progress is unconditional. `runahead` (per shard), `look_in`
    ([S] in-edge lookahead, NEVER = unconstrained), `spread` and `stop`
    are all TRACED — the fleet passes per-lane values, and a rebalance
    re-derives the matrix without recompiling.

    The conservative invariant, per superstep: shard j's emissions in
    [ws_j, we_j) land at or after ws_j + L[j->i] >= frontier[j] +
    look_in[j->i] >= horizon_i >= we_i, so nothing i processes this
    superstep can be overtaken by an in-flight delivery. That LBTS
    argument covers only events still to be EMITTED; a deferred
    exchange row has already been emitted and paid its path latency —
    it lands at its pool time, NOT at source-frontier + L — so both
    the running horizon and the initial frontier f0 must additionally
    min against the gathered exch_deferred_min (the earliest
    in-transit row fleet-wide). Committed per-host event order is
    identical to the barrier schedule, so the audit digest chain is
    bit-identical (tests/test_async_sync.py).
    """

    NEV = jnp.int64(simtime.NEVER)

    if shifts is not None:
        if num_shards is None:
            raise ValueError(
                "make_shard_run_to_async(shifts=...) needs num_shards "
                "(the ppermute schedule is a static compiled property)"
            )
        S = int(num_shards)
        _perms = [
            [(j, (j + int(d)) % S) for j in range(S)] for d in shifts
        ]

    def _occ(state):
        return jnp.sum(state.pool.time != simtime.NEVER)

    def _press(state):
        return jax.lax.pmax((_occ(state) >= hi).astype(jnp.int32), axis)

    def run_to(state, params, runahead, look_in, spread, stop, max_windows):
        runahead = jnp.asarray(runahead, jnp.int64)
        look_in = jnp.asarray(look_in, jnp.int64)
        spread = jnp.asarray(spread, jnp.int64)
        stop = jnp.asarray(stop, jnp.int64)
        max_windows = jnp.asarray(max_windows, jnp.int32)

        # min over in-neighbors j of vec[j] + L[j->i], guarded against
        # i64 overflow (NEVER is the i64 max): an unreachable edge, or a
        # neighbor already at stop (it will never emit below stop + L),
        # is unconstraining. Two exchanges, one horizon: the all_gather
        # arm ships every shard's value; the ppermute arm ships only the
        # covered in-edges (one collective-permute per static shift, the
        # neighbor's lookahead read from the traced look_in row at
        # (i - shift) mod S) — identical value, degree-scaled volume.
        if shifts is None:
            def _bound(vec):
                allv = jax.lax.all_gather(vec, axis)  # [S]
                nocon = (look_in >= NEV) | (allv >= stop)
                return jnp.min(jnp.where(nocon, NEV, allv + look_in))
        else:
            def _bound(vec):
                i = jax.lax.axis_index(axis)
                iota = jnp.arange(S, dtype=jnp.int32)
                acc = NEV
                for d, perm in zip(shifts, _perms):
                    recv = jax.lax.ppermute(vec, axis, perm)
                    # the delivering neighbor's in-edge lookahead, read
                    # from the traced row by masked reduce (no gather —
                    # the rank the shard vmap adds would otherwise turn
                    # an index into a per-element fetch the HLO audit
                    # bans): non-selected entries are NEVER, so the min
                    # IS the selected entry
                    j = jnp.mod(i - int(d), S).astype(jnp.int32)
                    w = jnp.min(jnp.where(iota == j, look_in, NEV))
                    nocon = (w >= NEV) | (recv >= stop)
                    acc = jnp.minimum(
                        acc, jnp.where(nocon, NEV, recv + w)
                    )
                return acc

        def _horizon(frontier, state):
            bound = _bound(frontier)
            defer = jax.lax.pmin(state.exch_deferred_min, axis)
            return jnp.minimum(jnp.minimum(bound, defer), stop)

        def cond(c):
            state, frontier, mn, w, _ = c
            live = jax.lax.pmin(frontier, axis) < stop
            return live & (w < max_windows) & (_press(state) == 0)

        def body(c):
            state, frontier, mn, w, stats = c
            spread_max, steps, yields, blocked = stats
            hz = _horizon(frontier, state)
            minF = jax.lax.pmin(frontier, axis)
            maxF = jax.lax.pmax(frontier, axis)
            spread_max = jnp.maximum(spread_max, maxF - minF)
            mn_all = jax.lax.pmin(mn, axis)
            has_work = (mn < hz) & (mn < stop)
            # roughness suppression (cond-mat/0302050): a shard whose
            # frontier — or whose NEXT window — sits more than `spread`
            # above the minimum frontier yields its slot; the minimum-
            # frontier shard can never lag, so progress is unconditional
            cap = minF + spread
            lag = (frontier > cap) | (mn > cap)
            stepped = has_work & ~lag
            ws = jnp.where(stepped, mn, frontier)
            we = jnp.where(
                stepped,
                jnp.minimum(jnp.minimum(ws + runahead, hz), stop),
                ws,
            )
            state, mn2 = step(state, params, ws, jnp.maximum(we, ws))
            # frontier advance — for every non-yielding shard, as far as
            # all three bounds allow: min(local min after the step,
            # horizon, roughness cap). A stepped shard that cleared its
            # pool leaps straight past the window end toward its next
            # event (the null-message advance fused into the same
            # superstep); rank-deferred in-window leftovers hold it at
            # mn2 < we; an idle shard advances to its horizon; a
            # yielding shard holds. Exchange arrivals of THIS superstep
            # land at or after the pre-step horizon, so min(mn2, hz)
            # never overtakes one.
            raw = jnp.where(
                has_work & lag, frontier, jnp.minimum(mn2, hz)
            )
            adv = jnp.minimum(raw, jnp.maximum(frontier, cap))
            clipped = raw > adv  # null-advance suppressed by the cap
            any_step = jax.lax.pmax(stepped.astype(jnp.int32), axis) > 0
            # gap jump, exempt from the cap: it raises the MINIMUM
            # frontier too, so the surface moves up flat
            adv = jnp.where(
                any_step, adv,
                jnp.maximum(adv, jnp.minimum(mn_all, stop)),
            )
            frontier = jnp.maximum(frontier, jnp.minimum(adv, stop))
            one = jnp.int64(1)
            zero = jnp.int64(0)
            stats = (
                spread_max,
                steps + jnp.where(stepped, one, zero),
                yields + jnp.where((has_work & lag) | clipped, one, zero),
                blocked + jnp.where((mn < stop) & (mn >= hz), one, zero),
            )
            return state, frontier, mn2, w + 1, stats

        mn0 = jnp.min(state.pool.time)
        # per-dispatch frontier re-derivation from pool state alone, so
        # the restart is safe after any host-side interruption (spill
        # manage, fault drain, checkpoint resume, gear resize). Two
        # bounds, both required: events still TO BE EMITTED by shard j
        # cannot arrive at i below mn_j + L[j->i]; events ALREADY
        # emitted but in transit (deferred exchange rows) have paid
        # their path latency and land at their pool time — they are
        # bounded only by the gathered exch_deferred_min, exactly as in
        # _horizon. Omitting the deferred clamp would charge an
        # in-transit row its link latency a second time and initialize
        # the destination frontier past the row's landing time — a
        # silent causality violation once the row lands. (_bound treats
        # a neighbor minimum at/above stop as unconstraining; that term
        # could only have exceeded stop anyway, and f0 mins with stop.)
        f0 = jnp.minimum(
            jnp.minimum(
                jnp.minimum(mn0, _bound(mn0)),
                jax.lax.pmin(state.exch_deferred_min, axis),
            ),
            stop,
        )
        z = jnp.int64(0)
        state, frontier, mn, w, stats = jax.lax.while_loop(
            cond, body, (state, f0, mn0, jnp.int32(0), (z, z, z, z))
        )
        spread_max, steps, yields, blocked = stats
        return (
            state, jax.lax.pmin(mn, axis), _press(state) > 0, _occ(state),
            w, frontier, spread_max, steps, yields, blocked,
        )

    return run_to


def make_shard_substep(step, axis: str = AXIS):
    """Build substep(state, params, ws, we) -> (state, min_next, viol)
    for ONE shard of the optimistic islands engine: one window sub-step
    with the frontier and earliest-violation scalars pmin-combined so
    every shard reports the same values."""

    def substep(state, params, ws, we):
        st2, mn2 = step(state, params, ws, we)
        mn2 = jax.lax.pmin(mn2, axis)
        viol = jax.lax.pmin(st2.xmit_min, axis)
        return st2, mn2, viol

    return substep


# ---------------------------------------------------------------------------
# State layout transform: global [H]/[C] arrays → per-shard [S, ...] blocks
# ---------------------------------------------------------------------------


def _split_host_leaf(x, S: int, H: int):
    """[H, ...] → [S, H/S, ...]; scalars → shard-0-holds-value (summed at
    fetch, so counter aggregation stays exact)."""
    x = jnp.asarray(x)
    if x.ndim >= 1 and x.shape[0] == H:
        return x.reshape((S, H // S) + x.shape[1:])
    if x.ndim == 0:
        z = jnp.zeros((S,), x.dtype)
        return z.at[0].set(x)
    raise ValueError(
        f"sub-state leaf with shape {x.shape} is neither [H]-leading nor "
        f"scalar; the islands layout cannot place it"
    )


def islandize_state(state: SimState, S: int, C_shard: int) -> SimState:
    """Rebuild a freshly-built GLOBAL SimState in the [S, ...] islands
    layout: host rows block-partitioned, pool rows routed to their
    destination's shard, counters/scalars summed-at-fetch."""
    H = state.host.gid.shape[0]
    if H % S:
        raise ValueError(f"num_hosts {H} must divide by num_shards {S}")
    Hl = H // S

    # --- pool: route rows home by dst block (np on host; build-time) ---
    pool = jax.device_get(state.pool)
    C = state.pool.capacity
    PPcols = pool.payload.shape[1]
    live = pool.time != simtime.NEVER
    t = np.full((S, C_shard), simtime.NEVER, np.int64)
    d = np.zeros((S, C_shard), np.int32)
    s_ = np.zeros((S, C_shard), np.int32)
    q = np.zeros((S, C_shard), np.int32)
    k = np.zeros((S, C_shard), np.int32)
    p = np.zeros((S, C_shard, PPcols), np.int64)
    for sh in range(S):
        rows = np.where(live & (pool.dst // Hl == sh))[0]
        if len(rows) > C_shard:
            raise ValueError(
                f"shard {sh} initial events ({len(rows)}) exceed per-shard "
                f"pool capacity {C_shard}"
            )
        n = len(rows)
        t[sh, :n] = pool.time[rows]
        d[sh, :n] = pool.dst[rows]
        s_[sh, :n] = pool.src[rows]
        q[sh, :n] = pool.seq[rows]
        k[sh, :n] = pool.kind[rows]
        p[sh, :n] = pool.payload[rows]
    new_pool = EventPool(
        time=jnp.asarray(t), dst=jnp.asarray(d), src=jnp.asarray(s_),
        seq=jnp.asarray(q), kind=jnp.asarray(k), payload=jnp.asarray(p),
    )

    host = jax.tree.map(lambda x: _split_host_leaf(x, S, H), state.host)
    subs = jax.tree.map(lambda x: _split_host_leaf(x, S, H), state.subs)
    counters = jax.tree.map(lambda x: _split_host_leaf(x, S, H),
                            state.counters)
    obs = state.obs
    if obs is not None:
        # telemetry block: host rows block-partition like everything else;
        # the window-plane row is per-shard (the kernel scales shared
        # bumps by axis_index==0, so the fetch-time sum matches the
        # global engine's counts)
        obs = obs.replace(
            win=jnp.zeros((S,) + obs.win.shape, obs.win.dtype)
            .at[0].set(obs.win),
            host_events=obs.host_events.reshape((S, Hl)),
            host_last_t=obs.host_last_t.reshape((S, Hl)),
            host_digest=obs.host_digest.reshape((S, Hl)),
        )
    flight = state.flight
    if flight is not None:
        # flight ring rows are host-indexed: block-partition like every
        # other host leaf ([H, R] -> [S, Hl, R], count [H] -> [S, Hl])
        flight = jax.tree.map(
            lambda x: _split_host_leaf(x, S, H), flight
        )
    bcast = lambda v: jnp.broadcast_to(jnp.asarray(v), (S,))  # noqa: E731
    return state.replace(
        pool=new_pool,
        host=host,
        subs=subs,
        counters=counters,
        obs=obs,
        flight=flight,
        rng_keys=state.rng_keys.reshape((S, Hl) + state.rng_keys.shape[1:]),
        now=bcast(state.now),
        xmit_min=bcast(state.xmit_min),
        exch_deferred_min=bcast(state.exch_deferred_min),
    )


def deislandize_host_array(x, *trailing):
    """[S, H/S, ...] → [H, ...] (for tracker/observability fetch)."""
    x = np.asarray(x)
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def globalize_state(foreign: SimState, pool_capacity: int) -> SimState:
    """Invert the islands layout: a [S, ...] (possibly migrated) SimState
    back to the CANONICAL global layout — host rows in global-id order
    (state.host.gid is the authority; a checkpoint taken after a live
    migration carries permuted rows), live pool rows compacted into a
    [pool_capacity] pool in full-event-key order, per-shard counter rows
    summed, clocks reduced (now = max frontier, xmit_min = min), and the
    exchange-deferral clamp cleared (every re-routed row is home — no row
    is in transit in a single global pool).

    This is the checkpoint→resume re-layout seam (core/checkpoint.
    restore_relayout): a mesh checkpoint resumes on a DIFFERENT mesh size
    (or on the global engine) by globalizing here and re-islandizing for
    the target partition. Pure host-side numpy; determinism is free —
    per-host order, RNG streams and digests key on global host ids, so
    the audit chain is preserved exactly."""
    gid = np.asarray(jax.device_get(foreign.host.gid))
    batched = gid.ndim == 2
    S_old = gid.shape[0] if batched else 1
    H = int(gid.reshape(-1).shape[0])
    flat_gid = gid.reshape(-1)
    inv = np.empty(H, np.int64)
    inv[flat_gid] = np.arange(H, dtype=np.int64)

    def canon(x):
        x = np.asarray(jax.device_get(x))
        flat = x.reshape((H,) + x.shape[2:]) if batched else x
        return jnp.asarray(flat[inv])

    def host_like(x):
        """Host-indexed leaf ([S, Hl, ...] or [H, ...]) → canonical
        [H, ...]; per-shard scalar rows ([S]) → summed scalar."""
        x = np.asarray(jax.device_get(x))
        if batched and x.ndim >= 2 and x.shape[:2] == (S_old, H // S_old):
            return jnp.asarray(x.reshape((H,) + x.shape[2:])[inv])
        if batched and x.shape == (S_old,):
            return jnp.asarray(x.sum())
        return jnp.asarray(x)

    # --- pool: compact live rows in full-event-key order ---
    pt = np.asarray(jax.device_get(foreign.pool.time)).reshape(-1)
    cols = [
        np.asarray(jax.device_get(c)).reshape((-1,) + c.shape[2:] if batched
                                              else c.shape)
        for c in (foreign.pool.dst, foreign.pool.src, foreign.pool.seq,
                  foreign.pool.kind, foreign.pool.payload)
    ]
    live = np.flatnonzero(pt != simtime.NEVER)
    if live.shape[0] > pool_capacity:
        raise ValueError(
            f"{live.shape[0]} live pool rows exceed the target pool "
            f"capacity {pool_capacity}; raise experimental.event_capacity "
            f"on the resuming build"
        )
    order = live[np.lexsort((
        cols[2][live], cols[1][live], cols[0][live], pt[live]
    ))]
    C = int(pool_capacity)
    t = np.full((C,), simtime.NEVER, np.int64)
    n = order.shape[0]
    t[:n] = pt[order]
    out_cols = []
    for c in cols:
        buf = np.zeros((C,) + c.shape[1:], c.dtype)
        buf[:n] = c[order]
        out_cols.append(buf)
    pool = EventPool(
        time=jnp.asarray(t), dst=jnp.asarray(out_cols[0]),
        src=jnp.asarray(out_cols[1]), seq=jnp.asarray(out_cols[2]),
        kind=jnp.asarray(out_cols[3]), payload=jnp.asarray(out_cols[4]),
    )

    obs = foreign.obs
    if obs is not None:
        obs = obs.replace(
            # the window-plane row: per-shard bumps sum to the global
            # engine's counts (islandize's inverse)
            win=jnp.asarray(np.asarray(
                jax.device_get(obs.win)
            ).sum(axis=0) if batched else jax.device_get(obs.win)),
            host_events=canon(obs.host_events),
            host_last_t=canon(obs.host_last_t),
            host_digest=canon(obs.host_digest),
        )
    red = lambda x, f: jnp.asarray(  # noqa: E731
        f(np.asarray(jax.device_get(x))))
    return foreign.replace(
        pool=pool,
        host=jax.tree.map(canon, foreign.host),
        subs=jax.tree.map(host_like, foreign.subs),
        counters=jax.tree.map(host_like, foreign.counters),
        obs=obs,
        flight=(
            jax.tree.map(canon, foreign.flight)
            if foreign.flight is not None else None
        ),
        rng_keys=canon(foreign.rng_keys),
        now=red(foreign.now, np.max),
        xmit_min=red(foreign.xmit_min, np.min),
        exch_deferred_min=jnp.asarray(np.int64(simtime.NEVER)),
    )


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


class IslandSimulation(Simulation):
    """Simulation whose window kernel runs as S islands.

    Accepts every Simulation kwarg plus:
      num_shards      S (must divide num_hosts)
      exchange_slots  X rows per destination shard per window (0 = auto:
                      sized from EXPECTED per-window cross-shard traffic,
                      C/(2·S²) with a floor of 64; misses defer safely,
                      so undersizing costs window clamps, while
                      oversizing re-grows sort volume — see __init__)
      mode            "vmap" (virtual islands, one device) or "shard_map"
                      (one island per mesh device)
      exchange        async frontier-exchange collective: "ppermute"
                      (neighbor-only, one collective-permute per static
                      ring shift covering the in-edge matrix — per-chip
                      volume scales with topology degree) or "all_gather"
                      (every shard's frontier every superstep — the
                      bench comparison arm). Identical horizons, chains
                      bit-identical.
      placement       initial host→chip assignment: "block" (contiguous
                      global-id blocks) or "min_cut" (greedy affinity
                      clustering, parallel/balancer.min_cut_placement —
                      lookahead-critical links land intra-chip; implies
                      the rebalance-capable slot_of kernel)
      force_path      optional engine path pin. Under vmap a lax.cond with
                      a batched predicate executes BOTH branches, so
                      matrix-capable sims (PHOLD) should pin "matrix" —
                      sound whenever the bulk contract is static.
    """

    def __init__(self, *, num_shards: int, exchange_slots: int = 0,
                 mode: str = "vmap", force_path: str | None = None,
                 rebalance: bool = False, pool_gears: int = 1,
                 async_sync: bool = True, async_spread: int = 0,
                 balancer: bool = False, balancer_policy=None,
                 exchange: str = "ppermute", placement: str = "block",
                 exclude_chips: tuple = (), **kw):
        if mode not in ("vmap", "shard_map"):
            raise ValueError(f"unknown islands mode {mode!r}")
        if exchange not in ("ppermute", "all_gather"):
            raise ValueError(f"unknown islands exchange {exchange!r}")
        if placement not in ("block", "min_cut"):
            raise ValueError(f"unknown islands placement {placement!r}")
        self.num_shards = int(num_shards)
        self.mode = mode
        self._exchange = exchange
        self.placement = placement
        self.exclude_chips = tuple(int(c) for c in exclude_chips)
        if placement == "min_cut":
            # the placement permutes host→slot at build time through the
            # same seam a live rebalance uses, so it needs the slot_of
            # routing table compiled in
            rebalance = True
        # the balancer migrates through the slot_of routing seam, so
        # enabling it implies the rebalance-capable kernel
        self.rebalance_enabled = bool(rebalance) or bool(balancer)
        self.rebalances = 0
        # Asynchronous conservative sync (cs/0409032): the fused
        # conservative driver runs per-shard virtual-time frontiers with
        # topology-derived lookahead instead of the lockstep window
        # barrier. experimental.async_islands: false restores the
        # barrier loop (the bench comparison arm).
        self._async = bool(async_sync)
        if int(async_spread) < 0:
            raise ValueError("async_spread must be >= 0 ns (0 = auto)")
        self._async_spread_cfg = int(async_spread)
        H = kw["num_hosts"]
        S = self.num_shards
        if H % S:
            raise ValueError(f"num_hosts {H} must divide by num_shards {S}")
        Hl = H // S
        C = kw.get("event_capacity", 1 << 14)
        if exchange_slots <= 0:
            # Typical-case sizing from EXPECTED cross-shard traffic, not
            # the worst case. Per window a shard commits at most its live
            # rows (≤ C/S, and capacity is user-sized to ~1.5× the live
            # population); uniform destinations put 1/S of emissions on
            # each of the S−1 foreign shards, so expected rows per
            # (src, dst, window) ≈ C/(1.5·S²). Misses defer safely under
            # the exch_deferred_min window-end clamp (late, never lost),
            # so X is a PERF knob — and an oversized X is itself a perf
            # bug: the exchange block occupies S·X pool rows structurally
            # and rides every grouping sort as S·X filler rows, so
            # inflating it re-grows the very sort volume the islands
            # formulation exists to shrink. (Round 4 shipped a worst-case
            # formula, Hl·O/S, that made each shard's pool LARGER than the
            # global pool at the 8-device dryrun shape — VERDICT r4 weak
            # #1. Measured traffic there was ~112 rows/pair/window; this
            # formula gives 192 at that shape.) Tune from a live run with
            # suggest_exchange_slots().
            exchange_slots = max(64, C // (2 * S * S))
        self.exchange_slots = int(exchange_slots)
        # The exchange block occupies S·X pool slots STRUCTURALLY (the
        # received rows land in the pool's tail block each window, mostly
        # fillers), so the per-shard pool is the per-shard share of the
        # configured capacity PLUS that block — otherwise the block eats
        # real event storage and the shard overflows at C/S − S·X.
        C_shard = (C + S - 1) // S + S * self.exchange_slots
        if S > 1 and C_shard >= C:
            raise ValueError(
                f"islands sizing defeats itself: per-shard pool "
                f"{C_shard} (= capacity/{S} + {S}x{self.exchange_slots} "
                f"exchange block) is not smaller than the global pool "
                f"{C}, so per-shard sort volume would exceed the "
                f"single-pool engine's — the S× locality win inverts. "
                f"Lower exchange_slots (misses defer safely) or raise "
                f"event_capacity."
            )
        kw = dict(kw)
        kw["pool_gears"] = 1  # global build first (islandized below); the
        # islands ladder replaces the global one with per-shard capacities
        super().__init__(**kw)

        # Topology-derived async-sync bounds (parallel/lookahead.py):
        # per-shard-pair lookahead matrix + per-shard safe window widths,
        # re-derived (never recompiled — the kernel takes them as traced
        # arguments) whenever the host->shard assignment changes
        # (rebalance_now / resume of a rebalanced layout).
        self._latency_np = np.asarray(
            jax.device_get(self.params.latency_vv))
        self._host_vertex_g = np.asarray(kw["host_vertex"], dtype=np.int64)
        self._lookahead = lookahead_mod.derive(
            self._latency_np, self._host_vertex_g, S
        )
        self._refresh_async_args()
        # the compiled neighbor-exchange schedule: ring ppermute shifts
        # covering every finite in-edge of the partition (a static
        # kernel property — lookahead VALUES stay traced). Re-derived
        # below if a min-cut placement changes shard connectivity;
        # _ensure_shift_coverage widens it (one counted rebuild) if a
        # later rebalance ever introduces an uncovered edge.
        self._async_shifts = lookahead_mod.ppermute_shifts(self._lookahead)
        self._exchange_rebuilds = 0
        self._mesh_collective_bytes = 0
        self._async_counters = {
            "dispatches": 0, "supersteps": 0, "shard_windows": 0,
            "yields": 0, "blocked_on_neighbor": 0,
        }
        self._async_spread_max = 0
        self._async_frontier = None
        # cumulative per-shard [3, S] (steps / yields / blocked) — the
        # critical-path attribution signal (obs/prof.py); reset when an
        # elastic relayout changes S
        self._async_shard_stats = np.zeros((3, S), np.int64)
        self._look_in_cache = None

        spec = IslandSpec(
            axis=AXIS, num_shards=S, exchange_slots=self.exchange_slots,
            use_slot_table=self.rebalance_enabled,
        )
        self._island_spec = spec
        self._force_path = force_path

        # Islands gear ladder (core/gearbox.py): tiers over the GLOBAL
        # capacity, each mapped to its per-shard pool (share + structural
        # exchange block) with exchange-aware red-zone marks. Tiers whose
        # per-shard pool can't hold the exchange block + red zone are
        # skipped; the top tier is exactly the pre-gearbox C_shard.
        SX = S * self.exchange_slots

        def island_marks(C_s: int) -> tuple[int, int]:
            """Per-gear marks: the merge truncates the remainder at
            C_keep = C_shard − S·X (the exchange block structurally
            occupies the pool tail), so pressure must fire below C_keep,
            not raw capacity."""
            keep = C_s - SX
            hi = keep - spill_mod.red_zone(C_s)
            if hi <= 0:
                raise ValueError(
                    "per-shard pool too small for its exchange block + "
                    "red zone; raise event_capacity or lower "
                    "exchange_slots"
                )
            return hi, max(1, (3 * hi) // 4)

        self.pool_gears = int(pool_gears)
        self._gear_ladder = gearbox.build_ladder(
            self.pool_gears, C, self.K, Hl, island_marks,
            capacity_map=lambda c: (c + S - 1) // S + SX,
        )
        # initial gear from the per-shard initial occupancy (max shard)
        pt = np.asarray(jax.device_get(self.state.pool.time))
        pd = np.asarray(jax.device_get(self.state.pool.dst))
        live = pt != simtime.NEVER
        occ0 = int(np.bincount(
            pd[live] // Hl, minlength=S
        ).max()) if live.any() else 0
        self._gear = (
            gearbox.target_level(self._gear_ladder, occ0)
            if len(self._gear_ladder) > 1
            else self._gear_ladder[-1].level
        )
        self._shifter = (
            gearbox.GearShifter(self._gear_ladder)
            if len(self._gear_ladder) > 1
            else None
        )
        # Per-shard gears for the async driver (gearbox.ShardGearShifter):
        # each shard's ladder state advances at its own dispatch
        # boundaries from the per-shard occupancy vector; the compiled
        # tier is the envelope (vmap shares one pool shape). The scalar
        # shifter stays bound for the barrier/stepwise/optimistic paths.
        self._shard_shifter = (
            gearbox.ShardGearShifter(self._gear_ladder, S)
            if self._async and len(self._gear_ladder) > 1
            else None
        )
        if self._shard_shifter is not None:
            self._shard_shifter.seed(self._gear)
        self._gear_shifts = 0
        self._gear_dispatches = {}
        self._C_shard = self._gear_ladder[self._gear].capacity
        # Re-layout the built global state into islands.
        self.state = islandize_state(self.state, S, self._C_shard)
        if self.rebalance_enabled:
            # identity assignment to start; the table is a runtime param,
            # so later rebalances never recompile
            self.params = self.params.replace(
                slot_of=jnp.arange(H, dtype=jnp.int32)
            )

        def build_step(sp: IslandSpec, K: int):
            return make_window_step(
                self.handlers, Hl, K=K, B=self.B, O=self.O,
                bulk_kinds=self._bulk_kinds,
                matrix_handlers=self._matrix_handlers,
                with_cpu_model=self._with_cpu,
                bulk_gate=self._bulk_gate,
                bulk_self_excluded=self._bulk_self_excluded,
                payload_words=self._payload_words,
                island=sp,
                audit=self._audit_digest,
                _force_path=force_path,
            )

        self._step_builder = build_step

        self.mesh = None
        if mode == "vmap":
            # self._jit honors supervisor CPU failover (core/supervisor):
            # kernels re-lower on the CPU backend while the accelerator
            # is gone. `rest_shard` marks which trailing kernel arguments
            # (after state, params) carry per-shard data — the async
            # loop's [S] runahead vector and [S, S] lookahead matrix.
            def _wrap(fn, n=1, rest_shard=(False, False)):
                in_axes = (0, None) + tuple(
                    0 if sh else None for sh in rest_shard
                )
                return self._jit(jax.vmap(
                    fn, in_axes=in_axes, axis_name=AXIS
                ))

            self._wrap = _wrap
        else:  # shard_map: _wrap is defined below with the mesh in scope
            from jax.sharding import PartitionSpec as P

            from shadow_tpu.parallel import mesh as mesh_mod

            # deterministically-ordered device mesh (parallel/mesh.py:
            # one axis, S chips) — the same construction every process
            # of a multi-host run resolves to. `exclude_chips` names
            # dead devices the surviving-mesh rebuild must skip
            # (elastic resilience, parallel/elastic.py).
            mesh = mesh_mod.host_mesh(
                S, axis=AXIS, exclude=tuple(exclude_chips)
            )
            self.mesh = mesh
            # jax >= 0.7 exposes jax.shard_map with the varying-manual-axes
            # checker (check_vma); earlier releases ship the experimental
            # module with the replication checker (check_rep). Both must be
            # disabled for the same reason (see the sm() comment below).
            shard_map = getattr(jax, "shard_map", None)
            if shard_map is not None:
                no_check = {"check_vma": False}
            else:
                from jax.experimental.shard_map import shard_map
                no_check = {"check_rep": False}

            def _sq(tree):
                return jax.tree.map(lambda x: x[0], tree)

            def _unsq(tree):
                return jax.tree.map(lambda x: x[None], tree)

            state_spec = jax.tree.map(
                lambda _: P(AXIS), self.state,
            )
            params_spec = jax.tree.map(lambda _: P(), self.params)

            def sm(fn, n_scalar_out, rest_shard=(False, False)):
                def body(state, params, *rest):
                    vals = [
                        jax.tree.map(lambda x: x[0], r) if sh else r
                        for r, sh in zip(rest, rest_shard)
                    ]
                    out = fn(_sq(state), params, *vals)
                    return (_unsq(out[0]),) + tuple(
                        o[None] for o in out[1:]
                    )

                wrapped = shard_map(
                    body, mesh=mesh,
                    in_specs=(state_spec, params_spec) + tuple(
                        P(AXIS) if sh else P() for sh in rest_shard
                    ),
                    out_specs=(state_spec,) + (P(AXIS),) * n_scalar_out,
                    # the fused while_loops carry pmin-reduced scalars back
                    # into varying state fields (e.g. state.now ← window
                    # start): semantically sound — every shard computes the
                    # identical value from the collective — but the static
                    # varying/replication checker can't see that, so it is
                    # disabled for these wrappers
                    **no_check,
                )
                return self._jit(wrapped)

            self._wrap = sm
        # min-cut host->chip placement (parallel/balancer.py): cluster
        # high-affinity (low-latency) hosts onto one chip at partition
        # time, through the same slot_of permutation seam a live
        # rebalance uses — applied BEFORE the kernels bind so the
        # ppermute schedule compiles against the placed connectivity
        if placement == "min_cut" and S > 1:
            from shadow_tpu.parallel import balancer as balancer_mod

            slot = balancer_mod.min_cut_placement(
                self._latency_np, self._host_vertex_g, S
            )
            if not np.array_equal(
                slot, np.arange(H, dtype=slot.dtype)
            ):
                self.migrate_hosts(slot)
                self.rebalances = 0  # a build-time placement, not a heal
            # the schedule compiles against the PLACED connectivity (the
            # kernels bind below), so re-narrow past the transitional
            # union _ensure_shift_coverage took and zero its counter —
            # nothing was rebuilt, nothing had compiled yet
            self._async_shifts = lookahead_mod.ppermute_shifts(
                self._lookahead
            )
            self._exchange_rebuilds = 0
        # shard_map: pin every [S, ...] state leaf to its chip so the
        # first dispatch starts resident instead of paying a reshard
        self._place_state()
        # drop the GLOBAL-layout kernels super().__init__ bound and rebind
        # the islands kernels for the active gear (one compiled set per
        # gear level, cached in _gear_fns like the global engine's)
        self._gear_fns = {}
        self._bind_gear()
        self.windows_run = 0  # dispatched windows (suggest_exchange_slots)
        # Self-balancing plane (parallel/balancer.py): the closed-loop
        # hot-shard controller, consulted at every fused-dispatch
        # boundary by run(). None = detection-only telemetry (the async
        # posture still rides metrics; nothing acts on it).
        self.balancer = None
        if balancer:
            from shadow_tpu.parallel import balancer as balancer_mod

            self.balancer = balancer_mod.ShardBalancer(balancer_policy)

    def _build_gear_fns(self, spec: gearbox.GearSpec) -> dict:
        if getattr(self, "_step_builder", None) is None:
            # super().__init__ pre-build (global layout, discarded once the
            # islands ladder rebinds below)
            return super()._build_gear_fns(spec)
        step = self._step_builder(self._island_spec, spec.K)
        runahead = jnp.int64(self.runahead)
        lane_run_to = make_shard_run_to(step, spec.hi)

        def step_shard(state, params, ws, we):
            st, mn = step(state, params, ws, we)
            return st, jax.lax.pmin(mn, AXIS)

        def run_to(state, params, stop, max_windows):
            return lane_run_to(state, params, runahead, stop, max_windows)

        fns = {
            "step_fn": step,
            "step": self._wrap(step_shard, 1),
            "run_to": self._wrap(run_to, 4),
            # the optimistic sub-step kernel compiles lazily per gear
            # (_ensure_optimistic): conservative runs never pay for it
            "attempt": None,
        }
        if self._async:
            # the async conservative loop: per-shard [S] runahead and
            # [S, S] in-edge lookahead ride as per-shard traced inputs;
            # the neighbor-only ppermute schedule (when configured) is a
            # static closure over the covering ring shifts
            shifts = (
                self._async_shifts if self._exchange == "ppermute"
                else None
            )
            fns["run_to_async"] = self._wrap(
                make_shard_run_to_async(
                    step, spec.hi, shifts=shifts,
                    num_shards=self.num_shards,
                ), 9,
                rest_shard=(True, True, False, False, False),
            )
        return fns

    def _bind_gear(self) -> None:
        super()._bind_gear()
        fns = self._gear_fns.get(self._gear_ladder[self._gear].level)
        self._run_to_async = (fns or {}).get("run_to_async")

    def _shift_gear(self, level: int) -> None:
        super()._shift_gear(level)
        self._C_shard = self._gear_ladder[level].capacity
        sh = getattr(self, "_shard_shifter", None)
        # a shard-shifter-initiated shift (_gear_tick_async) already has
        # level == max(levels): the per-shard ladder states PRODUCED the
        # new envelope, so keep them — seeding here would hoist every
        # cool shard to the envelope and clear its downshift streak,
        # reverting to exactly the fleet-wide behavior the shard shifter
        # removes. Only shifts that bypassed it (pressure downshifts,
        # scalar-path shifts, checkpoint restore) need the re-alignment.
        if sh is not None and level != max(sh.levels):
            sh.seed(level)
        # the resize re-materialized the pool off-mesh: re-pin per chip
        self._place_state()

    def _pool_occupancy(self) -> int:
        """Gearing decision signal: live rows on the FULLEST shard."""
        return int(jnp.max(
            jnp.sum(self.state.pool.time != simtime.NEVER, axis=-1)
        ))

    # ---- asynchronous conservative sync (cs/0409032) plumbing ----

    def _refresh_async_args(self) -> None:
        """(Re)build the traced async-kernel inputs from the current
        lookahead spec: per-shard window widths, the in-edge lookahead
        view, and the roughness-suppression spread bound (configured, or
        auto-derived — parallel/lookahead.auto_spread)."""
        spec = self._lookahead
        self._async_runahead = jnp.asarray(
            lookahead_mod.shard_runahead(spec, self.runahead)
        )
        self._async_look_in = jnp.asarray(
            lookahead_mod.in_edge_matrix(spec)
        )
        self._async_spread = jnp.int64(
            self._async_spread_cfg
            or lookahead_mod.auto_spread(spec, self.runahead)
        )
        self._look_in_cache = None  # host copy re-derived on next read

    def _note_async_dispatch(self, ainfo, supersteps: int) -> None:
        frontier, spread_max, steps, yields, blocked = ainfo[:5]
        c = self._async_counters
        c["dispatches"] += 1
        c["supersteps"] += supersteps
        c["shard_windows"] += steps
        c["yields"] += yields
        c["blocked_on_neighbor"] += blocked
        self._async_spread_max = max(self._async_spread_max, spread_max)
        self._async_frontier = frontier
        if len(ainfo) > 5 and ainfo[5] is not None:
            delta = ainfo[5]
            if self._async_shard_stats.shape != delta.shape:
                # elastic relayout resized the mesh mid-run
                self._async_shard_stats = np.zeros_like(delta)
            self._async_shard_stats += delta
        # analytic per-chip frontier-exchange volume: every superstep
        # runs one horizon exchange, plus one f0 exchange per dispatch;
        # each ships one i64 per partner (len(shifts) under ppermute,
        # S under the all_gather arm) — the quantity --mesh-smoke gates
        self._mesh_collective_bytes += (
            (supersteps + 1) * self.exchange_partners * 8
        )

    @property
    def exchange_partners(self) -> int:
        """Collective partners per chip per frontier exchange: the
        compiled ppermute schedule's width, or S for the all_gather arm."""
        if self._exchange == "ppermute":
            return len(self._async_shifts)
        return self.num_shards

    def _place_state(self) -> None:
        """shard_map only: pin every [S, ...] state leaf to its chip
        (parallel/mesh.shard_island_state). Called after any host-side
        relayout — build, gear resize, migration, checkpoint restore —
        so dispatches start chip-resident instead of paying an implicit
        reshard; a no-op under vmap."""
        if getattr(self, "mesh", None) is None:
            return
        from shadow_tpu.parallel import mesh as mesh_mod

        self.state = mesh_mod.shard_island_state(self.state, self.mesh)

    def _ensure_shift_coverage(self) -> None:
        """Safety gate after any assignment change: every finite in-edge
        of the re-derived lookahead must ride a compiled ppermute shift —
        an uncovered edge would silently drop that neighbor's frontier
        bound from the horizon (causality, not perf). A value-only
        rebalance (connectivity preserved — the common case, and what
        min-cut refinement produces) changes nothing; a structural
        change widens the schedule and rebuilds the kernel set once
        (counted in mesh.exchange_rebuilds)."""
        if not self._async or self._exchange != "ppermute":
            return
        req = lookahead_mod.ppermute_shifts(self._lookahead)
        if set(req) <= set(self._async_shifts):
            return
        self._async_shifts = tuple(
            sorted(set(self._async_shifts) | set(req))
        )
        if getattr(self, "_gear_fns", None):
            self._gear_fns = {}
            self._bind_gear()
            self._exchange_rebuilds += 1

    def mesh_stats(self) -> dict[str, int] | None:
        """Multi-chip counters for the metrics registry (schema v11
        `mesh.*`); None on single-shard builds."""
        if self.num_shards <= 1 or not self._async:
            return None
        return {
            "frontier_exchange_bytes": int(self._mesh_collective_bytes),
            "exchange_rebuilds": int(self._exchange_rebuilds),
        }

    def mesh_gauges(self) -> dict | None:
        """Multi-chip gauges (schema v11 `mesh.*`): chip count, the
        neighbor-exchange schedule width vs the in-edge degree, per-chip
        committed-event balance, and the placement's cut cost against
        the block partition's."""
        if self.num_shards <= 1:
            return None
        from shadow_tpu.parallel import balancer as balancer_mod

        ev = np.asarray(jax.device_get(
            self.state.counters.events_committed
        )).reshape(-1)
        deg = lookahead_mod.in_degree(self._lookahead)
        slot = (
            np.asarray(jax.device_get(self.params.slot_of))
            if self.rebalance_enabled
            else np.arange(self.num_hosts)
        )
        Hl = self.num_hosts // self.num_shards
        g = {
            "chips": int(self.num_shards),
            "shard_map": int(self.mode == "shard_map"),
            "exchange_partners": int(self.exchange_partners),
            "in_degree_max": int(deg.max()) if deg.size else 0,
            "events_per_chip_min": int(ev.min()),
            "events_per_chip_max": int(ev.max()),
            "events_per_chip_mean": float(ev.mean()),
            "cut_cost": float(balancer_mod.cut_cost(
                np.asarray(slot) // Hl, self._latency_np,
                self._host_vertex_g,
            )),
            "cut_cost_block": float(balancer_mod.cut_cost(
                lookahead_mod.shard_of_hosts(
                    self.num_hosts, self.num_shards
                ),
                self._latency_np, self._host_vertex_g,
            )),
        }
        return g

    def _gear_tick_async(self, occ_v: np.ndarray) -> bool:
        """Per-shard gearing decision from the async kernel's occupancy
        vector; returns True iff the envelope (compiled tier) changed."""
        if self._shard_shifter is None:
            return False
        if self.pressure is not None and self.pressure.hold_gear:
            return False
        hi = self._gear_ladder[self._gear].hi
        new = self._shard_shifter.observe(
            self._gear, occ_v, press=(occ_v >= hi)
        )
        if new is None:
            return False
        self._shift_gear(new)
        return True

    def async_stats(self) -> dict[str, int] | None:
        """Async-sync counters for the metrics registry (schema v9
        `async.*`); None when the barrier driver is configured."""
        if not self._async:
            return None
        return dict(self._async_counters)

    def async_shard_profile(self) -> dict | None:
        """Per-shard async posture for the profiling recorder
        (obs/prof.py): cumulative steps/yields/blocked per shard, the
        last-fetched frontier surface, and the in-edge lookahead matrix
        (host-cached — no device read on the tick path). None when the
        barrier driver is configured."""
        if not self._async:
            return None
        st = self._async_shard_stats
        p = {
            "shards": int(self.num_shards),
            "steps": [int(x) for x in st[0]],
            "yields": [int(x) for x in st[1]],
            "blocked": [int(x) for x in st[2]],
        }
        if self._async_frontier is not None:
            p["frontier_ns"] = [int(x) for x in self._async_frontier]
        la = self._look_in_cache
        if la is None:
            la = self._look_in_cache = [
                [int(x) for x in row]
                for row in np.asarray(jax.device_get(self._async_look_in))
            ]
        p["lookahead_in"] = la
        return p

    def reset_frontier_spread(self) -> None:
        """Zero the max-observed frontier-spread gauge — phase-windowed
        measurement (bench.py --balance-smoke gates on the spread AFTER
        the balancer had its chance to heal, not the whole-run max that
        the pre-migration transient dominates)."""
        self._async_spread_max = 0

    def async_gauges(self) -> dict[str, int] | None:
        """Async-sync gauges: the spread bound, the maximum observed
        frontier spread, the last dispatch's frontier extent, and the
        per-shard gear envelope."""
        if not self._async:
            return None
        spec = self._lookahead
        g = {
            "spread_bound_ns": int(self._async_spread),
            "frontier_spread_max_ns": int(self._async_spread_max),
            "min_cross_lookahead_ns": (
                int(spec.min_cross)
                if spec.min_cross < int(simtime.NEVER) else -1
            ),
        }
        if self._async_frontier is not None:
            g["frontier_min_ns"] = int(self._async_frontier.min())
            g["frontier_max_ns"] = int(self._async_frontier.max())
        if self._shard_shifter is not None:
            g["gear_level_min"] = int(min(self._shard_shifter.levels))
            g["gear_level_max"] = int(max(self._shard_shifter.levels))
        return g

    def _async_meta(self) -> dict | None:
        """Checkpoint-header async block (core/checkpoint.save): the
        derived bounds and last-observed frontier surface, so an operator
        can audit a resumed run's async posture without replaying it.
        Informational — resume re-derives frontiers from pool state."""
        if not self._async:
            return None
        m = {
            "spread_ns": int(self._async_spread),
            "runahead_ns": [int(x) for x in np.asarray(
                jax.device_get(self._async_runahead))],
        }
        spec = self._lookahead
        if spec.min_cross < int(simtime.NEVER):
            m["min_cross_lookahead_ns"] = int(spec.min_cross)
            m["critical_link"] = list(spec.critical)
        if self._async_frontier is not None:
            m["frontier_ns"] = [int(x) for x in self._async_frontier]
        if self._shard_shifter is not None:
            m["gear_levels"] = [int(x) for x in self._shard_shifter.levels]
        return m

    def _runahead_bound_hint(self) -> str:
        """The derived safe bounds, for runahead-violation errors: the
        minimum cross-shard path latency (the async lookahead) and the
        minimum intra-shard latency — the tighter of the two is the
        largest safe experimental.runahead."""
        spec = self._lookahead
        never = int(simtime.NEVER)
        intra = int(spec.intra.min()) if spec.intra.size else never
        parts = []
        if spec.min_cross < never:
            j, i = spec.critical
            parts.append(
                f"derived minimum cross-shard path latency (the safe "
                f"lookahead) is {int(spec.min_cross)} ns on shard link "
                f"{j}->{i}"
            )
        if intra < never:
            parts.append(f"minimum intra-shard path latency is {intra} ns")
        if not parts:
            return "the topology bakes no finite path latency"
        safe = min(int(spec.min_cross), intra)
        parts.append(f"set experimental.runahead <= {safe} ns")
        return "; ".join(parts)

    # ---- self-balancing plane (parallel/balancer.py) ----

    def attach_balancer(self, balancer) -> None:
        """Arm (or replace) the closed-loop hot-shard controller; needs
        the rebalance-capable kernel (slot_of routing)."""
        if not self.rebalance_enabled:
            raise RuntimeError(
                "attach_balancer needs rebalance=True or balancer=True "
                "at build time (the slot_of routing table compiles in)"
            )
        self.balancer = balancer

    def balance_stats(self) -> dict[str, int] | None:
        """Balancer counters for the metrics registry (schema v10
        `balance.*`); None when no controller is attached."""
        if self.balancer is None:
            return None
        d = self.balancer.stats()
        d["rebalances"] = int(self.rebalances)
        return d

    def balance_gauges(self) -> dict | None:
        if self.balancer is None:
            return None
        return self.balancer.gauges()

    def _balance_meta(self) -> dict | None:
        """Checkpoint-header balance block (core/checkpoint.save): the
        LIVE host→slot assignment plus the controller posture, so a
        drain-to-checkpoint persists a migrated layout auditable without
        replay. Restore rebuilds the routing table from the state's own
        gid rows (_post_restore) — the assignment here is the operator-
        facing record, the controller block is what resume re-arms."""
        if not self.rebalance_enabled:
            return None
        slot = np.asarray(jax.device_get(self.params.slot_of))
        m = {
            "rebalances": int(self.rebalances),
            "assignment": [int(x) for x in slot],
        }
        if self.balancer is not None:
            m["controller"] = self.balancer.meta()
        return m

    def _import_foreign_layout(self, foreign, meta) -> None:
        """checkpoint.restore_relayout hook: adopt a checkpoint taken at
        a DIFFERENT partition (another mesh size, or the global engine)
        into this build — globalize by gid to the canonical order, then
        re-islandize for this partition (identity block assignment; the
        _post_restore hook that follows re-derives slot_of/lookahead
        from the restored rows). Chains/RNG key on global host ids, so
        the resumed run extends the checkpointed chain exactly."""
        live = int(np.sum(
            np.asarray(jax.device_get(foreign.pool.time))
            != simtime.NEVER
        ))
        tmp = globalize_state(foreign, max(live, 1))
        self.state = islandize_state(
            tmp, self.num_shards, self._C_shard
        )
        self._place_state()

    def _post_restore(self, meta: dict) -> None:
        """Re-sync layout-derived runtime state after a checkpoint
        restore (core/checkpoint.restore calls this once the leaves are
        in place): the slot_of routing table and the derived async
        lookahead live OUTSIDE the checkpointed state pytree, but the
        restored host rows carry their layout in state.host.gid — a
        checkpoint taken after a live migration restores the permuted
        rows, so the routing table must be rebuilt from them (without
        this hook, resuming a migrated run silently misroutes every
        cross-shard event against a stale identity table)."""
        if self.rebalance_enabled:
            gid = np.asarray(
                jax.device_get(self.state.host.gid)
            ).reshape(-1)
            slot = np.empty(self.num_hosts, np.int32)
            slot[gid] = np.arange(self.num_hosts, dtype=np.int32)
            self.params = self.params.replace(slot_of=jnp.asarray(slot))
            if self._async:
                self._lookahead = lookahead_mod.derive(
                    self._latency_np, self._host_vertex_g,
                    self.num_shards, assignment=slot,
                )
                self._refresh_async_args()
                self._ensure_shift_coverage()
        if self._shard_shifter is not None:
            # restore the per-shard ladder states the checkpoint header
            # recorded (gearbox.ShardGearShifter.restore); a header
            # without them (pre-v11, or barrier run) seeds flat
            levels = (meta.get("async") or {}).get("gear_levels")
            if not self._shard_shifter.restore(levels, self._gear):
                self._shard_shifter.seed(self._gear)
        self._place_state()
        if self.balancer is not None:
            bm = (meta.get("balance") or {}).get("controller")
            if bm:
                self.balancer.restore_meta(bm)

    # ---- between-window re-sharding (the P3 work-stealing replacement,
    # scheduler_policy_host_steal.c:1-562 / logical_processor.rs:43-54) ----

    def shard_loads(self) -> np.ndarray:
        """[S] resident event rows per shard (pool + host spill)."""
        t = np.asarray(jax.device_get(self.state.pool.time))
        occ = (t != simtime.NEVER).sum(axis=-1)
        sp = getattr(self, "_spill", None)
        if sp is not None:
            occ = occ + np.array(
                [r[0].shape[0] for r in sp._rows]
            )
        return occ

    def host_loads(self) -> np.ndarray:
        """[H] resident event rows per GLOBAL host id (pool + spill, by
        destination) — the per-host load proxy both the LPT rebalance and
        the balancer's min-cut refinement consume."""
        H = self.num_hosts
        sp = self._spill_store()
        pt = np.array(jax.device_get(self.state.pool.time)).reshape(-1)
        pd = np.array(jax.device_get(self.state.pool.dst)).reshape(-1)
        live = pt != simtime.NEVER
        load = np.bincount(pd[live], minlength=H).astype(np.int64)
        for rows in sp._rows:
            if rows[0].shape[0]:
                load += np.bincount(rows[1], minlength=H)
        return load

    def rebalance_now(self) -> None:
        """Permute host→shard assignment to even out resident load.

        Load proxy = events resident per destination host (pool + spill
        histogram). Assignment = LPT greedy onto S bins of exactly H/S
        hosts each. All [H]-leading state permutes host-side (rare, a few
        MB); pool and spill rows re-route to their new owners; the
        slot_of routing table updates in place — no recompilation, and no
        observable effect on results (per-host order, RNG streams and seq
        numbering are functions of the GLOBAL host id only).
        """
        S, Hl = self.num_shards, self.num_hosts // self.num_shards
        H = self.num_hosts
        load = self.host_loads()

        # --- LPT: heaviest host to the lightest non-full shard ---
        order = np.argsort(-load, kind="stable")
        shard_load = np.zeros(S, np.int64)
        shard_fill = np.zeros(S, np.int32)
        new_slot = np.zeros(H, np.int32)
        for h in order:
            open_ = shard_fill < Hl
            cand = np.flatnonzero(open_)
            s = int(cand[np.argmin(shard_load[cand])])
            new_slot[h] = s * Hl + shard_fill[s]
            shard_fill[s] += 1
            shard_load[s] += load[h]
        self._apply_assignment(new_slot)

    def migrate_hosts(self, new_slot) -> None:
        """Apply an EXPLICIT host→slot assignment (the balancer's min-cut
        refinement output, parallel/balancer.py): validated — a
        permutation of range(H) with exactly H/S slots per shard — then
        applied through the same recompile-free permutation seam as
        rebalance_now."""
        S, Hl = self.num_shards, self.num_hosts // self.num_shards
        H = self.num_hosts
        new_slot = np.asarray(new_slot, np.int32)
        if new_slot.shape != (H,) or not np.array_equal(
            np.sort(new_slot), np.arange(H, dtype=np.int32)
        ):
            raise ValueError(
                f"migrate_hosts needs a permutation of range({H}) "
                f"(host -> slot); got shape {new_slot.shape}"
            )
        del S, Hl  # permutation of range(H) implies H/S slots per shard
        self._apply_assignment(new_slot)

    def _balance_snapshot(self):
        """Rollback point for a verify-then-commit migration: state and
        params are immutable pytrees (references suffice); the spill
        store and lookahead spec mutate, so they are copied."""
        sp = self._spill_store()
        return {
            "state": self.state,
            "params": self.params,
            "spill_rows": [tuple(r) for r in sp._rows],
            "spill_partial_min": list(sp._partial_min),
            "spill_drained": sp.drained_total,
            "lookahead": self._lookahead,
            "rebalances": self.rebalances,
        }

    def _balance_rollback(self, snap) -> None:
        """Restore the pre-migration layout (mid-migration failure or
        digest divergence — parallel/balancer.py): the pre-move pytrees
        re-bind wholesale, the spill store's rows roll back, and the
        async traced inputs re-derive for the restored assignment."""
        self.state = snap["state"]
        self.params = snap["params"]
        sp = self._spill_store()
        sp._rows = [tuple(r) for r in snap["spill_rows"]]
        sp._partial_min = list(snap["spill_partial_min"])
        sp.drained_total = snap["spill_drained"]
        self._lookahead = snap["lookahead"]
        self.rebalances = snap["rebalances"]
        if self._async:
            self._refresh_async_args()
        if self._shard_shifter is not None:
            self._shard_shifter.seed(self._gear)
        self._place_state()

    def _apply_assignment(self, new_slot: np.ndarray) -> None:
        """The permutation seam shared by rebalance_now (LPT) and
        migrate_hosts (balancer refinement): permute host-indexed state,
        re-route pool + spill rows to their new owner shards, update the
        slot_of routing table, and re-derive the traced async lookahead —
        never a recompile."""
        if not self.rebalance_enabled:
            raise RuntimeError(
                "rebalance_now()/migrate_hosts() need rebalance=True (or "
                "balancer=True) at build time: the window kernel must "
                "compile slot_of-table routing, or the permuted layout "
                "would silently misroute events"
            )
        S, Hl = self.num_shards, self.num_hosts // self.num_shards
        H = self.num_hosts
        sp = self._spill_store()
        new_slot = np.asarray(new_slot, np.int32)

        # --- permute every [S, Hl, ...] host-indexed leaf ---
        gid = np.array(jax.device_get(self.state.host.gid)).reshape(-1)
        cur_slot = np.empty(H, np.int32)
        cur_slot[gid] = np.arange(H, dtype=np.int32)
        # row j of the NEW layout holds the host whose new_slot == j
        host_at_new = np.empty(H, np.int32)
        host_at_new[new_slot] = np.arange(H, dtype=np.int32)
        idx = cur_slot[host_at_new]  # new flat row j ← old flat row idx[j]

        def perm(x):
            x = np.array(jax.device_get(x))
            flat = x.reshape((H,) + x.shape[2:])
            return jnp.asarray(flat[idx].reshape(x.shape))

        self.state = self.state.replace(
            host=jax.tree.map(perm, self.state.host),
            subs=jax.tree.map(
                lambda x: perm(x) if getattr(x, "ndim", 0) >= 2
                and x.shape[0] == S and x.shape[1] == Hl else x,
                self.state.subs,
            ),
            obs=(
                self.state.obs.replace(
                    host_events=perm(self.state.obs.host_events),
                    host_last_t=perm(self.state.obs.host_last_t),
                    host_digest=perm(self.state.obs.host_digest),
                )
                if self.state.obs is not None
                else None
            ),
            flight=(
                jax.tree.map(perm, self.state.flight)
                if self.state.flight is not None
                else None
            ),
            rng_keys=perm(self.state.rng_keys),
        )

        # --- re-route pool + spill rows to their new owner shards ---
        cols = [
            np.array(jax.device_get(c)) for c in (
                self.state.pool.time, self.state.pool.dst,
                self.state.pool.src, self.state.pool.seq,
                self.state.pool.kind, self.state.pool.payload,
            )
        ]
        C_s = cols[0].shape[1]
        flatc = [c.reshape((-1,) + c.shape[2:]) for c in cols]
        livef = flatc[0] != simtime.NEVER
        allrows = [c[livef] for c in flatc]
        for rows in sp._rows:
            if rows[0].shape[0]:
                allrows = [
                    np.concatenate([a, r]) for a, r in zip(allrows, rows)
                ]
        owner = new_slot[allrows[1]] // Hl
        t_new = np.full((S, C_s), simtime.NEVER, np.int64)
        o_new = [np.zeros((S, C_s) + c.shape[1:], c.dtype)
                 for c in allrows[1:]]
        sp._rows = [sp._empty() for _ in range(S)]
        # the partial-residency clamps describe the OLD layout; reset so a
        # stale minimum cannot clamp future windows (manage recomputes per
        # rebalance)
        sp._partial_min = [int(simtime.NEVER)] * S
        for s in range(S):
            rows = np.where(owner == s)[0]
            # earliest rows stay on device; overflow goes to the spill
            # tier (never dropped)
            osort = rows[HostSpill._order(
                allrows[0][rows], allrows[1][rows],
                allrows[2][rows], allrows[3][rows],
            )]
            fill = self._spill_marks()[1]
            keep, rest = osort[:fill], osort[fill:]
            n = keep.shape[0]
            t_new[s, :n] = allrows[0][keep]
            for c_new, c in zip(o_new, allrows[1:]):
                c_new[s, :n] = c[keep]
            if rest.shape[0]:
                sp._rows[s] = tuple(
                    c[rest] for c in allrows
                )
                sp.drained_total += rest.shape[0]
        from shadow_tpu.core.state import EventPool

        self.state = self.state.replace(pool=EventPool(
            time=jnp.asarray(t_new), dst=jnp.asarray(o_new[0]),
            src=jnp.asarray(o_new[1]), seq=jnp.asarray(o_new[2]),
            kind=jnp.asarray(o_new[3]), payload=jnp.asarray(o_new[4]),
        ))
        self.params = self.params.replace(
            slot_of=jnp.asarray(new_slot)
        )
        self.rebalances += 1
        if self._async:
            # the permuted host->shard assignment changes which latencies
            # bound each shard pair; re-derive (traced inputs — the
            # compiled async kernel is untouched)
            self._lookahead = lookahead_mod.derive(
                self._latency_np, self._host_vertex_g, self.num_shards,
                assignment=new_slot,
            )
            self._refresh_async_args()
            self._ensure_shift_coverage()
        if self._shard_shifter is not None:
            # per-shard occupancies just shuffled wholesale: the per-shard
            # ladder states describe the OLD layout — re-align to the
            # bound envelope (a bypass shift, like checkpoint restore)
            self._shard_shifter.seed(self._gear)
        self._place_state()

    def _maybe_rebalance(self) -> None:
        """Skew trigger: rebalance when the heaviest shard holds 2x the
        mean resident load (and enough rows for the skew to matter)."""
        if not self.rebalance_enabled:
            return
        occ = self.shard_loads()
        mean = occ.mean()
        if mean > 0 and occ.max() > max(2 * mean, occ.min() + 256):
            self.rebalance_now()

    def _run_to_halves(self, stop_at, wpd):
        """(issue_fn, fetch_fn) halves of one fused islands dispatch —
        the async per-shard-frontier loop or the barrier loop. issue
        enqueues the device program (futures only); fetch performs every
        blocking host read, once, in one place (the old thunk fetched
        the window count twice). Supervised retries re-run both halves,
        re-reading bound kernels and re-clamping the spill stop."""

        def issue(stop_at=stop_at, wpd=wpd):
            # per-attempt clamp: a pressure rung may have engaged the
            # spill tier since the driver computed stop_at
            stop_at, wpd = self._live_spill_clamp(stop_at, wpd)
            if self._async:
                return self._run_to_async(
                    self.state, self.params,
                    self._async_runahead, self._async_look_in,
                    self._async_spread, stop_at, wpd,
                )
            return self._run_to(self.state, self.params, stop_at, wpd)

        def fetch(out):
            if self._async:
                st, mn, press, occ, w, fr, sp, stp, yld, blk = out
                stp_v = np.asarray(jax.device_get(stp)).reshape(-1)
                yld_v = np.asarray(jax.device_get(yld)).reshape(-1)
                blk_v = np.asarray(jax.device_get(blk)).reshape(-1)
                extra = (
                    np.asarray(jax.device_get(fr)).reshape(-1),
                    int(np.max(np.asarray(jax.device_get(sp)))),
                    int(stp_v.sum()),
                    int(yld_v.sum()),
                    int(blk_v.sum()),
                    # per-shard [3, S] deltas for the profiling plane
                    np.stack([stp_v, yld_v, blk_v]).astype(np.int64),
                )
            else:
                st, mn, press, occ, w = out
                extra = None
            return (
                st,
                int(np.min(np.asarray(jax.device_get(mn)))),
                bool(np.max(np.asarray(jax.device_get(press)))),
                np.asarray(jax.device_get(occ)).reshape(-1),
                int(np.max(np.asarray(jax.device_get(w)))),
                extra,
            )

        return issue, fetch

    def run(self, until=None, windows_per_dispatch: int = 64) -> None:
        from shadow_tpu.core import spill as spill_mod
        from shadow_tpu.obs import metrics as metrics_mod

        stop = self.stop_time if until is None else min(until, self.stop_time)
        spill = self._spill_store()
        obs = self.obs_session
        pipe = self._pipeline()
        last = None
        try:
            while True:
                if (
                    (last is not None and last[2]) or spill.count
                    or self._force_spill  # injected force_spill fault
                ):
                    if pipe is not None:
                        # rebalance + spill manage mutate the layout /
                        # pool: a barrier point (already tallied as a
                        # forced drain when speculation was skipped)
                        pipe.close()
                    with metrics_mod.span(obs, "spill"):
                        self._maybe_rebalance()
                        stop_at = spill_mod.manage(self, spill, stop)
                else:
                    stop_at = stop
                # single-window dispatches while the spill is active
                # (exactness requires a manage pass between windows —
                # core/spill.py)
                wpd = 1 if spill.count else windows_per_dispatch
                if self._fault_plane_active():
                    # hand off at the next injection/checkpoint mark
                    stop_at = min(stop_at, self._fault_mark())
                # adopt the issued-ahead dispatch iff the committed state
                # and recomputed args match (core/pipeline.py)
                pending = (
                    pipe.take(self.state, (stop_at, wpd))
                    if pipe is not None else None
                )
                if pending is None:
                    with metrics_mod.span(obs, "dispatch", windows=wpd):
                        p = self._sv_issue(
                            "run_to", *self._run_to_halves(stop_at, wpd)
                        )
                        (self.state, mn, press, occ_v, w,
                         ainfo) = self._sv_await(p)
                else:
                    with metrics_mod.span(obs, "await", windows=wpd):
                        (self.state, mn, press, occ_v, w,
                         ainfo) = self._sv_await(pending)
                occ = int(occ_v.max())
                # two-slot pipeline: issue the next fused dispatch before
                # draining this handoff — the mesh computes its next
                # supersteps while the host drains; balancer migrations,
                # fault drains and gear shifts stay barrier points (the
                # invalidate below discards on any state mutation)
                if pipe is not None and mn < stop:
                    if (not press and not spill.count
                            and not self._force_spill
                            and self._handoff_quiet(mn)
                            and not self._sv_disrupted()):
                        nxt = stop
                        if self._fault_plane_active():
                            nxt = min(nxt, self._fault_mark())
                        with metrics_mod.span(
                            obs, "issue", windows=windows_per_dispatch
                        ):
                            pipe.put(
                                self._sv_issue(
                                    "run_to",
                                    *self._run_to_halves(
                                        nxt, windows_per_dispatch
                                    ),
                                ),
                                self.state,
                                (nxt, windows_per_dispatch),
                            )
                    else:
                        pipe.forced_drain()
                with metrics_mod.span(obs, "host_drain"):
                    self._gear_note_dispatch()
                    self.windows_run += w
                    if ainfo is not None:
                        self._note_async_dispatch(ainfo, w)
                    if obs is not None:
                        obs.round_done(self, mn)
                    self._audit_tick(mn)
                    # gearing: a red-zone early exit upshifts (one pool
                    # re-sort) before the spill tier would pay host drain
                    # round-trips; under async the decision is PER SHARD
                    # from the occupancy vector (gearbox.ShardGearShifter),
                    # each shard's ladder state advancing at its own
                    # dispatch boundary
                    if self._async and self._shard_shifter is not None:
                        shifted = self._gear_tick_async(occ_v)
                    else:
                        shifted = self._gear_tick(occ, press=press)
                    if self._fault_plane_active():
                        self._handoff_tick(mn)
                    if self.balancer is not None:
                        # closed-loop hot-shard healing (parallel/
                        # balancer.py): detection from the dispatch's own
                        # occupancy vector + frontier surface; a committed
                        # migration permutes the layout through the
                        # traced-lookahead seam (no recompile)
                        if self.balancer.observe(
                            self, occ_v,
                            ainfo[0] if ainfo is not None else None,
                        ):
                            shifted = True
                    self._run_handoff_hooks(mn)
                if pipe is not None:
                    if self._sv_disrupted():
                        pipe.discard()
                    else:
                        pipe.invalidate(self.state)
                if mn >= stop and spill.min_time >= stop and not press:
                    break
                if self.elastic is not None:
                    # elastic re-expansion probe (parallel/elastic.py):
                    # may raise MeshReexpand at this committed boundary —
                    # the runner drains and relayouts onto the recovered
                    # mesh
                    self.elastic.on_dispatch(self, mn)
                fr_min = int(ainfo[0].min()) if ainfo is not None else None
                cur = (mn, spill.count, press, fr_min)
                if cur == last and mn >= stop_at and not shifted:
                    cap = self._gear_ladder[self._gear].capacity
                    if self._pressure_stall(window=mn, occupancy=occ,
                                            capacity=cap):
                        last = None  # a ladder rung reshaped the tier
                        continue
                    raise self._pool_exhausted(
                        "spill tier cannot make progress (single over-full "
                        "timestamp or no pool headroom for one window's "
                        "emissions); raise experimental.event_capacity",
                        window=mn, occupancy=occ, capacity=cap,
                    )
                elif self.pressure is not None:
                    self.pressure.note_progress()
                last = cur
        finally:
            if pipe is not None:
                pipe.close()

    def run_stepwise(self, until=None) -> int:
        from shadow_tpu.core import spill as spill_mod
        from shadow_tpu.obs import metrics as metrics_mod

        stop = self.stop_time if until is None else min(until, self.stop_time)
        spill = self._spill_store()
        obs = self.obs_session
        windows = 0
        stall = 0
        # committed frontier carried from the dispatch's own return value
        # (a fresh per-iteration jnp.min dispatched one tiny reduce kernel
        # per window for nothing); None = derive from the pool
        min_next = None
        while True:
            if self._shifter is not None:
                # gear decision BEFORE spill manage: an upshift absorbs
                # red-zone pressure without a host drain episode
                self._gear_tick(self._pool_occupancy())
            with metrics_mod.span(obs, "spill"):
                tok = self.state
                stop_at = spill_mod.manage(self, spill, stop)
            if self.state is not tok or min_next is None:
                min_next = int(jax.device_get(jnp.min(self.state.pool.time)))
            if self._fault_plane_active():
                tok = self.state
                self._handoff_tick(min_next)
                if self.state is not tok:
                    # a drain may have removed the frontier event
                    min_next = int(
                        jax.device_get(jnp.min(self.state.pool.time))
                    )
            if min_next >= stop_at:
                if min_next >= stop and spill.min_time >= stop:
                    break
                stall += 1
                if stall > 2:
                    occ = self._pool_occupancy()
                    cap = self._gear_ladder[self._gear].capacity
                    if self._pressure_stall(window=min_next, occupancy=occ,
                                            capacity=cap):
                        stall = 0  # a ladder rung reshaped the tier
                        continue
                    raise self._pool_exhausted(
                        "spill tier cannot make progress (single over-full "
                        "timestamp or no pool headroom for one window's "
                        "emissions); raise experimental.event_capacity",
                        window=min_next, occupancy=occ, capacity=cap,
                    )
                continue
            stall = 0
            if self.pressure is not None:
                self.pressure.note_progress()
            ws = min_next
            clamp = int(jax.device_get(
                jnp.min(self.state.exch_deferred_min)
            ))
            we = min(ws + self.runahead, stop_at, clamp)
            with metrics_mod.span(obs, "dispatch", windows=1):

                def _dispatch(ws=ws, we=we):
                    we, _ = self._live_spill_clamp(we, 1)
                    st, mn = self._step(
                        self.state, self.params, ws, max(ws, we)
                    )
                    return st, int(np.min(np.asarray(jax.device_get(mn))))

                self.state, mn = self._sv("step", _dispatch)
            self._gear_note_dispatch()
            min_next = mn
            if self._audit_active():
                self._audit_tick(mn)
            # host-drain contract parity with the fused driver: handoff
            # hooks (sharded ones drain through the multi-worker host
            # plane, core/hostplane.py) run at every stepwise boundary
            self._run_handoff_hooks(mn)
            windows += 1
            self.windows_run += 1
        return windows

    def suggest_exchange_slots(self) -> dict[str, int | float]:
        """Runtime-informed X sizing (VERDICT r4 #2): from the observed
        exchange traffic of THIS run, compute the X a rebuild should use.

        avg rows per (src, dst, window) = exchange_sent / (windows·S·(S−1));
        the suggestion is 2× that (headroom for wave clustering) with the
        auto-sizing floor of 64. Changing X changes compiled shapes, so
        apply it by rebuilding — the intended loop is: short calibration
        run, read the suggestion, rebuild for the long run.
        """
        S = self.num_shards
        c = self.counters()
        sent, deferred = c["exchange_sent"], c["exchange_deferred"]
        w = max(self.windows_run, 1)
        avg = sent / (w * S * max(S - 1, 1))
        return {
            "exchange_slots": self.exchange_slots,
            "suggested": max(64, int(2 * avg) + 1),
            "avg_rows_per_pair_per_window": round(avg, 2),
            "windows": self.windows_run,
            "exchange_sent": sent,
            "exchange_deferred": deferred,
            "defer_ratio": round(deferred / max(sent + deferred, 1), 4),
        }

    def _ensure_optimistic(self):
        """Lazily compile the speculative SUB-STEP kernel (a second XLA
        program): the conservative kernel stays untouched, so conservative
        runs never pay for the done_t checks.

        The attempt loop is HOST-DRIVEN (one dispatch per sub-step, like
        run_stepwise) rather than a fused on-device while_loop: compiling
        vmap(S) of while_loop(full netstack step) measured >90 min on a
        CPU host at S=8 — the fused program buys one dispatch per attempt
        but costs a pathological compile. The sub-step kernel is the same
        size as the conservative step (known-fast compile), semantics are
        identical (each sub-step processes [max(mn, ws), we) and reports
        the pmin'd frontier + earliest violation), and the host loop gets
        stall detection for free."""
        if self._attempt is not None:
            return
        spec = self._gear_ladder[self._gear]
        spec_opt = self._island_spec._replace(optimistic=True)
        step_opt = self._step_builder(spec_opt, spec.K)
        # one pmin each inside (make_shard_substep): the shards agree on
        # the frontier + earliest violation, so every shard reports the
        # same scalars
        substep = make_shard_substep(step_opt)

        # cache per gear: a shift rebinds _attempt to the new gear's entry
        # (None until this runs again for that gear)
        self._attempt = self._gear_fns[spec.level]["attempt"] = self._wrap(
            substep, 2
        )

    def run_optimistic(
        self,
        until: int | None = None,
        window_factor: int = 8,
        adaptive: bool = True,
    ) -> tuple[int, int]:
        """Optimistic synchronization ON the islands runner (VERDICT r4
        #4; reference window machinery: controller.c:390-422).

        Same Time-Warp shape as the global engine's run_optimistic —
        speculate [ws, ws + factor·runahead), sub-step to completion,
        roll the WHOLE window back on violation (pure arrays: rollback =
        dropping the speculated pytree on every shard) — with the two
        cross-shard pieces the global engine doesn't need:

          * violation detection: LOCAL-dst emissions check against the
            shard's own done_t at the merge; FOREIGN emissions are
            checked at ARRIVAL on the destination shard, right after the
            all_to_all they already ride (engine.assemble arrival_min) —
            so detection needs no extra collective, and the per-shard
            xmit_min signals combine with ONE pmin per sub-step;
          * the safe retreat width: a conservative-runahead window is
            only violation-free up to the exchange-backpressure clamp
            (an in-transit deferred row at T must not be overtaken), so
            the shrink floor is min(ws + runahead, exch_deferred_min);
            when that floor collapses to ws, one NULL conservative
            window retries the exchange (delivering the earliest
            deferred row — X >= 1 guarantees it) and speculation
            resumes.

        Returns (windows_committed, rollbacks); results match the
        conservative schedule bit-for-bit (tests/test_optimistic.py
        islands gates, vmap and shard_map).
        """
        self._ensure_optimistic()
        spill = self._spill_store()
        if spill.count:
            raise RuntimeError(
                "optimistic islands cannot start with an active spill "
                "tier (speculation has no manage() barrier); drain first "
                "or raise experimental.event_capacity"
            )
        stop = self.stop_time if until is None else min(until, self.stop_time)
        cons = self.runahead
        windows = rollbacks = 0
        factor = window_factor
        streak = 0
        S = self.num_shards
        Hl = self.num_hosts // S
        neg1 = jnp.full((S, Hl), -1, dtype=jnp.int64)
        self.state = self.state.replace(
            host=self.state.host.replace(done_t=neg1)
        )
        from shadow_tpu.obs import counters as obs_mod
        from shadow_tpu.obs import metrics as metrics_mod

        obs = self.obs_session
        min_next = int(jax.device_get(jnp.min(self.state.pool.time)))
        while min_next < stop:
            if self._shifter is not None:
                # margin=2: a speculative window absorbs several windows'
                # inflow between decision points (core/gearbox.target_level);
                # a shift rebinds _attempt to None, so re-ensure per gear
                self._gear_tick(self._pool_occupancy(), margin=2)
                self._ensure_optimistic()
            ws = min_next
            clamp = int(jax.device_get(
                jnp.min(self.state.exch_deferred_min)
            ))
            floor = min(ws + cons, clamp)
            if floor <= ws:
                # in-transit deferred row parked AT the frontier: null
                # conservative window to retry the exchange
                with metrics_mod.span(obs, "dispatch", null_window=1):

                    def _null(ws=ws):
                        st, mn = self._step(
                            self.state, self.params, ws, ws
                        )
                        return st, int(
                            np.min(np.asarray(jax.device_get(mn)))
                        )

                    self.state, min_next = self._sv("step", _null)
                self.state = obs_mod.bump_win(
                    self.state, obs_mod.WIN_OPT_STALLS
                )
                self.windows_run += 1  # one exchange round dispatched
                continue
            # never past stop (the conservative schedule's end), even when
            # the floor itself sits beyond it (then the [ws, stop) window
            # is narrower than the safe width — trivially violation-free)
            we = min(max(min(ws + factor * cons, stop), floor), stop)
            base = self.state  # rollback snapshot (done_t already reset)
            rb0 = rollbacks
            shrinks = 0
            never = int(simtime.NEVER)
            # reshaping pressure rungs are unsafe while `base` pins the
            # compiled shapes (core/pressure.py)
            self._pressure_reshape_ok = False
            while True:  # attempt [ws, we); shrink on violation
                # host-driven sub-step loop (see _ensure_optimistic): one
                # dispatch per sub-step until the window completes or a
                # shard reports a violation
                st = base
                mn_i, viol, k = ws, never, 0
                while mn_i < we and viol >= never:
                    if k >= _MAX_SUBSTEPS:
                        if mn_i <= ws:
                            # mid-attempt: no reshaping rung is safe
                            # (the snapshot pins the compiled shapes) —
                            # typed exhaustion, never a bare RuntimeError
                            raise self._pool_exhausted(
                                "optimistic attempt cannot make progress "
                                "(pool-headroom stall: the window commits "
                                "nothing and its frontier is frozen); "
                                "raise experimental.event_capacity",
                                window=ws,
                                occupancy=self._pool_occupancy(),
                                capacity=self._gear_ladder[
                                    self._gear].capacity,
                            )
                        # genuinely enormous window: shrink to the
                        # reached frontier, retry from the snapshot
                        break
                    with metrics_mod.span(obs, "dispatch"):

                        def _dispatch(st=st, lo=max(mn_i, ws), we=we):
                            s2, mn, vl = self._attempt(
                                st, self.params, lo, we
                            )
                            return (
                                s2,
                                int(np.min(np.asarray(
                                    jax.device_get(mn)
                                ))),
                                int(np.min(np.asarray(
                                    jax.device_get(vl)
                                ))),
                            )

                        st, mn_i, viol = self._sv("attempt", _dispatch)
                        self._gear_note_dispatch()
                    k += 1
                if viol >= never and mn_i < we and k >= _MAX_SUBSTEPS:
                    we = mn_i
                    shrinks += 1
                    continue
                if viol < never and we <= floor:
                    # A floor-width window is violation-free BY CONSTRUCTION
                    # (floor = min(ws + runahead, exchange clamp): emissions
                    # land at or after ws + runahead, and no shard overtakes
                    # an in-transit deferred row). A violation here means
                    # the conservative-width invariant itself is broken —
                    # committing would silently accept a causally-violated
                    # window (ADVICE round-5 finding).
                    raise RuntimeError(
                        f"speculation violation at t={viol} inside a "
                        f"floor-width window [{ws}, {we}) (floor {floor}): "
                        f"the conservative-width invariant is broken — "
                        f"runahead {cons} ns exceeds a real path latency "
                        f"({self._runahead_bound_hint()}), or a handler "
                        f"emitted into the past; refusing to commit"
                    )
                if viol >= never or we <= floor:
                    break
                rollbacks += 1
                shrinks += 1
                if obs is not None and obs.tracer:
                    obs.tracer.instant("rollback", viol_ns=viol)
                we = min(max(viol, floor), stop)
            self._pressure_reshape_ok = True
            if self.pressure is not None:
                self.pressure.note_progress()
            # exchange rounds of the ACCEPTED attempt only: rolled-back
            # sub-steps' exchange counters are discarded with the rollback,
            # and suggest_exchange_slots normalizes sent/windows_run
            self.windows_run += k
            st = obs_mod.bump_win(st, obs_mod.WIN_ROLLBACKS, rollbacks - rb0)
            st = obs_mod.bump_win(st, obs_mod.WIN_SHRINKS, shrinks)
            self.state = st.replace(host=st.host.replace(done_t=neg1))
            min_next = mn_i
            windows += 1
            if obs is not None:
                obs.round_done(self, min_next)
            self._audit_tick(min_next)
            if self._fault_plane_active():
                self._handoff_tick(min_next)
                min_next = int(jax.device_get(jnp.min(self.state.pool.time)))
            # host-drain contract parity with the conservative driver:
            # handoff hooks (sharded ones fan out across the host plane's
            # pinned workers with the canonical (vt, gid) merge) run at
            # every optimistic commit boundary
            self._run_handoff_hooks(min_next)
            if adaptive:
                factor, streak = self.adapt_window_factor(
                    factor, streak, rollbacks > rb0, window_factor
                )
        return windows, rollbacks

    def counters(self) -> dict[str, int]:
        c = jax.device_get(self.state.counters)
        return {
            f.name: int(np.sum(np.asarray(getattr(c, f.name))))
            for f in dataclasses.fields(c)
        }

    def host_trackers(self) -> dict[str, np.ndarray]:
        sub = self.state.subs.get("nic")
        if sub is None:
            return {}
        return {
            k: deislandize_host_array(jax.device_get(getattr(sub, k)))
            for k in ("tx_packets", "tx_bytes", "rx_packets", "rx_bytes")
        }
