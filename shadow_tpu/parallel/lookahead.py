"""Topology-derived cross-shard lookahead for asynchronous conservative sync.

The async conservative protocol (cs/0409032, PAPERS.md) lets shard i
advance whenever its local virtual time is below every in-neighbor's
frontier plus the LINK LOOKAHEAD of that edge:

    horizon_i = min over shards j != i of  frontier[j] + L[j -> i]

where L[j -> i] is the minimum simulated latency any event emitted by a
host of shard j can take to reach a host of shard i. On this engine every
cross-host delivery is one emission delayed by the baked PATH latency
(net/link.py: deliver at now + latency_vv[src_vertex, dst_vertex]), so the
exact per-edge lookahead is a pure function of the topology bake and the
host -> shard assignment:

    L[j -> i] = min over (a in hosts_j, b in hosts_i) latency_vv[v(a), v(b)]

This module derives that [S, S] matrix (host-side numpy at partition
time — it never rides a kernel; the drivers pass it as a TRACED argument
so a rebalance or a fleet lane swap never recompiles). The diagonal is
the INTRA-shard minimum, which doubles as the shard's safe local window
width (the per-shard runahead): emissions between hosts of one shard land
at or after window end whenever the window is no wider than it.

An unreachable pair (latency NEVER) imposes no constraint: the protocol's
constraint graph is the direct-communication graph, and transitive
influence is already carried hop-by-hop by the frontier rule.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from shadow_tpu.core import simtime

NEVER = int(simtime.NEVER)

# Per-shard window widths are clamped below the packed sort key's
# window-relative time field (core/engine._DT_BITS = 44 bits): a derived
# intra-shard lookahead of NEVER (single-host shard on a cross-only
# graph) must never widen a window past what the extraction keys can
# order exactly. Half the field keeps every in-window dt comfortably
# inside the 2^44 ns span.
WIDTH_CAP = (1 << 43) - 1


@dataclasses.dataclass(frozen=True)
class LookaheadSpec:
    """The derived async-sync bounds for one shard partition.

    matrix[j, i]  min path latency from any host of shard j to any host
                  of shard i (i64 ns; NEVER = no direct path). The
                  diagonal holds the intra-shard minimum (including
                  self-sends via latency_vv[v, v]).
    intra[i]      matrix[i, i] — the shard's safe local window width.
    min_cross     minimum finite off-diagonal entry (NEVER if the shards
                  never talk): the critical link that bounds async slack
                  fleet-wide.
    critical      (src_shard, dst_shard) of min_cross, or (-1, -1).
    """

    matrix: np.ndarray
    intra: np.ndarray
    min_cross: int
    critical: tuple[int, int]

    @property
    def num_shards(self) -> int:
        return int(self.matrix.shape[0])


def shard_of_hosts(num_hosts: int, num_shards: int,
                   assignment: np.ndarray | None = None) -> np.ndarray:
    """[H] shard index per GLOBAL host id. Contiguous block partition by
    default; `assignment` is the rebalancer's host -> slot table
    (parallel/islands.rebalance_now), under which shard = slot // (H/S)."""
    Hl = num_hosts // num_shards
    if assignment is None:
        return np.arange(num_hosts, dtype=np.int64) // Hl
    return np.asarray(assignment, dtype=np.int64) // Hl


def derive(latency_vv: np.ndarray, host_vertex: np.ndarray, num_shards: int,
           assignment: np.ndarray | None = None) -> LookaheadSpec:
    """Derive the per-shard-pair lookahead matrix at partition time.

    latency_vv   [U, U] baked path latencies (NEVER = unreachable)
    host_vertex  [H] host -> used-vertex index
    assignment   optional host -> slot table (post-rebalance layouts)
    """
    lat = np.asarray(latency_vv, dtype=np.int64)
    hv = np.asarray(host_vertex, dtype=np.int64)
    H = hv.shape[0]
    S = int(num_shards)
    if S <= 0 or H % S:
        raise ValueError(
            f"num_hosts {H} must divide by num_shards {S}"
        )
    shard = shard_of_hosts(H, S, assignment)
    # vertex sets per shard (U is small; hosts collapse onto vertices)
    verts = [np.unique(hv[shard == s]) for s in range(S)]
    m = np.full((S, S), NEVER, dtype=np.int64)
    for j in range(S):
        for i in range(S):
            sub = lat[np.ix_(verts[j], verts[i])]
            if sub.size:
                m[j, i] = int(sub.min())
    finite_cross = [
        (int(m[j, i]), j, i)
        for j in range(S) for i in range(S)
        if j != i and m[j, i] < NEVER
    ]
    if finite_cross:
        mc, cj, ci = min(finite_cross)
        critical = (cj, ci)
    else:
        mc, critical = NEVER, (-1, -1)
    return LookaheadSpec(
        matrix=m, intra=np.diagonal(m).copy(), min_cross=mc,
        critical=critical,
    )


def shard_runahead(spec: LookaheadSpec, base_runahead: int) -> np.ndarray:
    """[S] safe per-shard window widths: never narrower than the
    configured global runahead (sub-minimum explicit runaheads are a perf
    choice, not a safety bound), widened to the shard's intra-shard
    minimum latency where that is provably exact, and capped below the
    packed sort key's window span (WIDTH_CAP)."""
    w = np.maximum(spec.intra, int(base_runahead))
    return np.clip(w, 1, WIDTH_CAP).astype(np.int64)


def in_edge_matrix(spec: LookaheadSpec) -> np.ndarray:
    """[S(dst-major), S(src)] lookahead view the async kernel consumes:
    row i holds shard i's IN-edge lookaheads L[j -> i] with the diagonal
    masked to NEVER (a shard's own frontier never bounds its horizon —
    local safety is the per-shard window width)."""
    m = spec.matrix.T.copy()
    np.fill_diagonal(m, NEVER)
    return m


def ppermute_shifts(spec: LookaheadSpec) -> tuple[int, ...]:
    """The static ring-shift schedule covering every finite in-edge of
    the partition: shard i needs frontier[j] whenever L[j -> i] is
    finite, and a ``jax.lax.ppermute`` by shift d delivers exactly the
    edges (j, j + d mod S) — so the schedule is the sorted set of
    distinct shifts {(i - j) mod S} over finite off-diagonal entries.

    This is the neighbor-only frontier exchange the mesh driver runs:
    per superstep each chip sends/receives len(shifts) scalars instead
    of the all_gather's S, so cross-chip collective volume scales with
    the TOPOLOGY's shard-level degree, not the mesh size (a bidirected
    ring is 2 shifts at any S). The schedule is a COMPILED property of
    the kernel; the per-edge lookahead VALUES stay traced, so a
    rebalance that preserves shard-level connectivity (shifts_covered)
    never recompiles."""
    S = spec.num_shards
    m = spec.matrix
    shifts = {
        (i - j) % S
        for j in range(S)
        for i in range(S)
        if j != i and m[j, i] < NEVER
    }
    return tuple(sorted(shifts))


def shifts_covered(spec: LookaheadSpec,
                   shifts: tuple[int, ...]) -> bool:
    """True iff every finite in-edge of `spec` rides one of the compiled
    `shifts` — the safety condition a re-derived (post-rebalance)
    lookahead must meet before the compiled ppermute kernel may keep
    running: an uncovered edge would silently drop a neighbor's frontier
    bound from the horizon (a causality hazard, not a perf bug)."""
    return set(ppermute_shifts(spec)) <= set(int(s) % spec.num_shards
                                             for s in shifts)


def in_degree(spec: LookaheadSpec) -> np.ndarray:
    """[S] finite in-edge count per destination shard (diagonal
    excluded) — the per-chip collective-partner count the mesh
    telemetry reports (`mesh.exchange_partners_max`)."""
    m = spec.matrix
    off = m < NEVER
    np.fill_diagonal(off, False)
    return off.sum(axis=0).astype(np.int64)


def auto_spread(spec: LookaheadSpec, base_runahead: int) -> int:
    """Default roughness-suppression bound (cond-mat/0302050): wide
    enough that lookahead-limited asynchrony is never throttled (8x the
    largest finite lookahead, off-diagonal or intra), tight enough that
    frontier spread — and with it the exchange/pool buffering for
    run-ahead rows — stays bounded. Falls back to 64x the global
    runahead on cross-silent partitions."""
    finite = spec.matrix[spec.matrix < NEVER]
    if finite.size:
        return int(min(8 * int(finite.max()), WIDTH_CAP))
    return int(min(64 * int(base_runahead), WIDTH_CAP))
