"""CPU-side managed-process plane: run REAL Linux binaries inside the
simulation via the native LD_PRELOAD shim (native/shim) and a shared-memory
syscall channel.

Reference parity: src/main/host/process.c / thread_preload.c /
syscall_handler.c / lib/shim — re-architected per SURVEY.md §7.5: the
interposition plane stays on CPU; the network hot path the syscalls feed is
the device-stepped engine.
"""

from shadow_tpu.procs.driver import ManagedProcess, ProcessDriver  # noqa: F401
