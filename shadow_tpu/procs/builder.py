"""Config → ProcessDriver: the managed-process plane wired to the topology.

Plays the reference's controller host-registration sequence
(src/main/core/controller.c:227-336: for each configured host, register with
DNS, attach to a topology vertex, then add its processes) for simulations
whose hosts run real binaries. Path latency/reliability lookups come from the
baked topology matrices — the same arrays the device engine uses — so both
planes see one network model (topology.c:1995,2007 analogs).
"""

from __future__ import annotations

import pathlib

from shadow_tpu.core.config import Config, load_config
from shadow_tpu.procs.driver import ProcessDriver
from shadow_tpu.routing.dns import Dns
from shadow_tpu.routing.topology import Topology


class ProcessBuildError(ValueError):
    pass


def build_process_driver(
    source, data_root: str | pathlib.Path | None = None
) -> ProcessDriver:
    """Build a ProcessDriver from a Config (or YAML path/string/dict).

    If ``data_root`` is given, per-host working directories are created under
    ``<data_root>/hosts/<hostname>/`` and process stdout/stderr are written to
    ``<exe>.<n>.stdout`` / ``.stderr`` files there, mirroring the reference's
    shadow.data layout (manager.c:352-432, process.c:468-481).
    """
    cfg = source if isinstance(source, Config) else load_config(source)
    if not cfg.hosts:
        raise ProcessBuildError("no hosts with processes configured")
    bad = [h.name for h in cfg.hosts if not h.processes]
    if bad:
        raise ProcessBuildError(f"hosts without processes: {bad}")
    hosts = cfg.hosts

    topo = Topology.from_gml(cfg.graph_gml(), cfg.network.use_shortest_path)
    dns = Dns()
    for i, h in enumerate(hosts):
        topo.attach_host(
            i,
            ip_address_hint=h.ip_address_hint,
            city_code_hint=h.city_code_hint,
            country_code_hint=h.country_code_hint,
            network_node_id=h.network_node_id,
        )
    # Path model: dense baked matrices below the threshold, lazy per-source
    # Dijkstra + row cache above it (no dense [U, U] allocation — the
    # reference's strategy for Tor-scale maps, topology.c:1144-1259). The
    # device-network bridge needs the dense arrays on device either way.
    n_used = len(set(topo._attached_vertex))
    lazy = cfg.experimental.lazy_paths
    if lazy is None:
        lazy = (
            n_used > cfg.experimental.lazy_paths_threshold
            and not cfg.experimental.use_device_network
        )
    if lazy and cfg.experimental.use_device_network:
        raise ProcessBuildError(
            "experimental.lazy_paths is incompatible with "
            "use_device_network (device lookups need baked arrays)"
        )
    baked = topo.bake_lazy() if lazy else topo.bake()

    driver = ProcessDriver(
        stop_time=cfg.general.stop_time,
        seed=cfg.general.seed,
        host_workers=cfg.experimental.host_workers,
    )
    driver.dns = dns
    driver.bootstrap_end = cfg.general.bootstrap_end_time
    driver.use_seccomp = cfg.experimental.use_seccomp
    driver.socket_send_buffer = cfg.experimental.socket_send_buffer
    driver.use_perf_timers = cfg.experimental.use_perf_timers
    driver.log_stamp = cfg.experimental.use_shim_log_stamps
    driver.cpu_ns_per_syscall = cfg.experimental.cpu_ns_per_syscall
    driver.cpu_threshold_ns = cfg.experimental.max_unapplied_cpu_latency
    # fault-tolerance plane (shadow_tpu/faults): recovery policy + armed
    # injections; corrupt_file globs resolve against the data directory
    driver.on_proc_failure = cfg.faults.on_proc_failure
    driver.ipc_timeout_retries = cfg.faults.ipc_timeout_retries
    faults = cfg.faults.load_faults()
    if faults:
        from shadow_tpu.faults import FaultInjector

        driver.fault_injector = FaultInjector(faults)
    if data_root is not None:
        driver.fault_dir = str(data_root)

    # Register hinted hosts first so a sequential allocation for an
    # unhinted host can never claim another host's requested address
    # (the sequential allocator starts at 11.0.0.1 — exactly the range
    # users pick hints from).
    for i, h in enumerate(hosts):
        if h.ip_address_hint is not None:
            dns.register(i, h.name, h.ip_address_hint)

    ip_to_vertex: dict[int, int] = {}
    for i, h in enumerate(hosts):
        ip = (
            dns.resolve_name(h.name)
            if h.ip_address_hint is not None
            else dns.register(i, h.name)
        )
        sim_host = driver.add_host(h.name, ip)
        ip_to_vertex[ip] = int(baked.host_vertex[i])

        host_dir = None
        if data_root is not None:
            host_dir = pathlib.Path(data_root) / "hosts" / h.name
            host_dir.mkdir(parents=True, exist_ok=True)
        if h.pcap_directory is not None:
            # relative paths land under the host's data dir, like the
            # reference (configuration.rs:412-415)
            p = pathlib.Path(h.pcap_directory)
            if not p.is_absolute():
                p = (host_dir or pathlib.Path(".")) / p
            sim_host.pcap_dir = str(p)

        n = 0
        for popt in h.processes:
            for _ in range(max(1, popt.quantity)):
                out_path = err_path = None
                if host_dir is not None:
                    stem = f"{pathlib.Path(popt.path).name}.{n}"
                    out_path = str(host_dir / f"{stem}.stdout")
                    err_path = str(host_dir / f"{stem}.stderr")
                driver.add_process(
                    sim_host,
                    [popt.path, *popt.args],
                    start_time=popt.start_time,
                    stop_time=popt.stop_time,
                    env=dict(popt.environment),
                    cwd=str(host_dir) if host_dir is not None else None,
                    stdout_path=out_path,
                    stderr_path=err_path,
                )
                n += 1

    if lazy:
        lat_at = baked.latency_ns
        rel_at = baked.reliability
    else:
        lat_vv = baked.latency_vv
        rel_vv = baked.reliability_vv
        lat_at = lambda sv, dv: int(lat_vv[sv, dv])  # noqa: E731
        rel_at = lambda sv, dv: float(rel_vv[sv, dv])  # noqa: E731

    # Unknown destination IPs (apps sending to addresses that are not sim
    # hosts) fall back to defaults; the packet then vanishes at delivery
    # time like any datagram with no listener.
    def latency_fn(src_ip: int, dst_ip: int) -> int:
        sv = ip_to_vertex.get(src_ip)
        dv = ip_to_vertex.get(dst_ip)
        if sv is None or dv is None:
            return driver.latency_ns
        return lat_at(sv, dv)

    def reliability_fn(src_ip: int, dst_ip: int) -> float:
        sv = ip_to_vertex.get(src_ip)
        dv = ip_to_vertex.get(dst_ip)
        if sv is None or dv is None:
            return 1.0
        return rel_at(sv, dv)

    driver.set_latency_fn(latency_fn)
    driver.set_reliability_fn(reliability_fn)

    if cfg.experimental.use_device_network:
        # the CPU↔TPU seam: UDP rides the device-stepped network
        import numpy as np

        from shadow_tpu.procs.bridge import DeviceNetBridge

        H = len(hosts)
        bw_up = np.zeros(H, dtype=np.int64)
        bw_down = np.zeros(H, dtype=np.int64)
        for i, h in enumerate(hosts):
            v = baked.host_vertex[i]
            bw_up[i] = h.bandwidth_up or baked.vertex_bw_up_bits[v] or 10**9
            bw_down[i] = (
                h.bandwidth_down or baked.vertex_bw_down_bits[v] or 10**9
            )
        driver.bridge = DeviceNetBridge(
            baked=baked,
            bw_up_bits=bw_up,
            bw_down_bits=bw_down,
            host_vertex=baked.host_vertex,
            seed=cfg.general.seed,
            stop_time=cfg.general.stop_time,
            bootstrap_end=cfg.general.bootstrap_end_time,
            sockets_per_host=cfg.experimental.sockets_per_host,
            event_capacity=cfg.experimental.event_capacity,
            K=cfg.experimental.events_per_host_per_window,
            router_queue_slots=cfg.experimental.router_queue_slots,
            router_variant=cfg.experimental.router_queue_variant,
            with_tcp=cfg.experimental.use_device_tcp,
        )

    driver.config = cfg
    driver.topology = topo
    driver.baked = baked
    return driver
