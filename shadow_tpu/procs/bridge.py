"""The CPU↔TPU seam: managed-process traffic through the device network.

This is the BASELINE north star (SURVEY.md header): keep syscall-emulated
host processes on the CPU, but lift the network hot path — NIC token
buckets, CoDel router queues, port demux, latency/loss path model, and the
full TCP state machine — onto the device engine, with the Router/Topology
boundary as the handoff.

Protocol (conservative, deadlock-free):

- Managed sendto()/send() calls append injection records host-side; payload
  BYTES stay in host-side buffers — the device moves 12-word packet headers
  and sequence space only (UDP rides a claim ticket in W_HANDLE; TCP bytes
  are matched to device-reported in-order advances, which is sound because
  TCP delivers in order by construction).
- When every process is parked, the driver syncs: pending injections enter
  the device event pool as KIND_PROC_SYSCALL events at their send times,
  and the device steps conservative windows until the first batch of
  outputs lands (or its pool drains past the driver's next local event).
  Output rows (UDP deliveries, TCP establishment/receive/EOF notifications)
  drain from per-host rings and become ordinary driver wakeups at their
  device-computed times.
- Injections that land behind the device's completed window are processed
  one window late with their true timestamps — the engine's documented
  deferral semantics; their effects still land at t + latency ≥ the
  next window, so causality holds (window length ≤ min path latency).

Port binds/unbinds and TCP listens from syscalls update the device socket
tables host-side between dispatches (bind is rare; the hot path stays
compiled). TCP slot space is partitioned: the CPU plane allocates
active-open slots in [0, child_base); the device allocates accept-side
children in [child_base, S) (tcp.py child_base), so a pending connect
injection can never collide with a device-side accept.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime, soa
from shadow_tpu.core.engine import Simulation, _set_col
from shadow_tpu.core.state import KIND_PROC_SYSCALL, NetParams
from shadow_tpu.net import packet as pkt, tcp as tcp_mod, udp
from shadow_tpu.net.stack import NetStack
from shadow_tpu.net.tcp import _g

NEVER = simtime.NEVER

BRIDGE_SUB = "bridge"

# Injection opcodes riding in W_PROTO of KIND_PROC_SYSCALL payloads.
# OP_UDP doubles as the wire protocol number so the payload row IS the
# datagram; TCP ops are control rows interpreted by the inject handler.
OP_UDP = pkt.PROTO_UDP
OP_TCP_CONNECT = 1
OP_TCP_SEND = 2
OP_TCP_CLOSE = 3


@dataclass
class Delivery:
    """A UDP datagram reached a bound device socket."""

    time: int
    dst_host: int
    src_host: int
    src_port: int
    dst_port: int
    length: int
    handle: int


@dataclass
class TcpEstablished:
    """A device TCP connection reached ESTABLISHED on `host`."""

    time: int
    host: int
    slot: int
    peer_host: int
    peer_port: int
    local_port: int
    is_accept: bool


@dataclass
class TcpBytes:
    """`nbytes` new in-order stream bytes arrived at (host, slot)."""

    time: int
    host: int
    slot: int
    nbytes: int


@dataclass
class TcpFin:
    """Peer FIN consumed at (host, slot): EOF after all data.

    ``time_wait`` means the consume moved the socket into TIME_WAIT — both
    FINs are exchanged and acked, so the CPU plane can recycle its slot
    mirror immediately instead of waiting out the 60 s device timer."""

    time: int
    host: int
    slot: int
    time_wait: bool = False


@dataclass
class TcpClosed:
    """The device freed (host, slot): orderly close completed (reset=False)
    or the connection was torn down by RST / refused (reset=True)."""

    time: int
    host: int
    slot: int
    reset: bool


# drain ordering at equal timestamps: establishment before data before
# EOF before teardown
_EVENT_RANK = {
    TcpEstablished: 0, Delivery: 1, TcpBytes: 1, TcpFin: 2, TcpClosed: 3,
}


class DeviceNetBridge:
    """Owns the device Simulation that carries managed-process traffic."""

    def __init__(
        self,
        *,
        baked,
        bw_up_bits,
        bw_down_bits,
        host_vertex,
        seed: int,
        stop_time: int,
        bootstrap_end: int = 0,
        sockets_per_host: int = 16,
        event_capacity: int = 4096,
        K: int = 16,
        ring_slots: int | None = None,
        with_tcp: bool = False,
        router_queue_slots: int = 64,
        router_variant: str = "codel",
    ):
        H = len(host_vertex)
        if ring_slots is None:
            # a window can deliver up to K datagrams per host
            ring_slots = max(32, 2 * K)
        self.H = H
        self.S = sockets_per_host
        self.R = ring_slots
        self.with_tcp = with_tcp
        self.child_base = sockets_per_host // 2 if with_tcp else 0
        stack = NetStack(
            H,
            jnp.asarray(bw_up_bits),
            jnp.asarray(bw_down_bits),
            sockets_per_host=sockets_per_host,
            router_queue_slots=router_queue_slots,
            router_variant=router_variant,
            with_tcp=with_tcp,
            tcp_child_base=self.child_base,
        )
        self.stack = stack
        stack.on_receive(self._on_recv)
        if with_tcp:
            stack.tcp.on_established(self._on_tcp_established)
            stack.tcp.on_receive(self._on_tcp_bytes)
            stack.tcp.on_peer_fin(self._on_tcp_fin)
            stack.tcp.on_reset(self._on_tcp_reset)
            stack.tcp.on_closed(self._on_tcp_closed)
        handlers = dict(stack.handlers())
        handlers[KIND_PROC_SYSCALL] = self._on_inject
        subs = stack.init_subs()
        R = ring_slots
        br = {
            # UDP delivery ring
            "time": jnp.full((H, R), NEVER, jnp.int64),
            "src_host": jnp.zeros((H, R), jnp.int32),
            "src_port": jnp.zeros((H, R), jnp.int32),
            "dst_port": jnp.zeros((H, R), jnp.int32),
            "length": jnp.zeros((H, R), jnp.int32),
            "handle": jnp.zeros((H, R), jnp.int32),
            "count": jnp.zeros((H,), jnp.int32),
            "overflow": jnp.zeros((), jnp.int64),
        }
        if with_tcp:
            br.update({
                # establishment ring
                "e_time": jnp.full((H, R), NEVER, jnp.int64),
                "e_slot": jnp.zeros((H, R), jnp.int32),
                "e_peer_host": jnp.zeros((H, R), jnp.int32),
                "e_peer_port": jnp.zeros((H, R), jnp.int32),
                "e_local_port": jnp.zeros((H, R), jnp.int32),
                "e_accept": jnp.zeros((H, R), bool),
                "e_count": jnp.zeros((H,), jnp.int32),
                # in-order byte-advance ring
                "r_time": jnp.full((H, R), NEVER, jnp.int64),
                "r_slot": jnp.zeros((H, R), jnp.int32),
                "r_bytes": jnp.zeros((H, R), jnp.int32),
                "r_count": jnp.zeros((H,), jnp.int32),
                # peer-FIN (EOF) ring
                "f_time": jnp.full((H, R), NEVER, jnp.int64),
                "f_slot": jnp.zeros((H, R), jnp.int32),
                "f_tw": jnp.zeros((H, R), bool),
                "f_count": jnp.zeros((H,), jnp.int32),
                # teardown ring (orderly close completion or RST)
                "c_time": jnp.full((H, R), NEVER, jnp.int64),
                "c_slot": jnp.zeros((H, R), jnp.int32),
                "c_reset": jnp.zeros((H, R), bool),
                "c_count": jnp.zeros((H,), jnp.int32),
            })
        subs[BRIDGE_SUB] = br
        params = NetParams(
            latency_vv=jnp.asarray(baked.latency_vv),
            reliability_vv=jnp.asarray(baked.reliability_vv),
            bootstrap_end=jnp.int64(bootstrap_end),
        )
        self.sim = Simulation(
            num_hosts=H,
            handlers=handlers,
            params=params,
            host_vertex=np.asarray(host_vertex),
            seed=seed,
            stop_time=stop_time,
            runahead=baked.min_latency_ns,
            event_capacity=event_capacity,
            K=K,
            subs=subs,
        )
        self._pending: list[tuple[int, int, np.ndarray]] = []  # (t, src, row)
        self._drained = False  # device pool empty since the last injection
        self._ring_prefixes = [""] + (
            ["e_", "r_", "f_", "c_"] if with_tcp else []
        )
        # Fused sync loop: ONE device dispatch advances many windows, exiting
        # early as soon as any output ring holds a row. Replaces the
        # window-per-dispatch round trips that dominated managed-plane wall
        # time over the accelerator tunnel (docs/bench_notes.md round 2).
        self._sync_max_windows = 32
        self._run_sync = jax.jit(self._make_run_sync())
        self._handles: dict[int, bytes] = {}
        self._next_handle = 1
        self._port_slot: dict[tuple[int, int], int] = {}
        self._inflight = 0  # injected minus delivered UDP datagrams (drops
        # reconciled when the device drains — see sync())
        self._overflow_seen = 0
        # TCP host-side slot mirror: free active-open slots per host
        self._tcp_free: list[list[int]] = [
            list(range(self.child_base - 1, -1, -1)) for _ in range(H)
        ]
        # (host, slot) pairs the CPU believes are live on device (listeners,
        # active opens, accepted children); while non-empty, sync() must let
        # the device advance (timers/retransmits may be pending)
        self._tcp_live: set[tuple[int, int]] = set()

    def _make_run_sync(self):
        """Build the fused device sync loop: step conservative windows until
        (a) any output ring holds a row, (b) the pool drains past `horizon`,
        or (c) max_windows elapse (bounds dispatch length for the
        accelerator watchdog). Returns (state, min_next, out_rows)."""
        step = self.sim._step_fn
        runahead = jnp.int64(self.sim.runahead)
        prefixes = list(self._ring_prefixes)

        def out_count(state):
            br = state.subs[BRIDGE_SUB]
            tot = jnp.zeros((), jnp.int32)
            for p in prefixes:
                tot = tot + jnp.sum(br[f"{p}count"], dtype=jnp.int32)
            return tot

        def run_sync(state, params, horizon, max_windows):
            horizon = jnp.asarray(horizon, jnp.int64)
            max_windows = jnp.asarray(max_windows, jnp.int32)

            def cond(c):
                state, mn, w = c
                return (
                    (out_count(state) == 0) & (mn < horizon)
                    & (w < max_windows)
                )

            def body(c):
                state, mn, w = c
                we = jnp.minimum(mn + runahead, horizon)
                state, mn2 = step(state, params, mn, we)
                return state, mn2, w + 1

            mn0 = jnp.min(state.pool.time)
            state, mn, _ = jax.lax.while_loop(
                cond, body, (state, mn0, jnp.int32(0))
            )
            return state, mn, out_count(state)

        return run_sync

    # ------------------------------------------------------------------
    # device-side handlers
    # ------------------------------------------------------------------

    def _on_inject(self, state, ev, emitter, params):
        """A managed syscall enters the device network. The opcode rides in
        W_PROTO: a UDP row is the datagram itself (dst host in W_SEQ); TCP
        control rows drive the device TCP machine."""
        op = ev.payload[:, pkt.W_PROTO]
        m_udp = ev.mask & (op == OP_UDP)
        dst = ev.payload[:, pkt.W_SEQ]
        payload = ev.payload.at[:, pkt.W_SEQ].set(0)
        state = self.stack.udp_sendto(
            state, emitter, m_udp, ev.time, dst,
            dst_port=0, src_port=0, size_bytes=0,
            socket_slot=ev.payload[:, pkt.W_SOCKET],
            payload=payload, params=params,
        )
        if self.with_tcp:
            tcp = self.stack.tcp
            slot = ev.payload[:, pkt.W_SOCKET]
            m_conn = ev.mask & (op == OP_TCP_CONNECT)
            state = tcp.connect(
                state, emitter, m_conn, slot,
                dst_host=ev.payload[:, pkt.W_SEQ],
                dst_port=ev.payload[:, pkt.W_DST_PORT],
                local_port=ev.payload[:, pkt.W_SRC_PORT],
                now=ev.time, params=params,
            )
            m_send = ev.mask & (op == OP_TCP_SEND)
            state = tcp.send_app(
                state, emitter, m_send, slot, ev.payload[:, pkt.W_LEN],
                ev.time,
            )
            m_close = ev.mask & (op == OP_TCP_CLOSE)
            state = tcp.close_app(state, emitter, m_close, slot, ev.time)
        return state

    def _ring_append(self, state, prefix: str, mask, cols: dict):
        """Append one row per masked host to the `prefix` ring; overflow is
        counted (and warned about at drain time)."""
        br = state.subs[BRIDGE_SUB]
        cnt = br[f"{prefix}count"]
        fits = mask & (cnt < self.R)
        col = jnp.clip(cnt, 0, self.R - 1)
        new = dict(br)
        for name, val in cols.items():
            key = f"{prefix}{name}"
            new[key] = _set_col(br[key], col, fits, val)
        new[f"{prefix}count"] = cnt + fits.astype(jnp.int32)
        new["overflow"] = br["overflow"] + jnp.sum(mask & ~fits,
                                                  dtype=jnp.int64)
        return state.with_sub(BRIDGE_SUB, new)

    def _on_recv(self, state, found, slot, src, payload, emitter, now, params):
        """A datagram reached a bound UDP socket: record it in the delivered
        ring for the CPU plane to drain."""
        nowv = jnp.broadcast_to(now, found.shape).astype(jnp.int64)
        return self._ring_append(state, "", found, {
            "time": nowv,
            "src_host": src.astype(jnp.int32),
            "src_port": payload[:, pkt.W_SRC_PORT],
            "dst_port": payload[:, pkt.W_DST_PORT],
            "length": payload[:, pkt.W_LEN],
            "handle": payload[:, pkt.W_HANDLE],
        })

    def _on_tcp_established(self, state, mask, slot, is_accept, src, now,
                            emitter, params):
        t = state.subs[tcp_mod.SUB]
        nowv = jnp.broadcast_to(now, mask.shape).astype(jnp.int64)
        return self._ring_append(state, "e_", mask, {
            "time": nowv,
            "slot": slot.astype(jnp.int32),
            "peer_host": _g(t.peer_host, slot),
            "peer_port": _g(t.peer_port, slot),
            "local_port": _g(t.local_port, slot),
            "accept": is_accept,
        })

    def _on_tcp_bytes(self, state, mask, slot, nbytes, src, now, emitter,
                      params):
        nowv = jnp.broadcast_to(now, mask.shape).astype(jnp.int64)
        return self._ring_append(state, "r_", mask & (nbytes > 0), {
            "time": nowv,
            "slot": slot.astype(jnp.int32),
            "bytes": nbytes.astype(jnp.int32),
        })

    def _on_tcp_fin(self, state, mask, slot, now, emitter, params):
        t = state.subs[tcp_mod.SUB]
        nowv = jnp.broadcast_to(now, mask.shape).astype(jnp.int64)
        return self._ring_append(state, "f_", mask, {
            "time": nowv,
            "slot": slot.astype(jnp.int32),
            # hooks run after the consume transition, so this reads the
            # post-FIN state
            "tw": _g(t.state, slot) == tcp_mod.TIME_WAIT,
        })

    def _on_tcp_reset(self, state, mask, slot, now, emitter, params):
        nowv = jnp.broadcast_to(now, mask.shape).astype(jnp.int64)
        return self._ring_append(state, "c_", mask, {
            "time": nowv,
            "slot": slot.astype(jnp.int32),
            "reset": jnp.ones(mask.shape, bool),
        })

    def _on_tcp_closed(self, state, mask, slot, now, emitter, params):
        nowv = jnp.broadcast_to(now, mask.shape).astype(jnp.int64)
        return self._ring_append(state, "c_", mask, {
            "time": nowv,
            "slot": slot.astype(jnp.int32),
            "reset": jnp.zeros(mask.shape, bool),
        })

    # ------------------------------------------------------------------
    # host-side API (called by ProcessDriver)
    # ------------------------------------------------------------------

    def bind(self, host: int, port: int) -> bool:
        """Bind (host, port) in the device UDP socket table (host-side array
        update; runs between device dispatches)."""
        if (host, port) in self._port_slot:
            return True
        used = np.asarray(jax.device_get(self.sim.state.subs[udp.SUB].used[host]))
        free = np.where(~used)[0]
        if len(free) == 0:
            return False
        slot = int(free[0])
        self._port_slot[(host, port)] = slot
        self.sim.state = self.sim.state.with_sub(
            udp.SUB,
            udp.bind_static(self.sim.state.subs[udp.SUB], host, slot, port),
        )
        return True

    def unbind(self, host: int, port: int) -> None:
        slot = self._port_slot.pop((host, port), None)
        if slot is None:
            return
        u = self.sim.state.subs[udp.SUB]
        self.sim.state = self.sim.state.with_sub(
            udp.SUB, u.replace(used=u.used.at[host, slot].set(False))
        )

    def send(self, t: int, src_host: int, dst_host: int, src_port: int,
             dst_port: int, data: bytes) -> None:
        handle = self._next_handle
        self._next_handle += 1
        self._handles[handle] = data
        self._inflight += 1
        row = np.zeros(pkt.PAYLOAD_WORDS, np.int32)
        row[pkt.W_PROTO] = OP_UDP
        row[pkt.W_SRC_PORT] = src_port
        row[pkt.W_DST_PORT] = dst_port
        row[pkt.W_LEN] = len(data)
        row[pkt.W_SRC_HOST] = src_host
        row[pkt.W_SOCKET] = self._port_slot.get((src_host, src_port), 0)
        row[pkt.W_SEQ] = dst_host  # dst host rides in the seq word
        row[pkt.W_HANDLE] = handle
        self._pending.append((t, src_host, row))

    def take_payload(self, handle: int) -> bytes:
        return self._handles.pop(handle, b"")

    # ---- TCP control plane ----

    def tcp_alloc_slot(self, host: int) -> int | None:
        """Reserve an active-open/listener slot in the CPU-owned region."""
        if not self._tcp_free[host]:
            return None
        return self._tcp_free[host].pop()

    def tcp_free_slot(self, host: int, slot: int) -> None:
        if slot not in self._tcp_free[host]:  # idempotent
            self._tcp_free[host].append(slot)

    def tcp_release(self, host: int, slot: int) -> None:
        """A connection finished with (host, slot): drop it from the live
        set and, if CPU-owned, return it to the mirror free list. Safe to
        call more than once per occupancy."""
        self._tcp_live.discard((host, slot))
        if slot < self.child_base:
            self.tcp_free_slot(host, slot)

    def tcp_listen(self, host: int, port: int) -> int | None:
        """Install a device-side listener (host-side array update between
        dispatches, like UDP bind). Returns the slot or None if full."""
        slot = self.tcp_alloc_slot(host)
        if slot is None:
            return None
        # listeners are deliberately NOT in _tcp_live: a bare listener
        # cannot produce device output without a connect injection first,
        # so it must not defeat sync()'s idle early-out
        self.sim.state = self.sim.state.with_sub(
            tcp_mod.SUB,
            tcp_mod.listen_static(
                self.sim.state.subs[tcp_mod.SUB], host, slot, port
            ),
        )
        return slot

    def tcp_unlisten(self, host: int, slot: int) -> None:
        t = self.sim.state.subs[tcp_mod.SUB]
        self.sim.state = self.sim.state.with_sub(
            tcp_mod.SUB,
            t.replace(
                used=t.used.at[host, slot].set(False),
                state=t.state.at[host, slot].set(tcp_mod.CLOSED),
            ),
        )
        self.tcp_free_slot(host, slot)

    def _tcp_ctl(self, t: int, host: int, op: int, slot: int,
                 words: dict | None = None) -> None:
        row = np.zeros(pkt.PAYLOAD_WORDS, np.int32)
        row[pkt.W_PROTO] = op
        row[pkt.W_SOCKET] = slot
        for w, v in (words or {}).items():
            row[w] = v
        self._pending.append((t, host, row))

    def tcp_connect(self, t: int, src_host: int, slot: int, dst_host: int,
                    dst_port: int, local_port: int) -> None:
        self._tcp_live.add((src_host, slot))
        self._tcp_ctl(
            t, src_host, OP_TCP_CONNECT, slot,
            {pkt.W_SEQ: dst_host, pkt.W_DST_PORT: dst_port,
             pkt.W_SRC_PORT: local_port},
        )

    def tcp_send(self, t: int, host: int, slot: int, nbytes: int) -> None:
        self._tcp_ctl(t, host, OP_TCP_SEND, slot, {pkt.W_LEN: nbytes})

    def tcp_close(self, t: int, host: int, slot: int) -> None:
        self._tcp_ctl(t, host, OP_TCP_CLOSE, slot)

    # ------------------------------------------------------------------
    # injection + drain
    # ------------------------------------------------------------------

    def _inject_pending(self) -> None:
        if not self._pending:
            return
        self._drained = False
        rows = self._pending
        self._pending = []
        pool = self.sim.state.pool
        time_np = np.asarray(jax.device_get(pool.time))
        free = np.where(time_np == NEVER)[0]
        if len(free) < len(rows):
            raise RuntimeError(
                "bridge event pool full (raise event_capacity)"
            )
        idx = jnp.asarray(free[: len(rows)], jnp.int32)
        t = jnp.asarray([r[0] for r in rows], jnp.int64)
        src = jnp.asarray([r[1] for r in rows], jnp.int32)
        payload_rows = np.stack([r[2] for r in rows])
        seq0 = self.sim.state.host.seq_next  # per-src sequence numbers
        seqs = []
        seq_np = np.array(jax.device_get(seq0))  # writable copy
        for (_, s, _row) in rows:
            seqs.append(int(seq_np[s]))
            seq_np[s] += 1
        self.sim.state = self.sim.state.replace(
            pool=pool.replace(
                time=pool.time.at[idx].set(t),
                dst=pool.dst.at[idx].set(src),  # inject AT the sender
                src=pool.src.at[idx].set(src),
                seq=pool.seq.at[idx].set(jnp.asarray(seqs, jnp.int32)),
                kind=pool.kind.at[idx].set(KIND_PROC_SYSCALL),
                payload=pool.payload.at[idx].set(
                    soa.pack_words(jnp.asarray(payload_rows, jnp.int32))
                ),
            ),
            host=self.sim.state.host.replace(
                seq_next=jnp.asarray(seq_np)
            ),
        )

    def _ring_fields(self, prefix: str) -> list[str]:
        """Column names of one ring, derived from the sub-state keys so the
        drain can never silently miss a column added to the schema above.
        A key belongs to ring `prefix` iff it starts with it, the remainder
        has no further ring prefix, and it isn't the count/overflow scalar."""
        br = self.sim.state.subs[BRIDGE_SUB]
        others = [p for p in self._ring_prefixes if p]
        out = []
        for k in br:
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            if prefix == "" and any(k.startswith(o) for o in others):
                continue
            if rest in ("count", "overflow"):
                continue
            out.append(rest)
        return out

    def _drain_ring(self) -> list:
        # Count-first drain: fetch only the [H] per-ring counts (one small
        # transfer), then fetch ring columns SLICED to the max occupancy of
        # rings that actually hold rows. The old whole-sub device_get moved
        # H*R*~20 arrays over the tunnel every window — megabytes per
        # round trip at 1k hosts — for a usually-empty ring.
        br_state = self.sim.state.subs[BRIDGE_SUB]
        fetched = jax.device_get(
            {
                **{p: br_state[f"{p}count"] for p in self._ring_prefixes},
                "_overflow": br_state["overflow"],
            }
        )
        overflow_now = int(np.asarray(fetched.pop("_overflow")))
        counts = {p: np.asarray(v) for p, v in fetched.items()}
        fetch = {}
        for p in self._ring_prefixes:
            cm = int(counts[p].max()) if counts[p].size else 0
            if cm == 0:
                continue
            for name in self._ring_fields(p):
                fetch[f"{p}{name}"] = br_state[f"{p}{name}"][:, :cm]
        if not fetch:
            return []
        br = jax.device_get(fetch)
        out: list = []
        cnt = counts[""]
        if "time" in br:
            for h in np.where(cnt > 0)[0]:
                for c in range(cnt[h]):
                    out.append(Delivery(
                        time=int(br["time"][h, c]),
                        dst_host=int(h),
                        src_host=int(br["src_host"][h, c]),
                        src_port=int(br["src_port"][h, c]),
                        dst_port=int(br["dst_port"][h, c]),
                        length=int(br["length"][h, c]),
                        handle=int(br["handle"][h, c]),
                    ))
        ndel = len(out)
        if self.with_tcp:
            if "e_time" in br:
                ec = counts["e_"]
                for h in np.where(ec > 0)[0]:
                    for c in range(ec[h]):
                        out.append(TcpEstablished(
                            time=int(br["e_time"][h, c]), host=int(h),
                            slot=int(br["e_slot"][h, c]),
                            peer_host=int(br["e_peer_host"][h, c]),
                            peer_port=int(br["e_peer_port"][h, c]),
                            local_port=int(br["e_local_port"][h, c]),
                            is_accept=bool(br["e_accept"][h, c]),
                        ))
            if "r_time" in br:
                rc = counts["r_"]
                for h in np.where(rc > 0)[0]:
                    for c in range(rc[h]):
                        out.append(TcpBytes(
                            time=int(br["r_time"][h, c]), host=int(h),
                            slot=int(br["r_slot"][h, c]),
                            nbytes=int(br["r_bytes"][h, c]),
                        ))
            if "f_time" in br:
                fc = counts["f_"]
                for h in np.where(fc > 0)[0]:
                    for c in range(fc[h]):
                        out.append(TcpFin(
                            time=int(br["f_time"][h, c]), host=int(h),
                            slot=int(br["f_slot"][h, c]),
                            time_wait=bool(br["f_tw"][h, c]),
                        ))
            if "c_time" in br:
                cc = counts["c_"]
                for h in np.where(cc > 0)[0]:
                    for c in range(cc[h]):
                        out.append(TcpClosed(
                            time=int(br["c_time"][h, c]), host=int(h),
                            slot=int(br["c_slot"][h, c]),
                            reset=bool(br["c_reset"][h, c]),
                        ))
        if not out:
            return []
        # reset all rings
        live = self.sim.state.subs[BRIDGE_SUB]
        reset = dict(live)
        for prefix in ("", "e_", "r_", "f_", "c_"):
            if f"{prefix}count" not in reset:
                continue
            reset[f"{prefix}time"] = jnp.full(
                (self.H, self.R), NEVER, jnp.int64
            )
            reset[f"{prefix}count"] = jnp.zeros((self.H,), jnp.int32)
        self.sim.state = self.sim.state.with_sub(BRIDGE_SUB, reset)
        self._inflight = max(0, self._inflight - ndel)
        overflow = overflow_now
        if overflow > self._overflow_seen:
            from shadow_tpu.utils import log

            log.logger.warning(
                "device output ring overflowed %d row(s); raise the bridge "
                "ring_slots / lower events_per_host_per_window",
                overflow - self._overflow_seen,
            )
            self._overflow_seen = overflow
        out.sort(key=lambda d: (
            d.time, _EVENT_RANK[type(d)],
            getattr(d, "dst_host", getattr(d, "host", 0)),
            getattr(d, "slot", getattr(d, "handle", 0)),
        ))
        # Liveness bookkeeping at drain time in device-event order: accepted
        # children become live; slot release (live-set removal + mirror
        # free) is driven by the ProcessDriver via tcp_release, which also
        # guards against stale rows for recycled slots.
        for ev in out:
            if isinstance(ev, TcpEstablished) and ev.is_accept:
                self._tcp_live.add((ev.host, ev.slot))
        return out

    def sync(self, horizon: int) -> list:
        """Flush pending injections and advance the device until the first
        outputs land or its pool drains up to `horizon`. Returns the output
        events (possibly empty)."""
        if not self._pending and (
            self._drained or (self._inflight == 0 and not self._tcp_live)
        ):
            # nothing new injected and the device pool was already observed
            # empty (or nothing is in flight at all): skip the round trip
            return []
        self._inject_pending()
        evs = self._drain_ring()
        if evs:
            return evs
        hz = min(horizon, self.sim.stop_time)
        while True:
            # ONE dispatch advances up to _sync_max_windows windows, exiting
            # early when output lands (fused while_loop — the per-window
            # dispatch + readback round trips were the managed plane's
            # dominant wall cost at 1k processes)
            self.sim.state, mn, nout = self._run_sync(
                self.sim.state, self.sim.params, hz, self._sync_max_windows
            )
            if int(nout):
                evs = self._drain_ring()
                if evs:
                    return evs
            min_next = int(mn)
            if min_next >= NEVER:
                # device fully drained: any UDP datagram still unaccounted
                # was dropped on-device (loss/CoDel/no-socket) — reclaim its
                # payload bytes and the in-flight count
                self._inflight = 0
                self._handles.clear()
                self._drained = True
                return []
            if min_next >= hz:
                return []
