"""The CPU↔TPU seam: managed-process traffic through the device network.

This is the BASELINE north star (SURVEY.md header): keep syscall-emulated
host processes on the CPU, but lift the network hot path — NIC token
buckets, CoDel router queues, port demux, latency/loss path model — onto
the device engine, with the Router/Topology boundary as the handoff.

Protocol (conservative, deadlock-free):

- Managed sendto() calls append send records host-side; payload BYTES stay
  in a host-side handle table — the device moves 12-word packet headers
  only (W_HANDLE carries the claim ticket).
- When every process is parked, the driver syncs: pending sends are
  injected into the device event pool as KIND_PROC_SYSCALL events at their
  send times, and the device steps conservative windows until the first
  batch of deliveries lands (or its pool drains past the driver's next
  local event). Delivered rows (time, addressing, handle) drain from a
  per-host ring and become ordinary driver wakeups at their device-computed
  delivery times.
- Injections that land behind the device's completed window are processed
  one window late with their true timestamps — the engine's documented
  deferral semantics; their deliveries still land at t + latency ≥ the
  next window, so causality holds (window length ≤ min path latency).

Port binds/unbinds from syscalls update the device UDP socket table
host-side between dispatches (bind is rare; the hot path stays compiled).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import Simulation, _set_col
from shadow_tpu.core.state import KIND_PROC_SYSCALL, NetParams
from shadow_tpu.net import packet as pkt, udp
from shadow_tpu.net.stack import NetStack

NEVER = simtime.NEVER

BRIDGE_SUB = "bridge"


@dataclass
class Delivery:
    time: int
    dst_host: int
    src_host: int
    src_port: int
    dst_port: int
    length: int
    handle: int


class DeviceNetBridge:
    """Owns the device Simulation that carries managed-process datagrams."""

    def __init__(
        self,
        *,
        baked,
        bw_up_bits,
        bw_down_bits,
        host_vertex,
        seed: int,
        stop_time: int,
        bootstrap_end: int = 0,
        sockets_per_host: int = 16,
        event_capacity: int = 4096,
        K: int = 16,
        ring_slots: int | None = None,
    ):
        H = len(host_vertex)
        if ring_slots is None:
            # a window can deliver up to K datagrams per host
            ring_slots = max(32, 2 * K)
        self.H = H
        self.S = sockets_per_host
        self.R = ring_slots
        stack = NetStack(
            H,
            jnp.asarray(bw_up_bits),
            jnp.asarray(bw_down_bits),
            sockets_per_host=sockets_per_host,
            with_tcp=False,
        )
        self.stack = stack
        stack.on_receive(self._on_recv)
        handlers = dict(stack.handlers())
        handlers[KIND_PROC_SYSCALL] = self._on_inject
        subs = stack.init_subs()
        subs[BRIDGE_SUB] = {
            "time": jnp.full((H, ring_slots), NEVER, jnp.int64),
            "src_host": jnp.zeros((H, ring_slots), jnp.int32),
            "src_port": jnp.zeros((H, ring_slots), jnp.int32),
            "dst_port": jnp.zeros((H, ring_slots), jnp.int32),
            "length": jnp.zeros((H, ring_slots), jnp.int32),
            "handle": jnp.zeros((H, ring_slots), jnp.int32),
            "count": jnp.zeros((H,), jnp.int32),
            "overflow": jnp.zeros((), jnp.int64),
        }
        params = NetParams(
            latency_vv=jnp.asarray(baked.latency_vv),
            reliability_vv=jnp.asarray(baked.reliability_vv),
            bootstrap_end=jnp.int64(bootstrap_end),
        )
        self.sim = Simulation(
            num_hosts=H,
            handlers=handlers,
            params=params,
            host_vertex=np.asarray(host_vertex),
            seed=seed,
            stop_time=stop_time,
            runahead=baked.min_latency_ns,
            event_capacity=event_capacity,
            K=K,
            subs=subs,
        )
        self._pending: list[tuple] = []
        self._handles: dict[int, bytes] = {}
        self._next_handle = 1
        self._port_slot: dict[tuple[int, int], int] = {}
        self._inflight = 0  # injected minus delivered (drops reconciled
        # when the device drains — see sync())
        self._overflow_seen = 0

    # ------------------------------------------------------------------
    # device-side handlers
    # ------------------------------------------------------------------

    def _on_inject(self, state, ev, emitter, params):
        """A managed send enters the device network: the event payload IS
        the UDP packet row; the destination host rides in W_SEQ."""
        dst = ev.payload[:, pkt.W_SEQ]
        payload = ev.payload.at[:, pkt.W_SEQ].set(0)
        return self.stack.udp_sendto(
            state, emitter, ev.mask, ev.time, dst,
            dst_port=0, src_port=0, size_bytes=0,
            socket_slot=ev.payload[:, pkt.W_SOCKET],
            payload=payload,
        )

    def _on_recv(self, state, found, slot, src, payload, emitter, now, params):
        """A datagram reached a bound socket: record it in the delivered
        ring for the CPU plane to drain."""
        br = state.subs[BRIDGE_SUB]
        cnt = br["count"]
        fits = found & (cnt < self.R)
        col = jnp.clip(cnt, 0, self.R - 1)
        nowv = jnp.broadcast_to(now, cnt.shape).astype(jnp.int64)
        new = {
            "time": _set_col(br["time"], col, fits, nowv),
            "src_host": _set_col(br["src_host"], col, fits, src.astype(jnp.int32)),
            "src_port": _set_col(br["src_port"], col, fits,
                                 payload[:, pkt.W_SRC_PORT]),
            "dst_port": _set_col(br["dst_port"], col, fits,
                                 payload[:, pkt.W_DST_PORT]),
            "length": _set_col(br["length"], col, fits, payload[:, pkt.W_LEN]),
            "handle": _set_col(br["handle"], col, fits,
                               payload[:, pkt.W_HANDLE]),
            "count": cnt + fits.astype(jnp.int32),
            "overflow": br["overflow"]
            + jnp.sum(found & ~fits, dtype=jnp.int64),
        }
        return state.with_sub(BRIDGE_SUB, new)

    # ------------------------------------------------------------------
    # host-side API (called by ProcessDriver)
    # ------------------------------------------------------------------

    def bind(self, host: int, port: int) -> bool:
        """Bind (host, port) in the device socket table (host-side array
        update; runs between device dispatches)."""
        if (host, port) in self._port_slot:
            return True
        used = np.asarray(jax.device_get(self.sim.state.subs[udp.SUB].used[host]))
        free = np.where(~used)[0]
        if len(free) == 0:
            return False
        slot = int(free[0])
        self._port_slot[(host, port)] = slot
        self.sim.state = self.sim.state.with_sub(
            udp.SUB,
            udp.bind_static(self.sim.state.subs[udp.SUB], host, slot, port),
        )
        return True

    def unbind(self, host: int, port: int) -> None:
        slot = self._port_slot.pop((host, port), None)
        if slot is None:
            return
        u = self.sim.state.subs[udp.SUB]
        self.sim.state = self.sim.state.with_sub(
            udp.SUB, u.replace(used=u.used.at[host, slot].set(False))
        )

    def send(self, t: int, src_host: int, dst_host: int, src_port: int,
             dst_port: int, data: bytes) -> None:
        handle = self._next_handle
        self._next_handle += 1
        self._handles[handle] = data
        self._inflight += 1
        self._pending.append(
            (t, src_host, dst_host, src_port, dst_port, len(data), handle)
        )

    def take_payload(self, handle: int) -> bytes:
        return self._handles.pop(handle, b"")

    def _inject_pending(self) -> None:
        if not self._pending:
            return
        rows = self._pending
        self._pending = []
        pool = self.sim.state.pool
        time_np = np.asarray(jax.device_get(pool.time))
        free = np.where(time_np == NEVER)[0]
        if len(free) < len(rows):
            raise RuntimeError(
                "bridge event pool full (raise event_capacity)"
            )
        idx = jnp.asarray(free[: len(rows)], jnp.int32)
        t = jnp.asarray([r[0] for r in rows], jnp.int64)
        src = jnp.asarray([r[1] for r in rows], jnp.int32)
        payload_rows = np.zeros((len(rows), pkt.PAYLOAD_WORDS), np.int32)
        for i, (_, s, d, sp, dp, ln, h) in enumerate(rows):
            payload_rows[i, pkt.W_PROTO] = pkt.PROTO_UDP
            payload_rows[i, pkt.W_SRC_PORT] = sp
            payload_rows[i, pkt.W_DST_PORT] = dp
            payload_rows[i, pkt.W_LEN] = ln
            payload_rows[i, pkt.W_SRC_HOST] = s
            payload_rows[i, pkt.W_SOCKET] = self._port_slot.get((s, sp), 0)
            payload_rows[i, pkt.W_SEQ] = d  # dst host rides in the seq word
            payload_rows[i, pkt.W_HANDLE] = h
        seq0 = self.sim.state.host.seq_next  # per-src sequence numbers
        seqs = []
        seq_np = np.array(jax.device_get(seq0))  # writable copy
        for (_, s, *_rest) in rows:
            seqs.append(int(seq_np[s]))
            seq_np[s] += 1
        self.sim.state = self.sim.state.replace(
            pool=pool.replace(
                time=pool.time.at[idx].set(t),
                dst=pool.dst.at[idx].set(src),  # inject AT the sender
                src=pool.src.at[idx].set(src),
                seq=pool.seq.at[idx].set(jnp.asarray(seqs, jnp.int32)),
                kind=pool.kind.at[idx].set(KIND_PROC_SYSCALL),
                payload=pool.payload.at[idx].set(jnp.asarray(payload_rows)),
            ),
            host=self.sim.state.host.replace(
                seq_next=jnp.asarray(seq_np)
            ),
        )

    def _drain_ring(self) -> list[Delivery]:
        br = jax.device_get(self.sim.state.subs[BRIDGE_SUB])
        counts = np.asarray(br["count"])
        if not counts.any():
            return []
        out = []
        for h in np.where(counts > 0)[0]:
            for c in range(counts[h]):
                out.append(Delivery(
                    time=int(br["time"][h, c]),
                    dst_host=int(h),
                    src_host=int(br["src_host"][h, c]),
                    src_port=int(br["src_port"][h, c]),
                    dst_port=int(br["dst_port"][h, c]),
                    length=int(br["length"][h, c]),
                    handle=int(br["handle"][h, c]),
                ))
        H, R = self.H, self.R
        reset = {
            **{k: self.sim.state.subs[BRIDGE_SUB][k] for k in br},
            "time": jnp.full((H, R), NEVER, jnp.int64),
            "count": jnp.zeros((H,), jnp.int32),
        }
        self.sim.state = self.sim.state.with_sub(BRIDGE_SUB, reset)
        self._inflight = max(0, self._inflight - len(out))
        overflow = int(np.asarray(br["overflow"]))
        if overflow > self._overflow_seen:
            from shadow_tpu.utils import log

            log.logger.warning(
                "device delivery ring overflowed %d datagram(s); raise the "
                "bridge ring_slots / lower events_per_host_per_window",
                overflow - self._overflow_seen,
            )
            self._overflow_seen = overflow
        out.sort(key=lambda d: (d.time, d.dst_host, d.src_host, d.handle))
        return out

    def sync(self, horizon: int) -> list[Delivery]:
        """Flush pending sends and advance the device until the first
        deliveries land or its pool drains up to `horizon`. Returns the
        deliveries (possibly empty)."""
        if not self._pending and self._inflight == 0:
            return []  # nothing injected and nothing in flight: no sync
        self._inject_pending()
        dels = self._drain_ring()
        if dels:
            return dels
        while True:
            min_next = int(jnp.min(self.sim.state.pool.time))
            if min_next >= NEVER:
                # device fully drained: anything still unaccounted was
                # dropped on-device (loss/CoDel/no-socket) — reclaim its
                # payload bytes and the in-flight count
                self._inflight = 0
                self._handles.clear()
                return []
            if min_next >= min(horizon, self.sim.stop_time):
                return []
            ws = min_next
            we = min(ws + self.sim.runahead, horizon, self.sim.stop_time)
            self.sim.state, _ = self.sim._step(
                self.sim.state, self.sim.params, ws, we
            )
            dels = self._drain_ring()
            if dels:
                return dels
