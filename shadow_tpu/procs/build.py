"""Locate (and lazily build) the native shim library."""

from __future__ import annotations

import pathlib
import shutil
import subprocess

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
NATIVE_DIR = REPO_ROOT / "native"
SHIM_SO = NATIVE_DIR / "build" / "libshadow_tpu_shim.so"


def toolchain_available() -> bool:
    return shutil.which("g++") is not None and shutil.which("make") is not None


def shim_path(rebuild: bool = False) -> pathlib.Path:
    """Return the shim .so path, building it if missing (or on rebuild)."""
    src_newer = (
        SHIM_SO.exists()
        and max(
            (NATIVE_DIR / "shim" / "shim.cpp").stat().st_mtime,
            (NATIVE_DIR / "common" / "ipc.h").stat().st_mtime,
        )
        > SHIM_SO.stat().st_mtime
    )
    if rebuild or not SHIM_SO.exists() or src_newer:
        if not toolchain_available():
            raise RuntimeError(
                "native toolchain (g++/make) unavailable and shim not built"
            )
        subprocess.run(
            ["make", "-s"], cwd=NATIVE_DIR, check=True, capture_output=True
        )
    return SHIM_SO
