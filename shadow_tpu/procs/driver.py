"""Managed-process driver: spawns real binaries under the native shim and
services their syscalls against the simulated network + clock.

Reference parity map (SURVEY.md §3.3, §3.5):
  - process launch env injection  -> ManagedProcess.spawn (LD_PRELOAD +
    SHADOW_TPU_SHM), reference: manager.c:352-432, thread_preload.c:131-179
  - resume/syscall event loop     -> ProcessDriver._service_one, reference:
    threadpreload_resume (thread_preload.c:200-291)
  - syscall dispatch              -> ProcessDriver._dispatch, reference:
    syscallhandler_make_syscall (syscall_handler.c:247-511)
  - SYSCALL_BLOCK + condition     -> Parked records + wake events, reference:
    syscall_condition.c
  - scheduler determinism         -> strict sequential service order over
    processes + (time, seq) event heap, reference: event.c:109-152

Execution model: a managed process is either RUNNING (we posted its reply;
it is executing app code; the driver waits for its next syscall) or PARKED
(its last syscall blocked; no reply posted yet — the process sits in
sem_wait). Sim time advances only when every live process is parked, exactly
the reference's conservative rule that plugin execution happens "inside" an
event at a fixed sim time.

The network model here is the stage-A CPU backend: latency/loss scheduling
in a Python heap with a simplified reliable TCP (no cwnd dynamics). It is
the golden reference for dual-target tests; the device-stepped engine is the
performance path and the two are bridged at the Router seam (stage B).
"""

from __future__ import annotations

import errno
import heapq
import os
import random
import subprocess
import time as wall_time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs import ipc

NS_PER_SEC = 1_000_000_000

# Linux x86-64 syscall numbers the shim forwards
SYS_read = 0
SYS_write = 1
SYS_close = 3
SYS_poll = 7
SYS_ioctl = 16
SYS_nanosleep = 35
SYS_socket = 41
SYS_connect = 42
SYS_accept = 43
SYS_sendto = 44
SYS_recvfrom = 45
SYS_shutdown = 48
SYS_bind = 49
SYS_listen = 50
SYS_getsockname = 51
SYS_getpeername = 52
SYS_setsockopt = 54
SYS_getsockopt = 55
SYS_fcntl = 72
SYS_gettimeofday = 96
SYS_clock_gettime = 228
SYS_epoll_wait = 232
SYS_epoll_ctl = 233
SYS_accept4 = 288
SYS_epoll_create1 = 291

SOCK_STREAM = 1
SOCK_DGRAM = 2
SOCK_NONBLOCK = 0o4000
O_NONBLOCK = 0o4000
F_GETFL = 3
F_SETFL = 4
FIONREAD = 0x541B

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3
EPOLLIN = 0x1
EPOLLOUT = 0x4
EPOLLERR = 0x8
EPOLLHUP = 0x10
POLLIN = 0x1
POLLOUT = 0x4
POLLERR = 0x8
POLLHUP = 0x10


# ---------------------------------------------------------------------------
# simulated socket objects (driver side)
# ---------------------------------------------------------------------------


@dataclass
class Sock:
    fd: int
    proto: int  # SOCK_DGRAM | SOCK_STREAM
    owner: "ManagedProcess"
    bound: tuple[int, int] | None = None  # (ip, port)
    peer: tuple[int, int] | None = None
    nonblock: bool = False
    # UDP: deque of (src_ip, src_port, bytes)
    dgrams: deque = field(default_factory=deque)
    # TCP
    listening: bool = False
    accept_q: deque = field(default_factory=deque)  # Conn objects
    conn: "Conn | None" = None
    connecting: bool = False

    def readable(self) -> bool:
        if self.proto == SOCK_DGRAM:
            return len(self.dgrams) > 0
        if self.listening:
            return len(self.accept_q) > 0
        if self.conn is not None:
            return len(self.conn.rx) > 0 or self.conn.rx_eof
        return False

    def writable(self) -> bool:
        if self.proto == SOCK_DGRAM:
            return True
        return self.conn is not None and self.conn.established


@dataclass
class Conn:
    """One direction-pair of a stage-A TCP connection (per endpoint)."""

    established: bool = False
    rx: bytearray = field(default_factory=bytearray)
    rx_eof: bool = False
    remote: "Conn | None" = None  # the peer endpoint's Conn
    remote_addr: tuple[int, int] | None = None
    local_addr: tuple[int, int] | None = None


@dataclass
class Epoll:
    fd: int
    owner: "ManagedProcess"
    interest: dict = field(default_factory=dict)  # fd -> (events, data)


@dataclass
class Parked:
    """A blocked syscall awaiting a condition (syscall_condition.c analog)."""

    proc: "ManagedProcess"
    kind: str  # recv|accept|connect|sleep|poll|epoll
    fd: int = -1
    want: int = 0
    deadline: int | None = None  # sim ns; None = no timeout
    pollset: list = field(default_factory=list)  # [(fd, events)]
    epfd: int = -1
    maxevents: int = 0


class ManagedProcess:
    RUNNING = "running"
    PARKED = "parked"
    EXITED = "exited"

    def __init__(self, name: str, args: list[str], host: "SimHost",
                 start_time: int = 0, env: dict | None = None,
                 cwd: str | None = None):
        self.name = name
        self.args = args
        self.host = host
        self.start_time = start_time
        self.extra_env = env or {}
        self.cwd = cwd
        self.channel: ipc.Channel | None = None
        self.popen: subprocess.Popen | None = None
        self.state = ManagedProcess.PARKED  # not yet spawned
        self.fds: dict[int, object] = {}
        self.next_fd = ipc.FD_BASE
        self.parked: Parked | None = None
        self.exit_code: int | None = None

    def spawn(self, spin: int = 4096) -> None:
        self.channel = ipc.Channel()
        env = dict(os.environ)
        env["LD_PRELOAD"] = str(build_mod.shim_path())
        env[ipc.ENV_SHM] = self.channel.path
        env[ipc.ENV_SPIN] = str(spin)
        env.update(self.extra_env)
        self.popen = subprocess.Popen(
            self.args, env=env, cwd=self.cwd,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        self.state = ManagedProcess.RUNNING  # executing until HELLO arrives

    def alloc_fd(self) -> int:
        fd = self.next_fd
        self.next_fd += 1
        return fd

    def alive(self) -> bool:
        return self.state != ManagedProcess.EXITED

    def finish(self) -> tuple[bytes, bytes]:
        out, err = b"", b""
        if self.popen:
            try:
                out, err = self.popen.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                self.popen.kill()
                out, err = self.popen.communicate()
            self.exit_code = self.popen.returncode
        if self.channel:
            self.channel.close()
            self.channel = None
        self.state = ManagedProcess.EXITED
        return out, err


@dataclass
class SimHost:
    """A simulated host that owns managed processes (host.c analog)."""

    name: str
    ip: int  # ipv4 host-order
    procs: list = field(default_factory=list)
