"""Managed-process driver: spawns real binaries under the native shim and
services their syscalls against the simulated network + clock.

Reference parity map (SURVEY.md §3.3, §3.5):
  - process launch env injection  -> ManagedProcess.spawn (LD_PRELOAD +
    SHADOW_TPU_SHM), reference: manager.c:352-432, thread_preload.c:131-179
  - resume/syscall event loop     -> ProcessDriver._service_one, reference:
    threadpreload_resume (thread_preload.c:200-291)
  - syscall dispatch              -> ProcessDriver._dispatch, reference:
    syscallhandler_make_syscall (syscall_handler.c:247-511)
  - SYSCALL_BLOCK + condition     -> Parked records + wake events, reference:
    syscall_condition.c
  - scheduler determinism         -> strict sequential service order over
    processes + (time, seq) event heap, reference: event.c:109-152

Execution model: a managed process is either RUNNING (we posted its reply;
it is executing app code; the driver waits for its next syscall) or PARKED
(its last syscall blocked; no reply posted yet — the process sits in
sem_wait). Sim time advances only when every live process is parked, exactly
the reference's conservative rule that plugin execution happens "inside" an
event at a fixed sim time.

The network model here is the stage-A CPU backend: latency/loss scheduling
in a Python heap with a simplified reliable TCP (no cwnd dynamics). It is
the golden reference for dual-target tests; the device-stepped engine is the
performance path and the two are bridged at the Router seam (stage B).
"""

from __future__ import annotations

import errno
import heapq
import os
import random
import signal as os_signal
import struct as struct_mod
import subprocess
import time as wall_time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs import ipc
from shadow_tpu.procs.bridge import (
    Delivery,
    TcpBytes,
    TcpClosed,
    TcpEstablished,
    TcpFin,
)
from shadow_tpu.utils import log

NS_PER_SEC = 1_000_000_000

# Linux x86-64 syscall numbers the shim forwards
SYS_read = 0
SYS_write = 1
SYS_close = 3
SYS_poll = 7
SYS_ioctl = 16
SYS_dup = 32
SYS_dup2 = 33
SYS_nanosleep = 35
SYS_socket = 41
SYS_connect = 42
SYS_accept = 43
SYS_sendto = 44
SYS_recvfrom = 45
SYS_shutdown = 48
SYS_bind = 49
SYS_listen = 50
SYS_getsockname = 51
SYS_getpeername = 52
SYS_setsockopt = 54
SYS_getsockopt = 55
SYS_fcntl = 72
SYS_gettimeofday = 96
SYS_clock_gettime = 228
SYS_epoll_wait = 232
SYS_epoll_ctl = 233
SYS_timerfd_create = 283
SYS_timerfd_settime = 286
SYS_timerfd_gettime = 287
SYS_accept4 = 288
SYS_eventfd2 = 290
SYS_epoll_create1 = 291
SYS_dup3 = 292
SYS_pipe2 = 293
SYS_getrandom = 318
SYS_signalfd4 = 289
SYS_sched_getaffinity = 204
SYS_rt_sigaction = 13
SYS_rt_sigprocmask = 14
SYS_socketpair = 53
SYS_kill = 62

EFD_SEMAPHORE = 0x1
TFD_TIMER_ABSTIME = 0x1
O_NONBLOCK_FLAG = 0o4000

AF_UNIX = 1
AF_INET = 2

# virtual signal plane (reference: syscall/signal.c emulation)
SIGINT = 2
SIGKILL = 9
SIGUSR1 = 10
SIGUSR2 = 12
SIGPIPE = 13
SIGALRM = 14
SIGTERM = 15
SIGCHLD = 17
SA_SIGINFO = 4
SA_NODEFER = 0x40000000
# SIG_DFL disposition that ignores (POSIX: CHLD/URG/WINCH/CONT ignore)
_SIG_DFL_IGNORE = {SIGCHLD, 18, 23, 28}
# park kinds a signal may interrupt with EINTR (interruptible waits)
_SIG_INTERRUPTIBLE = {
    "recv", "read", "accept", "connect", "send", "sleep", "poll", "epoll",
    "futex", "waitpid",
}

# sysno -> name for syscall-count reporting (built from the SYS_* constants
# above plus the pseudo-syscalls)
SYSCALL_NAMES = {
    v: k[4:] for k, v in list(globals().items())
    if k.startswith("SYS_") and isinstance(v, int)
}
SYSCALL_NAMES.update({
    ipc.PSYS_RESOLVE_NAME: "resolve_name",
    ipc.PSYS_YIELD: "yield",
    ipc.PSYS_GETHOSTNAME: "gethostname",
})


def _wait_status(q) -> int:
    """Linux wait-status word: signaled = sig in the low 7 bits; normal
    exit = (code & 0xff) << 8 (the shim passes this through verbatim, so
    WIFEXITED/WIFSIGNALED/WTERMSIG all work)."""
    if q.killed_by_signal:
        return q.killed_by_signal & 0x7F
    return (int(q.exit_code or 0) & 0xFF) << 8


def format_syscall_counts(counts: dict[int, int]) -> str:
    parts = [
        f"{SYSCALL_NAMES.get(n, n)}:{c}"
        for n, c in sorted(counts.items(), key=lambda kv: -kv[1])
    ]
    return " ".join(parts)

SOCK_STREAM = 1
SOCK_DGRAM = 2
SOCK_NONBLOCK = 0o4000
O_NONBLOCK = 0o4000
F_GETFL = 3
F_SETFL = 4
FIONREAD = 0x541B

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3
EPOLLIN = 0x1
EPOLLOUT = 0x4
EPOLLERR = 0x8
EPOLLHUP = 0x10
POLLIN = 0x1
POLLOUT = 0x4
POLLERR = 0x8
POLLHUP = 0x10


# ---------------------------------------------------------------------------
# simulated socket objects (driver side)
# ---------------------------------------------------------------------------


@dataclass
class Sock:
    fd: int
    proto: int  # SOCK_DGRAM | SOCK_STREAM
    owner: "ManagedProcess"
    bound: tuple[int, int] | None = None  # (ip, port)
    peer: tuple[int, int] | None = None
    nonblock: bool = False
    cloexec: bool = False  # FD_CLOEXEC/SOCK_CLOEXEC: dropped at exec respawn
    # UDP: deque of (src_ip, src_port, bytes)
    dgrams: deque = field(default_factory=deque)
    # TCP
    listening: bool = False
    accept_q: deque = field(default_factory=deque)  # Conn | BridgeEnd
    conn: "Conn | None" = None
    # AF_UNIX (descriptor/channel.c + unix-socket analog): family marks the
    # namespace; `pair` links datagram socketpair twins; `unix_path` is the
    # bound filesystem name in the host-scoped unix namespace
    family: int = 2  # AF_INET
    pair: "Sock | None" = None
    unix_path: str | None = None
    bend: "BridgeEnd | None" = None  # device-carried TCP endpoint
    dev_listen_slot: int | None = None  # device listener slot (bridge mode)
    connecting: bool = False
    conn_refused: bool = False

    def readable(self) -> bool:
        if self.proto == SOCK_DGRAM:
            return len(self.dgrams) > 0
        if self.listening:
            return len(self.accept_q) > 0
        if self.bend is not None:
            return len(self.bend.rx) > 0 or self.bend.rx_eof
        if self.conn is not None:
            return len(self.conn.rx) > 0 or self.conn.rx_eof
        return False

    def writable(self) -> bool:
        if self.proto == SOCK_DGRAM:
            return True
        if self.bend is not None:
            return (
                self.bend.established
                and not self.bend.closed
                and self.bend.send_space() > 0
            )
        return self.conn is not None and self.conn.established


@dataclass
class Conn:
    """One direction-pair of a stage-A TCP connection (per endpoint)."""

    established: bool = False
    rx: bytearray = field(default_factory=bytearray)
    rx_eof: bool = False
    remote: "Conn | None" = None  # the peer endpoint's Conn
    remote_addr: tuple[int, int] | None = None
    local_addr: tuple[int, int] | None = None
    sock: "Sock | None" = None  # owning endpoint socket (None until accepted)
    unix: bool = False  # AF_UNIX: zero-latency local delivery


@dataclass
class BridgeEnd:
    """One endpoint of a TCP connection carried by the device network.

    The device TCP machine (net/tcp.py) moves sequence space; actual bytes
    stay host-side: a sender appends to its `tx_queue`, and the receiver
    claims the device-reported in-order advance from the PEER's tx_queue
    (sound because TCP delivers in order by construction). Maps to the
    reference's split between tcp.c seq/ack state and socket byte buffers.
    """

    host: "SimHost"
    slot: int  # device socket slot on `host`
    local_addr: tuple[int, int]
    remote_addr: tuple[int, int]
    sock: "Sock | None" = None  # None while un-accepted in the accept queue
    peer: "BridgeEnd | None" = None
    established: bool = False
    rx: bytearray = field(default_factory=bytearray)
    rx_eof: bool = False
    tx_queue: bytearray = field(default_factory=bytearray)
    # send-buffer byte cap (reference: bounded tcp.c send buffer backed by
    # socket_send_buffer): a writer that outruns the path parks/EAGAINs
    # instead of buffering the whole stream host-side
    sndbuf: int = 131072
    closed: bool = False  # we injected a close (FIN) for this end
    recycled: bool = False  # slot returned to the mirror (end is finished)
    born_t: int = 0  # sim time this end claimed the slot (staleness guard)

    def send_space(self) -> int:
        return max(0, self.sndbuf - len(self.tx_queue))


@dataclass
class Epoll:
    fd: int
    owner: "ManagedProcess"
    interest: dict = field(default_factory=dict)  # fd -> (events, data)
    # EPOLLET bookkeeping: fd -> the watched object's wake_seq at the last
    # report; an edge-triggered fd re-reports only after new data/readiness
    # arrived (every wake path bumps the object's wake_seq)
    reported_seq: dict = field(default_factory=dict)


@dataclass
class PipeBuf:
    """Shared byte queue between a pipe's two ends (reference: the Rust
    descriptor/pipe.rs over utility/byte_queue.rs)."""

    data: bytearray = field(default_factory=bytearray)
    read_closed: bool = False
    write_closed: bool = False


@dataclass
class PipeEnd:
    fd: int
    owner: "ManagedProcess"
    buf: PipeBuf
    is_read: bool
    nonblock: bool = False
    cloexec: bool = False

    def readable(self) -> bool:
        return self.is_read and (len(self.buf.data) > 0 or self.buf.write_closed)

    def writable(self) -> bool:
        return not self.is_read  # unbounded buffer: writes never block


@dataclass
class EventFd:
    """eventfd emulation (reference: descriptor/eventd.c)."""

    fd: int
    owner: "ManagedProcess"
    value: int = 0
    semaphore: bool = False
    nonblock: bool = False

    def readable(self) -> bool:
        return self.value > 0

    def writable(self) -> bool:
        return self.value < (1 << 64) - 2


@dataclass
class SignalFd:
    """signalfd emulation on the VIRTUAL signal plane (reference:
    syscall/signal.c + descriptor surface): reads consume pending virtual
    signals of the owning process that match the fd's mask, regardless of
    thread signal masks (the kernel's signalfd contract — the standard
    usage blocks the signals first so only the fd consumes them)."""

    fd: int
    owner: "ManagedProcess"
    mask: int = 0
    nonblock: bool = False
    cloexec: bool = False

    def _process(self):
        return getattr(self.owner, "proc", self.owner)

    def readable_for(self, p) -> bool:
        """Readiness is relative to the process LOOKING at the fd: reads
        consume the reading process's pending signals, so a fork-inherited
        signalfd must poll readable against the poller's queue, not the
        creator's."""
        return any((self.mask >> (s - 1)) & 1 for s in p.sig_pending)

    def readable(self) -> bool:
        return self.readable_for(self._process())

    def writable(self) -> bool:
        return False


@dataclass
class TimerFd:
    """timerfd emulation driving scheduled wake events (reference:
    descriptor/timer.c timerfd-backed Timer objects)."""

    fd: int
    owner: "ManagedProcess"
    nonblock: bool = False
    expirations: int = 0
    interval_ns: int = 0
    next_expiry: int | None = None  # absolute sim ns; None = disarmed
    gen: int = 0  # invalidates stale scheduled callbacks after settime

    def readable(self) -> bool:
        return self.expirations > 0

    def writable(self) -> bool:
        return False


@dataclass
class Parked:
    """A blocked syscall awaiting a condition (syscall_condition.c analog)."""

    proc: "ManagedProcess"
    kind: str  # recv|read|accept|connect|sleep|poll|epoll|send
    fd: int = -1
    want: int = 0
    deadline: int | None = None  # sim ns; None = no timeout
    pollset: list = field(default_factory=list)  # [(fd, events)]
    epfd: int = -1
    maxevents: int = 0
    hdr: bool = True  # recv: prepend the 6-byte source-address header
    data: bytes = b""  # send: payload awaiting send-buffer space


class ManagedThread:
    """One schedulable execution stream of a managed process: its own
    channel, run state, and parked record (reference analog: the per-thread
    IPC block + resume loop, thread_preload.c:200-291).

    The syscall dispatch code addresses this object as `proc` everywhere —
    attribute access for process-level state (fds, host, name, popen, …)
    delegates to the owning ManagedProcess, while the scheduling trio
    (channel/state/parked) is per-thread. Exactly one thread of a process
    runs app code at a time (the driver withholds wake replies until the
    running thread blocks), which is what makes multithreaded apps
    deterministic — the reference's one-thread-at-a-time resume model.
    """

    RUNNING = "running"
    PARKED = "parked"
    READY = "ready"  # woken; reply deferred until the run token is free
    EXITED = "exited"

    def __init__(self, proc: "ManagedProcess", tid: int,
                 channel: "ipc.Channel | None" = None):
        self.proc = proc
        self.tid = tid
        self.channel = channel
        self.state = ManagedThread.PARKED
        self.parked: Parked | None = None
        self.pending: tuple[int, bytes] | None = None  # deferred reply
        self.sig_mask = 0  # blocked virtual signals (rt_sigprocmask)
        # saved masks for in-flight handler invocations: delivery blocks
        # the signal (plus sa_mask) for the handler's duration, restored by
        # PSYS_SIG_RETURN — Linux's auto-block-during-handler semantics
        self.sig_mask_stack: list[int] = []

    def __getattr__(self, name):
        # only called for attributes NOT found on the thread itself
        return getattr(self.proc, name)

    def alive(self) -> bool:
        return (
            self.state != ManagedThread.EXITED and self.proc.alive()
        )

    def __repr__(self):
        return f"<ManagedThread {self.proc.name}:{self.tid} {self.state}>"


class ManagedProcess:
    RUNNING = "running"
    PARKED = "parked"
    EXITED = "exited"

    def __init__(self, name: str, args: list[str], host: "SimHost",
                 start_time: int = 0, env: dict | None = None,
                 cwd: str | None = None, stop_time: int | None = None,
                 stdout_path: str | None = None,
                 stderr_path: str | None = None):
        self.name = name
        self.args = args
        self.host = host
        self.start_time = start_time
        self.stop_time = stop_time  # sim ns; None = run until exit/sim end
        self.extra_env = env or {}
        self.cwd = cwd
        # When set, process output goes to these files (the reference writes
        # shadow.data/hosts/<host>/<exe>.<n>.stdout — process.c:468-481);
        # contents are still loaded into .stdout/.stderr at finish().
        if stderr_path is None and stdout_path is not None:
            stderr_path = stdout_path + ".err"
        if stderr_path is not None and stdout_path is None:
            raise ValueError("stderr_path requires stdout_path")
        self.stdout_path = stdout_path
        self.stderr_path = stderr_path
        self.stopped_by_sim = False  # stopped at stop_time, not app exit
        self.faulted = False  # killed/quarantined by the fault plane
        self.popen: subprocess.Popen | None = None
        self.exited = False  # process-level liveness (threads track their own)
        self.fds: dict[int, object] = {}
        self.next_fd = ipc.FD_BASE
        self.exit_code: int | None = None
        self.threads: list[ManagedThread] = [ManagedThread(self, 0)]
        # per-process futex table: uaddr -> list of parked ManagedThread in
        # park order (futex_table.c analog)
        self.futexes: dict[int, list] = {}
        # fork lineage (process.c:460-531 analog): parent process, the
        # child's real pid (recorded at HELLO), and waitpid bookkeeping
        self.parent: "ManagedProcess | None" = None
        self.native_pid: int | None = None
        self.wait_reported = False
        # virtual signal plane (syscall/signal.c analog): signo ->
        # (handler addr, sa_flags, sa_mask); pending queue in post order
        self.sig_actions: dict[int, tuple[int, int, int]] = {}
        self.sig_pending: list[int] = []
        self.killed_by_signal: int | None = None
        # prior native images retired by exec respawns (outputs are
        # concatenated in finish(), preserving stdio continuity)
        self.old_popens: list = []

    # --- main-thread delegation (single-thread call sites and tests) ---

    @property
    def main(self) -> ManagedThread:
        return self.threads[0]

    @property
    def channel(self):
        return self.main.channel

    @property
    def state(self):
        return self.main.state

    @state.setter
    def state(self, v):
        self.main.state = v

    @property
    def parked(self):
        return self.main.parked

    @parked.setter
    def parked(self, v):
        self.main.parked = v

    def spawn(self, spin: int = 4096, seccomp: bool = True,
              log_stamp: bool = False) -> None:
        self.main.channel = ipc.Channel()
        env = dict(os.environ)
        env["LD_PRELOAD"] = str(build_mod.shim_path())
        env[ipc.ENV_SHM] = self.main.channel.path
        env[ipc.ENV_SPIN] = str(spin)
        env[ipc.ENV_SECCOMP] = "1" if seccomp else "0"
        if log_stamp:
            # shim stamps stdout/stderr lines with the sim clock
            # (shim_logger.c analog)
            env[ipc.ENV_LOG_STAMP] = "1"
        env.update(self.extra_env)
        if self.stdout_path is not None:
            out_f = open(self.stdout_path, "wb")
            err_f = open(self.stderr_path, "wb")
        else:
            out_f = err_f = subprocess.PIPE
        self.popen = subprocess.Popen(
            self.args, env=env, cwd=self.cwd, stdout=out_f, stderr=err_f,
        )
        if self.stdout_path is not None:
            out_f.close()
            err_f.close()
        self.state = ManagedProcess.RUNNING  # executing until HELLO arrives

    def alloc_fd(self) -> int:
        # skip occupied slots: dup2/dup3 can park an alias ahead of the
        # counter, and allocating over it would silently drop the alias
        while self.next_fd in self.fds:
            self.next_fd += 1
        if self.next_fd >= VIRT_NOFILE:
            # clamp against the shim's virtual RLIMIT_NOFILE soft limit:
            # the app observes EMFILE, exactly what its getrlimit() predicts
            raise FdLimitError(
                f"{self.name}: virtual fd space exhausted "
                f"(RLIMIT_NOFILE soft limit {VIRT_NOFILE})"
            )
        fd = self.next_fd
        self.next_fd += 1
        return fd

    def alive(self) -> bool:
        return not self.exited

    @staticmethod
    def _communicate(op, timeout: float) -> tuple[bytes, bytes]:
        """Bounded output collection. The post-kill retry must stay bounded
        too: killing `op` does not close pipe fds inherited by its fork
        children, so an unconditional communicate() can wait on EOF forever
        while a descendant lives."""
        try:
            return op.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            op.kill()
            try:
                return op.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                return b"", b""

    def finish(self) -> tuple[bytes, bytes]:
        out, err = b"", b""
        for op in self.old_popens:
            o2, e2 = self._communicate(op, 5)
            out += o2 or b""
            err += e2 or b""
        if self.popen:
            o2, e2 = self._communicate(self.popen, 10)
            out += o2 or b""
            err += e2 or b""
            self.exit_code = self.popen.returncode
        if self.stdout_path is not None:
            with open(self.stdout_path, "rb") as f:
                out = f.read()
            with open(self.stderr_path, "rb") as f:
                err = f.read()
        for t in self.threads:
            if t.channel:
                t.channel.close()
                t.channel = None
            t.state = ManagedThread.EXITED
        self.exited = True
        return out, err


def _new_tracker() -> dict:
    return {
        "tx_packets": 0, "tx_bytes": 0,
        "rx_packets": 0, "rx_bytes": 0,
        "dropped_packets": 0,
    }


@dataclass
class SimHost:
    """A simulated host that owns managed processes (host.c analog)."""

    name: str
    ip: int  # ipv4 host-order
    index: int = 0  # device host id (registration order)
    procs: list = field(default_factory=list)
    next_port: int = 10000  # ephemeral port allocator (deterministic)
    # per-host byte/packet accounting (tracker.c:215-247 analog)
    tracker: dict = field(default_factory=_new_tracker)
    pcap_dir: str | None = None  # capture rx/tx packets when set
    # deterministic per-host random stream (getrandom; reference: per-host
    # nodeSeed from the controller's master RNG, random.c:15-51). add_host
    # derives the real stream from the controller master seed; the default
    # is a fixed-seed stream so a directly-constructed SimHost can never
    # draw OS entropy (shadowlint STL003)
    rand: random.Random = field(default_factory=lambda: random.Random(0))
    # CPU model (host/cpu.c): simulated processing time not yet applied to
    # the virtual clock
    cpu_unapplied: int = 0
    # fault plane: a quarantined (crashed) host — its processes are dead
    # and pending deliveries to it are drained instead of delivered
    dead: bool = False


def ip_from_str(s: str) -> int:
    import ipaddress

    return int(ipaddress.IPv4Address(s))  # v4 only: wire format is 4 bytes


def _pack_epoll_event(events: int, data: int) -> bytes:
    """Wire format for one epoll_event: u32 events + u64 data. The shim
    hands us epoll_data as a signed register value, so mask to u64 —
    apps legitimately store sentinels like -1 there."""
    return (events & 0xFFFFFFFF).to_bytes(4, "little") + (
        data & 0xFFFFFFFFFFFFFFFF
    ).to_bytes(8, "little")


class DriverError(RuntimeError):
    pass


class ProcWedged(DriverError):
    """A managed process stopped responding on its IPC channel and the
    escalation ladder (bounded retries with backoff) is exhausted. The
    on_proc_failure policy decides: abort re-raises, quarantine marks the
    simulated host dead and the run continues."""


class FdLimitError(DriverError):
    """Virtual fd space exhausted (the shim's synthesized RLIMIT_NOFILE
    soft limit). Dispatch translates this to -EMFILE for the app."""


# Mirror of the shim's synthesized RLIMIT_NOFILE soft limit
# (native/shim/shim.cpp rlim_init_locked): managed fds live in
# [FD_BASE, VIRT_NOFILE), well clear of FD_BASE + any per-host socket
# budget; alloc_fd clamps here so the driver can never hand out an fd the
# app's own getrlimit() says cannot exist.
VIRT_NOFILE = 65536


class ProcessDriver:
    """Sequential syscall service loop over all managed processes.

    Determinism by construction (reference analog: event.c:109-152 total
    order + one-worker-per-host rounds): processes are serviced one at a
    time in registration order; a process runs until its syscall BLOCKs;
    sim time advances only when every live process is parked; network
    events fire from a (time, seq) heap; loss rolls come from one seeded
    RNG consumed in event order.

    The network model is the stage-A CPU backend (latency + loss + byte
    streams); the device-stepped engine is the performance path, bridged at
    the Router seam in stage B.
    """

    def __init__(
        self,
        *,
        stop_time: int = 60 * NS_PER_SEC,
        latency_ns: int = 10_000_000,
        loss: float = 0.0,
        seed: int = 1,
        spin: int = 4096,
        service_timeout_s: float = 10.0,
        host_workers: int = 1,
    ):
        self.stop_time = int(stop_time)
        self.latency_ns = int(latency_ns)
        self.loss = float(loss)
        self.seed = seed
        self.spin = spin
        # seccomp/SIGSYS backstop in the shim (use_seccomp flag;
        # configuration.rs:247-250 analog): catches raw syscall
        # instructions that bypass the interposed libc symbols
        self.use_seccomp = True
        # shim-side sim-time stamping of managed stdout/stderr lines
        # (shim_logger.c analog; off by default — byte-exact app output is
        # what the determinism tests compare)
        self.log_stamp = False
        # CPUs a managed process observes via sched_getaffinity (and thus
        # glibc nproc): deterministic, decoupled from the real machine
        self.virtual_cpus = 1
        self.service_timeout_s = service_timeout_s
        self.now = 0
        self.hosts: list[SimHost] = []
        self._hosts_by_ip: dict[int, SimHost] = {}
        self.procs: list[ManagedProcess] = []
        self._heap: list = []  # (time, seq, callback)
        self._seq = 0
        self._rng = random.Random(seed)
        # (ip, port) -> Sock, per protocol
        self._udp_binds: dict[tuple[int, int], Sock] = {}
        self._tcp_binds: dict[tuple[int, int], Sock] = {}
        # AF_UNIX namespace, scoped per host: (host index, path) -> Sock
        self._unix_binds: dict[tuple[int, str], Sock] = {}
        self._latency_fn: Callable[[int, int], int] | None = None
        self._reliability_fn: Callable[[int, int], float] | None = None
        self.bootstrap_end = 0  # sim ns: no drops before this (worker.c:536)
        self.dns = None  # optional routing.dns.Dns for name resolution
        # CPU model (host/cpu.c analog): each serviced syscall costs the
        # host simulated processing time; once the accumulated delay
        # exceeds the threshold, the process's next completion is deferred
        # by it on the virtual clock (event.c:64-92 delay-blocking analog).
        self.cpu_ns_per_syscall = 0  # 0 = model off
        self.cpu_threshold_ns = 1_000
        # CPU↔TPU seam (procs/bridge.py): when set, non-loopback UDP rides
        # the device-stepped network (NIC/CoDel/latency/loss on device);
        # with bridge.with_tcp, TCP connections ride the device TCP machine
        self.bridge = None
        # per-connection send-buffer cap for device-carried TCP ends
        # (experimental.socket_send_buffer analog)
        self.socket_send_buffer = 131072
        self._dev_tcp: dict[tuple[int, int], BridgeEnd] = {}
        # connect-side ends awaiting their accept-side twin, keyed by
        # (host index, local port) — the accept-side establishment event
        # carries exactly that pair as (peer_host, peer_port)
        self._tcp_pending_conn: dict[tuple[int, int], BridgeEnd] = {}
        # heartbeat (manager.c:515-541 analog): period ns + callback(driver)
        self.heartbeat_interval: int | None = None
        self.heartbeat_fn: Callable[["ProcessDriver"], None] | None = None
        self._pcaps: dict[str, object] = {}  # host name -> PcapWriter
        self.counters = {
            "syscalls": 0,
            "packets_sent": 0,
            "packets_dropped": 0,
            "bytes_sent": 0,
        }
        # per-syscall tallies (use_syscall_counters analog: counter.rs
        # aggregation logged at exit, syscall_handler.c:109-121)
        self.syscall_counts: dict[int, int] = {}
        # per-handler wall-time accumulation (reference: -DUSE_PERF_TIMERS
        # GTimers around each syscall handler, syscall_handler.c:80-83);
        # enabled via use_perf_timers, reported at exit with the counts
        self.use_perf_timers = False
        self.syscall_times: dict[int, float] = {}
        # Runnable-process queue (reference analog: the worker pool's ready
        # queues, logical_processor.rs:17-68): the service loop visits only
        # processes with RUNNING/READY threads instead of scanning all N
        # procs per quiescence round — the O(N)-scan retirement that makes
        # 4k+ processes serviceable. Ordered by an EXPLICIT canonical key
        # (virtual time at mark, owning host gid, mark seq) — the same
        # (vt, gid, seq) key the multi-worker host plane merges by
        # (core/hostplane.py) — instead of registration order, which made
        # the service order depend on process creation history (a latent
        # nondeterminism hazard when runtime forks interleave with
        # static registration). `_runq_set` stays keyed by reg_idx for
        # idempotent marking.
        self._runq_heap: list[tuple[int, int, int, int]] = []
        self._runq_set: dict[int, ManagedProcess] = {}
        self._runq_seq = 0
        self._next_reg_idx = 0
        # Multi-worker host plane (core/hostplane.py): with host_workers
        # > 1 the service loop's IPC waits shard per owning host across
        # pinned workers — each worker blocks on its partition's shm
        # semaphores concurrently (the sem waits release the GIL), then
        # syscall EXECUTION stays on the coordinator in the canonical
        # runq order above, so two runs service identically.
        self.host_workers = max(1, int(host_workers))
        self._hostplane_obj = None
        self._hostplane_stats: dict | None = None
        self._prewaited: set[tuple[int, int]] = set()
        # fd-waiter registry: id(watched object) -> (obj, [(thread, Parked)])
        # — replaces the O(procs × fds) scan per wake (_wake_fd_waiters).
        # Entries are registered at park time and lazily pruned.
        self._fd_waiters: dict[int, tuple[object, list]] = {}
        # wall-clock budget per plane, logged at exit: where a managed-plane
        # second actually goes (service = syscall handling + channel waits,
        # device = bridge dispatches/readbacks, events = heap callbacks)
        self.plane_wall = {"service": 0.0, "device": 0.0, "events": 0.0}
        # Fault-tolerance plane (shadow_tpu/faults): supervised recovery
        # policy + deterministic injections. on_proc_failure governs what
        # the supervisor does when the IPC-timeout escalation ladder
        # exhausts: "abort" re-raises (the pre-fault-plane behavior),
        # "quarantine" marks the simulated host dead and keeps running.
        self.on_proc_failure = "abort"
        # extra timed waits (doubling backoff) before declaring a
        # non-responsive process wedged
        self.ipc_timeout_retries = 1
        self.fault_injector = None  # faults.FaultInjector (proc/file ops)
        self.fault_dir: str | None = None  # corrupt_file default base dir
        self.fault_counters = {
            "hosts_quarantined": 0,
            "procs_wedged": 0,
            "events_drained": 0,
            "ipc_retries": 0,
            "ipc_replies_refused": 0,
            "files_corrupted": 0,
        }

    # ------------------------------------------------------------------
    # build API
    # ------------------------------------------------------------------

    def add_host(self, name: str, ip: str | int) -> SimHost:
        h = SimHost(
            name=name,
            ip=ip if isinstance(ip, int) else ip_from_str(ip),
            index=len(self.hosts),
            # per-host nodeSeed derived from the controller master seed
            # (random.c:15-51 analog): same (seed, name) -> same stream
            rand=random.Random(f"{self.seed}:{name}"),
        )
        self.hosts.append(h)
        self._hosts_by_ip[h.ip] = h
        return h

    def add_process(
        self, host: SimHost, args: list[str], start_time: int = 0,
        env: dict | None = None, cwd: str | None = None,
        stop_time: int | None = None, stdout_path: str | None = None,
        stderr_path: str | None = None,
    ) -> ManagedProcess:
        p = ManagedProcess(
            name=f"{host.name}.{len(host.procs)}", args=args, host=host,
            start_time=start_time, env=env, cwd=cwd, stop_time=stop_time,
            stdout_path=stdout_path, stderr_path=stderr_path,
        )
        host.procs.append(p)
        p.reg_idx = self._next_reg_idx
        self._next_reg_idx += 1
        self.procs.append(p)
        return p

    def _register_proc(self, p: ManagedProcess) -> None:
        """Register a runtime-created process (fork child) for scheduling."""
        p.reg_idx = self._next_reg_idx
        self._next_reg_idx += 1
        self.procs.append(p)

    def _mark_runnable(self, p) -> None:
        """Queue p's process for the service loop (idempotent), keyed by
        the canonical (virtual time at mark, owning host gid, mark seq)
        order — explicit, not insertion order (the host plane's merge
        key, core/hostplane.py)."""
        proc = p.proc if isinstance(p, ManagedThread) else p
        idx = proc.reg_idx
        if idx not in self._runq_set:
            self._runq_set[idx] = proc
            self._runq_seq += 1
            gid = proc.host.index if proc.host is not None else 0
            heapq.heappush(
                self._runq_heap, (self.now, gid, self._runq_seq, idx)
            )

    def _hostplane(self):
        """The managed plane's drain-worker pool (core/hostplane.py), or
        None on the serial path (host_workers == 1)."""
        if self.host_workers <= 1:
            return None
        if self._hostplane_obj is None:
            from shadow_tpu.core import hostplane as hostplane_mod

            if self._hostplane_stats is None:
                self._hostplane_stats = hostplane_mod.new_stats(
                    self.host_workers
                )
            self._hostplane_obj = hostplane_mod.HostPlane(
                self.host_workers, self._hostplane_stats
            )
        return self._hostplane_obj

    def hostplane_stats(self) -> dict:
        """`hostplane.*` telemetry (metrics schema v15); {} until a
        sharded pre-wait ran (host_workers == 1 emits no keys)."""
        st = self._hostplane_stats
        return dict(st) if st is not None else {}

    def _prewait_runnable(self) -> None:
        """Fan the runnable processes' next IPC waits out per owning
        host across the host plane's pinned workers. Each worker blocks
        on its partition's request semaphores (libpthread sem waits
        release the GIL, so the waits genuinely overlap); a consumed
        semaphore is recorded in `_prewaited` — the buffered request is
        then read WITHOUT waiting when the coordinator services that
        thread, in unchanged canonical order."""
        if self.host_workers <= 1 or len(self._runq_set) < 2:
            return
        from shadow_tpu.core import hostplane as hostplane_mod

        targets = []
        for idx, p in self._runq_set.items():
            if p.host is not None and p.host.dead:
                continue
            for t in p.threads:
                if t.state == ManagedThread.RUNNING and t.channel:
                    key = (idx, t.tid)
                    if key not in self._prewaited:
                        targets.append(
                            (p.host.index if p.host is not None else 0,
                             key, t)
                        )
                    break
        if len(targets) < 2:
            return

        def _note(ok, key):
            if ok:
                self._prewaited.add(key)

        self._hostplane().drain([
            hostplane_mod.HostAction(
                self.now, gid,
                (lambda ch=t.channel: ch.wait_request(timeout_s=0.02)),
                (lambda ok, k=key: _note(ok, k)),
            )
            for gid, key, t in targets
        ])

    def set_latency_fn(self, fn: Callable[[int, int], int]) -> None:
        """fn(src_ip, dst_ip) -> one-way latency ns (topology hook)."""
        self._latency_fn = fn

    def set_reliability_fn(self, fn: Callable[[int, int], float]) -> None:
        """fn(src_ip, dst_ip) -> path reliability in [0,1] (topology hook:
        reference topology_getReliability, topology.c:2007)."""
        self._reliability_fn = fn

    # ------------------------------------------------------------------
    # event heap
    # ------------------------------------------------------------------

    def _schedule(self, t: int, cb: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, cb))

    def _latency(self, src_ip: int, dst_ip: int) -> int:
        if src_ip == dst_ip:
            return 0  # loopback: same-timestamp delivery (netif loopback path)
        if self._latency_fn is not None:
            return self._latency_fn(src_ip, dst_ip)
        return self.latency_ns

    def _drop_roll(self, src_ip: int, dst_ip: int, control: bool) -> bool:
        """True if the packet is dropped (reference: worker.c:536-545;
        zero-length control packets are never dropped, and nothing drops
        during the bootstrap warm-up phase)."""
        if control or src_ip == dst_ip or self.now < self.bootstrap_end:
            return False
        if self._reliability_fn is not None:
            rel = self._reliability_fn(src_ip, dst_ip)
            if rel >= 1.0:
                return False
            return self._rng.random() > rel
        if self.loss <= 0.0:
            return False
        return self._rng.random() < self.loss

    def _host_by_ip(self, ip: int) -> SimHost | None:
        return self._hosts_by_ip.get(ip)

    def _bridge_tcp(self) -> bool:
        return self.bridge is not None and self.bridge.with_tcp

    def _host_by_name(self, name: str) -> SimHost | None:
        for h in self.hosts:
            if h.name == name:
                return h
        return None

    # ------------------------------------------------------------------
    # readiness + wakeups (status_listener.c / syscall_condition.c analog)
    # ------------------------------------------------------------------

    def _fd_readable(self, proc, obj) -> bool:
        """Readiness of obj as OBSERVED by proc: objects whose readiness
        depends on the observing process (SignalFd after fork) expose
        readable_for(process); everything else falls back to readable().
        Every readiness call site must go through here, or a new site
        would silently judge a fork-inherited signalfd against its
        CREATOR's signal queue."""
        f = getattr(obj, "readable_for", None)
        if f is not None:
            return f(getattr(proc, "proc", proc))
        return obj.readable()

    def _poll_revents(self, proc: ManagedProcess, fd: int, events: int) -> int:
        # POLLIN/POLLOUT/POLLERR/POLLHUP share values with their EPOLL*
        # counterparts, so one readiness routine serves both interfaces.
        rev = 0
        obj = proc.fds.get(fd)
        if obj is None:
            return POLLERR if fd >= ipc.FD_BASE else 0
        if hasattr(obj, "readable"):
            if (events & POLLIN) and self._fd_readable(proc, obj):
                rev |= POLLIN
            if (events & POLLOUT) and obj.writable():
                rev |= POLLOUT
        if isinstance(obj, Sock):
            if obj.conn_refused:
                rev |= POLLERR  # reported regardless of requested events
            if obj.conn is not None and obj.conn.rx_eof and not obj.conn.rx:
                rev |= POLLHUP if (events & (POLLIN | POLLHUP)) else 0
            if obj.bend is not None and obj.bend.rx_eof and not obj.bend.rx:
                rev |= POLLHUP if (events & (POLLIN | POLLHUP)) else 0
        elif isinstance(obj, PipeEnd):
            if obj.is_read and obj.buf.write_closed and not obj.buf.data:
                rev |= POLLHUP
            if not obj.is_read and obj.buf.read_closed:
                rev |= POLLERR
        return rev

    EPOLLET = 1 << 31

    def _epoll_ready(self, proc: ManagedProcess, ep: Epoll,
                     maxevents: int | None = None) -> list[tuple[int, int]]:
        out = []
        for fd, (events, data) in sorted(ep.interest.items()):
            if maxevents is not None and len(out) >= maxevents:
                # stop BEFORE consuming further edges: an ET fd must not be
                # marked reported unless its event is actually delivered
                break
            obj = proc.fds.get(fd)
            if obj is None:
                continue  # closed fds silently leave the interest set
            rev = self._poll_revents(proc, fd, events)
            if not rev:
                continue
            if events & self.EPOLLET:
                # edge semantics (epoll.c:162-227 edge/level): report only
                # if new data/readiness arrived since the last report
                seq = getattr(obj, "wake_seq", 0)
                if ep.reported_seq.get(fd) == seq:
                    continue
                ep.reported_seq[fd] = seq
            out.append((rev, data))
        return out

    def _futex_wake(self, p: ManagedProcess, uaddr: int, n: int) -> int:
        """Wake up to n threads parked on (process, uaddr), in park order
        (futex.c FIFO wake semantics)."""
        q = p.futexes.get(uaddr)
        woken = 0
        while q and woken < n:
            t = q.pop(0)
            if (
                t.state == ManagedThread.PARKED
                and t.parked is not None
                and t.parked.kind == "futex"
            ):
                t.parked = None
                self._resume(t, 0)
                woken += 1
        if q is not None and not q:
            p.futexes.pop(uaddr, None)
        return woken

    def _waitpid(self, thread: "ManagedThread", target: int, nohang: bool,
                 park, done) -> None:
        """PSYS_WAITPID: emulated wait for a managed fork child (the shim
        never blocks — or polls — natively; both would leak wall-clock
        state into the simulation)."""
        p = thread.proc
        kids = [q for q in self.procs if q.parent is p]

        def match(q):
            return target in (-1, 0) or q.native_pid == target

        dead = [
            q for q in kids if match(q) and q.exited and not q.wait_reported
        ]
        if dead:
            q = dead[0]
            q.wait_reported = True
            done(q.native_pid or 0,
                 data=_wait_status(q).to_bytes(4, "little"))
        elif any(match(q) and q.alive() for q in kids):
            if nohang:
                done(0)
            else:
                park(Parked(thread, "waitpid", want=target))
        else:
            done(-errno.ECHILD)

    def _release_fds(self, p: ManagedProcess) -> None:
        """Drop p's fd table, tearing down objects no other live process
        still references (fork shares open descriptions)."""
        for fd in list(p.fds):
            obj = p.fds.pop(fd)
            still = any(
                o is obj
                for q in self.procs if q.alive()
                for o in q.fds.values()
            )
            if not still:
                self._close_obj(obj)

    def _exec_respawn(self, thread: "ManagedThread", data: bytes,
                      argc: int) -> None:
        """PSYS_EXEC: replace the process image by spawning the target as a
        FRESH managed process that keeps this ManagedProcess's virtual
        identity — fd table, native-pid bookkeeping, fork/waitpid linkage.
        Native execve is unsurvivable under the inherited seccomp filter
        (glibc startup hits trapped syscalls before any SIGSYS handler can
        exist), so exec is emulated at the driver, like everything else
        about process lifecycle (reference analog: process.c:460-531 spawns
        every image fresh too)."""
        p = thread.proc
        parts = data.split(b"\0")
        if len(parts) < 1 + argc:
            thread.channel.reply(-errno.EINVAL, sim_time_ns=self.now)
            return
        path = parts[0].decode("utf-8", "replace")
        argv = [
            x.decode("utf-8", "replace") for x in parts[1:1 + argc]
        ]
        envl = [
            x.decode("utf-8", "replace") for x in parts[1 + argc:] if x
        ]
        # resolve relative to the PROCESS's cwd, not the driver's
        full = path if os.path.isabs(path) else os.path.join(
            p.cwd or os.getcwd(), path
        )
        if not os.path.isfile(full) or not os.access(full, os.X_OK):
            thread.channel.reply(-errno.ENOENT, sim_time_ns=self.now)
            return
        # reply DIRECTLY (not via the CPU-delay deferral: the old threads
        # are retired below) — the old image _exits on receipt
        thread.channel.reply(0, sim_time_ns=self.now)
        if p.popen is not None:
            p.old_popens.append(p.popen)
            p.popen = None
        for t in p.threads:
            t.state = ManagedThread.EXITED
            if t.channel:
                t.channel.close()
                t.channel = None
        # close-on-exec: descriptors flagged cloexec do not survive
        for fd in [
            f for f, o in p.fds.items() if getattr(o, "cloexec", False)
        ]:
            obj = p.fds.pop(fd)
            still = any(
                o is obj
                for q in self.procs if q.alive()
                for o in q.fds.values()
            )
            if not still:
                self._close_obj(obj)
        new_ch = ipc.Channel()
        nt = ManagedThread(p, 0, new_ch)
        nt.state = ManagedThread.RUNNING  # HELLO incoming from the spawn
        nt.sig_mask = thread.sig_mask  # exec keeps the mask...
        p.sig_actions.clear()  # ...but resets handlers to default (POSIX)
        p.threads = [nt]
        self._mark_runnable(p)
        # exec semantics: the caller's envp REPLACES the environment; the
        # shim's own vars are forced on top so the new image is managed
        env = dict(kv.split("=", 1) for kv in envl if "=" in kv)
        env["LD_PRELOAD"] = str(build_mod.shim_path())
        env[ipc.ENV_SHM] = new_ch.path
        env.setdefault(ipc.ENV_SPIN, str(self.spin))
        env[ipc.ENV_SECCOMP] = "1" if self.use_seccomp else "0"
        if self.log_stamp:
            env[ipc.ENV_LOG_STAMP] = "1"
        if p.stdout_path is not None:
            out_f = open(p.stdout_path, "ab")
            err_f = open(p.stderr_path, "ab")
        else:
            out_f = err_f = subprocess.PIPE
        p.args = argv or [full]
        try:
            p.popen = subprocess.Popen(
                p.args, executable=full, env=env, cwd=p.cwd,
                stdout=out_f, stderr=err_f,
            )
        except OSError as e:
            # the old image already exited on our 0-reply; record the
            # failure instead of crashing the whole simulation
            log.logger.error(
                "exec respawn of %s failed: %s", full, e, host=p.host.name
            )
            p.exit_code = 127
            nt.state = ManagedThread.EXITED
            p.exited = True
            self._release_fds(p)
        if p.stdout_path is not None:
            out_f.close()
            err_f.close()

    def _try_complete_waitpid(self, t: "ManagedThread") -> None:
        if (
            t.state != ManagedThread.PARKED
            or t.parked is None
            or t.parked.kind != "waitpid"
        ):
            return
        target = t.parked.want
        kids = [q for q in self.procs if q.parent is t.proc]
        for q in kids:
            if (target in (-1, 0) or q.native_pid == target) and q.exited \
                    and not q.wait_reported:
                q.wait_reported = True
                t.parked = None
                self._resume(t, q.native_pid or 0,
                             data=_wait_status(q).to_bytes(4, "little"))
                return

    def _park(self, proc: ManagedProcess, pk: Parked) -> None:
        """Park proc's in-flight syscall on pk (no reply is sent until a
        wake or deadline; syscall_condition.c analog). fd-condition parks
        register in the waiter registry so wakes are O(waiters), not
        O(processes × fds)."""
        proc.parked = pk
        proc.state = ManagedProcess.PARKED
        self._register_waiter(proc, pk)
        if pk.deadline is not None:
            self._schedule(pk.deadline, lambda: self._fire_deadline(proc, pk))

    def _watch_objects(self, thread, pk: Parked) -> list:
        """The fd objects whose state changes could satisfy pk."""
        objs = []
        if pk.kind in ("recv", "read", "accept", "connect", "send"):
            o = thread.fds.get(pk.fd)
            if o is not None:
                objs.append(o)
        elif pk.kind == "poll":
            for fd, _ev in pk.pollset:
                o = thread.fds.get(fd)
                if o is not None:
                    objs.append(o)
        elif pk.kind == "epoll":
            ep = thread.fds.get(pk.epfd)
            if isinstance(ep, Epoll):
                objs.append(ep)
                for fd in ep.interest:
                    o = thread.fds.get(fd)
                    if o is not None:
                        objs.append(o)
        return objs

    def _register_waiter(self, thread, pk: Parked) -> None:
        for o in self._watch_objects(thread, pk):
            ent = self._fd_waiters.get(id(o))
            if ent is None:
                self._fd_waiters[id(o)] = (o, [(thread, pk)])
            else:
                ent[1].append((thread, pk))

    def _unregister_waiter(self, thread, pk: Parked) -> None:
        """Drop pk's registry entries after a non-wake unpark (deadline,
        signal EINTR, condition completion) so closed/idle objects don't
        pin stale waiter lists for the rest of the run."""
        for o in self._watch_objects(thread, pk):
            ent = self._fd_waiters.get(id(o))
            if ent is None:
                continue
            lst = [e for e in ent[1] if e[1] is not pk]
            if lst:
                self._fd_waiters[id(o)] = (ent[0], lst)
            else:
                del self._fd_waiters[id(o)]

    def _epoll_interest_added(self, proc, ep: "Epoll", fd: int) -> None:
        """EPOLL_CTL_ADD/MOD while sibling threads are parked on ep: extend
        their waiter registrations to the newly watched object."""
        ent = self._fd_waiters.get(id(ep))
        if not ent:
            return
        o = proc.fds.get(fd)
        if o is None:
            return
        for (t, pk) in ent[1]:
            if t.parked is pk and pk.kind == "epoll":
                e2 = self._fd_waiters.get(id(o))
                if e2 is None:
                    self._fd_waiters[id(o)] = (o, [(t, pk)])
                elif (t, pk) not in e2[1]:
                    e2[1].append((t, pk))

    def _bend_send(self, proc: ManagedProcess, end: "BridgeEnd",
                   chunk: bytes) -> int:
        """Queue chunk on a device-carried TCP end (space already checked)
        and notify the device machine; returns the byte count accepted."""
        self.counters["packets_sent"] += 1
        self.counters["bytes_sent"] += len(chunk)
        self._track_tx(
            proc.host, "tcp", end.local_addr, end.remote_addr, chunk,
            dropped=False,
        )
        end.tx_queue += chunk
        self.bridge.tcp_send(self.now, proc.host.index, end.slot, len(chunk))
        return len(chunk)

    def _try_wake(self, obj) -> None:
        """If a parked condition is now satisfied, complete the syscall and
        resume its thread (condition wakeup -> process_continue analog).
        Accepts a thread or a process; always scans every thread of the
        process, because any of them may be the one parked on the
        now-satisfied condition (e.g. a reader thread on a socket another
        thread wrote to)."""
        owner = obj.proc if isinstance(obj, ManagedThread) else obj
        for t in owner.threads:
            self._try_wake_thread(t)

    def _try_wake_thread(self, proc: ManagedThread) -> None:
        if proc.state != ManagedThread.PARKED or proc.parked is None:
            return
        pk = proc.parked
        try:
            self._try_wake_thread_inner(proc, pk)
        finally:
            if proc.parked is not pk:  # completed: purge registry entries
                self._unregister_waiter(proc, pk)

    def _try_wake_thread_inner(self, proc: ManagedThread,
                               pk: Parked) -> None:
        if pk.kind == "recv":
            sock = proc.fds.get(pk.fd)
            if isinstance(sock, Sock) and sock.readable():
                proc.parked = None
                self._complete_recv(proc, sock, pk.want, hdr=pk.hdr)
        elif pk.kind == "read":
            obj = proc.fds.get(pk.fd)
            if (obj is not None and hasattr(obj, "readable")
                    and self._fd_readable(proc, obj)):
                proc.parked = None
                self._complete_read(proc, obj, pk.want)
        elif pk.kind == "accept":
            sock = proc.fds.get(pk.fd)
            if isinstance(sock, Sock) and sock.accept_q:
                proc.parked = None
                self._complete_accept(proc, sock, bool(pk.want & SOCK_NONBLOCK))
        elif pk.kind == "connect":
            sock = proc.fds.get(pk.fd)
            if isinstance(sock, Sock) and (
                (sock.conn and sock.conn.established)
                or (sock.bend and sock.bend.established)
            ):
                proc.parked = None
                self._resume(proc, 0)
        elif pk.kind == "send":
            sock = proc.fds.get(pk.fd)
            if isinstance(sock, Sock) and sock.bend is not None:
                end = sock.bend
                if end.closed or not end.established:
                    # connection torn down while the writer was blocked:
                    # report bytes already accepted, else the error
                    proc.parked = None
                    self._resume(
                        proc, pk.want if pk.want > 0 else -errno.EPIPE
                    )
                    return
                space = end.send_space()
                if space > 0:
                    chunk = pk.data[:space]
                    self._bend_send(proc, end, chunk)
                    pk.want += len(chunk)
                    pk.data = pk.data[len(chunk):]
                    if not pk.data:
                        # whole payload buffered: blocking send completes
                        # with the full count (Linux stream semantics)
                        proc.parked = None
                        self._resume(proc, pk.want)
        elif pk.kind == "poll":
            results = [
                self._poll_revents(proc, fd, ev) for fd, ev in pk.pollset
            ]
            n = sum(1 for r in results if r)
            if n > 0:
                proc.parked = None
                data = b"".join(
                    int(r).to_bytes(2, "little", signed=True) for r in results
                )
                self._resume(proc, n, data=data)
        elif pk.kind == "epoll":
            ep = proc.fds.get(pk.epfd)
            if isinstance(ep, Epoll):
                ready = self._epoll_ready(proc, ep, pk.maxevents)
                if ready:
                    data = b"".join(_pack_epoll_event(ev, d) for ev, d in ready)
                    proc.parked = None
                    self._resume(proc, len(ready), data=data)

    def _fire_deadline(self, proc: ManagedProcess, pk: Parked) -> None:
        """Timeout event for a parked syscall (Timer trigger analog)."""
        if proc.state != ManagedProcess.PARKED or proc.parked is not pk:
            return  # already woken by data
        proc.parked = None
        self._unregister_waiter(proc, pk)
        if pk.kind == "sleep":
            self._resume(proc, 0)
        elif pk.kind == "poll":
            data = b"\x00\x00" * len(pk.pollset)
            self._resume(proc, 0, data=data)
        elif pk.kind == "epoll":
            self._resume(proc, 0)
        elif pk.kind == "futex":
            q = proc.proc.futexes.get(pk.want)
            if q is not None and proc in q:
                q.remove(proc)
            self._resume(proc, -errno.ETIMEDOUT)
        elif pk.kind in ("recv", "accept", "connect"):
            self._resume(proc, -errno.ETIMEDOUT)

    # ------------------------------------------------------------------
    # virtual signal plane (reference: syscall/signal.c + process signal
    # checks at resume points). Delivery is piggybacked on syscall replies:
    # the shim runs the registered handler at the syscall boundary — a
    # deterministic delivery point (no async interruption of app code).
    # ------------------------------------------------------------------

    def _next_signal(self, thread) -> tuple[int, int, int] | None:
        """Pop the first pending, unblocked, handler-registered signal of
        thread's process as a (signo, handler, flags) reply rider."""
        p = thread.proc if isinstance(thread, ManagedThread) else thread
        pend = p.sig_pending
        if not pend:
            return None
        mask = getattr(thread, "sig_mask", 0)
        for i, s in enumerate(pend):
            if (mask >> (s - 1)) & 1:
                continue  # blocked for this thread; stays pending
            act = p.sig_actions.get(s)
            if act is None or act[0] == 0:
                # Disposition reset to SIG_DFL after posting: POSIX delivers
                # under the CURRENT disposition — apply the default action
                # (terminate unless default-ignore), don't drop.
                pend.pop(i)
                if s not in _SIG_DFL_IGNORE:
                    self._schedule(self.now, lambda: self._signal_kill(p, s))
                return self._next_signal(thread)
            if act[0] == 1:  # SIG_IGN since posting: discard
                pend.pop(i)
                return self._next_signal(thread)
            pend.pop(i)
            flags = ipc.SIGF_SIGINFO if act[1] & SA_SIGINFO else 0
            # Auto-block during the handler (Linux semantics): the signal
            # itself (unless SA_NODEFER) plus the action's sa_mask are
            # blocked until the shim's PSYS_SIG_RETURN restores the mask.
            if isinstance(thread, ManagedThread):
                thread.sig_mask_stack.append(thread.sig_mask)
                thread.sig_mask |= act[2]
                if not act[1] & SA_NODEFER:
                    thread.sig_mask |= 1 << (s - 1)
            return (s, act[0], flags)
        return None

    def _post_signal(self, p: ManagedProcess, sig: int) -> None:
        """Deliver signal `sig` to process p (kill(2) / SIGCHLD analog)."""
        if not p.alive():
            return
        act = p.sig_actions.get(sig)
        if sig == SIGKILL or act is None or act[0] == 0:  # SIG_DFL
            if sig != SIGKILL and all(
                (t.sig_mask >> (sig - 1)) & 1 for t in p.threads
                if t.state != ManagedThread.EXITED
            ):
                # Blocked in every thread: POSIX keeps the signal PENDING
                # — INCLUDING default-ignore signals like SIGCHLD, whose
                # discard must happen at delivery/unblock time, not here
                # (the canonical signalfd pattern blocks SIGCHLD and
                # consumes child exits through the fd). _next_signal
                # applies the then-current disposition on unblock.
                if sig not in p.sig_pending:
                    p.sig_pending.append(sig)
                    self._wake_signalfds(p, sig)
                return
            if sig != SIGKILL and sig in _SIG_DFL_IGNORE:
                return
            # default disposition terminates at this sim time
            self._schedule(self.now, lambda: self._signal_kill(p, sig))
            return
        if act[0] == 1:  # SIG_IGN
            return
        if sig in p.sig_pending:
            return  # standard signals don't queue: already-pending collapses
        p.sig_pending.append(sig)
        self._wake_signalfds(p, sig)
        # interrupt the lowest-tid parked thread in an interruptible wait
        # whose mask admits the signal; the EINTR completion's reply
        # carries the handler invocation
        for t in p.threads:
            if (
                t.state == ManagedThread.PARKED
                and t.parked is not None
                and t.parked.kind in _SIG_INTERRUPTIBLE
                and not ((t.sig_mask >> (sig - 1)) & 1)
            ):
                pk = t.parked
                t.parked = None
                self._unregister_waiter(t, pk)
                if pk.kind == "futex":
                    q = p.futexes.get(pk.want)
                    if q is not None and t in q:
                        q.remove(t)
                ret = -errno.EINTR
                if pk.kind == "send" and pk.want > 0:
                    ret = pk.want  # partial write already accepted
                self._resume(t, ret)
                break

    def _wake_signalfds(self, p: ManagedProcess, sig: int) -> None:
        """A newly-pending signal makes matching signalfds readable: wake
        their parked readers and bump EPOLLET edges."""
        for o in p.fds.values():
            if isinstance(o, SignalFd) and (o.mask >> (sig - 1)) & 1:
                self._wake_fd_waiters(o)

    def _signal_kill(self, p: ManagedProcess, sig: int) -> None:
        """Terminate p by default signal disposition: release fds, stop the
        native image (fork children included — MSG_STOP works on any parked
        channel), record the signaled wait status, and notify the parent
        (waitpid completion + SIGCHLD), exactly like a natural exit would."""
        if not p.alive():
            return
        p.killed_by_signal = sig
        self._release_fds(p)
        stopped = False
        for t in p.threads:
            if t.state == ManagedThread.PARKED and t.channel and t.parked:
                t.channel.reply(128 + sig, sim_time_ns=self.now,
                                msg_type=ipc.MSG_STOP)
                t.parked = None
                stopped = True
                break
        for t in p.threads:
            t.state = ManagedThread.EXITED
        p.exited = True
        p.exit_code = 128 + sig  # shell-style exit code; wait status is sig
        if p.popen is not None:
            if stopped:
                try:
                    p.popen.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.popen.terminate()
            else:
                p.popen.terminate()
            p.stdout, p.stderr = p.finish()
        if p.parent is not None:
            for t in p.parent.threads:
                self._try_complete_waitpid(t)
            self._post_signal(p.parent, SIGCHLD)

    def _proc_by_pid(self, caller, pid: int) -> ManagedProcess | None:
        """Resolve a kill(2) target: 0 = self; otherwise match the native
        pid recorded at HELLO (what fork returned to the app)."""
        if pid in (0, caller.proc.native_pid):
            return caller.proc
        for q in self.procs:
            if q.native_pid == pid and q.alive():
                return q
        return None

    def _resume(self, proc: ManagedThread, ret: int, data: bytes = b"") -> None:
        """Complete a previously-blocked syscall. If no other thread of the
        process is running app code, reply immediately (the thread runs);
        otherwise defer the reply (state READY) until the running thread
        blocks — at most one thread of a process executes between syscalls,
        which is what keeps multithreaded apps deterministic."""
        if not proc.alive() or proc.channel is None:
            return  # stopped/exited while the completion was in flight
        owner = proc.proc if isinstance(proc, ManagedThread) else proc
        running = any(
            t is not proc and t.state == ManagedThread.RUNNING
            for t in owner.threads
        )
        if running:
            proc.pending = (ret, data)
            proc.state = ManagedThread.READY
            self._mark_runnable(proc)
            return
        proc.channel.reply(ret, sim_time_ns=self.now, data=data,
                           signal=self._next_signal(proc))
        proc.state = ManagedThread.RUNNING
        self._mark_runnable(proc)

    def _release_ready(self, p: ManagedProcess) -> ManagedThread | None:
        """If no thread of p is running, hand the run token to the lowest-
        tid READY thread (deterministic choice) by posting its deferred
        reply. Returns the released thread, or None."""
        if any(t.state == ManagedThread.RUNNING for t in p.threads):
            return None
        for t in p.threads:
            if t.state == ManagedThread.READY and t.pending is not None:
                ret, data = t.pending
                t.pending = None
                if t.channel is None:
                    t.state = ManagedThread.EXITED
                    continue
                t.channel.reply(ret, sim_time_ns=self.now, data=data,
                                signal=self._next_signal(t))
                t.state = ManagedThread.RUNNING
                self._mark_runnable(t)
                return t
        return None

    def _wake_sock_waiters(self, sock: Sock) -> None:
        self._wake_fd_waiters(sock)

    def _wake_pipe_readers(self, buf) -> None:
        for q in self.procs:
            if not q.alive():
                continue
            for o in q.fds.values():
                if isinstance(o, PipeEnd) and o.buf is buf and o.is_read:
                    self._wake_fd_waiters(o)

    def _wake_fd_waiters(self, obj) -> None:
        """Wake any thread parked on obj via the waiter registry (registered
        at park time — fork children share open descriptions, so waiters may
        belong to any process). O(registered waiters) instead of the old
        O(processes × fds) scan; stale entries (already resumed) are pruned
        lazily. Wake order is park order — deterministic."""
        try:
            obj.wake_seq = getattr(obj, "wake_seq", 0) + 1  # EPOLLET edges
        except AttributeError:
            pass  # slotted/frozen objects: stay level-triggered
        owner = getattr(obj, "owner", None)
        if owner is not None:
            self._try_wake(owner)
        ent = self._fd_waiters.get(id(obj))
        if ent is None:
            return
        keep = []
        for (t, pk) in ent[1]:
            if t.parked is pk and t.state == ManagedThread.PARKED:
                self._try_wake_thread(t)
                if t.parked is pk and t.state == ManagedThread.PARKED:
                    keep.append((t, pk))  # condition not satisfied yet
        if keep:
            self._fd_waiters[id(obj)] = (obj, keep)
        else:
            del self._fd_waiters[id(obj)]

    # ------------------------------------------------------------------
    # per-host tracking + pcap (tracker.c / pcap_writer.c analogs)
    # ------------------------------------------------------------------

    def _pcap_writer(self, host: SimHost):
        if host.pcap_dir is None:
            return None
        w = self._pcaps.get(host.name)
        if w is None:
            from shadow_tpu.utils.pcap import PcapWriter

            os.makedirs(host.pcap_dir, exist_ok=True)
            w = PcapWriter(os.path.join(host.pcap_dir, f"{host.name}.pcap"))
            self._pcaps[host.name] = w
        return w

    def _track_tx(self, host: SimHost, proto: str, src_addr, dst_addr,
                  payload: bytes, dropped: bool) -> None:
        t = host.tracker
        if dropped:
            t["dropped_packets"] += 1
        else:
            t["tx_packets"] += 1
            t["tx_bytes"] += len(payload)
        w = self._pcap_writer(host)
        if w is not None and not dropped:
            w.write_packet(
                self.now, proto=proto,
                src_ip=src_addr[0], src_port=src_addr[1],
                dst_ip=dst_addr[0], dst_port=dst_addr[1], payload=payload,
            )

    def _track_rx(self, dst_ip: int, proto: str, src_addr, dst_addr,
                  payload: bytes) -> None:
        host = self._host_by_ip(dst_ip)
        if host is None:
            return
        t = host.tracker
        t["rx_packets"] += 1
        t["rx_bytes"] += len(payload)
        w = self._pcap_writer(host)
        if w is not None:
            w.write_packet(
                self.now, proto=proto,
                src_ip=src_addr[0], src_port=src_addr[1],
                dst_ip=dst_addr[0], dst_port=dst_addr[1], payload=payload,
            )

    def host_trackers(self) -> dict[str, dict]:
        return {h.name: dict(h.tracker) for h in self.hosts}

    # ------------------------------------------------------------------
    # network delivery (stage-A model)
    # ------------------------------------------------------------------

    def _deliver_dgram(self, src_addr, dst_addr, payload: bytes) -> None:
        dst_host = self._host_by_ip(dst_addr[0])
        if dst_host is not None and dst_host.dead:
            # quarantined host: in-flight deliveries drain at their event
            # time, like packets arriving at a crashed machine
            self.fault_counters["events_drained"] += 1
            return
        sock = self._udp_binds.get(dst_addr)
        if sock is None or not sock.owner.alive():
            return  # no listener: datagram vanishes (no ICMP in v1)
        if sock.peer is not None and sock.peer != src_addr:
            return
        self._track_rx(dst_addr[0], "udp", src_addr, dst_addr, payload)
        sock.dgrams.append((src_addr[0], src_addr[1], payload))
        self._wake_sock_waiters(sock)

    def _deliver_syn(self, src_sock: Sock, src_addr, dst_addr) -> None:
        listener = self._tcp_binds.get(dst_addr)
        if listener is None or not listener.listening or not listener.owner.alive():
            # RST path: fail the connect after another RTT
            lat = self._latency(dst_addr[0], src_addr[0])
            self._schedule(
                self.now + lat, lambda: self._fail_connect(src_sock)
            )
            return
        # create the child endpoint on the listener side
        child = Conn(
            established=True,
            remote=src_sock.conn,
            remote_addr=src_addr,
            local_addr=dst_addr,
        )
        if src_sock.conn is not None:
            src_sock.conn.remote = child
        listener.accept_q.append(child)
        self._wake_sock_waiters(listener)
        # SYN-ACK back
        lat = self._latency(dst_addr[0], src_addr[0])
        self._schedule(
            self.now + lat, lambda: self._complete_connect(src_sock)
        )

    def _fail_connect(self, sock: Sock) -> None:
        if sock.conn is not None:
            sock.conn.rx_eof = True
        sock.connecting = False
        sock.conn_refused = True
        p = sock.owner
        if (
            p.state == ManagedProcess.PARKED
            and p.parked is not None
            and p.parked.kind == "connect"
            and p.parked.fd == sock.fd
        ):
            p.parked = None
            self._resume(p, -errno.ECONNREFUSED)
        else:
            # nonblocking connect: surface POLLERR/EPOLLERR to pollers
            self._wake_sock_waiters(sock)

    def _complete_connect(self, sock: Sock) -> None:
        if sock.conn is None:
            return
        sock.conn.established = True
        sock.connecting = False
        self._wake_sock_waiters(sock)

    def _deliver_stream(self, conn: Conn, payload: bytes) -> None:
        if conn.local_addr is not None:
            h = self._host_by_ip(conn.local_addr[0])
            if h is not None and h.dead:
                self.fault_counters["events_drained"] += 1
                return
        if conn.local_addr is not None:
            self._track_rx(
                conn.local_addr[0], "tcp",
                conn.remote_addr or (0, 0), conn.local_addr, payload,
            )
        conn.rx += payload
        if conn.sock is not None:
            self._wake_sock_waiters(conn.sock)
        # conn.sock is None while the endpoint sits un-accepted in the
        # accept queue: bytes buffer silently until accept() wraps it

    def _deliver_eof(self, conn: Conn) -> None:
        conn.rx_eof = True
        if conn.sock is not None:
            self._wake_sock_waiters(conn.sock)


    # ------------------------------------------------------------------
    # syscall dispatch (syscallhandler_make_syscall analog)
    # ------------------------------------------------------------------

    def _ephemeral_port(self, host: SimHost) -> int:
        # skip ports already bound on this host (either protocol) so an
        # ephemeral allocation never clobbers an explicit bind
        while (
            (host.ip, host.next_port) in self._udp_binds
            or (host.ip, host.next_port) in self._tcp_binds
        ):
            host.next_port += 1
        port = host.next_port
        host.next_port += 1
        return port

    def _ensure_bound(self, proc: ManagedProcess, sock: Sock) -> None:
        if sock.bound is None:
            port = self._ephemeral_port(proc.host)
            sock.bound = (proc.host.ip, port)
            binds = self._udp_binds if sock.proto == SOCK_DGRAM else self._tcp_binds
            binds[sock.bound] = sock
            if self.bridge is not None and sock.proto == SOCK_DGRAM:
                if not self.bridge.bind(proc.host.index, port):
                    raise DriverError(
                        f"{proc.host.name}: device UDP socket table full "
                        f"(raise experimental.sockets_per_host)"
                    )

    def _dispatch(self, proc: ManagedProcess) -> None:
        """Handle one MSG_SYSCALL from proc (with optional per-handler wall
        timing — the USE_PERF_TIMERS analog, syscall_handler.c:80-83)."""
        try:
            if not self.use_perf_timers:
                return self._dispatch_inner(proc)
            sysno = proc.channel.sysno
            t0 = wall_time.perf_counter()
            try:
                return self._dispatch_inner(proc)
            finally:
                self.syscall_times[sysno] = self.syscall_times.get(
                    sysno, 0.0
                ) + (wall_time.perf_counter() - t0)
        except FdLimitError as e:
            # virtual RLIMIT_NOFILE clamp (alloc_fd): the app observes
            # EMFILE — consistent with the limit its getrlimit() reports
            log.logger.warning("%s: %s", proc.name, e, host=proc.host.name)
            proc.channel.reply(-errno.EMFILE, sim_time_ns=self.now)

    def _dispatch_inner(self, proc: ManagedProcess) -> None:
        """Handle one MSG_SYSCALL from proc. Either replies (proc keeps
        running) or parks it (reply deferred until a condition fires)."""
        ch = proc.channel
        sysno = ch.sysno
        a = ch.args
        self.counters["syscalls"] += 1
        self.syscall_counts[sysno] = self.syscall_counts.get(sysno, 0) + 1

        if self.cpu_ns_per_syscall:
            proc.host.cpu_unapplied += self.cpu_ns_per_syscall

        def done(ret: int, data: bytes = b"") -> None:
            host = proc.host
            if self.cpu_ns_per_syscall and (
                host.cpu_unapplied > self.cpu_threshold_ns
            ):
                # apply the accumulated CPU delay: defer this completion on
                # the virtual clock (the process "computes" meanwhile)
                delay = host.cpu_unapplied
                host.cpu_unapplied = 0
                proc.state = ManagedProcess.PARKED
                self._schedule(
                    self.now + delay,
                    lambda: self._resume(proc, ret, data=data),
                )
                return
            ch.reply(ret, sim_time_ns=self.now, data=data,
                     signal=self._next_signal(proc))

        def park(pk: Parked) -> None:
            self._park(proc, pk)

        # ---- time ----
        if sysno == SYS_clock_gettime:
            done(self.now)
        elif sysno == SYS_nanosleep:
            dur = max(0, a[0])
            park(Parked(proc, "sleep", deadline=self.now + dur))
        # ---- socket lifecycle ----
        elif sysno == SYS_socket:
            stype = a[1] & 0xFF
            if stype not in (SOCK_STREAM, SOCK_DGRAM):
                done(-errno.EPROTONOSUPPORT)
                return
            fd = proc.alloc_fd()
            sock = Sock(fd=fd, proto=stype, owner=proc,
                        family=(AF_UNIX if a[0] == AF_UNIX else AF_INET),
                        nonblock=bool(a[1] & SOCK_NONBLOCK),
                        cloexec=bool(a[1] & 0o2000000))  # SOCK_CLOEXEC
            proc.fds[fd] = sock
            done(fd)
        elif sysno == SYS_socketpair:
            # AF_UNIX socketpair (reference: descriptor/channel.c legacy
            # unix-socketpair analog): two connected endpoints, zero-latency
            # local delivery. Streams link Conn twins; datagrams link via
            # `pair`.
            stype = a[1] & 0xFF
            if stype not in (SOCK_STREAM, SOCK_DGRAM):
                done(-errno.EPROTONOSUPPORT)
                return
            nb = bool(a[1] & SOCK_NONBLOCK)
            cx = bool(a[1] & 0o2000000)  # SOCK_CLOEXEC
            fd1 = proc.alloc_fd()
            fd2 = proc.alloc_fd()
            s1 = Sock(fd=fd1, proto=stype, owner=proc, family=AF_UNIX,
                      nonblock=nb, cloexec=cx)
            s2 = Sock(fd=fd2, proto=stype, owner=proc, family=AF_UNIX,
                      nonblock=nb, cloexec=cx)
            addr = (proc.host.ip, 0)
            if stype == SOCK_STREAM:
                c1 = Conn(established=True, local_addr=addr,
                          remote_addr=addr, sock=s1, unix=True)
                c2 = Conn(established=True, local_addr=addr,
                          remote_addr=addr, sock=s2, unix=True)
                c1.remote = c2
                c2.remote = c1
                s1.conn = c1
                s2.conn = c2
            else:
                s1.pair = s2
                s2.pair = s1
            proc.fds[fd1] = s1
            proc.fds[fd2] = s2
            done(0, data=struct_mod.pack("<ii", fd1, fd2))
        elif sysno == SYS_bind:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            if sock.family == AF_UNIX:
                path = ch.data.decode("utf-8", "replace")
                if not path:
                    done(-errno.EINVAL)
                    return
                key = (proc.host.index, path)
                if key in self._unix_binds:
                    done(-errno.EADDRINUSE)
                    return
                sock.unix_path = path
                sock.bound = (proc.host.ip, 0)
                self._unix_binds[key] = sock
                done(0)
                return
            ip, port = a[1], a[2]
            if ip == 0:  # INADDR_ANY -> this host's address
                ip = proc.host.ip
            if ip == 0x7F000001:  # loopback binds resolve to host ip in v1
                ip = proc.host.ip
            if port == 0:
                port = self._ephemeral_port(proc.host)
            binds = self._udp_binds if sock.proto == SOCK_DGRAM else self._tcp_binds
            if (ip, port) in binds:
                done(-errno.EADDRINUSE)
                return
            if self.bridge is not None and sock.proto == SOCK_DGRAM:
                if not self.bridge.bind(proc.host.index, port):
                    # device socket table full: refuse loudly rather than
                    # silently blackholing inbound traffic
                    done(-errno.ENOBUFS)
                    return
            sock.bound = (ip, port)
            binds[(ip, port)] = sock
            done(0)
        elif sysno == SYS_listen:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock) or sock.proto != SOCK_STREAM:
                done(-errno.EBADF)
                return
            if sock.family == AF_UNIX:
                if sock.unix_path is None:
                    done(-errno.EINVAL)  # autobind unsupported
                    return
                sock.listening = True
                done(0)
                return
            self._ensure_bound(proc, sock)
            if self._bridge_tcp() and sock.dev_listen_slot is None:
                # install the device-side listener so remote SYNs demux
                lslot = self.bridge.tcp_listen(proc.host.index, sock.bound[1])
                if lslot is None:
                    done(-errno.ENOBUFS)
                    return
                sock.dev_listen_slot = lslot
            sock.listening = True
            done(0)
        elif sysno == SYS_connect:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            if sock.family == AF_UNIX:
                if sock.conn is not None:
                    done(-errno.EISCONN)
                    return
                path = ch.data.decode("utf-8", "replace")
                lst = self._unix_binds.get((proc.host.index, path))
                if lst is None or not lst.listening:
                    done(-errno.ECONNREFUSED)
                    return
                # unix connect completes once queued on the listener's
                # backlog (zero latency; Linux semantics)
                addr = (proc.host.ip, 0)
                cc = Conn(established=True, local_addr=addr,
                          remote_addr=addr, sock=sock, unix=True)
                sc = Conn(established=True, local_addr=addr,
                          remote_addr=addr, unix=True)
                cc.remote = sc
                sc.remote = cc
                sock.conn = cc
                lst.accept_q.append(sc)
                self._wake_sock_waiters(lst)
                done(0)
                return
            ip, port = a[1], a[2]
            if ip == 0x7F000001:
                ip = proc.host.ip
            if sock.proto == SOCK_DGRAM:
                sock.peer = (ip, port)
                self._ensure_bound(proc, sock)
                done(0)
                return
            if sock.conn is not None or sock.bend is not None or sock.connecting:
                done(-errno.EISCONN)
                return
            self._ensure_bound(proc, sock)
            dst_sim = self._host_by_ip(ip)
            if (
                self._bridge_tcp()
                and ip != proc.host.ip
                and dst_sim is not None
            ):
                # the device TCP machine carries this connection: handshake,
                # pacing, loss recovery and delivery timing all on-device
                hidx = proc.host.index
                slot = self.bridge.tcp_alloc_slot(hidx)
                if slot is None:
                    log.logger.warning(
                        "%s: no free device TCP slot (listeners + "
                        "connections in TIME_WAIT hold them); raise "
                        "experimental.sockets_per_host", proc.host.name,
                    )
                    done(-errno.ENOBUFS)
                    return
                end = BridgeEnd(
                    host=proc.host, slot=slot, sock=sock,
                    local_addr=sock.bound, remote_addr=(ip, port),
                    sndbuf=self.socket_send_buffer, born_t=self.now,
                )
                sock.bend = end
                sock.connecting = True
                self._dev_tcp[(hidx, slot)] = end
                self._tcp_pending_conn[(hidx, sock.bound[1])] = end
                self.bridge.tcp_connect(
                    self.now, hidx, slot, dst_sim.index, port, sock.bound[1]
                )
                if sock.nonblock:
                    done(-errno.EINPROGRESS)
                else:
                    park(Parked(proc, "connect", fd=sock.fd))
                return
            sock.conn = Conn(local_addr=sock.bound, remote_addr=(ip, port),
                             sock=sock)
            sock.connecting = True
            lat = self._latency(proc.host.ip, ip)
            dst = (ip, port)
            src = sock.bound
            self._schedule(
                self.now + lat, lambda: self._deliver_syn(sock, src, dst)
            )
            if sock.nonblock:
                done(-errno.EINPROGRESS)
            else:
                park(Parked(proc, "connect", fd=sock.fd))
        elif sysno in (SYS_accept, SYS_accept4):
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock) or not sock.listening:
                done(-errno.EINVAL)
                return
            child_nonblock = bool(a[1] & SOCK_NONBLOCK)
            if sock.accept_q:
                self._complete_accept(proc, sock, child_nonblock)
            elif sock.nonblock:
                done(-errno.EAGAIN)
            else:
                park(Parked(proc, "accept", fd=sock.fd, want=a[1]))
        elif sysno == SYS_close:
            obj = proc.fds.pop(a[0], None)
            if obj is None:
                done(-errno.EBADF)
                return
            # dup aliases AND fork sharing: only tear the object down when
            # NO live process's fd table still references it (fork children
            # share open descriptions across arbitrary generations)
            still = any(
                o is obj
                for q in self.procs if q.alive()
                for o in q.fds.values()
            )
            if not still:
                self._close_obj(obj)
            done(0)
        elif sysno in (SYS_dup, SYS_dup2, SYS_dup3):
            obj = proc.fds.get(a[0])
            if obj is None:
                done(-errno.EBADF)
                return
            if sysno == SYS_dup:
                newfd = proc.alloc_fd()
            else:
                newfd = a[1]
                if newfd == a[0]:
                    done(newfd if sysno == SYS_dup2 else -errno.EINVAL)
                    return
                if newfd < ipc.FD_BASE:
                    # aliasing into native fd space would escape the shim's
                    # managed-fd routing; refuse loudly rather than misroute
                    done(-errno.EINVAL)
                    return
                old = proc.fds.pop(newfd, None)
                if old is not None and not any(
                    o is old for o in proc.fds.values()
                ):
                    self._close_obj(old)
            proc.fds[newfd] = obj
            done(newfd)
        elif sysno == SYS_shutdown:
            sock = proc.fds.get(a[0])
            if isinstance(sock, Sock):
                if sock.bend is not None:
                    self._bridge_close_end(sock.bend)
                elif sock.conn is not None:
                    self._send_eof(proc, sock)
            done(0)
        # ---- data plane ----
        elif sysno == SYS_sendto:
            self._handle_sendto(proc, a, ch.data)
        elif sysno == SYS_recvfrom:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            if sock.proto == SOCK_STREAM and (
                sock.listening or (sock.conn is None and sock.bend is None)
            ):
                done(-errno.ENOTCONN)
                return
            if sock.readable():
                # covers rx_eof too: _complete_recv returns 0 on drained+EOF
                self._complete_recv(proc, sock, a[1])
            elif sock.nonblock:
                done(-errno.EAGAIN)
            else:
                park(Parked(proc, "recv", fd=sock.fd, want=a[1]))
        # ---- metadata ----
        elif sysno == SYS_getsockname:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            ip, port = sock.bound or (proc.host.ip, 0)
            done(0, data=ip.to_bytes(4, "little") + port.to_bytes(2, "little"))
        elif sysno == SYS_getpeername:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            addr = None
            if sock.bend is not None:
                addr = sock.bend.remote_addr
            elif sock.conn is not None:
                addr = sock.conn.remote_addr
            elif sock.peer is not None:
                addr = sock.peer
            if addr is None:
                done(-errno.ENOTCONN)
                return
            done(0, data=addr[0].to_bytes(4, "little")
                 + addr[1].to_bytes(2, "little"))
        elif sysno == SYS_setsockopt:
            done(0)  # buffer-size etc. accepted and ignored in v1
        elif sysno == SYS_getsockopt:
            sock = proc.fds.get(a[0])
            refused = isinstance(sock, Sock) and sock.conn_refused
            done(errno.ECONNREFUSED if refused else 0)  # SO_ERROR
        elif sysno == SYS_fcntl:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            cmd, arg = a[1], a[2]
            if cmd == F_GETFL:
                done(O_NONBLOCK if sock.nonblock else 0)
            elif cmd == F_SETFL:
                sock.nonblock = bool(arg & O_NONBLOCK)
                done(0)
            elif cmd == 1:  # F_GETFD
                done(1 if sock.cloexec else 0)
            elif cmd == 2:  # F_SETFD
                sock.cloexec = bool(arg & 1)  # FD_CLOEXEC
                done(0)
            else:
                done(0)
        elif sysno == SYS_ioctl:
            sock = proc.fds.get(a[0])
            if not isinstance(sock, Sock):
                done(-errno.EBADF)
                return
            if a[1] == FIONREAD:
                n = 0
                if sock.proto == SOCK_DGRAM and sock.dgrams:
                    n = len(sock.dgrams[0][2])
                elif sock.bend is not None:
                    n = len(sock.bend.rx)
                elif sock.conn is not None:
                    n = len(sock.conn.rx)
                done(n)
            else:
                done(-errno.EINVAL)
        # ---- readiness ----
        elif sysno == SYS_epoll_create1:
            fd = proc.alloc_fd()
            proc.fds[fd] = Epoll(fd=fd, owner=proc)
            done(fd)
        elif sysno == SYS_epoll_ctl:
            ep = proc.fds.get(a[0])
            if not isinstance(ep, Epoll):
                done(-errno.EBADF)
                return
            op, fd, events, data = a[1], a[2], a[3], a[4]
            if op == EPOLL_CTL_ADD or op == EPOLL_CTL_MOD:
                ep.interest[fd] = (events, data)
                ep.reported_seq.pop(fd, None)
                self._epoll_interest_added(proc, ep, fd)
                done(0)
            elif op == EPOLL_CTL_DEL:
                ep.interest.pop(fd, None)
                ep.reported_seq.pop(fd, None)
                done(0)
            else:
                done(-errno.EINVAL)
        elif sysno == SYS_epoll_wait:
            ep = proc.fds.get(a[0])
            if not isinstance(ep, Epoll):
                done(-errno.EBADF)
                return
            maxevents, timeout_ms = a[1], a[2]
            ready = self._epoll_ready(proc, ep, maxevents)
            if ready:
                data = b"".join(_pack_epoll_event(ev, d) for ev, d in ready)
                done(len(ready), data=data)
            elif timeout_ms == 0:
                done(0)
            else:
                deadline = (
                    None if timeout_ms < 0
                    else self.now + timeout_ms * 1_000_000
                )
                park(Parked(proc, "epoll", epfd=a[0], maxevents=maxevents,
                            deadline=deadline))
        elif sysno == SYS_poll:
            nfds, timeout_ms = a[0], a[1]
            raw = ch.data
            pollset = []
            for i in range(nfds):
                fd = int.from_bytes(raw[i * 6:i * 6 + 4], "little", signed=True)
                ev = int.from_bytes(raw[i * 6 + 4:i * 6 + 6], "little",
                                    signed=True)
                pollset.append((fd, ev))
            results = [self._poll_revents(proc, fd, ev) for fd, ev in pollset]
            n = sum(1 for r in results if r)
            if n > 0:
                data = b"".join(
                    int(r).to_bytes(2, "little", signed=True) for r in results
                )
                done(n, data=data)
            elif timeout_ms == 0:
                done(0, data=b"\x00\x00" * nfds)
            else:
                deadline = (
                    None if timeout_ms < 0
                    else self.now + timeout_ms * 1_000_000
                )
                park(Parked(proc, "poll", pollset=pollset, deadline=deadline))
        # ---- generic fd read/write (pipes, eventfds, timerfds, sockets) ----
        elif sysno == SYS_read:
            obj = proc.fds.get(a[0])
            want = a[1]
            if obj is None:
                done(-errno.EBADF)
            elif isinstance(obj, Sock):
                if obj.proto == SOCK_STREAM and (
                    obj.listening or (obj.conn is None and obj.bend is None)
                ):
                    done(-errno.ENOTCONN)
                elif obj.readable():
                    self._complete_recv(proc, obj, want, hdr=False)
                elif obj.nonblock:
                    done(-errno.EAGAIN)
                else:
                    park(Parked(proc, "recv", fd=a[0], want=want, hdr=False))
            elif isinstance(obj, PipeEnd) and not obj.is_read:
                done(-errno.EBADF)
            elif isinstance(obj, (EventFd, TimerFd)) and want < 8:
                done(-errno.EINVAL)  # Linux: 8-byte counter reads only
            elif isinstance(obj, SignalFd) and want < 128:
                done(-errno.EINVAL)  # Linux: whole signalfd_siginfo reads
            elif hasattr(obj, "readable"):
                if self._fd_readable(proc, obj):
                    self._complete_read(proc, obj, want)
                elif obj.nonblock:
                    done(-errno.EAGAIN)
                else:
                    park(Parked(proc, "read", fd=a[0], want=want))
            else:
                done(-errno.EBADF)
        elif sysno == SYS_write:
            obj = proc.fds.get(a[0])
            data = ch.data[: a[1]]
            if obj is None:
                done(-errno.EBADF)
            elif isinstance(obj, Sock):
                self._handle_sendto(proc, [a[0], a[1], 0, 0, 0, 0], data)
            elif isinstance(obj, PipeEnd):
                if obj.is_read:
                    done(-errno.EBADF)
                elif obj.buf.read_closed:
                    done(-errno.EPIPE)
                else:
                    obj.buf.data += data
                    done(len(data))
                    self._wake_pipe_readers(obj.buf)
            elif isinstance(obj, EventFd):
                if len(data) < 8:
                    done(-errno.EINVAL)
                else:
                    add = int.from_bytes(data[:8], "little")
                    if add == (1 << 64) - 1:
                        done(-errno.EINVAL)  # Linux: 0xffffffffffffffff
                    elif obj.value + add > (1 << 64) - 2:
                        # counter would overflow; Linux blocks — we report
                        # EAGAIN (blocking eventfd writes are not supported)
                        done(-errno.EAGAIN)
                    else:
                        obj.value += add
                        done(8)
                        self._wake_fd_waiters(obj)
            else:
                done(-errno.EBADF)
        # ---- pipes / eventfd / timerfd / randomness ----
        elif sysno == SYS_pipe2:
            nb = bool(a[0] & O_NONBLOCK_FLAG)
            buf = PipeBuf()
            rfd = proc.alloc_fd()
            wfd = proc.alloc_fd()
            ce = bool(a[1] & 0o2000000)  # O_CLOEXEC
            proc.fds[rfd] = PipeEnd(rfd, proc, buf, is_read=True, nonblock=nb,
                                    cloexec=ce)
            proc.fds[wfd] = PipeEnd(wfd, proc, buf, is_read=False,
                                    nonblock=nb, cloexec=ce)
            done(0, data=rfd.to_bytes(4, "little") + wfd.to_bytes(4, "little"))
        elif sysno == SYS_eventfd2:
            fd = proc.alloc_fd()
            proc.fds[fd] = EventFd(
                fd, proc, value=a[0],
                semaphore=bool(a[1] & EFD_SEMAPHORE),
                nonblock=bool(a[1] & O_NONBLOCK_FLAG),
            )
            done(fd)
        elif sysno == SYS_timerfd_create:
            fd = proc.alloc_fd()
            proc.fds[fd] = TimerFd(
                fd, proc, nonblock=bool(a[1] & O_NONBLOCK_FLAG)
            )
            done(fd)
        elif sysno == SYS_signalfd4:
            # data = 8-byte little-endian sigset; a[0] = -1 (new) or an
            # existing signalfd whose mask is replaced (Linux semantics)
            mask = int.from_bytes(ch.data[:8], "little")
            if a[0] == -1:
                fd = proc.alloc_fd()
                proc.fds[fd] = SignalFd(
                    fd, proc, mask=mask,
                    nonblock=bool(a[1] & O_NONBLOCK_FLAG),
                    cloexec=bool(a[1] & 0o2000000),
                )
                done(fd)
            else:
                sfd = proc.fds.get(a[0])
                if isinstance(sfd, SignalFd):
                    sfd.mask = mask
                    # a widened mask may match an ALREADY-pending signal:
                    # re-evaluate parked readers/pollers now
                    self._wake_fd_waiters(sfd)
                    done(a[0])
                else:
                    done(-errno.EINVAL)
        elif sysno == SYS_timerfd_settime:
            tf = proc.fds.get(a[0])
            if not isinstance(tf, TimerFd):
                done(-errno.EBADF)
                return
            raw = ch.data
            value_ns = int.from_bytes(raw[0:8], "little", signed=True)
            interval_ns = int.from_bytes(raw[8:16], "little", signed=True)
            old = self._timerfd_remaining(tf)
            tf.gen += 1
            tf.expirations = 0
            if value_ns == 0:
                tf.next_expiry = None
                tf.interval_ns = 0
            else:
                expiry = (
                    value_ns if (a[1] & TFD_TIMER_ABSTIME)
                    else self.now + value_ns
                )
                tf.next_expiry = expiry
                tf.interval_ns = interval_ns
                gen = tf.gen
                self._schedule(expiry, lambda: self._timer_fire(proc, tf, gen))
            done(0, data=old)
        elif sysno == SYS_timerfd_gettime:
            tf = proc.fds.get(a[0])
            if not isinstance(tf, TimerFd):
                done(-errno.EBADF)
                return
            done(0, data=self._timerfd_remaining(tf))
        elif sysno == SYS_getrandom:
            n = min(a[0], ipc.IPC_DATA_MAX)
            done(n, data=proc.host.rand.randbytes(n))
        elif sysno == SYS_sched_getaffinity:
            # Virtual CPU visibility (deterministic nproc): the simulated
            # host exposes `virtual_cpus` CPUs regardless of the real
            # machine — glibc's __get_nprocs and app thread-pool sizing
            # derive from this syscall. Kernel convention: ret = size of
            # the kernel cpumask copy, data = the affinity mask bytes.
            ncpu = max(1, self.virtual_cpus)
            mask = bytearray((ncpu + 7) // 8)
            for i in range(ncpu):
                mask[i // 8] |= 1 << (i % 8)
            want = a[1]
            if want and want < len(mask):
                done(-errno.EINVAL)
            else:
                done(8, data=bytes(mask))
        # ---- pseudo-syscalls ----
        elif sysno == ipc.PSYS_RESOLVE_NAME:
            name = ch.data.decode("utf-8", "replace")
            if self.dns is not None:
                ip = self.dns.resolve_name(name)
                done(ip if ip is not None else -errno.ENOENT)
            else:
                h = self._host_by_name(name)
                done(h.ip if h is not None else -errno.ENOENT)
        elif sysno == ipc.PSYS_GETHOSTNAME:
            done(0, data=proc.host.name.encode())
        # ---- threads / processes (multiproc_design.md) ----
        elif sysno == ipc.PSYS_THREAD_NEW:
            ch_new = ipc.Channel()
            t_new = ManagedThread(proc.proc, len(proc.proc.threads), ch_new)
            # will HELLO on its own channel; serviced once the spawner blocks
            t_new.state = ManagedThread.RUNNING
            proc.proc.threads.append(t_new)
            self._mark_runnable(proc)
            done(0, data=ch_new.path.encode())
        elif sysno == ipc.PSYS_THREAD_EXIT:
            if a[1] == 2:
                # fork retraction: native fork failed after PSYS_FORK
                # registered a child — drop the ghost record
                ch.reply(0, sim_time_ns=self.now)
                for q in self.procs:
                    if q.parent is proc.proc and q.native_pid is None \
                            and not q.exited and q.popen is None:
                        for t in q.threads:
                            if t.channel:
                                t.channel.close()
                                t.channel = None
                            t.state = ManagedThread.EXITED
                        q.exited = True
                        q.wait_reported = True
                        break
            elif a[1]:  # process-level exit (on_exit notification)
                p = proc.proc
                p.exit_code = a[0]
                # reply DIRECTLY (never via the CPU-delay deferral: the
                # threads are marked exited below, so a deferred reply
                # would be dropped and the process would hang in exit())
                ch.reply(0, sim_time_ns=self.now)
                for t in p.threads:
                    t.state = ManagedThread.EXITED
                p.exited = True
                # release the fd footprint (unbind ports, EOF peers) like
                # _stop_process does — an exiting child must not leak its
                # sockets for the rest of the run
                self._release_fds(p)
                # a parent parked in waitpid wakes NOW, at this sim time;
                # then SIGCHLD posts (a completed waitpid's reply carries
                # the handler; otherwise an interruptible park EINTRs)
                if p.parent is not None:
                    for t in p.parent.threads:
                        self._try_complete_waitpid(t)
                    self._post_signal(p.parent, SIGCHLD)
            else:
                # reply directly (same deferred-reply hazard as above)
                ch.reply(0, sim_time_ns=self.now)
                proc.state = ManagedThread.EXITED
        elif sysno == ipc.PSYS_FORK:
            p = proc.proc
            child = ManagedProcess(
                name=f"{p.name}+{len(self.procs)}", args=p.args,
                host=proc.host, start_time=self.now,
            )
            child.parent = p
            # fork shares open descriptions: same objects, both tables.
            # close() only tears the object down from its owning process
            # (the other side just unlinks its fd) — see _dispatch close.
            child.fds = dict(p.fds)
            child.next_fd = p.next_fd
            # fork inherits dispositions and the calling thread's mask;
            # pending signals are NOT inherited (POSIX)
            child.sig_actions = dict(p.sig_actions)
            child.main.sig_mask = proc.sig_mask
            ch_new = ipc.Channel()
            child.main.channel = ch_new
            child.main.state = ManagedThread.RUNNING  # HELLO incoming
            self._register_proc(child)
            self._mark_runnable(child)
            done(0, data=ch_new.path.encode())
        elif sysno == ipc.PSYS_EXEC:
            self._exec_respawn(proc, ch.data, a[0])
        elif sysno == ipc.PSYS_FUTEX_WAIT:
            uaddr, timeout_ns = a[0], a[1]
            proc.proc.futexes.setdefault(uaddr, []).append(proc)
            dl = None if timeout_ns < 0 else self.now + max(0, timeout_ns)
            park(Parked(proc, "futex", want=uaddr, deadline=dl))
        elif sysno == ipc.PSYS_FUTEX_WAKE:
            done(self._futex_wake(proc.proc, a[0], a[1]))
        elif sysno == ipc.PSYS_WAITPID:
            self._waitpid(proc, a[0], bool(a[1]), park, done)
        elif sysno == ipc.PSYS_FSTAT:
            # stat family on managed fds (syscall_handler.c stat rows
            # analog): report the descriptor KIND; the shim synthesizes
            # the struct stat (st_mode by kind, anonymous-inode style)
            obj = proc.fds.get(a[0])
            if obj is None:
                done(-errno.EBADF)
            elif isinstance(obj, (Sock, BridgeEnd)):
                done(ipc.FD_KIND_SOCKET)
            elif isinstance(obj, PipeEnd):
                done(ipc.FD_KIND_PIPE)
            elif isinstance(obj, EventFd):
                done(ipc.FD_KIND_EVENTFD)
            elif isinstance(obj, TimerFd):
                done(ipc.FD_KIND_TIMERFD)
            elif isinstance(obj, Epoll):
                done(ipc.FD_KIND_EPOLL)
            else:
                done(0)
        elif sysno == ipc.PSYS_FD_LIST:
            # open managed fds of the calling process, sorted (the shim
            # merges them into /proc/self/fd directory listings)
            fds = sorted(proc.fds.keys())
            done(len(fds), data=b"".join(
                int(f).to_bytes(4, "little") for f in fds
            ))
        elif sysno == ipc.PSYS_SIG_RETURN:
            # handler finished: restore the pre-delivery mask (delivery
            # pushed it in _next_signal); the done() reply may itself carry
            # the next now-unblocked pending signal
            if proc.sig_mask_stack:
                proc.sig_mask = proc.sig_mask_stack.pop()
            done(0)
        # ---- virtual signals (syscall/signal.c analog) ----
        elif sysno == SYS_rt_sigaction:
            sig, handler, flags, mask = a[0], a[1], a[2], a[3]
            if not (1 <= sig <= 64) or sig == SIGKILL:
                done(-errno.EINVAL)
                return
            old = proc.proc.sig_actions.get(sig)
            oldh, oldf = (old[0], old[1]) if old else (0, 0)
            if a[4]:  # act present (null act = query only)
                proc.proc.sig_actions[sig] = (handler, flags, mask)
            done(0, data=struct_mod.pack(
                "<QII", oldh & ((1 << 64) - 1), oldf & 0xFFFFFFFF, 0
            ))
        elif sysno == SYS_rt_sigprocmask:
            how, mask = a[0], a[1] & ((1 << 64) - 1)
            oldm = proc.sig_mask
            if how == 0:  # SIG_BLOCK
                proc.sig_mask |= mask
            elif how == 1:  # SIG_UNBLOCK
                proc.sig_mask &= ~mask
            elif how == 2:  # SIG_SETMASK
                proc.sig_mask = mask
            elif how == 3:  # query only (null set)
                pass
            else:
                done(-errno.EINVAL)
                return
            # the reply itself delivers any newly-unblocked pending signal
            done(0, data=struct_mod.pack("<Q", oldm))
        elif sysno == SYS_kill:
            pid, sig, group = a[0], a[1], a[2]
            if sig != 0 and not (1 <= sig <= 64):
                done(-errno.EINVAL)
                return
            if group:
                # Group/broadcast kill, kept VIRTUAL (a native kill(0)
                # would signal the simulator's own process group). Process
                # groups are modeled as fork lineages: pid 0 = caller's
                # lineage, -1 = every managed process except the caller,
                # g = the lineage containing native pid g.
                if pid == -1:
                    targets = [q for q in self.procs
                               if q.alive() and q is not proc.proc]
                else:
                    leader = self._proc_by_pid(proc, pid)
                    if leader is None:
                        done(-errno.ESRCH)
                        return

                    def root(q):
                        while q.parent is not None:
                            q = q.parent
                        return q

                    r = root(leader)
                    targets = [q for q in self.procs
                               if q.alive() and root(q) is r]
                if sig != 0:
                    for q in targets:
                        self._post_signal(q, sig)
                done(0)
                return
            target = self._proc_by_pid(proc, pid)
            if target is None:
                done(-errno.ESRCH)
            elif sig == 0:
                done(0)  # existence probe
            else:
                self._post_signal(target, sig)
                done(0)
        else:
            done(-errno.ENOSYS)

    def _handle_sendto(self, proc: ManagedProcess, a: list[int],
                       payload: bytes) -> None:
        ch = proc.channel
        sock = proc.fds.get(a[0])
        if not isinstance(sock, Sock):
            ch.reply(-errno.EBADF, sim_time_ns=self.now)
            return
        n, has_addr, ip, port = a[1], a[3], a[4], a[5]
        payload = payload[:n]
        if sock.proto == SOCK_DGRAM:
            if sock.pair is not None:
                # datagram socketpair: zero-latency delivery to the twin
                peer = sock.pair
                peer.dgrams.append((proc.host.ip, 0, bytes(payload)))
                self.counters["packets_sent"] += 1
                self.counters["bytes_sent"] += len(payload)
                self._wake_sock_waiters(peer)
                ch.reply(len(payload), sim_time_ns=self.now)
                return
            if has_addr:
                dst = (ip if ip != 0x7F000001 else proc.host.ip, port)
            elif sock.peer is not None:
                dst = sock.peer
            else:
                ch.reply(-errno.EDESTADDRREQ, sim_time_ns=self.now)
                return
            self._ensure_bound(proc, sock)
            src = sock.bound
            self.counters["packets_sent"] += 1
            self.counters["bytes_sent"] += len(payload)
            dst_host = self._host_by_ip(dst[0])
            if (
                self.bridge is not None
                and dst[0] != proc.host.ip
                and dst_host is not None
            ):
                # the device network carries it: NIC pacing, CoDel, path
                # latency and loss all happen on-device (loopback and
                # unknown destinations stay local)
                self._track_tx(proc.host, "udp", src, dst, payload, False)
                self.bridge.send(
                    self.now, proc.host.index, dst_host.index,
                    src[1], dst[1], bytes(payload),
                )
                ch.reply(len(payload), sim_time_ns=self.now)
                return
            dropped = self._drop_roll(
                proc.host.ip, dst[0], control=len(payload) == 0
            )
            self._track_tx(proc.host, "udp", src, dst, payload, dropped)
            if dropped:
                self.counters["packets_dropped"] += 1
            else:
                lat = self._latency(proc.host.ip, dst[0])
                data = bytes(payload)
                self._schedule(
                    self.now + lat,
                    lambda: self._deliver_dgram(src, dst, data),
                )
            ch.reply(len(payload), sim_time_ns=self.now)
        else:
            end = sock.bend
            if end is not None:
                # device-carried stream: bytes wait host-side; the device
                # moves sequence space and reports in-order advances
                if not end.established or end.closed:
                    ch.reply(-errno.ENOTCONN, sim_time_ns=self.now)
                    return
                space = end.send_space()
                if space >= len(payload):
                    n = self._bend_send(proc, end, payload)
                    ch.reply(n, sim_time_ns=self.now)
                    return
                # Bounded send buffer (reference: tcp.c blocks the writer).
                # Nonblocking: partial accept or EAGAIN. Blocking: Linux
                # stream semantics — queue what fits now, park with the
                # remainder, and reply with the FULL count only once
                # everything is buffered (drains as the device reports
                # in-order advances, _bridge_bytes -> _try_wake).
                if sock.nonblock:
                    if space > 0:
                        n = self._bend_send(proc, end, payload[:space])
                        ch.reply(n, sim_time_ns=self.now)
                    else:
                        ch.reply(-errno.EAGAIN, sim_time_ns=self.now)
                    return
                accepted = (
                    self._bend_send(proc, end, payload[:space])
                    if space > 0 else 0
                )
                self._park(
                    proc,
                    Parked(proc, "send", fd=sock.fd,
                           data=bytes(payload[space:]), want=accepted),
                )
                return
            conn = sock.conn
            if conn is None or not conn.established:
                ch.reply(-errno.ENOTCONN, sim_time_ns=self.now)
                return
            remote = conn.remote
            self.counters["packets_sent"] += 1
            self.counters["bytes_sent"] += len(payload)
            self._track_tx(
                proc.host, "tcp", conn.local_addr or (proc.host.ip, 0),
                conn.remote_addr or (0, 0), payload, dropped=False,
            )
            if remote is not None:
                lat = (
                    0 if conn.unix
                    else self._latency(proc.host.ip, conn.remote_addr[0])
                )
                data = bytes(payload)
                self._schedule(
                    self.now + lat,
                    lambda: self._deliver_stream(remote, data),
                )
            ch.reply(len(payload), sim_time_ns=self.now)

    def _complete_recv(self, proc: ManagedProcess, sock: Sock, want: int,
                       hdr: bool = True) -> None:
        # recvfrom replies carry a 6-byte source-address header before the
        # payload (read() replies don't); cap so header+payload always fits
        # the IPC data area (the shim asks for up to IPC_DATA_MAX bytes).
        hn = 6 if hdr else 0
        want = min(want, ipc.IPC_DATA_MAX - hn)
        if sock.proto == SOCK_DGRAM:
            src_ip, src_port, data = sock.dgrams.popleft()
            data = data[:want]
            addr = src_ip.to_bytes(4, "little") + src_port.to_bytes(2, "little")
            self._resume(proc, len(data), data=(addr if hdr else b"") + data)
        elif sock.bend is not None:
            end = sock.bend
            take = min(want, len(end.rx))
            data = bytes(end.rx[:take])
            del end.rx[:take]
            ra = end.remote_addr
            addr = ra[0].to_bytes(4, "little") + ra[1].to_bytes(2, "little")
            self._resume(proc, take, data=(addr if hdr else b"") + data)
        else:
            conn = sock.conn
            take = min(want, len(conn.rx))
            data = bytes(conn.rx[:take])
            del conn.rx[:take]
            ra = conn.remote_addr or (0, 0)
            addr = ra[0].to_bytes(4, "little") + ra[1].to_bytes(2, "little")
            self._resume(proc, take, data=(addr if hdr else b"") + data)

    def _complete_read(self, proc: ManagedProcess, obj, want: int) -> None:
        """Finish a read() on a non-socket readable object (pipe/eventfd/
        timerfd); caller guarantees obj.readable()."""
        if isinstance(obj, PipeEnd):
            want = min(want, ipc.IPC_DATA_MAX)
            take = min(want, len(obj.buf.data))
            data = bytes(obj.buf.data[:take])
            del obj.buf.data[:take]
            self._resume(proc, take, data=data)  # 0 == EOF (write end closed)
        elif isinstance(obj, EventFd):
            val = 1 if obj.semaphore else obj.value
            obj.value -= val
            self._resume(proc, 8, data=val.to_bytes(8, "little"))
        elif isinstance(obj, TimerFd):
            n = obj.expirations
            obj.expirations = 0
            self._resume(proc, 8, data=n.to_bytes(8, "little"))
        elif isinstance(obj, SignalFd):
            # Linux signalfd semantics: a read consumes signals pending
            # for the READING process (matters after fork — the fd is
            # inherited but each process's signal queue is its own), and
            # ONE read fills as many whole signalfd_siginfo records as the
            # buffer holds — kernel behavior (fs/signalfd.c
            # signalfd_read dequeues until the count is exhausted), not
            # one record per read
            p = getattr(proc, "proc", proc)
            max_rec = min(want // 128, ipc.IPC_DATA_MAX // 128)
            recs = []
            while len(recs) < max_rec:
                idx = next(
                    (j for j, s in enumerate(p.sig_pending)
                     if (obj.mask >> (s - 1)) & 1),
                    None,
                )
                if idx is None:
                    break
                s = p.sig_pending.pop(idx)
                # struct signalfd_siginfo: ssi_signo u32 first; the
                # remaining fields (errno/code/pid/...) read as zero
                recs.append(s.to_bytes(4, "little") + b"\x00" * 124)
            if recs:
                buf = b"".join(recs)
                self._resume(proc, len(buf), data=buf)
                return
            # no matching signal for THIS process (raced, or readiness was
            # judged against another process's queue): a blocking reader
            # re-parks, a nonblocking one gets EAGAIN
            if obj.nonblock:
                self._resume(proc, -errno.EAGAIN)
            else:
                self._park(proc, Parked(proc, "read", fd=obj.fd, want=want))
        else:
            self._resume(proc, -errno.EBADF)

    def _complete_accept(self, proc: ManagedProcess, listener: Sock,
                         nonblock: bool = False) -> None:
        conn = listener.accept_q.popleft()
        fd = proc.alloc_fd()
        if isinstance(conn, BridgeEnd):
            child = Sock(fd=fd, proto=SOCK_STREAM, owner=proc,
                         bound=conn.local_addr, bend=conn, nonblock=nonblock)
        else:
            child = Sock(fd=fd, proto=SOCK_STREAM, owner=proc,
                         bound=conn.local_addr, conn=conn, nonblock=nonblock)
        conn.sock = child
        proc.fds[fd] = child
        ra = conn.remote_addr or (0, 0)
        data = ra[0].to_bytes(4, "little") + ra[1].to_bytes(2, "little")
        self._resume(proc, fd, data=data)

    def _send_eof(self, proc: ManagedProcess, sock: Sock) -> None:
        conn = sock.conn
        if conn is None or conn.remote is None:
            return
        remote = conn.remote
        lat = self._latency(
            proc.host.ip,
            conn.remote_addr[0] if conn.remote_addr else proc.host.ip,
        )
        self._schedule(self.now + lat, lambda: self._deliver_eof(remote))

    # ------------------------------------------------------------------
    # device-carried TCP event handlers (bridge drain → driver wakeups)
    # ------------------------------------------------------------------

    def _bridge_close_end(self, end: BridgeEnd) -> None:
        """Inject an app close (FIN after queued data) for a device end."""
        if end.closed or end.recycled:
            return
        end.closed = True
        self.bridge.tcp_close(self.now, end.host.index, end.slot)

    def _recycle_end(self, end: BridgeEnd) -> None:
        """The connection behind this end is finished on device: release
        the slot for reuse and drop the CPU-side mappings (idempotent)."""
        if end.recycled:
            return
        end.recycled = True
        key = (end.host.index, end.slot)
        self.bridge.tcp_release(*key)
        if self._dev_tcp.get(key) is end:
            del self._dev_tcp[key]
        pkey = (end.host.index, end.local_addr[1])
        if self._tcp_pending_conn.get(pkey) is end:
            del self._tcp_pending_conn[pkey]

    def _bridge_accepted(self, d, child: BridgeEnd) -> None:
        """A device child reached ESTABLISHED: hand it to the listener."""
        host = self.hosts[d.host]
        listener = self._tcp_binds.get((host.ip, d.local_port))
        if listener is not None and listener.listening:
            listener.accept_q.append(child)
            self._wake_sock_waiters(listener)
        else:
            # listener went away while the handshake was in flight:
            # close the orphan so the peer sees EOF
            self._bridge_close_end(child)

    def _bridge_established(self, end: BridgeEnd | None) -> None:
        """A connect-side device end reached ESTABLISHED."""
        if end is None:
            return
        end.established = True
        if end.sock is not None:
            end.sock.connecting = False
            self._wake_sock_waiters(end.sock)

    def _bridge_bytes(self, d, end: BridgeEnd | None) -> None:
        """In-order stream bytes arrived at a device end: claim them from
        the peer's host-side tx queue (TCP delivers in order)."""
        if end is None or end.peer is None:
            # establishment row lost (ring overflow) or pairing failed —
            # the sequence space is consumed on device, so these bytes are
            # unrecoverable: make it loud
            log.logger.error(
                "device TCP advance for host %d slot %d has no paired "
                "endpoint; %d stream byte(s) lost (raise bridge ring_slots)",
                d.host, d.slot, d.nbytes,
            )
            return
        n = min(d.nbytes, len(end.peer.tx_queue))
        data = bytes(end.peer.tx_queue[:n])
        del end.peer.tx_queue[:n]
        # freed send-buffer space: a writer parked (or polling POLLOUT)
        # on the peer end can proceed
        if n > 0 and end.peer.sock is not None:
            self._wake_fd_waiters(end.peer.sock)
        end.rx += data
        self._track_rx(
            end.local_addr[0], "tcp", end.remote_addr, end.local_addr, data
        )
        if end.sock is not None:
            self._wake_sock_waiters(end.sock)
        # un-accepted child: bytes buffer silently until accept() wraps it

    def _bridge_fin(self, end: BridgeEnd | None) -> None:
        if end is None:
            return
        end.rx_eof = True
        if end.sock is not None:
            self._wake_sock_waiters(end.sock)

    def _bridge_closed(self, d, end: BridgeEnd | None) -> None:
        """The device freed (host, slot): orderly close completion, or a
        RST/refused teardown (d.reset) that must error the app side."""
        if end is None or not d.reset:
            return
        end.rx_eof = True
        # The device already freed the slot: no further sends may reach it
        # (a later tcp_send would cross-wire into whoever reuses the slot),
        # and a writer parked on a full send buffer must error out now —
        # no TcpBytes advance will ever free space again.
        end.closed = True
        sock = end.sock
        if sock is None:
            return
        if not end.established:
            sock.conn_refused = True  # connect() failed: RST to our SYN
        p = sock.owner
        if (
            p.state == ManagedProcess.PARKED
            and p.parked is not None
            and p.parked.kind == "connect"
            and p.parked.fd == sock.fd
        ):
            p.parked = None
            self._resume(p, -errno.ECONNREFUSED)
        else:
            self._wake_sock_waiters(sock)

    def _timerfd_remaining(self, tf: TimerFd) -> bytes:
        """Pack (remaining_ns, interval_ns) as the gettime/settime-old reply."""
        rem = 0 if tf.next_expiry is None else max(0, tf.next_expiry - self.now)
        return rem.to_bytes(8, "little") + tf.interval_ns.to_bytes(8, "little")

    def _timer_fire(self, proc: ManagedProcess, tf: TimerFd, gen: int) -> None:
        if tf.gen != gen or tf.next_expiry is None:
            return  # re-armed or disarmed since this was scheduled
        if proc.fds.get(tf.fd) is not tf and not any(
            o is tf for o in proc.fds.values()
        ):
            return  # closed
        tf.expirations += 1
        if tf.interval_ns > 0:
            tf.next_expiry += tf.interval_ns
            self._schedule(
                tf.next_expiry, lambda: self._timer_fire(proc, tf, gen)
            )
        else:
            tf.next_expiry = None
        self._wake_fd_waiters(tf)

    def _close_obj(self, obj) -> None:
        self._fd_waiters.pop(id(obj), None)
        if isinstance(obj, Sock):
            if obj.unix_path is not None:
                key = (obj.owner.host.index, obj.unix_path)
                if self._unix_binds.get(key) is obj:
                    del self._unix_binds[key]
                obj.unix_path = None
            if obj.bound is not None:
                binds = (
                    self._udp_binds if obj.proto == SOCK_DGRAM
                    else self._tcp_binds
                )
                if binds.get(obj.bound) is obj:
                    del binds[obj.bound]
                    if self.bridge is not None and obj.proto == SOCK_DGRAM:
                        self.bridge.unbind(obj.owner.host.index, obj.bound[1])
            if obj.dev_listen_slot is not None:
                self.bridge.tcp_unlisten(
                    obj.owner.host.index, obj.dev_listen_slot
                )
                obj.dev_listen_slot = None
            if obj.bend is not None:
                self._bridge_close_end(obj.bend)
            elif obj.conn is not None:
                self._send_eof(obj.owner, obj)
        elif isinstance(obj, PipeEnd):
            if obj.is_read:
                obj.buf.read_closed = True
            else:
                obj.buf.write_closed = True
                self._wake_pipe_readers(obj.buf)  # reader sees EOF
        elif isinstance(obj, TimerFd):
            obj.gen += 1  # cancel any scheduled fire
            obj.next_expiry = None

    # ------------------------------------------------------------------
    # the service loop (manager_run / scheduler round analog)
    # ------------------------------------------------------------------

    def _service_one(self, proc: ManagedThread) -> bool:
        """Wait for the thread's next message and handle it. Returns False
        if the process exited instead of posting a message.

        Non-responsiveness escalates instead of aborting outright: after
        the base service timeout, up to ipc_timeout_retries extra waits
        with doubling backoff (the bounded-retry rung of the recovery
        ladder); only then is the process declared wedged (ProcWedged),
        which the service loop resolves via the on_proc_failure policy."""
        deadline = wall_time.monotonic() + self.service_timeout_s
        attempt = 0
        prekey = (proc.proc.reg_idx, proc.tid)
        while True:
            if prekey in self._prewaited:
                # the sharded pre-wait already consumed this thread's
                # request semaphore — the message is buffered in the
                # channel, so read it without waiting again
                self._prewaited.discard(prekey)
                break
            if proc.channel.wait_request(timeout_s=0.05):
                break
            if proc.popen is not None and proc.popen.poll() is not None:
                # drain any message raced in just before exit
                if not proc.channel.try_request():
                    if proc.tid == 0 and proc.proc.native_pid is None:
                        # The image ran and exited WITHOUT ever completing
                        # the shim handshake: LD_PRELOAD never took (a
                        # statically linked binary, or an exec of one).
                        # The reference covers these with ptrace
                        # (thread_ptrace.c); we fail LOUDLY instead of
                        # letting the process run unsimulated and silently
                        # corrupt determinism.
                        raise DriverError(
                            f"{proc.name}: process exited (rc="
                            f"{proc.popen.returncode}) without completing "
                            f"the shim handshake — statically linked "
                            f"binary? Interposition requires dynamically "
                            f"linked executables (reference covers static "
                            f"binaries via ptrace; unsupported here)"
                        )
                    proc.proc.exit_code = proc.popen.returncode
                    for t in proc.proc.threads:
                        t.state = ManagedThread.EXITED
                    proc.proc.exited = True
                    return False
                break
            if wall_time.monotonic() > deadline and (
                proc.tid == 0 and proc.proc.native_pid is None
            ):
                raise DriverError(
                    f"{proc.name}: no shim handshake within "
                    f"{self.service_timeout_s}s — statically linked "
                    f"binary running unsimulated? Interposition requires "
                    f"dynamically linked executables"
                )
            if wall_time.monotonic() > deadline:
                if attempt < self.ipc_timeout_retries:
                    attempt += 1
                    backoff = self.service_timeout_s * (2 ** attempt)
                    self.fault_counters["ipc_retries"] += 1
                    log.logger.warning(
                        "%s: no syscall within %.1fs; IPC retry %d/%d "
                        "(backoff %.1fs)",
                        proc.name, self.service_timeout_s, attempt,
                        self.ipc_timeout_retries, backoff,
                        host=proc.host.name,
                    )
                    deadline = wall_time.monotonic() + backoff
                    continue
                self.fault_counters["procs_wedged"] += 1
                raise ProcWedged(
                    f"{proc.name}: no syscall within "
                    f"{self.service_timeout_s}s (+{attempt} backoff "
                    f"retries) — wedged managed process"
                )
        mtype = proc.channel.msg_type
        if mtype == ipc.MSG_HELLO:
            if proc.tid == 0 and proc.proc.native_pid is None:
                proc.proc.native_pid = proc.channel.shim_pid
            proc.channel.reply(0, sim_time_ns=self.now)
        elif mtype == ipc.MSG_SYSCALL:
            self._dispatch(proc)
        else:
            raise DriverError(f"{proc.name}: unexpected message {mtype}")
        return True

    def _spawn(self, proc: ManagedProcess) -> None:
        if not proc.alive():
            return  # already stopped (e.g. stop event preceded the spawn)
        log.logger.debug(
            "starting process %s: %s", proc.name, " ".join(proc.args),
            host=proc.host.name,
        )
        proc.spawn(spin=self.spin, seccomp=self.use_seccomp,
                   log_stamp=self.log_stamp)
        self._mark_runnable(proc)

    def _stop_process(self, p: ManagedProcess) -> None:
        """Scheduled per-process stop (process.c:655-677 stop task analog):
        release a parked process with a STOP reply, then terminate it."""
        if not p.alive():
            return
        p.stopped_by_sim = True
        # Release this process's network footprint: unregister port bindings
        # and send EOF to stream peers (so blocked remotes wake), like the
        # reference's descriptor-table teardown on process stop.
        self._release_fds(p)
        if p.popen is None:
            # never spawned (stop scheduled before start); just mark dead
            p.state = ManagedProcess.EXITED
            p.stdout, p.stderr = b"", b""
            return
        stopped = False
        for t in p.threads:
            if t.state == ManagedThread.PARKED and t.channel and t.parked:
                # The shim's STOP handler _exit(0)s the whole process; wait
                # for that so the exit code is deterministic rather than
                # racing a SIGTERM. One STOP suffices.
                t.channel.reply(0, sim_time_ns=self.now,
                                msg_type=ipc.MSG_STOP)
                t.parked = None
                stopped = True
                break
        if stopped and p.popen is not None:
            try:
                p.popen.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        if p.popen is not None and p.popen.poll() is None:
            p.popen.terminate()
        p.stdout, p.stderr = p.finish()

    # ------------------------------------------------------------------
    # fault plane: injections + supervised recovery (shadow_tpu/faults)
    # ------------------------------------------------------------------

    def _find_proc(self, name: str) -> ManagedProcess | None:
        for p in self.procs:
            if p.name == name:
                return p
        return None

    def _execute_fault(self, f) -> None:
        """Fire one scheduled injection at its virtual time (event-heap
        callback: every live process is parked, so the process state the
        fault observes is deterministic)."""
        self.fault_injector.mark_fired(f)
        log.logger.warning("fault injection: %s", f.describe())
        if f.op == "corrupt_file":
            from shadow_tpu.faults import injector as inj_mod

            touched = inj_mod.corrupt_file(f, default_dir=self.fault_dir)
            self.fault_counters["files_corrupted"] += len(touched)
            return
        if f.op == "kill_host":
            h = (
                self._host_by_name(f.host) if isinstance(f.host, str)
                else (self.hosts[f.host] if 0 <= f.host < len(self.hosts)
                      else None)
            )
            if h is None:
                raise DriverError(
                    f"fault plan names unknown host {f.host!r}"
                )
            self._quarantine_host(h, "injected kill_host")
            return
        p = self._find_proc(f.proc)
        if p is None:
            raise DriverError(
                f"fault plan names unknown process {f.proc!r} "
                f"(known: {[q.name for q in self.procs[:8]]})"
            )
        if not p.alive() or p.popen is None or p.popen.poll() is not None:
            log.logger.warning(
                "fault %s: process already exited; no-op", f.describe()
            )
            return
        if f.op == "kill_proc":
            # the crashed-plugin case: SIGKILL the native image. Under the
            # quarantine policy the whole simulated host dies with it
            # (crashed-host semantic); under abort the exit surfaces as a
            # normal nonzero exit code via the service loop.
            p.faulted = True
            os.kill(p.popen.pid, os_signal.SIGKILL)
            if self.on_proc_failure == "quarantine":
                self._quarantine_host(
                    p.host, f"injected kill_proc({p.name})"
                )
        elif f.op == "wedge_proc":
            # the wedged-plugin case: freeze the image; detection is the
            # IPC-timeout escalation ladder's job (ProcWedged -> policy)
            p.faulted = True
            os.kill(p.popen.pid, os_signal.SIGSTOP)
        elif f.op == "refuse_ipc":
            # drop the next `count` replies on the main-thread channel:
            # the shim blocks exactly as if the reply were lost
            ch = p.threads[0].channel
            if ch is not None:
                ch.refuse_next += f.count
                self.fault_counters["ipc_replies_refused"] += f.count
                p.faulted = True

    def _quarantine_host(self, host: SimHost, reason: str) -> None:
        """Mark a simulated host dead and keep the run going (the crashed
        -host semantic real Shadow models when a plugin segfaults): every
        process on the host is killed and collected, its network footprint
        is released (peers see EOF), and pending deliveries TO the host
        are drained at their event time instead of delivered. Idempotent;
        deterministic because it only ever runs from event-heap callbacks
        or the service loop's policy rung — both fixed points of the
        virtual-time schedule."""
        if host.dead:
            return
        host.dead = True
        self.fault_counters["hosts_quarantined"] += 1
        log.logger.warning(
            "quarantining host %s: %s", host.name, reason, host=host.name
        )
        mine = [p for p in self.procs if p.host is host]
        # kill every native image FIRST, then collect: a fork child holds
        # its parent's stdout pipe end, so collecting the parent while any
        # descendant lives would deadlock in communicate()
        for p in mine:
            if p.alive() and p.popen is not None and p.popen.poll() is None:
                p.faulted = True
                p.popen.kill()
        for p in mine:
            if not p.alive():
                continue
            p.faulted = True
            self._release_fds(p)
            if p.popen is not None or p.channel:
                p.stdout, p.stderr = p.finish()
            else:
                # never spawned: cancel by marking dead (the scheduled
                # _spawn checks alive())
                p.state = ManagedProcess.EXITED
                p.exited = True
                p.stdout, p.stderr = b"", b""

    def fault_stats(self) -> dict:
        """Fault-plane telemetry (faults.* namespace, schema v3)."""
        d = dict(self.fault_counters)
        if self.fault_injector is not None:
            d.update(self.fault_injector.stats())
        return d

    def run(self) -> None:
        """Run the simulation until stop_time or all processes exit."""
        # Point the global logger's sim clock at this driver for the run
        # (restored after, so stacked/sequential drivers don't leak).
        prev_now_fn = log.logger.sim_now_fn
        log.logger.sim_now_fn = lambda: self.now
        try:
            self._run()
        finally:
            log.logger.sim_now_fn = prev_now_fn

    def _run(self) -> None:
        for p in self.procs:
            self._schedule(p.start_time, lambda p=p: self._spawn(p))
            if p.stop_time is not None:
                self._schedule(p.stop_time, lambda p=p: self._stop_process(p))
        if self.fault_injector is not None:
            # deterministic injection: faults ride the same (time, seq)
            # event heap as every other scheduled action, keyed to virtual
            # time — two runs with the same plan fire them identically
            from shadow_tpu.faults import plan as plan_mod

            ops = plan_mod.PROC_OPS | plan_mod.FILE_OPS | {"kill_host"}
            for f in self.fault_injector.faults:
                if f.op in ops:
                    self._schedule(f.at_ns, lambda f=f: self._execute_fault(f))
                else:
                    log.logger.warning(
                        "fault plan op %s has no managed-plane executor; "
                        "ignored", f.op,
                    )
        if self.heartbeat_interval and self.heartbeat_fn:

            def beat():
                self.heartbeat_fn(self)
                if any(p.alive() for p in self.procs):
                    self._schedule(self.now + self.heartbeat_interval, beat)

            self._schedule(self.heartbeat_interval, beat)

        while True:
            # 1. service runnable processes to quiescence (deterministic:
            # lowest registration index first; each process's threads by
            # tid; deferred wakes release one thread per process at a time).
            # Only processes with RUNNING/READY threads are visited — wakes
            # re-queue their process via _mark_runnable.
            t_svc = wall_time.perf_counter()
            while self._runq_heap:
                # sharded IPC pre-wait (core/hostplane.py): while the
                # coordinator services the canonical-order front, pinned
                # workers consume the OTHER runnable hosts' shm request
                # semaphores concurrently — execution order is untouched
                self._prewait_runnable()
                _, _, _, idx = heapq.heappop(self._runq_heap)
                p = self._runq_set.pop(idx, None)
                if p is None:
                    continue
                progressed = True
                while progressed:
                    progressed = False
                    for t in p.threads:
                        while t.state == ManagedThread.RUNNING and t.channel:
                            progressed = True
                            try:
                                if not self._service_one(t):
                                    break
                            except ProcWedged as e:
                                # recovery ladder exhausted: the policy rung
                                if self.on_proc_failure != "quarantine":
                                    raise
                                self._quarantine_host(p.host, str(e))
                                break
                    if self._release_ready(p) is not None:
                        progressed = True
            self.plane_wall["service"] += wall_time.perf_counter() - t_svc

            # 2. all quiescent: let the device network advance first — its
            # deliveries may precede our next local event (the CPU↔TPU sync
            # point; reference analog: the round barrier)
            t_dev = wall_time.perf_counter()
            if self.bridge is not None:
                horizon = self._heap[0][0] if self._heap else self.stop_time
                # Endpoint-map bookkeeping happens HERE, in device-event
                # order, so a freed-and-reused (host, slot) key can never
                # cross-wire events of the old and new connection; only the
                # app-visible effects are deferred to the events' times.
                for d in self.bridge.sync(horizon):
                    if isinstance(d, Delivery):
                        if self.hosts[d.dst_host].dead:
                            # quarantined host: device-plane deliveries for
                            # it are cancelled at the handoff boundary
                            self.bridge.take_payload(d.handle)
                            self.fault_counters["events_drained"] += 1
                            continue
                        data = self.bridge.take_payload(d.handle)
                        src_addr = (self.hosts[d.src_host].ip, d.src_port)
                        dst_addr = (self.hosts[d.dst_host].ip, d.dst_port)
                        self._schedule(
                            d.time,
                            lambda s=src_addr, a=dst_addr, dt=data:
                            self._deliver_dgram(s, a, dt),
                        )
                    elif isinstance(d, TcpEstablished):
                        if d.is_accept:
                            host = self.hosts[d.host]
                            child = BridgeEnd(
                                host=host, slot=d.slot,
                                local_addr=(host.ip, d.local_port),
                                remote_addr=(
                                    self.hosts[d.peer_host].ip, d.peer_port
                                ),
                                sndbuf=self.socket_send_buffer,
                                established=True,
                            )
                            self._dev_tcp[(d.host, d.slot)] = child
                            mate = self._tcp_pending_conn.pop(
                                (d.peer_host, d.peer_port), None
                            )
                            if mate is not None:
                                child.peer = mate
                                mate.peer = child
                            self._schedule(
                                d.time,
                                lambda d=d, e=child:
                                self._bridge_accepted(d, e),
                            )
                        else:
                            end = self._dev_tcp.get((d.host, d.slot))
                            self._schedule(
                                d.time,
                                lambda e=end: self._bridge_established(e),
                            )
                    elif isinstance(d, TcpBytes):
                        end = self._dev_tcp.get((d.host, d.slot))
                        self._schedule(
                            d.time, lambda d=d, e=end: self._bridge_bytes(d, e)
                        )
                    elif isinstance(d, TcpFin):
                        end = self._dev_tcp.get((d.host, d.slot))
                        if d.time_wait and end is not None:
                            # both FINs exchanged and acked: recycle now
                            # rather than waiting out the 60 s device
                            # TIME_WAIT timer (whose closed row, if it ever
                            # fires pre-reuse, is de-duplicated by born_t)
                            self._recycle_end(end)
                        self._schedule(
                            d.time, lambda e=end: self._bridge_fin(e)
                        )
                    elif isinstance(d, TcpClosed):
                        end = self._dev_tcp.get((d.host, d.slot))
                        if end is not None and d.time < end.born_t:
                            end = None  # stale row for a prior occupant
                        if end is not None:
                            self._recycle_end(end)
                        self._schedule(
                            d.time, lambda d=d, e=end: self._bridge_closed(d, e)
                        )

            self.plane_wall["device"] += wall_time.perf_counter() - t_dev

            if not self._heap:
                break
            t, _, cb = heapq.heappop(self._heap)
            if t >= self.stop_time:
                break
            t_ev = wall_time.perf_counter()
            self.now = max(self.now, t)
            cb()
            # coalesce same-timestamp events before re-servicing
            while self._heap and self._heap[0][0] <= self.now:
                t2, _, cb2 = heapq.heappop(self._heap)
                cb2()
            self.plane_wall["events"] += wall_time.perf_counter() - t_ev

            live = [p for p in self.procs if p.alive() and p.channel]
            if not live and not self._heap:
                break

        # teardown: stop EVERYTHING still alive first, THEN collect output.
        # Collection order matters: a fork child inherits its parent's
        # stdout pipe fd, so finish() (communicate → EOF wait) on the
        # parent deadlocks while any descendant lives.
        for p in self.procs:
            for t in p.threads:
                if t.state == ManagedThread.PARKED and t.channel:
                    t.channel.reply(0, sim_time_ns=self.now,
                                    msg_type=ipc.MSG_STOP)
                    break
        for p in self.procs:
            if p.channel:
                p.stdout, p.stderr = p.finish()
            elif not hasattr(p, "stdout"):
                p.stdout, p.stderr = b"", b""
            log.logger.debug(
                "process %s exited with %s", p.name, p.exit_code,
                host=p.host.name,
            )
        for w in self._pcaps.values():
            w.close()
        if self.syscall_counts:
            # per-syscall tally at exit (manager.c:269-274 analog)
            log.logger.debug(
                "syscall counts: %s", format_syscall_counts(self.syscall_counts)
            )
        if self.use_perf_timers and self.syscall_times:
            top = sorted(
                self.syscall_times.items(), key=lambda kv: -kv[1]
            )[:12]
            log.logger.info(
                "perf timers (handler wall seconds): %s",
                ", ".join(f"{k}={v:.4f}" for k, v in top),
            )
        # wall budget per plane: where the managed-plane seconds went
        log.logger.info(
            "plane wall budget: service=%.1fs device=%.1fs events=%.1fs "
            "(sim %.3fs)",
            self.plane_wall["service"], self.plane_wall["device"],
            self.plane_wall["events"], self.now / 1e9,
        )
        # leak-style check (reference: alloc/dealloc counter mismatch
        # warning, manager.c:276-292): device TCP slots still held after
        # every process's fds are released indicate a recycling leak —
        # release force-stopped processes' fds first so normal still-open
        # connections at stop_time don't read as leaks
        for p in self.procs:
            self._release_fds(p)
        if self.bridge is not None:
            held = sum(1 for e in self._dev_tcp.values() if not e.recycled)
            if held:
                log.logger.warning(
                    "leak check: %d device TCP slot(s) still held at "
                    "shutdown (connections neither closed nor reset)", held,
                )
