"""ctypes view of the native IPC channel (native/common/ipc.h).

The struct layout is pinned by static_asserts in the header; offsets here
must match. Semaphores are glibc process-shared sem_t operated directly in
the mapped file via ctypes calls into libpthread — the driver-side half of
the reference's spinning-sem channel (binary_spinning_sem.h), with the spin
loop living on the C++ side only (Python parks straight away; its reply
latency is dominated by handler work, not the futex).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import mmap
import os
import struct
import tempfile

IPC_MAGIC = 0x53545031
IPC_DATA_MAX = 1 << 16

MSG_NONE = 0
MSG_HELLO = 1
MSG_SYSCALL = 2
MSG_RESULT = 3
MSG_DO_NATIVE = 4
MSG_STOP = 5

PSYS_RESOLVE_NAME = -100
PSYS_YIELD = -101
PSYS_GETHOSTNAME = -102
PSYS_THREAD_NEW = -103
PSYS_THREAD_EXIT = -104
PSYS_FORK = -105
PSYS_EXEC = -106
PSYS_FUTEX_WAIT = -107
PSYS_FUTEX_WAKE = -108
PSYS_WAITPID = -109
PSYS_SIG_RETURN = -110  # handler finished: restore pre-delivery sig mask
PSYS_FSTAT = -111  # args: fd -> FD_KIND_* code (shim builds struct stat)
PSYS_FD_LIST = -112  # ret = count; data = i32[] open managed fds (sorted)
FD_KIND_SOCKET, FD_KIND_PIPE, FD_KIND_EVENTFD, FD_KIND_TIMERFD, FD_KIND_EPOLL = (
    1, 2, 3, 4, 5,
)

FD_BASE = 1000

# field offsets (pinned in ipc.h)
OFF_MAGIC = 0
OFF_SHIM_PID = 4
OFF_SEM_TO_DRIVER = 8
OFF_SEM_TO_SHIM = 40
OFF_TYPE = 72
OFF_SYSNO = 80
OFF_ARGS = 88
OFF_RET = 136
OFF_SIM_TIME = 144
OFF_SIG_NO = 152
OFF_SIG_FLAGS = 156
OFF_SIG_HANDLER = 160
OFF_DATA_LEN = 168
OFF_DATA = 176
CHANNEL_SIZE = OFF_DATA + IPC_DATA_MAX

SIGF_SIGINFO = 1  # sig_flags bit: SA_SIGINFO-style 3-arg handler

ENV_SHM = "SHADOW_TPU_SHM"
ENV_SPIN = "SHADOW_TPU_SPIN"
ENV_DEBUG = "SHADOW_TPU_SHIM_DEBUG"
ENV_SECCOMP = "SHADOW_TPU_SECCOMP"  # "0" disables the SIGSYS backstop
ENV_LOG_STAMP = "SHADOW_TPU_LOG_STAMP"  # "1": sim-time stdout/stderr stamps

_libpthread = ctypes.CDLL(None, use_errno=True)  # glibc hosts sem_* now


class _timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


_libpthread.sem_init.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_uint]
_libpthread.sem_post.argtypes = [ctypes.c_void_p]
_libpthread.sem_wait.argtypes = [ctypes.c_void_p]
_libpthread.sem_trywait.argtypes = [ctypes.c_void_p]
_libpthread.sem_timedwait.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(_timespec)]


class Channel:
    """Driver-side handle on one managed process's channel."""

    def __init__(self, path: str | None = None):
        if path is None:
            fd, path = tempfile.mkstemp(prefix="shadow_tpu_ch_",
                                        dir="/dev/shm")
            os.ftruncate(fd, CHANNEL_SIZE)
        else:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
            os.ftruncate(fd, CHANNEL_SIZE)
        self.path = path
        # Fault plane (shadow_tpu/faults refuse_ipc): while > 0, reply()
        # consumes the pending request but never writes the response or
        # posts the shim's semaphore — the managed process blocks exactly
        # as if the reply were lost, and the driver's IPC-timeout
        # escalation ladder is what must notice.
        self.refuse_next = 0
        self.refused_total = 0
        self._mm = mmap.mmap(fd, CHANNEL_SIZE)
        os.close(fd)
        self._buf = (ctypes.c_char * CHANNEL_SIZE).from_buffer(self._mm)
        self._base = ctypes.addressof(self._buf)
        # init semaphores (pshared=1, value=0), then the magic
        for off in (OFF_SEM_TO_DRIVER, OFF_SEM_TO_SHIM):
            if _libpthread.sem_init(self._base + off, 1, 0) != 0:
                raise OSError("sem_init failed")
        self._mm[OFF_MAGIC:OFF_MAGIC + 4] = struct.pack("<I", IPC_MAGIC)

    # --- raw field access ---

    def _i32(self, off) -> int:
        return struct.unpack_from("<i", self._mm, off)[0]

    def _i64(self, off) -> int:
        return struct.unpack_from("<q", self._mm, off)[0]

    @property
    def shim_pid(self) -> int:
        return self._i32(OFF_SHIM_PID)

    @property
    def msg_type(self) -> int:
        return self._i32(OFF_TYPE)

    @property
    def sysno(self) -> int:
        return self._i64(OFF_SYSNO)

    @property
    def args(self) -> list[int]:
        return list(struct.unpack_from("<6q", self._mm, OFF_ARGS))

    @property
    def data(self) -> bytes:
        n = self._i32(OFF_DATA_LEN)
        n = max(0, min(n, IPC_DATA_MAX))
        return self._mm[OFF_DATA:OFF_DATA + n]

    def reply(self, ret: int, *, sim_time_ns: int, data: bytes = b"",
              msg_type: int = MSG_RESULT,
              signal: tuple[int, int, int] | None = None) -> None:
        """Write the response and wake the shim. `signal` optionally
        piggybacks one pending virtual signal as (signo, handler, flags) —
        the shim runs the handler before returning from the syscall."""
        if len(data) > IPC_DATA_MAX:
            raise ValueError("reply data too large")
        if self.refuse_next > 0:
            self.refuse_next -= 1
            self.refused_total += 1
            return  # injected fault: the reply is dropped on the floor
        struct.pack_into("<i", self._mm, OFF_TYPE, msg_type)
        struct.pack_into("<q", self._mm, OFF_RET, ret)
        struct.pack_into("<q", self._mm, OFF_SIM_TIME, sim_time_ns)
        if signal is not None:
            signo, handler, flags = signal
            struct.pack_into("<i", self._mm, OFF_SIG_NO, signo)
            struct.pack_into("<i", self._mm, OFF_SIG_FLAGS, flags)
            struct.pack_into("<Q", self._mm, OFF_SIG_HANDLER, handler)
        else:
            struct.pack_into("<i", self._mm, OFF_SIG_NO, 0)
        struct.pack_into("<i", self._mm, OFF_DATA_LEN, len(data))
        if data:
            self._mm[OFF_DATA:OFF_DATA + len(data)] = data
        _libpthread.sem_post(self._base + OFF_SEM_TO_SHIM)

    def wait_request(self, timeout_s: float | None = None) -> bool:
        """Block until the shim posts a request. Returns False on timeout."""
        if timeout_s is None:
            while _libpthread.sem_wait(self._base + OFF_SEM_TO_DRIVER) != 0:
                pass
            return True
        import time as _time

        ts = _timespec()
        deadline = _time.clock_gettime(_time.CLOCK_REALTIME) + timeout_s
        ts.tv_sec = int(deadline)
        ts.tv_nsec = int((deadline - int(deadline)) * 1e9)
        r = _libpthread.sem_timedwait(self._base + OFF_SEM_TO_DRIVER,
                                      ctypes.byref(ts))
        return r == 0

    def try_request(self) -> bool:
        return _libpthread.sem_trywait(self._base + OFF_SEM_TO_DRIVER) == 0

    def close(self) -> None:
        try:
            del self._buf
            self._mm.close()
        except BufferError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
