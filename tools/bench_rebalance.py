"""Skew-recovery benchmark (VERDICT r4 gate 3): deliberately skewed PHOLD
(hot 10% of hosts, clustered in shard 0's block by construction) run on the
islands engine with STATIC host→shard assignment vs with the between-window
REBALANCER — the P3 work-stealing replacement
(scheduler_policy_host_steal.c analog).

Static assignment parks every hot host on shard 0: its pool saturates, the
driver's spill tier thrashes host round-trips, and windows clamp below
spilled timestamps. The rebalancer spreads hot hosts across shards and the
run stays on the fast path. Gate: rebalanced >= 1.5x static throughput.

Usage: python tools/bench_rebalance.py [--hosts 4096] [--shards 8]
Prints one JSON line. Runs on whatever backend jax selects (TPU via axon,
or JAX_PLATFORMS=cpu for a functional check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build(hosts, shards, rebalance, capacity, msgload, stop_s):
    from shadow_tpu.flagship import SELF_LOOP_50MS_GML
    from shadow_tpu.sim import build_simulation

    return build_simulation({
        "general": {"stop_time": stop_s, "seed": 3},
        "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
        "experimental": {
            "event_capacity": capacity,
            "events_per_host_per_window": msgload + 12,
            "outbox_slots": msgload + 12,
            "inbox_slots": 4,
            "num_shards": shards,
            "exchange_slots": max(64, 2 * hosts * msgload // (shards * shards)),
            "rebalance": rebalance,
        },
        "hosts": {"peer": {"quantity": hosts, "app_model": "phold",
                           "app_options": {"msgload": msgload,
                                           "runtime": stop_s - 1,
                                           "hot_frac": 0.1,
                                           "hot_share": 0.6}}},
    })


def timed(sim, stop_s, wpd):
    import jax

    sim.run(until=1_200_000_000, windows_per_dispatch=wpd)  # warm compile
    jax.block_until_ready(sim.state.pool.time)
    t0 = time.perf_counter()
    sim.run(windows_per_dispatch=wpd)
    jax.block_until_ready(sim.state.pool.time)
    wall = time.perf_counter() - t0
    c = sim.counters()
    return wall, c


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=4096)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--msgload", type=int, default=4)
    ap.add_argument("--stop", type=int, default=6)
    ap.add_argument("--wpd", type=int, default=16)
    ap.add_argument("--capacity", type=int, default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon site hook "
                         "otherwise pins the TPU platform)")
    args = ap.parse_args()
    if args.cpu:
        from shadow_tpu.parallel.virtualize import force_cpu_devices

        force_cpu_devices(1, cache_dir=os.path.join(_REPO, ".jax_cache"))
    # Capacity chosen so the hot shard (60% of the population) exceeds its
    # per-shard pool while the BALANCED layout fits comfortably.
    pop = args.hosts * args.msgload
    capacity = args.capacity or int(1.25 * pop)

    st_sim = build(args.hosts, args.shards, False, capacity, args.msgload,
                   args.stop)
    st_wall, st_c = timed(st_sim, args.stop, args.wpd)
    rb_sim = build(args.hosts, args.shards, True, capacity, args.msgload,
                   args.stop)
    rb_wall, rb_c = timed(rb_sim, args.stop, args.wpd)

    assert st_c["events_committed"] == rb_c["events_committed"], (
        st_c["events_committed"], rb_c["events_committed"]
    )
    recovery = st_wall / rb_wall if rb_wall > 0 else 0.0
    print(json.dumps({
        "metric": "skew_recovery_rebalance_vs_static",
        "value": round(recovery, 3),
        "unit": "x",
        "vs_baseline": round(recovery, 3),
        "detail": {
            "hosts": args.hosts, "shards": args.shards,
            "events": st_c["events_committed"],
            "static_wall_s": round(st_wall, 3),
            "rebalanced_wall_s": round(rb_wall, 3),
            "rebalances": rb_sim.rebalances,
            "static_spill_episodes": st_sim.spill_stats()["spill_episodes"],
            "rebalanced_spill_episodes": (
                rb_sim.spill_stats()["spill_episodes"]
            ),
        },
    }))


if __name__ == "__main__":
    main()
