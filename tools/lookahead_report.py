#!/usr/bin/env python
"""Per-shard-pair lookahead report for asynchronous conservative sync.

Given a simulation config, derive and print the [S, S] lookahead matrix
the async islands driver runs under (parallel/lookahead.py): entry
(j, i) is the minimum baked path latency from any host of shard j to any
host of shard i — how far shard i may safely run ahead of shard j's
frontier. The diagonal is each shard's intra-shard minimum (its safe
local window width), and the CRITICAL LINK — the minimum off-diagonal
entry — is the edge that bounds async slack fleet-wide: raising that one
latency (or re-partitioning hosts so the chatty pair lands in one shard,
the ROADMAP's min-cut placement item) buys the most asynchrony.

  python tools/lookahead_report.py config.yaml [--shards S] [--json]

--shards overrides experimental.num_shards (the partition to analyze;
the config's host count must divide by it). --json emits one machine-
readable object instead of the table. Exit 0 on success, 2 with a
one-line diagnosis on a bad config — never a traceback.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _fmt_ns(v: int, never: int) -> str:
    if v >= never:
        return "-"
    if v % 1_000_000 == 0:
        return f"{v // 1_000_000}ms"
    if v % 1_000 == 0:
        return f"{v // 1_000}us"
    return f"{v}ns"


def main(argv: list[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    shards = None
    if "--shards" in args:
        i = args.index("--shards")
        try:
            shards = int(args[i + 1])
        except (IndexError, ValueError):
            print("--shards needs an integer", file=sys.stderr)
            return 2
        del args[i:i + 2]
    if len(args) != 1 or args[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        return 0 if args and args[0] in ("-h", "--help") else 2

    import numpy as np

    from shadow_tpu.core import simtime
    from shadow_tpu.core.config import ConfigError, load_config
    from shadow_tpu.parallel import lookahead as lookahead_mod
    from shadow_tpu.routing.topology import Topology

    path = args[0]
    try:
        cfg = load_config(path)
    except FileNotFoundError:
        print(f"{path}: no such file", file=sys.stderr)
        return 2
    except (ConfigError, ValueError) as e:
        print(f"{path}: {e}", file=sys.stderr)
        return 2
    S = shards if shards is not None else cfg.experimental.num_shards
    if S < 1:
        print(f"{path}: num_shards must be >= 1, got {S}", file=sys.stderr)
        return 2
    try:
        topo = Topology.from_gml(
            cfg.graph_gml(), cfg.network.use_shortest_path
        )
        for i, h in enumerate(cfg.hosts):
            topo.attach_host(
                i,
                ip_address_hint=h.ip_address_hint,
                city_code_hint=h.city_code_hint,
                country_code_hint=h.country_code_hint,
                network_node_id=h.network_node_id,
            )
        baked = topo.bake()
        spec = lookahead_mod.derive(
            baked.latency_vv, baked.host_vertex, S
        )
    except (ValueError, KeyError) as e:
        print(f"{path}: {e}", file=sys.stderr)
        return 2

    never = int(simtime.NEVER)
    widths = lookahead_mod.shard_runahead(spec, baked.min_latency_ns)
    if as_json:
        doc = {
            "kind": "shadow_tpu.lookahead",
            "num_shards": S,
            "num_hosts": len(cfg.hosts),
            "matrix_ns": [
                [int(v) if v < never else None for v in row]
                for row in spec.matrix
            ],
            "intra_ns": [int(v) if v < never else None for v in spec.intra],
            "shard_runahead_ns": [int(v) for v in widths],
            "min_cross_ns": (
                int(spec.min_cross) if spec.min_cross < never else None
            ),
            "critical_link": (
                list(spec.critical) if spec.min_cross < never else None
            ),
            "global_runahead_ns": int(baked.min_latency_ns),
            "auto_spread_ns": lookahead_mod.auto_spread(
                spec, baked.min_latency_ns
            ),
        }
        print(json.dumps(doc, indent=1))
        return 0

    print(f"lookahead matrix ({S} shards, {len(cfg.hosts)} hosts; "
          f"row=src shard, col=dst shard; '-' = no direct path):")
    hdr = "      " + "".join(f"{i:>10d}" for i in range(S))
    print(hdr)
    for j in range(S):
        row = "".join(
            f"{_fmt_ns(int(spec.matrix[j, i]), never):>10}"
            for i in range(S)
        )
        print(f"  {j:>3d} {row}")
    print()
    print("per-shard safe window widths (intra minimum, floored at the "
          "configured runahead):")
    for s in range(S):
        print(f"  shard {s}: {_fmt_ns(int(widths[s]), never)}")
    print()
    if spec.min_cross < never:
        j, i = spec.critical
        print(f"critical link: shard {j} -> shard {i} at "
              f"{_fmt_ns(int(spec.min_cross), never)} — this latency "
              f"bounds how far any shard may run ahead; re-partitioning "
              f"the chatty pair into one shard (min-cut placement) or "
              f"raising it buys the most async slack")
    else:
        print("critical link: none — no shard pair communicates "
              "directly; shards are fully decoupled")
    print(f"global conservative runahead (barrier window width): "
          f"{_fmt_ns(int(baked.min_latency_ns), never)}")
    print(f"auto roughness spread bound: "
          f"{_fmt_ns(lookahead_mod.auto_spread(spec, baked.min_latency_ns), never)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
