#!/usr/bin/env python
"""Per-shard-pair lookahead report for asynchronous conservative sync.

Given a simulation config, derive and print the [S, S] lookahead matrix
the async islands driver runs under (parallel/lookahead.py): entry
(j, i) is the minimum baked path latency from any host of shard j to any
host of shard i — how far shard i may safely run ahead of shard j's
frontier. The diagonal is each shard's intra-shard minimum (its safe
local window width), and the CRITICAL LINK — the minimum off-diagonal
entry — is the edge that bounds async slack fleet-wide: raising that one
latency (or re-partitioning hosts so the chatty pair lands in one shard,
the ROADMAP's min-cut placement item) buys the most asynchrony.

  python tools/lookahead_report.py config.yaml [--shards S] [--json]
      [--assignment FILE] [--mesh]

--mesh adds the multi-chip placement report: per-chip host placement,
per-link collective partners (each chip's in-edge matrix row — exactly
the neighbors its ppermute frontier exchange talks to, with the derived
ring-shift schedule), and the intra- vs cross-chip affinity split of
the analyzed assignment (block partition, or --assignment's proposal)
next to the block partition's cross cut — the offline review for a
min-cut placement before a run commits to it.

--shards overrides experimental.num_shards (the partition to analyze;
the config's host count must divide by it). --assignment FILE analyzes
a PROPOSED host→shard assignment instead of the contiguous block
partition: FILE is a JSON array of per-host shard indices (exactly H/S
hosts per shard). The report then also prints the assignment's CUT COST
(total cross-shard communication affinity, parallel/balancer.cut_cost)
next to the block partition's, so a balancer migration — or a hand-
tuned partition — is reviewable offline before a run commits to it.
--json emits one machine-readable object instead of the table. Exit 0
on success, 2 with a one-line diagnosis on a bad input — never a
traceback.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _fmt_ns(v: int, never: int) -> str:
    if v >= never:
        return "-"
    if v % 1_000_000 == 0:
        return f"{v // 1_000_000}ms"
    if v % 1_000 == 0:
        return f"{v // 1_000}us"
    return f"{v}ns"


def main(argv: list[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    mesh = "--mesh" in args
    if mesh:
        args.remove("--mesh")
    shards = None
    if "--shards" in args:
        i = args.index("--shards")
        try:
            shards = int(args[i + 1])
        except (IndexError, ValueError):
            print("--shards needs an integer", file=sys.stderr)
            return 2
        del args[i:i + 2]
    assignment_path = None
    if "--assignment" in args:
        i = args.index("--assignment")
        try:
            assignment_path = args[i + 1]
        except IndexError:
            print("--assignment needs a JSON file path", file=sys.stderr)
            return 2
        del args[i:i + 2]
    if len(args) != 1 or args[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        return 0 if args and args[0] in ("-h", "--help") else 2

    import numpy as np

    from shadow_tpu.core import simtime
    from shadow_tpu.core.config import ConfigError, load_config
    from shadow_tpu.parallel import balancer as balancer_mod
    from shadow_tpu.parallel import lookahead as lookahead_mod
    from shadow_tpu.routing.topology import Topology

    path = args[0]
    try:
        cfg = load_config(path)
    except FileNotFoundError:
        print(f"{path}: no such file", file=sys.stderr)
        return 2
    except (ConfigError, ValueError) as e:
        print(f"{path}: {e}", file=sys.stderr)
        return 2
    S = shards if shards is not None else cfg.experimental.num_shards
    if S < 1:
        print(f"{path}: num_shards must be >= 1, got {S}", file=sys.stderr)
        return 2
    try:
        topo = Topology.from_gml(
            cfg.graph_gml(), cfg.network.use_shortest_path
        )
        for i, h in enumerate(cfg.hosts):
            topo.attach_host(
                i,
                ip_address_hint=h.ip_address_hint,
                city_code_hint=h.city_code_hint,
                country_code_hint=h.country_code_hint,
                network_node_id=h.network_node_id,
            )
        baked = topo.bake()
        H = len(cfg.hosts)
        slots = None
        shard_of = lookahead_mod.shard_of_hosts(H, S)
        if assignment_path is not None:
            with open(assignment_path) as f:
                proposed = json.load(f)
            if (not isinstance(proposed, list) or len(proposed) != H
                    or not all(isinstance(x, int) for x in proposed)):
                raise ValueError(
                    f"--assignment must be a JSON array of {H} per-host "
                    f"shard indices"
                )
            counts = np.bincount(
                np.asarray(proposed, np.int64), minlength=S
            )
            if counts.shape[0] > S or (counts != H // S).any():
                raise ValueError(
                    f"--assignment must place exactly {H // S} hosts on "
                    f"each of {S} shards (got counts {counts.tolist()})"
                )
            # synthesize the host->slot table the engine would run under
            # (slots fill per shard in host-id order)
            slots = np.empty(H, np.int64)
            fill = np.zeros(S, np.int64)
            for h, s in enumerate(proposed):
                slots[h] = s * (H // S) + fill[s]
                fill[s] += 1
            shard_of = np.asarray(proposed, np.int64)
        spec = lookahead_mod.derive(
            baked.latency_vv, baked.host_vertex, S, assignment=slots
        )
        cut = balancer_mod.cut_cost(
            shard_of, baked.latency_vv, baked.host_vertex
        )
        cut_block = balancer_mod.cut_cost(
            lookahead_mod.shard_of_hosts(H, S),
            baked.latency_vv, baked.host_vertex,
        )
    except (ValueError, KeyError, OSError,
            json.JSONDecodeError) as e:
        src = assignment_path if assignment_path else path
        print(f"{src}: {e}", file=sys.stderr)
        return 2

    never = int(simtime.NEVER)
    widths = lookahead_mod.shard_runahead(spec, baked.min_latency_ns)
    mesh_doc = None
    if mesh:
        shifts = lookahead_mod.ppermute_shifts(spec)
        in_edges = lookahead_mod.in_edge_matrix(spec)  # [dst, src]
        # intra- vs cross-chip affinity split of the analyzed assignment
        aff = balancer_mod._affinity_vv(baked.latency_vv)
        aff = aff + aff.T
        hv = np.asarray(baked.host_vertex, np.int64)
        cnt = np.zeros((S, aff.shape[0]), np.float64)
        np.add.at(cnt, (shard_of, hv), 1.0)
        n_v = cnt.sum(axis=0)
        diag = float((np.diagonal(aff) * n_v).sum())
        total = (float(n_v @ aff @ n_v) - diag) / 2.0
        intra = total - cut
        chips = []
        for i in range(S):
            hosts_i = np.flatnonzero(shard_of == i)
            partners = [
                {"src_chip": int(j), "lookahead_ns": int(in_edges[i, j])}
                for j in range(S)
                if in_edges[i, j] < never
            ]
            chips.append({
                "chip": i,
                "hosts": [int(h) for h in hosts_i],
                "vertices": sorted(
                    int(v) for v in np.unique(hv[hosts_i])
                ),
                "in_edges": partners,
            })
        mesh_doc = {
            "chips": chips,
            "ppermute_shifts": [int(d) for d in shifts],
            "exchange_partners": len(shifts),
            "all_gather_partners": S,
            "cut_intra": round(intra, 3),
            "cut_cross": round(cut, 3),
            "cut_cross_block": round(cut_block, 3),
        }
    if as_json:
        doc = {
            "kind": "shadow_tpu.lookahead",
            "num_shards": S,
            "num_hosts": len(cfg.hosts),
            "matrix_ns": [
                [int(v) if v < never else None for v in row]
                for row in spec.matrix
            ],
            "intra_ns": [int(v) if v < never else None for v in spec.intra],
            "shard_runahead_ns": [int(v) for v in widths],
            "min_cross_ns": (
                int(spec.min_cross) if spec.min_cross < never else None
            ),
            "critical_link": (
                list(spec.critical) if spec.min_cross < never else None
            ),
            "global_runahead_ns": int(baked.min_latency_ns),
            "auto_spread_ns": lookahead_mod.auto_spread(
                spec, baked.min_latency_ns
            ),
            "cut_cost": round(cut, 3),
            "cut_cost_block": round(cut_block, 3),
            "assignment": (
                None if assignment_path is None
                else [int(x) for x in shard_of]
            ),
        }
        if mesh_doc is not None:
            doc["mesh"] = mesh_doc
        print(json.dumps(doc, indent=1))
        return 0

    print(f"lookahead matrix ({S} shards, {len(cfg.hosts)} hosts; "
          f"row=src shard, col=dst shard; '-' = no direct path):")
    hdr = "      " + "".join(f"{i:>10d}" for i in range(S))
    print(hdr)
    for j in range(S):
        row = "".join(
            f"{_fmt_ns(int(spec.matrix[j, i]), never):>10}"
            for i in range(S)
        )
        print(f"  {j:>3d} {row}")
    print()
    print("per-shard safe window widths (intra minimum, floored at the "
          "configured runahead):")
    for s in range(S):
        print(f"  shard {s}: {_fmt_ns(int(widths[s]), never)}")
    print()
    if spec.min_cross < never:
        j, i = spec.critical
        print(f"critical link: shard {j} -> shard {i} at "
              f"{_fmt_ns(int(spec.min_cross), never)} — this latency "
              f"bounds how far any shard may run ahead; re-partitioning "
              f"the chatty pair into one shard (min-cut placement) or "
              f"raising it buys the most async slack")
    else:
        print("critical link: none — no shard pair communicates "
              "directly; shards are fully decoupled")
    print(f"global conservative runahead (barrier window width): "
          f"{_fmt_ns(int(baked.min_latency_ns), never)}")
    print(f"auto roughness spread bound: "
          f"{_fmt_ns(lookahead_mod.auto_spread(spec, baked.min_latency_ns), never)}")
    if assignment_path is not None:
        delta = cut - cut_block
        print(f"cut cost of proposed assignment: {cut:.3f} "
              f"(block partition: {cut_block:.3f}, "
              f"{'+' if delta >= 0 else ''}{delta:.3f}) — cross-shard "
              f"communication affinity; lower keeps lookahead-critical "
              f"links intra-shard")
    else:
        print(f"cut cost (block partition): {cut_block:.3f}")
    if mesh_doc is not None:
        print()
        print(f"mesh placement ({S} chips):")
        for row in mesh_doc["chips"]:
            hosts_i = row["hosts"]
            span = (
                f"{hosts_i[0]}-{hosts_i[-1]}"
                if hosts_i == list(range(hosts_i[0], hosts_i[-1] + 1))
                else ",".join(str(h) for h in hosts_i[:8])
                + ("…" if len(hosts_i) > 8 else "")
            )
            if row["in_edges"]:
                links = ", ".join(
                    f"chip {e['src_chip']} "
                    f"({_fmt_ns(e['lookahead_ns'], never)})"
                    for e in row["in_edges"]
                )
            else:
                links = "none (fully decoupled)"
            print(f"  chip {row['chip']}: hosts {span} | receives "
                  f"frontiers from {links}")
        print(f"frontier exchange: {mesh_doc['exchange_partners']} "
              f"ppermute partner(s) per chip per superstep (ring shifts "
              f"{mesh_doc['ppermute_shifts']}) vs {S} under all_gather")
        print(f"affinity split: intra-chip {mesh_doc['cut_intra']:.3f} / "
              f"cross-chip {mesh_doc['cut_cross']:.3f} (block partition "
              f"cross: {mesh_doc['cut_cross_block']:.3f}) — min-cut "
              f"placement (experimental.placement: min_cut) moves "
              f"affinity intra-chip")
    return 0


if __name__ == "__main__":
    sys.exit(main())
