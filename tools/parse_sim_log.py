#!/usr/bin/env python3
"""Parse simulator log output into structured JSON (reference analog:
src/tools/parse-shadow.py, which digests the reference's log format for
plotting).

Input: lines from the CLI's stderr log (SimLogger format,
shadow_tpu/utils/log.py):

    WALL SIM [level] [host] message

plus `heartbeat: ...` progress lines and per-host `tracker: ...` lines.
Output: one JSON document with heartbeats, per-host tracker series, and
process exit records — feed it to your plotting tool of choice.

Usage:  python -m shadow_tpu ... 2>&1 | python tools/parse_sim_log.py
        python tools/parse_sim_log.py < sim.log > sim.json
"""

from __future__ import annotations

import json
import re
import sys

class ParseError(ValueError):
    """A line matched the log-line shape but its fields do not parse.

    Carries the 1-based line number and the offending line so the CLI can
    exit with a clear message instead of a bare traceback.
    """

    def __init__(self, lineno: int, line: str, why: str):
        super().__init__(
            f"malformed log line {lineno}: {why}\n  {line!r}"
        )
        self.lineno = lineno
        self.line = line


_TS = r"(\d+:\d+:\d+\.\d+)"
LOG_RE = re.compile(
    rf"^{_TS} {_TS} \[(\w+)\](?: \[([^\]]+)\])? (.*)$"
)
HEARTBEAT_RE = re.compile(
    r"heartbeat: sim ([\d.]+)s(?: / [\d.]+s)?, (\d+) (?:syscalls|events)"
)
TRACKER_RE = re.compile(
    r"tracker: tx (\d+) pkts / (\d+) B, rx (\d+) pkts / (\d+) B, (\d+) dropped"
)
EXIT_RE = re.compile(r"process (\S+) exited with (\S+)")
COUNTS_RE = re.compile(r"syscall counts: (.*)")


def _ts_to_seconds(ts: str) -> float:
    h, m, s = ts.split(":")
    return int(h) * 3600 + int(m) * 60 + float(s)


def parse(lines) -> dict:
    out = {
        "heartbeats": [],
        "trackers": {},  # host -> [{sim_s, tx_packets, ...}]
        "process_exits": [],
        "syscall_counts": {},
        "warnings": [],
    }
    for lineno, line in enumerate(lines, start=1):
        line = line.rstrip("\n")
        m = LOG_RE.match(line)
        if not m:
            hb = HEARTBEAT_RE.search(line)
            if hb:
                out["heartbeats"].append(
                    {"sim_s": float(hb.group(1)), "count": int(hb.group(2))}
                )
            continue
        wall, sim, level, host, msg = m.groups()
        try:
            rec_time = {
                "wall_s": _ts_to_seconds(wall),
                "sim_s": _ts_to_seconds(sim),
            }
            tm = TRACKER_RE.match(msg)
            if tm and host:
                out["trackers"].setdefault(host, []).append(
                    {
                        **rec_time,
                        "tx_packets": int(tm.group(1)),
                        "tx_bytes": int(tm.group(2)),
                        "rx_packets": int(tm.group(3)),
                        "rx_bytes": int(tm.group(4)),
                        "dropped_packets": int(tm.group(5)),
                    }
                )
                continue
            em = EXIT_RE.match(msg)
            if em:
                out["process_exits"].append(
                    {**rec_time, "process": em.group(1),
                     "exit_code": None if em.group(2) == "None"
                     else int(em.group(2))}
                )
                continue
            cm = COUNTS_RE.match(msg)
            if cm:
                for part in cm.group(1).split():
                    name, _, count = part.rpartition(":")
                    out["syscall_counts"][name] = int(count)
                continue
        except ValueError as e:
            raise ParseError(lineno, line, str(e)) from None
        if level in ("warning", "error", "panic"):
            out["warnings"].append({**rec_time, "level": level, "msg": msg})
    return out


def main() -> int:
    try:
        doc = parse(sys.stdin)
    except ParseError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    json.dump(doc, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
