#!/usr/bin/env python3
"""tgen-class scaled e2e runner (reference analog: src/test/tor/minimal —
run a network of real transfer processes under the simulator, then
grep-verify stream successes like verify.sh:7-22).

Builds a <hosts>-host network (servers + clients running the real
tests/apps/tgen_like binary), runs it under `python -m shadow_tpu` with
device TCP, then counts stream-success lines across every client's stdout
file and reports PASS/FAIL.

    python tools/run_tgen.py --hosts 1024 --servers 32 --streams 2 \
        --bytes 8192 --data-dir /tmp/tgen1k.data
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=1024)
    ap.add_argument("--servers", type=int, default=32)
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--bytes", type=int, default=8192)
    ap.add_argument("--stop", type=int, default=15, help="sim seconds")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--latency-ms", type=int, default=50)
    ap.add_argument("--cpu-plane", action="store_true",
                    help="stage-A CPU network model (no device bridge): "
                    "isolates driver-plane scaling from the chip")
    args = ap.parse_args()

    n_cli = args.hosts - args.servers
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="tgen_run_")
    if os.path.exists(data_dir):
        shutil.rmtree(data_dir)

    cc = shutil.which("cc") or shutil.which("gcc")
    app = os.path.join(tempfile.gettempdir(), "tgen_like_bin")
    subprocess.run(
        [cc, "-O1", "-o", app,
         os.path.join(REPO, "tests", "apps", "tgen_like.c")],
        check=True,
    )

    yaml = f"""
general:
  stop_time: {args.stop} s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{args.latency_ms} ms" packet_loss 0.001 ]
      ]
experimental:
  use_device_network: {str(not args.cpu_plane).lower()}
  use_device_tcp: {str(not args.cpu_plane).lower()}
  event_capacity: {1 << 17}
  events_per_host_per_window: 8
  sockets_per_host: 160
hosts:
  srv:
    quantity: {args.servers}
    processes:
      - path: {app}
        args: --server 9100 0
        stop_time: {args.stop - 2} s
  cli:
    quantity: {n_cli}
    processes:
      - path: {app}
        args: srv {args.servers} 9100 {args.streams} {args.bytes}
        start_time: 1 s
"""
    cfg = os.path.join(tempfile.gettempdir(), "tgen_run.yaml")
    with open(cfg, "w") as f:
        f.write(yaml)

    print(f"running {args.hosts} hosts ({n_cli} clients x {args.streams} "
          f"streams x {args.bytes} B) ...", flush=True)
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", cfg,
         "--data-directory", data_dir],
        cwd=REPO,
    )

    # verify.sh-style grep across the per-process stdout files
    want = n_cli * args.streams
    got = complete = 0
    for root, _dirs, files in os.walk(data_dir):
        for fn in files:
            if fn.endswith(".stdout"):
                with open(os.path.join(root, fn)) as f:
                    txt = f.read()
                got += txt.count("stream-success")
                complete += txt.count(f"transfers-complete {args.streams}")
    print(f"stream-success {got}/{want}; clients complete "
          f"{complete}/{n_cli}; sim rc={r.returncode}")
    ok = got == want and complete == n_cli
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
