#!/usr/bin/env python3
"""Convert a flight-recorder spool (--flight-out) into a Perfetto trace.

The spool holds committed event records in VIRTUAL time; this tool emits
them as a second clock domain — pid 1 ("virtual time"), one named thread
per simulated host, timestamps = event time in microseconds of sim time —
so Perfetto renders per-host virtual-time tracks. With --merge, the
events are appended to an existing wall-time trace (--trace-out output,
pid 0), giving both clock domains side by side in one document.

Usage:
  python tools/flight_to_trace.py run.flight.spool -o flight.trace.json
  python tools/flight_to_trace.py run.flight.spool --merge run.trace.json \
      -o combined.trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

VIRTUAL_PID = 1


def spool_to_events(spool: dict) -> list[dict]:
    """Flight records -> trace events on the virtual-time clock domain."""
    events = [{
        "name": "process_name", "ph": "M", "pid": VIRTUAL_PID, "tid": 0,
        "args": {"name": "virtual time (flight recorder)"},
    }]
    named: set[int] = set()
    n_lost = 0
    for frame in spool["frames"]:
        n_lost += frame["lost"]
        for host, t_ns, src, seq, kind in frame["records"]:
            if host not in named:
                named.add(host)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": VIRTUAL_PID,
                    "tid": host, "args": {"name": f"host {host}"},
                })
            events.append({
                "name": f"k{kind}", "cat": "vtime", "ph": "i", "s": "t",
                "pid": VIRTUAL_PID, "tid": host, "ts": t_ns / 1e3,
                "args": {"src": src, "seq": seq, "kind": kind,
                         "time_ns": t_ns},
            })
    if n_lost:
        # the ring's overwrite budget: surface it so a sparse track is
        # read as "overwritten", not "idle"
        events.append({
            "name": "flight_records_lost", "ph": "i", "s": "g",
            "pid": VIRTUAL_PID, "tid": 0, "ts": 0.0,
            "args": {"lost": n_lost},
        })
    return events


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("spool", help="flight spool written by --flight-out")
    ap.add_argument("-o", "--out", required=True,
                    help="output trace JSON path")
    ap.add_argument("--merge", metavar="TRACE_JSON",
                    help="existing wall-time trace (--trace-out output) "
                         "to merge the virtual-time tracks into")
    args = ap.parse_args(argv)

    from shadow_tpu.obs.flight import read_spool

    try:
        spool = read_spool(args.spool)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    events = spool_to_events(spool)

    doc = {
        "displayTimeUnit": "ms",
        "metadata": {
            "format": "chrome-trace-events",
            "clock_domains": ["virtual"],
            "flight_capacity": spool["capacity"],
        },
        "traceEvents": events,
    }
    if args.merge:
        try:
            with open(args.merge) as f:
                wall = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: --merge {args.merge}: {e}", file=sys.stderr)
            return 2
        # accept both the object form and the bare-array form
        wall_events = (
            wall if isinstance(wall, list) else wall.get("traceEvents")
        )
        if not isinstance(wall_events, list):
            print(
                f"error: --merge {args.merge}: not a Chrome trace-event "
                f"document", file=sys.stderr,
            )
            return 2
        doc["traceEvents"] = list(wall_events) + events
        if isinstance(wall, dict) and isinstance(wall.get("metadata"), dict):
            md = dict(wall["metadata"])
            md["clock_domains"] = ["wall", "virtual"]
            md["flight_capacity"] = spool["capacity"]
            doc["metadata"] = md
    with open(args.out, "w") as f:
        json.dump(doc, f)
        f.write("\n")
    n = sum(len(fr["records"]) for fr in spool["frames"])
    print(
        f"{args.out}: {n} virtual-time records across "
        f"{len(spool['frames'])} frame(s), "
        f"{len({e['tid'] for e in events if e.get('ph') == 'i'})} host "
        f"track(s)", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
