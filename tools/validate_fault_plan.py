#!/usr/bin/env python
"""Validate a fault-plan JSON document against the documented schema.

Mirrors the metrics validator's role (obs/metrics.validate_metrics_doc):
one reference check shared by the simulator's loader, CI gates, and
downstream tooling. Exit 0 on a valid plan, 2 with a one-line diagnosis
otherwise — never a traceback for malformed input.

  python tools/validate_fault_plan.py plan.json [more.json ...]
  python tools/validate_fault_plan.py --mesh-size 8 chaos.json

--mesh-size N additionally bounds-checks every kill_chip target against
an N-chip mesh (faults/plan.check_backend_ops's rule): a chip index
at/past the mesh is a plan bug, refused before any run loads it.
"""

from __future__ import annotations

import json
import sys


def main(argv: list[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else argv
    if not args or "-h" in args or "--help" in args:
        print(__doc__.strip(), file=sys.stderr)
        return 0 if args else 2
    mesh_size: int | None = None
    if "--mesh-size" in args:
        i = args.index("--mesh-size")
        try:
            mesh_size = int(args[i + 1])
            if mesh_size < 1:
                raise ValueError
        except (IndexError, ValueError):
            print("--mesh-size needs a positive integer chip count",
                  file=sys.stderr)
            return 2
        args = args[:i] + args[i + 2:]
        if not args:
            print("--mesh-size given but no plan file(s)", file=sys.stderr)
            return 2
    from shadow_tpu.faults.plan import (
        FaultPlanError,
        parse_fault_plan,
        validate_fault_plan_doc,
    )

    rc = 0
    for path in args:
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            print(f"{path}: no such file", file=sys.stderr)
            rc = 2
            continue
        except json.JSONDecodeError as e:
            print(f"{path}: not valid JSON: {e}", file=sys.stderr)
            rc = 2
            continue
        try:
            validate_fault_plan_doc(doc)
            faults = parse_fault_plan(doc["faults"])
            if mesh_size is not None:
                # bounds-check chip targets without constraining the op
                # mix (a run-scoped plan may carry device/proc ops too)
                from shadow_tpu.faults.plan import check_backend_ops

                check_backend_ops(
                    [fl for fl in faults if fl.op == "kill_chip"],
                    mesh_size=mesh_size,
                )
        except FaultPlanError as e:
            print(f"{path}: INVALID: {e}", file=sys.stderr)
            rc = 2
            continue
        by_op: dict[str, int] = {}
        for fl in faults:
            by_op[fl.op] = by_op.get(fl.op, 0) + 1
        ops = ", ".join(f"{k}×{v}" for k, v in sorted(by_op.items()))
        print(f"{path}: OK ({len(faults)} injection(s): {ops or 'none'})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
