#!/usr/bin/env python3
"""Scaled multi-hop relay e2e — the tor-minimal analog at 1k+ hosts
(VERDICT r4 #8; reference src/test/tor/minimal + verify.sh:7-22).

Builds a mixed network: R relay hosts + E exit servers + circuit clients
(every stream crosses a 3-relay chained-TCP circuit) ALONGSIDE a tgen-class
bulk-transfer population (tgen_like servers + clients) — heterogeneous
multi-process, multi-protocol interplay like the reference's 9-relay tor
test, then grep-verifies stream successes across both workloads.

    python tools/run_relay.py --hosts 1024 [--cpu-plane] [--rerun]

--rerun executes the whole network twice and also requires byte-identical
circuit-client stdout across runs (determinism1_compare.cmake analog).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RELAY_PORT = 9200
EXIT_PORT = 9300


def build_app(name: str) -> str:
    cc = shutil.which("cc") or shutil.which("gcc")
    out = os.path.join(tempfile.gettempdir(), f"{name}_bin")
    subprocess.run(
        [cc, "-O1", "-o", out, os.path.join(REPO, "tests", "apps",
                                            f"{name}.c")],
        check=True,
    )
    return out


def run_once(args, data_dir: str) -> tuple[int, int, int, int, dict]:
    relay = build_app("relay")
    server = build_app("circuit_server")
    client = build_app("circuit_client")
    tgen = build_app("tgen_like")

    n_relays = args.relays
    # quantity-1 host groups keep their bare name (no numeric suffix),
    # which would break the name{i} references below — keep every group >= 2
    n_exits = max(2, n_relays // 8)
    n_tsrv = max(2, args.hosts // 32)
    n_circ = (args.hosts - n_relays - n_exits - n_tsrv) // 2
    n_tgen = args.hosts - n_relays - n_exits - n_tsrv - n_circ

    # every circuit client picks a distinct 3-relay chain round-robin
    circ_hosts = []
    for i in range(n_circ):
        r1 = 1 + (3 * i) % n_relays
        r2 = 1 + (3 * i + 1) % n_relays
        r3 = 1 + (3 * i + 2) % n_relays
        ex = 1 + i % n_exits
        circuit = (
            f"relay{r2}:{RELAY_PORT}/relay{r3}:{RELAY_PORT}/"
            f"exit{ex}:{EXIT_PORT}/"
        )
        # stagger starts over 8 buckets: 490 simultaneous circuit opens
        # against 9 relays would exceed any realistic accept backlog
        circ_hosts.append(f"""
  circ{i + 1}:
    processes:
      - path: {client}
        args: relay{r1} {RELAY_PORT} {circuit} {args.streams} {args.bytes}
        start_time: {1 + (i % 8)} s""")

    yaml = f"""
general:
  stop_time: {args.stop} s
  seed: 29
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{args.latency_ms} ms" packet_loss 0.0 ]
      ]
experimental:
  use_device_network: {str(not args.cpu_plane).lower()}
  use_device_tcp: {str(not args.cpu_plane).lower()}
  event_capacity: {1 << 17}
  events_per_host_per_window: 8
  sockets_per_host: 256
hosts:
  relay:
    quantity: {n_relays}
    processes:
      - path: {relay}
        args: {RELAY_PORT} 0
        stop_time: {args.stop - 2} s
  exit:
    quantity: {n_exits}
    processes:
      - path: {server}
        args: {EXIT_PORT} 0
        stop_time: {args.stop - 2} s
  tsrv:
    quantity: {n_tsrv}
    processes:
      - path: {tgen}
        args: --server 9100 0
        stop_time: {args.stop - 2} s
  tcli:
    quantity: {n_tgen}
    processes:
      - path: {tgen}
        args: tsrv {n_tsrv} 9100 {args.streams} {args.bytes}
        start_time: 1 s
{"".join(circ_hosts)}
"""
    cfg = os.path.join(tempfile.gettempdir(), "relay_run.yaml")
    with open(cfg, "w") as f:
        f.write(yaml)
    if os.path.exists(data_dir):
        shutil.rmtree(data_dir)
    print(
        f"running {args.hosts} hosts: {n_relays} relays, {n_exits} exits, "
        f"{n_circ} circuit clients, {n_tgen} tgen clients "
        f"({args.streams} streams x {args.bytes} B each) ...",
        flush=True,
    )
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", cfg,
         "--data-directory", data_dir],
        cwd=REPO,
    )
    circ_ok = tgen_ok = 0
    circ_out: dict[str, str] = {}
    for root, _dirs, files in os.walk(data_dir):
        for fn in files:
            if not fn.endswith(".stdout"):
                continue
            with open(os.path.join(root, fn)) as f:
                txt = f.read()
            if "/circ" in root or "circ" in os.path.basename(root):
                circ_ok += txt.count("stream-success")
                circ_out[os.path.relpath(root, data_dir)] = txt
            else:
                tgen_ok += txt.count("stream-success")
    return (circ_ok, n_circ * args.streams, tgen_ok,
            n_tgen * args.streams, circ_out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=1024)
    ap.add_argument("--relays", type=int, default=9)  # tor-minimal's 9
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--bytes", type=int, default=8192)
    ap.add_argument("--stop", type=int, default=20)
    ap.add_argument("--latency-ms", type=int, default=50)
    ap.add_argument("--cpu-plane", action="store_true")
    ap.add_argument("--rerun", action="store_true",
                    help="run twice; require identical circuit outputs")
    ap.add_argument("--data-dir", default=None)
    args = ap.parse_args()
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="relay_run_")

    c_ok, c_want, t_ok, t_want, out1 = run_once(args, data_dir)
    print(f"circuit stream-success {c_ok}/{c_want}; "
          f"tgen stream-success {t_ok}/{t_want}")
    ok = c_ok == c_want and t_ok == t_want
    if args.rerun and ok:
        c2, _, t2, _, out2 = run_once(args, data_dir + "_b")
        same = out1 == out2
        print(f"rerun: circuit {c2}/{c_want}, tgen {t2}/{t_want}, "
              f"outputs identical: {same}")
        ok = ok and c2 == c_want and t2 == t_want and same
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
