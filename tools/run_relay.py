#!/usr/bin/env python3
"""Scaled multi-hop relay e2e — the tor-minimal analog at 1k+ hosts
(VERDICT r4 #8; reference src/test/tor/minimal + verify.sh:7-22).

Builds a mixed network: R relay hosts + E exit servers + circuit clients
(every stream crosses a 3-relay chained-TCP circuit) ALONGSIDE a tgen-class
bulk-transfer population (tgen_like servers + clients) — heterogeneous
multi-process, multi-protocol interplay like the reference's 9-relay tor
test, then grep-verifies stream successes across both workloads.

    python tools/run_relay.py --hosts 1024 [--cpu-plane] [--rerun]

--rerun executes the whole network twice and also requires byte-identical
circuit-client stdout across runs (determinism1_compare.cmake analog).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RELAY_PORT = 9200
EXIT_PORT = 9300


def build_app(name: str) -> str:
    cc = shutil.which("cc") or shutil.which("gcc")
    out = os.path.join(tempfile.gettempdir(), f"{name}_bin")
    subprocess.run(
        [cc, "-O1", "-o", out, os.path.join(REPO, "tests", "apps",
                                            f"{name}.c")],
        check=True,
    )
    return out


def circuit_host_blocks(n_circ: int, n_relays: int, n_exits: int,
                        client_path: str, streams: int, nbytes: int) -> str:
    """YAML host blocks for circuit clients: client i takes a distinct
    3-relay chain round-robin plus an exit, with starts staggered over 8
    buckets (hundreds of simultaneous circuit opens against a handful of
    relays would exceed any realistic accept backlog). Shared with the
    in-suite scale gate (tests/test_relay_e2e.py) so the chain selection
    and the quantity>=2 naming rule live in ONE place."""
    blocks = []
    for i in range(n_circ):
        r1 = 1 + (3 * i) % n_relays
        r2 = 1 + (3 * i + 1) % n_relays
        r3 = 1 + (3 * i + 2) % n_relays
        ex = 1 + i % n_exits
        circuit = (
            f"relay{r2}:{RELAY_PORT}/relay{r3}:{RELAY_PORT}/"
            f"exit{ex}:{EXIT_PORT}/"
        )
        blocks.append(f"""
  circ{i + 1}:
    processes:
      - path: {client_path}
        args: relay{r1} {RELAY_PORT} {circuit} {streams} {nbytes}
        start_time: {1 + (i % 8)} s""")
    return "".join(blocks)


def run_once(args, data_dir: str) -> tuple[int, int, int, int, dict]:
    relay = build_app("relay")
    server = build_app("circuit_server")
    client = build_app("circuit_client")
    tgen = build_app("tgen_like")

    n_relays = args.relays
    # quantity-1 host groups keep their bare name (no numeric suffix),
    # which would break the name{i} references below — keep every group >= 2
    n_exits = max(2, n_relays // 8)
    n_tsrv = max(2, args.hosts // 32)
    n_circ = (args.hosts - n_relays - n_exits - n_tsrv) // 2
    n_tgen = args.hosts - n_relays - n_exits - n_tsrv - n_circ

    circ_hosts = circuit_host_blocks(
        n_circ, n_relays, n_exits, client, args.streams, args.bytes
    )

    yaml = f"""
general:
  stop_time: {args.stop} s
  seed: 29
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{args.latency_ms} ms" packet_loss 0.0 ]
      ]
experimental:
  use_device_network: {str(not args.cpu_plane).lower()}
  use_device_tcp: {str(not args.cpu_plane).lower()}
  event_capacity: {1 << 17}
  events_per_host_per_window: 8
  sockets_per_host: 256
hosts:
  relay:
    quantity: {n_relays}
    processes:
      - path: {relay}
        args: {RELAY_PORT} 0
        stop_time: {args.stop - 2} s
  exit:
    quantity: {n_exits}
    processes:
      - path: {server}
        args: {EXIT_PORT} 0
        stop_time: {args.stop - 2} s
  tsrv:
    quantity: {n_tsrv}
    processes:
      - path: {tgen}
        args: --server 9100 0
        stop_time: {args.stop - 2} s
  tcli:
    quantity: {n_tgen}
    processes:
      - path: {tgen}
        args: tsrv {n_tsrv} 9100 {args.streams} {args.bytes}
        start_time: 1 s
{circ_hosts}
"""
    cfg = os.path.join(tempfile.gettempdir(), "relay_run.yaml")
    with open(cfg, "w") as f:
        f.write(yaml)
    if os.path.exists(data_dir):
        shutil.rmtree(data_dir)
    print(
        f"running {args.hosts} hosts: {n_relays} relays, {n_exits} exits, "
        f"{n_circ} circuit clients, {n_tgen} tgen clients "
        f"({args.streams} streams x {args.bytes} B each) ...",
        flush=True,
    )
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", cfg,
         "--data-directory", data_dir],
        cwd=REPO,
    )
    circ_ok = tgen_ok = 0
    circ_out: dict[str, str] = {}
    for root, _dirs, files in os.walk(data_dir):
        for fn in files:
            if not fn.endswith(".stdout"):
                continue
            with open(os.path.join(root, fn)) as f:
                txt = f.read()
            if "/circ" in root or "circ" in os.path.basename(root):
                circ_ok += txt.count("stream-success")
                circ_out[os.path.relpath(root, data_dir)] = txt
            else:
                tgen_ok += txt.count("stream-success")
    return (circ_ok, n_circ * args.streams, tgen_ok,
            n_tgen * args.streams, circ_out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=1024)
    ap.add_argument("--relays", type=int, default=9)  # tor-minimal's 9
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--bytes", type=int, default=8192)
    ap.add_argument("--stop", type=int, default=20)
    ap.add_argument("--latency-ms", type=int, default=50)
    ap.add_argument("--cpu-plane", action="store_true")
    ap.add_argument("--rerun", action="store_true",
                    help="run twice; require identical circuit outputs")
    ap.add_argument("--data-dir", default=None)
    args = ap.parse_args()
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="relay_run_")

    t0 = time.perf_counter()
    c_ok, c_want, t_ok, t_want, out1 = run_once(args, data_dir)
    wall = time.perf_counter() - t0
    print(f"circuit stream-success {c_ok}/{c_want}; "
          f"tgen stream-success {t_ok}/{t_want}")
    ok = c_ok == c_want and t_ok == t_want
    rerun_identical = None
    if args.rerun and ok:
        c2, _, t2, _, out2 = run_once(args, data_dir + "_b")
        rerun_identical = out1 == out2
        print(f"rerun: circuit {c2}/{c_want}, tgen {t2}/{t_want}, "
              f"outputs identical: {rerun_identical}")
        ok = ok and c2 == c_want and t2 == t_want and rerun_identical
    # Driver-verifiable artifact (VERDICT r4 #7): ONE JSON line with the
    # stream counts, sim/wall, and a content hash of every circuit
    # client's stdout (the determinism fingerprint — two identical-config
    # runs must reproduce it bit-for-bit). Also persisted to
    # docs/relay_artifact.json so the per-round record outlives stdout.
    h = hashlib.sha256()
    for name in sorted(out1):
        h.update(name.encode())
        h.update(out1[name].encode())
    rec = {
        "stage": "relay_tor_analog",
        "hosts": args.hosts,
        "relays": args.relays,
        "plane": "cpu" if args.cpu_plane else "device",
        "circuit_streams": f"{c_ok}/{c_want}",
        "tgen_streams": f"{t_ok}/{t_want}",
        "sim_sec_per_wall_sec": round(args.stop / wall, 3),
        "wall_s": round(wall, 1),
        "output_sha256": h.hexdigest()[:16],
        "rerun_identical": rerun_identical,
        "pass": ok,
    }
    print(json.dumps(rec), flush=True)
    try:
        with open(os.path.join(REPO, "docs", "relay_artifact.json"), "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
