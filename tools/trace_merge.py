#!/usr/bin/env python3
"""Fuse N peers' Chrome traces into one Perfetto timeline.

    tools/trace_merge.py a=peer_a.trace.json b=peer_b.trace.json -o fused.json

Each input is a --trace-out document (obs/trace.py); bare paths take
their peer name from the file stem. Every peer lands on its own pid
(named via process_name metadata) and its timestamps are shifted onto
one clock using the per-document ``metadata.t0_unix`` anchor — the
earliest peer defines t=0, later peers start at their real wall offset.
Traces written before t0_unix existed (format v2) merge too, just
without the cross-peer alignment (offset 0, noted on stderr).

Exit status: 0 on success; 2 on unreadable/malformed inputs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _parse_spec(spec: str) -> tuple[str, str]:
    if "=" in spec:
        name, path = spec.split("=", 1)
        return name, path
    stem = os.path.basename(spec)
    for suf in (".trace.json", ".json"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    return stem, spec


def merge_traces(docs: dict[str, dict]) -> dict:
    """Merge named trace documents: one pid per peer (insertion order of
    the sorted names), timestamps shifted by each document's t0_unix
    delta from the earliest anchor. Returns the fused document."""
    anchors = {
        name: float((doc.get("metadata") or {}).get("t0_unix", 0.0))
        for name, doc in docs.items()
    }
    known = [t for t in anchors.values() if t > 0]
    t_base = min(known) if known else 0.0
    events: list[dict] = []
    for pid, name in enumerate(sorted(docs), start=1):
        doc = docs[name]
        t0 = anchors[name]
        shift_us = (t0 - t_base) * 1e6 if t0 > 0 else 0.0
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the peer-named row above
            out = dict(ev)
            out["pid"] = pid
            if "ts" in out:
                out["ts"] = float(out["ts"]) + shift_us
            events.append(out)
    return {
        "displayTimeUnit": "ms",
        "metadata": {
            "format": "chrome-trace-events",
            "merged": True,
            "peers": {
                n: {"pid": i, "t0_unix": anchors[n],
                    "offset_us": round((anchors[n] - t_base) * 1e6, 3)
                    if anchors[n] > 0 else 0.0}
                for i, n in enumerate(sorted(docs), start=1)
            },
            "t0_unix": t_base,
        },
        "traceEvents": events,
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_merge", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("traces", nargs="+", metavar="NAME=PATH",
                   help="trace documents to fuse (bare PATH uses the "
                        "file stem as the peer name)")
    p.add_argument("-o", "--out", required=True,
                   help="fused trace output path")
    args = p.parse_args(argv)

    docs: dict[str, dict] = {}
    for spec in args.traces:
        name, path = _parse_spec(spec)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {path}: {e}", file=sys.stderr)
            return 2
        if not isinstance(doc.get("traceEvents"), list):
            print(f"error: {path}: not a trace document "
                  f"(no traceEvents)", file=sys.stderr)
            return 2
        if not float((doc.get("metadata") or {}).get("t0_unix", 0.0)):
            print(
                f"note: {path} has no t0_unix anchor (pre-v3 trace); "
                f"merged at offset 0",
                file=sys.stderr,
            )
        docs[name] = doc
    fused = merge_traces(docs)
    from shadow_tpu.obs.metrics import dump_json_atomic

    dump_json_atomic(args.out, fused, indent=None)
    n_ev = len(fused["traceEvents"])
    print(
        f"merged {len(docs)} trace(s), {n_ev} events -> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
