#!/usr/bin/env python3
"""Validate metrics JSON documents against the reference schema.

A standalone CLI wrapper over `obs.metrics.validate_metrics_doc`
(docs/observability.md; the schema version and per-namespace rules —
including `--strict-namespaces` membership of the closed
KNOWN_METRIC_NAMESPACES table, `qdisc.*` since schema v17 —
come from obs/metrics.py, so this tool tracks every schema bump
automatically): CI and tools/tpu_watch.py gate every
captured metrics artifact with this at capture time, so a schema
regression is caught on the line that produced it, not months later by a
consumer.

Usage:  python tools/validate_metrics.py run.metrics.json [...]

Exit status: 0 when every document validates, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+", metavar="METRICS_JSON",
                    help="metrics documents written by --metrics-out")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress per-file ok lines (errors still print)")
    ap.add_argument("--strict-namespaces", action="store_true",
                    help="additionally require every dotted metric key to "
                         "live in KNOWN_METRIC_NAMESPACES (obs/metrics.py) "
                         "— the runtime twin of shadowlint STL008")
    args = ap.parse_args(argv)

    from shadow_tpu.obs.metrics import validate_metrics_doc

    rc = 0
    for path in args.paths:
        try:
            with open(path) as f:
                doc = json.load(f)
            validate_metrics_doc(
                doc, strict_namespaces=args.strict_namespaces
            )
        except (OSError, ValueError) as e:
            print(f"{path}: INVALID: {e}", file=sys.stderr)
            rc = 1
            continue
        if not args.quiet:
            print(f"{path}: ok (schema v{doc['schema_version']})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
