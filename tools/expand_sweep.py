#!/usr/bin/env python3
"""Expand a `sweep:` config matrix into a validated job list.

    python tools/expand_sweep.py sweep.yaml [-o jobs.yaml] [--json]

Each expanded job's config is parsed through the experiment-config loader
and the set is checked for kernel compatibility (all jobs of a fleet share
ONE compiled window kernel — see docs/fleet.md), so a bad sweep spec fails
HERE with a clean nonzero exit and the offending job/field named, never
minutes into a fleet run. The output loads back with
``python -m shadow_tpu sweep --fleet jobs.yaml``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import yaml

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("sweep", help="sweep YAML (base config + sweep: section)")
    p.add_argument(
        "-o", "--out", metavar="PATH",
        help="write the job list here (default: stdout)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit JSON instead of YAML",
    )
    args = p.parse_args(argv)

    # import after arg parsing so --help never pays jax startup
    from shadow_tpu.core.config import ConfigError
    from shadow_tpu.fleet.sweep import SweepError, load_sweep

    try:
        jobs, sweep = load_sweep(args.sweep)
    except (SweepError, ConfigError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except yaml.YAMLError as e:
        print(f"error: {args.sweep}: invalid YAML: {e}", file=sys.stderr)
        return 2

    doc = {"jobs": [j.to_json() for j in jobs]}
    text = (
        json.dumps(doc, indent=1) + "\n" if args.json
        else yaml.safe_dump(doc, sort_keys=False)
    )
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(
            f"{len(jobs)} job(s) validated -> {args.out}", file=sys.stderr
        )
    else:
        sys.stdout.write(text)
        print(f"# {len(jobs)} job(s) validated", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
