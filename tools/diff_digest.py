#!/usr/bin/env python3
"""Divergence bisector: compare two determinism-audit digest documents
(--digest-out output), or a digest document against a checkpoint ring.

Two runs that committed bit-identical histories carry identical digest
chains; this tool turns "the runs disagree" into the FIRST divergent
window (aligned by virtual-time frontier, so different dispatch chunking
or a mid-run resume still compare) and the exact hosts whose sub-chains
differ — one invocation instead of a full-rerun bisect.

Usage:
  python tools/diff_digest.py a.digest.json b.digest.json
  python tools/diff_digest.py a.digest.json --checkpoint ckpt-dir/
  ... [--json]

Exit status: 0 identical / checkpoint matches, 1 divergent, 2 bad input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load(path: str) -> dict:
    from shadow_tpu.obs.audit import validate_digest_doc

    with open(path) as f:
        doc = json.load(f)
    validate_digest_doc(doc)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("digest_a", help="digest JSON written by --digest-out")
    ap.add_argument("digest_b", nargs="?",
                    help="second digest JSON to compare against")
    ap.add_argument("--checkpoint", metavar="DIR",
                    help="audit digest_a against the newest readable "
                         "checkpoint in DIR instead of a second document")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    if bool(args.digest_b) == bool(args.checkpoint):
        print("error: pass exactly one of a second digest file or "
              "--checkpoint DIR", file=sys.stderr)
        return 2

    from shadow_tpu.obs.audit import (
        diff_digest_docs,
        diff_digest_vs_checkpoint,
    )

    try:
        a = _load(args.digest_a)
        if args.checkpoint:
            rep = diff_digest_vs_checkpoint(a, args.checkpoint)
            if args.json:
                print(json.dumps(rep, indent=1))
            elif rep["match"]:
                print(
                    f"checkpoint {os.path.basename(rep['checkpoint'])} "
                    f"matches the digest chain at frontier "
                    f"{rep['checkpoint_frontier_ns']} ns "
                    f"(chain {rep['checkpoint_chain']:#018x})"
                )
            else:
                rec = rep["record"]
                got = f"{rec['chain']:#018x}" if rec else "no record"
                print(
                    f"DIVERGENT: checkpoint chain "
                    f"{rep['checkpoint_chain']:#018x} at frontier "
                    f"{rep['checkpoint_frontier_ns']} ns vs digest "
                    f"document {got}"
                )
            return 0 if rep["match"] else 1
        b = _load(args.digest_b)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    rep = diff_digest_docs(a, b)
    if args.json:
        print(json.dumps(rep, indent=1))
        return 0 if rep["identical"] else 1
    if rep["identical"]:
        print(
            f"identical: {rep['common_windows']} common window(s), final "
            f"chain {a['final']['chain']:#018x}, "
            f"{rep['host_count'][0]} host sub-chains equal"
        )
        return 0
    first = rep["first_divergent_record"]
    if first is not None:
        print(
            f"DIVERGENT at window frontier {first['frontier_ns']} ns "
            f"(record {first['seq_a']} vs {first['seq_b']}): chain "
            f"{first['chain_a']:#018x} != {first['chain_b']:#018x} "
            f"({first['events_a']} vs {first['events_b']} events "
            f"committed)"
        )
    elif "diverged_after_ns" in rep:
        print(
            f"DIVERGENT after frontier {rep['diverged_after_ns']} ns "
            f"(every common window matches; the final chains differ)"
        )
    else:
        print("DIVERGENT: final chains differ")
    if rep["divergent_hosts"]:
        hs = rep["divergent_hosts"]
        shown = ", ".join(str(h) for h in hs[:16])
        more = f" (+{len(hs) - 16} more)" if len(hs) > 16 else ""
        print(f"hosts whose sub-chains differ: {shown}{more}")
    if rep["host_count"][0] != rep["host_count"][1]:
        print(
            f"host counts differ: {rep['host_count'][0]} vs "
            f"{rep['host_count'][1]} (different configs?)"
        )
    return 1


if __name__ == "__main__":
    sys.exit(main())
