"""Profile one udp_flood window batch on the real chip: wall per window,
plus a jax.profiler trace parsed for op-class totals."""
import glob, gzip, json, time, os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

cache = "/root/repo/.jax_cache"
jax.config.update("jax_compilation_cache_dir", cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

from shadow_tpu.sim import build_simulation

H = 10240
cfg = {
    "general": {"stop_time": 4, "seed": 7},
    "network": {"graph": {"type": "gml", "inline": (
        'graph [\n'
        '  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]\n'
        '  edge [ source 0 target 0 latency "10 ms" packet_loss 0.001 ]\n]\n')}},
    "experimental": {
        "event_capacity": 1 << 15,
        "events_per_host_per_window": 12,
        "outbox_slots": 8,
        "router_queue_slots": 16,
        "inbox_slots": 4,
    },
    "hosts": {
        "server": {"quantity": H // 8, "app_model": "udp_flood",
                   "app_options": {"role": "server"}},
        "client": {"quantity": H - H // 8, "app_model": "udp_flood",
                   "app_options": {"interval": "20 ms", "size": 1024,
                                   "runtime": 3}},
    },
}
sim = build_simulation(cfg)
sim.run(until=1_600_000_000, windows_per_dispatch=8)
jax.block_until_ready(sim.state.pool.time)
c0 = sim.counters()

# timed: dispatch sizes 1 / 8 / 32 to split dispatch overhead from window cost
for wpd in (1, 8, 32):
    t0 = time.perf_counter()
    n_disp = 4 if wpd >= 8 else 16
    for _ in range(n_disp):
        sim.state, mn, _press = sim._run_to(sim.state, sim.params,
                                    sim.stop_time, wpd)
    jax.block_until_ready(sim.state.pool.time)
    dt = time.perf_counter() - t0
    print(json.dumps({"wpd": wpd, "dispatches": n_disp,
                      "wall_per_dispatch_ms": round(1000*dt/n_disp, 1),
                      "wall_per_window_ms": round(1000*dt/(n_disp*wpd), 1)}))

c1 = sim.counters()
print("micro_steps delta:", c1["micro_steps"] - c0["micro_steps"],
      "events delta:", c1["events_committed"] - c0["events_committed"])

# profile a few dispatches
trace_dir = "/tmp/flood_trace"
with jax.profiler.trace(trace_dir):
    for _ in range(2):
        sim.state, mn, _press = sim._run_to(sim.state, sim.params, sim.stop_time, 8)
    jax.block_until_ready(sim.state.pool.time)

# parse the trace: op-class totals
files = glob.glob(trace_dir + "/**/*.trace.json.gz", recursive=True)
print("trace files:", files)
if files:
    with gzip.open(files[-1], "rt") as f:
        tr = json.load(f)
    tot = {}
    for ev in tr.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        pid_name = ev.get("pid")
        dur = ev.get("dur", 0)
        key = name.split(".")[0].split("(")[0][:40]
        tot[key] = tot.get(key, 0) + dur
    top = sorted(tot.items(), key=lambda kv: -kv[1])[:25]
    for k, v in top:
        print(f"{v/1000:10.1f} ms  {k}")
