"""Plot events/sec vs shard count from docs/shard_sweep.json
(`python bench.py --shard-sweep` writes it). Emits docs/shard_sweep.png.

VERDICT r4 gate 1c: "a plot of events/sec vs shard count exists".
"""

from __future__ import annotations

import json
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(path=None, out=None):
    path = path or os.path.join(_REPO, "docs", "shard_sweep.json")
    out = out or os.path.join(_REPO, "docs", "shard_sweep.png")
    rows = json.load(open(path))
    stages = sorted({r["stage"] for r in rows})
    fig, ax = plt.subplots(figsize=(6, 4))
    for st in stages:
        pts = sorted(
            [(r["num_shards"], r["events_per_sec"]) for r in rows
             if r["stage"] == st]
        )
        ax.plot([p[0] for p in pts], [p[1] for p in pts], "o-", label=st)
    ax.set_xlabel("virtual islands (shards) on one chip")
    ax.set_ylabel("committed events / sec")
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend()
    ax.set_title("islands engine: throughput vs shard count (one TPU chip)")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")


if __name__ == "__main__":
    main(*sys.argv[1:])
