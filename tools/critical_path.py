#!/usr/bin/env python3
"""Critical-path attribution from a shadow_tpu.profile document.

    tools/critical_path.py shadow.profile.json [--json]

Names the shard the run's wall clock is attributable to: per recorded
interval, the shard holding the minimum committed frontier is what every
blocked neighbor is waiting on (conservative sync bounds everyone's
horizon by that frontier plus their in-edge lookahead), so wall time of
blocking intervals accrues to that interval's laggard. The report names
the winning shard, the in-edge link it throttles hardest (with the baked
lookahead bound when the profile carries the matrix), and the fraction
of total wall / of shard-supersteps lost to blocking.

Exit status: 0 with a report; 1 when the profile has no per-shard
intervals (barrier or global-engine run — nothing to attribute);
2 on a bad document.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="critical_path", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("profile", help="shadow_tpu.profile JSON (--profile-out)")
    p.add_argument("--json", action="store_true",
                   help="print the attribution dict instead of prose")
    args = p.parse_args(argv)

    from shadow_tpu.obs import prof as prof_mod

    try:
        with open(args.profile) as f:
            doc = json.load(f)
        prof_mod.validate_profile_doc(doc)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"error: {args.profile}: {e}", file=sys.stderr)
        return 2
    cp = prof_mod.critical_path(doc)
    if cp is None:
        print(
            "no per-shard intervals in this profile (barrier or "
            "global-engine run) — nothing to attribute",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(cp, indent=1))
        return 0
    print(
        f"critical shard: {cp['critical_shard']} of {cp['shards']} "
        f"({cp['intervals']} intervals)"
    )
    print(
        f"  attributable wall: {cp['attributed_wall_s']:.3f}s of "
        f"{cp['wall_s']:.3f}s ({cp['wall_frac']:.0%})"
    )
    print(f"  blocked fraction:  {cp['blocked_frac']:.3f} "
          f"(blocked / (blocked + supersteps + yields))")
    link = cp.get("link")
    if link:
        bound = (
            f", in-edge lookahead {link['lookahead_ns']}ns"
            if "lookahead_ns" in link else ""
        )
        print(
            f"  hottest link:      shard {link['src']} -> shard "
            f"{link['dst']} ({link['blocked']} blocks{bound})"
        )
    ranked = sorted(
        enumerate(cp["per_shard_wall_s"]), key=lambda kv: -kv[1]
    )[:5]
    print("  per-shard attributed wall:")
    for s, w in ranked:
        if w > 0:
            print(f"    shard {s:>3}: {w:.3f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
