"""Background TPU watcher + incremental benchmark capture.

Rounds 3 and 4 both ended with an empty on-chip record because the axon
TPU worker was down at the driver's END-of-round capture, even though a
healthy window may have existed mid-round. This watcher closes that hole
(VERDICT r4 next-step #1): it probes the backend continuously and, the
moment it answers, drains the full capture queue from docs/bench_notes.md
stage by stage — each stage a separate subprocess whose JSON lines are
appended to BENCH_live.jsonl IMMEDIATELY, so a mid-queue backend death
loses nothing already measured.

Usage:  nohup python tools/tpu_watch.py >> tools/tpu_watch.log 2>&1 &

Files (repo root):
  BENCH_live.jsonl         one JSON object per captured stage line
  .capture_ready_islands   flag: islands-dependent stages (shard sweep,
                           rebalance) may run — created once the round-5
                           exchange-sizing fix lands
  .capture_active          exists while a stage subprocess is running
                           (this box has 1 core: pause heavy local test
                           runs while present)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
LIVE = os.path.join(REPO, "BENCH_live.jsonl")
ISLANDS_FLAG = os.path.join(REPO, ".capture_ready_islands")
ACTIVE_FLAG = os.path.join(REPO, ".capture_active")
PROBE_TIMEOUT_S = 180.0
SLEEP_S = 240.0

# (name, argv, needs_islands_flag, timeout_s)  — priority order per
# VERDICT r4: headline first, tcp_bulk/flood 10k next ("must be the first
# thing captured"), then scale rows, then islands-gated sweeps, then the
# managed-plane rows.
STAGES = [
    # static-analysis gate first: pure CPU (AST walk + one tiny compile),
    # so it lands a row even while the accelerator is still flaky, and
    # every later capture runs against a lint-clean tree
    ("lint_smoke", [PY, "bench.py", "--lint-smoke"], False, 3600),
    # all source-level passes in one stage: AST rules + cross-plane
    # contract auditor + host-thread race lint (the HLO ledger rides the
    # lint_smoke gate above, which pays the variant compiles once)
    ("shadowlint_json",
     [PY, "tools/shadowlint.py", "--contracts", "--threads",
      "--format", "json"],
     False, 600),
    ("phold_16k", [PY, "bench.py"], False, 5400),
    ("audit_smoke", [PY, "bench.py", "--audit-smoke"], False, 7200),
    ("resilience_smoke", [PY, "bench.py", "--resilience-smoke"],
     False, 7200),
    ("serve_smoke", [PY, "bench.py", "--serve-smoke"], False, 7200),
    ("federation_smoke", [PY, "bench.py", "--federation-smoke"],
     False, 7200),
    ("pressure_smoke", [PY, "bench.py", "--pressure-smoke"], False, 7200),
    ("pipeline_smoke", [PY, "bench.py", "--pipeline-smoke"], False, 7200),
    ("hostplane_smoke", [PY, "bench.py", "--hostplane-smoke"],
     False, 7200),
    ("qdisc_smoke", [PY, "bench.py", "--qdisc-smoke"], False, 7200),
    ("async_smoke", [PY, "bench.py", "--async-smoke"], False, 7200),
    # shadowscope gate: profiler-on vs off bit-identical + <=3% overhead,
    # critical-path attribution names the deliberately skewed shard,
    # two-peer /timez merge folds exactly, strict-validated artifact
    ("profile_smoke", [PY, "bench.py", "--profile-smoke"], False, 7200),
    # regression diff of this pass's freshly regenerated artifacts: the
    # async_smoke and profile_smoke stages run the SAME seeded workload,
    # so determinism keys (events, audit chain) must match exactly and
    # thresholded perf keys must hold (rc 1 on regression; artifacts
    # recording ok:false or a stale schema are skipped, not failed)
    ("perf_compare",
     [PY, "tools/perf_compare.py", "async_smoke.metrics.json",
      "profile_smoke.metrics.json", "--json"], False, 600),
    ("balance_smoke", [PY, "bench.py", "--balance-smoke"], False, 7200),
    ("mesh_smoke", [PY, "bench.py", "--mesh-smoke"], False, 7200),
    ("mesh_resilience_smoke",
     [PY, "bench.py", "--mesh-resilience-smoke"], False, 7200),
    ("stages_10k", [PY, "bench.py", "--stages"], False, 10800),
    ("stages_50k", [PY, "bench.py", "--stages-50k"], False, 14400),
    ("stages_100k", [PY, "bench.py", "--stages-100k"], False, 10800),
    ("shard_sweep", [PY, "bench.py", "--shard-sweep"], True, 14400),
    ("rebalance", [PY, "tools/bench_rebalance.py"], True, 7200),
    ("tgen_1k_device", [PY, "tools/run_tgen.py", "--hosts", "1024"],
     False, 10800),
    ("relay_1k", [PY, "tools/run_relay.py", "--hosts", "1024", "--rerun"],
     False, 10800),
    ("tgen_4k_device", [PY, "tools/run_tgen.py", "--hosts", "4096"],
     False, 10800),
]


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe_backend() -> bool:
    """True iff a NON-cpu jax backend answers a trivial dispatch."""
    try:
        proc = subprocess.run(
            [PY, "-c",
             "import jax, jax.numpy as jnp;"
             "jnp.ones(8).sum().block_until_ready();"
             "print('BACKEND_OK', jax.default_backend())"],
            timeout=PROBE_TIMEOUT_S, capture_output=True, text=True,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False
    return (proc.returncode == 0 and "BACKEND_OK" in proc.stdout
            and "BACKEND_OK cpu" not in proc.stdout)


def done_stages() -> set[str]:
    done = set()
    if os.path.exists(LIVE):
        with open(LIVE) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("_rc") == 0:
                    done.add(rec.get("_stage"))
    return done


def gate_metrics_artifact(path: str) -> bool:
    """Schema-gate a metrics artifact at capture time (subprocess so a
    validator crash never takes the watcher down): True iff the document
    validates against obs.metrics' schema."""
    if not os.path.isabs(path):
        path = os.path.join(REPO, path)
    if not os.path.exists(path):
        return False
    try:
        proc = subprocess.run(
            [PY, os.path.join(REPO, "tools", "validate_metrics.py"),
             "-q", path],
            timeout=120, capture_output=True, text=True, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0 and proc.stderr:
        sys.stderr.write(proc.stderr[-500:] + "\n")
    return proc.returncode == 0


def record(stage: str, rc: int, lines: list[str], wall: float) -> None:
    with open(LIVE, "a") as f:
        wrote = False
        for ln in lines:
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            rec["_stage"] = stage
            rec["_rc"] = rc
            rec["_wall_s"] = round(wall, 1)
            rec["_ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            # stage lines that point at a metrics artifact are schema-
            # gated the moment they are captured (tools/validate_metrics)
            mp = rec.get("metrics_out")
            if isinstance(mp, str) and mp:
                rec["_metrics_schema_ok"] = gate_metrics_artifact(mp)
            f.write(json.dumps(rec) + "\n")
            wrote = True
        if not wrote:
            f.write(json.dumps({
                "_stage": stage, "_rc": rc, "_wall_s": round(wall, 1),
                "_ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "_note": "no JSON output",
            }) + "\n")


def run_stage(name: str, argv: list[str], timeout_s: int) -> int:
    log(f"capture: starting {name}: {' '.join(argv)}")
    open(ACTIVE_FLAG, "w").close()
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            argv, cwd=REPO, capture_output=True, text=True,
            timeout=timeout_s,
        )
        rc, out = proc.returncode, proc.stdout
        if proc.stderr:
            sys.stderr.write(proc.stderr[-2000:] + "\n")
    except subprocess.TimeoutExpired as e:
        rc, out = -9, (e.stdout or "")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
    finally:
        try:
            os.remove(ACTIVE_FLAG)
        except OSError:
            pass
    wall = time.monotonic() - t0
    record(name, rc, out.splitlines(), wall)
    log(f"capture: {name} rc={rc} wall={wall:.0f}s")
    return rc


def main() -> None:
    log(f"watcher up; repo={REPO}")
    while True:
        alive = probe_backend()
        pending = [s for s in STAGES if s[0] not in done_stages()
                   and (not s[2] or os.path.exists(ISLANDS_FLAG))]
        if not pending:
            log("all stages captured; watcher exiting")
            return
        log(f"backend={'ALIVE' if alive else 'down'}; "
            f"pending={[s[0] for s in pending]}")
        if alive:
            for name, argv, _, timeout_s in pending:
                rc = run_stage(name, argv, timeout_s)
                if rc != 0 and not probe_backend():
                    log("backend died mid-queue; back to probing")
                    break
        time.sleep(SLEEP_S)


if __name__ == "__main__":
    main()
