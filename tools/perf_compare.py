#!/usr/bin/env python3
"""Diff two shadow_tpu.metrics artifacts with per-key thresholds.

    tools/perf_compare.py BASELINE.json CANDIDATE.json [--json]
    tools/perf_compare.py a.json b.json --thresholds rules.json

Compares every counter/gauge key the two documents share (plus
``meta.wall_s``) under a direction-aware threshold table:

  * ``eq``   — determinism keys (committed events, audit chain): any
               difference is a regression;
  * ``down`` — lower-is-better keys (wall-time percentiles): candidate
               exceeding baseline by more than ``rel_tol`` regresses;
  * ``up``   — higher-is-better keys: candidate falling short of
               baseline by more than ``rel_tol`` regresses.

Unmatched shared keys are reported as drift but never gate. A custom
table (JSON list of ``[pattern, direction, rel_tol]`` rows, first match
wins) replaces the default. Documents whose ``meta.ok`` is false are
SKIPPED (exit 0): a failed producing gate is that stage's failure, not
a perf regression to double-report. Mismatched schema_versions also
skip — cross-schema numbers are not comparable.

Exit status: 0 no regression (or skipped, with the reason printed);
1 at least one thresholded key regressed; 2 unreadable/malformed input.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# first match wins; keys with no row are informational only
DEFAULT_THRESHOLDS: list[tuple[str, str, float]] = [
    ("engine.events_committed", "eq", 0.0),
    ("engine.events_emitted", "eq", 0.0),
    ("audit.chain", "eq", 0.0),
    # wall-time latency percentiles (profiling plane): generous relative
    # bounds — CI boxes are noisy, a real regression is not 10%
    ("prof.*_p50", "down", 0.50),
    ("prof.*_p90", "down", 0.50),
    ("prof.*_p99", "down", 0.75),
    ("prof.blocked_frac", "down", 0.50),
    ("meta.wall_s", "down", 0.50),
]


def _flatten(doc: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    for sect in ("counters", "gauges"):
        for k, v in (doc.get(sect) or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = float(v)
    w = (doc.get("meta") or {}).get("wall_s")
    if isinstance(w, (int, float)) and not isinstance(w, bool):
        out["meta.wall_s"] = float(w)
    return out


def _rule_for(key: str, rules) -> tuple[str, float] | None:
    for pat, direction, tol in rules:
        if fnmatch.fnmatchcase(key, pat):
            return direction, float(tol)
    return None


def compare_docs(base: dict, cand: dict, rules=None) -> dict:
    """Pure comparison: {regressions: [...], drift: [...], compared: N}.
    Each row: {key, base, cand, rel, direction, rel_tol}."""
    rules = DEFAULT_THRESHOLDS if rules is None else rules
    b, c = _flatten(base), _flatten(cand)
    regressions, drift = [], []
    shared = sorted(set(b) & set(c))
    for key in shared:
        bv, cv = b[key], c[key]
        rel = (cv - bv) / abs(bv) if bv else (0.0 if cv == bv else 1.0)
        rule = _rule_for(key, rules)
        row = {"key": key, "base": bv, "cand": cv, "rel": round(rel, 4)}
        if rule is None:
            if cv != bv:
                drift.append(row)
            continue
        direction, tol = rule
        row["direction"], row["rel_tol"] = direction, tol
        regressed = (
            (direction == "eq" and cv != bv)
            or (direction == "down" and rel > tol)
            or (direction == "up" and rel < -tol)
        )
        if regressed:
            regressions.append(row)
        elif cv != bv:
            drift.append(row)
    return {
        "compared": len(shared),
        "regressions": regressions,
        "drift": drift,
    }


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _skip_reason(name: str, doc: dict) -> str | None:
    if doc.get("kind") != "shadow_tpu.metrics":
        return f"{name} is not a shadow_tpu.metrics document"
    if (doc.get("meta") or {}).get("ok") is False:
        return (f"{name} records ok:false — its producing gate already "
                f"failed; not double-reporting as a perf regression")
    return None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_compare", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("baseline", help="reference metrics artifact")
    p.add_argument("candidate", help="metrics artifact under test")
    p.add_argument("--thresholds", metavar="JSON",
                   help="replace the default threshold table "
                        "(list of [pattern, direction, rel_tol] rows)")
    p.add_argument("--json", action="store_true",
                   help="print the full comparison dict")
    args = p.parse_args(argv)

    try:
        base = _load(args.baseline)
        cand = _load(args.candidate)
        rules = None
        if args.thresholds:
            rules = [
                (str(r[0]), str(r[1]), float(r[2]))
                for r in _load(args.thresholds)
            ]
    except (OSError, json.JSONDecodeError, ValueError,
            IndexError, TypeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for name, doc in ((args.baseline, base), (args.candidate, cand)):
        reason = _skip_reason(name, doc)
        if reason:
            print(f"perf_compare: skipped — {reason}")
            return 0
    if base.get("schema_version") != cand.get("schema_version"):
        print(
            f"perf_compare: skipped — schema_version "
            f"{base.get('schema_version')} vs "
            f"{cand.get('schema_version')}: cross-schema numbers are "
            f"not comparable"
        )
        return 0
    result = compare_docs(base, cand, rules)
    result["baseline"] = args.baseline
    result["candidate"] = args.candidate
    if args.json:
        # one line so log scrapers (tools/tpu_watch.py) capture it whole
        print(json.dumps(result))
    else:
        for row in result["regressions"]:
            print(
                f"REGRESSION {row['key']}: {row['base']:g} -> "
                f"{row['cand']:g} ({row['rel']:+.1%}, "
                f"{row['direction']} tol {row['rel_tol']:.0%})"
            )
        for row in result["drift"]:
            print(
                f"drift      {row['key']}: {row['base']:g} -> "
                f"{row['cand']:g} ({row['rel']:+.1%})"
            )
        print(
            f"perf_compare: {result['compared']} shared key(s), "
            f"{len(result['regressions'])} regression(s), "
            f"{len(result['drift'])} drifted"
        )
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
