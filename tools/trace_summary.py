#!/usr/bin/env python3
"""Summarize a Chrome trace-event file written by --trace-out.

Aggregates complete ("X") span events by name — count, total/mean/max
wall milliseconds — and prints the top spans, widest first. Instant and
counter events are tallied but not timed. Accepts both trace-event forms
the spec allows: the object form ({"traceEvents": [...]}) and the bare
JSON array form ([...]). With --json the summary is machine-readable, so
CI can diff span stats across runs.

Pipelined-handoff traces (docs/architecture.md §Pipelined handoff) carry
`issue` / `await` / `host_drain` spans instead of one fused `dispatch`
span per boundary; for those the summary also reports OVERLAP EFFICIENCY
— the fraction of host-drain wall time that fell inside an in-flight
device dispatch (between an issue span's end and its await span's end),
i.e. how much of the host-side handoff the pipeline actually hid.

Multi-worker host-plane traces (docs/architecture.md §Host plane) carry
additional `host_drain` spans on worker tids (one tid per drain worker,
numbered from the host plane's WORKER_TID_BASE); for those the summary
reports DRAIN PARALLELISM — summed per-worker drain time over the union
of worker-busy wall time, i.e. how many workers were effectively
draining at once.

Usage:  python tools/trace_summary.py shadow.trace.json [-n TOP] [--json]
        [--percentiles]  (adds per-span-name p50/p90/p99 duration rows)
"""

from __future__ import annotations

import argparse
import json
import sys


def _pctl(sorted_us: list[float], q: int) -> float:
    """Nearest-rank percentile over an ascending duration list (µs)."""
    rank = max(1, min(len(sorted_us), -(-q * len(sorted_us) // 100)))
    return sorted_us[rank - 1]


def percentiles(doc, qs=(50, 90, 99)) -> list[dict]:
    """Per-span-name duration percentiles (nearest-rank, in ms) from the
    complete ("X") events — the --percentiles table: one row per span
    name, widest p99 first."""
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    durs: dict[str, list[float]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            durs.setdefault(ev.get("name", "?"), []).append(
                float(ev.get("dur", 0.0))
            )
    rows = []
    for name, ds in durs.items():
        ds.sort()
        rows.append({
            "name": name, "count": len(ds),
            **{f"p{q}_ms": _pctl(ds, q) / 1e3 for q in qs},
        })
    rows.sort(key=lambda r: -r[f"p{qs[-1]}_ms"])
    return rows


def summarize(doc) -> tuple[list[dict], dict[str, int]]:
    # the trace-event spec allows two top-level forms: the object form
    # with a traceEvents array, and the bare array form (events only)
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict):
        events = doc.get("traceEvents")
    else:
        events = None
    if not isinstance(events, list):
        raise ValueError(
            "not a Chrome trace-event document (neither a traceEvents "
            "object nor a bare event array)"
        )
    spans: dict[str, dict] = {}
    other: dict[str, int] = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            s = spans.setdefault(
                ev.get("name", "?"),
                {"count": 0, "total_us": 0.0, "max_us": 0.0},
            )
            dur = float(ev.get("dur", 0.0))
            s["count"] += 1
            s["total_us"] += dur
            s["max_us"] = max(s["max_us"], dur)
        elif ph in ("i", "C"):
            key = f"{'instant' if ph == 'i' else 'counter'}:{ev.get('name', '?')}"
            other[key] = other.get(key, 0) + 1
    rows = [
        {
            "name": name,
            "count": s["count"],
            "total_ms": s["total_us"] / 1e3,
            "mean_ms": s["total_us"] / s["count"] / 1e3,
            "max_ms": s["max_us"] / 1e3,
        }
        for name, s in spans.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows, other


def overlap_stats(doc) -> dict | None:
    """Pipelined-handoff overlap efficiency from a driver trace.

    Pairs each `await` span with the latest unpaired `issue` span that
    ended before it: the interval [issue end, await end] is device work
    in flight. `host_drain` span time inside any in-flight interval was
    HIDDEN behind the device; time outside was exposed (the serial-loop
    cost). Returns None when the trace carries no issue/await spans (a
    serial run, or a pre-pipeline trace)."""
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    issues, awaits, drains = [], [], []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        name = ev.get("name")
        if name == "issue":
            issues.append((ts, ts + dur))
        elif name == "await":
            awaits.append((ts, ts + dur))
        elif name == "host_drain":
            drains.append((ts, ts + dur))
    if not issues or not awaits:
        return None
    issues.sort()
    awaits.sort()
    inflight = []
    i = 0
    for a0, a1 in awaits:
        start = None
        while i < len(issues) and issues[i][1] <= a0:
            start = issues[i][1]  # latest issue ending before this await
            i += 1
        if start is not None:
            inflight.append((start, a1))
    total = sum(d1 - d0 for d0, d1 in drains)
    hidden = 0.0
    for d0, d1 in drains:
        for f0, f1 in inflight:
            lo, hi = max(d0, f0), min(d1, f1)
            if hi > lo:
                hidden += hi - lo
    return {
        "issued_ahead": len(issues),
        "adopted": len(inflight),
        "host_drain_ms": total / 1e3,
        "hidden_ms": hidden / 1e3,
        "overlap_efficiency": (hidden / total) if total > 0 else 0.0,
    }


# First worker tid the host plane assigns (coordinator spans stay on the
# driver tid below this). Mirrors shadow_tpu/core/hostplane.py; kept as a
# literal so the tool stays runnable against a bare trace file.
WORKER_TID_BASE = 100


def drain_parallelism(doc) -> dict | None:
    """Host-plane drain parallelism from per-worker `host_drain` spans.

    The host plane emits one `host_drain` span per worker per sharded
    drain, each on its own tid (WORKER_TID_BASE + worker id). Summed
    worker-busy time over the union of worker-busy intervals is the
    effective parallelism: 1.0 means the workers never overlapped (or
    there is only one), N means N workers were always busy together.
    Returns None when the trace has no worker-tid drain spans (a serial
    run, or host_workers: 1)."""
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    per_worker: dict[int, float] = {}
    intervals: list[tuple[float, float]] = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != "host_drain":
            continue
        tid = int(ev.get("tid", 0))
        if tid < WORKER_TID_BASE:
            continue
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        per_worker[tid] = per_worker.get(tid, 0.0) + dur
        intervals.append((ts, ts + dur))
    if not intervals:
        return None
    intervals.sort()
    union = 0.0
    cur0, cur1 = intervals[0]
    for s, e in intervals[1:]:
        if s > cur1:
            union += cur1 - cur0
            cur0, cur1 = s, e
        else:
            cur1 = max(cur1, e)
    union += cur1 - cur0
    busy = sum(per_worker.values())
    return {
        "workers": len(per_worker),
        "worker_drain_ms": busy / 1e3,
        "elapsed_ms": union / 1e3,
        "parallelism": (busy / union) if union > 0 else 0.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace JSON written by --trace-out")
    ap.add_argument("-n", "--top", type=int, default=20,
                    help="spans to print (default 20)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (spans + marker tallies) "
                         "so CI can diff span stats")
    ap.add_argument("--percentiles", action="store_true",
                    help="add per-span-name p50/p90/p99 duration rows "
                         "(nearest-rank over the span's samples)")
    args = ap.parse_args(argv)
    try:
        with open(args.trace) as f:
            doc = json.load(f)
        rows, other = summarize(doc)
        overlap = overlap_stats(doc)
        drain = drain_parallelism(doc)
        pctl_rows = percentiles(doc) if args.percentiles else None
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        out = {
            "spans": rows[: args.top],
            "span_kinds": len(rows),
            "markers": dict(sorted(other.items())),
        }
        if overlap is not None:
            out["overlap"] = overlap
        if drain is not None:
            out["drain_parallelism"] = drain
        if pctl_rows is not None:
            out["percentiles"] = pctl_rows[: args.top]
        print(json.dumps(out, indent=1))
        return 0
    if not rows:
        print("no span events in trace")
        return 0
    w = max(len(r["name"]) for r in rows[: args.top])
    print(f"{'span':<{w}}  {'count':>7}  {'total ms':>10}  "
          f"{'mean ms':>9}  {'max ms':>9}")
    for r in rows[: args.top]:
        print(
            f"{r['name']:<{w}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
            f"{r['mean_ms']:>9.3f}  {r['max_ms']:>9.3f}"
        )
    if overlap is not None:
        print(
            f"\npipeline overlap: {overlap['hidden_ms']:.3f} of "
            f"{overlap['host_drain_ms']:.3f} ms host-drain hidden "
            f"({100 * overlap['overlap_efficiency']:.1f}% efficiency, "
            f"{overlap['adopted']}/{overlap['issued_ahead']} issued-ahead "
            f"dispatches adopted)"
        )
    if drain is not None:
        print(
            f"drain parallelism: {drain['worker_drain_ms']:.3f} ms worker "
            f"drain over {drain['elapsed_ms']:.3f} ms elapsed "
            f"({drain['parallelism']:.2f}x across {drain['workers']} "
            f"workers)"
        )
    if pctl_rows:
        pw = max(len(r["name"]) for r in pctl_rows[: args.top])
        print(f"\n{'span':<{pw}}  {'count':>7}  {'p50 ms':>9}  "
              f"{'p90 ms':>9}  {'p99 ms':>9}")
        for r in pctl_rows[: args.top]:
            print(
                f"{r['name']:<{pw}}  {r['count']:>7}  "
                f"{r['p50_ms']:>9.3f}  {r['p90_ms']:>9.3f}  "
                f"{r['p99_ms']:>9.3f}"
            )
    if other:
        marks = ", ".join(f"{k} x{v}" for k, v in sorted(other.items()))
        print(f"\nmarkers: {marks}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
