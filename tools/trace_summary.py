#!/usr/bin/env python3
"""Summarize a Chrome trace-event file written by --trace-out.

Aggregates complete ("X") span events by name — count, total/mean/max
wall milliseconds — and prints the top spans, widest first. Instant and
counter events are tallied but not timed. Accepts both trace-event forms
the spec allows: the object form ({"traceEvents": [...]}) and the bare
JSON array form ([...]). With --json the summary is machine-readable, so
CI can diff span stats across runs.

Usage:  python tools/trace_summary.py shadow.trace.json [-n TOP] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys


def summarize(doc) -> tuple[list[dict], dict[str, int]]:
    # the trace-event spec allows two top-level forms: the object form
    # with a traceEvents array, and the bare array form (events only)
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict):
        events = doc.get("traceEvents")
    else:
        events = None
    if not isinstance(events, list):
        raise ValueError(
            "not a Chrome trace-event document (neither a traceEvents "
            "object nor a bare event array)"
        )
    spans: dict[str, dict] = {}
    other: dict[str, int] = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            s = spans.setdefault(
                ev.get("name", "?"),
                {"count": 0, "total_us": 0.0, "max_us": 0.0},
            )
            dur = float(ev.get("dur", 0.0))
            s["count"] += 1
            s["total_us"] += dur
            s["max_us"] = max(s["max_us"], dur)
        elif ph in ("i", "C"):
            key = f"{'instant' if ph == 'i' else 'counter'}:{ev.get('name', '?')}"
            other[key] = other.get(key, 0) + 1
    rows = [
        {
            "name": name,
            "count": s["count"],
            "total_ms": s["total_us"] / 1e3,
            "mean_ms": s["total_us"] / s["count"] / 1e3,
            "max_ms": s["max_us"] / 1e3,
        }
        for name, s in spans.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows, other


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace JSON written by --trace-out")
    ap.add_argument("-n", "--top", type=int, default=20,
                    help="spans to print (default 20)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (spans + marker tallies) "
                         "so CI can diff span stats")
    args = ap.parse_args(argv)
    try:
        with open(args.trace) as f:
            doc = json.load(f)
        rows, other = summarize(doc)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "spans": rows[: args.top],
            "span_kinds": len(rows),
            "markers": dict(sorted(other.items())),
        }, indent=1))
        return 0
    if not rows:
        print("no span events in trace")
        return 0
    w = max(len(r["name"]) for r in rows[: args.top])
    print(f"{'span':<{w}}  {'count':>7}  {'total ms':>10}  "
          f"{'mean ms':>9}  {'max ms':>9}")
    for r in rows[: args.top]:
        print(
            f"{r['name']:<{w}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
            f"{r['mean_ms']:>9.3f}  {r['max_ms']:>9.3f}"
        )
    if other:
        marks = ", ".join(f"{k} x{v}" for k, v in sorted(other.items()))
        print(f"\nmarkers: {marks}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
