#!/usr/bin/env python3
"""Operator client for the serve daemon (`python -m shadow_tpu serve`).

Talks HTTP over the daemon's unix socket (docs/serving.md):

    shadowctl.py --socket DIR/serve.sock health
    shadowctl.py --socket DIR/serve.sock submit sweep.yaml [--tenant t1]
    shadowctl.py --socket DIR/serve.sock status [SWEEP_ID]
    shadowctl.py --socket DIR/route.sock status --peers a=DIR_A b=DIR_B
    shadowctl.py --socket DIR/serve.sock results SWEEP_ID [--wait SECS]
    shadowctl.py --socket DIR/serve.sock metrics
    shadowctl.py --socket DIR/serve.sock top [--once] [--interval S]
    shadowctl.py --socket DIR/serve.sock drain

Exit status: 0 ok; 2 usage / bad sweep document; 3 daemon unreachable;
4 submission shed (admission backpressure — the printed JSON carries
`retry_after_s`); 5 the sweep finished with failed jobs.
"""

from __future__ import annotations

import argparse
import json
import sys

REPO_ROOT = __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
)
sys.path.insert(0, REPO_ROOT)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadowctl", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--socket", required=True, metavar="PATH",
                   help="the daemon's unix socket (<state-dir>/serve.sock)")
    p.add_argument("--timeout", type=float, default=60.0, metavar="SECS")
    p.add_argument("--retries", type=int, default=2, metavar="N",
                   help="bounded in-client retries (jittered backoff) "
                   "when the daemon socket refuses a connection — rides "
                   "out a restart window instead of a bare traceback")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("health", help="GET /healthz")
    sub.add_parser("metrics", help="GET /metricz (the current-schema "
                   "serve.* + pressure.* doc; federation.* on a router)")
    sub.add_parser("drain", help="graceful drain: flush the running "
                   "fleet to its checkpoint and exit")
    pt = sub.add_parser("top", help="live text dashboard from GET /timez "
                        "(latency percentiles, interval throughput, "
                        "critical-path posture); point it at a router "
                        "socket for the fleet-merged view")
    pt.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripts, tests)")
    pt.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="refresh period (default 2.0)")
    ps = sub.add_parser("submit", help="submit a sweep document")
    ps.add_argument("sweep", help="sweep YAML (base config + sweep: matrix)")
    ps.add_argument("--tenant", default="default")
    ps.add_argument("--fault-plan", metavar="JSON",
                    help="daemon-level chaos plan (backend + pressure "
                    "ops: kill_backend/stall_backend/exhaust_backend/"
                    "saturate_pool) attached to this sweep")
    pst = sub.add_parser("status", help="list sweeps, or show one")
    pst.add_argument("id", nargs="?")
    pst.add_argument("--peers", nargs="+", metavar="SPEC", default=None,
                     help="federation members (NAME=STATE_DIR or bare "
                     "STATE_DIR, docs/serving.md §7): print one health "
                     "row per member instead of a single-daemon status")
    pr = sub.add_parser("results", help="print a sweep's per-job rows")
    pr.add_argument("id")
    pr.add_argument("--wait", type=float, metavar="SECS", default=None,
                    help="block until the sweep settles (max SECS)")
    return p


def _fmt_ns(v) -> str:
    v = int(v)
    if v >= 1_000_000_000:
        return f"{v / 1e9:.2f}s"
    if v >= 1_000_000:
        return f"{v / 1e6:.1f}ms"
    if v >= 1_000:
        return f"{v / 1e3:.1f}us"
    return f"{v}ns"


def render_top(doc: dict) -> str:
    """One text frame of the /timez dashboard: histogram percentiles,
    recent interval throughput, and the critical-path posture. Works on
    a single daemon's profile document and on the router's merged one
    (which carries `series` + `peers` instead of one ring)."""
    from shadow_tpu.obs.hist import LogHistogram
    from shadow_tpu.obs.prof import critical_path

    lines = []
    peers = doc.get("peers")
    if peers:
        up = ", ".join(
            f"{n}({p.get('recorded', 0)}iv)" for n, p in sorted(peers.items())
        )
        lines.append(f"shadowscope top — {len(peers)} peer(s): {up}")
    else:
        lines.append(
            f"shadowscope top — {doc.get('recorded', 0)} interval(s), "
            f"{doc.get('dropped', 0)} dropped"
        )
    hists = doc.get("hists") or {}
    if hists:
        lines.append(
            f"{'histogram':<22}{'count':>8}{'p50':>10}{'p90':>10}"
            f"{'p99':>10}{'max':>10}"
        )
        for name in sorted(hists):
            s = LogHistogram.from_doc(hists[name]).summary()
            lines.append(
                f"{name:<22}{s['count']:>8}{_fmt_ns(s['p50']):>10}"
                f"{_fmt_ns(s['p90']):>10}{_fmt_ns(s['p99']):>10}"
                f"{_fmt_ns(s['max']):>10}"
            )
    else:
        lines.append("(no histogram samples yet)")
    rows = doc.get("intervals") or doc.get("series") or []
    recent = rows[-5:]
    if recent:
        lines.append("recent intervals:")
        for r in recent:
            dw = float(r.get("d_wall_s", 0.0)) or 1e-9
            tag = f" [{r['peer']}]" if "peer" in r else ""
            lines.append(
                f"  +{r.get('wall_s', 0.0):>9.3f}s{tag} "
                f"vt={_fmt_ns(r.get('vt_ns', 0))} "
                f"ev/s={r.get('d_events', 0) / dw:,.0f} "
                f"win={r.get('d_windows', 0)} "
                f"blocked={r.get('d_blocked', 0)}"
            )
    cp = critical_path(doc)
    if cp is not None:
        link = cp.get("link")
        edge = ""
        if link:
            edge = (
                f", throttling shard {link['dst']} "
                f"({link['blocked']} blocks"
                + (f", lookahead {_fmt_ns(link['lookahead_ns'])}"
                   if "lookahead_ns" in link else "")
                + ")"
            )
        lines.append(
            f"critical path: shard {cp['critical_shard']} holds "
            f"{cp['wall_frac']:.0%} of wall "
            f"({cp['attributed_wall_s']:.3f}s of {cp['wall_s']:.3f}s), "
            f"blocked_frac={cp['blocked_frac']:.2f}{edge}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from shadow_tpu.serve.client import (
        ServeClient, ServeClientError, Shed,
    )

    client = ServeClient(
        args.socket, timeout=args.timeout, retries=args.retries
    )
    try:
        if args.cmd == "status" and getattr(args, "peers", None):
            # federation fleet view (docs/serving.md §7): one line per
            # member, best-effort — an unreachable peer is a row, not
            # an error exit (that is exactly when you need the others)
            import os

            from shadow_tpu.serve.federation import parse_peer_spec

            worst = 0
            for spec in args.peers:
                name, state_dir = parse_peer_spec(spec)
                sock = os.path.join(state_dir, "serve.sock")
                peer_client = ServeClient(
                    sock, timeout=args.timeout, retries=args.retries
                )
                try:
                    h = peer_client.health()
                except ServeClientError as e:
                    print(json.dumps({
                        "peer": name, "ok": False, "unreachable": True,
                        "error": str(e), "socket": sock,
                    }))
                    worst = 3
                    continue
                q = h.get("queue") or {}
                print(json.dumps({
                    "peer": name,
                    "ok": h.get("ok"),
                    "draining": h.get("draining"),
                    "queue_depth": q.get("depth"),
                    "running": q.get("running"),
                    "journal_lag": (h.get("journal") or {}).get("lag"),
                    "retry_after_s": h.get("retry_after_s"),
                    "socket": sock,
                }))
            return worst
        if args.cmd == "health":
            print(json.dumps(client.health(), indent=1))
            return 0
        if args.cmd == "metrics":
            print(json.dumps(client.metrics(), indent=1))
            return 0
        if args.cmd == "drain":
            print(json.dumps(client.drain()))
            return 0
        if args.cmd == "top":
            import time as time_mod

            while True:
                frame = render_top(client.timez())
                if args.once:
                    print(frame)
                    return 0
                # clear + home, like top(1); one frame per interval
                print("\x1b[2J\x1b[H" + frame, flush=True)
                time_mod.sleep(args.interval)
        if args.cmd == "submit":
            import yaml

            with open(args.sweep) as f:
                doc = yaml.safe_load(f)
            faults = None
            if args.fault_plan:
                with open(args.fault_plan) as f:
                    plan = json.load(f)
                faults = plan["faults"] if isinstance(plan, dict) else plan
            try:
                out = client.submit(doc, tenant=args.tenant,
                                    backend_faults=faults)
            except Shed as e:
                print(json.dumps(e.body))
                return 4
            print(json.dumps(out))
            return 0
        if args.cmd == "status":
            if args.id:
                print(json.dumps(client.sweep(args.id), indent=1))
            else:
                # lead with the daemon's live posture: memory headroom +
                # pressure-ladder gauges from /healthz (docs/serving.md),
                # plus the async/balance posture (ISSUE 11) — frontier
                # spread, WHICH shard is the laggard, and the balance
                # plane's state — so an operator sees a hot shard here
                # instead of grepping metrics JSON
                h = client.health()
                asy = h.get("async") or {}
                bal = dict(h.get("balance") or {})
                mesh = dict(h.get("mesh") or {})
                if bal and "state" not in bal:
                    # the outer-ring (packing/steal) posture has no
                    # migration state machine; say so explicitly
                    bal["state"] = "stable"
                print(json.dumps({
                    "health": {
                        "ok": h.get("ok"),
                        "queue_depth": h.get("queue", {}).get("depth"),
                        "memory": h.get("memory"),
                        "pressure": {
                            k: v
                            for k, v in (h.get("pressure") or {}).items()
                            if v
                        },
                        "async": {
                            "frontier_spread_ns":
                                asy.get("frontier_spread_ns"),
                            "laggard_shard": asy.get("laggard_shard"),
                            "laggard_lane": asy.get("laggard_lane"),
                        } if asy else {},
                        "balance": bal,
                        # mesh posture (schema v12): chips up/total,
                        # the dead set, and the last relayout record —
                        # a degraded mesh is visible HERE, not only in
                        # the metrics artifact
                        "mesh": {
                            "chips": (
                                f"{mesh.get('chips_up')}/"
                                f"{mesh.get('chips_total')}"
                            ),
                            "chips_down": mesh.get("chips_down"),
                            "exchange_rebuilds":
                                mesh.get("exchange_rebuilds"),
                            "relayouts": mesh.get("relayouts"),
                            "re_expansions": mesh.get("re_expansions"),
                            "last_relayout": mesh.get("last_relayout"),
                        } if mesh else {},
                    }
                }))
                for row in client.sweeps():
                    print(json.dumps(row))
            return 0
        if args.cmd == "results":
            info = (
                client.wait(args.id, timeout_s=args.wait)
                if args.wait is not None else client.sweep(args.id)
            )
            for row in info.get("results") or []:
                print(json.dumps(row))
            print(json.dumps(
                {"id": info["id"], "status": info["status"],
                 "stats": info.get("stats")},
            ))
            return 0 if info["status"] == "done" else 5
    except (ServeClientError, FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 3 if "unreachable" in str(e) else 2
    return 2


if __name__ == "__main__":
    sys.exit(main())
