#!/usr/bin/env python3
"""shadowlint CLI: device-purity & determinism static analysis.

Runs the STL0xx AST rule set (shadow_tpu/analysis) over the tree —
default scope: shadow_tpu/, tools/, bench.py — and reports findings that
are neither ``# noqa``-suppressed nor grandfathered by the baseline
file (.shadowlint_baseline.json at the repo root).

Usage:
  python tools/shadowlint.py                      # text report
  python tools/shadowlint.py --format json        # machine-readable
  python tools/shadowlint.py shadow_tpu/net       # restrict scope
  python tools/shadowlint.py --select STL003      # one rule
  python tools/shadowlint.py --no-baseline        # include grandfathered
  python tools/shadowlint.py --write-baseline     # grandfather the rest

Exit status: 0 when no non-baselined findings, 1 otherwise (2 on a
parse/usage error).  CI wiring: tools/tpu_watch.py runs the JSON form as
a capture stage; ``bench.py --lint-smoke`` is the schema'd smoke gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_SCOPE = ("shadow_tpu", "tools", "bench.py")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", metavar="PATH",
                    help=f"files/dirs to lint (default: {' '.join(DEFAULT_SCOPE)})")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", action="append", metavar="STL0xx",
                    help="restrict to these rule codes (repeatable)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: <repo>/.shadowlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report grandfathered findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding to the baseline file and exit 0")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the per-finding lines (summary only)")
    args = ap.parse_args(argv)

    from shadow_tpu.analysis import linter

    paths = args.paths or [os.path.join(_REPO, p) for p in DEFAULT_SCOPE]
    select = (
        {c.strip().upper() for c in args.select} if args.select else None
    )
    if select is not None:
        from shadow_tpu.analysis.rules import RULE_INDEX

        unknown = select - set(RULE_INDEX)
        if unknown:
            print(f"unknown rule code(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    try:
        findings = linter.lint_paths(paths, _REPO, select=select)
    except (SyntaxError, OSError) as e:
        print(f"shadowlint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(_REPO, linter.BASELINE_NAME)
    if args.write_baseline:
        doc = linter.write_baseline(findings, baseline_path)
        print(
            f"wrote {len(doc['entries'])} baseline entr"
            f"{'y' if len(doc['entries']) == 1 else 'ies'} to {baseline_path}"
        )
        return 0

    baseline = (
        {} if args.no_baseline else linter.load_baseline(baseline_path)
    )
    new, old = linter.split_baselined(findings, baseline)
    scanned = list(linter.iter_python_files(paths))
    doc = linter.findings_doc(new, old, scanned)

    if args.format == "json":
        # one line: tools/tpu_watch.py captures stage output line-wise
        json.dump(doc, sys.stdout)
        sys.stdout.write("\n")
    else:
        if not args.quiet:
            for f in new:
                print(f.render())
        print(
            f"shadowlint: {len(new)} finding(s), "
            f"{len(old)} grandfathered, {len(scanned)} file(s) scanned"
        )
    return 0 if not new else 1


if __name__ == "__main__":
    sys.exit(main())
