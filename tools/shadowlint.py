#!/usr/bin/env python3
"""shadowlint CLI: device-purity, determinism & contract static analysis.

Four passes over the tree (default scope: shadow_tpu/, tools/, bench.py;
docs/ and tests/ for the contract pass):

  (default)     the STL0xx AST rule set (shadow_tpu/analysis/rules.py)
  --contracts   the SLC0xx cross-plane contract auditor (contracts.py):
                metric-namespace table vs emit sites, fault-op registries
                vs injector arms and docs tables, schema-version literals,
                config_spec.md vs the loader, supervisor policy sets
  --threads     the STH0xx host-thread race lint (threads.py): declared-
                guard discipline over the thread-bearing host modules
  --hlo         the HLO budget ledger (hlo_audit.py): per-variant
                collective/sort/gather/byte budgets vs the checked-in
                shadow_tpu/analysis/hlo_baseline.json

Findings that are neither ``# noqa``-suppressed nor grandfathered by the
baseline file (.shadowlint_baseline.json) fail the run.

Usage:
  python tools/shadowlint.py                      # STL text report
  python tools/shadowlint.py --contracts --threads --format json
  python tools/shadowlint.py --hlo                # ledger check (compiles)
  python tools/shadowlint.py --hlo --write-hlo-baseline --virtual-devices 8
  python tools/shadowlint.py --select STH001      # one rule, any pass
  python tools/shadowlint.py --write-baseline     # grandfather the rest

Exit status: 0 when no non-baselined findings, 1 otherwise, 2 on an
environment/usage failure (unparseable source, missing/corrupt HLO
baseline, unknown rule code) — each exit-2 path prints a one-line
remediation hint.  CI wiring: tools/tpu_watch.py runs the JSON form as a
capture stage; ``bench.py --lint-smoke`` is the schema'd smoke gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_SCOPE = ("shadow_tpu", "tools", "bench.py")


def _fail2(msg: str, hint: str) -> int:
    print(f"shadowlint: {msg}", file=sys.stderr)
    print(f"hint: {hint}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", metavar="PATH",
                    help=f"files/dirs for the STL pass "
                         f"(default: {' '.join(DEFAULT_SCOPE)})")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", action="append", metavar="CODE",
                    help="restrict to these rule codes (repeatable; "
                         "STL/SLC/STH)")
    ap.add_argument("--contracts", action="store_true",
                    help="run the cross-plane contract auditor (SLC0xx)")
    ap.add_argument("--threads", action="store_true",
                    help="run the host-thread race lint (STH0xx)")
    ap.add_argument("--hlo", action="store_true",
                    help="check the HLO budget ledger against "
                         "shadow_tpu/analysis/hlo_baseline.json "
                         "(compiles every kernel variant — slow)")
    ap.add_argument("--write-hlo-baseline", action="store_true",
                    help="with --hlo: regenerate the ledger baseline "
                         "from the current lowerings and exit 0")
    ap.add_argument("--virtual-devices", type=int, default=0,
                    metavar="N",
                    help="force N virtual CPU devices before jax "
                         "initializes (lets the mesh/shard_map ledger "
                         "cells lower on a 1-chip box)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: "
                         "<repo>/.shadowlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report grandfathered "
                         "findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding to the baseline "
                         "file and exit 0")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the per-finding lines (summary only)")
    args = ap.parse_args(argv)

    if args.virtual_devices:
        from shadow_tpu.parallel.virtualize import force_cpu_devices

        force_cpu_devices(
            args.virtual_devices,
            cache_dir=os.path.join(_REPO, ".jax_cache"),
        )

    from shadow_tpu.analysis import contracts, linter, threads
    from shadow_tpu.analysis.rules import RULE_INDEX

    all_codes = (
        set(RULE_INDEX) | set(contracts.CONTRACT_RULES)
        | set(threads.THREAD_RULES) | {"SLH001"}
    )
    select = (
        {c.strip().upper() for c in args.select} if args.select else None
    )
    if select is not None:
        unknown = select - all_codes
        if unknown:
            return _fail2(
                f"unknown rule code(s): {sorted(unknown)}",
                f"known codes: {', '.join(sorted(all_codes))}",
            )

    passes: dict[str, int] = {}
    findings = []
    stl_select = (
        None if select is None else select & set(RULE_INDEX)
    )
    run_stl = select is None or bool(stl_select)
    paths = args.paths or [os.path.join(_REPO, p) for p in DEFAULT_SCOPE]
    if run_stl:
        try:
            stl = linter.lint_paths(paths, _REPO, select=stl_select)
        except SyntaxError as e:
            return _fail2(
                f"cannot parse {e.filename}:{e.lineno}: {e.msg}",
                "fix the syntax error (shadowlint needs ast-parseable "
                "sources) or narrow the PATH arguments around the file",
            )
        except OSError as e:
            return _fail2(str(e), "check the PATH arguments exist and "
                                  "are readable")
        findings += stl
        passes["lint"] = len(stl)

    def _want(codes) -> bool:
        return select is None or bool(select & set(codes))

    if args.contracts and _want(contracts.CONTRACT_RULES):
        slc = contracts.audit_tree(_REPO)
        if select is not None:
            slc = [f for f in slc if f.code in select]
        findings += slc
        passes["contracts"] = len(slc)
    if args.threads and _want(threads.THREAD_RULES):
        try:
            sth = threads.lint_threads_paths(_REPO)
        except SyntaxError as e:
            return _fail2(
                f"cannot parse a thread-lint module: {e}",
                "fix the syntax error; the race lint walks "
                "analysis/threads.THREAD_MODULES",
            )
        if select is not None:
            sth = [f for f in sth if f.code in select]
        findings += sth
        passes["threads"] = len(sth)

    hlo_findings: list[linter.Finding] = []
    if args.hlo:
        from shadow_tpu.analysis import hlo_audit

        bpath = hlo_audit.baseline_path(_REPO)
        if not args.write_hlo_baseline:
            # fail BEFORE paying the compiles when the baseline is bad
            try:
                baseline = hlo_audit.load_hlo_baseline(bpath)
            except hlo_audit.HloBaselineError as e:
                return _fail2(str(e).split(" — ")[0],
                              str(e).split(" — ")[-1])
        ledger = hlo_audit.budget_ledger(
            hlo_audit.default_ledger_variants()
        )
        if args.write_hlo_baseline:
            hlo_audit.write_hlo_baseline(ledger, bpath)
            print(
                f"wrote {len(ledger)} HLO ledger entr"
                f"{'y' if len(ledger) == 1 else 'ies'} to {bpath}"
            )
            return 0
        for problem in hlo_audit.check_ledger(ledger, baseline):
            hlo_findings.append(linter.Finding(
                path="shadow_tpu/analysis/hlo_baseline.json", line=1,
                col=0, code="SLH001", message=problem,
                text=problem.split(":", 1)[0],
            ))
        if select is not None:
            hlo_findings = [f for f in hlo_findings if f.code in select]
        findings += hlo_findings
        passes["hlo"] = len(hlo_findings)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    baseline_path = args.baseline or os.path.join(
        _REPO, linter.BASELINE_NAME
    )
    if args.write_baseline:
        doc = linter.write_baseline(findings, baseline_path)
        print(
            f"wrote {len(doc['entries'])} baseline entr"
            f"{'y' if len(doc['entries']) == 1 else 'ies'} to "
            f"{baseline_path}"
        )
        return 0

    try:
        baseline = (
            {} if args.no_baseline else linter.load_baseline(baseline_path)
        )
    except ValueError as e:
        return _fail2(str(e), "regenerate with `python "
                              "tools/shadowlint.py --write-baseline`")
    new, old = linter.split_baselined(findings, baseline)
    # per-pass counts are post-baseline: grandfathered findings drop out
    code_pass = {"STL": "lint", "SLC": "contracts", "STH": "threads",
                 "SLH": "hlo"}
    for name in list(passes):
        passes[name] = 0
    for f in new:
        name = code_pass.get(f.code[:3])
        if name is not None:
            passes[name] = passes.get(name, 0) + 1
    scanned = list(linter.iter_python_files(paths)) if run_stl else []
    doc = linter.findings_doc(new, old, scanned, passes=passes)

    if args.format == "json":
        # one line: tools/tpu_watch.py captures stage output line-wise
        json.dump(doc, sys.stdout)
        sys.stdout.write("\n")
    else:
        if not args.quiet:
            for f in new:
                print(f.render())
        per_pass = ", ".join(
            f"{k}={v}" for k, v in sorted(passes.items())
        )
        print(
            f"shadowlint: {len(new)} finding(s) [{per_pass}], "
            f"{len(old)} grandfathered, {len(scanned)} file(s) scanned"
        )
    return 0 if not new else 1


if __name__ == "__main__":
    sys.exit(main())
