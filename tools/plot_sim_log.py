#!/usr/bin/env python3
"""Plot simulator progress and per-host traffic from a parsed log
(reference analog: src/tools/plot-shadow.py, the companion to
parse-shadow.py).

Input: the JSON emitted by tools/parse_sim_log.py. Output: a PNG with
(1) simulated-time progress vs wall time (the headline PDES speed curve)
and (2) per-host rx/tx byte series from tracker heartbeats.

Usage:
    python -m shadow_tpu cfg.yaml 2>&1 | python tools/parse_sim_log.py \
        > sim.json
    python tools/plot_sim_log.py sim.json -o sim.png
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("json", nargs="?", default="-")
    ap.add_argument("-o", "--output", default="sim.png")
    args = ap.parse_args()

    data = json.load(
        sys.stdin if args.json == "-" else open(args.json)
    )

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    hb = data.get("heartbeats", [])
    trackers = data.get("trackers", {})

    n_plots = (1 if hb else 0) + (1 if trackers else 0)
    if n_plots == 0:
        print("nothing to plot (no heartbeats/trackers in input)")
        return 1
    fig, axes = plt.subplots(n_plots, 1, figsize=(8, 4 * n_plots))
    if n_plots == 1:
        axes = [axes]
    ax_i = 0

    if hb:
        ax = axes[ax_i]
        ax_i += 1
        sim_s = [h["sim_s"] for h in hb]
        xs = list(range(len(sim_s)))
        ax.plot(xs, sim_s, marker="o", ms=3)
        ax.set_xlabel("heartbeat #")
        ax.set_ylabel("simulated seconds")
        ax.set_title("simulation progress")
        ax.grid(True, alpha=0.3)

    if trackers:
        ax = axes[ax_i]
        for host, series in sorted(trackers.items()):
            xs = [p.get("sim_s", i) for i, p in enumerate(series)]
            rx = [p.get("rx_bytes", 0) for p in series]
            tx = [p.get("tx_bytes", 0) for p in series]
            ax.plot(xs, rx, label=f"{host} rx")
            ax.plot(xs, tx, label=f"{host} tx", linestyle="--")
        ax.set_xlabel("simulated seconds")
        ax.set_ylabel("bytes")
        ax.set_title("per-host traffic (tracker heartbeats)")
        if len(trackers) <= 12:
            ax.legend(fontsize=7)
        ax.grid(True, alpha=0.3)

    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
