from shadow_tpu.core import simtime, units

import pytest

pytestmark = pytest.mark.quick



def test_time_parsing():
    assert units.parse_time_ns("50 ms") == 50 * simtime.NS_PER_MS
    assert units.parse_time_ns("10") == 10 * simtime.NS_PER_SEC
    assert units.parse_time_ns(10) == 10 * simtime.NS_PER_SEC
    assert units.parse_time_ns("2 min") == 120 * simtime.NS_PER_SEC
    assert units.parse_time_ns("1.5 s") == 1_500_000_000
    assert units.parse_time_ns("100 us") == 100_000
    assert units.parse_time_ns("1 h") == 3600 * simtime.NS_PER_SEC
    assert units.parse_time_ns("3 ns") == 3


def test_bit_parsing():
    assert units.parse_bits("1 Gbit") == 10**9
    assert units.parse_bits("81920 Kibit") == 81920 * 1024
    assert units.parse_bits("10 Mbit") == 10 * 10**6
    assert units.parse_bits("100") == 100
    assert units.parse_bits("1 MiB") == 2**20 * 8  # byte bandwidths → bits


def test_byte_parsing():
    assert units.parse_bytes("1 KiB") == 1024
    assert units.parse_bytes("1 kB") == 1000
    assert units.parse_bytes("174760") == 174760
    assert units.parse_bytes(131072) == 131072


def test_bad_units():
    with pytest.raises(units.UnitParseError):
        units.parse_time_ns("10 parsecs")
    with pytest.raises(units.UnitParseError):
        units.parse_bits("nonsense")
