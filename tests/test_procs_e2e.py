"""End-to-end managed-process tests: REAL Linux binaries run under the
native LD_PRELOAD shim, their syscalls serviced by the ProcessDriver against
the simulated network + virtual clock.

Reference test model: dual-target tests (SURVEY.md §4) — the same C
programs compile and run natively too; under the simulator their observed
round-trip times must equal the CONFIGURED topology latency exactly
(virtual time), which no native run could produce.
"""

import subprocess

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver
from shadow_tpu.procs.driver import NS_PER_SEC, ProcessDriver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)


def test_udp_echo_virtual_rtt(apps):
    """UDP echo between two real processes; RTT == 2 × configured latency
    on the virtual clock, bit-exactly."""
    lat = 50_000_000  # 50 ms
    d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=lat)
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    d.add_process(hs, [apps["udp_echo_server"], "9000", "3"], start_time=0)
    d.add_process(
        hc, [apps["udp_echo_client"], "server", "9000", "3"],
        start_time=NS_PER_SEC,
    )
    d.run()
    sp, cp = d.procs
    assert sp.exit_code == 0, sp.stderr
    assert cp.exit_code == 0, cp.stderr
    lines = cp.stdout.decode().strip().splitlines()
    rtts = [int(l.split()[1]) for l in lines if l.startswith("rtt")]
    assert len(rtts) == 3
    # virtual time: every RTT is exactly 2 × latency
    assert all(r == 2 * lat for r in rtts), rtts
    assert b"server done" in sp.stdout
    assert b"client done" in cp.stdout


def test_udp_echo_deterministic(apps):
    """Flagship determinism property (determinism1_compare.cmake analog):
    two identical runs produce byte-identical process stdout."""
    def run_once():
        d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=10_000_000,
                          seed=7)
        hs = d.add_host("server", "11.0.0.1")
        hc = d.add_host("client", "11.0.0.2")
        d.add_process(hs, [apps["udp_echo_server"], "9000", "2"])
        d.add_process(
            hc, [apps["udp_echo_client"], "server", "9000", "2"],
            start_time=NS_PER_SEC,
        )
        d.run()
        return [p.stdout for p in d.procs]

    assert run_once() == run_once()


def test_tcp_bulk_transfer(apps):
    """TCP source→sink through the simulated network: all bytes arrive,
    byte count observed by the real sink process matches."""
    total = 300_000
    d = ProcessDriver(stop_time=60 * NS_PER_SEC, latency_ns=20_000_000)
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    d.add_process(hs, [apps["tcp_sink"], "9001"])
    d.add_process(
        hc, [apps["tcp_source"], "server", "9001", str(total)],
        start_time=NS_PER_SEC,
    )
    d.run()
    sink, source = d.procs
    assert source.exit_code == 0, source.stderr
    assert sink.exit_code == 0, sink.stderr
    assert f"sent {total} bytes".encode() in source.stdout
    assert f"received {total} bytes".encode() in sink.stdout


def test_udp_native_vs_simulated(apps):
    """Dual-target check: the same binaries run NATIVELY (loopback, no shim)
    and produce the same functional output (echo success), demonstrating the
    programs are ordinary Linux binaries (README.md:7-31 property)."""
    import os
    import time

    server = subprocess.Popen(
        [apps["udp_echo_server"], "19123", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    time.sleep(0.2)
    client = subprocess.run(
        [apps["udp_echo_client"], "127.0.0.1", "19123", "1"],
        capture_output=True, timeout=10,
    )
    out, err = server.communicate(timeout=10)
    assert client.returncode == 0, client.stderr
    assert b"client done" in client.stdout
    assert b"server done" in out


def test_stopped_process_releases_port(apps):
    """A process stopped at its stop_time releases its port bindings so a
    later process can rebind (descriptor teardown on stop)."""
    lat = 5_000_000
    d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=lat)
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    # first server parks forever (asks for 99 echoes), stopped at t=2s
    d.add_process(hs, [apps["udp_echo_server"], "9000", "99"],
                  stop_time=2 * NS_PER_SEC)
    # second server takes over the same port at t=3s
    d.add_process(hs, [apps["udp_echo_server"], "9000", "1"],
                  start_time=3 * NS_PER_SEC)
    d.add_process(hc, [apps["udp_echo_client"], "server", "9000", "1"],
                  start_time=4 * NS_PER_SEC)
    d.run()
    stopped, server2, client = d.procs
    assert stopped.stopped_by_sim
    assert server2.exit_code == 0, server2.stderr
    assert client.exit_code == 0, client.stderr
    assert b"client done" in client.stdout


def test_fd_kit(apps):
    """Pipes, eventfd, timerfd, dup, readv/writev, getrandom under the shim.
    Timerfd ticks measure EXACTLY the configured period on the virtual
    clock; getrandom output is deterministic (seeded per-host stream)."""
    def run_once():
        d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=10_000_000,
                          seed=11)
        h = d.add_host("solo", "11.0.0.1")
        d.add_process(h, [apps["fd_kit"]])
        d.run()
        return d.procs[0]

    p = run_once()
    assert p.exit_code == 0, p.stderr
    out = p.stdout.decode()
    assert "pipe ok" in out
    assert "eventfd ok" in out
    # every timerfd tick is exactly 50ms of virtual time
    dts = [int(l.split()[3]) for l in out.splitlines() if l.startswith("tick")]
    assert dts == [50_000_000] * 3, dts
    assert "fd kit done" in out
    # deterministic getrandom: identical across runs
    assert run_once().stdout == p.stdout


def test_cpu_model_delays_virtual_clock(apps):
    """CPU model (host/cpu.c analog): charging simulated processing time
    per syscall stretches observed RTTs on the virtual clock, and stays
    deterministic."""
    def run(cpu_ns):
        d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=10_000_000)
        d.cpu_ns_per_syscall = cpu_ns
        d.cpu_threshold_ns = 1_000
        hs = d.add_host("server", "11.0.0.1")
        hc = d.add_host("client", "11.0.0.2")
        d.add_process(hs, [apps["udp_echo_server"], "9000", "2"])
        d.add_process(hc, [apps["udp_echo_client"], "server", "9000", "2"],
                      start_time=NS_PER_SEC)
        d.run()
        assert d.procs[1].exit_code == 0, d.procs[1].stderr
        out = d.procs[1].stdout.decode()
        return [int(l.split()[1]) for l in out.splitlines()
                if l.startswith("rtt")]

    plain = run(0)
    loaded = run(500_000)  # 0.5 ms of CPU per syscall
    assert all(r == 2 * 10_000_000 for r in plain)
    # CPU cost inflates the observed RTT beyond pure network latency
    assert all(r > 2 * 10_000_000 for r in loaded), loaded
    assert loaded == run(500_000)  # deterministic


def test_epoll_edge_triggered(apps):
    """EPOLLET semantics (reference: epoll.c edge/level): readiness is
    reported once per new-data edge — a wait with no new arrivals since
    the last report times out even though the buffer is non-empty."""
    d = build_process_driver(f"""
general:
  stop_time: 20 s
  seed: 4
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
hosts:
  rx:
    ip_address_hint: 11.0.0.1
    processes:
      - path: {apps['epollet']}
        args: "7300"
  tx:
    processes:
      - path: {apps['epollet']}
        args: --send 11.0.0.1 7300
        start_time: 1 s
""")
    d.run()
    rx = next(p for p in d.procs if "--send" not in p.args)
    assert rx.exit_code == 0, (rx.stdout, rx.stderr)
    lines = rx.stdout.decode().splitlines()
    # edge on first datagram; edge on second; NO report without new data;
    # fresh edge after drain + third datagram
    assert lines == ["wait1 1", "wait2 1", "wait3 0", "wait4 1"], lines


def test_shim_log_stamps(apps):
    """Shim-side sim-time log stamping (reference: shim_logger.c — managed
    stdout lines carry the SIMULATED clock): with log_stamp on, every
    stdout line gains an HH:MM:SS.micros prefix whose value is sim time
    (the client starts at sim 1 s, so stamps are >= 1 s while the whole
    run takes well under a wall second of managed-process time)."""
    import re

    lat = 50_000_000
    d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=lat)
    d.log_stamp = True
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    d.add_process(hs, [apps["udp_echo_server"], "9000", "2"], start_time=0)
    d.add_process(
        hc, [apps["udp_echo_client"], "server", "9000", "2"],
        start_time=NS_PER_SEC,
    )
    d.run()
    sp, cp = d.procs
    assert cp.exit_code == 0, cp.stderr
    lines = cp.stdout.decode().strip().splitlines()
    pat = re.compile(r"^(\d{2}):(\d{2}):(\d{2})\.(\d{6}) \[stdio\] ")
    assert lines and all(pat.match(l) for l in lines), lines
    # rtt lines are printed right after the recv completes at sim >= 1 s
    # + RTT; their stamp must reflect that virtual clock
    for l in lines:
        m = pat.match(l)
        ns = (int(m[1]) * 3600 + int(m[2]) * 60 + int(m[3])) * 10**9 \
            + int(m[4]) * 1000
        if "rtt" in l:
            assert ns >= NS_PER_SEC + 2 * lat, l
    # the payload after the prefix is unchanged
    rtts = [l.split("] ", 1)[1] for l in lines if "rtt" in l]
    assert len(rtts) == 2, lines


def test_virtual_cpu_visibility(apps):
    """sched_getaffinity (and glibc's sysconf(_SC_NPROCESSORS_ONLN), which
    derives from it) reports the SIMULATED host's CPU count — apps that
    size thread pools from nproc behave deterministically regardless of
    the real machine."""
    d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    h = d.add_host("solo", "11.0.0.1")
    d.add_process(h, [apps["nproc_probe"]])
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    lines = p.stdout.decode().splitlines()
    assert lines[0] == "affinity rc=0 count=1", lines
    assert lines[1] == "nproc 1", lines

    # configurable: a 4-CPU virtual host reports 4
    d2 = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    d2.virtual_cpus = 4
    h2 = d2.add_host("quad", "11.0.0.1")
    d2.add_process(h2, [apps["nproc_probe"]])
    d2.run()
    p2 = d2.procs[0]
    assert p2.exit_code == 0, (p2.stdout, p2.stderr)
    lines2 = p2.stdout.decode().splitlines()
    assert lines2[0] == "affinity rc=0 count=4", lines2
    assert lines2[1] == "nproc 4", lines2


def test_uname_nodename_simulated(apps):
    """uname(2).nodename agrees with the simulated hostname (the real
    machine's name must not leak into determinism-compared output)."""
    d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    h = d.add_host("relay7", "11.0.0.1")
    d.add_process(h, [apps["uname_probe"]])
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    assert p.stdout.decode().strip() == "match 1 nodename=relay7", p.stdout


def test_proc_cpu_files_virtualized(apps):
    """/proc/cpuinfo and /sys .../cpu/online report the SIMULATED CPU
    count through the openat seccomp trap (glibc's internal opens never
    cross the PLT); unrelated paths still open natively."""
    d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    d.virtual_cpus = 3
    h = d.add_host("solo", "11.0.0.1")
    d.add_process(h, [apps["procfs_probe"]])
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    lines = p.stdout.decode().splitlines()
    assert lines == ["cpuinfo 3", "online 0-2", "other 1"], lines
