"""Optimistic window synchronization: speculative long windows + rollback
must produce results equivalent to the conservative schedule (SURVEY §7.6;
BASELINE staged config 4 calls for optimistic PDES windows)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import Simulation
from shadow_tpu.core.state import KIND_APP_TIMER, NetParams
from shadow_tpu.sim import build_simulation

MS = simtime.NS_PER_MS

# Two-vertex graph with asymmetric latencies: the runahead is the 10ms
# edge, so 50ms-path deliveries land mid-window during speculation and
# force rollbacks.
MIXED_YAML = """
general:
  stop_time: 2
  seed: 9
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "50 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 4096
  events_per_host_per_window: 16
hosts:
  near:
    quantity: 6
    network_node_id: 0
    app_model: phold
    app_options: {msgload: 2, runtime: 1}
  far:
    quantity: 2
    network_node_id: 1
    app_model: phold
    app_options: {msgload: 2, runtime: 1}
"""


def _final_fingerprint(sim):
    c = sim.counters()
    c.pop("pool_overflow_dropped", None)
    # schedule metrics, not results: optimistic windows legitimately take a
    # different number of engine iterations than the conservative schedule
    c.pop("micro_steps", None)
    c.pop("outbox_stall_deferred", None)
    # transport metrics of the islands layout, not results (always 0 on
    # the global engine)
    c.pop("exchange_sent", None)
    c.pop("exchange_deferred", None)
    subs = jax.device_get(sim.state.subs)
    return c, jax.tree.map(lambda x: np.asarray(x), subs)


def _assert_equivalent(a, b):
    ca, sa = _final_fingerprint(a)
    cb, sb = _final_fingerprint(b)
    assert ca == cb
    for key in sa:
        for leaf_a, leaf_b in zip(
            jax.tree.leaves(sa[key]), jax.tree.leaves(sb[key])
        ):
            assert np.array_equal(leaf_a, leaf_b), key


def test_mixed_latency_rollback_and_equivalence():
    """Asymmetric path latencies force speculation violations; after
    rollbacks the results still match the conservative schedule."""
    cons = build_simulation(MIXED_YAML)
    assert cons.runahead == 10 * MS
    cons.run_stepwise()

    opt = build_simulation(MIXED_YAML)
    windows, rollbacks = opt.run_optimistic(window_factor=8)
    assert rollbacks > 0  # speculation actually violated and rolled back
    _assert_equivalent(cons, opt)


def test_uniform_latency_no_rollbacks():
    """With one uniform latency every delivery lands exactly one sub-step
    ahead of its destination's progress clock: speculation always holds."""
    yaml = MIXED_YAML.replace('latency "50 ms"', 'latency "10 ms"')
    cons = build_simulation(yaml)
    cons.run_stepwise()

    opt = build_simulation(yaml)
    _, rollbacks = opt.run_optimistic(window_factor=8)
    assert rollbacks == 0
    _assert_equivalent(cons, opt)


def _noop_sim():
    """8 hosts, no-op timer handler, 200 pre-scheduled events spread over
    200 runaheads — the schedule shape where speculation pays: one long
    window absorbs work that costs conservative one barrier per runahead."""
    H = 8
    initial = []
    for i in range(200):
        t = (i + 1) * MS
        initial.append((t, i % H, (i + 3) % H, KIND_APP_TIMER, [0]))
    params = NetParams(
        latency_vv=jnp.full((1, 1), MS, dtype=jnp.int64),
        reliability_vv=jnp.ones((1, 1), jnp.float32),
        bootstrap_end=jnp.int64(0),
    )
    return Simulation(
        num_hosts=H,
        handlers={KIND_APP_TIMER: lambda state, ev, em, p: state},
        params=params,
        host_vertex=np.zeros(H, np.int32),
        seed=1,
        stop_time=300 * MS,
        runahead=MS,
        event_capacity=512,
        K=32,
        initial_events=initial,
    )


def test_prescheduled_work_commits_long_windows():
    cons = _noop_sim()
    cons_windows = cons.run_stepwise()
    assert cons_windows >= 200  # one barrier per 1ms runahead

    opt = _noop_sim()
    opt_windows, rollbacks = opt.run_optimistic(window_factor=64)
    assert rollbacks == 0
    assert opt_windows <= cons_windows / 8
    assert cons.counters()["events_committed"] == 200
    assert opt.counters()["events_committed"] == 200


def _islandize_yaml(yaml: str, shards: int = 4, slots: int = 16,
                    mode: str = "vmap") -> str:
    return yaml.replace(
        "experimental:\n",
        f"experimental:\n  num_shards: {shards}\n"
        f"  exchange_slots: {slots}\n  island_mode: {mode}\n",
    )


def _assert_equivalent_islands(cons, isl):
    """Counters sum over shards already; subs leaves need the [S, Hl] →
    [H] reshape before comparing."""
    ca, sa = _final_fingerprint(cons)
    cb, sb = _final_fingerprint(isl)
    assert ca == cb
    for key in sa:
        for leaf_a, leaf_b in zip(
            jax.tree.leaves(sa[key]), jax.tree.leaves(sb[key])
        ):
            assert np.array_equal(
                leaf_a, np.asarray(leaf_b).reshape(leaf_a.shape)
            ), key


def test_islands_optimistic_mixed_latency_equivalence():
    """Optimistic windows ON the islands runner (VERDICT r4 #4): the
    asymmetric-latency workload forces speculation violations whose
    detection now spans shards — local emissions against local done_t,
    cross-shard emissions at arrival after the all_to_all — and after
    rollbacks the results must match the global conservative schedule
    bit-for-bit."""
    cons = build_simulation(MIXED_YAML)
    cons.run_stepwise()

    opt = build_simulation(_islandize_yaml(MIXED_YAML))
    windows, rollbacks = opt.run_optimistic(window_factor=8)
    assert rollbacks > 0, "speculation never violated across shards"
    _assert_equivalent_islands(cons, opt)


def test_islands_optimistic_shard_map_equivalence(devices):
    """The multi-chip form: one island per mesh device (shard_map), the
    attempt loop's pmin riding real collectives, rollback dropping the
    speculated pytree on every device. Exercises the shard_map-only
    machinery (pcast'd cond branches, check_vma=False wrappers) that the
    vmap tests never compile."""
    if len(devices) < 4:
        pytest.skip("needs 4 virtual devices")
    cons = build_simulation(MIXED_YAML)
    cons.run_stepwise()

    opt = build_simulation(_islandize_yaml(MIXED_YAML, mode="shard_map"))
    windows, rollbacks = opt.run_optimistic(window_factor=8)
    assert rollbacks > 0
    _assert_equivalent_islands(cons, opt)


def test_islands_optimistic_under_exchange_backpressure():
    """exchange_slots=1 keeps cross-shard rows in transit across
    sub-steps: the speculative windows must respect the deferred-row
    floor (never overtake an in-transit delivery without detecting it)
    and still reproduce the conservative results exactly."""
    cons = build_simulation(MIXED_YAML)
    cons.run_stepwise()

    opt = build_simulation(_islandize_yaml(MIXED_YAML, slots=1))
    windows, rollbacks = opt.run_optimistic(window_factor=8)
    ci = opt.counters()
    assert ci["exchange_deferred"] > 0, "no exchange backpressure"
    _assert_equivalent_islands(cons, opt)


FLOOD_YAML = """
general:
  stop_time: 3
  seed: 7
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.001 ]
      ]
experimental:
  event_capacity: 2048
  events_per_host_per_window: 8
  outbox_slots: 8
  inbox_slots: 4
  router_queue_slots: 8
hosts:
  server:
    quantity: 4
    app_model: udp_flood
    app_options: {role: server}
  client:
    quantity: 28
    app_model: udp_flood
    app_options: {interval: "40 ms", size: 512, runtime: 1}
"""


def test_islands_optimistic_netstack_equivalence():
    """The LOOP path (full NIC/router/UDP netstack — no matrix pin) under
    optimistic islands: the PHOLD gates above exercise only the matrix
    path, so this is the coverage for the micro-step loop's emission
    check + the exchange arrival check together. Must reproduce the
    global conservative run bit-for-bit."""
    cons = build_simulation(FLOOD_YAML)
    cons.run_stepwise()
    cc = cons.counters()

    opt = build_simulation(_islandize_yaml(FLOOD_YAML))
    windows, rollbacks = opt.run_optimistic(window_factor=8)
    co = opt.counters()
    for k in ("events_committed", "events_emitted", "packets_sent",
              "packets_delivered", "packets_dropped_loss", "bytes_sent",
              "bytes_delivered", "pool_overflow_dropped"):
        assert cc[k] == co[k], (k, cc[k], co[k])
    a = np.asarray(jax.device_get(cons.state.subs["udp_flood"]["recv"]))
    b = np.asarray(jax.device_get(opt.state.subs["udp_flood"]["recv"]))
    assert (a == b.reshape(a.shape)).all()


def test_floor_width_violation_refuses_commit():
    """ADVICE r5 #1 regression (global engine): forge a speculation
    violation inside a conservative-width window. Such a window is
    violation-free BY CONSTRUCTION, so a reported violation means the
    invariant itself broke — the driver must raise instead of silently
    committing the causally-violated window."""
    sim = _noop_sim()

    def forged_attempt(state, params, ws, we):
        # window "completes" (mn = we) but reports a violation at ws
        return state, jnp.asarray(we, jnp.int64), jnp.asarray(ws, jnp.int64)

    sim._attempt = forged_attempt
    with pytest.raises(RuntimeError, match="refusing to commit"):
        # factor 1: every window is conservative-width, the guard zone
        sim.run_optimistic(window_factor=1)


def test_islands_floor_width_violation_refuses_commit():
    """ADVICE r5 #1 regression (islands runner): same forged violation
    through the per-shard attempt kernel's return shape — the
    floor-width commit path must raise, mirroring the engine-side
    guard."""
    sim = build_simulation(_islandize_yaml(MIXED_YAML))
    S = sim.num_shards

    def forged_attempt(state, params, ws, we):
        return (
            state,
            jnp.full((S,), jnp.asarray(we, jnp.int64)),
            jnp.full((S,), jnp.asarray(ws, jnp.int64)),
        )

    sim._attempt = forged_attempt  # _ensure_optimistic keeps it (non-None)
    with pytest.raises(RuntimeError, match="refusing to commit"):
        sim.run_optimistic(window_factor=1)


def test_adaptive_factor_equivalence():
    """Adaptive window_factor (BASELINE config 4 tuning: halve on
    rollback, re-grow after clean streaks) must still reproduce the
    conservative schedule bit-for-bit."""
    cons = build_simulation(MIXED_YAML)
    cons.run_stepwise()

    opt = build_simulation(MIXED_YAML)
    windows, rollbacks = opt.run_optimistic(window_factor=8, adaptive=True)
    assert rollbacks > 0
    _assert_equivalent(cons, opt)

    # adaptive throttling must not raise the rollback count vs fixed
    fixed = build_simulation(MIXED_YAML)
    _, rb_fixed = fixed.run_optimistic(window_factor=8, adaptive=False)
    assert rollbacks <= rb_fixed
