"""True multi-chip sharded simulation (ISSUE 12): shard_map mesh
execution with neighbor-only ppermute frontier exchange and min-cut
chip placement.

The acceptance surface: chain equality {conservative, optimistic,
async} × {global, islands, mesh} on 2/4/8 virtual devices, checkpoint →
resume ACROSS mesh sizes (restore_relayout), host_mesh hardening,
ppermute shift-schedule units, min-cut placement units, schema-v11
mesh.* telemetry, and the kcache machine-fingerprint eviction.
"""

import json
import os

import jax
import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import checkpoint, simtime
from shadow_tpu.parallel import balancer as balancer_mod
from shadow_tpu.parallel import lookahead as lookahead_mod
from shadow_tpu.parallel import mesh as mesh_mod
from shadow_tpu.sim import build_simulation

NEVER = int(simtime.NEVER)


def _ring_gml(n: int, span: int = 2, seed: int = 3) -> str:
    """One vertex per host; edges within ring distance <= span with
    decohered latencies (direct-edge routing keeps the in-edge matrix
    sparse when use_shortest_path is off)."""
    rng = np.random.RandomState(seed)
    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        lines.append(
            f'  edge [ source {a} target {a} latency '
            f'"{int(rng.randint(2000, 3000))} us" ]'
        )
        for d in range(1, span + 1):
            lines.append(
                f'  edge [ source {a} target {(a + d) % n} latency '
                f'"{int(rng.randint(30000, 45000))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def _cfg(n: int, gml: str, *, shards: int = 1, stop: int = 3,
         span: int = 2, **exp) -> dict:
    hosts = {}
    for v in range(n):
        hosts[f"h{v:02d}"] = {
            "quantity": 1, "network_node_id": v, "app_model": "phold",
            "app_options": {
                "msgload": 1, "runtime": stop - 1, "local_span": span,
            },
        }
    experimental = {
        "event_capacity": 1024, "events_per_host_per_window": 8,
        "outbox_slots": 8, "inbox_slots": 4,
    }
    if shards > 1:
        experimental.update({"num_shards": shards, "exchange_slots": 16})
    experimental.update(exp)
    return {
        "general": {"stop_time": stop, "seed": 11},
        "network": {"graph": {"type": "gml", "inline": gml}},
        "experimental": experimental,
        "hosts": hosts,
    }


N = 16
GML = _ring_gml(N)


@pytest.fixture(scope="module")
def global_chain():
    sim = build_simulation(_cfg(N, GML))
    sim.run()
    return sim.audit_chain(), sim.counters()["events_committed"]


# ---------------------------------------------------------------------------
# chain-equality matrix: {conservative, optimistic, async} × layouts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_mesh_async_chain_matches_global(global_chain, shards):
    """The mesh (shard_map) async driver on 2/4/8 chips commits the
    global engine's exact event stream — ppermute frontier exchange and
    per-chip placement change where state lives, never the sim."""
    chain, events = global_chain
    sim = build_simulation(
        _cfg(N, GML, shards=shards, island_mode="shard_map")
    )
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events


@pytest.mark.parametrize("sync", ["conservative", "async"])
def test_islands_vmap_chain_matches_global(global_chain, sync):
    chain, events = global_chain
    sim = build_simulation(_cfg(
        N, GML, shards=4, async_islands=(sync == "async"),
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events


def test_mesh_conservative_barrier_chain_matches_global(global_chain):
    chain, events = global_chain
    sim = build_simulation(_cfg(
        N, GML, shards=4, island_mode="shard_map", async_islands=False,
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events


def test_mesh_optimistic_chain_matches_global(global_chain):
    chain, events = global_chain
    sim = build_simulation(_cfg(
        N, GML, shards=2, island_mode="shard_map",
    ))
    sim.run_optimistic()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events


def test_mesh_min_cut_placement_chain_matches_global(global_chain):
    chain, events = global_chain
    sim = build_simulation(_cfg(
        N, GML, shards=4, island_mode="shard_map", placement="min_cut",
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events


def test_ppermute_matches_all_gather_arm(global_chain):
    """The two frontier-exchange arms compute identical horizons —
    supersteps, yields, blocked counts AND chains all equal."""
    chain, _ = global_chain
    pp = build_simulation(_cfg(N, GML, shards=4))
    ag = build_simulation(_cfg(
        N, GML, shards=4, mesh_exchange="all_gather",
    ))
    pp.run()
    ag.run()
    assert pp.audit_chain() == ag.audit_chain() == chain
    assert pp.async_stats() == ag.async_stats()


# ---------------------------------------------------------------------------
# checkpoint → resume across mesh sizes
# ---------------------------------------------------------------------------


def test_checkpoint_resume_across_mesh_sizes(tmp_path, global_chain):
    """A mesh checkpoint taken at S=4 resumes on a 2-chip mesh AND on
    the global engine, both finishing with the uninterrupted chain —
    the restore_relayout seam globalizes by gid and re-routes."""
    chain, events = global_chain
    src = build_simulation(_cfg(N, GML, shards=4))
    src.run(until=1 * simtime.NS_PER_SEC)
    path = os.path.join(tmp_path, "ck.npz")
    checkpoint.save(src, path)

    dst2 = build_simulation(_cfg(N, GML, shards=2))
    checkpoint.restore_relayout(dst2, path)
    dst2.run()
    assert dst2.audit_chain() == chain
    assert dst2.counters()["events_committed"] == events

    dstg = build_simulation(_cfg(N, GML))
    checkpoint.restore_relayout(dstg, path)
    dstg.run()
    assert dstg.audit_chain() == chain
    assert dstg.counters()["events_committed"] == events


def test_restore_relayout_same_layout_falls_through(tmp_path):
    """Matching layouts take the strict restore path (gear rebind and
    all) — restore_relayout is a superset, not a fork."""
    src = build_simulation(_cfg(N, GML, shards=2))
    src.run(until=1 * simtime.NS_PER_SEC)
    path = os.path.join(tmp_path, "ck.npz")
    checkpoint.save(src, path)
    dst = build_simulation(_cfg(N, GML, shards=2))
    checkpoint.restore_relayout(dst, path)
    src.run()
    dst.run()
    assert dst.audit_chain() == src.audit_chain()


def test_restore_relayout_rejects_host_count_mismatch(tmp_path):
    src = build_simulation(_cfg(N, GML, shards=2))
    path = os.path.join(tmp_path, "ck.npz")
    checkpoint.save(src, path)
    other = build_simulation(_cfg(8, _ring_gml(8)))
    with pytest.raises(checkpoint.CheckpointError, match="hosts"):
        checkpoint.restore_relayout(other, path)


# ---------------------------------------------------------------------------
# host_mesh hardening
# ---------------------------------------------------------------------------


def test_host_mesh_deterministic_device_order():
    mesh = mesh_mod.host_mesh(8)
    devs = list(mesh.devices.flat)
    keys = [(d.process_index, d.id) for d in devs]
    assert keys == sorted(keys)
    # stable across calls
    mesh2 = mesh_mod.host_mesh(8)
    assert [d.id for d in mesh2.devices.flat] == [d.id for d in devs]


def test_host_mesh_uneven_hosts_error_documents_pad_rule():
    with pytest.raises(ValueError) as ei:
        mesh_mod.host_mesh(8, num_hosts=12)
    msg = str(ei.value)
    assert "12" in msg and "pad" in msg and "16" in msg
    # evenly divisible passes
    mesh_mod.host_mesh(8, num_hosts=16)
    with pytest.raises(ValueError):
        mesh_mod.host_mesh(0)


def test_shard_map_build_places_state_on_mesh():
    sim = build_simulation(
        _cfg(N, GML, shards=4, island_mode="shard_map")
    )
    sharding = sim.state.pool.time.sharding
    assert set(getattr(sharding, "mesh").axis_names) == {"islands"}
    spec = sharding.spec
    assert spec[0] == "islands"


# ---------------------------------------------------------------------------
# ppermute shift schedule units
# ---------------------------------------------------------------------------


def _spec(matrix) -> lookahead_mod.LookaheadSpec:
    m = np.asarray(matrix, np.int64)
    return lookahead_mod.LookaheadSpec(
        matrix=m, intra=np.diagonal(m).copy(), min_cross=0,
        critical=(-1, -1),
    )


def test_ppermute_shifts_cover_in_edges_only():
    # 4-shard bidirected ring: finite edges j <-> j+1 only
    m = np.full((4, 4), NEVER, np.int64)
    for j in range(4):
        m[j, j] = 5
        m[j, (j + 1) % 4] = 100
        m[(j + 1) % 4, j] = 100
    spec = _spec(m)
    assert lookahead_mod.ppermute_shifts(spec) == (1, 3)
    assert list(lookahead_mod.in_degree(spec)) == [2, 2, 2, 2]
    assert lookahead_mod.shifts_covered(spec, (1, 3))
    assert not lookahead_mod.shifts_covered(spec, (1,))
    # adding a chord needs a new shift
    m2 = m.copy()
    m2[0, 2] = 500
    assert lookahead_mod.ppermute_shifts(_spec(m2)) == (1, 2, 3)


def test_ppermute_shifts_empty_on_decoupled_partition():
    m = np.full((4, 4), NEVER, np.int64)
    np.fill_diagonal(m, 7)
    assert lookahead_mod.ppermute_shifts(_spec(m)) == ()
    assert lookahead_mod.shifts_covered(_spec(m), ())


def test_sparse_topology_shifts_scale_with_degree():
    """Direct-edge routing on the span-2 host ring: only adjacent chips
    exchange frontiers — 2 ppermute partners at any mesh size, where
    all_gather ships S."""
    cfg = _cfg(N, GML, shards=8, span=2)
    cfg["network"]["use_shortest_path"] = False
    sim = build_simulation(cfg)
    assert sim._async_shifts == (1, 7)
    assert sim.exchange_partners == 2
    sim.run()
    g = build_simulation(_cfg(N, GML))
    g.run()
    assert sim.audit_chain() == g.audit_chain()


# ---------------------------------------------------------------------------
# min-cut placement units
# ---------------------------------------------------------------------------


def test_min_cut_placement_beats_block_on_offset_communities():
    """Communities of 4 hosts offset by 2 from the chip blocks: the
    block partition splits every community; the placement re-aligns."""
    H, S = 16, 4
    hv = np.arange(H, dtype=np.int64)
    lat = np.full((H, H), NEVER, np.int64)
    comm = ((hv - 2) % H) // 4
    for a in range(H):
        lat[a, a] = 1_000_000
        for b in range(H):
            if a != b and comm[a] == comm[b]:
                lat[a, b] = 2_000_000  # fast chatty intra-community
            elif abs(a - b) in (1, H - 1):
                lat[a, b] = 80_000_000  # slow ring boundary
    slot = balancer_mod.min_cut_placement(lat, hv, S)
    assert np.array_equal(np.sort(slot), np.arange(H))
    cut_p = balancer_mod.cut_cost(slot // (H // S), lat, hv)
    cut_b = balancer_mod.cut_cost(
        lookahead_mod.shard_of_hosts(H, S), lat, hv
    )
    assert cut_p < cut_b
    # each community lands on one chip
    shard_of = np.asarray(slot) // (H // S)
    for c in range(S):
        assert len(set(shard_of[comm == c])) == 1


def test_min_cut_placement_never_worse_than_block():
    """On a topology whose id order already encodes locality (plain
    ring), the placement falls back to the identity block partition."""
    H, S = 16, 4
    hv = np.arange(H, dtype=np.int64)
    lat = np.full((H, H), NEVER, np.int64)
    for a in range(H):
        lat[a, a] = 1_000_000
        lat[a, (a + 1) % H] = 10_000_000
        lat[(a + 1) % H, a] = 10_000_000
    slot = balancer_mod.min_cut_placement(lat, hv, S)
    assert np.array_equal(slot, np.arange(H, dtype=slot.dtype))


def test_cut_cost_vertex_formula_matches_host_pairs():
    """The vertex-level cut formula equals the O(H²) host-pair sum."""
    rng = np.random.RandomState(0)
    U, H, S = 5, 12, 3
    hv = rng.randint(0, U, H).astype(np.int64)
    lat = rng.randint(1_000_000, 90_000_000, (U, U)).astype(np.int64)
    lat[0, 3] = NEVER
    shard = rng.randint(0, S, H).astype(np.int64)
    aff = balancer_mod.host_affinity(lat, hv)
    cross = shard[:, None] != shard[None, :]
    want = float(aff[cross].sum() / 2.0)
    got = balancer_mod.cut_cost(shard, lat, hv)
    assert got == pytest.approx(want, rel=1e-9)


# ---------------------------------------------------------------------------
# schema v11 mesh.* telemetry
# ---------------------------------------------------------------------------


def test_mesh_metrics_v11(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    sim = build_simulation(_cfg(N, GML, shards=4))
    sim.run()
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(os.path.join(tmp_path, "m.json"))
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert doc["counters"]["mesh.frontier_exchange_bytes"] > 0
    assert doc["counters"]["mesh.exchange_rebuilds"] == 0
    g = doc["gauges"]
    assert g["mesh.chips"] == 4
    assert g["mesh.exchange_partners"] >= 1
    assert g["mesh.events_per_chip_max"] >= g["mesh.events_per_chip_min"]
    assert "mesh.cut_cost" in g and "mesh.cut_cost_block" in g


def test_global_run_emits_no_mesh_keys(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    sim = build_simulation(_cfg(N, GML))
    sim.run()
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.to_doc()
    assert not [k for k in doc["counters"] if k.startswith("mesh.")]
    assert not [k for k in doc["gauges"] if k.startswith("mesh.")]


# ---------------------------------------------------------------------------
# kcache machine fingerprint
# ---------------------------------------------------------------------------


def test_kcache_foreign_machine_entry_evicts(tmp_path):
    from shadow_tpu.serve import kcache

    root = str(tmp_path / "cache")
    cache = kcache.KernelCache(root)
    key = "f" * 40
    bin_path, hdr_path = cache._paths(key)
    blob = b"not-an-export"
    import hashlib
    import jaxlib

    with open(bin_path, "wb") as f:
        f.write(blob)
    with open(hdr_path, "w") as f:
        json.dump({
            "header_version": kcache.HEADER_VERSION,
            "sha256": hashlib.sha256(blob).hexdigest(),
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "machine": "somebody-elses-laptop",
        }, f)
    assert cache.get(key) is None
    assert cache.stats_counters["evictions"] == 1
    assert not os.path.exists(bin_path)


def test_xla_cache_machine_marker_sweeps_foreign_entries(tmp_path):
    from shadow_tpu.serve import kcache

    root = str(tmp_path / "xla")
    os.makedirs(root)
    with open(os.path.join(root, "machine.json"), "w") as f:
        json.dump({"machine": "old-machine"}, f)
    entry = os.path.join(root, "xla_entry_abc")
    with open(entry, "wb") as f:
        f.write(b"\x00" * 64)
    fp = kcache.machine_fingerprint()
    removed = kcache._sweep_foreign_machine(root, fp)
    assert removed == 1 and not os.path.exists(entry)
    with open(os.path.join(root, "machine.json")) as f:
        assert json.load(f)["machine"] == fp
    # same machine: nothing evicted
    with open(entry, "wb") as f:
        f.write(b"\x00" * 64)
    assert kcache._sweep_foreign_machine(root, fp) == 0
    assert os.path.exists(entry)


def test_machine_fingerprint_rides_kernel_cache_key(tmp_path):
    from shadow_tpu.serve import kcache

    cache = kcache.KernelCache(str(tmp_path / "c"))
    k1 = cache.key("cfg", "tag", [np.zeros(3)])
    old = kcache._MACHINE_FP
    try:
        kcache._MACHINE_FP = "different-machine"
        k2 = cache.key("cfg", "tag", [np.zeros(3)])
    finally:
        kcache._MACHINE_FP = old
    assert k1 != k2
