"""Engine correctness: PHOLD on-device vs a sequential heapq oracle.

The oracle replays the reference semantics (global event order by
(time, dst, src, seq); per-host RNG streams) in plain Python. Because the
engine's randomness is a pure function of (seed, host, draw counter), the
oracle and the vectorized engine must agree EXACTLY: same delivery counts,
same drop counts, same per-host draw counters.
"""

import pytest
import heapq

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.engine import Simulation, draw_uniform
from shadow_tpu.core.state import (
    KIND_APP_MSG,
    KIND_APP_TIMER,
    NetParams,
)
from shadow_tpu.net.apps import PholdApp

pytestmark = pytest.mark.quick


MS = simtime.NS_PER_MS
SEC = simtime.NS_PER_SEC


def make_params(H, latency_ns, reliability=1.0, bootstrap_end=0):
    return NetParams(
        latency_vv=jnp.full((1, 1), latency_ns, dtype=jnp.int64),
        reliability_vv=jnp.full((1, 1), reliability, dtype=jnp.float32),
        bootstrap_end=jnp.int64(bootstrap_end),
    )


def phold_oracle(H, seed, latency_ns, reliability, msgload, start, stop_send, stop):
    """Sequential reference implementation mirroring the engine bit-for-bit."""
    hkeys = [jax.random.fold_in(jax.random.PRNGKey(seed), i) for i in range(H)]
    counters = [0] * H
    seq_next = [0] * H

    def draw(h):
        u = float(
            jax.random.uniform(
                jax.random.fold_in(hkeys[h], counters[h]), dtype=jnp.float32
            )
        )
        counters[h] += 1
        return u

    heap = []
    for h in range(H):
        for _ in range(msgload):
            heapq.heappush(heap, (start, h, h, seq_next[h]))
            seq_next[h] += 1
    # mirror Simulation.__init__: initial events consume seq in list order
    received = [0] * H
    forwarded = [0] * H
    sent = dropped = 0
    while heap and heap[0][0] < stop:
        t, dsth, srch, seq = heapq.heappop(heap)
        received[dsth] += 1
        if t < stop_send:
            u = draw(dsth)
            # same float math as the engine (f32)
            dst = int(np.float32(u) * np.float32(H - 1))
            dst = min(max(dst, 0), H - 2)
            if dst >= dsth:
                dst += 1
            forwarded[dsth] += 1
            sent += 1
            u2 = draw(dsth)  # reliability roll (path always reachable here)
            if u2 < reliability:
                heapq.heappush(heap, (t + latency_ns, dst, dsth, seq_next[dsth]))
                seq_next[dsth] += 1
            else:
                dropped += 1
    return {
        "received": received,
        "forwarded": forwarded,
        "sent": sent,
        "dropped": dropped,
        "rng_counters": counters,
    }


def build_phold_sim(H, seed, latency_ns, reliability, msgload, runtime, stop,
                    bulk=False):
    app = PholdApp(
        H,
        msgload=msgload,
        size_bytes=64,
        start_time=SEC,
        runtime=runtime,
    )
    params = make_params(H, latency_ns, reliability)
    return (
        Simulation(
            num_hosts=H,
            handlers=app.handlers(),
            params=params,
            host_vertex=np.zeros(H, dtype=np.int32),
            seed=seed,
            stop_time=stop,
            runahead=latency_ns,
            event_capacity=4096,
            K=16,
            B=4,
            O=16,
            subs={PholdApp.SUB: app.init_sub()},
            initial_events=app.initial_events(),
            bulk_kinds=app.bulk_kinds() if bulk else None,
            matrix_handlers=app.matrix_handlers() if bulk == "matrix" else None,
        ),
        app,
    )


def test_phold_matches_oracle():
    H, seed = 5, 12345
    latency, rel, msgload = 50 * MS, 1.0, 2
    runtime, stop = 5 * SEC, 10 * SEC
    sim, app = build_phold_sim(H, seed, latency, rel, msgload, runtime, stop)
    windows = sim.run_stepwise()
    assert windows > 0
    oracle = phold_oracle(H, seed, latency, rel, msgload, SEC, SEC + runtime, stop)

    sub = jax.device_get(sim.state.subs[PholdApp.SUB])
    assert list(sub["received"]) == oracle["received"]
    assert list(sub["forwarded"]) == oracle["forwarded"]
    c = sim.counters()
    assert c["packets_sent"] == oracle["sent"]
    assert c["packets_dropped_loss"] == oracle["dropped"]
    assert c["pool_overflow_dropped"] == 0
    assert c["outbox_overflow_dropped"] == 0
    assert c["inbox_overflow_deferred"] == 0
    rng_c = jax.device_get(sim.state.host.rng_counter)
    assert list(rng_c) == oracle["rng_counters"]


def test_phold_lossy_matches_oracle():
    H, seed = 4, 777
    latency, rel, msgload = 10 * MS, 0.7, 3
    runtime, stop = 3 * SEC, 6 * SEC
    sim, app = build_phold_sim(H, seed, latency, rel, msgload, runtime, stop)
    sim.run_stepwise()
    oracle = phold_oracle(H, seed, latency, rel, msgload, SEC, SEC + runtime, stop)
    sub = jax.device_get(sim.state.subs[PholdApp.SUB])
    assert list(sub["received"]) == oracle["received"]
    c = sim.counters()
    assert c["packets_sent"] == oracle["sent"]
    assert c["packets_dropped_loss"] == oracle["dropped"]


def test_fused_run_matches_stepwise():
    H, seed = 4, 99
    sim1, _ = build_phold_sim(H, seed, 50 * MS, 0.9, 1, 3 * SEC, 5 * SEC)
    sim2, _ = build_phold_sim(H, seed, 50 * MS, 0.9, 1, 3 * SEC, 5 * SEC)
    sim1.run_stepwise()
    sim2.run()  # single fused XLA while_loop
    c1, c2 = sim1.counters(), sim2.counters()
    assert c1 == c2
    s1 = jax.device_get(sim1.state.subs[PholdApp.SUB])
    s2 = jax.device_get(sim2.state.subs[PholdApp.SUB])
    assert list(s1["received"]) == list(s2["received"])


def test_determinism_rerun():
    """Reference determinism gate: identical configs → identical results
    (src/test/determinism)."""
    a, _ = build_phold_sim(6, 31337, 25 * MS, 0.8, 2, 4 * SEC, 8 * SEC)
    b, _ = build_phold_sim(6, 31337, 25 * MS, 0.8, 2, 4 * SEC, 8 * SEC)
    a.run()
    b.run()
    assert a.counters() == b.counters()
    sa = jax.device_get(a.state.subs[PholdApp.SUB])
    sb = jax.device_get(b.state.subs[PholdApp.SUB])
    assert list(sa["received"]) == list(sb["received"])
    assert list(sa["forwarded"]) == list(sb["forwarded"])


def test_k_overflow_defers_self_emissions_past_leftovers():
    """When a host overflows K (window matrix full), a self-emission landing
    AFTER the earliest deferred leftover must not jump the queue via the
    inbox — it must be processed in timestamp order in a later window."""
    H = 1
    T = 8

    def record(state, ev, emitter, params):
        sub = dict(state.subs["trace"])
        n = sub["n"]
        hosts = jnp.arange(H, dtype=jnp.int32)
        slot = jnp.where(ev.mask, jnp.clip(n, 0, T - 1), T)
        sub["times"] = sub["times"].at[hosts, slot].set(ev.time, mode="drop")
        sub["n"] = n + ev.mask.astype(jnp.int32)
        subs = dict(state.subs)
        subs["trace"] = sub
        return state.replace(subs=subs)

    def timer_then_emit(state, ev, emitter, params):
        state = record(state, ev, emitter, params)
        hosts = jnp.arange(H, dtype=jnp.int32)
        # lands at 4ms — after the deferred 3ms leftover
        emitter.emit(
            ev.mask, ev.time + 3 * MS, hosts, jnp.int32(KIND_APP_MSG), ev.payload
        )
        return state

    params = make_params(H, 50 * MS)
    sim = Simulation(
        num_hosts=H,
        handlers={KIND_APP_TIMER: timer_then_emit, KIND_APP_MSG: record},
        params=params,
        host_vertex=np.zeros(H, dtype=np.int32),
        seed=1,
        stop_time=SEC,
        runahead=50 * MS,
        event_capacity=64,
        K=2,  # forces the 3ms event to be a leftover
        B=4,
        O=8,
        subs={
            "trace": {
                "times": jnp.full((H, T), -1, dtype=jnp.int64),
                "n": jnp.zeros((H,), dtype=jnp.int32),
            }
        },
        initial_events=[
            (1 * MS, 0, 0, KIND_APP_TIMER, []),  # emits MSG at 4ms
            (2 * MS, 0, 0, KIND_APP_MSG, []),
            (3 * MS, 0, 0, KIND_APP_MSG, []),  # leftover (rank K)
        ],
    )
    sim.run_stepwise()
    trace = jax.device_get(sim.state.subs["trace"])
    assert list(trace["times"][0][:4]) == [1 * MS, 2 * MS, 3 * MS, 4 * MS]
    assert trace["n"][0] == 4


def test_intra_window_self_events_processed_in_order():
    """A self-emitted event landing inside the current window must be
    processed before later pre-existing events of the same host (the
    reference's per-host priority queue does this naturally)."""
    H = 2
    T = 8

    def record(state, ev, emitter, params):
        sub = dict(state.subs["trace"])
        n = sub["n"]
        hosts = jnp.arange(H, dtype=jnp.int32)
        slot = jnp.where(ev.mask, jnp.clip(n, 0, T - 1), T)
        sub["times"] = sub["times"].at[hosts, slot].set(ev.time, mode="drop")
        sub["n"] = n + ev.mask.astype(jnp.int32)
        subs = dict(state.subs)
        subs["trace"] = sub
        return state.replace(subs=subs)

    def timer_then_emit(state, ev, emitter, params):
        state = record(state, ev, emitter, params)
        hosts = jnp.arange(H, dtype=jnp.int32)
        # self event 2ms later — still inside the 50ms window
        emitter.emit(
            ev.mask, ev.time + 2 * MS, hosts, jnp.int32(KIND_APP_MSG), ev.payload
        )
        return state

    params = make_params(H, 50 * MS)
    sim = Simulation(
        num_hosts=H,
        handlers={KIND_APP_TIMER: timer_then_emit, KIND_APP_MSG: record},
        params=params,
        host_vertex=np.zeros(H, dtype=np.int32),
        seed=1,
        stop_time=SEC,
        runahead=50 * MS,
        event_capacity=64,
        K=8,
        B=4,
        O=8,
        subs={
            "trace": {
                "times": jnp.full((H, T), -1, dtype=jnp.int64),
                "n": jnp.zeros((H,), dtype=jnp.int32),
            }
        },
        initial_events=[
            (1 * MS, 0, 0, KIND_APP_TIMER, []),  # emits self MSG at 3ms
            (5 * MS, 0, 0, KIND_APP_MSG, []),
            (5 * MS, 1, 1, KIND_APP_MSG, []),
        ],
    )
    sim.run_stepwise()
    trace = jax.device_get(sim.state.subs["trace"])
    assert list(trace["times"][0][:3]) == [1 * MS, 3 * MS, 5 * MS]
    assert trace["n"][0] == 3
    assert list(trace["times"][1][:1]) == [5 * MS]


def test_k_overflow_time_tie_exact_order():
    """The exact-tie edge the round-1 kernel documented as unfixed: a
    self-emission landing at EXACTLY the earliest deferred leftover's
    nanosecond must still interleave correctly against extracted same-time
    events — the full-key (time, src, seq) compare routes it through the
    inbox iff it precedes the deferred leftover."""
    H = 4
    T = 8
    TIE = 20 * MS

    def record(state, ev, emitter, params):
        sub = dict(state.subs["trace"])
        n = sub["n"]
        hosts = jnp.arange(H, dtype=jnp.int32)
        slot = jnp.where(ev.mask, jnp.clip(n, 0, T - 1), T)
        sub["srcs"] = sub["srcs"].at[hosts, slot].set(ev.src, mode="drop")
        sub["n"] = n + ev.mask.astype(jnp.int32)
        subs = dict(state.subs)
        subs["trace"] = sub
        return state.replace(subs=subs)

    def timer_then_emit(state, ev, emitter, params):
        state = record(state, ev, emitter, params)
        hosts = jnp.arange(H, dtype=jnp.int32)
        # lands at exactly the deferred leftover's time (10ms + 10ms = TIE)
        emitter.emit(
            ev.mask, ev.time + 10 * MS, hosts, jnp.int32(KIND_APP_MSG),
            ev.payload,
        )
        return state

    params = make_params(H, 50 * MS)
    sim = Simulation(
        num_hosts=H,
        handlers={KIND_APP_TIMER: timer_then_emit, KIND_APP_MSG: record},
        params=params,
        host_vertex=np.zeros(H, dtype=np.int32),
        seed=1,
        stop_time=SEC,
        runahead=50 * MS,
        event_capacity=64,
        K=2,  # extracts (10ms,src1), (TIE,src2); defers (TIE,src3)
        B=4,
        O=8,
        subs={
            "trace": {
                "srcs": jnp.full((H, T), -1, dtype=jnp.int32),
                "n": jnp.zeros((H,), dtype=jnp.int32),
            }
        },
        initial_events=[
            (10 * MS, 0, 1, KIND_APP_TIMER, []),  # emits MSG at TIE, src=0
            (TIE, 0, 2, KIND_APP_MSG, []),
            (TIE, 0, 3, KIND_APP_MSG, []),  # deferred leftover (rank K)
        ],
    )
    sim.run_stepwise()
    trace = jax.device_get(sim.state.subs["trace"])
    # Correct total order at host 0 among the TIE-time events is by src:
    # the self-emission (src 0) BEFORE src 2 and src 3.
    assert list(trace["srcs"][0][:4]) == [1, 0, 2, 3]
    assert trace["n"][0] == 4


def test_outbox_overflow_defers_never_drops():
    """Outbox pressure must stall the host (deferring its remaining events
    to later windows), not drop emissions: every message is delivered and
    outbox_overflow_dropped stays zero (round-1 verdict hole #6b)."""
    H = 2
    N = 10  # events on host 0, each emitting one cross-host message

    def count_rx(state, ev, emitter, params):
        sub = dict(state.subs["trace"])
        sub["rx"] = sub["rx"] + ev.mask.astype(jnp.int32)
        subs = dict(state.subs)
        subs["trace"] = sub
        return state.replace(subs=subs)

    def emit_cross(state, ev, emitter, params):
        hosts = jnp.arange(H, dtype=jnp.int32)
        emitter.emit(
            ev.mask, ev.time + 60 * MS, (hosts + 1) % H,
            jnp.int32(KIND_APP_MSG), ev.payload,
        )
        return state

    params = make_params(H, 50 * MS)
    sim = Simulation(
        num_hosts=H,
        handlers={KIND_APP_TIMER: emit_cross, KIND_APP_MSG: count_rx},
        params=params,
        host_vertex=np.zeros(H, dtype=np.int32),
        seed=1,
        stop_time=SEC,
        runahead=50 * MS,
        event_capacity=64,
        K=16,
        B=4,
        O=4,  # absorbs 4 emissions per window, then backpressure
        subs={"trace": {"rx": jnp.zeros((H,), dtype=jnp.int32)}},
        initial_events=[
            (i * MS, 0, 0, KIND_APP_TIMER, []) for i in range(1, N + 1)
        ],
    )
    sim.run_stepwise()
    trace = jax.device_get(sim.state.subs["trace"])
    c = sim.counters()
    assert int(trace["rx"][1]) == N, (trace, c)
    assert c["outbox_overflow_dropped"] == 0
    assert c["outbox_stall_deferred"] > 0  # the path was actually forced
    assert c["pool_overflow_dropped"] == 0


def test_phold_bulk_matches_oracle():
    """The engine's bulk same-kind batch (G-way consecutive pop) must be
    result-invariant: identical received/forwarded/drop/RNG counters vs the
    sequential oracle, with far fewer micro-steps."""
    H, seed = 5, 12345
    latency, rel, msgload = 50 * MS, 0.9, 4
    runtime, stop = 5 * SEC, 10 * SEC
    sim, app = build_phold_sim(H, seed, latency, rel, msgload, runtime, stop,
                               bulk=True)
    sim.run_stepwise()
    plain, _ = build_phold_sim(H, seed, latency, rel, msgload, runtime, stop)
    plain.run_stepwise()
    oracle = phold_oracle(H, seed, latency, rel, msgload, SEC, SEC + runtime, stop)
    sub = jax.device_get(sim.state.subs[PholdApp.SUB])
    assert list(sub["received"]) == oracle["received"]
    assert list(sub["forwarded"]) == oracle["forwarded"]
    cb, cp = sim.counters(), plain.counters()
    assert cb["events_committed"] == cp["events_committed"]
    assert cb["packets_dropped_loss"] == cp["packets_dropped_loss"]
    assert cb["micro_steps"] < cp["micro_steps"]  # the batch actually bit
    rng_c = jax.device_get(sim.state.host.rng_counter)
    assert list(rng_c) == oracle["rng_counters"]


def test_phold_matrix_path_matches_oracle():
    """The whole-window matrix fast path (engine run_matrix) must be
    bit-identical to the sequential oracle: same received/forwarded, same
    drop counts, same RNG counters — and must actually take one micro-step
    per window."""
    H, seed = 5, 12345
    latency, rel, msgload = 50 * MS, 0.8, 3
    runtime, stop = 5 * SEC, 10 * SEC
    sim, app = build_phold_sim(H, seed, latency, rel, msgload, runtime, stop,
                               bulk="matrix")
    windows = sim.run_stepwise()
    oracle = phold_oracle(H, seed, latency, rel, msgload, SEC, SEC + runtime, stop)
    sub = jax.device_get(sim.state.subs[PholdApp.SUB])
    assert list(sub["received"]) == oracle["received"]
    assert list(sub["forwarded"]) == oracle["forwarded"]
    c = sim.counters()
    assert c["packets_sent"] == oracle["sent"]
    assert c["packets_dropped_loss"] == oracle["dropped"]
    assert c["pool_overflow_dropped"] == 0
    rng_c = jax.device_get(sim.state.host.rng_counter)
    assert list(rng_c) == oracle["rng_counters"]
    # one micro-step per window: the loop path never ran
    assert c["micro_steps"] == windows


@pytest.mark.quick
def test_cpu_model_serializes_and_skews():
    """Device-plane CPU model (reference host/cpu.c + event.c:64-92):
    heterogeneous per-host costs serialize each host's events on its
    virtual CPU — loaded hosts' commit clocks (done_t via cpu_avail) run
    correspondingly behind, deterministically, and the loop and matrix
    paths implement the identical serialization."""
    H, seed = 6, 4242
    # msgload 16 over 6 hosts ≈ 800 events/s/host; at 2 ms/event a loaded
    # host's CPU caps at 500/s, so its backlog clock must run away from
    # the free hosts' (the observable skew the reference model produces)
    latency, msgload = 20 * MS, 32
    runtime, stop = 2 * SEC, 4 * SEC
    # hosts 0-2 free CPU; hosts 3-5 pay 10 ms per event (capacity 100
    # events/s, well under the offered load -> the backlog clock runs away)
    cost = np.array([0, 0, 0, 10 * MS, 10 * MS, 10 * MS], dtype=np.int64)

    def build(bulk):
        app = PholdApp(H, msgload=msgload, size_bytes=64, start_time=SEC,
                       runtime=runtime)
        return Simulation(
            num_hosts=H,
            handlers=app.handlers(),
            params=make_params(H, latency, 1.0),
            host_vertex=np.zeros(H, dtype=np.int32),
            seed=seed, stop_time=stop, runahead=latency,
            event_capacity=4096, K=16, B=4, O=16,
            subs={PholdApp.SUB: app.init_sub()},
            initial_events=app.initial_events(),
            bulk_kinds=app.bulk_kinds() if bulk else None,
            matrix_handlers=app.matrix_handlers() if bulk == "matrix" else None,
            cpu_ns_per_event=cost,
        )

    sim = build(False)
    sim.run_stepwise()
    c = sim.counters()
    assert c["cpu_delay_applied"] > 0
    avail = jax.device_get(sim.state.host.cpu_avail)
    # free hosts' CPU clock tracks their last event time; saturated hosts'
    # backlog clock runs well past it (commit-time skew)
    assert min(avail[3:]) > max(avail[:3]) + 200 * MS, avail

    # determinism: bit-identical rerun
    sim2 = build(False)
    sim2.run_stepwise()
    assert sim2.counters() == c
    assert list(jax.device_get(sim2.state.host.cpu_avail)) == list(avail)

    # matrix fast path implements the same serialization
    simm = build("matrix")
    simm._step = jax.jit(
        lambda st, p, ws, we: simm._step_fn(st, p, ws, we)
    )
    simm.run_stepwise()
    cm = simm.counters()
    assert cm["cpu_delay_applied"] == c["cpu_delay_applied"]
    assert cm["events_committed"] == c["events_committed"]
    assert list(jax.device_get(simm.state.host.cpu_avail)) == list(avail)

    # the model is observable: zero-cost run differs
    sim0 = build(False)
    # same build but no cpu cost
    app0 = PholdApp(H, msgload=msgload, size_bytes=64, start_time=SEC,
                    runtime=runtime)
    sim0 = Simulation(
        num_hosts=H, handlers=app0.handlers(),
        params=make_params(H, latency, 1.0),
        host_vertex=np.zeros(H, dtype=np.int32),
        seed=seed, stop_time=stop, runahead=latency,
        event_capacity=4096, K=16, B=4, O=16,
        subs={PholdApp.SUB: app0.init_sub()},
        initial_events=app0.initial_events(),
    )
    sim0.run_stepwise()
    assert sim0.counters()["cpu_delay_applied"] == 0
