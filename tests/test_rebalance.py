"""Between-window re-sharding (parallel/islands.py rebalance — the P3
work-stealing replacement, scheduler_policy_host_steal.c:1-562).

Correctness property: a rebalance permutes the host→shard layout ONLY —
results stay bit-identical to the global engine (per-host order, RNG
streams and sequence numbering key on GLOBAL host ids, never on layout).
"""

import numpy as np
import pytest

from shadow_tpu.flagship import SELF_LOOP_50MS_GML
from shadow_tpu.sim import build_simulation


def _hot_cfg(num_shards=1, rebalance=False, hosts=128, capacity=1024):
    """Skewed PHOLD: 60% of traffic targets the first 12.5% of hosts —
    which a static contiguous assignment parks ALL on shard 0."""
    exp = {
        "event_capacity": capacity,
        "events_per_host_per_window": 12,
        "outbox_slots": 12,
        "inbox_slots": 4,
    }
    if num_shards > 1:
        exp.update(num_shards=num_shards, exchange_slots=64,
                   rebalance=rebalance)
    return {
        "general": {"stop_time": 3, "seed": 9},
        "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
        "experimental": exp,
        "hosts": {"peer": {"quantity": hosts, "app_model": "phold",
                           "app_options": {"msgload": 4, "runtime": 2,
                                           "hot_frac": 0.125,
                                           "hot_share": 0.6}}},
    }


_KEYS = (
    "events_committed", "events_emitted", "packets_sent",
    "packets_dropped_loss", "bytes_sent", "pool_overflow_dropped",
)


def _phold_state(sim):
    return {
        k: np.asarray(sim.state.subs["phold"][k]).reshape(-1)
        for k in ("received", "forwarded")
    }


@pytest.mark.quick
def test_hot_phold_islands_match_global():
    g = build_simulation(_hot_cfg())
    g.run_stepwise()
    i = build_simulation(_hot_cfg(num_shards=4))
    i.run_stepwise()
    cg, ci = g.counters(), i.counters()
    for k in _KEYS:
        assert cg[k] == ci[k], (k, cg[k], ci[k])
    sg, si = _phold_state(g), _phold_state(i)
    for k in sg:
        assert (sg[k] == si[k]).all(), k


@pytest.mark.quick
def test_rebalance_preserves_results():
    """Force rebalances mid-run (explicit + auto) and require bit-equality
    with the global engine."""
    g = build_simulation(_hot_cfg())
    g.run_stepwise()
    r = build_simulation(_hot_cfg(num_shards=4, rebalance=True))
    # interleave: run a bit, rebalance, run on (fused path auto-triggers
    # only under pressure; force one to exercise the permutation)
    r.run(until=1_500_000_000, windows_per_dispatch=8)
    r.rebalance_now()
    assert r.rebalances >= 1
    r.run(windows_per_dispatch=8)
    cg, cr = g.counters(), r.counters()
    for k in _KEYS:
        assert cg[k] == cr[k], (k, cg[k], cr[k])
    sg, sr = _phold_state(g), _phold_state(r)
    for k in sg:
        # islands state is laid out in permuted slots; map back via gid
        gid = np.asarray(r.state.host.gid).reshape(-1)
        back = np.empty_like(sr[k])
        back[gid] = sr[k]
        assert (sg[k] == back).all(), k


@pytest.mark.quick
def test_rebalance_actually_evens_load():
    """After rebalancing, the skewed workload's per-shard resident load
    must flatten (max/mean below the static assignment's)."""
    static = build_simulation(_hot_cfg(num_shards=4, capacity=2048))
    static.run(until=2_000_000_000, windows_per_dispatch=8)
    occ_s = static.shard_loads().astype(float)

    reb = build_simulation(
        _hot_cfg(num_shards=4, rebalance=True, capacity=2048)
    )
    reb.run(until=1_000_000_000, windows_per_dispatch=8)
    reb.rebalance_now()
    reb.run(until=2_000_000_000, windows_per_dispatch=8)
    occ_r = reb.shard_loads().astype(float)

    skew_s = occ_s.max() / max(occ_s.mean(), 1.0)
    skew_r = occ_r.max() / max(occ_r.mean(), 1.0)
    assert skew_s > 1.8, f"workload not skewed enough: {occ_s}"
    assert skew_r < skew_s * 0.7, (occ_s, occ_r)
