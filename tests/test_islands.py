"""Islands engine (parallel/islands.py): equivalence with the global
engine, exchange backpressure, shard_map execution, determinism.

The property under test is the reference's: results are independent of the
worker/host partition (scheduler.c:329-353 shuffles host→worker assignment
precisely because it must not matter). Here: counters and final app state
are bit-identical between the global single-pool engine and any islands
layout, including under exchange backpressure (bounded all_to_all misses
defer, never drop, never reorder).
"""

import pytest

from shadow_tpu.core import simtime
from shadow_tpu.flagship import SELF_LOOP_50MS_GML
from shadow_tpu.sim import build_simulation

def _phold_cfg(num_shards=1, exchange_slots=32, hosts=64, mode="vmap"):
    exp = {
        "event_capacity": 1024,
        "events_per_host_per_window": 8,
        "outbox_slots": 8,
        "inbox_slots": 4,
    }
    if num_shards > 1:
        exp.update(num_shards=num_shards, exchange_slots=exchange_slots,
                   island_mode=mode)
    return {
        "general": {"stop_time": 3, "seed": 42},
        "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
        "experimental": exp,
        "hosts": {"peer": {"quantity": hosts, "app_model": "phold",
                           "app_options": {"msgload": 2, "runtime": 2}}},
    }


def _flood_cfg(num_shards=1, exchange_slots=48, hosts=32, mode="vmap"):
    exp = {
        "event_capacity": 2048,
        "events_per_host_per_window": 8,
        "outbox_slots": 8,
        "inbox_slots": 4,
        "router_queue_slots": 8,
    }
    if num_shards > 1:
        exp.update(num_shards=num_shards, exchange_slots=exchange_slots,
                   island_mode=mode)
    return {
        "general": {"stop_time": 3, "seed": 7},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "10 ms" packet_loss 0.001 ]\n'
            ']\n')}},
        "experimental": exp,
        "hosts": {
            "server": {"quantity": 4, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": hosts - 4, "app_model": "udp_flood",
                       "app_options": {"interval": "40 ms", "size": 512,
                                       "runtime": 1}},
        },
    }


def _tcp_cfg(num_shards=1, hosts=16, mode="vmap"):
    exp = {
        "event_capacity": 4096,
        "events_per_host_per_window": 8,
        "outbox_slots": 32,
        "inbox_slots": 8,
        "router_queue_slots": 16,
    }
    if num_shards > 1:
        exp.update(num_shards=num_shards, exchange_slots=64,
                   island_mode=mode)
    return {
        "general": {"stop_time": 3, "seed": 11},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]\n'
            ']\n')}},
        "experimental": exp,
        "hosts": {
            "server": {"quantity": 4, "app_model": "tcp_bulk",
                       "app_options": {"role": "server"}},
            "client": {"quantity": hosts - 4, "app_model": "tcp_bulk",
                       "app_options": {"total": "8 KiB"}},
        },
    }


_PHYS_KEYS = (
    "events_committed", "events_emitted", "packets_sent",
    "packets_delivered", "packets_dropped_loss", "bytes_sent",
    "bytes_delivered", "pool_overflow_dropped", "outbox_overflow_dropped",
    "bulk_contract_violations",
)


def _run(cfg):
    sim = build_simulation(cfg)
    sim.run_stepwise()
    return sim


def _assert_phys_equal(ca, cb):
    for k in _PHYS_KEYS:
        assert ca[k] == cb[k], (k, ca[k], cb[k])


@pytest.mark.quick
def test_phold_islands_match_global():
    g = _run(_phold_cfg())
    i = _run(_phold_cfg(num_shards=4))
    cg, ci = g.counters(), i.counters()
    _assert_phys_equal(cg, ci)
    assert ci["exchange_sent"] > 0  # uniform dsts must cross shards
    assert ci["exchange_deferred"] == 0
    # per-host app state identical (received/forwarded counts)
    import numpy as np

    for key in ("received", "forwarded"):
        a = np.asarray(g.state.subs["phold"][key])
        b = np.asarray(i.state.subs["phold"][key]).reshape(-1)
        assert (a == b).all(), key


@pytest.mark.quick
def test_phold_islands_deferred_exchange_still_exact():
    """exchange_slots=1 forces heavy backpressure: rows defer across
    windows under the window-end clamp, and the results must still be
    bit-identical (late, never lost, never reordered)."""
    g = _run(_phold_cfg())
    i = _run(_phold_cfg(num_shards=4, exchange_slots=1))
    cg, ci = g.counters(), i.counters()
    _assert_phys_equal(cg, ci)
    assert ci["exchange_deferred"] > 0  # the point of this test


@pytest.mark.quick
def test_flood_islands_match_global():
    g = _run(_flood_cfg())
    i = _run(_flood_cfg(num_shards=4))
    _assert_phys_equal(g.counters(), i.counters())
    import numpy as np

    a = np.asarray(g.state.subs["udp_flood"]["recv"])
    b = np.asarray(i.state.subs["udp_flood"]["recv"]).reshape(-1)
    assert (a == b).all()


def test_tcp_islands_match_global():
    g = _run(_tcp_cfg())
    i = _run(_tcp_cfg(num_shards=4))
    cg, ci = g.counters(), i.counters()
    _assert_phys_equal(cg, ci)
    import numpy as np

    a = np.asarray(g.state.subs["tcp_bulk"]["eof_seen"])
    b = np.asarray(i.state.subs["tcp_bulk"]["eof_seen"]).reshape(-1)
    assert (a == b).all()
    assert a.sum() > 0  # streams actually completed


@pytest.mark.quick
def test_islands_shard_map_matches_vmap(devices):
    if len(devices) < 4:
        pytest.skip("needs 4 virtual devices")
    v = _run(_phold_cfg(num_shards=4, mode="vmap"))
    s = _run(_phold_cfg(num_shards=4, mode="shard_map"))
    cv, cs = v.counters(), s.counters()
    _assert_phys_equal(cv, cs)
    assert cv["exchange_sent"] == cs["exchange_sent"]


@pytest.mark.quick
def test_islands_deterministic_rerun():
    a = _run(_phold_cfg(num_shards=4))
    b = _run(_phold_cfg(num_shards=4))
    ca, cb = a.counters(), b.counters()
    assert ca == cb


@pytest.mark.quick
def test_islands_fused_run_matches_stepwise():
    i = _run(_phold_cfg(num_shards=4))
    f = build_simulation(_phold_cfg(num_shards=4))
    f.run(windows_per_dispatch=16)
    _assert_phys_equal(i.counters(), f.counters())
