"""Wider syscall surface (VERDICT r4 #5): stat family on managed fds,
getifaddrs, deterministic localtime, the mmap policy, /proc/self/fd — and
the LOUD failure for binaries that never complete the shim handshake
(static binaries would otherwise run unsimulated and silently break
determinism; the reference covers them with ptrace, thread_ptrace.c).
"""

import shutil
import subprocess

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.driver import DriverError, NS_PER_SEC, ProcessDriver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)


@pytest.mark.quick
def test_wide_syscall_surface(apps):
    d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    h = d.add_host("wideling", "11.0.0.7")
    d.add_process(h, [apps["wide_syscalls"]], start_time=NS_PER_SEC)
    d.run()
    p = d.procs[0]
    out = p.stdout.decode()
    assert p.exit_code == 0, (out, p.stderr.decode())
    for probe in (
        "fstat-sock", "fstat-pipe", "fstat-eventfd", "stat-path", "statx", "statx-raw",
        "getifaddrs",
        "localtime", "mmap-anon", "mmap-policy", "mmap-managed-denied",
        "proc-self-fd", "proc-fd-listing", "signalfd", "signalfd-chld",
        "ppoll-sigmask", "rlimit-roundtrip",
    ):
        assert f"ok {probe}" in out, (probe, out)
    # getifaddrs reports the SIMULATED address
    assert "ok getifaddrs 11.0.0.7" in out, out
    # localtime is on the virtual clock (sim epoch, not wall time):
    # time() at 1 sim-second = 1
    lt = [l for l in out.splitlines() if l.startswith("ok localtime")][0]
    assert lt.split()[2] == "1", lt
    assert "1970-01-01" in lt, lt  # UTC rendering of the sim epoch
    # rlimits are the deterministic synthesized table, not the machine's;
    # the NOFILE soft limit must clear FD_BASE + the managed-fd budget
    # (procs/driver.VIRT_NOFILE mirrors it)
    assert "ok rlimit-nofile 65536 262144" in out, out
    # getrusage serves the virtual clock as CPU time (sim t >= 1s here)
    ru = [l for l in out.splitlines() if l.startswith("ok rusage")][0]
    assert ru.split()[2].startswith("1."), ru
    assert ru.split()[3] == "65536", ru


@pytest.mark.quick
def test_wide_surface_deterministic(apps):
    def run_once():
        d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000,
                          seed=3)
        h = d.add_host("wideling", "11.0.0.7")
        d.add_process(h, [apps["wide_syscalls"]], start_time=NS_PER_SEC)
        d.run()
        return d.procs[0].stdout

    assert run_once() == run_once()


@pytest.mark.quick
def test_static_binary_fails_loudly(apps, tmp_path):
    """A statically linked binary never loads the shim; the driver must
    abort the simulation with a clear error instead of letting it run
    unsimulated (VERDICT r3 missing #5)."""
    cc = shutil.which("cc") or shutil.which("gcc")
    src = tmp_path / "hello_static.c"
    src.write_text(
        '#include <stdio.h>\nint main(void){printf("hi\\n");return 0;}\n'
    )
    exe = tmp_path / "hello_static"
    r = subprocess.run(
        [cc, "-static", "-O0", "-o", str(exe), str(src)],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"no static libc available: {r.stderr[:200]}")
    d = ProcessDriver(stop_time=5 * NS_PER_SEC, latency_ns=10_000_000)
    h = d.add_host("stat", "11.0.0.9")
    d.add_process(h, [str(exe)], start_time=NS_PER_SEC)
    with pytest.raises(DriverError, match="shim handshake"):
        d.run()


@pytest.mark.quick
def test_rdtsc_reads_virtual_clock(apps):
    """Raw rdtsc/rdtscp (host/tsc.c analog): PR_SET_TSC traps the
    instruction and the shim serves the virtual clock — syscall-free reads
    advance deterministically by one cycle each (so calibrated pure-rdtsc
    delay loops terminate), exact sim-time advance across a nanosleep."""
    d = ProcessDriver(stop_time=10 * NS_PER_SEC, latency_ns=10_000_000)
    h = d.add_host("ticker", "11.0.0.8")
    d.add_process(h, [apps["tsc_probe"]], start_time=NS_PER_SEC)
    d.run()
    p = d.procs[0]
    out = p.stdout.decode()
    assert p.exit_code == 0, (out, p.stderr.decode())
    lines = dict(l.split(" ", 1) for l in out.strip().splitlines())
    # 1 GHz virtual TSC: cycle == sim-ns; first read at sim t=1s
    assert lines["tsc-a"] == str(NS_PER_SEC), lines
    assert lines["tsc-mono"] == "1", lines
    # nanosleep(250ms): the delta is EXACTLY the virtual elapsed time
    # (the sleep's syscall stamp overtakes the few per-read ticks)
    assert lines["tsc-delta"] == str(250_000_000), lines
