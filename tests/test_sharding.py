"""Multi-device sharding: the window step runs sharded over an 8-device CPU
mesh (the driver's dryrun_multichip contract) — host-dimension data
parallelism, GSPMD-inserted collectives (SURVEY.md §2.5 P1/P2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.flagship import build_phold_flagship
from shadow_tpu.parallel import host_mesh, shard_params, shard_state


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (conftest sets this up)")
    return host_mesh(8)


def test_sharded_step_matches_single_device(mesh):
    """One window stepped sharded over 8 devices produces the same counters
    and pool as the unsharded step (GSPMD must not change semantics)."""
    H, C, K = 64, 1024, 8
    sim = build_phold_flagship(H, msgload=2, stop_s=10, runtime_s=8,
                               event_capacity=C, K=K)
    ws = simtime.NS_PER_SEC
    we = ws + sim.runahead

    ref_state, ref_min = sim._step(sim.state, sim.params, ws, we)
    jax.block_until_ready(ref_min)

    state = shard_state(sim.state, mesh)
    params = shard_params(sim.params, mesh)
    with mesh:
        out_state, out_min = sim._step(
            state, params, jnp.int64(ws), jnp.int64(we)
        )
        jax.block_until_ready(out_min)

    assert int(out_min) == int(ref_min)
    ref_c = jax.device_get(ref_state.counters)
    out_c = jax.device_get(out_state.counters)
    assert ref_c == out_c
    # event pools match as multisets (sort order may differ only in free
    # slots, which all carry NEVER)
    for field in ("time", "dst", "src", "seq", "kind"):
        a = np.sort(np.asarray(jax.device_get(getattr(ref_state.pool, field))))
        b = np.sort(np.asarray(jax.device_get(getattr(out_state.pool, field))))
        assert np.array_equal(a, b), field


def test_graft_dryrun_entrypoint_runs(mesh):
    """The driver's dryrun contract stays green from inside the suite."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)
