"""Multi-device sharding: the window step runs sharded over an 8-device CPU
mesh (the driver's dryrun_multichip contract) — host-dimension data
parallelism, GSPMD-inserted collectives (SURVEY.md §2.5 P1/P2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.flagship import build_phold_flagship
from shadow_tpu.parallel import host_mesh, shard_params, shard_state


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (conftest sets this up)")
    return host_mesh(8)


def test_sharded_step_matches_single_device(mesh):
    """One window stepped sharded over 8 devices produces the same counters
    and pool as the unsharded step (GSPMD must not change semantics)."""
    H, C, K = 64, 1024, 8
    sim = build_phold_flagship(H, msgload=2, stop_s=10, runtime_s=8,
                               event_capacity=C, K=K)
    ws = simtime.NS_PER_SEC
    we = ws + sim.runahead

    ref_state, ref_min = sim._step(sim.state, sim.params, ws, we)
    jax.block_until_ready(ref_min)

    state = shard_state(sim.state, mesh)
    params = shard_params(sim.params, mesh)
    with mesh:
        out_state, out_min = sim._step(
            state, params, jnp.int64(ws), jnp.int64(we)
        )
        jax.block_until_ready(out_min)

    assert int(out_min) == int(ref_min)
    ref_c = jax.device_get(ref_state.counters)
    out_c = jax.device_get(out_state.counters)
    assert ref_c == out_c
    # event pools match as multisets (sort order may differ only in free
    # slots, which all carry NEVER)
    for field in ("time", "dst", "src", "seq", "kind"):
        a = np.sort(np.asarray(jax.device_get(getattr(ref_state.pool, field))))
        b = np.sort(np.asarray(jax.device_get(getattr(out_state.pool, field))))
        assert np.array_equal(a, b), field


def test_graft_dryrun_entrypoint_runs(mesh):
    """The driver's dryrun contract stays green from inside the suite."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def _pool_multiset(state):
    cols = []
    for field in ("time", "dst", "src", "seq", "kind"):
        cols.append(np.asarray(jax.device_get(getattr(state.pool, field))))
    rows = list(zip(*[c.tolist() for c in cols]))
    return sorted(rows)


def test_sharded_full_phold_run_matches_single(mesh):
    """FULL multi-window PHOLD run (matrix fast path under GSPMD): 8
    devices vs 1, identical counters, app results, RNG counters, and the
    final event pool as a multiset (VERDICT r1 #9 — full runs, not one
    window)."""
    def build():
        return build_phold_flagship(64, msgload=3, stop_s=6, runtime_s=6,
                                    event_capacity=1024, K=8)

    ref = build()
    ref.run_stepwise()

    from shadow_tpu.parallel import shard_sim

    sh = build()
    shard_sim(sh, mesh)
    with mesh:
        sh.run_stepwise()

    assert ref.counters() == sh.counters()
    ra = jax.device_get(ref.state.subs["phold"])
    sa = jax.device_get(sh.state.subs["phold"])
    assert list(ra["received"]) == list(sa["received"])
    assert list(ra["forwarded"]) == list(sa["forwarded"])
    assert list(jax.device_get(ref.state.host.rng_counter)) == list(
        jax.device_get(sh.state.host.rng_counter)
    )
    assert _pool_multiset(ref.state) == _pool_multiset(sh.state)


def test_sharded_tcp_netstack_run_matches_single(mesh):
    """A sharded TCP net-stack sim (NIC + CoDel + vectorized TCP machines,
    the micro-step loop path) over 8 devices equals the single-device run:
    counters, delivered bytes, and per-socket outcomes."""
    from shadow_tpu.parallel import shard_sim
    from shadow_tpu.sim import build_simulation

    def build():
        return build_simulation({
            "general": {"stop_time": 4, "seed": 13},
            "network": {"graph": {"type": "gml", "inline": (
                'graph [\n'
                '  node [ id 0 bandwidth_down "50 Mbit" '
                'bandwidth_up "50 Mbit" ]\n'
                '  edge [ source 0 target 0 latency "15 ms" ]\n]\n')}},
            "experimental": {
                "event_capacity": 4096,
                "events_per_host_per_window": 8,
                "sockets_per_host": 8,
            },
            "hosts": {
                "server": {"quantity": 8, "app_model": "tcp_bulk",
                           "app_options": {"role": "server"}},
                "client": {"quantity": 56, "app_model": "tcp_bulk",
                           "app_options": {"total": "24 KiB"}},
            },
        })

    ref = build()
    ref.run_stepwise()

    sh = build()
    shard_sim(sh, mesh)
    with mesh:
        sh.run_stepwise()

    assert ref.counters() == sh.counters()
    from shadow_tpu.net import tcp as tcp_mod

    ta = jax.device_get(ref.state.subs[tcp_mod.SUB])
    tb = jax.device_get(sh.state.subs[tcp_mod.SUB])
    assert int(ta.retransmits) == int(tb.retransmits)
    assert np.array_equal(ta.bytes_acked, tb.bytes_acked)
    assert np.array_equal(ta.bytes_received, tb.bytes_received)


def test_sharded_determinism_rerun(mesh):
    """Two identical SHARDED runs are bit-identical (the determinism gate
    under GSPMD)."""
    from shadow_tpu.parallel import shard_sim

    def run_once():
        sim = build_phold_flagship(64, msgload=2, stop_s=5, runtime_s=5,
                                   event_capacity=1024, K=8)
        shard_sim(sim, mesh)
        with mesh:
            sim.run_stepwise()
        return sim.counters(), _pool_multiset(sim.state)

    c1, p1 = run_once()
    c2, p2 = run_once()
    assert c1 == c2
    assert p1 == p2
