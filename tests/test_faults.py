"""Fault-tolerance plane (shadow_tpu/faults): deterministic injection,
supervised recovery, crash-consistent auto-checkpointing.

The acceptance gates of ISSUE 3:
  * determinism under faults — the same fault plan twice yields identical
    committed-event counts and final state (device plane) / byte-identical
    per-host outputs (managed plane), with unaffected hosts matching a
    fault-free run;
  * crash-resume exactness — SIGKILL the simulator between handoffs,
    re-launch with --resume, and the final committed-event totals equal an
    uninterrupted run's.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.faults import plan as plan_mod
from shadow_tpu.faults.injector import FaultInjector, corrupt_file
from shadow_tpu.procs import build as build_mod
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick

NS = simtime.NS_PER_SEC

DEVICE_YAML = """
general:
  stop_time: 4
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 3}
"""


def _states_equal(a, b) -> bool:
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb)
    )


# ---------------------------------------------------------------------------
# plan schema + injector bookkeeping (pure host code)
# ---------------------------------------------------------------------------


def test_plan_validation():
    good = {
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [
            {"at": "1 s", "op": "kill_proc", "proc": "a.0"},
            {"at": "500 ms", "op": "refuse_ipc", "proc": "a.0", "count": 2},
            {"at": 2, "op": "kill_host", "host": 3},
            {"at": "2 s", "op": "skew_hosts", "span": [0, 4],
             "factor": 6},
            {"at": "2 s", "op": "skew_hosts", "hosts": ["relay.0", 7]},
            {"at": "1 s", "op": "force_spill"},
            {"at": "3 s", "op": "corrupt_file", "path": "*.npz",
             "mode": "flip"},
        ],
    }
    plan_mod.validate_fault_plan_doc(good)
    faults = plan_mod.parse_fault_plan(good["faults"])
    # ordered by (at, declaration index)
    assert [f.op for f in faults] == [
        "refuse_ipc", "kill_proc", "force_spill", "kill_host",
        "skew_hosts", "skew_hosts", "corrupt_file",
    ]
    assert faults[1].at_ns == 1 * NS
    assert faults[4].span == [0, 4] and faults[4].factor == 6
    assert faults[5].hosts == ["relay.0", 7]
    assert faults[5].factor == 2  # the default multiplier

    for bad in (
        {**good, "kind": "nope"},
        {**good, "schema_version": 99},
        {**good, "faults": [{"op": "kill_proc", "proc": "a.0"}]},  # no at
        {**good, "faults": [{"at": 1, "op": "explode"}]},
        {**good, "faults": [{"at": 1, "op": "kill_proc"}]},  # no proc
        {**good, "faults": [{"at": 1, "op": "kill_proc", "proc": "a",
                             "bogus": 1}]},
        {**good, "faults": [{"at": 1, "op": "corrupt_file", "path": "x",
                             "mode": "eat"}]},
        {**good, "faults": [{"at": -1, "op": "force_spill"}]},
        {**good, "extra_top": {}},
        # skew_hosts: exactly one of hosts|span, sane span, factor >= 2
        {**good, "faults": [{"at": 1, "op": "skew_hosts"}]},
        {**good, "faults": [{"at": 1, "op": "skew_hosts",
                             "hosts": [1], "span": [0, 2]}]},
        {**good, "faults": [{"at": 1, "op": "skew_hosts", "hosts": []}]},
        {**good, "faults": [{"at": 1, "op": "skew_hosts",
                             "span": [0, 0]}]},
        {**good, "faults": [{"at": 1, "op": "skew_hosts",
                             "span": [-1, 2]}]},
        {**good, "faults": [{"at": 1, "op": "skew_hosts",
                             "span": [0, 2], "factor": 1}]},
    ):
        with pytest.raises(plan_mod.FaultPlanError):
            plan_mod.validate_fault_plan_doc(bad)


def test_validator_tool(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import validate_fault_plan as tool
    finally:
        sys.path.pop(0)
    good = tmp_path / "good.json"
    good.write_text(json.dumps({
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [{"at": "1 s", "op": "force_spill"}],
    }))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"kind": "x"}))
    notjson = tmp_path / "notjson.json"
    notjson.write_text("{nope")
    assert tool.main([str(good)]) == 0
    assert tool.main([str(bad)]) == 2
    assert tool.main([str(notjson)]) == 2
    assert tool.main([str(tmp_path / "absent.json")]) == 2


def test_injector_planes_and_stats():
    faults = plan_mod.parse_fault_plan([
        {"at": 1, "op": "kill_proc", "proc": "a.0"},
        {"at": 2, "op": "kill_host", "host": 0},
        {"at": 3, "op": "force_spill"},
    ])
    inj = FaultInjector(faults)
    # device plane at t=2.5s: only the device op fires; the proc op is
    # another plane's and stays pending
    due = inj.due(int(2.5 * NS), plan_mod.DEVICE_OPS)
    assert [f.op for f in due] == ["kill_host"]
    assert inj.pending == 2
    # firing is once-only
    assert inj.due(int(2.5 * NS), plan_mod.DEVICE_OPS) == []
    s = inj.stats()
    assert s["injections_fired"] == 1 and s["injected_kill_host"] == 1


def test_corrupt_file_modes(tmp_path):
    for i in range(2):
        (tmp_path / f"f{i}.bin").write_bytes(bytes(range(200)))
    f = plan_mod.parse_fault_plan(
        [{"at": 0, "op": "corrupt_file", "path": "f*.bin", "mode": "flip"}]
    )[0]
    touched = corrupt_file(f, default_dir=str(tmp_path))
    assert len(touched) == 2
    data = (tmp_path / "f0.bin").read_bytes()
    assert len(data) == 200 and data != bytes(range(200))
    f2 = plan_mod.parse_fault_plan(
        [{"at": 0, "op": "corrupt_file", "path": "f0.bin",
          "mode": "truncate"}]
    )[0]
    corrupt_file(f2, default_dir=str(tmp_path))
    assert (tmp_path / "f0.bin").stat().st_size == 100
    f3 = plan_mod.parse_fault_plan(
        [{"at": 0, "op": "corrupt_file", "path": "f1.bin", "mode": "delete"}]
    )[0]
    corrupt_file(f3, default_dir=str(tmp_path))
    assert not (tmp_path / "f1.bin").exists()


# ---------------------------------------------------------------------------
# device plane: quarantine determinism, force_spill exactness, islands
# ---------------------------------------------------------------------------


def _device_run(inject=None, **build_kw):
    sim = build_simulation(DEVICE_YAML)
    if inject:
        sim.attach_faults(plan_mod.parse_fault_plan(inject))
    sim.run(**build_kw)
    return sim


def test_device_kill_host_deterministic():
    """Acceptance gate: the same kill_host plan twice is bit-identical —
    same committed counts, same final state digest."""
    plan = [{"at": "1 s", "op": "kill_host", "host": 3}]
    a = _device_run(plan)
    b = _device_run(plan)
    assert a.counters() == b.counters()
    assert _states_equal(a.state, b.state)
    assert a.fault_counters["hosts_quarantined"] == 1
    assert a.fault_counters["events_drained"] >= 1
    # the dead host stops committing: a fault-free run commits more
    ref = _device_run()
    assert ref.counters()["events_committed"] > a.counters()[
        "events_committed"]
    # obs block records the fault-plane actions (slot 8, block v3)
    snap = a.obs_snapshot()
    assert snap["win"]["fault_actions"] >= 1


def _live_rows(sim):
    """Canonical multiset of pending pool events: spill round-trips may
    permute SLOTS (immaterial — core/spill.py docstring) but never the
    event set itself."""
    p = jax.device_get(sim.state.pool)
    t = np.asarray(p.time).reshape(-1)
    live = t != simtime.NEVER
    cols = np.stack([
        t[live],
        np.asarray(p.dst).reshape(-1)[live],
        np.asarray(p.src).reshape(-1)[live],
        np.asarray(p.seq).reshape(-1)[live],
        np.asarray(p.kind).reshape(-1)[live],
    ])
    return cols[:, np.lexsort(cols[::-1])]


def test_device_force_spill_is_bit_exact():
    """An injected spill episode exercises the drain/clamp/re-inject
    machinery without changing ANY result: committed work, per-host
    frontiers, and the pending-event multiset all match a fault-free run
    (only pool SLOT order — immaterial — may differ)."""
    ref = _device_run()
    sim = _device_run([{"at": "1 s", "op": "force_spill"}])
    assert sim.counters() == ref.counters()
    assert np.array_equal(_live_rows(sim), _live_rows(ref))
    sa, sr = sim.obs_snapshot(), ref.obs_snapshot()
    assert np.array_equal(sa["host_events"], sr["host_events"])
    assert np.array_equal(sa["host_last_t"], sr["host_last_t"])
    assert sim.spill_stats()["spill_episodes"] >= 1


def test_islands_kill_host_composes_with_exchange():
    """Quarantine on the islands runner: rows for the dead host drain from
    EVERY shard's pool (exchange-deferred rows included, via the recurring
    handoff drain), and the run stays deterministic."""
    yaml = DEVICE_YAML.replace(
        "  event_capacity: 1024",
        "  event_capacity: 1024\n  num_shards: 2",
    )
    assert "num_shards" in yaml

    def run():
        sim = build_simulation(yaml)
        sim.attach_faults(plan_mod.parse_fault_plan(
            [{"at": "1 s", "op": "kill_host", "host": 5}]
        ))
        sim.run()
        return sim

    a, b = run(), run()
    assert a.counters() == b.counters()
    assert _states_equal(a.state, b.state)
    assert a.fault_counters["hosts_quarantined"] == 1
    assert a.fault_counters["events_drained"] >= 1


def test_device_metrics_carry_faults_namespace():
    from shadow_tpu.obs import metrics as obs_metrics

    sim = _device_run([{"at": "1 s", "op": "kill_host", "host": 0}])
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_device(sim, reg)
    doc = reg.to_doc()
    obs_metrics.validate_metrics_doc(doc)
    assert doc["counters"]["faults.hosts_quarantined"] == 1
    assert doc["counters"]["faults.injections_fired"] == 1
    assert doc["counters"]["faults.events_drained"] >= 1


# ---------------------------------------------------------------------------
# crash-resume exactness (acceptance gate): SIGKILL between handoffs,
# re-launch with --resume, totals equal an uninterrupted run
# ---------------------------------------------------------------------------


def test_cli_sigkill_then_resume_matches_uninterrupted(tmp_path):
    ref = build_simulation(DEVICE_YAML)
    ref.run()
    want = ref.counters()["events_committed"]

    cfg = tmp_path / "c.yaml"
    cfg.write_text(DEVICE_YAML)
    data = tmp_path / "data"
    ckdir = data / "checkpoints"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.abspath(os.path.join(os.path.dirname(__file__), "..",
                                     ".jax_cache")),
    )
    env["PYTHONPATH"] = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..")
    ) + os.pathsep + env.get("PYTHONPATH", "")

    p = subprocess.Popen(
        [sys.executable, "-m", "shadow_tpu", str(cfg), "-d", str(data),
         "--checkpoint-every", "1 s"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            if ckdir.is_dir() and any(
                n.startswith("ckpt-") and n.endswith(".npz")
                for n in os.listdir(ckdir)
            ):
                break
            if p.poll() is not None:
                pytest.fail(
                    "run finished before SIGKILL: "
                    + p.stdout.read().decode()[-400:]
                )
            time.sleep(0.05)
        else:
            pytest.fail("no checkpoint appeared within 240 s")
        os.kill(p.pid, signal.SIGKILL)
    finally:
        p.wait()

    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", str(cfg), "-d", str(data),
         "--resume", str(ckdir)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resumed from" in r.stderr
    m = re.search(r"done: 8 hosts, (\d+) events", r.stdout)
    assert m, r.stdout
    assert int(m.group(1)) == want


# ---------------------------------------------------------------------------
# managed plane: kill/wedge/refuse + quarantine policy (needs toolchain)
# ---------------------------------------------------------------------------

toolchain = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)

GML_50MS = (
    'graph [\n'
    '  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]\n'
    '  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]\n'
    ']\n'
)


def _pairs_cfg(apps, faults: dict):
    """Two independent UDP echo pairs: pair A finishes early, pair B's
    client stays busy (40 pings x 100 ms RTT) — the fault target."""
    return {
        "general": {"stop_time": "6 s", "seed": 7},
        "network": {"graph": {"type": "gml", "inline": GML_50MS}},
        "faults": faults,
        "hosts": {
            "servera": {"processes": [
                {"path": apps["udp_echo_server"], "args": "9000 3"}]},
            "clienta": {"processes": [
                {"path": apps["udp_echo_client"], "args": "servera 9000 3",
                 "start_time": "1 s"}]},
            "serverb": {"processes": [
                {"path": apps["udp_echo_server"], "args": "9000 40"}]},
            "clientb": {"processes": [
                {"path": apps["udp_echo_client"], "args": "serverb 9000 40",
                 "start_time": "1 s"}]},
        },
    }


def _run_managed(apps, faults: dict, tmp, tag, timeout_s=None, retries=None):
    from shadow_tpu.core.config import load_config
    from shadow_tpu.procs.builder import build_process_driver

    data = tmp / f"data_{tag}"
    cfg = load_config(_pairs_cfg(apps, faults))
    driver = build_process_driver(cfg, data_root=data)
    if timeout_s is not None:
        driver.service_timeout_s = timeout_s
    if retries is not None:
        driver.ipc_timeout_retries = retries
    driver.run()
    outs = {
        str(p.relative_to(data)): p.read_bytes()
        for p in sorted(data.rglob("*.stdout"))
    }
    return driver, outs


@toolchain
def test_managed_kill_proc_quarantine_deterministic(apps, tmp_path):
    """Acceptance gate: kill one managed process mid-run under quarantine
    — two runs are byte-identical, and the UNAFFECTED pair's outputs match
    a fault-free run exactly."""
    faults = {
        "on_proc_failure": "quarantine",
        "inject": [{"at": "3 s", "op": "kill_proc", "proc": "clientb.0"}],
    }
    d1, o1 = _run_managed(apps, faults, tmp_path, "a")
    d2, o2 = _run_managed(apps, faults, tmp_path, "b")
    assert o1 == o2
    assert d1.counters == d2.counters
    assert d1.fault_counters == d2.fault_counters
    assert d1.fault_counters["hosts_quarantined"] == 1
    # non-faulted processes all succeeded; faulted ones excluded
    for p in d1.procs:
        if not p.faulted:
            assert p.exit_code in (0, None), (p.name, p.exit_code)
    # unaffected pair matches the fault-free run byte for byte
    _, o_ref = _run_managed(apps, {}, tmp_path, "ref")
    for k in o_ref:
        if "hosts/servera" in k or "hosts/clienta" in k:
            assert o1[k] == o_ref[k], k


@toolchain
def test_managed_wedge_recovery_quarantine(apps, tmp_path):
    """SIGSTOP-wedged process: the escalation ladder (retry with backoff,
    then policy) quarantines the host and the run completes."""
    faults = {
        "on_proc_failure": "quarantine",
        "inject": [{"at": "3 s", "op": "wedge_proc", "proc": "clientb.0"}],
    }
    d, _ = _run_managed(apps, faults, tmp_path, "wedge",
                        timeout_s=0.4, retries=1)
    assert d.fault_counters["procs_wedged"] == 1
    assert d.fault_counters["ipc_retries"] >= 1
    assert d.fault_counters["hosts_quarantined"] == 1
    assert d.hosts[[h.name for h in d.hosts].index("clientb")].dead


@toolchain
def test_managed_refuse_ipc_recovery(apps, tmp_path):
    """A dropped IPC reply wedges the shim exactly like a lost message;
    the same ladder detects it and quarantine keeps the run alive."""
    faults = {
        "on_proc_failure": "quarantine",
        "inject": [{"at": "2 s", "op": "refuse_ipc", "proc": "clientb.0"}],
    }
    d, _ = _run_managed(apps, faults, tmp_path, "refuse",
                        timeout_s=0.4, retries=1)
    assert d.fault_counters["ipc_replies_refused"] == 1
    assert d.fault_counters["hosts_quarantined"] == 1


@toolchain
def test_managed_wedge_abort_policy_raises(apps, tmp_path):
    """Default policy: a wedged process still aborts the run loudly."""
    from shadow_tpu.procs.driver import ProcWedged

    faults = {
        "on_proc_failure": "abort",
        "inject": [{"at": "3 s", "op": "wedge_proc", "proc": "clientb.0"}],
    }
    with pytest.raises(ProcWedged):
        _run_managed(apps, faults, tmp_path, "abort",
                     timeout_s=0.4, retries=0)
