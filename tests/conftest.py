"""Test fixtures: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; the sharding layer is
validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun_multichip does. The environment's axon site hook pre-registers the
TPU platform and pins JAX_PLATFORMS=axon, so we must override both the env
var AND the jax config value before any backend initializes.
"""

import os

from shadow_tpu.parallel.virtualize import force_cpu_devices

jax = force_cpu_devices(
    8, cache_dir=os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
)

import pathlib  # noqa: E402
import shutil  # noqa: E402
import subprocess  # noqa: E402

import pytest  # noqa: E402

APPS_SRC = pathlib.Path(__file__).parent / "apps"


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def apps(tmp_path_factory):
    """Compile the tiny C workload programs once per session."""
    out = tmp_path_factory.mktemp("apps")
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        pytest.skip("no C compiler available")
    bins = {}
    for src in APPS_SRC.glob("*.c"):
        exe = out / src.stem
        subprocess.run(
            [cc, "-O1", "-o", str(exe), str(src)], check=True,
            capture_output=True,
        )
        bins[src.stem] = str(exe)
    return bins
