"""Test fixtures: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; the sharding layer is
validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun_multichip does. The environment's axon site hook pre-registers the
TPU platform and pins JAX_PLATFORMS=axon, so we must override both the env
var AND the jax config value before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's dominant cost is XLA compiles of
# the big window-step program (one per distinct sim shape, ~1-2 min each on
# CPU). Cache them on disk so repeat runs are seconds, not minutes.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pathlib  # noqa: E402
import shutil  # noqa: E402
import subprocess  # noqa: E402

import pytest  # noqa: E402

APPS_SRC = pathlib.Path(__file__).parent / "apps"


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def apps(tmp_path_factory):
    """Compile the tiny C workload programs once per session."""
    out = tmp_path_factory.mktemp("apps")
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        pytest.skip("no C compiler available")
    bins = {}
    for src in APPS_SRC.glob("*.c"):
        exe = out / src.stem
        subprocess.run(
            [cc, "-O1", "-o", str(exe), str(src)], check=True,
            capture_output=True,
        )
        bins[src.stem] = str(exe)
    return bins
