"""Test fixtures: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; the sharding layer is
validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun_multichip does. The environment's axon site hook pre-registers the
TPU platform and pins JAX_PLATFORMS=axon, so we must override both the env
var AND the jax config value before any backend initializes.
"""

import os

from shadow_tpu.parallel.virtualize import force_cpu_devices

jax = force_cpu_devices(
    8, cache_dir=os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
)

import pathlib  # noqa: E402
import shutil  # noqa: E402
import subprocess  # noqa: E402

import pytest  # noqa: E402

APPS_SRC = pathlib.Path(__file__).parent / "apps"

# Budgeted-run ordering: the full suite costs a multiple of the tier-1
# wall budget (XLA compiles dominate), so CI kills it mid-run — whatever
# sorts last never executes. Run the cheap, broad correctness surface
# first and the compile-heavy parity matrices last, so a timeout
# truncates the most expensive tail instead of the unit tests. Tiers are
# rough wall-cost buckets (measured warm-cache); unknown files default to
# mid-pack. Stable sort: in-file order (and fixture sharing) is preserved.
_BUDGET_TIER = {
    # ~0-15 s each: pure-host units + fast managed-plane gates
    "test_units": 0, "test_topology": 0, "test_config": 0,
    "test_wide_syscalls": 0, "test_seccomp": 0, "test_signals": 0,
    "test_multiproc": 0, "test_cli": 0, "test_procs_e2e": 0,
    # tens of seconds: single-engine device tiers
    "test_checkpoint": 1, "test_engine_phold": 1, "test_faults": 1,
    "test_observability": 2, "test_net_stack": 2, "test_bridge": 2,
    "test_sim_build": 3, "test_spill": 3, "test_optimistic": 3,
    "test_audit": 3, "test_resilience": 3, "test_analysis": 3,
    # the pressure chaos matrix is an acceptance gate: before the
    # compile-heavy parity matrices, like test_serve
    "test_pressure": 3,
    # the serve chaos choreography is an acceptance gate: it must land
    # BEFORE the compile-heavy parity matrices so a budget truncation
    # never silently skips it
    "test_serve": 3,
    # the async-sync chain-equality matrix is the ISSUE 10 acceptance
    # gate: same rule — ahead of the compile-heavy tier-4 matrices
    "test_async_sync": 3,
    # the self-balancing acceptance gate (ISSUE 11): same rule
    "test_balancer": 3,
    # the pipelined-handoff chain-equality matrix (ISSUE 15): same rule —
    # ahead of the compile-heavy tier-4 matrices
    "test_pipeline": 3,
    # the multi-worker host-plane chain-equality matrix (ISSUE 17):
    # same rule — ahead of the compile-heavy tier-4 matrices
    "test_hostplane": 3,
    # the per-interface scheduling-plane acceptance gate (ISSUE 19):
    # same rule — compat goldens + PIFO/Eiffel parity before the tail
    "test_qdisc": 3,
    # the profiling-plane acceptance gate (ISSUE 20): mostly pure-host
    # units plus one tiny islands run — cheap, keep it ahead of the tail
    "test_prof": 2,
    # the multi-chip mesh acceptance gate (ISSUE 12): same rule — its
    # shard_map cells compile more than the vmap tiers but the chain
    # matrix + relayout resume must land before the tier-4 tail
    "test_mesh": 3,
    # the elastic-resilience acceptance gate (ISSUE 13): same rule —
    # the kill_chip chaos matrix must land before the tier-4 tail
    "test_mesh_resilience": 3,
    # minutes: multi-engine parity matrices / many-shape compiles
    "test_gearbox": 4, "test_islands": 4, "test_rebalance": 4,
    "test_sharding": 4, "test_tcp": 4, "test_fleet": 4, "test_tgen": 5,
    # slow-marked e2e tiers (excluded from tier-1 anyway)
    "test_bridge_tcp": 6, "test_relay_e2e": 6,
}


def pytest_collection_modifyitems(session, config, items):
    items.sort(key=lambda it: _BUDGET_TIER.get(it.module.__name__, 3))


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def apps(tmp_path_factory):
    """Compile the tiny C workload programs once per session."""
    out = tmp_path_factory.mktemp("apps")
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        pytest.skip("no C compiler available")
    bins = {}
    for src in APPS_SRC.glob("*.c"):
        exe = out / src.stem
        # -lpthread must be explicit: this toolchain's libc does not fold
        # libpthread in, and a missing symbol here used to error out the
        # session fixture — killing EVERY managed-plane test at once
        subprocess.run(
            [cc, "-O1", "-o", str(exe), str(src), "-lpthread"], check=True,
            capture_output=True,
        )
        bins[src.stem] = str(exe)
    return bins
