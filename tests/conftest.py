"""Test fixtures: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; the sharding layer is
validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun_multichip does. The environment's axon site hook pre-registers the
TPU platform and pins JAX_PLATFORMS=axon, so we must override both the env
var AND the jax config value before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
