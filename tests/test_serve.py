"""Sim-as-a-service (shadow_tpu/serve): crash-safe daemon + journal + AOT
kernel cache.

The load-bearing guarantee is that DAEMON DEATH IS A NON-EVENT: a sweep
accepted by the daemon finishes — across SIGTERM drains and SIGKILL +
journal-replay restarts — with per-job audit digest chains bit-identical
(and identically ordered) to the same sweep run as one uninterrupted
in-process fleet, and a warm restart re-binds every fleet kernel from
the AOT cache with zero Python traces. Plus the admission plane: tenant
quotas and queue-depth backpressure shed with HTTP 429 + Retry-After,
and /healthz reports the supervisor probe, queue depth, and journal lag.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from shadow_tpu.serve import journal as journal_mod
from shadow_tpu.serve.client import ServeClient, ServeClientError, Shed
from shadow_tpu.serve.kcache import KernelCache, kernel_config_digest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GML = """\
graph [
  node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def _sweep_doc(jobs=6, lanes=2):
    return {
        "sweep": {
            "name": "serve-t",
            "lanes": lanes,
            "matrix": {
                "general.seed": list(range(11, 11 + jobs // 2)),
                "general.stop_time": ["900 ms", "1.4 s"],
            },
        },
        "general": {"stop_time": "1 s", "seed": 1},
        "network": {"graph": {"type": "gml", "inline": GML}},
        "experimental": {
            "event_capacity": 1024,
            "events_per_host_per_window": 8,
            "outbox_slots": 8,
            "inbox_slots": 4,
        },
        "fleet": {"windows_per_dispatch": 2},
        "hosts": {
            "peer": {
                "quantity": 8,
                "app_model": "phold",
                "app_options": {
                    "msgload": 2, "runtime": 2, "start_time": "100 ms",
                },
            }
        },
    }


# ---------------------------------------------------------------------------
# journal: framing, torn tails, replay folding
# ---------------------------------------------------------------------------


def test_journal_roundtrip_lag_and_state(tmp_path):
    path = str(tmp_path / "j.wal")
    j = journal_mod.Journal(path)
    j.append(journal_mod.SUBMIT, id="s0", tenant="t", doc={"x": 1})
    j.append(journal_mod.ADMIT, id="s0", ckpt_dir="/d")
    j.append(journal_mod.SUBMIT, id="s1", tenant="t", doc={"x": 2})
    assert j.lag() == 3  # no COMPLETE yet
    j.append(journal_mod.COMPLETE, id="s0", ok=True,
             results=[{"name": "a"}])
    assert j.lag() == 0
    j.close()

    # a fresh handle replays the same truth
    j2 = journal_mod.Journal(path)
    assert not j2.torn_tail_dropped
    st = j2.state()
    assert [s["id"] for s in st.completed()] == ["s0"]
    assert st.sweeps["s0"]["results"] == [{"name": "a"}]
    assert [s["id"] for s in st.unfinished()] == ["s1"]
    # seq numbering continues across restarts
    rec = j2.append(journal_mod.ADMIT, id="s1", ckpt_dir="/d2")
    assert rec["seq"] == 4
    j2.close()


def test_journal_torn_tail_and_corrupt_frame(tmp_path):
    path = str(tmp_path / "j.wal")
    j = journal_mod.Journal(path)
    j.append(journal_mod.SUBMIT, id="s0", tenant="t", doc={})
    j.append(journal_mod.SUBMIT, id="s1", tenant="t", doc={})
    j.close()
    blob = open(path, "rb").read()

    # SIGKILL mid-append: arbitrary truncation inside the last frame
    torn = str(tmp_path / "torn.wal")
    open(torn, "wb").write(blob[:-3])
    scan = journal_mod.scan(torn)
    assert [r["id"] for r in scan["records"]] == ["s0"]
    assert scan["truncated_at"] is not None
    # reopening drops the torn tail and appends cleanly after it
    j3 = journal_mod.Journal(torn)
    assert j3.torn_tail_dropped
    j3.append(journal_mod.SUBMIT, id="s2", tenant="t", doc={})
    j3.close()
    st = journal_mod.Journal(torn).state()
    assert [s["id"] for s in st.unfinished()] == ["s0", "s2"]

    # a flipped byte inside the last record fails its CRC
    flip = str(tmp_path / "flip.wal")
    open(flip, "wb").write(blob[:-5] + bytes([blob[-5] ^ 0xFF]) + blob[-4:])
    scan = journal_mod.scan(flip)
    assert [r["id"] for r in scan["records"]] == ["s0"]
    assert scan["truncated_at"] is not None

    # zero-length journal = empty, not an error
    empty = str(tmp_path / "empty.wal")
    open(empty, "wb").close()
    assert journal_mod.scan(empty) == {"records": [], "truncated_at": None}


def test_journal_lag_across_replay_boundary(tmp_path):
    """`lag()` is the /healthz journal-lag gauge: records since the last
    COMPLETE. It must stay truthful ACROSS a replay boundary — a
    SIGKILL-torn tail is dropped exactly once (the reopening handle
    reports `torn_tail_dropped`), the replayed records keep counting
    toward lag, and a subsequent clean reopen reports no tear."""
    path = str(tmp_path / "j.wal")
    j = journal_mod.Journal(path)
    j.append(journal_mod.SUBMIT, id="s0", tenant="t", doc={"x": 1})
    j.append(journal_mod.ADMIT, id="s0", ckpt_dir="/d")
    j.append(journal_mod.COMPLETE, id="s0", ok=True, results=[])
    j.append(journal_mod.SUBMIT, id="s1", tenant="t", doc={"x": 2})
    assert j.lag() == 1
    j.append(journal_mod.ADMIT, id="s1", ckpt_dir="/d1")
    assert j.lag() == 2
    j.close()

    # SIGKILL mid-append: the ADMIT frame is torn. The restarted
    # incarnation drops it and lag resets to the surviving records
    # (the SUBMIT after the last COMPLETE), not the pre-crash count.
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-4])
    j2 = journal_mod.Journal(path)
    assert j2.torn_tail_dropped  # reported exactly once, by this handle
    assert [r["type"] for r in j2.records][-1] == journal_mod.SUBMIT
    assert j2.lag() == 1
    # appends continue cleanly after the truncated tail; lag tracks them
    j2.append(journal_mod.ADMIT, id="s1", ckpt_dir="/d1")
    assert j2.lag() == 2
    j2.append(journal_mod.COMPLETE, id="s1", ok=True, results=[])
    assert j2.lag() == 0
    j2.close()

    # a clean reopen reports NO tear (the flag means "this incarnation
    # dropped bytes", not "a tear ever happened")
    j3 = journal_mod.Journal(path)
    assert not j3.torn_tail_dropped
    assert j3.lag() == 0
    assert [s["id"] for s in j3.state().completed()] == ["s0", "s1"]
    j3.close()


def test_retry_after_zero_when_idle(tmp_path):
    """Regression: an idle daemon must hint `retry_after_s == 0` — the
    federation router's placement score treats the hint as queue wait,
    so a floor of 1s made every idle peer look busy and fed the EWMA
    sweep wall into placements that should have been free."""
    from shadow_tpu.serve.daemon import ServeOptions, ShadowDaemon

    daemon = ShadowDaemon(ServeOptions(
        state_dir=str(tmp_path / "state"),
        cache_dir=str(tmp_path / "cache"),
    ))
    daemon._avg_sweep_wall_s = 120.0  # a busy past must not leak
    assert daemon.retry_after_s() == 0
    assert daemon.health()["retry_after_s"] == 0
    # with work queued the hint scales with depth x EWMA again
    out = daemon.submit(_sweep_doc(jobs=2, lanes=1))
    assert "id" in out
    assert daemon.retry_after_s() >= 1
    assert daemon.health()["retry_after_s"] >= 1
    daemon.journal.close()


# ---------------------------------------------------------------------------
# kernel cache: roundtrip, corruption eviction, version skew, digest keys
# ---------------------------------------------------------------------------


def test_kcache_roundtrip_corruption_and_skew(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    kc = KernelCache(str(tmp_path))

    def fn(x):
        return x * 2 + 1

    x = jnp.arange(8, dtype=jnp.int64)
    key = kc.key("cfg", "t", (x,))
    assert kc.get(key) is None  # cold miss
    ex = kc.export_and_put(key, fn, (x,))
    assert np.array_equal(np.asarray(ex.call(x)), np.asarray(fn(x)))
    assert kc.stats()["puts"] == 1 and kc.stats()["entries"] == 1

    # hit from a fresh handle, bit-identical result
    kc2 = KernelCache(str(tmp_path))
    ex2 = kc2.get(key)
    assert ex2 is not None
    assert np.array_equal(np.asarray(jax.jit(ex2.call)(x)),
                          np.asarray(fn(x)))

    # corrupt payload: evicted, reported as a miss, never trusted
    bin_path, hdr_path = kc2._paths(key)
    open(bin_path, "wb").write(b"garbage")
    kc3 = KernelCache(str(tmp_path))
    assert kc3.get(key) is None
    assert kc3.stats()["evictions"] == 1
    assert not os.path.exists(bin_path)

    # version skew: a header written by another jaxlib is evicted too
    key2 = kc3.key("cfg", "t2", (x,))
    kc3.export_and_put(key2, fn, (x,))
    _, hdr2 = kc3._paths(key2)
    hdr = json.load(open(hdr2))
    hdr["jaxlib"] = "0.0.0"
    json.dump(hdr, open(hdr2, "w"))
    kc4 = KernelCache(str(tmp_path))
    assert kc4.get(key2) is None
    assert kc4.stats()["evictions"] == 1

    # distinct avals → distinct keys (a hit is always arg-compatible)
    assert kc.key("cfg", "t", (x,)) != kc.key(
        "cfg", "t", (jnp.arange(9, dtype=jnp.int64),)
    )

    # the kernel-source fingerprint is part of the key: a code upgrade
    # is a cache miss, never a stale-kernel replay
    from shadow_tpu.serve import kcache as kcache_mod

    k_before = kc.key("cfg", "t", (x,))
    old_fp = kcache_mod.kernel_source_fingerprint()
    assert len(old_fp) == 64
    try:
        kcache_mod._SRC_FINGERPRINT = "f" * 64
        assert kc.key("cfg", "t", (x,)) != k_before
    finally:
        kcache_mod._SRC_FINGERPRINT = old_fp
    assert kc.key("cfg", "t", (x,)) == k_before


def test_kernel_config_digest_ignores_data_plane():
    a = _sweep_doc()
    b = _sweep_doc()
    b["general"]["seed"] = 999
    b["general"]["stop_time"] = "9 s"
    assert kernel_config_digest(a) == kernel_config_digest(b)
    c = _sweep_doc()
    c["experimental"]["event_capacity"] = 2048  # kernel-shaping
    assert kernel_config_digest(a) != kernel_config_digest(c)


def test_serve_modules_classified_host():
    """serve/ is daemon-plane host code: the kernel purity rule set must
    not apply to it (and shadowlint keeps the tree clean with zero
    baseline entries — bench.py --lint-smoke gates that)."""
    from shadow_tpu.analysis.linter import classify_module

    for mod in ("daemon", "journal", "kcache", "client", "cli"):
        assert classify_module(f"shadow_tpu/serve/{mod}.py") == "host"


def test_sweep_corrupt_entries_evicts_zero_length(tmp_path):
    from shadow_tpu.serve.kcache import sweep_corrupt_entries

    root = tmp_path / "cache"
    (root / "aot").mkdir(parents=True)
    (root / "ok.bin").write_bytes(b"fine")
    (root / "torn.bin").write_bytes(b"")
    (root / "aot" / "k-dead.bin").write_bytes(b"")
    assert sweep_corrupt_entries(str(root)) == 2
    assert (root / "ok.bin").exists()
    assert not (root / "torn.bin").exists()


# ---------------------------------------------------------------------------
# the daemon: chaos choreography + admission plane
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_env(tmp_path_factory):
    """Module-shared cache dir: every daemon the module spawns warms the
    same XLA + AOT caches, so only the first pays the fleet compile."""
    cache = tmp_path_factory.mktemp("serve_cache")
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SHADOW_TPU_CACHE_DIR": str(cache),
    }


def _spawn(state_dir: str, env: dict, *extra: str):
    proc = subprocess.Popen(
        [sys.executable, "-m", "shadow_tpu", "serve",
         "--state-dir", state_dir, "--checkpoint-every-dispatches", "1",
         *extra],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    client = ServeClient(os.path.join(state_dir, "serve.sock"), timeout=20)
    deadline = time.monotonic() + 120
    while True:
        if proc.poll() is not None:
            raise AssertionError(
                f"daemon died at startup:\n{proc.stdout.read()}"
            )
        try:
            client.health()
            return proc, client
        except ServeClientError:
            if time.monotonic() >= deadline:
                proc.kill()
                raise
            time.sleep(0.1)


def _wait_progress(client, sid, jobs_done: int, timeout_s: float = 240.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        info = client.sweep(sid)
        if info["status"] in ("done", "failed"):
            return info
        progress = info.get("progress") or {}
        if progress.get("jobs_done", 0) >= jobs_done:
            return info
        time.sleep(0.1)
    raise AssertionError(f"sweep {sid} made no progress in {timeout_s}s")


@pytest.fixture(scope="module")
def ref_rows():
    """The uninterrupted bar: the same sweep as ONE in-process fleet."""
    from shadow_tpu.fleet import build_fleet, load_sweep

    jobs, _ = load_sweep(_sweep_doc())
    fleet = build_fleet(jobs, lanes=2, windows_per_dispatch=2)
    fleet.run()
    return fleet.results()


def test_daemon_chaos_sigterm_drain_then_sigkill_replay(
    tmp_path, serve_env, ref_rows
):
    """The acceptance choreography, both deaths in one sweep's life:
    SIGTERM mid-sweep (graceful drain to checkpoint, journal DRAIN, rc
    0) → restart resumes → SIGKILL mid-sweep (no goodbye) → restart
    replays the journal and finishes. The final results must equal the
    uninterrupted run's rows CHAIN FOR CHAIN in submission order, and
    the post-SIGKILL incarnation must bind every fleet kernel from the
    AOT cache with zero Python traces."""
    state = str(tmp_path / "state")

    # incarnation 1: accept, make some progress, SIGTERM → graceful drain
    proc, client = _spawn(state, serve_env)
    sid = client.submit(_sweep_doc())["id"]
    info = _wait_progress(client, sid, 1)
    assert info["status"] != "failed"
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=120) == 0  # drained exit is clean
    recs = journal_mod.scan(os.path.join(state, "journal.wal"))["records"]
    types = [r["type"] for r in recs]
    assert types[:2] == [journal_mod.SUBMIT, journal_mod.ADMIT]
    if info["status"] != "done":
        assert journal_mod.DRAIN in types

    # incarnation 2: resumes the drained sweep; SIGKILL it mid-run
    proc, client = _spawn(state, serve_env)
    info = _wait_progress(client, sid, 3)
    proc.kill()
    proc.wait(timeout=60)

    # incarnation 3: journal replay finishes the sweep
    proc, client = _spawn(state, serve_env)
    health = client.health()
    assert health["journal"]["records"] >= 3
    info = client.wait(sid, timeout_s=420)
    assert info["status"] == "done"
    rows = info["results"]
    assert [r["name"] for r in rows] == [r["name"] for r in ref_rows]
    assert [r["audit"]["chain"] for r in rows] == \
        [r["audit"]["chain"] for r in ref_rows]
    assert [r["events_committed"] for r in rows] == \
        [r["events_committed"] for r in ref_rows]
    # zero window-kernel recompiles for fleet shapes already in the AOT
    # cache (the kernel_traces-gated property)
    assert info["stats"]["kernel_traces"] == 0

    # schema-v8 serve.* metrics document
    from shadow_tpu.obs import metrics as obs_metrics

    doc = client.metrics()
    obs_metrics.validate_metrics_doc(doc)
    assert doc["counters"]["serve.journal_replays"] == 1
    assert doc["counters"]["serve.kcache_hits"] >= 1

    client.drain()
    assert proc.wait(timeout=120) == 0


def test_daemon_admission_quota_shed_and_health(
    tmp_path, serve_env, ref_rows
):
    """Admission backpressure: per-tenant quotas and queue depth shed
    with HTTP 429 + a Retry-After derived from scheduler occupancy; a
    malformed sweep document is a 400 naming the problem; /healthz
    reports the shared supervisor probe and journal lag."""
    state = str(tmp_path / "state")
    proc, client = _spawn(
        state, serve_env,
        "--max-queue", "2", "--quota", "capped=0",
    )
    try:
        health = client.health()
        assert health["ok"] and health["backend"]["probe_ok"]
        assert health["backend"]["platform"] == "cpu"
        assert health["journal"] == {
            "records": 0, "lag": 0, "torn_tail_dropped": False,
        }

        # a zero-quota tenant is shed before any validation work
        with pytest.raises(Shed) as e:
            client.submit(_sweep_doc(), tenant="capped")
        assert e.value.body["shed"] == "tenant_quota"
        assert e.value.retry_after_s >= 1

        # malformed documents are a 400, never a queued time bomb
        # (checked while the queue is empty: shed outranks validation)
        with pytest.raises(ServeClientError, match="sweep"):
            client.submit({"general": {"stop_time": "1 s"}})

        # fill the queue to max depth, then shed on depth
        a = client.submit(_sweep_doc(), tenant="alice")
        b = client.submit(_sweep_doc(), tenant="bob")
        with pytest.raises(Shed) as e:
            client.submit(_sweep_doc(), tenant="carol")
        assert e.value.body["shed"] == "queue_full"

        # the accepted sweeps still finish correctly under all that
        info = client.wait(a["id"], timeout_s=420)
        assert info["status"] == "done"
        assert [r["audit"]["chain"] for r in info["results"]] == \
            [r["audit"]["chain"] for r in ref_rows]
        client.wait(b["id"], timeout_s=420)
        doc = client.metrics()
        assert doc["counters"]["serve.sheds"] == 2
        assert doc["counters"]["serve.sweeps_completed"] == 2
    finally:
        try:
            client.drain()
            proc.wait(timeout=120)
        except Exception:
            proc.kill()


# ---------------------------------------------------------------------------
# ISSUE 14 satellite: the drain-path lock discipline under contention
# ---------------------------------------------------------------------------


def test_concurrent_submit_and_drain_keep_journal_consistent(tmp_path):
    """Regression for the drain-path lock smell the STH004 race lint
    flags: `_drain()` used `self._lock.acquire(blocking=False)`, which
    silently skipped mutual exclusion whenever an HTTP thread held the
    lock. Restructured to a bounded blocking acquire, a storm of
    concurrent submits racing a SIGTERM-style drain must leave the
    journal and scheduler state consistent: every accepted id is
    journaled exactly once, ids are unique (the `_seq` counter never
    tore), post-drain submits shed `draining`, and a restarted daemon
    replays exactly the accepted-but-unfinished sweeps."""
    import threading

    from shadow_tpu.serve.daemon import ServeOptions, ShadowDaemon

    opts = ServeOptions(
        state_dir=str(tmp_path / "state"), max_queue_depth=10_000,
        default_quota=10_000, cache_dir=str(tmp_path / "cache"),
    )
    daemon = ShadowDaemon(opts)
    doc = _sweep_doc(jobs=2, lanes=1)
    accepted: list[str] = []
    shed = []
    errors = []
    acc_lock = threading.Lock()
    start = threading.Barrier(5)

    def submitter(tenant):
        start.wait()
        for _ in range(25):
            try:
                out = daemon.submit(json.loads(json.dumps(doc)),
                                    tenant=tenant)
            except Exception as e:  # noqa: BLE001 - the test must see it
                errors.append(e)
                return
            with acc_lock:
                if "shed" in out:
                    shed.append(out["shed"])
                else:
                    accepted.append(out["id"])

    def drainer():
        start.wait()
        time.sleep(0.02)
        daemon.drain()  # the SIGTERM handler body

    threads = [
        threading.Thread(target=submitter, args=(f"t{i}",))
        for i in range(4)
    ] + [threading.Thread(target=drainer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert daemon._draining.is_set()
    # post-drain shed arm actually engaged (the drain landed mid-storm)
    out = daemon.submit(json.loads(json.dumps(doc)))
    assert out.get("shed") == "draining"
    # ids unique and state consistent under the storm
    assert len(accepted) == len(set(accepted))
    assert all(s == "draining" for s in shed)
    assert set(accepted) <= set(daemon.sweeps)
    journaled = [
        r["id"] for r in daemon.journal.records
        if r["type"] == journal_mod.SUBMIT
    ]
    assert sorted(journaled) == sorted(accepted)
    daemon.journal.close()
    # a fresh incarnation replays exactly the accepted, unfinished work
    daemon2 = ShadowDaemon(ServeOptions(
        state_dir=str(tmp_path / "state"), cache_dir=str(tmp_path / "cache"),
    ))
    assert sorted(daemon2._queue) == sorted(accepted)
    assert daemon2.counters["journal_replays"] == (1 if accepted else 0)
    daemon2.journal.close()
