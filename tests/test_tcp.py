"""TCP stack tests: unit (seq arithmetic, scoreboard, RTT) and e2e bulk
transfers over lossless and lossy paths.

Mirrors the reference's tcp test matrix shape (src/test/tcp/: {blocking,...}
× {loopback, lossless, lossy}) at device-app level; the syscall-plane
variants land with the CPU interposition plane.
"""

import jax
import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.net import tcp as tcp_mod
from shadow_tpu.sim import build_simulation

MS = simtime.NS_PER_MS


def _gml(loss=0.0, latency="20 ms"):
    return f"""
graph [
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "{latency}" packet_loss {loss} ]
]
"""


def _bulk_cfg(total="200 KiB", loss=0.0, stop=20, seed=7, clients=1,
              bootstrap=None):
    hosts = {
        "server": {
            "network_node_id": 0,
            "app_model": "tcp_bulk",
            "app_options": {"role": "server"},
        }
    }
    for i in range(clients):
        hosts[f"client{i}"] = {
            "network_node_id": 1,
            "app_model": "tcp_bulk",
            "app_options": {"total": total},
        }
    general = {"stop_time": stop, "seed": seed}
    if bootstrap is not None:
        general["bootstrap_end_time"] = bootstrap
    return {
        "general": general,
        "network": {"graph": {"type": "gml", "inline": _gml(loss)}},
        "experimental": {
            # canonical small TCP shape (compile-cost policy, ROADMAP.md):
            # every _bulk_cfg variant shares (C, K) so XLA compiles the
            # TCP kernel once per HOST COUNT, and the pool is sized to the
            # ≤4-host in-flight population, not the 10k-host stages'
            "event_capacity": 4096,
            "events_per_host_per_window": 8,
        },
        "hosts": hosts,
    }


def _roles(sim):
    ci = [i for i, h in enumerate(sim.config.hosts)
          if h.name.startswith("client")]
    si = [i for i, h in enumerate(sim.config.hosts) if h.name == "server"][0]
    return ci, si


# ---------------------------------------------------------------------------
# unit tests
# ---------------------------------------------------------------------------


def test_seq_wraparound():
    a = jnp.asarray([0x7FFFFFF0, -5, 100], dtype=jnp.int32)
    b = jnp.asarray([-0x7FFFFFF0, 5, 50], dtype=jnp.int32)
    # a < b across the wrap point
    assert list(tcp_mod.seq_lt(a, b)) == [True, True, False]
    assert list(tcp_mod.seq_leq(a, a)) == [True, True, True]


def test_popcount_trailing_ones():
    x = jnp.asarray([0b0, 0b1, 0b1011, 0xFFFFFFFF], dtype=jnp.uint32)
    assert list(tcp_mod._popcount(x)) == [0, 1, 3, 32]
    assert list(tcp_mod._trailing_ones(x)) == [0, 1, 2, 32]


def test_demux_prefers_connection_over_listener():
    t = tcp_mod.init(2, 4)
    t = tcp_mod.listen_static(t, 0, 0, 80)
    # connected child on slot 1, peer = host 1 port 999
    t = t.replace(
        used=t.used.at[0, 1].set(True),
        local_port=t.local_port.at[0, 1].set(80),
        peer_host=t.peer_host.at[0, 1].set(1),
        peer_port=t.peer_port.at[0, 1].set(999),
        state=t.state.at[0, 1].set(tcp_mod.ESTABLISHED),
    )
    from shadow_tpu.net import packet as pkt

    payload = jnp.zeros((2, 12), jnp.int32)
    payload = payload.at[:, pkt.W_DST_PORT].set(80)
    payload = payload.at[:, pkt.W_SRC_PORT].set(999)
    src = jnp.asarray([1, 0], dtype=jnp.int32)
    mask = jnp.asarray([True, False])
    slot, found, is_listener = tcp_mod.demux(t, mask, payload, src)
    assert bool(found[0]) and int(slot[0]) == 1 and not bool(is_listener[0])


# ---------------------------------------------------------------------------
# e2e: lossless bulk transfer
# ---------------------------------------------------------------------------


def test_bulk_lossless():
    sim = build_simulation(_bulk_cfg())
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    sub = jax.device_get(sim.state.subs["tcp_bulk"])
    ci, si = _roles(sim)
    c = ci[0]
    assert int(sub["connected"][c]) == 1
    assert int(sub["accepted"][si]) == 1
    assert int(sub["eof_seen"][si]) == 1
    assert int(t.bytes_acked[c, 0]) == 200 * 1024
    assert int(t.bytes_received[si].sum()) == 200 * 1024
    assert int(t.retransmits) == 0
    assert int(t.timeouts) == 0
    # teardown: client reached TIME_WAIT; server child slot freed, listener
    # back to LISTEN only
    assert int(t.state[c, 0]) == tcp_mod.TIME_WAIT
    assert int(t.state[si, 0]) == tcp_mod.LISTEN
    assert not bool(t.used[si, 1])


def test_bulk_lossless_loopback():
    """Client and server on the same simulated host (loopback path)."""
    cfg = _bulk_cfg(total="100 KiB")
    # both hosts attach to vertex 0; traffic between them crosses the
    # 50ms... actually use distinct hosts but same vertex
    cfg["hosts"]["client0"]["network_node_id"] = 0
    sim = build_simulation(cfg)
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    ci, si = _roles(sim)
    assert int(t.bytes_acked[ci[0], 0]) == 100 * 1024
    assert int(t.bytes_received[si].sum()) == 100 * 1024


def test_bulk_multiple_clients():
    """3 clients → one server: child-socket demux under concurrency."""
    sim = build_simulation(_bulk_cfg(total="50 KiB", clients=3, stop=30))
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    sub = jax.device_get(sim.state.subs["tcp_bulk"])
    ci, si = _roles(sim)
    assert int(sub["accepted"][si]) == 3
    for c in ci:
        assert int(t.bytes_acked[c, 0]) == 50 * 1024, f"client {c}"
    assert int(t.bytes_received[si].sum()) == 3 * 50 * 1024
    assert int(sub["eof_seen"][si]) == 3


# ---------------------------------------------------------------------------
# e2e: lossy path — retransmission, Reno, recovery
# ---------------------------------------------------------------------------


def test_bulk_lossy_recovers():
    """2% loss: the transfer still completes exactly, via retransmits."""
    sim = build_simulation(
        _bulk_cfg(total="300 KiB", loss=0.02, stop=60, bootstrap=0)
    )
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    ci, si = _roles(sim)
    c = sim.counters()
    assert c["packets_dropped_loss"] > 0, "loss must actually occur"
    assert int(t.retransmits) > 0
    assert int(t.bytes_acked[ci[0], 0]) == 300 * 1024
    assert int(t.bytes_received[si].sum()) == 300 * 1024


def test_bulk_lossy_deterministic():
    a = build_simulation(_bulk_cfg(total="100 KiB", loss=0.05, stop=40,
                                   bootstrap=0))
    b = build_simulation(_bulk_cfg(total="100 KiB", loss=0.05, stop=40,
                                   bootstrap=0))
    a.run()
    b.run()
    assert a.counters() == b.counters()
    ta = jax.device_get(a.state.subs[tcp_mod.SUB])
    tb = jax.device_get(b.state.subs[tcp_mod.SUB])
    assert int(ta.retransmits) == int(tb.retransmits)
    assert ta.bytes_received.sum() == tb.bytes_received.sum()


def test_handshake_syn_loss_retries():
    """Drop-heavy path: SYN/SYN+ACK losses are retried by the RTO timer.

    With 30% loss the handshake may need several 1-2s retries; the transfer
    is tiny so the test bounds time via stop_time.
    """
    sim = build_simulation(
        _bulk_cfg(total="10 KiB", loss=0.30, stop=60, seed=3, bootstrap=0)
    )
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    ci, si = _roles(sim)
    assert int(t.bytes_acked[ci[0], 0]) == 10 * 1024
    assert int(t.bytes_received[si].sum()) == 10 * 1024
    assert int(t.timeouts) > 0 or int(t.retransmits) > 0


def test_sack_loss_recovery_not_timeout_bound():
    """SACK scoreboard gate (VERDICT r1 #8; reference
    tcp_retransmit_tally.cc): on a lossy path, holes are repaired by
    SACK-guided fast retransmissions — retransmit count stays in the
    vicinity of the loss count, and RTO timeouts stay rare instead of
    pacing the transfer."""
    # clients=2 is part of the tuned workload: at 3 clients the shared
    # bottleneck congests enough that spurious retransmits blur the
    # SACK-efficiency bound this gate exists to enforce (tried for the
    # compile-shape merge; not worth weakening the gate)
    sim = build_simulation(_bulk_cfg(total="300 KiB", loss=0.02, stop=30,
                                     clients=2, bootstrap=0))
    sim.run_stepwise()
    ci, si = _roles(sim)
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    for c in ci:
        assert int(t.bytes_acked[c, 0]) == 300 * 1024, \
            "transfer did not complete"
    losses = sim.counters()["packets_dropped_loss"]
    rtx = int(t.retransmits)
    timeouts = int(t.timeouts)
    assert losses > 0
    assert rtx >= losses * 0.5  # holes actually repaired via retransmits
    # the SACK gate: recovery is driven by fast/SACK retransmits, not RTO
    # expiries pacing the transfer
    assert timeouts <= max(2, losses // 4), (timeouts, losses, rtx)
    # Bounded spray: SACK measurably reduces retransmissions (117 vs 159
    # for this exact config with the bitmap zeroed), but recovery-cascade
    # retransmission after an RTO still inflates the count well above the
    # raw loss count — tightening that accounting is tracked work, and
    # this bound regresses if it worsens.
    assert rtx <= losses * 12 + 20, (timeouts, losses, rtx)


def test_lossy_rtx_bounded():
    """VERDICT r2 #6 gate: retransmissions stay <= 2x actual losses at 2%
    loss (round 2 was ~10x: RTO rewinds re-sent already-ACKed and
    already-SACKed data). The fixes: snd_nxt >= snd_una invariant on ACK
    advance, SACK board survives RTO, pump skips sacked chunks. Per-cause
    counters (rtx_fast/rtx_sack/rtx_walk) split the remainder."""
    sim = build_simulation(
        _bulk_cfg(total="120 KiB", loss=0.02, stop=40, bootstrap=0)
    )
    sim.run()
    c = sim.counters()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    losses = c["packets_dropped_loss"]
    rtx = int(t.retransmits)
    assert losses > 0, "loss must actually occur"
    assert rtx <= 2 * losses, (rtx, losses)
    # per-cause split covers the total
    assert int(t.rtx_fast) + int(t.rtx_sack) + int(t.rtx_walk) \
        + int(t.timeouts) >= rtx - 2, t
    # the transfer still completes exactly
    assert int(t.bytes_acked.sum()) == 120 * 1024


def test_tcp_packet_trails():
    """packet_trails covers TCP stacks: a delivered segment's breadcrumb
    chain starts at CREATED and ends at DELIVERED (packet.c PDS_* analog
    for the TCP path)."""
    from shadow_tpu.net import packet as pkt
    from shadow_tpu.net import pds as pds_mod

    cfg = _bulk_cfg(total="24 KiB", loss=0.0, stop=15)
    cfg["experimental"]["packet_trails"] = True
    sim = build_simulation(cfg)
    sim.run()
    t = jax.device_get(sim.state.subs[tcp_mod.SUB])
    assert int(t.bytes_acked.sum()) == 24 * 1024  # transfer unaffected
    p = jax.device_get(sim.state.subs[pds_mod.SUB])
    trails = [pkt.decode_trail(int(w)) for w in p["deliver_trail"]]
    got = [tr for tr in trails if tr]
    assert got, "deliveries must record trails"
    for tr in got:
        assert tr[0] == "CREATED" and tr[-1] == "DELIVERED", tr
