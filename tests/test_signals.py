"""Virtual signal plane + AF_UNIX sockets (VERDICT r2 ask #4).

Reference analogs: syscall/signal.c (rt_sigaction / rt_sigprocmask / kill
emulation, SIGCHLD on child exit), descriptor/channel.c and unix sockets,
src/test/signal. Delivery is deterministic: handlers run at syscall
boundaries (piggybacked on the reply), parked interruptible syscalls
return EINTR, and dispositions/masks live in the driver.
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)


def _yaml(path, args=""):
    arg_line = f"\n        args: {args}" if args else ""
    return f"""
general:
  stop_time: 30 s
  seed: 5
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
hosts:
  solo:
    processes:
      - path: {path}{arg_line}
        start_time: 1 s
"""


def test_sigchld_socketpair_unix_event_loop(apps):
    """The libevent shape: SIGCHLD handler + self-pipe socketpair + named
    AF_UNIX listener + epoll event loop + waitpid reaping — all
    deterministic under the virtual clock."""
    def run_once():
        d = build_process_driver(_yaml(apps["sigpair"]))
        d.run()
        p = d.procs[0]
        assert p.exit_code == 0, (p.stdout, p.stderr)
        return p.stdout

    out = run_once()
    lines = out.decode().splitlines()
    assert lines == [
        "got: hello-unix",
        "reaped: pid-match=1 status=7",
        "done",
    ], lines
    # byte-identical rerun (determinism gate)
    assert run_once() == out
