"""Virtual signal plane + AF_UNIX sockets (VERDICT r2 ask #4).

Reference analogs: syscall/signal.c (rt_sigaction / rt_sigprocmask / kill
emulation, SIGCHLD on child exit), descriptor/channel.c and unix sockets,
src/test/signal. Delivery is deterministic: handlers run at syscall
boundaries (piggybacked on the reply), parked interruptible syscalls
return EINTR, and dispositions/masks live in the driver.
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)


def _yaml(path, args=""):
    arg_line = f"\n        args: {args}" if args else ""
    return f"""
general:
  stop_time: 30 s
  seed: 5
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
hosts:
  solo:
    processes:
      - path: {path}{arg_line}
        start_time: 1 s
"""


def test_sigchld_socketpair_unix_event_loop(apps):
    """The libevent shape: SIGCHLD handler + self-pipe socketpair + named
    AF_UNIX listener + epoll event loop + waitpid reaping — all
    deterministic under the virtual clock."""
    def run_once():
        d = build_process_driver(_yaml(apps["sigpair"]))
        d.run()
        p = d.procs[0]
        assert p.exit_code == 0, (p.stdout, p.stderr)
        return p.stdout

    out = run_once()
    lines = out.decode().splitlines()
    assert lines == [
        "got: hello-unix",
        "reaped: pid-match=1 status=7",
        "done",
    ], lines
    # byte-identical rerun (determinism gate)
    assert run_once() == out


def test_handler_no_reentry(apps):
    """Delivery auto-blocks the signo for the handler's duration (Linux
    sigaction semantics): a handler that re-raises its own signal runs
    twice sequentially, never nested."""
    d = build_process_driver(_yaml(apps["sigsem"], "reenter"))
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    assert p.stdout.decode().strip() == "runs=2 maxdepth=1", p.stdout


def test_group_kill_stays_virtual(apps):
    """kill(0, SIGTERM) signals the fork lineage VIRTUALLY (the managed
    process shares the driver's real process group — a native escape
    would kill the test run): the parent's handler fires, the
    handler-less child dies by default disposition."""
    d = build_process_driver(_yaml(apps["sigsem"], "groupkill"))
    d.run()
    p = next(q for q in d.procs if q.parent is None)
    assert p.exit_code == 0, (p.stdout, p.stderr)
    lines = p.stdout.decode().splitlines()
    assert "parent-term" in lines, lines
    assert "child-signaled=1 sig=15 pid-match=1" in lines, lines


def test_pending_signal_delivers_under_current_disposition(apps):
    """A signal left pending while blocked, then reset to SIG_DFL and
    unblocked, applies the CURRENT (default, terminating) disposition
    instead of being dropped (POSIX delivery semantics)."""
    d = build_process_driver(_yaml(apps["sigsem"], "dflpending"))
    d.run()
    p = d.procs[0]
    out = p.stdout.decode()
    assert "about-to-unblock" in out, (p.stdout, p.stderr)
    assert "survived" not in out, p.stdout
    assert p.exit_code == 128 + 12, p.exit_code  # SIGUSR2 default kill
