"""Occupancy-adaptive pool gearing (core/gearbox.py): a geared run must be
semantically indistinguishable from a fixed-capacity run.

Capacity only bounds what fits, never the order: the pool is an unordered
bag re-sorted by the full event key every window, so compiling the window
kernel at a smaller capacity (and shifting between tiers at dispatch
boundaries) may change pacing — window passes, pool-headroom stalls, spill
episodes — but never WHAT commits. The parity gates here mirror
tests/test_spill.py's: the semantic counter set, app-visible state, and
host-state digests must match exactly; occupancy-paced counters
(outbox_stall_deferred, micro_steps, windows_run) legitimately vary with
pool geometry and are excluded for the same reason the spill tests exclude
them.

Also hosts the static-analysis guard for the engine's stated op ban: the
jitted window step must lower to no scatter ops and no serializing
(take_along_axis-shaped) gathers, and the low gear's sort rows must be at
most half the top gear's — the mechanism the gearing win comes from.
"""

import hashlib

import jax
import numpy as np
import pytest

from shadow_tpu.analysis import hlo_audit
from shadow_tpu.core import gearbox, simtime
from shadow_tpu.core import spill as spill_mod
from shadow_tpu.core.state import EventPool
from shadow_tpu.flagship import build_phold_flagship
from shadow_tpu.sim import build_simulation

# The semantic counter set (tests/test_spill.py _KEYS): what committed, not
# how the driver paced it.
SEMANTIC_KEYS = (
    "events_committed", "events_emitted", "packets_sent",
    "packets_delivered", "packets_dropped_loss", "bytes_sent",
    "bytes_delivered", "pool_overflow_dropped",
)


def _flood_cfg(gears, cap, shards=1):
    """The spill-suite flood ramp: ~40 packets in flight per client peaks
    around 1.1k live rows, then drains to ~0 after the 1 s runtime — a
    natural up-then-down occupancy cycle for the gearbox."""
    exp = {
        "event_capacity": cap, "events_per_host_per_window": 16,
        "outbox_slots": 8, "inbox_slots": 4, "router_queue_slots": 64,
        "pool_gears": gears,
    }
    if shards > 1:
        exp.update(num_shards=shards, exchange_slots=16)
    return {
        "general": {"stop_time": 3, "seed": 5},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]\n'
            '  edge [ source 0 target 0 latency "400 ms" packet_loss 0.001 ]\n'
            ']\n')}},
        "experimental": exp,
        "hosts": {
            "server": {"quantity": 4, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 28, "app_model": "udp_flood",
                       "app_options": {"interval": "10 ms", "size": 256,
                                       "runtime": 1}},
        },
    }


def _host_digest(sim) -> str:
    """Digest of every host-plane leaf (order-stable across runs of the
    same engine layout)."""
    h = jax.device_get(sim.state.host)
    m = hashlib.sha256()
    for name in sorted(vars(h)):
        m.update(np.ascontiguousarray(np.asarray(getattr(h, name))).tobytes())
    return m.hexdigest()


def _live_pool_rows(sim) -> np.ndarray:
    """The pool's live rows as a capacity-independent sorted array."""
    p = jax.device_get(sim.state.pool)
    t = np.asarray(p.time).reshape(-1)
    live = t != simtime.NEVER
    rows = np.stack([
        t[live],
        np.asarray(p.dst).reshape(-1)[live].astype(np.int64),
        np.asarray(p.src).reshape(-1)[live].astype(np.int64),
        np.asarray(p.seq).reshape(-1)[live].astype(np.int64),
        np.asarray(p.kind).reshape(-1)[live].astype(np.int64),
    ], axis=-1)
    return rows[np.lexsort(rows.T[::-1])]


def _assert_parity(fixed, geared):
    cf, cg = fixed.counters(), geared.counters()
    for k in SEMANTIC_KEYS:
        assert cf[k] == cg[k], f"{k}: fixed {cf[k]} != geared {cg[k]}"
    assert cg["pool_overflow_dropped"] == 0
    assert _host_digest(fixed) == _host_digest(geared)
    assert np.array_equal(_live_pool_rows(fixed), _live_pool_rows(geared))
    sf, sg = fixed.obs_snapshot(), geared.obs_snapshot()
    assert np.array_equal(sf["host_events"], sg["host_events"])
    assert np.array_equal(sf["host_last_t"], sg["host_last_t"])
    sub_f = fixed.state.subs.get("udp_flood")
    if sub_f is not None:
        rf = np.asarray(jax.device_get(sub_f["recv"])).reshape(-1)
        rg = np.asarray(
            jax.device_get(geared.state.subs["udp_flood"]["recv"])
        ).reshape(-1)
        assert np.array_equal(rf, rg)


# ---------------------------------------------------------------------------
# gearbox unit gates
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_ladder_top_tier_is_exact_configured_shape():
    ladder = gearbox.build_ladder(3, 4096, 16, 64, spill_mod.marks)
    assert ladder[-1].capacity == 4096 and ladder[-1].K == 16
    assert (ladder[-1].hi, ladder[-1].fill) == spill_mod.marks(4096)
    caps = [s.capacity for s in ladder]
    assert caps == sorted(caps) and len(set(caps)) == len(caps)
    for s in ladder:
        assert s.up < s.hi, "upshift mark must sit below the red zone"
        assert s.K >= gearbox.MIN_K
    one = gearbox.build_ladder(1, 4096, 16, 64, spill_mod.marks)
    assert len(one) == 1 and one[0].capacity == 4096 and one[0].K == 16


@pytest.mark.quick
def test_shifter_hysteresis():
    ladder = gearbox.build_ladder(3, 4096, 16, 64, spill_mod.marks)
    sh = gearbox.GearShifter(ladder, down_after=3)
    # upshift is immediate once occupancy reaches the current up mark
    assert sh.observe(0, ladder[0].up) is not None
    # red-zone pressure demands at least one level up even at low occupancy
    assert sh.observe(0, 0, press=True) == 1
    assert sh.observe(2, 0, press=True) is None or True  # top gear: no up
    sh.reset()
    # downshift needs down_after consecutive low observations, one level
    assert sh.observe(2, 1) is None
    assert sh.observe(2, 1) is None
    assert sh.observe(2, 1) == 1
    sh.reset()
    # an in-band observation resets the streak
    assert sh.observe(2, 1) is None
    assert sh.observe(2, ladder[1].up) is None  # needs gear 2: streak resets
    assert sh.observe(2, 1) is None
    assert sh.observe(2, 1) is None


@pytest.mark.quick
def test_resize_pool_grow_shrink_roundtrip():
    rng = np.random.default_rng(7)
    C, P = 64, 2
    pool = EventPool.empty(C, P * 2)
    n = 40
    t = np.sort(rng.integers(1, 1 << 40, n))
    pool = pool.replace(
        time=pool.time.at[:n].set(t),
        dst=pool.dst.at[:n].set(rng.integers(0, 8, n)),
        src=pool.src.at[:n].set(rng.integers(0, 8, n)),
        seq=pool.seq.at[:n].set(np.arange(n)),
        kind=pool.kind.at[:n].set(rng.integers(0, 4, n)),
    )
    big, dropped = gearbox.resize_pool(pool, 128)
    assert big.capacity == 128 and int(dropped) == 0
    back, dropped = gearbox.resize_pool(big, 64)
    assert back.capacity == 64 and int(dropped) == 0
    assert set(np.asarray(back.time[np.asarray(back.time) != simtime.NEVER])
               .tolist()) == set(t.tolist())
    # shrinking below occupancy keeps the EARLIEST rows and counts the rest
    tight, dropped = gearbox.resize_pool(pool, 32)
    assert int(dropped) == n - 32
    kept = np.asarray(tight.time)
    assert np.array_equal(np.sort(kept[kept != simtime.NEVER]), t[:32])


def test_resize_pool_batched_layouts():
    """ISSUE 10 regression: on the host-side BATCHED layouts ([S, C]
    islands shards, [L, C] fleet lanes) the capacity axis is the LAST
    one. The old code read EventPool.capacity (shape[0] — the kernel's
    per-shard contract), compared the target against S/L, and so every
    islands/fleet gear shift inflated the pool instead of resizing it —
    bit-exact but sort-volume-bloating, and a forced kernel re-lowering
    per shift (caught by the async per-shard-gear retrace test)."""
    import jax.numpy as jnp

    S, C = 2, 64
    pool = EventPool(
        time=jnp.full((S, C), simtime.NEVER, jnp.int64),
        dst=jnp.zeros((S, C), jnp.int32), src=jnp.zeros((S, C), jnp.int32),
        seq=jnp.zeros((S, C), jnp.int32), kind=jnp.zeros((S, C), jnp.int32),
        payload=jnp.zeros((S, C, 1), jnp.int64),
    )
    pool = pool.replace(time=pool.time.at[:, :8].set(
        jnp.arange(1, S * 8 + 1, dtype=jnp.int64).reshape(S, 8)
    ))
    big, dropped = gearbox.resize_pool(pool, 128)
    assert big.time.shape == (S, 128)
    assert np.asarray(dropped).tolist() == [0, 0]
    back, dropped = gearbox.resize_pool(big, 64)
    assert back.time.shape == (S, 64)
    assert np.asarray(dropped).tolist() == [0, 0]
    # shrink below per-shard occupancy: earliest kept, rest counted PER
    # leading dim
    tight, dropped = gearbox.resize_pool(pool, 4)
    assert tight.time.shape == (S, 4)
    assert np.asarray(dropped).tolist() == [4, 4]


# ---------------------------------------------------------------------------
# gearing parity: geared == fixed, both sync modes, both engines
# ---------------------------------------------------------------------------


def test_gearing_parity_and_shift_cycle_across_red_zone():
    """The flood ramp against a pool whose TOP gear is itself undersized:
    the gearbox must climb the full ladder on the way up (crossing each
    tier's red zone — the fused driver's press early-exit is the upshift
    trigger), hand off to the spill tier at the top, and shift back down
    as the flood drains — committing exactly what the fixed-capacity run
    commits."""
    fixed = build_simulation(_flood_cfg(1, 1024))
    fixed.run()
    assert fixed.spill_stats()["spill_episodes"] > 0

    geared = build_simulation(_flood_cfg(3, 1024))
    geared.run()
    g = geared.gear_stats()
    assert g["gear_tiers"] == 3
    assert g["gear_shifts"] >= 2, f"expected an up+down cycle, got {g}"
    assert len(g["gear_dispatches"]) >= 2, f"one gear served all work: {g}"
    assert geared.spill_stats()["spill_episodes"] > 0, \
        "top gear must still hand off to the spill tier"
    # the device telemetry block counts the same shifts the driver made
    assert geared.obs_snapshot()["win"]["gear_shifts"] == g["gear_shifts"]
    _assert_parity(fixed, geared)


def test_gearing_parity_phold_conservative():
    fixed = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=8192)
    fixed.run()
    geared = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=8192,
        pool_gears=3)
    geared.run()
    assert geared.gear_stats()["gear_level"] == 0
    _assert_parity(fixed, geared)


def test_gearing_parity_optimistic():
    fixed = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=8192)
    wf, rf = fixed.run_optimistic()
    geared = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=8192,
        pool_gears=3)
    wg, rg = geared.run_optimistic()
    # PHOLD steady state occupies C/32: the geared run must select the
    # bottom tier, not ride the burst-provisioned top
    assert geared.gear_stats()["gear_level"] == 0
    _assert_parity(fixed, geared)


def test_gearing_parity_islands_both_modes():
    base = dict(num_hosts=64, msgload=2, stop_s=2, runtime_s=2, seed=3,
                event_capacity=8192, num_shards=4)
    fixed = build_phold_flagship(**base)
    fixed.run()
    geared = build_phold_flagship(**base, pool_gears=3)
    geared.run()
    _assert_parity(fixed, geared)

    fixed_o = build_phold_flagship(**base)
    fixed_o.run_optimistic()
    geared_o = build_phold_flagship(**base, pool_gears=3)
    geared_o.run_optimistic()
    _assert_parity(fixed_o, geared_o)


def test_checkpoint_records_and_restores_gear(tmp_path):
    from shadow_tpu.core import checkpoint

    path = str(tmp_path / "gear.ckpt")
    src = build_phold_flagship(
        64, msgload=2, stop_s=4, runtime_s=4, seed=3, event_capacity=8192,
        pool_gears=3)
    src.run(until=int(1.0 * simtime.NS_PER_SEC))
    src._shift_gear(1)  # force a non-initial gear into the checkpoint
    src.run(until=int(2.0 * simtime.NS_PER_SEC))
    src.save_checkpoint(path)
    meta = checkpoint.load_meta(path)
    assert meta["gear"]["level"] == src._gear
    assert meta["gear"]["capacity"] == src._gear_ladder[src._gear].capacity

    dst = build_phold_flagship(
        64, msgload=2, stop_s=4, runtime_s=4, seed=3, event_capacity=8192,
        pool_gears=3)
    assert dst._gear != src._gear  # restore must re-bind, not assume
    dst.load_checkpoint(path)
    assert dst._gear == src._gear
    assert dst.state.pool.capacity == src.state.pool.capacity
    src.run()
    dst.run()
    assert src.counters() == dst.counters()
    assert _host_digest(src) == _host_digest(dst)

    # a build without the checkpointed tier must refuse, not misload
    flat = build_phold_flagship(
        64, msgload=2, stop_s=4, runtime_s=4, seed=3, event_capacity=8192)
    with pytest.raises(checkpoint.CheckpointError):
        flat.load_checkpoint(path)


# ---------------------------------------------------------------------------
# static-analysis guards: the op ban and the sort-volume mechanism.
# The HLO-parsing logic lives in shadow_tpu/analysis/hlo_audit.py (the
# shared compiled-kernel auditor — tests/test_analysis.py runs the full
# variant matrix); these tests keep the gearbox-local claims.
# ---------------------------------------------------------------------------


def test_window_kernel_bans_scatter_and_serializing_gather():
    # matrix path (PHOLD) and loop path (full netstack) both compile clean
    phold = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=4096)
    flood = build_simulation(_flood_cfg(1, 1024))
    for name, sim in (("phold", phold), ("flood", flood)):
        hlo = hlo_audit.kernel_hlo(sim)
        violations = hlo_audit.audit_hlo(hlo)
        assert not violations, f"{name}: {violations}"


def test_low_gear_sort_rows_at_most_half_of_top():
    sim = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=8192,
        pool_gears=3)
    assert sim._gear == 0
    low = max(hlo_audit.sort_rows(hlo_audit.kernel_hlo(sim)))
    sim._shift_gear(len(sim._gear_ladder) - 1)
    top = max(hlo_audit.sort_rows(hlo_audit.kernel_hlo(sim)))
    assert low * 2 <= top, f"low gear sorts {low} rows vs top {top}"
