"""Self-balancing fleet (ISSUE 11): closed-loop hot-shard healing with
verified live host migration (parallel/balancer.py).

The load-bearing properties:

  * a migration permutes the LAYOUT only — the balanced run's audit
    digest chain is bit-identical to the balancer-off run, and so is a
    run whose first migration was forced to fail mid-move (rollback);
  * the skew_hosts chaos input is itself layout-independent: the same
    fault plan produces the same chain on the global and islands engines;
  * a checkpoint taken AFTER a live migration resumes correctly: the
    slot_of routing table is rebuilt from the restored host rows
    (core/checkpoint.restore -> IslandSimulation._post_restore), and the
    resumed run's chain matches the uninterrupted migrated run's.
"""

import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import simtime
from shadow_tpu.parallel import balancer as balancer_mod
from shadow_tpu.parallel.balancer import (
    BalancerPolicy,
    HotnessDetector,
    refine_assignment,
)
from shadow_tpu.sim import build_simulation

NEVER = int(simtime.NEVER)


def _decohered_gml(shards, per, seed=7):
    """Uniform decohered intra bands + large cross latencies (the
    balance-smoke topology: hotness comes from skew_hosts, not the
    graph)."""
    rng = np.random.RandomState(seed)
    n = shards * per

    def band(a, b):
        if a // per != b // per:
            return 700000, 900000
        return 30000, 250000

    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        for b in range(a, n):
            lo, hi = band(a, b)
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def _cfg(shards=4, per=4, stop=8, skew_at="2 s", balancer=False,
         rebalance=True, **exp):
    n = shards * per
    hosts = {}
    for v in range(n):
        hosts[f"h{v:02d}"] = {
            "quantity": 1, "network_node_id": v, "app_model": "phold",
            "app_options": {
                "msgload": 2, "runtime": stop - 1,
                # persistent destination bias toward shard 0's hosts —
                # the skew amplification keeps re-concentrating there
                "hot_frac": per / n, "hot_share": 0.5,
            },
        }
    experimental = {
        "event_capacity": 4096, "events_per_host_per_window": 8,
        "outbox_slots": 8, "inbox_slots": 4,
        "num_shards": shards, "exchange_slots": 32,
        "rebalance": rebalance, "balancer": balancer,
        "balance_streak": 3, "balance_cooldown": 8,
        "balance_hot_ratio": 1.5,
    }
    experimental.update(exp)
    doc = {
        "general": {"stop_time": stop, "seed": 42},
        "network": {"graph": {"type": "gml", "inline": _decohered_gml(
            shards, per)}},
        "experimental": experimental,
        "hosts": hosts,
    }
    if skew_at is not None:
        doc["faults"] = {"inject": [{
            "at": skew_at, "op": "skew_hosts",
            "span": [0, per], "factor": 6,
        }]}
    return doc


def _run(cfg, hook=None, wpd=16):
    sim = build_simulation(cfg)
    if cfg.get("faults"):
        sim.attach_faults(sim.config.faults.load_faults())
    if hook is not None:
        hook(sim)
    sim.run(windows_per_dispatch=wpd)
    return sim


# ---------------------------------------------------------------------------
# detector + refinement units
# ---------------------------------------------------------------------------


def test_detector_requires_streak_and_resets():
    det = HotnessDetector(BalancerPolicy(
        hot_ratio=1.5, min_skew_rows=10, streak=3))
    hot = [100, 10, 10, 10]
    assert det.observe(hot) is None  # streak 1
    assert det.observe(hot) is None  # streak 2
    # a different shard going hot resets the streak
    assert det.observe([10, 100, 10, 10]) is None
    assert det.observe([10, 100, 10, 10]) is None
    assert det.observe([10, 100, 10, 10]) == 1
    # a cool dispatch resets too
    assert det.observe(hot) is None
    assert det.observe([20, 20, 20, 20]) is None
    assert det.observe(hot) is None
    assert det.observe(hot) is None
    assert det.observe(hot) == 0


def test_detector_requires_frontier_laggard():
    det = HotnessDetector(BalancerPolicy(
        hot_ratio=1.5, min_skew_rows=10, streak=1))
    occ = [100, 10, 10, 10]
    # hot shard running AHEAD of the others is absorbing its load fine
    assert det.observe(occ, frontier=[500, 100, 100, 100]) is None
    # hot shard as the laggard (or tied at a clamped boundary) triggers
    assert det.observe(occ, frontier=[100, 500, 500, 500]) == 0
    assert det.observe(occ, frontier=[100, 100, 100, 100]) == 0


def test_detector_noise_floor():
    det = HotnessDetector(BalancerPolicy(
        hot_ratio=1.5, min_skew_rows=50, streak=1))
    assert det.observe([20, 2, 2, 2]) is None  # skew 18 < 50 rows
    assert det.observe([80, 2, 2, 2]) == 0


def test_refine_flattens_load_and_keeps_shard_sizes():
    H, S = 16, 4
    load = np.zeros(H, np.int64)
    load[:4] = [60, 50, 40, 30]  # shard 0 holds everything
    load[4:] = 2
    lat = np.full((H, H), 500_000_000, np.int64)
    np.fill_diagonal(lat, 1_000_000)
    slot, moves, cut0, cut1 = refine_assignment(
        load, np.arange(H), S, 0, lat, np.arange(H),
        BalancerPolicy(max_moves=8),
    )
    assert moves >= 1
    # still a permutation with exactly H/S slots per shard
    assert sorted(slot) == list(range(H))
    shard_of = np.asarray(slot) // (H // S)
    assert (np.bincount(shard_of, minlength=S) == H // S).all()
    sl = np.bincount(shard_of, weights=load, minlength=S)
    skew_before = load[:4].sum() / (load.sum() / S)
    skew_after = sl.max() / sl.mean()
    assert sl[0] < load[:4].sum()  # shed something
    assert skew_after < skew_before * 0.6  # genuinely flattened


def test_refine_prefers_low_affinity_boundary_hosts():
    """Two equally heavy hosts on the hot shard; one is glued to the
    shard by a low-latency (high-affinity) link — the refinement must
    move the OTHER one (lookahead-critical links stay intra-shard)."""
    H, S = 8, 2
    load = np.array([50, 50, 1, 1, 1, 1, 1, 1], np.int64)
    lat = np.full((H, H), 100_000_000, np.int64)
    np.fill_diagonal(lat, 1_000_000)
    # host 0 <-> host 2: a 1 us lookahead-critical link inside shard 0
    lat[0, 2] = lat[2, 0] = 1_000
    slot, moves, cut0, cut1 = refine_assignment(
        load, np.arange(H), S, 0, lat, np.arange(H),
        BalancerPolicy(max_moves=1),
    )
    shard_of = np.asarray(slot) // (H // S)
    assert moves == 1
    assert shard_of[0] == 0, "moved the glued host (cut ignored)"
    assert shard_of[1] == 1, "the free heavy host should have moved"


def test_cut_cost_counts_cross_affinity_only():
    lat = np.array([[1_000, 1_000, NEVER, NEVER],
                    [1_000, 1_000, NEVER, NEVER],
                    [NEVER, NEVER, 1_000, 1_000],
                    [NEVER, NEVER, 1_000, 1_000]], np.int64)
    hv = np.arange(4)
    block = balancer_mod.cut_cost(np.array([0, 0, 1, 1]), lat, hv)
    split = balancer_mod.cut_cost(np.array([0, 1, 0, 1]), lat, hv)
    assert block == 0.0  # no finite cross links
    assert split > 0.0


# ---------------------------------------------------------------------------
# e2e: heal, verify, roll back — chains bit-identical throughout
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def control():
    sim = _run(_cfg(balancer=False))
    return sim, sim.audit_chain(), sim.counters()["events_committed"]


def test_balancer_heals_hot_shard_chain_identical(control):
    _, chain, ev = control
    sim = _run(_cfg(balancer=True))
    stats = sim.balance_stats()
    assert stats["migrations"] >= 1
    assert stats["rollbacks"] == 0
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev
    # healing shows up as less time blocked on the laggard's horizon
    # (end-state resident loads converge as the run drains, so the
    # schedule counter is the honest signal; bench --balance-smoke
    # gates the phase-windowed spread + load flattening)
    blocked_c = control[0].async_stats()["blocked_on_neighbor"]
    blocked_b = sim.async_stats()["blocked_on_neighbor"]
    assert blocked_b < blocked_c, (blocked_b, blocked_c)


def test_forced_midmigration_failure_rolls_back(control):
    _, chain, ev = control
    sim = _run(
        _cfg(balancer=True),
        hook=lambda s: s.balancer.inject_failure_next(),
    )
    stats = sim.balance_stats()
    assert stats["rollbacks"] >= 1
    assert "injected mid-migration failure" in sim.balancer.last_reason \
        or sim.balancer.last_reason == ""  # a later migration committed
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev


def test_balancer_yields_to_pressure_and_supervisor():
    from shadow_tpu.core.pressure import PressureController
    from shadow_tpu.core.supervisor import BackendSupervisor
    from shadow_tpu.parallel.balancer import ShardBalancer

    sim = build_simulation(_cfg(balancer=True, skew_at=None, stop=2))
    bal = sim.balancer
    # a real resident skew, so the refinement has load to shed once the
    # interlocks clear (detection itself runs on the passed vector)
    sim.skew_hosts([0, 1, 2, 3], 6)
    hot = np.array([500, 1, 1, 1])
    # pressure episode: hold, and the detection streak resets
    sim.pressure = PressureController()
    sim.pressure.hold_gear = True
    assert bal.observe(sim, hot) is False
    assert bal.counters["holds"] == 1
    sim.pressure.hold_gear = False
    # degraded supervisor: hold
    sup = BackendSupervisor()
    sim.attach_supervisor(sup)
    sup._dead = True
    assert bal.observe(sim, hot) is False
    assert bal.counters["holds"] == 2
    sup._dead = False
    # mid-optimistic-attempt: hold
    sim._pressure_reshape_ok = False
    assert bal.observe(sim, hot) is False
    assert bal.counters["holds"] == 3
    sim._pressure_reshape_ok = True
    # healthy again: the streak restarts from zero (3 dispatches to go)
    assert isinstance(bal, ShardBalancer)
    assert bal.observe(sim, hot) is False
    assert bal.observe(sim, hot) is False
    assert bal.observe(sim, hot) is True
    assert bal.counters["migrations"] == 1


# ---------------------------------------------------------------------------
# skew_hosts: layout-independence of the chaos input itself
# ---------------------------------------------------------------------------


def test_skew_hosts_layout_independent():
    """The same skew_hosts plan produces the same chain on the global
    single-pool engine and the islands engine: the injection keys on
    global host ids and pending-event content only."""
    g = _run(_cfg(stop=5), wpd=16)
    # strip islands fields for the global build
    doc = _cfg(stop=5)
    doc["experimental"].pop("num_shards")
    doc["experimental"].pop("exchange_slots")
    doc["experimental"].pop("rebalance")
    solo = _run(doc, wpd=16)
    assert solo.fault_stats()["events_skewed"] > 0
    assert solo.fault_stats()["events_skewed"] \
        == g.fault_stats()["events_skewed"]
    assert solo.audit_chain() == g.audit_chain()
    assert solo.counters()["events_committed"] \
        == g.counters()["events_committed"]


# ---------------------------------------------------------------------------
# checkpoint/resume of a migrated layout (the satellite regression:
# before _post_restore, a resumed migrated run misrouted every
# cross-shard event against a stale identity slot_of table)
# ---------------------------------------------------------------------------


def test_async_rebalance_survives_kill_and_resume(tmp_path):
    """Migrate mid-run under the ASYNC driver, auto-checkpoint past the
    migration, SIGKILL (abandon the process state), --resume in a fresh
    build, and require the final chain bit-identical to an uninterrupted
    migrated run."""
    cfg = _cfg(stop=6, balancer=False)  # explicit migration timing

    full = build_simulation(cfg)
    full.attach_faults(full.config.faults.load_faults())
    full.run(until=3 * simtime.NS_PER_SEC, windows_per_dispatch=16)
    full.rebalance_now()
    assert full.rebalances == 1
    full.run(windows_per_dispatch=16)
    chain_full = full.audit_chain()

    interrupted = build_simulation(cfg)
    interrupted.attach_faults(interrupted.config.faults.load_faults())
    interrupted.configure_auto_checkpoint(
        str(tmp_path), every_ns=simtime.NS_PER_SEC
    )
    interrupted.run(until=3 * simtime.NS_PER_SEC,
                    windows_per_dispatch=16)
    interrupted.rebalance_now()
    interrupted.run(until=5 * simtime.NS_PER_SEC,
                    windows_per_dispatch=16)
    assert interrupted.fault_counters["checkpoints_written"] >= 1
    del interrupted  # the SIGKILL: nothing survives but the ring

    res = build_simulation(cfg)
    res.attach_faults(res.config.faults.load_faults())
    info = res.resume_from(str(tmp_path))
    # the restored layout IS migrated: slot_of was rebuilt from the
    # checkpointed gid rows, not left at the build-time identity
    slot = np.asarray(res.params.slot_of)
    assert not np.array_equal(slot, np.arange(res.num_hosts))
    gid = np.asarray(res.state.host.gid).reshape(-1)
    assert (gid[slot] == np.arange(res.num_hosts)).all()
    # the header carries the assignment + rebalance count
    assert info["meta"]["balance"]["rebalances"] == 1
    assert info["meta"]["balance"]["assignment"] == [
        int(x) for x in slot
    ]
    res.run(windows_per_dispatch=16)
    assert res.audit_chain() == chain_full


def test_checkpoint_meta_restores_balancer_cooldown(tmp_path):
    from shadow_tpu.core import checkpoint as ckpt_mod

    sim = build_simulation(_cfg(balancer=True, skew_at=None, stop=2))
    sim.balancer._enter_cooldown("test")
    sim.balancer.counters["migrations"] = 3
    now = int(np.max(np.asarray(sim.state.now)))
    path, _ = ckpt_mod.save_ring(sim, str(tmp_path), seq=0, sim_ns=now)
    meta = ckpt_mod.load_meta(path)
    ctl = meta["balance"]["controller"]
    assert ctl["state"] == "cooldown"
    assert ctl["counters"]["migrations"] == 3

    res = build_simulation(_cfg(balancer=True, skew_at=None, stop=2))
    res.load_checkpoint(path)
    assert res.balancer.state == balancer_mod.STATE_COOLDOWN
    assert res.balancer.counters["migrations"] == 3


# ---------------------------------------------------------------------------
# metrics schema v10
# ---------------------------------------------------------------------------


def test_balance_metrics_schema_v10(tmp_path, control):
    import json

    from shadow_tpu.obs import metrics as obs_metrics

    sim = _run(_cfg(balancer=True))
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert_current_metrics_schema(doc)
    assert doc["counters"]["balance.migrations"] >= 1
    assert doc["counters"]["balance.rebalances"] >= 1
    assert "balance.state" in doc["gauges"]
    assert "balance.last_cut_after" in doc["gauges"]
    bad = json.loads(json.dumps(doc))
    bad["counters"]["balance.migrations"] = -1
    with pytest.raises(ValueError, match="balance counter"):
        obs_metrics.validate_metrics_doc(bad)
    # a balancer-off run emits NO balance keys
    session2 = obs_metrics.ObsSession()
    session2.finalize(control[0])
    doc2 = session2.metrics.dump(str(tmp_path / "m2.json"))
    assert not any(k.startswith("balance.") for k in doc2["counters"])
    assert not any(k.startswith("balance.") for k in doc2["gauges"])


# ---------------------------------------------------------------------------
# fleet outer ring: predicted-load packing + lane stealing
# ---------------------------------------------------------------------------


def test_scheduler_load_packing_steals_heaviest():
    from shadow_tpu.fleet.scheduler import FleetScheduler
    from shadow_tpu.fleet.sweep import JobSpec

    jobs = [JobSpec(f"j{i}", {"general": {}}) for i in range(4)]
    sched = FleetScheduler(jobs, lanes=2)
    # stub the config-derived costs: j2 is by far the heaviest
    sched._cost_cache = {"j0": 1.0, "j1": 2.0, "j2": 50.0, "j3": 3.0}
    # FIFO default: head of queue
    assert sched.pick(0).name == "j0"
    sched.packing = "load"
    picked = sched.pick(0)
    assert picked.name == "j2"
    assert sched.lane_steals == 1
    assert sched.pack_decisions == 1
    sched.admit(0, picked)
    # next heaviest among the remaining queue
    assert sched.pick(1).name == "j3"
    assert sched.lane_steals == 2
    st = sched.stats()
    assert st["lane_steals"] == 2 and st["pack_decisions"] == 2


def test_scheduler_calibration_ewma():
    from shadow_tpu.fleet.scheduler import FleetScheduler, JobRecord
    from shadow_tpu.fleet.sweep import JobSpec

    sched = FleetScheduler([JobSpec("a", {})], lanes=1)
    sched._cost_cache = {"a": 10.0}
    rec = JobRecord(spec=JobSpec("a", {}))
    rec.events_committed = 1000
    sched.calibrate(rec)
    assert sched.rate_ewma == pytest.approx(100.0)
    # the calibrated rate scales the prediction
    assert sched.predicted_load(sched.records[0]) \
        == pytest.approx(10.0 * 100.0)
