"""Host-spill overflow tier (core/spill.py): an undersized pool completes
with BIT-IDENTICAL results to an oversized one — the engine never silently
drops an event (VERDICT r3 #7; reference invariant: queues grow on the
heap, scheduler.c:232-255).

The workload: UDP flood over a 400 ms self-loop link at a 10 ms send
interval → ~40 packets in flight per client, far beyond the undersized
pool. The driver must spill to host memory and re-inject, clamping windows
below spilled timestamps.
"""

import numpy as np
import pytest

from shadow_tpu.sim import build_simulation


def _cfg(event_capacity, num_shards=1, exchange_slots=64):
    exp = {
        "event_capacity": event_capacity,
        "events_per_host_per_window": 16,
        "outbox_slots": 8,
        "inbox_slots": 4,
        "router_queue_slots": 64,
    }
    if num_shards > 1:
        exp.update(num_shards=num_shards, exchange_slots=exchange_slots)
    return {
        "general": {"stop_time": 3, "seed": 5},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]\n'
            '  edge [ source 0 target 0 latency "400 ms" packet_loss 0.001 ]\n'
            ']\n')}},
        "experimental": exp,
        "hosts": {
            "server": {"quantity": 4, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 28, "app_model": "udp_flood",
                       "app_options": {"interval": "10 ms", "size": 256,
                                       "runtime": 1}},
        },
    }


_KEYS = (
    "events_committed", "events_emitted", "packets_sent",
    "packets_delivered", "packets_dropped_loss", "bytes_sent",
    "bytes_delivered", "pool_overflow_dropped",
)


def _recv(sim):
    return np.asarray(sim.state.subs["udp_flood"]["recv"]).reshape(-1)


@pytest.mark.quick
def test_undersized_pool_matches_oversized():
    big = build_simulation(_cfg(1 << 13))
    big.run_stepwise()
    cb = big.counters()
    assert cb["pool_overflow_dropped"] == 0
    assert big.spill_stats()["spill_episodes"] == 0  # sized fine

    small = build_simulation(_cfg(384))
    small.run_stepwise()
    cs = small.counters()
    st = small.spill_stats()
    assert st["spill_episodes"] > 0, "undersized pool never spilled"
    assert st["spill_resident"] == 0, "spill must fully drain by stop"
    for k in _KEYS:
        assert cb[k] == cs[k], (k, cb[k], cs[k])
    assert (_recv(big) == _recv(small)).all()


@pytest.mark.quick
def test_undersized_pool_fused_run_matches():
    """The fused dispatch loop (run) exits on the red-zone flag and the
    driver spills between dispatches — same results as stepwise."""
    small = build_simulation(_cfg(384))
    small.run(windows_per_dispatch=16)
    cs = small.counters()
    assert small.spill_stats()["spill_episodes"] > 0
    big = build_simulation(_cfg(1 << 13))
    big.run_stepwise()
    cb = big.counters()
    for k in _KEYS:
        assert cb[k] == cs[k], (k, cb[k], cs[k])


@pytest.mark.quick
def test_undersized_islands_pool_matches():
    big = build_simulation(_cfg(1 << 13))
    big.run_stepwise()
    cb = big.counters()
    isl = build_simulation(_cfg(1024, num_shards=4))
    isl.run_stepwise()
    ci = isl.counters()
    assert isl.spill_stats()["spill_episodes"] > 0
    for k in _KEYS:
        assert cb[k] == ci[k], (k, cb[k], ci[k])
    assert (_recv(big) == _recv(isl)).all()


@pytest.mark.quick
def test_saturate_pool_spill_escalation_preserves_order_and_chain():
    """Pressure plane (ISSUE 9): a `saturate_pool` injection mid-run
    scales the spill marks down and forces sustained spill escalation —
    the run must still commit the identical events in the identical
    per-host order (the audit digest chain folds commit order, so chain
    equality IS the order proof), with the same app-level results as the
    unsaturated control."""
    from shadow_tpu.faults import plan as plan_mod

    control = build_simulation(_cfg(1 << 13))
    control.run_stepwise()
    cc = control.counters()
    chain = control.audit_chain()
    assert control.spill_stats()["spill_episodes"] == 0  # sized fine

    sat = build_simulation(_cfg(1 << 13))
    sat.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "500 ms", "op": "saturate_pool", "frac": 0.05}]
    ))
    sat.run_stepwise()
    st = sat.spill_stats()
    assert st["spill_episodes"] > 0, "saturation never engaged the spill"
    assert st["spill_resident"] == 0, "spill must fully drain by stop"
    assert sat.pressure_stats()["saturations"] == 1
    assert sat.audit_chain() == chain
    for k in _KEYS:
        assert cc[k] == sat.counters()[k], k
    assert (_recv(control) == _recv(sat)).all()


@pytest.mark.quick
def test_spill_under_exchange_backpressure_matches():
    """Deferral × spill combined (ADVICE r4, high): exchange_slots=1 keeps
    cross-shard rows IN TRANSIT across windows while the undersized pool
    spills — a foreign row caught by a spill rebalance must keep its strict
    ordering guarantee (manage() re-routes it to the destination shard
    host-side instead of parking it). Results must stay bit-identical to
    the oversized single-pool run."""
    big = build_simulation(_cfg(1 << 13))
    big.run_stepwise()
    cb = big.counters()
    isl = build_simulation(_cfg(768, num_shards=4, exchange_slots=1))
    isl.run_stepwise()
    ci = isl.counters()
    st = isl.spill_stats()
    assert st["spill_episodes"] > 0, "pool never spilled"
    assert ci["exchange_deferred"] > 0, "no exchange backpressure"
    for k in _KEYS:
        assert cb[k] == ci[k], (k, cb[k], ci[k])
    assert (_recv(big) == _recv(isl)).all()
