"""Pressure plane (ISSUE 9): graceful degradation under device-memory and
pool exhaustion.

The acceptance gate: `exhaust_backend` / `saturate_pool` injections
across {conservative, optimistic} × {global, islands, fleet} end with
audit digest chains BIT-IDENTICAL to the uninterrupted run, with zero
bare RuntimeError/XlaRuntimeError escaping a driver — every terminal
pool stall is the typed `PoolExhausted` (core/pressure.py), raised only
after the degradation ladder gave up and the frontier drained to a
checkpoint. The chain (obs/audit.py) is the proof instrument: a ladder
rung that merely "looks right" cannot pass it.
"""

import os

import pytest

from shadow_tpu.core import pressure as pressure_mod
from shadow_tpu.core.pressure import (
    PoolExhausted,
    PressureController,
    PressurePolicy,
)
from shadow_tpu.core.supervisor import (
    BACKEND_LOST,
    BackendLost,
    BackendSupervisor,
    FATAL,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    classify_failure,
)
from shadow_tpu.faults import plan as plan_mod
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick

DEVICE_YAML = """
general:
  stop_time: 4
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 3}
"""

ISLANDS_YAML = DEVICE_YAML.replace(
    "  event_capacity: 1024",
    "  event_capacity: 1024\n  num_shards: 2",
)

# two gear tiers so the memory ladder has a smaller pool to retreat to
GEARED_YAML = DEVICE_YAML.replace(
    "  event_capacity: 1024",
    "  event_capacity: 1024\n  pool_gears: 2",
)


def _build(yaml):
    return build_simulation(yaml)


def _run(sim, sync):
    if sync == "optimistic":
        sim.run_optimistic()
    else:
        sim.run()
    return sim


def _quiet_supervisor(policy="wait", **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe_budget_s", 30.0)
    return BackendSupervisor(policy, **kw)


_BASELINES: dict = {}


def _baseline(yaml, sync):
    key = (yaml, sync)
    if key not in _BASELINES:
        sim = _run(_build(yaml), sync)
        _BASELINES[key] = (
            sim.audit_chain(), sim.counters()["events_committed"],
        )
        assert _BASELINES[key][0] != 0
    return _BASELINES[key]


# ---------------------------------------------------------------------------
# classification + typed error + estimator (pure host code)
# ---------------------------------------------------------------------------


def test_classify_resource_exhausted_is_its_own_class():
    assert classify_failure(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 1073741824 bytes")
    ) == RESOURCE_EXHAUSTED
    assert classify_failure(
        RuntimeError("XlaRuntimeError: Resource exhausted: hbm")
    ) == RESOURCE_EXHAUSTED
    assert classify_failure(RuntimeError("failed to allocate request")) \
        == RESOURCE_EXHAUSTED
    assert classify_failure(PoolExhausted("stalled")) == RESOURCE_EXHAUSTED
    # the neighbors keep their classes
    assert classify_failure(RuntimeError("ABORTED: collective")) == TRANSIENT
    assert classify_failure(RuntimeError("UNAVAILABLE: socket closed")) \
        == BACKEND_LOST
    assert classify_failure(RuntimeError("device or resource busy")) \
        == BACKEND_LOST
    assert classify_failure(ValueError("shape mismatch")) == FATAL


def test_pool_exhausted_carries_diagnostics():
    e = PoolExhausted("stalled", window=123, occupancy=900, capacity=1024)
    assert isinstance(e, RuntimeError)
    assert (e.window, e.occupancy, e.capacity) == (123, 900, 1024)


def test_plan_pressure_ops_validate():
    good = {
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [
            {"at": "1 s", "op": "exhaust_backend"},
            {"at": "1 s", "op": "exhaust_backend", "recover_after": 3},
            {"at": "2 s", "op": "saturate_pool", "frac": 0.25},
            {"at": "2 s", "op": "saturate_pool"},
        ],
    }
    plan_mod.validate_fault_plan_doc(good)
    faults = plan_mod.parse_fault_plan(good["faults"])
    assert faults[1].recover_after == 3
    assert faults[2].frac == 0.25
    assert faults[3].frac == 0.5  # default
    assert "exhaust_backend" in plan_mod.BACKEND_OPS
    assert "saturate_pool" in plan_mod.DEVICE_OPS
    for bad in (
        [{"at": 1, "op": "saturate_pool", "frac": 0.0}],
        [{"at": 1, "op": "saturate_pool", "frac": 1.5}],
        [{"at": 1, "op": "saturate_pool", "frac": "nope"}],
        [{"at": 1, "op": "exhaust_backend", "recover_after": -1}],
        [{"at": 1, "op": "exhaust_backend", "frac": 0.5}],
    ):
        with pytest.raises(plan_mod.FaultPlanError):
            plan_mod.parse_fault_plan(bad)
    # daemon-level chaos plans may carry pressure ops; device-host ops no
    plan_mod.check_backend_ops(plan_mod.parse_fault_plan(
        [{"at": 1, "op": "exhaust_backend"},
         {"at": 1, "op": "saturate_pool", "frac": 0.5}]
    ))
    with pytest.raises(plan_mod.FaultPlanError):
        plan_mod.check_backend_ops(plan_mod.parse_fault_plan(
            [{"at": 1, "op": "kill_host", "host": 0}]
        ))


def test_hbm_estimator_scales_with_gear_and_budget_env(monkeypatch):
    sim = _build(GEARED_YAML)
    est_top = pressure_mod.estimate_hbm_bytes(sim, level=1)
    est_low = pressure_mod.estimate_hbm_bytes(sim, level=0)
    assert est_top["total_bytes"] > est_low["total_bytes"] > 0
    assert est_top["state_bytes"] == pressure_mod.tree_bytes(sim.state)
    monkeypatch.setenv("SHADOW_TPU_HBM_BUDGET", "1000000000")
    assert pressure_mod.device_memory_budget() == 1_000_000_000
    hb = pressure_mod.headroom_bytes(est_top["total_bytes"])
    assert hb == 1_000_000_000 - est_top["total_bytes"]


def test_supervisor_exhaust_runs_ladder_then_succeeds():
    sup = _quiet_supervisor("abort")
    steps = []

    class Sim:
        def _pressure_ladder_step(self, label):
            steps.append(label)
            return True

        def _drain_to_checkpoint(self, reason, ckpt_dir=None):
            return None

    sup.bind(Sim())
    sup.inject_exhaust(2)
    assert sup.call("run_to", lambda: "ok") == "ok"
    assert len(steps) == 2
    assert sup.counters["exhaustions"] == 2
    assert sup.counters["pressure_steps"] == 2
    assert sup.counters["backend_losses"] == 0


def test_supervisor_exhaust_ladder_exhausted_drains_to_policy():
    sup = _quiet_supervisor("abort")
    drains = []

    class Sim:
        def _pressure_ladder_step(self, label):
            return False  # ladder gave up

        def _drain_to_checkpoint(self, reason, ckpt_dir=None):
            drains.append(reason)
            return None

    sup.bind(Sim())
    sup.inject_exhaust(1)
    with pytest.raises(BackendLost):
        sup.call("run_to", lambda: "ok")
    assert drains and sup.counters["drains"] == 1


def test_controller_saturation_yields_and_relaxes():
    pc = PressureController()
    pc.saturate(0.25)
    assert pc.scaled_marks(800, 600) == (200, 150)

    class Sim:
        def _pressure_relieve_pool(self, step):
            return None  # no rung available: only the yield applies

    assert pc.on_pool_exhausted(Sim(), window=0)
    assert pc.saturate_frac == 0.5
    assert pc.on_pool_exhausted(Sim(), window=0)
    assert pc.saturate_frac == 1.0
    assert not pc.on_pool_exhausted(Sim(), window=0)  # fully yielded
    assert pc.counters["gave_up"] == 1
    # relaxation hysteresis: fill_shrink decays after clean dispatches
    pc.fill_shrink = 2
    for _ in range(pc.policy.recover_after_dispatches):
        pc.note_progress()
    assert pc.fill_shrink == 1


def test_disabled_policy_raises_typed_pool_exhausted(tmp_path):
    """The pre-ladder behavior, typed: with the ladder disabled a
    saturation stall surfaces as PoolExhausted (never a bare
    RuntimeError), after draining the frontier to the checkpoint ring."""
    sim = _build(DEVICE_YAML)
    sim.checkpoint_dir = str(tmp_path)
    ctl = PressureController(PressurePolicy(enabled=False))
    sim.attach_pressure(ctl)
    # saturation so severe the spill tier cannot place a window's inflow
    ctl.saturate_frac = 0.001
    sim._force_spill = True
    with pytest.raises(PoolExhausted) as e:
        sim.run()
    assert e.value.capacity == 1024
    assert e.value.occupancy is not None
    assert ctl.counters["gave_up"] >= 1
    entries = [n for n in os.listdir(tmp_path) if n.startswith("drain-")]
    assert len(entries) == 1  # drained before raising: resumable


# ---------------------------------------------------------------------------
# chaos matrix: exhaust_backend / saturate_pool ×
# {conservative, optimistic} × {global, islands} (fleet below)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sync", ["conservative", "optimistic"])
@pytest.mark.parametrize(
    "yaml", [DEVICE_YAML, ISLANDS_YAML], ids=["global", "islands"]
)
def test_exhaust_backend_ladder_chain_identical(yaml, sync):
    """Acceptance gate: a mid-run RESOURCE_EXHAUSTED drives the ladder
    and the run COMPLETES in-process with the uninterrupted chain."""
    chain, events = _baseline(yaml, sync)
    sim = _build(yaml)
    sim.attach_supervisor(_quiet_supervisor("wait"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "exhaust_backend", "recover_after": 2}]
    ))
    _run(sim, sync)
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    ps = sim.pressure_stats()
    assert ps["backend_exhausted"] == 2
    assert ps["ladder_steps"] == 2
    assert sim.supervisor.counters["exhaustions"] == 2
    assert sim.supervisor.counters["backend_losses"] == 0


@pytest.mark.parametrize(
    "yaml", [DEVICE_YAML, ISLANDS_YAML], ids=["global", "islands"]
)
def test_saturate_pool_spill_ladder_chain_identical(yaml):
    """Sustained simulated pool pressure is absorbed by the spill tier;
    events, order and chain stay bit-identical to the unsaturated run."""
    chain, events = _baseline(yaml, "conservative")
    sim = _build(yaml)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "saturate_pool", "frac": 0.2}]
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sim.pressure_stats()["saturations"] == 1
    assert sim.spill_stats()["spill_episodes"] >= 1


def test_saturate_pool_optimistic_is_benign():
    """saturate_pool under optimistic sync: the spill marks are unused
    by the speculative driver, so the injection records pressure but the
    run is untouched — and bit-identical."""
    chain, events = _baseline(DEVICE_YAML, "optimistic")
    sim = _build(DEVICE_YAML)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "saturate_pool", "frac": 0.2}]
    ))
    sim.run_optimistic()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sim.pressure_stats()["saturations"] == 1


def test_forced_downshift_overrides_red_zone_and_holds(tmp_path):
    """The memory ladder's first rung on a geared build: park overflow
    host-side, downshift one tier, HOLD the gear down (the red-zone
    upshift rule is overridden) — bit-identical completion."""
    chain, events = _baseline(GEARED_YAML, "conservative")
    sim = _build(GEARED_YAML)
    # force the top gear so a smaller tier exists to retreat to
    if sim._gear < len(sim._gear_ladder) - 1:
        sim._shift_gear(len(sim._gear_ladder) - 1)
        sim._gear_shifts = 0
    sim.attach_supervisor(_quiet_supervisor("wait"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "exhaust_backend", "recover_after": 1}]
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sim.pressure_stats()["downshifts"] == 1


# ---------------------------------------------------------------------------
# fleet cells: exhaust → lane eviction / saturate → recorded, chains equal
# ---------------------------------------------------------------------------

GML = """\
graph [
  node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def _fleet_cfg(seed, stop):
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": GML}},
        "experimental": {
            "event_capacity": 1024,
            "events_per_host_per_window": 8,
            "outbox_slots": 8,
            "inbox_slots": 4,
        },
        "hosts": {
            "peer": {
                "quantity": 8,
                "app_model": "phold",
                "app_options": {
                    "msgload": 2, "runtime": 2, "start_time": "100 ms",
                },
            }
        },
    }


def _fleet_jobs(n=3):
    from shadow_tpu.fleet import JobSpec

    stops = ["900 ms", "1.4 s", "1.1 s"]
    return [
        JobSpec(f"job{i}", _fleet_cfg(100 + i, stops[i])) for i in range(n)
    ]


def _fleet_ref_chains():
    from shadow_tpu.fleet import build_fleet

    ref = build_fleet(_fleet_jobs(), lanes=2, windows_per_dispatch=2)
    ref.run()
    return [r["audit"]["chain"] for r in ref.results()]


@pytest.mark.parametrize("op", ["exhaust_backend", "saturate_pool"])
def test_fleet_pressure_chains_identical(op):
    """Fleet cells of the chaos matrix: the injection fires against the
    fleet frontier; every job's harvested chain still equals the
    uninterrupted sweep's (lane eviction re-runs are pure re-executions)."""
    from shadow_tpu.fleet import build_fleet

    ref_chains = _fleet_ref_chains()
    fleet = build_fleet(_fleet_jobs(), lanes=2, windows_per_dispatch=2)
    if op == "exhaust_backend":
        fleet.attach_supervisor(_quiet_supervisor("wait"))
        fault = {"at": "500 ms", "op": op, "recover_after": 1}
    else:
        fault = {"at": "500 ms", "op": op, "frac": 0.5}
    fleet.attach_faults(plan_mod.parse_fault_plan([fault]))
    fleet.run()
    assert fleet.ok(), [r["status"] for r in fleet.results()]
    assert [r["audit"]["chain"] for r in fleet.results()] == ref_chains
    ps = fleet.pressure_stats()
    if op == "exhaust_backend":
        # pool_gears=1: no smaller tier → the ladder evicted a lane
        assert ps["lane_evictions"] >= 1
        assert fleet.sched.jobs_requeued >= 1
    else:
        assert ps["saturations"] == 1


def test_fleet_optimistic_exhaust_chains_identical():
    from shadow_tpu.fleet import build_fleet

    ref = build_fleet(_fleet_jobs(), lanes=2, windows_per_dispatch=2)
    ref.run_optimistic()
    ref_chains = [r["audit"]["chain"] for r in ref.results()]

    fleet = build_fleet(_fleet_jobs(), lanes=2, windows_per_dispatch=2)
    fleet.attach_supervisor(_quiet_supervisor("wait"))
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "500 ms", "op": "exhaust_backend", "recover_after": 1}]
    ))
    fleet.run_optimistic()
    assert fleet.ok(), [r["status"] for r in fleet.results()]
    assert [r["audit"]["chain"] for r in fleet.results()] == ref_chains
    # mid-attempt no rung is safe (the snapshot pins lane rows), so the
    # exhaustion rode the supervisor's drain → recovery → retry path
    assert fleet.supervisor.counters["exhaustions"] >= 1
    assert fleet.pressure_stats()["backend_exhausted"] >= 1


# ---------------------------------------------------------------------------
# serve: memory-aware admission (the preflight estimator vs live headroom)
# ---------------------------------------------------------------------------


def _sweep_doc():
    return {
        "sweep": {"name": "t", "lanes": 2,
                  "matrix": {"general.seed": [1, 2]}},
        **_fleet_cfg(1, "900 ms"),
    }


def test_serve_memory_aware_admission(tmp_path, monkeypatch):
    from shadow_tpu.serve.daemon import ServeOptions, ShadowDaemon

    # a 1 kB budget: nothing fits → shed 429 memory_pressure
    monkeypatch.setenv("SHADOW_TPU_HBM_BUDGET", "1024")
    d = ShadowDaemon(ServeOptions(str(tmp_path / "s1")))
    out = d.submit(_sweep_doc())
    assert out["shed"] == "memory_pressure"
    assert out["estimated_bytes"] > 1024
    assert out["retry_after_s"] >= 1
    assert d.counters["memory_sheds"] == 1
    mem = d._memory_view()
    assert mem["budget_bytes"] == 1024
    assert mem["headroom_bytes"] == 1024
    doc = d.metrics_doc()
    assert doc["counters"]["serve.memory_sheds"] == 1
    assert "pressure.headroom_bytes" in doc["gauges"]
    d.journal.close()

    # no budget (CPU backend): the same submission is admitted
    monkeypatch.delenv("SHADOW_TPU_HBM_BUDGET")
    d2 = ShadowDaemon(ServeOptions(str(tmp_path / "s2")))
    out2 = d2.submit(_sweep_doc())
    assert "id" in out2
    assert d2._memory_view()["budget_bytes"] is None
    d2.journal.close()


def test_config_estimator_is_conservative_and_lane_scaled():
    from shadow_tpu.core.config import load_config

    cfg = load_config(_fleet_cfg(1, "900 ms"))
    one = pressure_mod.estimate_config_bytes(cfg, lanes=1)
    four = pressure_mod.estimate_config_bytes(cfg, lanes=4)
    assert four == 4 * one
    # conservative: at least the raw pool bytes
    assert one > 1024 * (8 + 4 * 4)
