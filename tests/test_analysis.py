"""shadowlint (shadow_tpu/analysis): the static-analysis plane.

Layer 1 (AST rules): one firing fixture per STL0xx rule code, one
non-firing control per rule, `# noqa` suppression, kernel-vs-host
classification, and the baseline (grandfathering) workflow — plus the
load-bearing gate: the REAL tree (shadow_tpu/ + tools/ + bench.py) must
report zero non-baselined violations.

Layer 2 (compiled-kernel auditor, hlo_audit): the op-contract audit over
the window-kernel variant matrix {conservative, optimistic} × {global,
islands, fleet} × gear tiers (full matrix cells marked `slow` — each
costs a window-kernel compile; tier-1 keeps one representative cell),
and the retrace detector: one lowering per bound kernel across a driver
run, with a forged dtype-drift retrace caught.

Layers 3–5 (ISSUE 14): the cross-plane contract auditor (SLC0xx,
contracts.py) with forged-drift fixtures per rule, the host-thread race
lint (STH0xx, threads.py) with forged-race fixtures, and the HLO budget
ledger (hlo_baseline.json) with a forged-regression diff — each firing
exactly its rule code, with silent clean-tree controls, plus the
load-bearing gates: the real tree audits clean under all three.

Satellite regression: ProcessDriver per-host RNG streams are pure
functions of (controller seed, host name) — the driver.py:626 unseeded
default_factory bug class.
"""

import json
import os

import pytest

from shadow_tpu.analysis import contracts, hlo_audit, linter, threads
from shadow_tpu.analysis.rules import RULES
from shadow_tpu.flagship import build_phold_flagship

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Paths that classify as kernel / host for fixture-snippet linting
KPATH = "shadow_tpu/net/_fixture.py"
HPATH = "shadow_tpu/procs/_fixture.py"


def _codes(src, path=KPATH, kind=None):
    return [f.code for f in linter.lint_source(src, path, kind=kind)]


# ---------------------------------------------------------------------------
# rule fixtures: every code fires, every control stays silent
# ---------------------------------------------------------------------------

# (code, firing snippet, lint path, silent control snippet, control path)
_FIXTURES = [
    ("STL001",
     "import time\ndef f():\n    return time.time()\n", KPATH,
     # host modules may read wall clocks (obs/metrics.py metadata)
     "import time\ndef f():\n    return time.time()\n", HPATH),
    ("STL002",
     "import numpy as np\ndef f():\n    return np.random.uniform()\n", KPATH,
     # the sanctioned fold-in lineage is not ambient randomness
     "import jax\ndef f(k):\n    return jax.random.uniform(k)\n", KPATH),
    ("STL003",
     "import random\nr = random.Random()\n", HPATH,
     "import random\nr = random.Random(42)\n", HPATH),
    ("STL004",
     "import jax\n"
     "def outer():\n"
     "    def body(c):\n"
     "        return c + int(c)\n"
     "    return jax.lax.while_loop(lambda c: c < 9, body, 0)\n", KPATH,
     # same coercion OUTSIDE a traced body: host-side handoff fetch idiom
     "import jax.numpy as jnp\n"
     "def occupancy(state):\n"
     "    return int(jnp.sum(state))\n", KPATH),
    ("STL005",
     "import jax\n"
     "def outer():\n"
     "    def body(c):\n"
     "        x = c + 1\n"
     "        if x > 3:\n"
     "            return x\n"
     "        return c\n"
     "    return jax.lax.while_loop(lambda c: c < 9, body, 0)\n", KPATH,
     # pytree-structure checks are trace-time static — the factory idiom
     "import jax\n"
     "def outer(cfg):\n"
     "    def body(c):\n"
     "        if cfg is not None:\n"
     "            return c + 1\n"
     "        return c\n"
     "    return jax.lax.while_loop(lambda c: c < 9, body, 0)\n", KPATH),
    ("STL006",
     "import jax\ndef f(x):\n    jax.debug.print('{}', x)\n    return x\n",
     KPATH,
     "import jax\ndef f(x):\n    jax.debug.print('{}', x)\n    return x\n",
     HPATH),
    ("STL007",
     "def f(d):\n    return [v for k, v in d.items()]\n", KPATH,
     "def f(d):\n    return [v for k, v in sorted(d.items())]\n", KPATH),
    ("STL008",
     "def f(reg):\n    reg.counter_set('bogus.key', 1)\n", HPATH,
     "def f(reg):\n    reg.counter_set('engine.events_committed', 1)\n",
     HPATH),
]


@pytest.mark.quick
@pytest.mark.parametrize(
    "code,firing,fpath,control,cpath",
    _FIXTURES, ids=[f[0] for f in _FIXTURES],
)
def test_rule_fires_and_control_is_silent(code, firing, fpath, control, cpath):
    assert _codes(firing, fpath) == [code]
    assert code not in _codes(control, cpath)


@pytest.mark.quick
def test_every_registered_rule_has_a_firing_fixture():
    covered = {f[0] for f in _FIXTURES}
    assert covered == {r.code for r in RULES}


@pytest.mark.quick
def test_stl003_catches_unseeded_default_factory_and_stray_prngkey():
    field_src = (
        "import random\n"
        "from dataclasses import dataclass, field\n"
        "@dataclass\n"
        "class A:\n"
        "    r: random.Random = field(default_factory=random.Random)\n"
    )
    assert _codes(field_src, HPATH) == ["STL003"]
    key_src = "import jax\nk = jax.random.PRNGKey(7)\n"
    assert _codes(key_src, HPATH) == ["STL003"]
    # ...but core/rng.py IS the sanctioned construction site
    assert _codes(key_src, "shadow_tpu/core/rng.py") == []


@pytest.mark.quick
def test_noqa_suppresses_exact_code_only():
    src = "import time\ndef f():\n    return time.time()  # noqa: STL001\n"
    assert _codes(src, KPATH) == []
    wrong = "import time\ndef f():\n    return time.time()  # noqa: STL002\n"
    assert _codes(wrong, KPATH) == ["STL001"]
    bare = "import time\ndef f():\n    return time.time()  # noqa\n"
    assert _codes(bare, KPATH) == []


@pytest.mark.quick
def test_kernel_vs_host_classification():
    kernels = [
        "shadow_tpu/core/engine.py", "shadow_tpu/core/gearbox.py",
        "shadow_tpu/net/tcp.py", "shadow_tpu/obs/counters.py",
        "shadow_tpu/obs/audit.py", "shadow_tpu/obs/flight.py",
        "shadow_tpu/parallel/islands.py", "shadow_tpu/fleet/engine.py",
    ]
    hosts = [
        # metrics.py is the canonical host case: its time.time() is
        # registry metadata, allowlisted structurally by classification
        "shadow_tpu/obs/metrics.py",
        "shadow_tpu/procs/driver.py", "shadow_tpu/core/config.py",
        "shadow_tpu/fleet/scheduler.py", "shadow_tpu/faults/injector.py",
        # the pressure ladder (ISSUE 9) is pure host bookkeeping: every
        # rung executes at a dispatch boundary, nothing is ever traced
        "shadow_tpu/core/pressure.py",
        # the elastic mesh runner (ISSUE 13) is pure orchestration —
        # wall-clock probes and rebuilds at dispatch boundaries; a
        # structural HOST exception inside the parallel/* kernel glob
        "shadow_tpu/parallel/elastic.py",
        "tools/shadowlint.py", "bench.py",
    ]
    for p in kernels:
        assert linter.classify_module(p) == "kernel", p
    for p in hosts:
        assert linter.classify_module(p) == "host", p


@pytest.mark.quick
def test_baseline_grandfathers_by_fingerprint(tmp_path):
    src = "import time\ndef f():\n    return time.time()\n"
    findings = linter.lint_source(src, KPATH)
    assert [f.code for f in findings] == ["STL001"]
    path = str(tmp_path / "baseline.json")
    linter.write_baseline(findings, path)
    baseline = linter.load_baseline(path)

    # the identical finding is grandfathered...
    new, old = linter.split_baselined(findings, baseline)
    assert not new and len(old) == 1
    # ...a second occurrence of the same fingerprint is NOT (counts cap)
    new, old = linter.split_baselined(findings * 2, baseline)
    assert len(new) == 1 and len(old) == 1
    # ...and a different line is new even with the baseline loaded
    other = linter.lint_source(
        "import time\ndef g():\n    return time.monotonic()\n", KPATH)
    new, _ = linter.split_baselined(other, baseline)
    assert [f.code for f in new] == ["STL001"]
    # a line-number shift alone does not invalidate the baseline
    shifted = linter.lint_source("\n\n" + src, KPATH)
    new, old = linter.split_baselined(shifted, baseline)
    assert not new and len(old) == 1


@pytest.mark.quick
def test_findings_doc_schema():
    findings = linter.lint_source(
        "import time\ndef f():\n    return time.time()\n", KPATH)
    doc = linter.findings_doc(findings, [], ["a.py"])
    assert doc["kind"] == "shadow_tpu.shadowlint"
    assert doc["ok"] is False
    assert doc["counts"] == {
        "new": 1, "grandfathered": 0, "by_code": {"STL001": 1}}
    assert json.loads(json.dumps(doc)) == doc  # JSON-serializable
    clean = linter.findings_doc([], findings, ["a.py"])
    assert clean["ok"] is True and clean["counts"]["grandfathered"] == 1


# ---------------------------------------------------------------------------
# the load-bearing gate: the real tree is clean
# ---------------------------------------------------------------------------


def test_tree_has_zero_nonbaselined_violations():
    paths = [os.path.join(REPO, p)
             for p in ("shadow_tpu", "tools", "bench.py")]
    findings = linter.lint_paths(paths, REPO)
    baseline = linter.load_baseline(os.path.join(REPO, linter.BASELINE_NAME))
    new, _ = linter.split_baselined(findings, baseline)
    assert not new, "non-baselined shadowlint findings:\n" + "\n".join(
        f.render() for f in new)


# ---------------------------------------------------------------------------
# metric-namespace schema: the STL008 <-> validator contract
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_strict_namespace_validation_matches_linter_table():
    from shadow_tpu.obs.metrics import (
        MetricsRegistry, validate_metrics_doc,
    )

    reg = MetricsRegistry()
    reg.counter_set("engine.events_committed", 3)
    doc = reg.to_doc()
    validate_metrics_doc(doc, strict_namespaces=True)
    reg.counter_set("bogus.key", 1)
    with pytest.raises(ValueError, match="bogus"):
        validate_metrics_doc(reg.to_doc(), strict_namespaces=True)
    # non-strict keeps accepting (back-compat for foreign docs)
    validate_metrics_doc(reg.to_doc())


# ---------------------------------------------------------------------------
# satellite regression: ProcessDriver per-host RNG determinism
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_per_host_rng_streams_are_seed_deterministic():
    from shadow_tpu.procs.driver import ProcessDriver, SimHost

    def streams(seed):
        d = ProcessDriver(seed=seed)
        hosts = [d.add_host(f"h{i}", f"10.0.0.{i + 1}") for i in range(4)]
        return [h.rand.randbytes(32) for h in hosts]

    a, b = streams(7), streams(7)
    assert a == b  # same controller seed -> identical per-host streams
    assert streams(8) != a  # the master seed actually feeds the streams
    assert len({bytes(s) for s in a}) == len(a)  # hosts get distinct streams
    # a directly-constructed SimHost must not draw OS entropy either
    assert SimHost(name="x", ip=1).rand.random() == \
        SimHost(name="x", ip=1).rand.random()


# ---------------------------------------------------------------------------
# layer 2: compiled-kernel auditor
# ---------------------------------------------------------------------------


def _tiny_phold(**kw):
    kw.setdefault("msgload", 2)
    kw.setdefault("stop_s", 2)
    kw.setdefault("runtime_s", 2)
    kw.setdefault("seed", 3)
    return build_phold_flagship(32, event_capacity=2048, **kw)


def _fleet_cfg(seed, pool_gears=2):
    from shadow_tpu.flagship import SELF_LOOP_50MS_GML

    return {
        "general": {"stop_time": "1 s", "seed": seed},
        "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
        "experimental": {
            "event_capacity": 1024, "events_per_host_per_window": 8,
            "outbox_slots": 8, "inbox_slots": 4, "pool_gears": pool_gears,
        },
        "hosts": {"peer": {
            "quantity": 8, "app_model": "phold",
            "app_options": {"msgload": 2, "runtime": 2,
                            "start_time": "100 ms"},
        }},
    }


def test_hlo_audit_flags_a_forged_violation():
    # the checks must actually bite: a synthetic HLO with a scatter, a
    # take_along_axis gather, and an oversized sort trips all three
    forged = "\n".join([
        "  %s1 = s64[4,100]{1,0} sort(s64[4,100] %a), dimensions={1}",
        "  %g = s64[8,2]{1,0} gather(s64[8,16]{1,0} %t, s32[8,2,2] %i), "
        "slice_sizes={1,1}",
        "  %sc = s64[16]{0} scatter(s64[16] %o, s32[4] %idx, s64[4] %u)",
    ])
    v = hlo_audit.audit_hlo(forged, max_sort_rows=50)
    kinds = "\n".join(v)
    assert "scatter" in kinds and "serializing gather" in kinds \
        and "exceeds the structural bound" in kinds
    assert len(v) == 3
    # the allowance admits the documented lookup count, nothing more
    assert len(hlo_audit.audit_hlo(forged, max_sort_rows=50,
                                   max_serializing_gathers=1)) == 2


def test_variant_matrix_covers_sync_layout_gears():
    sim = _tiny_phold(pool_gears=2)
    vs = hlo_audit.variants_for_sim(sim, "global")
    assert {(v.sync, v.gear) for v in vs} == {
        ("conservative", 0), ("optimistic", 0),
        ("conservative", 1), ("optimistic", 1),
    }


def test_global_conservative_kernel_passes_audit():
    # tier-1 representative cell; the full matrix runs in the slow tests
    sim = _tiny_phold()
    v = hlo_audit.variants_for_sim(
        sim, "global", sync_modes=("conservative",))
    hlo_audit.assert_variants_clean(v)


@pytest.mark.slow
def test_global_matrix_passes_audit():
    sim = _tiny_phold(pool_gears=2)
    hlo_audit.assert_variants_clean(hlo_audit.variants_for_sim(sim, "global"))


@pytest.mark.slow
def test_islands_matrix_passes_audit():
    sim = _tiny_phold(pool_gears=2, num_shards=2, exchange_slots=16)
    vs = hlo_audit.variants_for_sim(sim, "islands")
    # the matrix now carries the async conservative loop per gear
    # (ISSUE 10): the per-shard-frontier kernel an async islands build
    # actually dispatches
    assert {v.sync for v in vs} == {"conservative", "optimistic", "async"}
    hlo_audit.assert_variants_clean(vs)


def test_async_islands_kernel_passes_audit():
    """Tier-1 representative async cell: the fused per-shard-frontier
    loop (frontier all_gather + horizon math + window step) compiles
    with no scatter, no serializing gather, and sorts within the gear's
    structural bound."""
    sim = _tiny_phold(num_shards=2, exchange_slots=16)
    vs = hlo_audit.variants_for_sim(
        sim, "islands", sync_modes=("conservative",))
    assert any(v.sync == "async" for v in vs)
    hlo_audit.assert_variants_clean(vs)


def test_async_per_shard_gear_shifts_are_retrace_free():
    """ISSUE 10 regression: per-shard gear shifts bind other gears'
    kernels (fresh compiles) but must never RE-lower one — an async run
    that shifted down and back up still shows at most one lowering per
    (gear, kernel)."""
    import numpy as np

    sim = _tiny_phold(num_shards=2, exchange_slots=16, pool_gears=2)
    assert sim._async and sim._shard_shifter is not None
    assert len(sim._gear_ladder) > 1
    lo = sim._gear  # occupancy-selected low gear
    sim.run(until=400_000_000)
    # ONE hot shard presses the envelope up (fresh compile, not a
    # retrace), the other stays cold
    hi_mark = sim._gear_ladder[sim._gear].hi
    assert sim._gear_tick_async(np.array([0, hi_mark]))
    up = sim._gear
    assert up > lo
    sim.run(until=700_000_000)
    # cool occupancies walk the per-shard streaks down to the low gear
    shifted_down = False
    for _ in range(10):
        if sim._gear_tick_async(np.array([0, 0])):
            shifted_down = True
            break
    assert shifted_down and sim._gear < up
    sim.run(until=1_000_000_000)
    # hot again: the big gear's async kernel REBINDS, never re-lowers
    hi_mark = sim._gear_ladder[sim._gear].hi
    assert sim._gear_tick_async(np.array([hi_mark, 0]))
    assert sim._gear == up
    sim.run()
    rep = hlo_audit.assert_no_retrace(sim)
    # two separate residencies of the big gear rode ONE lowering
    assert rep["kernels"][f"gear{up}.run_to_async"] == 1


@pytest.mark.slow
def test_fleet_matrix_passes_audit():
    from shadow_tpu.fleet import JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec("a", _fleet_cfg(1)), JobSpec("b", _fleet_cfg(2))])
    hlo_audit.assert_variants_clean(hlo_audit.variants_for_fleet(fleet))


# ---------------------------------------------------------------------------
# retrace detector: one compile per bound kernel, drift caught
# ---------------------------------------------------------------------------


def test_driver_smoke_run_has_no_retraces():
    sim = _tiny_phold()
    sim.run()
    rep = hlo_audit.assert_no_retrace(sim)
    assert rep["compiles_total"] == 1  # ONE run_to lowering for the run
    assert rep["kernels"]["gear0.run_to"] == 1


def test_pressure_ladder_catch_paths_are_retrace_free():
    """ISSUE 9 regression: driver catch-paths stay retrace-free — a
    pressure-ladder engagement must not re-lower an already-bound kernel
    per rung. Spill-escalation rungs reuse the bound gear's kernel, so a
    run that absorbed TWO separate exhaustion episodes still shows one
    lowering per bound kernel (a downshift binding a NEW gear is one
    fresh compile, not a retrace — the detector's per-kernel cap covers
    both)."""
    from shadow_tpu.core.supervisor import BackendSupervisor
    from shadow_tpu.faults import plan as plan_mod

    sim = _tiny_phold()
    sim.attach_supervisor(
        BackendSupervisor("wait", sleep=lambda s: None)
    )
    sim.attach_faults(plan_mod.parse_fault_plan([
        {"at": "500 ms", "op": "exhaust_backend", "recover_after": 1},
        {"at": "1500 ms", "op": "exhaust_backend", "recover_after": 1},
    ]))
    sim.run()
    assert sim.pressure_stats()["ladder_steps"] == 2
    rep = hlo_audit.assert_no_retrace(sim)
    assert rep["compiles_total"] == 1  # both rungs reused the bound kernel


def test_retrace_detector_catches_dtype_drift():
    import numpy as np

    sim = _tiny_phold()
    sim.run()
    # forge the r03–r05 bug class: re-dispatch the bound kernel with a
    # drifted stop dtype — a silent recompile of the same program
    sim._run_to(sim.state, sim.params, np.float64(1e9), 4)
    with pytest.raises(hlo_audit.RetraceError, match="gear0.run_to"):
        hlo_audit.assert_no_retrace(sim)


@pytest.mark.slow
def test_fleet_sweep_is_one_compile():
    from shadow_tpu.fleet import JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec("a", _fleet_cfg(1, pool_gears=1)),
         JobSpec("b", _fleet_cfg(2, pool_gears=1))])
    fleet.run()
    rep = hlo_audit.assert_no_retrace(fleet)
    # PR 4's fleet invariant, now gated via the generic detector: the
    # whole sweep cost one window-kernel trace (and the trace counter
    # the fleet smoke gate asserts on agrees)
    assert rep["compiles_total"] == 1
    assert rep["kernel_traces"] == 1


# ---------------------------------------------------------------------------
# layer 3: the cross-plane contract auditor (forged drift per rule code)
# ---------------------------------------------------------------------------


def _slc_codes(findings):
    return [f.code for f in findings]


@pytest.mark.quick
def test_slc001_unregistered_namespace_emitter_fires():
    known = frozenset({"engine"})
    firing = (
        "def f(reg):\n"
        "    reg.counter_set('engine.ok', 1)\n"
        "    reg.gauge_set('bogus.key', 1)\n"
    )
    out = contracts.audit_metric_sources({"x.py": firing}, known=known)
    assert _slc_codes(out) == ["SLC001"]
    control = "def f(reg):\n    reg.counter_set('engine.ok', 1)\n"
    assert contracts.audit_metric_sources({"x.py": control}, known=known) == []


@pytest.mark.quick
def test_slc002_namespace_without_emitter_fires():
    known = frozenset({"engine", "ghost"})
    src = "def f(reg):\n    reg.counter_set('engine.ok', 1)\n"
    out = contracts.audit_metric_sources({"x.py": src}, known=known)
    assert _slc_codes(out) == ["SLC002"]
    assert "ghost" in out[0].message
    # helper-argument evidence counts: the `_sub_counter` prefix idiom
    helper = (
        "def f(reg, sub):\n"
        "    reg.counter_set('engine.ok', 1)\n"
        "    helper(reg, sub, 'ghost.nic')\n"
    )
    assert contracts.audit_metric_sources({"x.py": helper}, known=known) == []


@pytest.mark.quick
def test_slc003_fault_op_missing_handler_fires():
    src = 'def tick(f):\n    if f.op == "kill_host":\n        pass\n'
    out = contracts.audit_fault_handlers(
        [("eng.py", src, frozenset({"kill_host", "skew_hosts"}))]
    )
    assert _slc_codes(out) == ["SLC003"]
    assert "skew_hosts" in out[0].message
    assert contracts.audit_fault_handlers(
        [("eng.py", src, frozenset({"kill_host"}))]
    ) == []


@pytest.mark.quick
def test_slc004_docs_op_table_drift_fires():
    table = "| `kill_host` | device | quarantine |\n"
    out = contracts.audit_doc_op_table(
        table, "docs/x.md", frozenset({"kill_host", "skew_hosts"})
    )
    assert _slc_codes(out) == ["SLC004"]
    stale = table + "| `vanished_op` | device | gone |\n"
    out = contracts.audit_doc_op_table(
        stale, "docs/x.md", frozenset({"kill_host"})
    )
    assert _slc_codes(out) == ["SLC004"] and "vanished_op" in out[0].message


@pytest.mark.quick
def test_slc005_stale_doc_sample_and_test_literal_fire():
    md = (
        "```json\n"
        '{"kind": "shadow_tpu.metrics",\n'
        ' "schema_version": 11}\n'
        "```\n"
    )
    out = contracts.audit_doc_schema_versions(
        md, "docs/x.md", {"shadow_tpu.metrics": 12}
    )
    assert _slc_codes(out) == ["SLC005"]
    ok = md.replace("11", "12")
    assert contracts.audit_doc_schema_versions(
        ok, "docs/x.md", {"shadow_tpu.metrics": 12}
    ) == []
    # the test-literal arm: any hard-coded comparison is drift bait
    src = "def test_x(doc):\n    assert doc['schema_version'] == 11\n"
    out = contracts.audit_test_version_literals(src, "tests/test_x.py")
    assert _slc_codes(out) == ["SLC005"]
    helper = (
        "from shadow_tpu.obs.metrics import SCHEMA_VERSION\n"
        "def test_x(doc):\n"
        "    assert doc['schema_version'] == SCHEMA_VERSION\n"
    )
    assert contracts.audit_test_version_literals(
        helper, "tests/test_x.py") == []


@pytest.mark.quick
def test_slc006_config_spec_drift_fires():
    md = (
        "### `general`\n\n"
        "| field | default | meaning |\n|---|---|---|\n"
        "| `stop_time` | — | end |\n"
        "| `vanished` | — | stale |\n"
    )
    out = contracts.audit_config_spec(
        md, "docs/config_spec.md",
        fields_by_section={"general": {"stop_time", "seed"}},
        prose_documented={},
    )
    assert sorted(_slc_codes(out)) == ["SLC006", "SLC006"]
    texts = " ".join(f.message for f in out)
    assert "vanished" in texts and "seed" in texts
    ok = md.replace("| `vanished` | — | stale |\n",
                    "| `seed` | 1 | master seed |\n")
    assert contracts.audit_config_spec(
        ok, "docs/config_spec.md",
        fields_by_section={"general": {"stop_time", "seed"}},
        prose_documented={},
    ) == []


@pytest.mark.quick
def test_slc007_policy_set_drift_fires():
    src = 'if v not in ("wait", "cpu", "abort"):\n    raise ValueError(v)\n'
    out = contracts.audit_policy_sets(
        src, "cfg.py", ("wait", "cpu", "abort", "relayout")
    )
    assert _slc_codes(out) == ["SLC007"]
    ok = src.replace('"abort"', '"abort", "relayout"')
    assert contracts.audit_policy_sets(
        ok, "cfg.py", ("wait", "cpu", "abort", "relayout")) == []


@pytest.mark.quick
def test_slc008_plan_registry_drift_fires():
    out = contracts.audit_plan_registry(
        frozenset({"kill_host", "new_op"}), {"kill_host"}
    )
    assert _slc_codes(out) == ["SLC008"] and "new_op" in out[0].message
    out = contracts.audit_plan_registry(
        frozenset({"kill_host"}), {"kill_host", "dead_row"}
    )
    assert _slc_codes(out) == ["SLC008"] and "dead_row" in out[0].message
    assert contracts.audit_plan_registry(
        frozenset({"kill_host"}), {"kill_host"}) == []


@pytest.mark.quick
def test_slc009_journal_record_table_drift_fires():
    from shadow_tpu.serve import journal as journal_mod

    types = journal_mod.RECORD_TYPES
    doc = "## journal\n\n| type | when | payload |\n|---|---|---|\n"
    rows = doc + "".join(
        f"| `{t}` | trigger | payload |\n" for t in types
    )
    region = contracts.extract_journal_table_region(rows)
    # clean control: every registered type documented, no stale rows
    assert contracts.audit_journal_record_table(
        region, "docs/serving.md", types) == []
    # forged drift: drop the handoff row → missing-record finding
    missing = contracts.extract_journal_table_region(
        rows.replace(f"| `{journal_mod.HANDOFF}` | trigger | payload |\n",
                     ""))
    out = contracts.audit_journal_record_table(
        missing, "docs/serving.md", types)
    assert _slc_codes(out) == ["SLC009"]
    assert out[0].text == "record:handoff"
    # forged drift: a row naming an unregistered type → stale finding
    stale = contracts.extract_journal_table_region(
        rows + "| `ghost` | never | nothing |\n")
    out = contracts.audit_journal_record_table(
        stale, "docs/serving.md", types)
    assert _slc_codes(out) == ["SLC009"]
    assert out[0].text == "stale:ghost"


@pytest.mark.quick
def test_every_contract_rule_has_a_firing_fixture():
    import re as re_mod

    src = open(__file__, encoding="utf-8").read()
    covered = set(re_mod.findall(r"def test_(slc\d+)_", src))
    assert {c.lower() for c in contracts.CONTRACT_RULES} == covered


def test_contract_auditor_tree_is_clean():
    # the load-bearing gate: zero drift findings across the real tree
    out = contracts.audit_tree(REPO)
    assert not out, "cross-plane contract drift:\n" + "\n".join(
        f.render() for f in out)


# ---------------------------------------------------------------------------
# layer 4: the host-thread race lint (forged races per rule code)
# ---------------------------------------------------------------------------

_TH_PREAMBLE = "import signal\nimport threading\n\n"

# a class whose discipline is correct: every guarded access under the
# lock, handler touches only the Event, bounded acquire on the wake path
_TH_CLEAN = _TH_PREAMBLE + """\
class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = threading.Event()
        self.queue = []
        signal.signal(signal.SIGTERM, lambda *_: self.drain())

    def submit(self, x):
        with self._lock:
            self.queue.append(x)
            self._wake.notify_all()

    def worker(self):
        with self._lock:
            while not self.queue:
                self._wake.wait(timeout=0.25)
            return self.queue.pop(0)

    def drain(self):
        self._stop.set()
        if self._lock.acquire(timeout=1.0):
            try:
                self._wake.notify_all()
            finally:
                self._lock.release()
"""


@pytest.mark.quick
def test_thread_lint_clean_class_is_silent():
    assert threads.lint_threads_source(_TH_CLEAN, "serve/d.py") == []


@pytest.mark.quick
def test_sth001_unguarded_write_fires():
    src = _TH_CLEAN + """\

    def racy(self):
        self.queue.append(99)
"""
    out = threads.lint_threads_source(src, "serve/d.py")
    assert [f.code for f in out] == ["STH001"]
    assert "queue" in out[0].message


@pytest.mark.quick
def test_sth002_condition_wait_without_lock_fires():
    src = _TH_CLEAN + """\

    def impatient(self):
        self._wake.wait(timeout=1.0)
"""
    out = threads.lint_threads_source(src, "serve/d.py")
    assert [f.code for f in out] == ["STH002"]


@pytest.mark.quick
def test_sth003_handler_touching_shared_state_fires():
    src = _TH_PREAMBLE + """\
class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.state = {}
        signal.signal(signal.SIGTERM, lambda *_: self.on_term())

    def on_term(self):
        self._stop.set()
        self.state["dirty"] = True
"""
    out = threads.lint_threads_source(src, "serve/d.py")
    assert [f.code for f in out] == ["STH003"]


@pytest.mark.quick
def test_sth004_nonblocking_acquire_fires():
    src = _TH_PREAMBLE + """\
class Daemon:
    def __init__(self):
        self._lock = threading.Lock()

    def skippy(self):
        if self._lock.acquire(blocking=False):
            self._lock.release()
"""
    out = threads.lint_threads_source(src, "serve/d.py")
    assert [f.code for f in out] == ["STH004"]


@pytest.mark.quick
def test_thread_lint_locked_context_methods_are_not_flagged():
    # a method called ONLY under the lock may touch guarded state
    # lock-free itself (the daemon's retry_after_s idiom)
    src = _TH_CLEAN + """\

    def _depth(self):
        return len(self.queue)

    def info(self):
        with self._lock:
            return self._depth()
"""
    assert threads.lint_threads_source(src, "serve/d.py") == []


@pytest.mark.quick
def test_thread_lint_noqa_suppresses():
    src = _TH_CLEAN + """\

    def racy(self):
        self.queue.append(99)  # noqa: STH001
"""
    assert threads.lint_threads_source(src, "serve/d.py") == []


# the host plane's discipline in miniature (core/hostplane.py): one lock,
# per-worker partition queues + merge buffer guarded by it, a Condition
# on the same lock for the wake path
_TH_HOSTPLANE_CLEAN = _TH_PREAMBLE + """\
class Plane:
    def __init__(self, workers):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queues = [[] for _ in range(workers)]
        self._results = []
        self._pending = 0

    def submit(self, wid, action):
        with self._lock:
            self._queues[wid].append(action)
            self._pending += 1
            self._wake.notify_all()

    def worker(self, wid):
        with self._lock:
            while not self._queues[wid]:
                self._wake.wait(timeout=0.25)
            batch = self._queues[wid]
            self._queues[wid] = []
        done = [a() for a in batch]
        with self._lock:
            self._results.extend(done)
            self._pending -= len(done)
"""


@pytest.mark.quick
def test_thread_lint_hostplane_clean_discipline_is_silent():
    out = threads.lint_threads_source(
        _TH_HOSTPLANE_CLEAN, "core/hostplane.py")
    assert out == []


@pytest.mark.quick
def test_thread_lint_hostplane_partition_queue_race_fires():
    # the exact race the plane's discipline exists to prevent: the
    # coordinator growing the partition-queue table without the lock
    # while a worker may be swapping its list out under it
    src = _TH_HOSTPLANE_CLEAN + """\

    def racy_enqueue(self, action):
        self._queues.append([action])
"""
    out = threads.lint_threads_source(src, "core/hostplane.py")
    assert [f.code for f in out] == ["STH001"]
    assert "_queues" in out[0].message


@pytest.mark.quick
def test_every_thread_rule_has_a_firing_fixture():
    import re as re_mod

    src = open(__file__, encoding="utf-8").read()
    covered = set(re_mod.findall(r"def test_(sth\d+)_", src))
    assert {c.lower() for c in threads.THREAD_RULES} == covered


def test_thread_lint_tree_is_clean():
    # the load-bearing gate: the declared thread-bearing modules hold
    # their lock discipline (the daemon's drain-path smell is FIXED)
    out = threads.lint_threads_paths(REPO)
    assert not out, "host-thread race findings:\n" + "\n".join(
        f.render() for f in out)


# ---------------------------------------------------------------------------
# layer 5: the HLO budget ledger
# ---------------------------------------------------------------------------

_FORGED_LEDGER_HLO = "\n".join([
    "  %p0 = s64[4,256]{1,0} parameter(0)",
    "  %ag = s64[8,256]{1,0} all-gather(s64[4,256] %p0), dimensions={0}",
    "  %s1 = s64[4,100]{1,0} sort(s64[4,100] %a), dimensions={1}",
    "  %g = s64[8,2]{1,0} gather(s64[8,16]{1,0} %t, s32[8,2,2] %i), "
    "slice_sizes={1,1}",
    "  %cp = s64[4,256]{1,0} collective-permute(s64[4,256] %p0)",
])


@pytest.mark.quick
def test_hlo_budget_accounts_forged_program():
    b = hlo_audit.hlo_budget(_FORGED_LEDGER_HLO)
    assert b["collectives"] == {"all-gather": 1, "collective-permute": 1}
    assert b["sorts"] == 1 and b["sort_rows"] == 100
    assert b["gathers"] == 1 and b["serializing_gathers"] == 1
    assert b["scatters"] == 0
    assert b["param_bytes"] == 4 * 256 * 8
    assert b["largest_tensor_bytes"] == 8 * 256 * 8


@pytest.mark.quick
def test_ledger_diff_catches_regression_and_staleness():
    base = hlo_audit.hlo_budget(_FORGED_LEDGER_HLO)
    cur = json.loads(json.dumps(base))
    assert hlo_audit.diff_budget("cell", cur, base) == []
    # a NEW all-gather on the path: the mesh-regression class
    cur["collectives"]["all-gather"] += 1
    out = hlo_audit.diff_budget("cell", cur, base)
    assert len(out) == 1 and "NEW collective" in out[0]
    # sort-volume blowup inside the structural slack still diffs
    cur = json.loads(json.dumps(base))
    cur["sort_rows"] *= 2
    assert any("sort_rows" in p for p in
               hlo_audit.diff_budget("cell", cur, base))
    # byte proxies tolerate layout jitter, fail real growth
    cur = json.loads(json.dumps(base))
    cur["largest_tensor_bytes"] = int(base["largest_tensor_bytes"] * 1.1)
    assert hlo_audit.diff_budget("cell", cur, base) == []
    cur["largest_tensor_bytes"] = int(base["largest_tensor_bytes"] * 2)
    assert any("largest_tensor_bytes" in p for p in
               hlo_audit.diff_budget("cell", cur, base))


@pytest.mark.quick
def test_ledger_missing_entry_and_missing_baseline_are_loud(tmp_path):
    base = {"known/cell": hlo_audit.hlo_budget(_FORGED_LEDGER_HLO)}
    out = hlo_audit.check_ledger(
        {"new/cell": hlo_audit.hlo_budget(_FORGED_LEDGER_HLO)}, base
    )
    assert len(out) == 1 and "no ledger entry" in out[0]
    # baseline entries this environment cannot lower are skipped
    assert hlo_audit.check_ledger({}, base) == []
    with pytest.raises(hlo_audit.HloBaselineError, match="regenerate"):
        hlo_audit.load_hlo_baseline(str(tmp_path / "absent.json"))


def test_ledger_representative_cell_matches_baseline():
    """Tier-1 ledger gate: one representative cell lowers to EXACTLY its
    checked-in budget (the full matrix runs in the slow tier)."""
    baseline = hlo_audit.load_hlo_baseline()
    vs = hlo_audit.default_ledger_variants(include_mesh=False)
    v = next(x for x in vs if x.label == "global/conservative/gear0")
    cur = hlo_audit.hlo_budget(v.hlo())
    assert hlo_audit.diff_budget(v.label, cur, baseline[v.label]) == []


@pytest.mark.slow
def test_ledger_covers_every_variant_and_gates_mesh_all_gathers():
    """ISSUE 14 acceptance: the checked-in ledger covers every kernel
    variant hlo_audit lowers today (this process sees 8 virtual devices,
    so the mesh/shard_map cells lower too), every cell matches its
    budget, and the mesh hot path still compiles with ZERO all-gathers."""
    baseline = hlo_audit.load_hlo_baseline()
    vs = hlo_audit.default_ledger_variants(include_mesh=True)
    ledger = hlo_audit.budget_ledger(vs)
    assert set(ledger) == set(baseline)
    problems = hlo_audit.check_ledger(ledger, baseline)
    assert not problems, "\n".join(problems)
    mesh_async = [k for k in ledger if k.startswith("mesh/async/")]
    assert mesh_async
    for k in mesh_async:
        assert ledger[k]["collectives"].get("all-gather", 0) == 0, k
        assert ledger[k]["collectives"].get("collective-permute", 0) > 0, k


# ---------------------------------------------------------------------------
# CLI failure modes: exit 2 + a one-line remediation hint, never a traceback
# ---------------------------------------------------------------------------


def _shadowlint_main():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "shadowlint_cli", os.path.join(REPO, "tools", "shadowlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.quick
def test_cli_exit2_on_unparseable_source(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    rc = _shadowlint_main().main([str(bad)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "hint:" in err and "syntax" in err


@pytest.mark.quick
def test_cli_exit2_on_unknown_rule_code(capsys):
    rc = _shadowlint_main().main(["--select", "STL999"])
    assert rc == 2
    assert "hint:" in capsys.readouterr().err


@pytest.mark.quick
def test_cli_exit2_on_missing_hlo_baseline(tmp_path, capsys, monkeypatch):
    # the baseline loads BEFORE any variant compiles, so this is fast
    mod = _shadowlint_main()
    monkeypatch.setattr(
        hlo_audit, "baseline_path",
        lambda root=None: str(tmp_path / "absent.json"),
    )
    rc = mod.main(["--hlo"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "hint:" in err and "--write-hlo-baseline" in err


@pytest.mark.quick
def test_cli_json_reports_per_pass_counts(tmp_path, capsys):
    good = tmp_path / "ok.py"
    good.write_text("x = 1\n")
    rc = _shadowlint_main().main(
        [str(good), "--threads", "--format", "json"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0 and doc["ok"] is True
    assert doc["passes"] == {"lint": 0, "threads": 0}
    assert doc["schema_version"] == linter.REPORT_SCHEMA_VERSION
