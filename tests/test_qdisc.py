"""Per-interface scheduling plane (net/qdisc): ISSUE 19 acceptance.

- default-FIFO compat: the discipline-interface reroute of nic.py's send
  ring is bit-identical to pre-qdisc builds (audit chains pinned from a
  pre-PR capture of the SAME configs in this SAME 8-virtual-device CPU
  environment).
- PIFO/Eiffel properties: exact-PIFO rank order, the bucketed
  discipline's error bound (inversions only within one bucket width) and
  its exactness regime (bucket_width 1, rank spread < B → identical to
  exact PIFO).
- CoDel-as-drop-hook parity: the folded-in state machine driven against
  net/codel.py's router on the same schedule must make identical drop
  decisions.
- WFQ virtual-finish-time ordering, config validation, schema-v17
  artifact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.core.config import ConfigError, load_config
from shadow_tpu.net import codel, packet as pkt
from shadow_tpu.net.apps import locality_targets
from shadow_tpu.net.qdisc import drops, ranks
from shadow_tpu.net.qdisc.eiffel import EiffelDiscipline
from shadow_tpu.net.qdisc.pifo import PifoDiscipline
from shadow_tpu.sim import build_simulation
from tests._contracts import assert_current_metrics_schema

GML_LOOP = (
    'graph [ node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ] '
    'edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ] ]'
)
GML_2V = """
graph [
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "50 ms" packet_loss 0.0 ]
]
"""
# 400B datagram = 428B wire = ~34 ms at 100 Kbit, sent every 5 ms: the
# send queue absorbs a 7x overload (the queue-exercising workload)
GML_SLOW = (
    'graph [ node [ id 0 bandwidth_down "10 Mbit" bandwidth_up "100 Kbit" ] '
    'edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ] ]'
)


def _flood_cfg(qdisc=None, interface_qdisc=None):
    exp = {"event_capacity": 2048, "events_per_host_per_window": 8}
    if interface_qdisc:
        exp["interface_qdisc"] = interface_qdisc
    cfg = {
        "general": {"stop_time": 2, "seed": 6},
        "network": {"graph": {"type": "gml", "inline": GML_LOOP}},
        "experimental": exp,
        "hosts": {
            "server": {"app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 3, "app_model": "udp_flood",
                       "app_options": {"interval": "50 ms", "size": 400,
                                       "runtime": 1}},
        },
    }
    if qdisc:
        cfg["qdisc"] = qdisc
    return cfg


def _overload_cfg(qdisc=None, **exp):
    experimental = {"event_capacity": 4096, "events_per_host_per_window": 8}
    experimental.update(exp)
    cfg = {
        "general": {"stop_time": 3, "seed": 6},
        "network": {"graph": {"type": "gml", "inline": GML_SLOW}},
        "experimental": experimental,
        "hosts": {
            "server": {"app_model": "udp_flood",
                       "app_options": {"role": "server"},
                       "bandwidth_down": "10 Mbit",
                       "bandwidth_up": "10 Mbit"},
            "client": {"quantity": 3, "app_model": "udp_flood",
                       "app_options": {"interval": "5 ms", "size": 400,
                                       "runtime": 2}},
        },
    }
    if qdisc:
        cfg["qdisc"] = qdisc
    return cfg


def _chain(sim):
    return int(sim.audit_chain()), int(sim.counters()["events_committed"])


def _run(cfg):
    sim = build_simulation(cfg)
    sim.run()
    return sim


# ---------------------------------------------------------------------------
# default-arm compat: chains pinned from a pre-qdisc capture
# ---------------------------------------------------------------------------

# captured on the pre-PR tree (same configs, same virtual-device setup)
_GOLDEN_FLOOD = (8799656395028767596, 120)
_GOLDEN_ECHO = (13198824729964439556, 31)


def test_default_fifo_chain_matches_pre_qdisc_capture():
    assert _chain(_run(_flood_cfg())) == _GOLDEN_FLOOD
    assert _chain(_run(_flood_cfg(interface_qdisc="fifo"))) == _GOLDEN_FLOOD
    assert _chain(
        _run(_flood_cfg(qdisc={"discipline": "fifo"}))
    ) == _GOLDEN_FLOOD


def test_default_roundrobin_chain_matches_pre_qdisc_capture():
    assert _chain(
        _run(_flood_cfg(interface_qdisc="roundrobin"))
    ) == _GOLDEN_FLOOD
    assert _chain(
        _run(_flood_cfg(qdisc={"discipline": "roundrobin"}))
    ) == _GOLDEN_FLOOD


def test_udp_echo_chain_matches_pre_qdisc_capture():
    cfg = {
        "general": {"stop_time": 4, "seed": 5},
        "network": {"graph": {"type": "gml", "inline": GML_2V}},
        "experimental": {"event_capacity": 4096,
                         "events_per_host_per_window": 8},
        "hosts": {
            "server": {"network_node_id": 0, "app_model": "udp_echo",
                       "app_options": {"role": "server"}},
            "client": {"network_node_id": 1, "app_model": "udp_echo",
                       "app_options": {"interval": "200 ms", "runtime": 2,
                                       "size": 512}},
        },
    }
    assert _chain(_run(cfg)) == _GOLDEN_ECHO


# ---------------------------------------------------------------------------
# discipline unit harness (no engine: drive the Discipline interface)
# ---------------------------------------------------------------------------


class _State:
    """Minimal SimState stand-in: the subs dict + with_sub."""

    def __init__(self, subs):
        self.subs = subs

    def with_sub(self, key, val):
        subs = dict(self.subs)
        subs[key] = val
        return _State(subs)


class _Stack:
    num_hosts = 1
    payload_words = 12
    sockets_per_host = 8


def _mk(disc):
    disc.attach(_Stack())
    return _State(disc.init_subs())


def _payload(priority=0, size=100, socket=0, port=0):
    return pkt.make_udp(
        src_port=jnp.array([40000 + port], jnp.int32),
        dst_port=jnp.array([9000], jnp.int32),
        length=jnp.array([size], jnp.int32),
        priority=jnp.array([priority], jnp.int32),
        src_host=jnp.array([0], jnp.int32),
        socket_slot=jnp.array([socket], jnp.int32),
        payload_words=12,
    )


_ON = jnp.array([True])
_DST = jnp.array([0], jnp.int32)


def _t(ns):
    return jnp.array([ns], jnp.int64)


def _drain(disc, st, now):
    """Pop until empty; return the served packets' priority words."""
    out = []
    while bool(disc.nonempty(st)[0]):
        st, have, payload, _dst = disc.dequeue(st, _t(now), _ON)
        if bool(have[0]):
            out.append(int(payload[0, pkt.W_PRIORITY]))
    return st, out


def test_exact_pifo_serves_rank_order_stably():
    disc = PifoDiscipline(queue_slots=16, ranker=ranks.PrioRank())
    st = _mk(disc)
    prios = [5, 1, 9, 1, 3, 9, 0, 5]
    for i, p in enumerate(prios):
        st, ok = disc.enqueue(st, _ON, _DST, _payload(priority=p, port=i),
                              _t(1000 + i))
        assert bool(ok[0])
    st, served = _drain(disc, st, 2000)
    assert served == sorted(prios)
    qd = st.subs["qdisc"]
    assert int(qd["enqueues"][0]) == len(prios)
    assert int(qd["dequeues"][0]) == len(prios)
    assert int(qd["depth_peak"][0]) == len(prios)


def test_eiffel_exact_regime_matches_pifo_order():
    # bucket_width 1 and rank spread < B: the bucket scan is exact
    prios = [5, 1, 9, 1, 3, 9, 0, 5]
    for mk in (
        lambda: PifoDiscipline(queue_slots=16, ranker=ranks.PrioRank()),
        lambda: EiffelDiscipline(queue_slots=16, buckets=16,
                                 bucket_width=1, ranker=ranks.PrioRank()),
    ):
        disc = mk()
        st = _mk(disc)
        for i, p in enumerate(prios):
            st, _ok = disc.enqueue(
                st, _ON, _DST, _payload(priority=p, port=i), _t(1000 + i)
            )
        _st, served = _drain(disc, st, 2000)
        assert served == sorted(prios), disc.name


def test_eiffel_ordering_error_bounded_by_bucket_width():
    width = 4
    disc = EiffelDiscipline(queue_slots=32, buckets=8, bucket_width=width,
                            ranker=ranks.PrioRank())
    st = _mk(disc)
    prios = [13, 2, 27, 6, 2, 19, 30, 11, 0, 25, 8, 15]  # spread < B*width
    for i, p in enumerate(prios):
        st, _ok = disc.enqueue(
            st, _ON, _DST, _payload(priority=p, port=i), _t(1000 + i)
        )
    _st, served = _drain(disc, st, 2000)
    assert sorted(served) == sorted(prios)
    # any inversion pair sits in the same bucket: rank gap < bucket width
    for a in range(len(served)):
        for b in range(a + 1, len(served)):
            if served[a] > served[b]:
                assert served[a] - served[b] < width, served


def test_wfq_virtual_finish_times_interleave_by_weight():
    # class 1 carries 4x the weight of class 0: per-byte virtual-time
    # cost is 4x smaller, so its finish times advance 4x slower
    r = ranks.WfqRank(classes=2, weights=[1.0, 4.0])
    disc = PifoDiscipline(queue_slots=32, ranker=r)
    st = _mk(disc)
    for i in range(8):
        st, _ok = disc.enqueue(
            st, _ON, _DST,
            _payload(priority=10 + i, size=256, socket=i % 2, port=i),
            _t(1000 + i),
        )
    qd = st.subs["qdisc"]
    fin = np.asarray(qd["finish"][0])
    # 4 packets each; class 0 accumulated 4x the virtual time of class 1
    assert fin[0] == 4 * fin[1] > 0
    _st, served = _drain(disc, st, 2000)
    # heavier class drains sooner: among the first half of services,
    # class-1 packets (odd sockets -> odd priorities here) dominate
    first_half = served[: len(served) // 2]
    cls1 = sum(1 for p in first_half if (p - 10) % 2 == 1)
    assert cls1 >= 3, served


def test_shaping_defers_rank_eligibility():
    # class 0 shaped to 1 Mbit: 128B packets are eligible 1024000 ns
    # apart; unshaped packets keep rank 0 and overtake deferred ones
    r = ranks.FifoRank(classes=2, shaping={0: 1_000_000})
    disc = PifoDiscipline(queue_slots=8, ranker=r)
    st = _mk(disc)
    st, _ok = disc.enqueue(st, _ON, _DST,
                           _payload(priority=1, size=128, socket=0),
                           _t(1000))
    st, _ok = disc.enqueue(st, _ON, _DST,
                           _payload(priority=2, size=128, socket=0),
                           _t(1001))
    st, _ok = disc.enqueue(st, _ON, _DST,
                           _payload(priority=3, size=128, socket=1),
                           _t(1002))
    qd = st.subs["qdisc"]
    rank = np.asarray(qd["q_rank"][0][: 3])
    # unshaped class-1 packet (rank 0) heads the queue; the second
    # class-0 packet is deferred one token-bucket interval after the first
    assert rank[0] == 0
    assert rank[2] - rank[1] == (pkt.UDP_HEADER_BYTES + 128) * (
        ranks.simtime.NS_PER_SEC * 8 // 1_000_000
    )


def test_red_drops_deterministically_between_thresholds():
    red = drops.RedConfig(queue_slots=16, min_frac=0.0, max_frac=0.5,
                          max_p=1.0)
    disc = PifoDiscipline(queue_slots=16, drop="red", red=red)
    st = _mk(disc)
    dropped = 0
    for i in range(16):
        st, ok = disc.enqueue(st, _ON, _DST, _payload(port=i), _t(1000 + i))
        dropped += int(not bool(ok[0]))
    qd = st.subs["qdisc"]
    assert int(qd["drops_red"][0]) == dropped > 0
    assert int(qd["drops_overflow"][0]) == 0
    # rerun: the deterministic schedule reproduces exactly
    disc2 = PifoDiscipline(queue_slots=16, drop="red", red=red)
    st2 = _mk(disc2)
    dropped2 = 0
    for i in range(16):
        st2, ok = disc2.enqueue(st2, _ON, _DST, _payload(port=i),
                                _t(1000 + i))
        dropped2 += int(not bool(ok[0]))
    assert dropped2 == dropped


def test_codel_drop_hook_parity_with_router():
    """The folded-in CoDel state machine against net/codel.py's router on
    an identical schedule: same packets served, same drop counts, same
    controller state at every step."""
    H = 1
    router = codel.init(H, queue_slots=32, payload_words=12)
    disc = PifoDiscipline(queue_slots=32, drop="codel")
    st = _mk(disc)
    on = jnp.ones((H,), bool)
    src = jnp.zeros((H,), jnp.int32)

    ms = 1_000_000
    # a sojourn-bloating schedule: a burst, then slow service (sojourn
    # crosses TARGET and stays there past INTERVAL -> drop mode), then a
    # second burst during drop mode
    schedule = [("enq", t * ms) for t in range(0, 24, 2)]
    schedule += [("deq", 130 * ms + t * 40 * ms) for t in range(12)]
    schedule += [("enq", 700 * ms + t * ms) for t in range(8)]
    schedule += [("deq", 900 * ms + t * 60 * ms) for t in range(12)]

    served_r, served_q = [], []
    for i, (op, t) in enumerate(schedule):
        now = jnp.full((H,), t, jnp.int64)
        if op == "enq":
            payload = _payload(size=1200, port=i)
            router = codel.enqueue(router, on, payload, src, now)
            st, _ok = disc.enqueue(st, on, src, payload, now)
        else:
            router, have_r, pay_r, _src = codel.dequeue(router, now, on)
            st, have_q, pay_q, _dst = disc.dequeue(st, now, on)
            if bool(have_r[0]):
                served_r.append(int(pay_r[0, pkt.W_SRC_PORT]))
            if bool(have_q[0]):
                served_q.append(int(pay_q[0, pkt.W_SRC_PORT]))
            qd = st.subs["qdisc"]
            # controller state tracks in lockstep
            assert bool(router.drop_mode[0]) == bool(qd["drop_mode"][0])
            assert int(router.drop_count[0]) == int(qd["drop_count"][0])
            assert int(router.next_drop[0]) == int(qd["next_drop"][0])
            assert int(router.interval_expire[0]) == int(
                qd["interval_expire"][0]
            )
    assert served_r == served_q
    qd = st.subs["qdisc"]
    assert int(qd["drops_codel"][0]) == int(router.codel_dropped) > 0


# ---------------------------------------------------------------------------
# engine-level: the overload workload across disciplines
# ---------------------------------------------------------------------------


def test_eiffel_matches_pifo_chains_and_counters_in_exact_regime():
    pifo_sim = _run(_overload_cfg({"discipline": "pifo",
                                   "queue_slots": 32}))
    eiffel_sim = _run(_overload_cfg({"discipline": "eiffel",
                                     "queue_slots": 32, "buckets": 8}))
    assert _chain(pifo_sim) == _chain(eiffel_sim)
    qp = jax.device_get(pifo_sim.state.subs["qdisc"])
    qe = jax.device_get(eiffel_sim.state.subs["qdisc"])
    for k in ("enqueues", "dequeues", "drops_overflow", "drops_red",
              "drops_codel", "sojourn_sum", "depth_peak", "q_bytes"):
        assert (np.asarray(qp[k]) == np.asarray(qe[k])).all(), k
    assert int(np.sum(qp["enqueues"])) > 0
    assert int(np.sum(qp["drops_overflow"])) > 0


def test_qdisc_metrics_schema_v17_artifact(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    sim = _run(_overload_cfg({"discipline": "pifo", "rank": "wfq",
                              "drop": "codel", "queue_slots": 32}))
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"),
                               meta={"stage": "test_qdisc"})
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert doc["counters"]["qdisc.enqueues"] > 0
    assert doc["counters"]["qdisc.dequeues"] > 0
    assert doc["counters"]["qdisc.drops_codel"] > 0
    assert doc["gauges"]["qdisc.sojourn_mean_ns"] > 0
    # FIFO runs carry no qdisc sub and emit no qdisc.* keys
    fifo_sim = _run(_flood_cfg())
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_device(fifo_sim, reg)
    assert not any(k.startswith("qdisc.") for k in reg.counters)


def test_checkpoint_roundtrip_carries_qdisc_plane(tmp_path):
    # qdisc rings are ordinary SimState pytree leaves: save/load restores
    # the queue plane and the resumed run reproduces the one-shot chain
    sim = build_simulation(_overload_cfg({"discipline": "pifo",
                                          "queue_slots": 32}))
    sim.run()
    want = _chain(sim)
    qd_want = jax.device_get(sim.state.subs["qdisc"])

    path = str(tmp_path / "ck.npz")
    sim2 = build_simulation(_overload_cfg({"discipline": "pifo",
                                           "queue_slots": 32}))
    sim2.run(until=1_500_000_000)
    sim2.save_checkpoint(path)
    sim3 = build_simulation(_overload_cfg({"discipline": "pifo",
                                           "queue_slots": 32}))
    sim3.load_checkpoint(path)
    sim3.run()
    assert _chain(sim3) == want
    qd_got = jax.device_get(sim3.state.subs["qdisc"])
    for k in qd_want:
        assert (np.asarray(qd_want[k]) == np.asarray(qd_got[k])).all(), k


# ---------------------------------------------------------------------------
# satellites: locality targets, config validation
# ---------------------------------------------------------------------------


def test_locality_targets_prefers_nearest_anchor_within_span():
    tgt = locality_targets(8, [2, 6], 1)
    # within one hop of an anchor -> that anchor; others round-robin
    assert tgt[1] == 2 and tgt[2] == 2 and tgt[3] == 2
    assert tgt[5] == 6 and tgt[6] == 6 and tgt[7] == 6
    assert tgt[0] == 2 and tgt[4] == 2  # round-robin fallback (i % 2)
    # span 0 is the classic round-robin spread
    assert list(locality_targets(6, [0, 3], 0)) == [0, 3, 0, 3, 0, 3]
    # circular distance: host 7 is 1 hop from anchor 0 on an 8-ring
    assert locality_targets(8, [0], 1)[7] == 0


def test_udp_flood_local_span_shapes_fan_in():
    cfg = _flood_cfg()
    cfg["hosts"]["client"]["app_options"]["local_span"] = 1
    sim = build_simulation(cfg)
    sub = jax.device_get(sim.state.subs["udp_flood"])
    # hosts sort as client1, client2, client3, server (index 3): only
    # clients within 1 ring hop of the server target it here — and all
    # do, because every other row IS within span or falls back to it
    assert (np.asarray(sub["target"]) == 3).all()
    sim.run()
    assert int(jax.device_get(
        sim.state.subs["udp_flood"])["recv"][3]) > 0


def test_qdisc_config_validation():
    with pytest.raises(ConfigError, match="discipline"):
        load_config(_flood_cfg(qdisc={"discipline": "cake"}))
    with pytest.raises(ConfigError, match="rank"):
        load_config(_flood_cfg(qdisc={"discipline": "pifo",
                                      "rank": "lstf"}))
    with pytest.raises(ConfigError, match="weights"):
        load_config(_flood_cfg(qdisc={"discipline": "pifo", "rank": "wfq",
                                      "classes": 2, "weights": [1.0]}))
    with pytest.raises(ConfigError, match="out of range"):
        load_config(_flood_cfg(qdisc={"discipline": "pifo", "classes": 2,
                                      "overrides": {"client": 5}}))
    with pytest.raises(ConfigError, match="red"):
        load_config(_flood_cfg(qdisc={"discipline": "pifo", "drop": "red",
                                      "red_min_frac": 0.9,
                                      "red_max_frac": 0.2}))
    with pytest.raises(ConfigError, match="requires discipline"):
        load_config(_flood_cfg(qdisc={"discipline": "fifo",
                                      "drop": "codel"}))
    cfg = load_config(_flood_cfg(qdisc={
        "discipline": "eiffel", "rank": "wfq", "classes": 2,
        "weights": [1, 3], "shaping": {0: "10 Mbit"}, "drop": "red",
        "overrides": {"client": 1},
    }))
    assert cfg.qdisc.shaping == {0: 10_000_000}
    assert cfg.qdisc.overrides == {"client": 1}


def test_host_class_override_pins_flow_class():
    cfg = _overload_cfg({
        "discipline": "pifo", "rank": "wfq", "classes": 2,
        "weights": [1, 8], "overrides": {"client": 1},
    })
    sim = build_simulation(cfg)
    cls = np.asarray(jax.device_get(sim.state.subs["qdisc"]["cls"]))
    # hosts sort client1..client3, server: clients pinned to class 1,
    # the server unpinned (per-socket classing)
    assert list(cls) == [1, 1, 1, -1]
    sim.run()
    assert int(jax.device_get(
        sim.state.subs["qdisc"])["enqueues"].sum()) > 0


def test_shipped_scenario_configs_expand_and_run():
    import pathlib

    import yaml

    from shadow_tpu.fleet import expand_sweep

    root = pathlib.Path(__file__).parent.parent / "configs"
    for name in ("incast.yaml", "bufferbloat.yaml"):
        doc = yaml.safe_load((root / name).read_text())
        jobs = expand_sweep(doc)
        assert len(jobs) == 4 and all(
            j.config.get("qdisc") for j in jobs
        ), name
    # the incast job runs end-to-end with live queue pressure and the
    # locality-shaped fan-in (all 8 workers within span of the aggregator)
    doc = yaml.safe_load((root / "incast.yaml").read_text())
    sim = build_simulation(expand_sweep(doc)[0].config)
    tgt = np.asarray(jax.device_get(sim.state.subs["udp_flood"]["target"]))
    role = np.asarray(jax.device_get(sim.state.subs["udp_flood"]["role"]))
    agg = int(np.flatnonzero(role == 0)[0])
    assert (tgt == agg).all()
    sim.run()
    qd = jax.device_get(sim.state.subs["qdisc"])
    assert int(np.sum(qd["enqueues"])) > 0
    assert int(np.sum(qd["drops_red"])) > 0
    assert int(sim.counters()["events_committed"]) > 0


# ---------------------------------------------------------------------------
# driver matrix (compile-heavy: slow tier; the bench gate runs it too)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_pifo_chain_identical_across_drivers():
    from shadow_tpu.fleet import JobSpec, build_fleet

    q = {"discipline": "pifo", "rank": "wfq", "drop": "codel",
         "queue_slots": 32}
    want = _chain(_run(_overload_cfg(q)))

    opt = build_simulation(_overload_cfg(q))
    opt.run_optimistic()
    assert _chain(opt) == want

    isl = build_simulation(_overload_cfg(q, num_shards=2,
                                         exchange_slots=16))
    isl.run()
    assert _chain(isl) == want

    fl = build_fleet([JobSpec("a", _overload_cfg(q)),
                      JobSpec("b", _overload_cfg(q))], lanes=2)
    fl.run()
    rows = {r["name"]: (r["audit"]["chain"], r["events_committed"])
            for r in fl.results()}
    assert rows["a"] == want and rows["b"] == want
