"""tgen-like multi-stream transfer workload over device TCP (reference
analog: src/test/tor/minimal — tgen client/server pairs, verified by
grepping stream-success counts, verify.sh:7-22). Real managed processes;
every byte rides the device TCP machine."""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)


def _yaml(app, n_servers, n_clients, streams, nbytes, stop="12 s"):
    return f"""
general:
  stop_time: {stop}
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss 0.001 ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: 16384
  events_per_host_per_window: 8
  sockets_per_host: 48
hosts:
  srv:
    quantity: {n_servers}
    processes:
      - path: {app}
        args: --server 9100 0
        stop_time: 10 s
  cli:
    quantity: {n_clients}
    processes:
      - path: {app}
        args: srv {n_servers} 9100 {streams} {nbytes}
        start_time: 1 s
"""


def test_tgen_multistream_all_succeed(apps):
    """36 clients x 2 sequential 8 KiB downloads from 4 servers, all over
    the device TCP machine: 100% stream success, grep-verified like the
    reference's tor test."""
    n_cli, streams = 36, 2
    d = build_process_driver(_yaml(apps["tgen_like"], 4, n_cli, streams, 8192))
    d.run()
    clients = [p for p in d.procs if "--server" not in p.args]
    assert len(clients) == n_cli
    success = 0
    for p in clients:
        out = p.stdout.decode()
        assert p.exit_code == 0, (p.name, out, p.stderr)
        assert f"transfers-complete {streams}" in out
        success += out.count("stream-success")
    assert success == n_cli * streams  # 72/72, the verify.sh-style gate
    # device actually carried it
    c = d.bridge.sim.counters()
    assert c["packets_delivered"] > n_cli * streams * 5


def test_tgen_deterministic_rerun(apps):
    def run_once():
        d = build_process_driver(
            _yaml(apps["tgen_like"], 2, 6, 2, 4096, stop="60 s")
        )
        d.run()
        return sorted(p.stdout for p in d.procs)

    assert run_once() == run_once()
