"""Shared cross-plane contract helpers for the test suite.

Before the contract auditor landed, every metrics schema bump hand-edited
`assert doc["schema_version"] == N` in six test files (and whichever one
was missed shipped stale).  Tests assert against the SOURCE constants
through these helpers instead; `analysis/contracts.py` rule SLC005 flags
any hard-coded literal comparison that creeps back in.
"""

from shadow_tpu.obs.metrics import DOC_KIND, SCHEMA_VERSION


def assert_current_metrics_schema(doc: dict) -> None:
    """The document is a current-schema metrics dump (kind + version
    match the obs/metrics.py source constants)."""
    assert doc.get("kind") == DOC_KIND, doc.get("kind")
    assert doc.get("schema_version") == SCHEMA_VERSION, (
        doc.get("schema_version"), SCHEMA_VERSION,
    )
