"""Observability: logger, per-host trackers (both planes), pcap capture.

Reference analogs: logger.h levels / shadow_logger.rs record shape (§5.5),
tracker.c per-host byte accounting (§5.1), pcap_writer.c captures readable
by wireshark (network_interface.c:438-440).
"""

import io
import struct

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.utils import log as log_mod
from shadow_tpu.utils.pcap import PcapWriter

NS_PER_SEC = 1_000_000_000


def test_logger_levels_and_format():
    buf = io.StringIO()
    lg = log_mod.SimLogger(stream=buf, level=log_mod.INFO)
    lg.sim_now_fn = lambda: 5 * NS_PER_SEC + 1_000
    lg.debug("hidden")
    lg.info("visible %d", 42, host="peer1")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "visible 42" in out
    assert "[info]" in out
    assert "[peer1]" in out
    assert "00:00:05.000001" in out  # sim time stamp


def test_logger_parse_level():
    assert log_mod.parse_level("TRACE") == log_mod.TRACE
    with pytest.raises(ValueError):
        log_mod.parse_level("loud")


def test_logger_panic_raises():
    lg = log_mod.SimLogger(stream=io.StringIO())
    with pytest.raises(RuntimeError, match="boom"):
        lg.panic("boom")


def _parse_pcap(path):
    raw = open(path, "rb").read()
    magic, _maj, _min, _tz, _sf, _snap, link = struct.unpack(
        "<IHHiIII", raw[:24]
    )
    assert magic == 0xA1B2C3D4
    off = 24
    pkts = []
    while off < len(raw):
        sec, usec, caplen, origlen = struct.unpack("<IIII", raw[off:off + 16])
        off += 16
        pkts.append((sec * 1_000_000 + usec, raw[off:off + caplen]))
        off += caplen
    return link, pkts


def test_pcap_writer_roundtrip(tmp_path):
    p = tmp_path / "t.pcap"
    with PcapWriter(str(p)) as w:
        w.write_packet(
            1_500_000_000, proto="udp", src_ip=0x0B000001, src_port=9000,
            dst_ip=0x0B000002, dst_port=1234, payload=b"hello",
        )
        w.write_packet(
            2_000_000_000, proto="tcp", src_ip=0x0B000002, src_port=1234,
            dst_ip=0x0B000001, dst_port=9000, payload=b"x" * 100,
            seq=7, ack=3,
        )
    link, pkts = _parse_pcap(str(p))
    assert link == 101  # LINKTYPE_RAW
    assert len(pkts) == 2
    ts, ip = pkts[0]
    assert ts == 1_500_000
    assert ip[0] == 0x45  # IPv4, IHL 5
    assert ip[9] == 17  # UDP
    assert ip[-5:] == b"hello"
    src_port, dst_port = struct.unpack(">HH", ip[20:24])
    assert (src_port, dst_port) == (9000, 1234)
    _, tcp = pkts[1]
    assert tcp[9] == 6  # TCP
    seq = struct.unpack(">I", tcp[24:28])[0]
    assert seq == 7


@pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)
def test_driver_tracker_and_pcap(tmp_path, apps):
    """Managed-process plane: per-host tracker counts and pcap capture of a
    3-ping UDP echo exchange."""
    from shadow_tpu.procs.driver import ProcessDriver

    d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=10_000_000)
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    hc.pcap_dir = str(tmp_path / "pcap")
    d.add_process(hs, [apps["udp_echo_server"], "9000", "3"])
    d.add_process(hc, [apps["udp_echo_client"], "server", "9000", "3"],
                  start_time=NS_PER_SEC)
    d.run()
    t = d.host_trackers()
    # client sends 3 pings, receives 3 echoes; server mirrors
    assert t["client"]["tx_packets"] == 3
    assert t["client"]["rx_packets"] == 3
    assert t["server"]["rx_packets"] == 3
    assert t["server"]["tx_packets"] == 3
    assert t["client"]["tx_bytes"] == t["server"]["rx_bytes"] > 0
    link, pkts = _parse_pcap(str(tmp_path / "pcap" / "client.pcap"))
    assert len(pkts) == 6  # 3 tx + 3 rx at the client
    # capture timestamps are sim time: first ping at t=1s exactly
    assert pkts[0][0] == 1_000_000


def test_device_tracker_counts():
    """Device plane: per-host NIC tracker arrays line up with the scalar
    delivery counters."""
    from shadow_tpu.sim import build_simulation

    yaml = """
general:
  stop_time: 3
  seed: 2
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 2048
  events_per_host_per_window: 8
hosts:
  server:
    app_model: udp_flood
    app_options: {role: server}
  client:
    quantity: 3
    app_model: udp_flood
    app_options: {interval: "100 ms", size: 600, runtime: 1}
"""
    sim = build_simulation(yaml)
    sim.run()
    t = sim.host_trackers()
    c = sim.counters()
    assert int(t["tx_packets"].sum()) > 0
    assert int(t["rx_packets"].sum()) == c["packets_delivered"]
    # hosts are name-sorted: client1..client3 then server; clients only send
    assert all(int(x) == 0 for x in t["rx_packets"][:3])
    assert int(t["tx_packets"][3]) == 0
    assert t["rx_bytes"][3] > 0


def test_parse_sim_log_tool():
    """tools/parse_sim_log.py digests logger output into structured JSON
    (reference analog: src/tools/parse-shadow.py)."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "parse_sim_log",
        pathlib.Path(__file__).parent.parent / "tools" / "parse_sim_log.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    lines = [
        "heartbeat: sim 2.000s, 53 syscalls, 4 packets, wall 0.2s",
        "00:00:01.324576 00:00:02.000000 [debug] [client] tracker: "
        "tx 2 pkts / 12 B, rx 3 pkts / 14 B, 1 dropped",
        "00:00:01.324824 00:00:02.100000 [debug] [client] process client.0 "
        "exited with 0",
        "00:00:00.725606 00:00:02.100000 [debug] syscall counts: read:8 "
        "resolve_name:1",
        "00:00:00.8 00:00:02.2 [warning] [srv] something odd",
    ]
    doc = mod.parse(lines)
    assert doc["heartbeats"] == [{"sim_s": 2.0, "count": 53}]
    t = doc["trackers"]["client"][0]
    assert (t["tx_packets"], t["rx_packets"], t["dropped_packets"]) == (2, 3, 1)
    assert t["sim_s"] == 2.0
    assert doc["process_exits"][0]["exit_code"] == 0
    assert doc["syscall_counts"] == {"read": 8, "resolve_name": 1}
    assert doc["warnings"][0]["level"] == "warning"


def test_packet_breadcrumb_trails():
    """Per-packet delivery-status trails (packet.c:37-77 PDS_* analog,
    VERDICT r2 #10): with experimental.packet_trails, a dropped packet's
    ordered stage chain (CREATED -> ... -> DROPPED@cause) is
    reconstructable from the drop registers, and deliveries record their
    full chain too."""
    import jax

    from shadow_tpu.net import codel as codel_mod
    from shadow_tpu.net import packet as pkt
    from shadow_tpu.net import pds as pds_mod
    from shadow_tpu.sim import build_simulation

    # 800 kbit downlink + 4 clients pushing 1 KiB every 5 ms = ~6.5 Mbit
    # offered: the server's router queue builds standing delay -> CoDel
    # drops; 2% path loss also exercises the loss-drop register.
    cfg = {
        "general": {"stop_time": 4, "seed": 11},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "800 Kbit" '
            'bandwidth_up "20 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "10 ms" '
            'packet_loss 0.02 ]\n]\n')}},
        "experimental": {"event_capacity": 8192,
                         "events_per_host_per_window": 16,
                         "packet_trails": True,
                         "router_queue_slots": 32},
        "hosts": {
            "server": {"quantity": 1, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 4, "app_model": "udp_flood",
                       "app_options": {"interval": "5 ms", "size": 1024,
                                       "runtime": 2}},
        },
    }
    sim = build_simulation(cfg)
    sim.run()
    r = jax.device_get(sim.state.subs[codel_mod.SUB])
    assert int(r.codel_dropped) > 0, "workload must force CoDel drops"
    # the server (host index of role=server) recorded the dropped packet's
    # full chain in order
    si = [i for i, h in enumerate(sim.config.hosts)
          if h.app_options.get("role") == "server"][0]
    trail = pkt.decode_trail(int(r.drop_trail[si]))
    assert trail == ["CREATED", "SENT", "ROUTER_ENQUEUED", "DROPPED_CODEL"], \
        trail
    assert int(r.drop_time[si]) > 0
    # loss drops recorded with their chain + cause
    p = jax.device_get(sim.state.subs[pds_mod.SUB])
    c = sim.counters()
    assert c["packets_dropped_loss"] > 0
    loss_hosts = [h for h in range(5) if p["drop_count"][h] > 0]
    assert loss_hosts, "loss drops must hit the registers"
    lt = pkt.decode_trail(int(p["drop_trail"][loss_hosts[0]]))
    assert lt[-1] in ("DROPPED_LOSS", "DROPPED_SENDQ", "DROPPED_OVERFLOW"), lt
    assert lt[0] == "CREATED"
    # delivered packets' chains end in DELIVERED
    dt = pkt.decode_trail(int(p["deliver_trail"][si]))
    assert dt[0] == "CREATED" and dt[-1] == "DELIVERED", dt
    # report helper decodes
    rep = pds_mod.drop_report(sim)
    assert rep and all("trail" in e for e in rep)
