"""Observability: logger, per-host trackers (both planes), pcap capture.

Reference analogs: logger.h levels / shadow_logger.rs record shape (§5.5),
tracker.c per-host byte accounting (§5.1), pcap_writer.c captures readable
by wireshark (network_interface.c:438-440).
"""

import io
import struct

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.utils import log as log_mod
from shadow_tpu.utils.pcap import PcapWriter

NS_PER_SEC = 1_000_000_000


def test_logger_levels_and_format():
    buf = io.StringIO()
    lg = log_mod.SimLogger(stream=buf, level=log_mod.INFO)
    lg.sim_now_fn = lambda: 5 * NS_PER_SEC + 1_000
    lg.debug("hidden")
    lg.info("visible %d", 42, host="peer1")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "visible 42" in out
    assert "[info]" in out
    assert "[peer1]" in out
    assert "00:00:05.000001" in out  # sim time stamp


def test_logger_parse_level():
    assert log_mod.parse_level("TRACE") == log_mod.TRACE
    with pytest.raises(ValueError):
        log_mod.parse_level("loud")


def test_logger_panic_raises():
    lg = log_mod.SimLogger(stream=io.StringIO())
    with pytest.raises(RuntimeError, match="boom"):
        lg.panic("boom")


def test_logger_literal_percent_never_crashes():
    """A literal '%' in the message must never raise: no-args messages go
    out verbatim, mismatched format args fall back to being appended."""
    buf = io.StringIO()
    lg = log_mod.SimLogger(stream=buf, level=log_mod.INFO)
    lg.info("queue 50% full")  # no args: no formatting applied
    lg.info("fetching http://x/?a=%b0")  # '%b' is not a format code
    lg.info("queue 50% full on %s", "peer2")  # '% f' breaks the format
    lg.warning("count %d of %d", 3)  # too few args
    out = buf.getvalue()
    assert "queue 50% full" in out
    assert "http://x/?a=%b0" in out
    assert "peer2" in out  # mismatched args appended, not lost
    assert "3" in out
    with pytest.raises(RuntimeError):
        lg.panic("dying at 99% with %s", "x", "y")  # must still raise


def _parse_pcap(path):
    """Classic pcap reader for both timestamp magics (pcap.MAGIC_USEC /
    MAGIC_NSEC); packet timestamps come back in NANOSECONDS either way."""
    raw = open(path, "rb").read()
    magic, _maj, _min, _tz, _sf, _snap, link = struct.unpack(
        "<IHHiIII", raw[:24]
    )
    assert magic in (0xA1B2C3D4, 0xA1B23C4D)
    frac_ns = 1 if magic == 0xA1B23C4D else 1_000
    off = 24
    pkts = []
    while off < len(raw):
        sec, frac, caplen, origlen = struct.unpack("<IIII", raw[off:off + 16])
        off += 16
        pkts.append(
            (sec * 1_000_000_000 + frac * frac_ns, raw[off:off + caplen])
        )
        off += caplen
    return link, pkts


def test_pcap_writer_roundtrip(tmp_path):
    p = tmp_path / "t.pcap"
    with PcapWriter(str(p)) as w:
        w.write_packet(
            1_500_000_000, proto="udp", src_ip=0x0B000001, src_port=9000,
            dst_ip=0x0B000002, dst_port=1234, payload=b"hello",
        )
        w.write_packet(
            2_000_000_000, proto="tcp", src_ip=0x0B000002, src_port=1234,
            dst_ip=0x0B000001, dst_port=9000, payload=b"x" * 100,
            seq=7, ack=3,
        )
    link, pkts = _parse_pcap(str(p))
    assert link == 101  # LINKTYPE_RAW
    assert len(pkts) == 2
    ts, ip = pkts[0]
    assert ts == 1_500_000_000
    assert ip[0] == 0x45  # IPv4, IHL 5
    assert ip[9] == 17  # UDP
    assert ip[-5:] == b"hello"
    src_port, dst_port = struct.unpack(">HH", ip[20:24])
    assert (src_port, dst_port) == (9000, 1234)
    _, tcp = pkts[1]
    assert tcp[9] == 6  # TCP
    seq = struct.unpack(">I", tcp[24:28])[0]
    assert seq == 7


def test_pcap_writer_nanosecond_mode(tmp_path):
    """Opt-in ns-resolution captures round-trip the engine's ns stamps
    exactly (the default microsecond magic truncates them)."""
    t_ns = 1_500_000_123  # not a whole microsecond
    mk = lambda name, **kw: tmp_path / name  # noqa: E731
    us_p, ns_p = mk("us.pcap"), mk("ns.pcap")
    for path, nanos in ((us_p, False), (ns_p, True)):
        with PcapWriter(str(path), nanosecond=nanos) as w:
            w.write_packet(
                t_ns, proto="udp", src_ip=1, src_port=1, dst_ip=2,
                dst_port=2, payload=b"p",
            )
    _, us_pkts = _parse_pcap(str(us_p))
    _, ns_pkts = _parse_pcap(str(ns_p))
    assert us_pkts[0][0] == 1_500_000_000  # truncated to us
    assert ns_pkts[0][0] == t_ns  # exact
    raw = open(ns_p, "rb").read()
    assert struct.unpack("<I", raw[:4])[0] == 0xA1B23C4D


@pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)
def test_driver_tracker_and_pcap(tmp_path, apps):
    """Managed-process plane: per-host tracker counts and pcap capture of a
    3-ping UDP echo exchange."""
    from shadow_tpu.procs.driver import ProcessDriver

    d = ProcessDriver(stop_time=30 * NS_PER_SEC, latency_ns=10_000_000)
    hs = d.add_host("server", "11.0.0.1")
    hc = d.add_host("client", "11.0.0.2")
    hc.pcap_dir = str(tmp_path / "pcap")
    d.add_process(hs, [apps["udp_echo_server"], "9000", "3"])
    d.add_process(hc, [apps["udp_echo_client"], "server", "9000", "3"],
                  start_time=NS_PER_SEC)
    d.run()
    t = d.host_trackers()
    # client sends 3 pings, receives 3 echoes; server mirrors
    assert t["client"]["tx_packets"] == 3
    assert t["client"]["rx_packets"] == 3
    assert t["server"]["rx_packets"] == 3
    assert t["server"]["tx_packets"] == 3
    assert t["client"]["tx_bytes"] == t["server"]["rx_bytes"] > 0
    link, pkts = _parse_pcap(str(tmp_path / "pcap" / "client.pcap"))
    assert len(pkts) == 6  # 3 tx + 3 rx at the client
    # capture timestamps are sim time: first ping at t=1s exactly
    assert pkts[0][0] == 1_000_000_000


def test_device_tracker_counts():
    """Device plane: per-host NIC tracker arrays line up with the scalar
    delivery counters."""
    from shadow_tpu.sim import build_simulation

    yaml = """
general:
  stop_time: 3
  seed: 2
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 2048
  events_per_host_per_window: 8
hosts:
  server:
    app_model: udp_flood
    app_options: {role: server}
  client:
    quantity: 3
    app_model: udp_flood
    app_options: {interval: "100 ms", size: 600, runtime: 1}
"""
    sim = build_simulation(yaml)
    sim.run()
    t = sim.host_trackers()
    c = sim.counters()
    assert int(t["tx_packets"].sum()) > 0
    assert int(t["rx_packets"].sum()) == c["packets_delivered"]
    # hosts are name-sorted: client1..client3 then server; clients only send
    assert all(int(x) == 0 for x in t["rx_packets"][:3])
    assert int(t["tx_packets"][3]) == 0
    assert t["rx_bytes"][3] > 0


def _load_tool(name):
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        name, pathlib.Path(__file__).parent.parent / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parse_sim_log_tool():
    """tools/parse_sim_log.py digests logger output into structured JSON
    (reference analog: src/tools/parse-shadow.py)."""
    mod = _load_tool("parse_sim_log")

    lines = [
        "heartbeat: sim 2.000s, 53 syscalls, 4 packets, wall 0.2s",
        "00:00:01.324576 00:00:02.000000 [debug] [client] tracker: "
        "tx 2 pkts / 12 B, rx 3 pkts / 14 B, 1 dropped",
        "00:00:01.324824 00:00:02.100000 [debug] [client] process client.0 "
        "exited with 0",
        "00:00:00.725606 00:00:02.100000 [debug] syscall counts: read:8 "
        "resolve_name:1",
        "00:00:00.8 00:00:02.2 [warning] [srv] something odd",
    ]
    doc = mod.parse(lines)
    assert doc["heartbeats"] == [{"sim_s": 2.0, "count": 53}]
    t = doc["trackers"]["client"][0]
    assert (t["tx_packets"], t["rx_packets"], t["dropped_packets"]) == (2, 3, 1)
    assert t["sim_s"] == 2.0
    assert doc["process_exits"][0]["exit_code"] == 0
    assert doc["syscall_counts"] == {"read": 8, "resolve_name": 1}
    assert doc["warnings"][0]["level"] == "warning"


def test_parse_sim_log_malformed_line_errors_cleanly():
    """A line that matches the log shape but whose fields do not parse
    raises ParseError carrying the line number — the CLI turns that into
    a nonzero exit with a clear message, not a bare traceback."""
    mod = _load_tool("parse_sim_log")
    lines = [
        "00:00:01.0 00:00:02.0 [debug] [h] process x.0 exited with 0",
        "00:00:01.1 00:00:02.1 [debug] [h] process y.0 exited with signal",
    ]
    with pytest.raises(mod.ParseError) as e:
        mod.parse(lines)
    assert e.value.lineno == 2
    assert "exited with signal" in str(e.value)


def test_packet_breadcrumb_trails():
    """Per-packet delivery-status trails (packet.c:37-77 PDS_* analog,
    VERDICT r2 #10): with experimental.packet_trails, a dropped packet's
    ordered stage chain (CREATED -> ... -> DROPPED@cause) is
    reconstructable from the drop registers, and deliveries record their
    full chain too."""
    import jax

    from shadow_tpu.net import codel as codel_mod
    from shadow_tpu.net import packet as pkt
    from shadow_tpu.net import pds as pds_mod
    from shadow_tpu.sim import build_simulation

    # 800 kbit downlink + 4 clients pushing 1 KiB every 5 ms = ~6.5 Mbit
    # offered: the server's router queue builds standing delay -> CoDel
    # drops; 2% path loss also exercises the loss-drop register.
    cfg = {
        "general": {"stop_time": 4, "seed": 11},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "800 Kbit" '
            'bandwidth_up "20 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "10 ms" '
            'packet_loss 0.02 ]\n]\n')}},
        "experimental": {"event_capacity": 8192,
                         "events_per_host_per_window": 16,
                         "packet_trails": True,
                         "router_queue_slots": 32},
        "hosts": {
            "server": {"quantity": 1, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 4, "app_model": "udp_flood",
                       "app_options": {"interval": "5 ms", "size": 1024,
                                       "runtime": 2}},
        },
    }
    sim = build_simulation(cfg)
    sim.run()
    r = jax.device_get(sim.state.subs[codel_mod.SUB])
    assert int(r.codel_dropped) > 0, "workload must force CoDel drops"
    # the server (host index of role=server) recorded the dropped packet's
    # full chain in order
    si = [i for i, h in enumerate(sim.config.hosts)
          if h.app_options.get("role") == "server"][0]
    trail = pkt.decode_trail(int(r.drop_trail[si]))
    assert trail == ["CREATED", "SENT", "ROUTER_ENQUEUED", "DROPPED_CODEL"], \
        trail
    assert int(r.drop_time[si]) > 0
    # loss drops recorded with their chain + cause
    p = jax.device_get(sim.state.subs[pds_mod.SUB])
    c = sim.counters()
    assert c["packets_dropped_loss"] > 0
    loss_hosts = [h for h in range(5) if p["drop_count"][h] > 0]
    assert loss_hosts, "loss drops must hit the registers"
    lt = pkt.decode_trail(int(p["drop_trail"][loss_hosts[0]]))
    assert lt[-1] in ("DROPPED_LOSS", "DROPPED_SENDQ", "DROPPED_OVERFLOW"), lt
    assert lt[0] == "CREATED"
    # delivered packets' chains end in DELIVERED
    dt = pkt.decode_trail(int(p["deliver_trail"][si]))
    assert dt[0] == "CREATED" and dt[-1] == "DELIVERED", dt
    # report helper decodes
    rep = pds_mod.drop_report(sim)
    assert rep and all("trail" in e for e in rep)


# ---------------------------------------------------------------------------
# Device telemetry plane (shadow_tpu/obs): counter block, metrics JSON,
# Chrome-trace spans — docs/observability.md
# ---------------------------------------------------------------------------

_UDP_TINY_YAML = """
general:
  stop_time: 3
  seed: 2
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 2048
  events_per_host_per_window: 8
hosts:
  server:
    app_model: udp_flood
    app_options: {role: server}
  client:
    quantity: 3
    app_model: udp_flood
    app_options: {interval: "100 ms", size: 600, runtime: 1}
"""


def test_counter_parity_conservative_vs_optimistic():
    """Same seed + config under the conservative and optimistic engines
    must report identical committed-event and packet counters (rollback
    accounting may differ) — the device counter block included."""
    from shadow_tpu.sim import build_simulation

    cons = build_simulation(_UDP_TINY_YAML)
    cons.run()
    opt = build_simulation(_UDP_TINY_YAML)
    opt.run_optimistic()
    cc, co = cons.counters(), opt.counters()
    for k in ("events_committed", "events_emitted", "packets_sent",
              "packets_delivered", "packets_dropped_loss", "bytes_sent",
              "bytes_delivered"):
        assert cc[k] == co[k], (k, cc[k], co[k])
    sc, so = cons.obs_snapshot(), opt.obs_snapshot()
    assert (sc["host_events"] == so["host_events"]).all()
    assert (sc["host_last_t"] == so["host_last_t"]).all()
    assert sc["win"]["windows_run"] > 0
    # the conservative run never rolls back; the block says so
    assert sc["win"]["rollbacks"] == 0 and sc["win"]["window_shrinks"] == 0


def test_obs_block_disabled_compiles_out():
    """experimental.obs_counters: false removes the block entirely — the
    bench's overhead-control arm — and snapshots degrade to {}."""
    from shadow_tpu.sim import build_simulation

    yaml = _UDP_TINY_YAML.replace(
        "experimental:", "experimental:\n  obs_counters: false"
    )
    sim = build_simulation(yaml)
    assert sim.state.obs is None
    sim.run(until=1_000_000_000)
    assert sim.obs_snapshot() == {}


def test_metrics_and_trace_smoke_cli(tmp_path):
    """Tier-1 smoke (ISSUE 1 gate): the flagship tiny config run through
    the CLI with --metrics-out/--trace-out produces schema-valid metrics
    JSON and a Perfetto-loadable Chrome trace, and tools/trace_summary.py
    digests the trace."""
    import json

    from shadow_tpu import flagship
    from shadow_tpu.__main__ import main as cli_main
    from shadow_tpu.obs import metrics as obs_metrics

    cfg = tmp_path / "flagship_tiny.yaml"
    cfg.write_text(
        "general: {stop_time: 2, seed: 3}\n"
        "network:\n  graph:\n    type: gml\n    inline: |\n"
        + "".join(f"      {ln}\n"
                  for ln in flagship.SELF_LOOP_50MS_GML.splitlines())
        + "experimental:\n"
        "  event_capacity: 2048\n"
        "  events_per_host_per_window: 18\n"
        "  outbox_slots: 18\n"
        "  inbox_slots: 4\n"
        "hosts:\n"
        "  peer:\n"
        "    quantity: 32\n"
        "    app_model: phold\n"
        "    app_options: {msgload: 2, runtime: 1}\n"
    )
    m_out = tmp_path / "metrics.json"
    t_out = tmp_path / "trace.json"
    rc = cli_main([
        str(cfg), "-d", str(tmp_path / "data"),
        "--metrics-out", str(m_out), "--trace-out", str(t_out),
    ])
    assert rc == 0

    doc = json.loads(m_out.read_text())
    obs_metrics.validate_metrics_doc(doc)  # the documented schema
    assert doc["counters"]["engine.events_committed"] > 0
    assert doc["counters"]["obs.windows_run"] > 0
    assert doc["counters"]["obs.matrix_dispatches"] \
        + doc["counters"]["obs.loop_dispatches"] \
        == doc["counters"]["obs.windows_run"]
    assert doc["gauges"]["vtime.committed_hosts"] == 32
    assert doc["histograms"]["wall.dispatch_s"]["count"] > 0

    trace = json.loads(t_out.read_text())
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert any(e["name"] == "dispatch" for e in spans)
    assert all("ts" in e and "dur" in e for e in spans)

    summary = _load_tool("trace_summary")
    rows, _ = summary.summarize(trace)
    assert rows and rows[0]["count"] > 0
    assert summary.main([str(t_out), "-n", "5"]) == 0


def test_metrics_schema_validator_rejects_bad_docs():
    from shadow_tpu.obs import metrics as obs_metrics

    good = obs_metrics.MetricsRegistry()
    good.counter_set("engine.events_committed", 1)
    good.histogram("wall.dispatch_s").observe(0.5)
    doc = good.to_doc()
    obs_metrics.validate_metrics_doc(doc)
    with pytest.raises(ValueError):
        obs_metrics.validate_metrics_doc({**doc, "schema_version": 99})
    with pytest.raises(ValueError):
        obs_metrics.validate_metrics_doc(
            {**doc, "counters": {"x": "not-an-int"}}
        )
    with pytest.raises(ValueError):
        bad_h = {**doc, "histograms": {"h": {"count": 1}}}
        obs_metrics.validate_metrics_doc(bad_h)
