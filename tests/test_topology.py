import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.routing.dns import Dns
from shadow_tpu.routing.gml import GmlParseError, parse_gml
from shadow_tpu.routing.topology import Topology, TopologyError

pytestmark = pytest.mark.quick


SELF_LOOP = """
graph [
  directed 0
  node [ id 0 country_code "US" bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""

TRIANGLE = """
graph [
  directed 0
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 2 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.1 ]
  edge [ source 1 target 2 latency "10 ms" packet_loss 0.1 ]
  edge [ source 0 target 2 latency "100 ms" packet_loss 0.0 ]
]
"""


def test_parse_gml_basic():
    g = parse_gml(SELF_LOOP)
    assert not g.directed
    assert len(g.nodes) == 1 and len(g.edges) == 1
    assert g.nodes[0]["country_code"] == "US"


def test_parse_gml_bad():
    with pytest.raises(GmlParseError):
        parse_gml("nothing here")


def test_self_loop_bake():
    topo = Topology.from_gml(SELF_LOOP)
    for i in range(4):
        topo.attach_host(i)
    baked = topo.bake()
    assert baked.latency_vv.shape == (1, 1)
    assert baked.latency_vv[0, 0] == 50 * simtime.NS_PER_MS
    assert baked.reliability_vv[0, 0] == 1.0
    assert baked.min_latency_ns == 50 * simtime.NS_PER_MS
    assert list(baked.host_vertex) == [0, 0, 0, 0]


def test_shortest_path_and_reliability():
    topo = Topology.from_gml(TRIANGLE)
    topo.attach_host(0)  # vertex 0
    topo.attach_host(1)  # vertex 1
    topo.attach_host(2)  # vertex 2
    baked = topo.bake()
    # 0→2: via 1 costs 20ms vs direct 100ms → shortest picks 20ms
    assert baked.latency_vv[0, 2] == 20 * simtime.NS_PER_MS
    # reliability along 0→1→2 = 0.9 * 0.9
    assert np.isclose(baked.reliability_vv[0, 2], 0.81, atol=1e-6)
    # direct edge 0→1
    assert baked.latency_vv[0, 1] == 10 * simtime.NS_PER_MS
    # min latency feeds runahead: self-loop 1ms is the min
    assert baked.min_latency_ns == 1 * simtime.NS_PER_MS


def test_direct_edge_mode_requires_edges():
    topo = Topology.from_gml(TRIANGLE, use_shortest_path=False)
    topo.attach_host(0, network_node_id=0)
    topo.attach_host(1, network_node_id=2)
    baked = topo.bake()  # 0↔2 has a direct edge
    assert baked.latency_vv[0, 1] == 100 * simtime.NS_PER_MS

    topo2 = Topology.from_gml(
        """
        graph [
          node [ id 0 ]
          node [ id 1 ]
          node [ id 2 ]
          edge [ source 0 target 1 latency "5 ms" ]
          edge [ source 1 target 2 latency "5 ms" ]
        ]
        """,
        use_shortest_path=False,
    )
    topo2.attach_host(0)
    topo2.attach_host(1)
    topo2.attach_host(2)
    baked2 = topo2.bake()
    # no direct 0↔2 edge → unreachable in direct mode (dropped at send time)
    assert baked2.latency_vv[0, 2] == np.iinfo(np.int64).max
    assert baked2.latency_vv[0, 1] == 5 * simtime.NS_PER_MS


def test_attach_hints():
    topo = Topology.from_gml(
        """
        graph [
          node [ id 0 country_code "US" ip_address "1.2.3.4" ]
          node [ id 1 country_code "DE" ip_address "5.6.7.8" ]
          edge [ source 0 target 1 latency "5 ms" ]
          edge [ source 0 target 0 latency "1 ms" ]
          edge [ source 1 target 1 latency "1 ms" ]
        ]
        """
    )
    v = topo.attach_host(0, country_code_hint="DE")
    assert v.id == 1
    v = topo.attach_host(1, ip_address_hint="1.2.3.4")
    assert v.id == 0
    v = topo.attach_host(2)  # round robin over all: index 2 % 2 = 0
    assert v.id == 0


def test_gml_hash_in_string_and_comments():
    g = parse_gml(
        """
        # a leading comment
        graph [
          node [ id 0 label "rack#3-us" ]  # trailing comment
          edge [ source 0 target 0 latency "1 ms" ]
        ]
        """
    )
    assert g.nodes[0]["label"] == "rack#3-us"


def test_bare_latency_is_seconds():
    # graph spec: bare numeric latency is seconds
    topo = Topology.from_gml(
        'graph [ node [ id 0 ] edge [ source 0 target 0 latency 2 ] ]'
    )
    topo.attach_host(0)
    assert topo.bake().latency_vv[0, 0] == 2 * simtime.NS_PER_SEC


def test_edge_unknown_node_id():
    with pytest.raises(TopologyError):
        Topology.from_gml(
            'graph [ node [ id 0 ] edge [ source 0 target 5 latency "1 ms" ] ]'
        )


def test_dns_restricted_ranges():
    dns = Dns()
    # restricted hints are regenerated like the reference (dns.c:141-142)
    ip = dns.register(0, "a", ip_hint="127.0.0.2")
    assert Dns.ip_str(ip) == "11.0.0.1"
    ip = dns.register(1, "b", ip_hint="224.0.0.1")
    assert Dns.ip_str(ip) == "11.0.0.2"


def test_dns():
    dns = Dns()
    ip_a = dns.register(0, "alpha")
    ip_b = dns.register(1, "beta", ip_hint="11.0.0.50")
    assert dns.resolve_name("alpha") == ip_a
    assert dns.ip_str(ip_b) == "11.0.0.50"
    assert dns.host_for_ip(ip_b) == 1
    assert dns.resolve_ip(ip_a) == "alpha"
    ip_c = dns.register(2, "gamma", ip_hint="11.0.0.50")  # taken → sequential
    assert ip_c != ip_b


def test_lazy_paths_match_dense():
    """LazyPaths (on-demand per-source rows, topology.c:1144-1259 analog)
    must agree with the dense bake on every used pair, including
    unreachable pairs and the explicit-self-loop diagonal rule."""
    gml = """graph [
      directed 0
      node [ id 0 ] node [ id 1 ] node [ id 2 ] node [ id 3 ]
      edge [ source 0 target 0 latency "5 ms" packet_loss 0.01 ]
      edge [ source 0 target 1 latency "10 ms" packet_loss 0.02 ]
      edge [ source 1 target 2 latency "20 ms" packet_loss 0.1 ]
      edge [ source 0 target 2 latency "50 ms" ]
    ]"""

    def build():
        t = Topology.from_gml(gml)
        for i in range(4):
            t.attach_host(i, network_node_id=i % 4)
        return t

    dense = build().bake()
    lazy = build().bake_lazy()
    U = len(dense.used_vertices)
    for i in range(U):
        for j in range(U):
            assert lazy.latency_ns(i, j) == int(dense.latency_vv[i, j]), (i, j)
            assert abs(
                lazy.reliability(i, j) - float(dense.reliability_vv[i, j])
            ) < 1e-6, (i, j)
    # lazy runahead bound is the min EDGE latency (a sound lower bound)
    assert lazy.min_latency_ns <= dense.min_latency_ns
    assert list(lazy.host_vertex) == list(dense.host_vertex)


def test_10k_vertex_gml_builds_without_dense_matrix():
    """VERDICT r2 #8: a 10k-vertex graph must build and serve lookups
    WITHOUT any dense [U, U] allocation, in seconds (the old Python U x U
    bake loop would take hours; the dense arrays would take 1.2 GB)."""
    import time

    V = 10_000
    lines = ["graph [", "  directed 0"]
    for i in range(V):
        lines.append(f"  node [ id {i} ]")
    # ring + a few chords; every vertex also gets a self-loop (co-located
    # host communication needs one)
    for i in range(V):
        lines.append(
            f'  edge [ source {i} target {(i + 1) % V} latency "2 ms" '
            f"packet_loss 0.001 ]"
        )
        lines.append(f'  edge [ source {i} target {i} latency "1 ms" ]')
    gml = "\n".join(lines) + "\n]"

    t0 = time.time()
    topo = Topology.from_gml(gml)
    for h in range(V):  # one host on every vertex: U = 10k
        topo.attach_host(h, network_node_id=h)
    lazy = topo.bake_lazy()
    build_s = time.time() - t0
    assert build_s < 60, f"lazy bake took {build_s:.1f}s"

    t0 = time.time()
    # ring distance 3 → 6 ms; reliability (1-0.001)^3
    assert lazy.latency_ns(0, 3) == 6 * simtime.NS_PER_MS
    assert abs(lazy.reliability(0, 3) - 0.999**3) < 1e-5
    assert lazy.latency_ns(5000, 5000) == simtime.NS_PER_MS  # self-loop
    assert lazy.min_latency_ns == simtime.NS_PER_MS
    assert time.time() - t0 < 30
    # only the queried source rows were materialized
    assert len(lazy._rows) == 2
